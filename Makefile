# Convenience run targets mirroring the reference's pinned experiment
# configs (reference Makefile:73-92), adapted to the mesh launcher: the
# reference's `mpirun -np 10 --hostfile hf ./svmTrain ...` becomes a single
# process driving the whole device mesh. Dataset CSVs are expected under
# data/ (not shipped; see dpsvm_tpu/data/converters.py to produce them).

PY ?= python
DATA ?= data
# The verify recipe uses pipefail/PIPESTATUS (the tier-1 command is
# pinned verbatim from ROADMAP.md, which assumes bash).
SHELL := /bin/bash

.PHONY: test test_all verify lint lint_budgets autotune autotune_smoke bench bench_ooc_smoke bench_fused_smoke bench_predict bench_serve bench_serve_smoke serve_net_smoke serve_replica_smoke serve_quant_smoke learn_smoke faults_smoke ooc_mesh_smoke loadgen fetch_real_data smoke tpu_smoke multihost_check parity parity_full native run_mnist run_cover run_adult run_test_mnist run_test_adult run_synth

# Quick loop (slow-marked parity/scale tests deselected); test_all is the
# full suite the CI/driver runs. JAX_PLATFORMS=cpu is exported at the
# SHELL level (belt-and-braces with tests/conftest.py's in-process
# override): with the axon TPU tunnel attached, per-test device->host
# latency (~80 ms/transfer and worse under load) blows the suite past
# any CI budget — the suite is designed for the 8-virtual-device CPU
# platform; tools/tpu_smoke.py is the real-TPU gate.
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow"

# The ROADMAP.md tier-1 command VERBATIM (what the CI/driver gate runs):
# same selection, same flags, same dot-count summary line.
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1500 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

test_all:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

# The local mirror of CI's lint gates (tier1.yml): compileall, the
# tpulint static HLO/jaxpr contract check against committed budgets
# (per-entrypoint PASS/DRIFT table), the threadlint concurrency
# contracts (guarded-by / lock-order / thread-lifecycle / seam
# coverage against dpsvm_tpu/analysis/contracts), and ruff when
# installed (CI pins and enforces it; locally it is best-effort so
# the target works on the bare image).
lint:
	$(PY) -m compileall -q dpsvm_tpu tools tests bench.py
	$(PY) -m tools.tpulint --check
	$(PY) -m tools.tpulint --threads --check
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check dpsvm_tpu tools tests bench.py; \
	else \
	  echo "ruff not installed locally; CI enforces it (tier1.yml)"; \
	fi

# Regenerate dpsvm_tpu/analysis/budgets/*.json after an INTENTIONAL
# structural change; commit the JSON diff (it is the review artifact).
lint_budgets:
	$(PY) -m tools.tpulint --write-budgets

# Regenerate dpsvm_tpu/analysis/contracts/*.json (threadlint) after an
# INTENTIONAL concurrency change; allow lists and the handoff->seam
# map survive regeneration. Commit the JSON diff. Deterministic: two
# consecutive runs produce byte-identical files.
lint_contracts:
	$(PY) -m tools.tpulint --threads --write-contracts

# Measured autotuner (ISSUE 14; ROADMAP item 5): run the probe
# registry on THIS device kind and persist the DeviceProfile JSON
# under dpsvm_tpu/autotune/profiles/ — commit the diff (the tpulint-
# budgets discipline; jax-version-stamped, refused on skew). On a pod
# session this is the ONE command that closes the *_pays measurement
# loop; on the CPU harness it regenerates the non-authoritative seed
# profile (all gates stay at the OFF defaults by construction):
#   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 make autotune
autotune:
	DPSVM_OBS=1 $(PY) -m dpsvm_tpu.cli autotune run

# CI leg (tier1.yml): tiny-shape probe pass into a TEMP profile, run
# twice, schema + stable-field/decision determinism asserted. Never
# touches the committed profiles.
autotune_smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 DPSVM_OBS=1 $(PY) -m dpsvm_tpu.cli autotune run --smoke

bench:
	$(PY) bench.py

# Out-of-core smoke (ISSUE 9): the --ooc benchmark leg on the CPU
# harness with the telemetry spine live — host-resident X, double-
# buffered tile stream, block cache — producing a gateable
# ooc_pairs_per_second JSON whose run log carries the tile-fetch and
# cache-hit counters (commit the output as BENCH_OOC_r<NN>.json).
bench_ooc_smoke:
	JAX_PLATFORMS=cpu DPSVM_OBS=1 $(PY) bench.py --ooc --obs

# One-HBM-pass fused-round smoke (ISSUE 12): the --fused-round bench
# leg on the CPU harness (interpret-mode kernels) — fused round vs the
# stock fused engine at the same pinned budget, BITWISE-checked, gated
# against the committed BENCH_FUSED_r*.json through the same drift-
# normalized regression gate (tier1.yml runs this next to
# bench_serve_smoke; the smoke output is not committed).
bench_fused_smoke:
	JAX_PLATFORMS=cpu DPSVM_OBS=1 $(PY) bench.py --fused-round --obs

# Network front-door smoke (ISSUE 15): the same loadgen engine driven
# through a REAL localhost socket — clean leg with per-class EXACT
# client/server verdict reconciliation, seeded connection-fault chaos
# leg (kills, a stalled reader, partial writes, an accept drop, one
# mid-leg hot swap), protocol fuzz burst, graceful drain under
# sustained load, journal rehydrate re-proven BITWISE through the
# socket path, zero server-thread leaks. Temp artifact (tier1.yml runs
# this next to bench_serve_smoke and faults_smoke).
serve_net_smoke:
	JAX_PLATFORMS=cpu DPSVM_OBS=1 $(PY) tools/loadgen.py --net --smoke --obs

# Replica scale-out smoke (ISSUE 16): a 2-replica ReplicaFleet behind
# one front door on the same wire path — clean scale-out mini-sweep
# (r=1 then r=2, aggregate throughput must clear the smoke frontier
# floor with every replica pulling) plus a seeded chaos mini-leg with
# a mid-leg CROSS-REPLICA hot swap, all reconciled exactly. Temp
# artifact (tier1.yml runs this next to serve_net_smoke).
serve_replica_smoke:
	JAX_PLATFORMS=cpu $(PY) tools/loadgen.py --net --replicas 2 --smoke

# Quantized-serving smoke (ISSUE 17): the int8 union hot path proven
# end-to-end — a guard-ACCEPTED model stages int8 (union bytes cut
# >3x, decisions served clean through closed-loop traffic), a risky
# model is REFUSED loudly and falls back without int8, and an f32 vs
# int8 frontier leg runs through the real wire front door with exact
# verdict reconciliation. Temp artifact (tier1.yml runs this next to
# serve_replica_smoke).
serve_quant_smoke:
	JAX_PLATFORMS=cpu $(PY) tools/loadgen.py --quant-smoke

# Continuous-learning smoke (ISSUE 18): `cli learn --smoke` — a tiny
# drifting two-generation stream retrained warm from the previous
# generation's support vectors (solver/cascade.py), each generation
# published into an in-process serving engine via hot swap. Asserts
# the warm retrain saved pairs > 0 vs the MEASURED cold baseline and
# that the post-swap probe serves ok (tier1.yml runs this next to
# serve_quant_smoke). Models go to a temp dir, never committed.
learn_smoke:
	JAX_PLATFORMS=cpu DPSVM_OBS=1 $(PY) -m dpsvm_tpu.cli learn --smoke --model-dir $$(mktemp -d)

# Fault-tolerance smoke (ISSUE 13): the deterministic fault-injection
# harness self-test, a kill -9 mid-ooc-solve followed by a --resume
# that must land BITWISE on the uninterrupted trajectory, and a
# dispatch-watchdog trip that must fail one batch explicitly and keep
# the engine serving (tier1.yml runs this next to bench_serve_smoke).
faults_smoke:
	JAX_PLATFORMS=cpu $(PY) tools/faults_smoke.py

# Mesh out-of-core smoke (ISSUE 19): solve_mesh + ooc at 2 virtual
# devices proven BITWISE equal to the single-chip ooc stream, and the
# ooc_tile_put fault seam proven to cover the mesh stream's H2D path
# (transient fault + retry lands on the same bitwise state).
ooc_mesh_smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 $(PY) tools/ooc_mesh_smoke.py

smoke:
	$(PY) -m dpsvm_tpu.cli smoke

# Real-TPU Mosaic lowering checks for the Pallas kernels (pytest covers
# them in interpret mode only): every subproblem rule x small/unaligned q,
# plus end-to-end block/pallas/fleet engine solves. Needs the axon TPU
# free. Writes a TPU_SMOKE_r<NN>.json artifact at the repo root — commit
# it; the artifact, not the commit message, is the evidence of the run.
tpu_smoke:
	$(PY) tools/tpu_smoke.py

# Two-process jax.distributed bring-up (the mpirun --hostfile equivalent,
# ref Makefile:74): cross-process mesh + collectives + a distributed
# block-engine chunk, all on CPU.
multihost_check:
	$(PY) tools/multihost_check.py

# Mid-scale LibSVM parity table -> PARITY.md (single-chip cases on the
# real TPU; mesh cases on the virtual 8-device CPU platform).
# parity_full additionally runs adult-shaped at the reference's exact
# n=32561 (reference Makefile:86).
parity:
	$(PY) tools/parity.py

# Covtype-stress LibSVM parity (one solve() call per row via the
# in-solver f64 reconstruction legs; oracle phase first on CPU) and the
# full-n 500k quality trajectory -> BENCH_COVTYPE.md.
parity_covtype:
	$(PY) tools/parity_covtype.py

covtype_fullscale:
	$(PY) tools/covtype_fullscale.py

parity_full:
	$(PY) tools/parity.py --full

# Batched-inference throughput -> BENCH_PREDICT.md (the svmTest role,
# timed; the reference's CPU tester publishes no timing).
bench_predict:
	$(PY) tools/bench_predict.py

# Serving benchmark: compacted-vs-stacked A/B + PredictServer offered-
# load sweep -> BENCH_SERVE_r<NN>.json (commit it) + BENCH_SERVE.md,
# through the drift-normalized cross-session regression gate.
bench_serve:
	$(PY) tools/bench_serve.py

# Serving v2 closed-loop load generator (ISSUE 10): registry hot swap
# under live traffic, deadline-aware batching, latency/throughput
# frontier -> BENCH_SERVE_r<NN>.json (commit it) + BENCH_SERVE.md.
loadgen:
	$(PY) tools/loadgen.py --obs

# Short CI leg of the same sweep on the CPU harness: run log live,
# rows runlog-reconciled, mid-sweep hot swap asserted zero-loss,
# through the regression gate — the smoke artifact goes to a temp
# file, never the committed r<NN> series (tier1.yml runs this).
bench_serve_smoke:
	JAX_PLATFORMS=cpu DPSVM_OBS=1 $(PY) tools/loadgen.py --smoke --obs

# Real-dataset recipe (MNIST / covtype / Adult a9a): download, verify
# sha256, run the converters into data/*.csv. Exits 0 with a SKIP note
# when the environment has no egress; real-data test/parity legs
# activate automatically once the files exist.
fetch_real_data:
	$(PY) tools/fetch_real_data.py

# Delegates to the Python builder so the compile command lives in exactly
# one place (dpsvm_tpu/utils/native.py, which also fingerprints the flags).
native:
	$(PY) -c "from dpsvm_tpu.utils.native import build_all; print('\n'.join(build_all()) or 'native build unavailable')"

# MNIST even-odd (ref Makefile:74: 10 ranks, c=10, g=0.125, e=0.01)
run_mnist:
	$(PY) -m dpsvm_tpu.cli train -f $(DATA)/mnist_train.csv -m $(DATA)/mnist.model \
	  -a 784 -x 60000 -c 10 -g 0.125 -e 0.01 -n 100000 --backend mesh

# Covtype binary (ref Makefile:77: c=2048, g=0.03125, e=0.001)
run_cover:
	$(PY) -m dpsvm_tpu.cli train -f $(DATA)/covtype_train.csv -m $(DATA)/covtype.model \
	  -a 54 -x 500000 -c 2048 -g 0.03125 -e 0.001 -n 3000000 --backend mesh

# Adult a9a (ref Makefile:86: 1 rank, c=100, g=0.5, e=0.001)
run_adult:
	$(PY) -m dpsvm_tpu.cli train -f $(DATA)/adult_train.csv -m $(DATA)/adult.model \
	  -a 123 -x 32561 -c 100 -g 0.5 -e 0.001 -n 150000 --backend single

run_test_mnist:
	$(PY) -m dpsvm_tpu.cli test -f $(DATA)/mnist_test.csv -m $(DATA)/mnist.model -a 784 -x 10000

run_test_adult:
	$(PY) -m dpsvm_tpu.cli test -f $(DATA)/adult_test.csv -m $(DATA)/adult.model -a 123 -x 16281

# Offline stand-in when no datasets are available (synthetic MNIST-shaped).
run_synth:
	$(PY) bench.py
