"""Shape bucketing (solve pad_to) + device-transfer reuse (_XDEV_MEMO)
— the multiclass-at-scale plumbing (VERDICT round-4 item 2).

pad_to pads the row axis and masks the padding out of every selection,
so a bucketed solve must reach the SAME model as the exact-shape solve;
the x-device memo must make repeated solves on one host X (one-vs-rest
trains k classes on the same features) skip the re-upload.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import _XDEV_MEMO, solve


def _blobs(n=600, d=8, seed=5, sep=1.0):
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=n, d=d, seed=seed, sep=sep)


BASE = SVMConfig(c=10.0, gamma=0.1, epsilon=1e-3, max_iter=200_000)


@pytest.mark.parametrize("cfg", [
    BASE,                                              # per-pair xla
    BASE.replace(selection="second_order"),            # WSS2
    BASE.replace(pair_batch=4),                        # micro-batch
    BASE.replace(engine="block", working_set_size=32),  # block plain
    BASE.replace(engine="block", working_set_size=32,
                 active_set_size=64),                  # block active-set
    # gram + pad_to: the padded rows get REAL kernel values (zero
    # feature vectors) but are masked out of selection — still exact.
    BASE.replace(gram_resident=True),
], ids=["xla", "wss2", "micro", "block", "active", "gram"])
def test_padded_solve_matches_exact_shape(cfg):
    x, y = _blobs(n=555)  # deliberately ragged
    ref = solve(x, y, cfg)
    got = solve(x, y, cfg, pad_to=1024)
    assert got.converged
    assert got.alpha.shape == (555,)
    assert abs(got.b - ref.b) < 5e-3
    dec_r = ref.stats["f"] + y - ref.b
    dec_g = got.stats["f"] + y - got.b
    assert np.mean(np.sign(dec_r) == np.sign(dec_g)) > 0.995


def test_padded_budget_mode_counts_real_pairs():
    x, y = _blobs(n=700, sep=0.6)
    res = solve(x, y, BASE.replace(budget_mode=True, max_iter=5000),
                pad_to=1024)
    assert res.iterations == 5000
    assert res.alpha.shape == (700,)


def test_pad_to_rejects_precomputed():
    from dpsvm_tpu.ops.kernels import kernel_matrix, KernelParams

    x, y = _blobs(n=64)
    g = np.asarray(kernel_matrix(x, x, KernelParams("rbf", 0.1)))
    with pytest.raises(ValueError, match="pad_to"):
        solve(g, y, BASE.replace(kernel="precomputed"), pad_to=128)


def test_xdev_memo_reuses_across_solves():
    """One-vs-rest trains k classes on the SAME host X: the device
    transfer + squared-norm pass must happen once."""
    import jax

    calls = {"n": 0}
    orig = jax.device_put

    def counting(v, *a, **kw):
        # Count HOST X uploads only (np.ndarray): jax >= 0.4.3x routes
        # jnp.asarray(np_array) through the public jax.device_put too,
        # so the one upload would otherwise be seen twice (once as the
        # ndarray, once as the resulting committed device array).
        if isinstance(v, np.ndarray) and v.ndim == 2:
            calls["n"] += 1
        return orig(v, *a, **kw)

    _XDEV_MEMO.clear()
    x, y = _blobs()
    x = np.asarray(x, np.float32)
    jax.device_put = counting
    try:
        solve(x, y, BASE)
        solve(x, -y, BASE)  # different labels, same features
        assert calls["n"] == 1
    finally:
        jax.device_put = orig
        _XDEV_MEMO.clear()


def test_ovo_bucketing_end_to_end():
    """train_multiclass OvO with ragged class sizes: bucketed subset
    solves produce a working model."""
    from dpsvm_tpu.models.multiclass import (accuracy_multiclass,
                                             train_multiclass)

    rng = np.random.default_rng(3)
    centers = np.array([[0.0] * 6, [4.0] * 6, [-4.0] * 6], np.float32)
    y = rng.integers(0, 3, 503).astype(np.int32)  # ragged sizes
    x = centers[y] + rng.normal(size=(503, 6)).astype(np.float32)
    m, results = train_multiclass(x, y, BASE.replace(c=5.0),
                                  strategy="ovo", backend="single")
    assert all(r.converged for r in results)
    assert accuracy_multiclass(m, x, y) > 0.95
