"""Precomputed-kernel support (LibSVM -t 4): the training input IS the
(n, n) Gram matrix; models carry SV indices and prediction consumes
K(test, train) columns — sklearn's kernel='precomputed' contract.

The reference has no equivalent (it hardcodes RBF, svmTrain.cu:696-714);
the oracle here is twofold: the repo's own rbf solve on the underlying
features (a precomputed solve over K_rbf must reproduce it exactly — the
iteration algebra never sees features, only kernel values), and
sklearn.svm.SVC(kernel='precomputed').
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
from dpsvm_tpu.solver.smo import solve


@pytest.fixture(scope="module")
def gram_problem():
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=600, d=12, seed=3, sep=1.2)
    kp = KernelParams("rbf", 0.1)
    K = np.asarray(kernel_matrix(x, x, kp), np.float32)
    return x, y, K


def test_precomputed_reproduces_rbf_solve(gram_problem):
    """Feeding K_rbf as a precomputed kernel must match the rbf solve on
    the features — same trajectory on the per-pair engine (the algebra
    only ever consumes kernel values), same optimum on block/WSS2."""
    x, y, K = gram_problem
    r_rbf = solve(x, y, SVMConfig(c=10.0, gamma=0.1))
    pre = SVMConfig(c=10.0, kernel="precomputed")
    r_pre = solve(K, y, pre)
    assert r_pre.converged
    # The rbf path evaluates kernel rows per iteration while the Gram
    # matrix here comes from one kernel_matrix matmul; a last-ulp
    # difference can shift the MVP trajectory, so near-identity (not
    # bitwise identity) is the contract.
    assert abs(r_pre.iterations - r_rbf.iterations) <= 0.02 * r_rbf.iterations
    assert abs(r_pre.n_sv - r_rbf.n_sv) <= max(2, 0.01 * r_rbf.n_sv)
    assert abs(r_pre.b - r_rbf.b) < 1e-3
    np.testing.assert_allclose(r_pre.alpha, r_rbf.alpha, atol=5e-3)

    for cfg in (pre.replace(engine="block", working_set_size=32),
                pre.replace(selection="second_order")):
        r = solve(K, y, cfg)
        assert r.converged
        assert abs(r.n_sv - r_rbf.n_sv) <= max(2, 0.01 * r_rbf.n_sv)
        assert abs(r.b - r_rbf.b) < 5e-3


def test_precomputed_facade_matches_sklearn(gram_problem):
    from sklearn.svm import SVC as SkSVC

    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.estimators import SVC

    xall, yall = make_blobs_binary(n=900, d=15, seed=9, sep=1.3)
    xtr, ytr, xte, yte = xall[:600], yall[:600], xall[600:], yall[600:]
    kp = KernelParams("rbf", 0.08)
    Ktr = np.asarray(kernel_matrix(xtr, xtr, kp), np.float32)
    Kte = np.asarray(kernel_matrix(xte, xtr, kp), np.float32)
    ours = SVC(C=10.0, kernel="precomputed").fit(Ktr, ytr)
    sk = SkSVC(C=10.0, kernel="precomputed").fit(Ktr, ytr)
    assert abs(int(ours.n_support_.sum()) - int(sk.n_support_.sum())) <= max(
        2, 0.01 * sk.n_support_.sum())
    assert abs(ours.score(Kte, yte) - sk.score(Kte, yte)) <= 1.0 / len(yte)
    assert np.mean(np.sign(ours.decision_function(Kte))
                   == np.sign(sk.decision_function(Kte))) >= 0.998
    # Block engine through the facade reaches the same answers.
    blk = SVC(C=10.0, kernel="precomputed", engine="block",
              working_set_size=32).fit(Ktr, ytr)
    assert abs(blk.score(Kte, yte) - sk.score(Kte, yte)) <= 1.0 / len(yte)


def test_precomputed_loud_rejections(gram_problem):
    """Unsupported combinations fail before any device work: fused pallas
    engine, kernel-row cache, mesh backend, file-model train() path,
    non-square input, multiclass/probability facade."""
    from dpsvm_tpu.estimators import SVC
    from dpsvm_tpu.parallel.dist_smo import solve_mesh
    from dpsvm_tpu.train import train

    x, y, K = gram_problem
    with pytest.raises(ValueError, match="pallas"):
        SVMConfig(kernel="precomputed", engine="pallas")
    with pytest.raises(ValueError, match="nothing to cache"):
        SVMConfig(kernel="precomputed", cache_lines=8)
    pre = SVMConfig(c=10.0, kernel="precomputed")
    # Mesh per-pair still rejects (a full Gram row per pair update);
    # mesh BLOCK is supported (test_precomputed_mesh_block_matches_dense).
    with pytest.raises(ValueError, match="engine='block'"):
        solve_mesh(K, y, pre.replace(engine="xla"))
    with pytest.raises(ValueError, match="SV indices"):
        train(K, y, pre)
    with pytest.raises(ValueError, match="square"):
        solve(K[:, :100], y, pre)
    y3 = y.copy()
    y3[:200] = 2
    with pytest.raises(ValueError, match="binary"):
        SVC(kernel="precomputed").fit(K, y3)
    with pytest.raises(ValueError, match="probability"):
        SVC(kernel="precomputed", probability=True).fit(K, y)
    with pytest.raises(ValueError, match="shrinking"):
        SVMConfig(kernel="precomputed", engine="block", active_set_size=64)
    from dpsvm_tpu.models.svr import train_svr
    with pytest.raises(ValueError, match="binary C-SVC only"):
        train_svr(K, y.astype(np.float32), config=pre)
    # Wrong-width test Gram rejected at predict time. Since round 5 the
    # sklearn validate_data layer catches the width mismatch first with
    # its standard wording; either way the rejection is loud.
    from dpsvm_tpu.estimators import SVC as OurSVC
    est = OurSVC(C=10.0, kernel="precomputed").fit(K, y)
    with pytest.raises(ValueError, match="columns|features"):
        est.decision_function(K[:, :300])


def test_precomputed_mesh_block_matches_dense(blobs_small):
    """kernel='precomputed' on the 8-device mesh block engine: feeding
    K(x, x) as the Gram matrix must reproduce the dense-RBF mesh solve
    (Gram symmetry makes the fold local — parallel/dist_block.py)."""
    import numpy as np

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    kp = KernelParams("rbf", 0.2)
    K = np.asarray(kernel_matrix(x, x, kp), np.float32)
    cfg = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, engine="block",
                    working_set_size=32, cache_lines=0)
    r_dense = solve_mesh(x, y, cfg, num_devices=8)
    r_gram = solve_mesh(K, y, cfg.replace(kernel="precomputed"),
                        num_devices=8)
    assert r_gram.converged
    # Same Gram values -> same optimum (fp paths differ: dense computes
    # rows on the fly, precomputed reads them).
    assert abs(r_gram.b - r_dense.b) < 2 * cfg.epsilon

    def obj(r):
        return float(np.sum(r.alpha)
                     - 0.5 * np.sum(r.alpha * y * (r.stats["f"] + y)))

    assert abs(obj(r_gram) - obj(r_dense)) <= 1e-3 * abs(obj(r_dense))
    assert abs(r_gram.n_sv - r_dense.n_sv) <= max(2, 0.02 * r_dense.n_sv)
    # Uneven rows: padding covers both axes of the Gram.
    n = len(y) - 3
    r_odd = solve_mesh(K[:n, :n], y[:n], cfg.replace(kernel="precomputed"),
                       num_devices=8)
    assert r_odd.converged and r_odd.alpha.shape == (n,)


def test_precomputed_mesh_rejects_per_pair(blobs_small):
    import pytest

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    with pytest.raises(ValueError, match="engine='block'"):
        solve_mesh(x, y, SVMConfig(kernel="precomputed", engine="xla",
                                   cache_lines=0), num_devices=2)
