"""tpulint (dpsvm_tpu/analysis) — fact-extractor self-tests and the
budget gate (ISSUE 5).

Two layers: (1) the extractor itself, on tiny hand-built jitted
functions with KNOWN facts — a deliberate collective, a deliberate f64
leak, a deliberately missed donation, a traced-branch recompile hazard,
each asserted detected AND its clean variant asserted quiet; (2) the
committed budgets, re-extracted from the live manifest and required to
PASS — the in-suite embodiment of ``python -m tools.tpulint --check``.
"""

import jax
import jax.numpy as jnp

from dpsvm_tpu.analysis import hlo_facts
from dpsvm_tpu.analysis.extract import Unit, entry_facts, unit_facts

SDS = jax.ShapeDtypeStruct


def _compiled_text(fn, *args, **kw):
    return jax.jit(fn).lower(*args, **kw).compile().as_text()


# ------------------------------------------------------ collectives

def test_collective_facts_detect_psum():
    from jax.sharding import PartitionSpec as P

    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         mesh_shard_map)

    mesh = make_data_mesh(8)

    def shard_fn(x):
        return jax.lax.psum(x.sum(0, keepdims=True), DATA_AXIS)

    mapped = jax.jit(mesh_shard_map(shard_fn, mesh=mesh,
                                    in_specs=(P(DATA_AXIS),),
                                    out_specs=P()))
    text = mapped.lower(SDS((64, 16), jnp.float32)).compile().as_text()
    facts = hlo_facts.collective_facts(text)
    assert facts["all-reduce"]["count"] == 1
    # Per-device result payload: (1, 16) f32.
    assert facts["all-reduce"]["payload_bytes"] == [64]
    assert facts["all-gather"]["count"] == 0
    assert facts["collective-permute"]["count"] == 0


def test_clean_function_is_quiet():
    text = _compiled_text(lambda a, b: jnp.dot(a, b),
                          SDS((16, 8), jnp.float32),
                          SDS((8, 4), jnp.float32))
    facts = hlo_facts.collective_facts(text)
    assert all(v["count"] == 0 for v in facts.values())
    assert all(v == 0 for v in hlo_facts.transfer_facts(text).values())
    dt = hlo_facts.dtype_facts(text)
    assert not dt["f64_present"] and dt["f32_to_bf16_converts"] == 0
    assert hlo_facts.dot_facts(text) == {
        "count": 1, "max_result_rank": 2, "batched_rank3plus": 0}


def test_host_callback_round_trip_detected():
    """jax host callbacks lower to custom-calls (NOT infeed/outfeed) —
    the 'no per-row host round-trips' contract must catch them."""
    import numpy as np

    from jax.experimental import io_callback

    def f(x):
        y = io_callback(lambda v: np.asarray(v) * 2,
                        jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1

    text = _compiled_text(f, SDS((8,), jnp.float32))
    assert hlo_facts.transfer_facts(text)["host_callbacks"] >= 1
    clean = _compiled_text(lambda x: x + 1, SDS((8,), jnp.float32))
    assert hlo_facts.transfer_facts(clean)["host_callbacks"] == 0


# ------------------------------------------------------- dtype leaks

def test_f64_leak_detected_and_clean_variant_quiet():
    from jax.experimental import enable_x64

    def leaky(x):
        return (x.astype(jnp.float64) * 2.0).sum()

    with enable_x64():
        text = _compiled_text(leaky, SDS((32,), jnp.float32))
        jx = jax.make_jaxpr(leaky)(SDS((32,), jnp.float32))
    assert hlo_facts.dtype_facts(text)["f64_present"]
    assert hlo_facts.dtype_facts(text)["f32_to_f64_converts"] >= 1
    assert hlo_facts.jaxpr_facts(jx)["f64_avals"] >= 1

    clean = _compiled_text(lambda x: (x * 2.0).sum(),
                           SDS((32,), jnp.float32))
    assert not hlo_facts.dtype_facts(clean)["f64_present"]


def test_bf16_convert_counting():
    def quantizing(q, sv):
        qc = q.astype(sv.dtype)  # the serving engine's one rounding
        return jnp.dot(qc, sv.T, preferred_element_type=jnp.float32)

    text = _compiled_text(quantizing, SDS((8, 4), jnp.float32),
                          SDS((16, 4), jnp.bfloat16))
    assert hlo_facts.dtype_facts(text)["f32_to_bf16_converts"] == 1


# --------------------------------------------------------- donation

def test_missed_donation_detected_and_donated_variant_quiet():
    def step(carry, delta):
        return carry + delta

    a = SDS((128,), jnp.float32)
    plain = jax.jit(step).lower(a, a)
    fx = hlo_facts.donation_facts(plain.compile().as_text())
    # Both inputs aval-match the output; one COULD be donated, none is.
    assert fx["aliased_outputs"] == 0
    assert fx["donatable"] >= 1
    assert fx["missed"] >= 1

    donated = jax.jit(step, donate_argnums=(0,)).lower(a, a)
    fd = hlo_facts.donation_facts(donated.compile().as_text())
    assert fd["aliased_outputs"] == 1
    assert fd["missed"] == fd["donatable"] - 1

    # unit_facts carries the jit-level declaration too.
    uf = unit_facts(Unit("d", lambda: donated))
    assert uf["donation"]["declared_donated"] == 1


# ------------------------------------------------- recompile hazards

def test_traced_branch_hazard_detected():
    def branchy(x):
        if x.sum() > 0:  # Python branch on a traced value
            return x
        return -x

    facts = unit_facts(Unit(
        "bad", lambda: jax.jit(branchy).lower(SDS((8,), jnp.float32))))
    assert facts["hazards"]["traced_branch"] is True
    assert "trace_error" in facts

    ok = unit_facts(Unit(
        "good", lambda: jax.jit(lambda x: jnp.where(x.sum() > 0, x, -x))
        .lower(SDS((8,), jnp.float32))))
    assert ok["hazards"]["traced_branch"] is False
    assert "trace_error" not in ok


def test_weak_type_arg_detected():
    def f(x, s):
        return x * s

    weak = jax.make_jaxpr(f)(SDS((8,), jnp.float32), 2.0)
    assert hlo_facts.jaxpr_facts(weak)["weak_in_avals"] == 1
    strong = jax.make_jaxpr(f)(SDS((8,), jnp.float32),
                               SDS((), jnp.float32))
    assert hlo_facts.jaxpr_facts(strong)["weak_in_avals"] == 0


# ------------------------------------------------- rank-3 kernel path

def test_rank3_batched_product_detected():
    text = _compiled_text(jnp.matmul, SDS((4, 8, 16), jnp.float32),
                          SDS((4, 16, 8), jnp.float32))
    facts = hlo_facts.dot_facts(text)
    assert facts["batched_rank3plus"] >= 1
    assert facts["max_result_rank"] == 3


# ------------------------------------------------ budget diff/verdict

def test_budget_check_names_entry_and_fact(tmp_path):
    from dpsvm_tpu.analysis import budget

    facts = {"dispatches": 1,
             "units": {"chunk": {"collectives": {
                 "all-reduce": {"count": 0}}}}}
    budget.write_budget("toy_entry", facts, tmp_path)
    assert budget.check_entry("toy_entry", facts,
                              tmp_path)["verdict"] == budget.PASS

    drifted = {"dispatches": 1,
               "units": {"chunk": {"collectives": {
                   "all-reduce": {"count": 3}}}}}
    res = budget.check_entry("toy_entry", drifted, tmp_path)
    assert res["verdict"] == budget.DRIFT
    (path, want, got), = res["diffs"]
    assert path == "units.chunk.collectives.all-reduce.count"
    assert (want, got) == (0, 3)
    table = budget.drift_table([res])
    assert "toy_entry" in table and "all-reduce.count" in table

    # The explicit allowlist tolerates (but still reports) the drift.
    import json
    doc = json.loads(budget.budget_path("toy_entry", tmp_path)
                     .read_text())
    doc["allow"] = ["units.chunk.collectives"]
    budget.budget_path("toy_entry", tmp_path).write_text(
        json.dumps(doc))
    res2 = budget.check_entry("toy_entry", drifted, tmp_path)
    assert res2["verdict"] == budget.PASS and res2["allowed"]

    # Missing budget is a hard failure, not a silent skip.
    assert budget.check_entry("other", facts,
                              tmp_path)["verdict"] == budget.MISSING

    # ... and so is the converse: a committed budget whose entrypoint
    # left the manifest (rename/delete) is ORPHANed lost coverage.
    assert budget.orphan_budgets(["toy_entry"], tmp_path) == []
    assert budget.orphan_budgets(["renamed_entry"],
                                 tmp_path) == ["toy_entry"]
    table = budget.drift_table([{"entry": "toy_entry",
                                 "verdict": budget.ORPHAN,
                                 "diffs": [], "allowed": []}])
    assert "no manifest entry" in table

    # write_budget records the generating jax version for in-suite
    # consumers to gate on (the facts are jax/XLA-version-coupled).
    assert budget.budget_jax_version(tmp_path) == jax.__version__

    # A partial regeneration under a different jax must be a hard
    # error, not whichever version sorts first.
    doc = json.loads(budget.budget_path("toy_entry", tmp_path)
                     .read_text())
    doc["jax"] = "0.0.0-other"
    budget.budget_path("zz_mixed", tmp_path).write_text(json.dumps(doc))
    import pytest
    with pytest.raises(ValueError, match="mixed jax versions"):
        budget.budget_jax_version(tmp_path)


def test_entry_facts_counts_dispatches():
    a = SDS((8,), jnp.float32)
    units = [Unit("one", lambda: jax.jit(lambda x: x + 1).lower(a)),
             Unit("two", lambda: jax.jit(lambda x: x * 2).lower(a))]
    facts = entry_facts(units)
    assert facts["dispatches"] == 2
    assert set(facts["units"]) == {"one", "two"}


# ------------------------------------------------- memory accounting

def test_memory_facts_present_and_deterministic():
    """The ISSUE 8 HBM-accounting facts: unit_facts carries a `memory`
    family (argument/output/temp/alias bytes from XLA's own
    memory_analysis) whose values are DETERMINISTIC across
    re-extraction — the property that lets budgets pin them with zero
    drift on regeneration."""
    a = SDS((64, 32), jnp.float32)

    def build():
        return unit_facts(Unit(
            "m", lambda: jax.jit(lambda x: (x @ x.T).sum(0)).lower(a)))

    f1, f2 = build(), build()
    mem = f1["memory"]
    assert set(mem) == {"argument_bytes", "output_bytes", "temp_bytes",
                        "alias_bytes"}
    assert mem["argument_bytes"] == 64 * 32 * 4
    assert mem["output_bytes"] == 64 * 4
    assert all(isinstance(v, int) and v >= 0 for v in mem.values())
    assert f1["memory"] == f2["memory"]  # zero drift on re-extraction


def test_memory_facts_see_donation_as_alias_bytes():
    """A donated carry shows up as alias_bytes — the footprint saving
    the donation satellite (PR 5) bought, now a pinned number."""
    a = SDS((256,), jnp.float32)
    donated = unit_facts(Unit(
        "d", lambda: jax.jit(lambda x: x * 2.0,
                             donate_argnums=0).lower(a)))
    plain = unit_facts(Unit(
        "p", lambda: jax.jit(lambda x: x * 2.0).lower(a)))
    assert donated["memory"]["alias_bytes"] == 256 * 4
    assert plain["memory"]["alias_bytes"] == 0


def test_ooc_fold_tile_budget_independent_of_n():
    """The out-of-core contract, mutation-verified (ISSUE 9): the
    per-tile fold program's facts — including the memory family's
    argument/output/temp bytes — are a pure function of
    (tile_rows, d, q). Rebuilding the manifest entry with total n
    DOUBLED must produce byte-identical facts: if anyone threads an
    (n, ...)-shaped operand into the tile program (full X, full f, the
    whole cache), argument_bytes moves and this fails."""
    from dpsvm_tpu.analysis.manifest import (N, T_TILE, ooc_fold_tile,
                                             require_devices)

    require_devices()
    base = entry_facts(ooc_fold_tile(N))
    doubled = entry_facts(ooc_fold_tile(2 * N))
    assert base == doubled
    mem = base["units"]["fold_tile"]["memory"]
    # Tile-pool-scale arguments only: the (T, d) tile + its norms +
    # the gradient slice + the q-sized working-set operands.
    from dpsvm_tpu.analysis.manifest import D, Q
    assert mem["argument_bytes"] == (
        T_TILE * D * 4 + T_TILE * 4 + T_TILE * 4
        + Q * D * 4 + Q * 4 + Q * 4)
    coll = base["units"]["fold_tile"]["collectives"]
    assert all(v["count"] == 0 for v in coll.values())
    tr = base["units"]["fold_tile"]["transfers"]
    assert all(v == 0 for v in tr.values())
    don = base["units"]["fold_tile"]["donation"]
    assert don["missed"] == 0 and don["declared_donated"] == 1


def test_fusedround_extra_hbm_pass_drifts():
    """The one-pass contract, mutation-verified (ISSUE 12, the
    ooc_fold_tile n-doubling discipline): the clean fused-round chunk
    must PASS its committed budget, and the extra_pass mutation — the
    same chunk plus one re-materialized XLA kernel-row pass over X,
    with the identical donation declaration — must DRIFT, naming a
    fact the extra pass moved (the dot count / temp bytes). Also pins
    the headline zeros the budget exists for: zero collectives, zero
    host-boundary transfers, donated carry, and the device form's
    zero-XLA-collective + single-gather-DMA kernel structure."""
    import json

    import pytest

    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.manifest import (block_chunk_fusedround,
                                             require_devices)

    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        pytest.skip(
            f"budgets generated under jax {gen}, running {jax.__version__}")
    require_devices()

    clean = entry_facts(block_chunk_fusedround())
    assert budget.check_entry("block_chunk_fusedround",
                              clean)["verdict"] == budget.PASS
    u = clean["units"]["chunk"]
    assert all(v["count"] == 0 for v in u["collectives"].values())
    assert all(v == 0 for v in u["transfers"].values())
    assert u["donation"]["missed"] == 0
    assert u["donation"]["declared_donated"] == 6  # the BlockState carry
    df = u["device_form"]
    assert df["xla_collective_total"] == 0
    # The in-kernel row gather's two DMA issue sites (pipeline warm-up
    # + in-loop refill), and nothing else.
    assert df["dma_starts"] == 2

    mutated = entry_facts(block_chunk_fusedround(extra_pass=True))
    res = budget.check_entry("block_chunk_fusedround", mutated)
    assert res["verdict"] == budget.DRIFT
    drifted_paths = [p for p, _, _ in res["diffs"]]
    assert any("dots" in p or "memory" in p for p in drifted_paths), \
        json.dumps(drifted_paths)


def test_ooc_shrink_fold_budget_and_masked_variant_drifts():
    """The shrunken-stream skip contract, mutation-verified (ISSUE 19):
    a skipped tile is a dispatch that never happens, so the in-cycle
    fold program (ooc_fold_tile at want_dots=False) stays a pure
    function of (T_TILE, D, Q) — n-doubling must be byte-identical,
    and the clean entry must PASS its committed budget. The REJECTED
    masked-kernel alternative — one program folding every tile of a
    device-resident (n, D) X under a live mask — must DRIFT, because
    its argument bytes are n-sized: exactly the out-of-core violation
    the budget exists to catch."""
    import json

    import pytest

    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.manifest import (D, N, Q, T_TILE,
                                             ooc_fold_tile_shrink,
                                             require_devices)

    require_devices()
    clean = entry_facts(ooc_fold_tile_shrink(N))
    assert clean == entry_facts(ooc_fold_tile_shrink(2 * N))
    u = clean["units"]["fold_tile"]
    # Tile-pool-scale arguments only (the ooc_fold_tile formula):
    # the (T, d) tile + its norms + the gradient slice + the q-sized
    # working-set operands.
    assert u["memory"]["argument_bytes"] == (
        T_TILE * D * 4 + T_TILE * 4 + T_TILE * 4
        + Q * D * 4 + Q * 4 + Q * 4)
    assert all(v["count"] == 0 for v in u["collectives"].values())
    assert all(v == 0 for v in u["transfers"].values())
    assert u["donation"]["missed"] == 0
    assert u["donation"]["declared_donated"] == 1

    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        pytest.skip(
            f"budgets generated under jax {gen}, running {jax.__version__}")
    assert budget.check_entry("ooc_fold_tile_shrink",
                              clean)["verdict"] == budget.PASS

    masked = entry_facts(ooc_fold_tile_shrink(N, masked=True))
    res = budget.check_entry("ooc_fold_tile_shrink", masked)
    assert res["verdict"] == budget.DRIFT
    drifted_paths = [p for p, _, _ in res["diffs"]]
    assert any("argument_bytes" in p for p in drifted_paths), \
        json.dumps(drifted_paths)
    # And the masked form is NOT n-independent: doubling n doubles its
    # resident operands — the property the budget's n-doubling pin
    # would silently lose if the stream ever became a masked kernel.
    masked2 = entry_facts(ooc_fold_tile_shrink(2 * N, masked=True))
    assert (masked2["units"]["fold_tile"]["memory"]["argument_bytes"]
            > masked["units"]["fold_tile"]["memory"]["argument_bytes"])


def test_ooc_mesh_fold_budget_and_extra_psum_drifts():
    """The mesh-stream collective budget, mutation-verified (ISSUE 19):
    the per-step local fold is ZERO-collective (each device folds only
    its own shard's tile) and the round's ONLY collectives live in the
    select unit — the candidate all_gather pair plus ONE (Q, 5)
    all-reduce replicating the working-set scalars. The extra_psum
    mutation — the same fold body plus one per-step psum — must DRIFT
    against the committed budget, naming the fold unit's collective
    facts."""
    import json

    import pytest

    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.manifest import (Q, ooc_mesh_fold,
                                             require_devices)

    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        pytest.skip(
            f"budgets generated under jax {gen}, running {jax.__version__}")
    require_devices()

    clean = entry_facts(ooc_mesh_fold())
    assert budget.check_entry("ooc_mesh_fold",
                              clean)["verdict"] == budget.PASS
    fold = clean["units"]["fold"]
    assert all(v["count"] == 0 for v in fold["collectives"].values())
    assert all(v == 0 for v in fold["transfers"].values())
    assert fold["donation"]["missed"] == 0
    sel = clean["units"]["select"]
    # ONE psum of the (Q, 5) [x_sq|k_diag|alpha|y|f] scalar stack...
    assert sel["collectives"]["all-reduce"]["count"] == 1
    assert sel["collectives"]["all-reduce"]["payload_bytes"] == [Q * 5 * 4]
    # ...plus the exact top-k merge's (value, id) all_gather pair, and
    # nothing else crosses devices in the whole round.
    assert sel["collectives"]["all-gather"]["count"] == 2
    for k in ("all-to-all", "collective-permute", "reduce-scatter"):
        assert sel["collectives"][k]["count"] == 0
    assert all(v == 0 for v in sel["transfers"].values())

    mutated = entry_facts(ooc_mesh_fold(extra_psum=True))
    res = budget.check_entry("ooc_mesh_fold", mutated)
    assert res["verdict"] == budget.DRIFT
    drifted_paths = [p for p, _, _ in res["diffs"]]
    assert any(p.startswith("units.fold.collectives") for p in
               drifted_paths), json.dumps(drifted_paths)


# ------------------------------------- the committed budgets (tier-1)

def test_manifest_budgets_pass_against_committed(monkeypatch):
    """The in-suite `tpulint --check`: every manifest entrypoint's
    re-extracted facts must match the committed budget files exactly.
    A structural regression in ANY budgeted entrypoint — a stray
    collective, a dtype leak, a lost donation, an extra dispatch —
    fails here with the entry and fact path in the message.

    Runs with the telemetry spine ENABLED (DPSVM_OBS=1 + a live
    registry — ISSUE 7) AND a live /metrics exporter thread serving
    scrapes throughout the extraction (ISSUE 8): observability — run
    logs, registry metrics, compile sinks, the HTTP endpoint — must
    change NO compiled HLO fact (including the new `memory.*` family)
    on any manifest entrypoint (obs off is a strict subset: the
    instrumented code paths simply don't run)."""
    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.extract import extract_entries
    from dpsvm_tpu.analysis.manifest import MANIFEST, require_devices
    from dpsvm_tpu.obs import metrics as obs_metrics
    from dpsvm_tpu.obs.export import MetricsExporter

    monkeypatch.setenv("DPSVM_OBS", "1")
    # Re-resolve the default registry from the patched env; monkeypatch
    # restores the previous registry object after the test.
    monkeypatch.setattr(obs_metrics, "_DEFAULT", None)
    assert obs_metrics.get_registry().enabled

    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        import pytest
        pytest.skip(
            f"budgets generated under jax {gen}, running {jax.__version__}"
            " — exact HLO facts are version-coupled; the pinned CI "
            "tpulint job (tier1.yml) is the gate for this check")

    require_devices()
    with MetricsExporter(lambda: "# EOF\n", port=0) as exporter:
        # The endpoint answers while the whole manifest traces and
        # compiles in this process — the "budget check stays at zero
        # diffs with the exporter running" acceptance pin.
        import urllib.request

        with urllib.request.urlopen(exporter.url, timeout=10) as r:
            assert r.status == 200
        observed = extract_entries(MANIFEST)
        with urllib.request.urlopen(exporter.url, timeout=10) as r:
            assert r.read().decode().endswith("# EOF\n")
    results = [budget.check_entry(entry, facts)
               for entry, facts in observed.items()]
    results += [{"entry": e, "verdict": budget.ORPHAN, "diffs": [],
                 "allowed": []}
                for e in budget.orphan_budgets(MANIFEST)]
    bad = [r for r in results if r["verdict"] != budget.PASS]
    assert not bad, "\n" + budget.drift_table(results)
