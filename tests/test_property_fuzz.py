"""Property-based fuzz tests: randomized small problems across kernels,
engines, selections and class weights, asserting the solver CONTRACTS
rather than specific values:

  * convergence within a generous iteration budget,
  * the KKT stopping condition actually holds on the returned alpha
    (recomputed from scratch — catches any drift between the solver's
    internal f and the true gradient, the class of bug that once hid in
    the mesh scatter),
  * exact dual-equality conservation sum(alpha * y) = 0,
  * box feasibility 0 <= alpha_i <= C_{y_i}.

The reference has nothing of this kind (SURVEY.md section 4: no tests at
all); deterministic seeds keep failures reproducible.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
from dpsvm_tpu.solver.smo import solve

EPS = 1e-3


def _random_problem(rng):
    n = int(rng.integers(24, 180))
    d = int(rng.integers(2, 24))
    x = rng.normal(size=(n, d)).astype(np.float32)
    # Nontrivial labels with both classes guaranteed.
    w = rng.normal(size=d)
    y = np.where(x @ w + 0.3 * rng.normal(size=n) > 0, 1, -1).astype(np.int32)
    y[0], y[1] = 1, -1
    return x, y


def _random_config(rng):
    kernel = str(rng.choice(["rbf", "linear", "poly", "sigmoid"]))
    kw = dict(
        kernel=kernel,
        c=float(10.0 ** rng.uniform(-1, 2)),
        gamma=float(10.0 ** rng.uniform(-2, 0)),
        epsilon=EPS,
        max_iter=400_000,
        degree=int(rng.integers(2, 4)),
        coef0=float(rng.uniform(0, 1)) if kernel in ("poly", "sigmoid") else 0.0,
    )
    if rng.random() < 0.3:
        kw["weight_pos"] = float(10.0 ** rng.uniform(-0.5, 0.5))
        kw["weight_neg"] = float(10.0 ** rng.uniform(-0.5, 0.5))
    mode = rng.integers(4)
    if mode == 1:
        kw["engine"] = "block"
        kw["working_set_size"] = int(rng.choice([8, 16, 64]))
    elif mode == 2:
        kw["selection"] = "second_order"
    elif mode == 3:
        # Batched disjoint-pair subproblem steps (SVMConfig.pair_batch):
        # the same contracts must hold when two exact pair updates
        # retire per inner trip.
        kw["engine"] = "block"
        kw["working_set_size"] = int(rng.choice([8, 16, 64]))
        kw["pair_batch"] = 2
    if rng.random() < 0.3:
        kw["cache_lines"] = int(rng.integers(4, 64))
    return SVMConfig(**kw)


def _check_contracts(x, y, cfg, res):
    cp, cn = cfg.c_bounds()
    c_i = np.where(y > 0, cp, cn)
    a = res.alpha
    assert np.all(a >= -1e-6), "alpha below 0"
    assert np.all(a <= c_i + 1e-5 * c_i), "alpha above class box"
    assert abs(np.dot(a, y)) < 1e-3 * max(1.0, np.abs(a).sum()), "conservation"
    if not res.converged:
        return  # iteration cap: no KKT promise (should not happen here)
    kp = KernelParams(cfg.kernel, cfg.resolve_gamma(x.shape[1]),
                      cfg.degree, cfg.coef0)
    K = np.asarray(kernel_matrix(x, x, kp), np.float64)
    f = (a * y) @ K - y
    # The solver's internal gradient must agree with the from-scratch
    # fp64 one to fp32-accumulation tolerance. This is the bug-catcher:
    # a lost/duplicated alpha update desyncs them by O(C) (the mesh
    # scatter regression showed drift 0.5), while honest fp32 drift on
    # these problem sizes stays ~1e-5 relative.
    drift = float(np.abs(res.stats["f"] - f).max())
    scale = max(1.0, float(np.abs(f).max()))
    assert drift <= 5e-2 * scale, f"f drift {drift} vs scale {scale}"
    up = np.where(y > 0, a < c_i - 1e-5 * c_i, a > 1e-6)
    low = np.where(y > 0, a > 1e-6, a < c_i - 1e-5 * c_i)
    if up.any() and low.any():
        gap = f[low].max() - f[up].min()
        # Slack beyond 2 eps: the engine applies the final pair update
        # AFTER measuring the gap (reference do-while parity,
        # svmTrainMain.cpp:235-310), so the RETURNED alpha's gap can
        # overshoot 2 eps by one step's ripple (observed up to ~2.5x eps
        # on low-eta linear problems). The bound below still fails loudly
        # on genuine non-convergence (the mesh scatter regression showed
        # gap = 120x eps).
        assert gap <= 8 * EPS + 2 * drift, f"KKT gap {gap} (drift {drift})"


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_solver_contracts(seed):
    rng = np.random.default_rng(1000 + seed)
    x, y = _random_problem(rng)
    cfg = _random_config(rng)
    res = solve(x, y, cfg)
    assert res.converged, (
        f"seed {seed} did not converge in {cfg.max_iter} iterations: {cfg}")
    _check_contracts(x, y, cfg, res)


def test_duplicate_points_eta_clamp():
    """Identical rows make eta = 0 for their pair; the tau clamp (bug B2
    fix) must keep the solver finite and convergent."""
    rng = np.random.default_rng(7)
    base = rng.normal(size=(20, 5)).astype(np.float32)
    x = np.vstack([base, base])  # every point duplicated
    y = np.concatenate([np.ones(20), -np.ones(20)]).astype(np.int32)
    for engine in ("xla", "block"):
        res = solve(x, y, SVMConfig(c=5.0, gamma=0.3, epsilon=EPS,
                                    max_iter=200_000, engine=engine,
                                    working_set_size=8))
        assert res.converged
        assert np.all(np.isfinite(res.alpha))


def test_minimal_two_point_problem():
    x = np.array([[0.0, 1.0], [1.0, 0.0]], np.float32)
    y = np.array([1, -1], np.int32)
    res = solve(x, y, SVMConfig(c=1.0, gamma=1.0, epsilon=EPS))
    assert res.converged
    # Symmetric problem: both alphas equal, at most C.
    assert res.alpha[0] == pytest.approx(res.alpha[1], abs=1e-5)


def test_constant_feature_column():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(60, 6)).astype(np.float32)
    x[:, 2] = 4.2  # constant column must not break norms/kernels
    y = np.where(x[:, 0] > 0, 1, -1).astype(np.int32)
    y[0], y[1] = 1, -1
    cfg = SVMConfig(c=2.0, gamma=0.2, epsilon=EPS, max_iter=200_000)
    res = solve(x, y, cfg)
    assert res.converged
    _check_contracts(x, y, cfg, res)
