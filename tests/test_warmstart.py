"""Warm-start carries (solver/warmstart.py, solver/cascade.py —
ISSUE 18).

Three load-bearing contracts:

* ZERO-SEED ROUTING: a seed that repairs to all-zeros (including the
  literal zero vector) must route BIT-IDENTICALLY through the cold
  path on every engine — same iterations, same alpha bits, same
  gradient bits.  prepare_warm_start returns (None, None, stats) so the
  solvers' existing ``alpha_init is None`` branches run untouched.
* FEASIBILITY REPAIR: for ANY seed — out-of-box, unbalanced,
  carried from a larger C into a shrunk box — the repaired alphas sit
  inside the per-class box and satisfy sum(alpha_i y_i) = 0.
* ONE SHARED FOLD: the warm gradient rebuild streams through
  ops/ooc.ooc_fold_tile (want_dots=False) — no second Gram-pass
  implementation — and the mesh rebuild (one psum per seed block) is
  BITWISE equal to the single-chip tile stream.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.solver.smo import solve
from dpsvm_tpu.solver.warmstart import (WarmStart, prepare_warm_start,
                                        repair_seed, seed_from_model,
                                        warm_f_rebuild, warm_rebuild_mesh)

CFG = SVMConfig(c=1.5, epsilon=1e-3, max_iter=50_000)


def _kp(cfg, d):
    return KernelParams(cfg.kernel, cfg.resolve_gamma(d), cfg.degree,
                        cfg.coef0)


def _assert_bitwise(a, b):
    assert a.iterations == b.iterations
    assert a.converged == b.converged
    assert a.b_hi == b.b_hi and a.b_lo == b.b_lo
    np.testing.assert_array_equal(a.alpha, b.alpha)
    np.testing.assert_array_equal(a.stats["f"], b.stats["f"])


@pytest.fixture(scope="module")
def data():
    return make_blobs_binary(n=256, d=8, seed=3, sep=0.9)


# --------------------------------------------- zero-seed routing pins

def test_zero_seed_bitwise_single(data):
    x, y = data
    cold = solve(x, y, CFG)
    warm = solve(x, y, CFG, warm_start=WarmStart(alpha=np.zeros(len(y))))
    _assert_bitwise(cold, warm)
    assert warm.stats["warm_start"]["zero_seed"] is True


def test_zero_seed_bitwise_mesh(data):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = data
    cold = solve_mesh(x, y, CFG, num_devices=8)
    warm = solve_mesh(x, y, CFG, num_devices=8,
                      warm_start=WarmStart(alpha=np.zeros(len(y))))
    _assert_bitwise(cold, warm)
    assert warm.stats["warm_start"]["zero_seed"] is True


def test_zero_seed_bitwise_ooc(data):
    x, y = data
    cfg = CFG.replace(engine="block", working_set_size=64, ooc=True,
                      ooc_tile_rows=64)
    cold = solve(x, y, cfg)
    warm = solve(x, y, cfg, warm_start=WarmStart(alpha=np.zeros(len(y))))
    _assert_bitwise(cold, warm)
    assert warm.stats["warm_start"]["zero_seed"] is True


def test_zero_seed_bitwise_fleet(data):
    """The fleet's carry is per-problem alpha_init/f_init; the zero
    carry (alpha=0, f=-y) IS the cold start and must not perturb a
    single bit of the trajectory."""
    from dpsvm_tpu.solver.fleet import FleetProblem, solve_fleet

    x, y = data
    cfg = SVMConfig(c=1.5, epsilon=1e-3, max_iter=50_000)
    cold = solve_fleet(x, [FleetProblem(y=y)], cfg)[0]
    warm = solve_fleet(x, [FleetProblem(
        y=y, alpha_init=np.zeros(len(y), np.float32),
        f_init=(-np.asarray(y)).astype(np.float32))], cfg)[0]
    assert cold.iterations == warm.iterations
    np.testing.assert_array_equal(cold.alpha, warm.alpha)
    np.testing.assert_array_equal(cold.stats["f"], warm.stats["f"])


def test_seed_rows_out_of_range_rejected(data):
    x, y = data
    bad = WarmStart(alpha=np.ones(4), rows=np.array([0, 1, 2, len(y)]))
    with pytest.raises(ValueError, match="out of range"):
        solve(x, y, CFG, warm_start=bad)
    with pytest.raises(ValueError, match="not both"):
        solve(x, y, CFG, warm_start=WarmStart(alpha=np.zeros(len(y))),
              alpha_init=np.zeros(len(y), np.float32),
              f_init=np.zeros(len(y), np.float32))


# ------------------------------------------- feasibility-repair laws

def _check_feasible(a, y, c_bounds):
    c_pos, c_neg = c_bounds
    box = np.where(np.asarray(y, np.float64) > 0, c_pos, c_neg)
    assert np.all(a >= 0.0) and np.all(a <= box + 1e-12)
    assert abs(float(np.dot(a, np.asarray(y, np.float64)))) < 1e-9


def test_repair_adversarial_seeds_property():
    """Random out-of-box, negative, unbalanced seeds against random
    (asymmetric) boxes: the repaired seed always satisfies BOTH dual
    constraints."""
    rng = np.random.default_rng(7)
    for trial in range(20):
        n = int(rng.integers(8, 200))
        y = np.where(rng.random(n) < 0.5, 1, -1).astype(np.int32)
        if np.all(y == y[0]):  # degenerate single-class draw
            y[0] = -y[0]
        c_bounds = (float(rng.uniform(0.1, 3.0)),
                    float(rng.uniform(0.1, 3.0)))
        seed = rng.uniform(-2.0, 4.0, size=n)
        a, st = repair_seed(seed, y, c_bounds)
        _check_feasible(a, y, c_bounds)
        assert st["seed_nnz"] == int(np.count_nonzero(a))
        # Idempotence: repairing a feasible point is (near-)identity.
        a2, _ = repair_seed(a, y, c_bounds)
        np.testing.assert_allclose(a2, a, rtol=0, atol=1e-12)


def test_repair_c_shrink_across_generations(data):
    """The cascade/C-sweep case: a converged solution at C=4 carried
    into a generation trained at C=0.25 — clipping into the shrunk box
    unbalances the class sides; the repair must restore equality."""
    x, y = data
    big = solve(x, y, CFG.replace(c=4.0))
    shrunk = SVMConfig(c=0.25)
    a, st = repair_seed(np.asarray(big.alpha, np.float64), y,
                        shrunk.c_bounds())
    _check_feasible(a, y, shrunk.c_bounds())
    assert st["clipped"] > 0 and not st["zero_seed"]
    # And the solver accepts the carry end-to-end.
    res = solve(x, y, CFG.replace(c=0.25),
                warm_start=WarmStart(alpha=np.asarray(big.alpha,
                                                      np.float64)))
    assert res.converged
    # The solver iterates in f32 — its output satisfies the equality
    # to f32 round-off (the repair's exact-zero bar is f64-only).
    a_out = np.asarray(res.alpha, np.float64)
    box = np.where(np.asarray(y, np.float64) > 0,
                   shrunk.c_bounds()[0], shrunk.c_bounds()[1])
    assert np.all(a_out >= 0.0) and np.all(a_out <= box + 1e-6)
    assert abs(float(np.dot(a_out, np.asarray(y, np.float64)))) < 1e-4


def test_repair_one_sided_seed_is_cold():
    """Mass on one class only: no feasible rescale exists except
    alpha=0 — the repair must declare a zero seed (which the solvers
    route through the cold path)."""
    y = np.array([1, 1, -1, -1], np.int32)
    a, st = repair_seed(np.array([1.0, 0.5, 0.0, 0.0]), y, (1.0, 1.0))
    assert st["zero_seed"] and np.all(a == 0.0)
    a0, f0, st2 = prepare_warm_start(
        np.zeros((4, 2), np.float32), y, SVMConfig(c=1.0),
        WarmStart(alpha=np.array([1.0, 0.5, 0.0, 0.0])))
    assert a0 is None and f0 is None and st2["zero_seed"]


# ------------------------------------- the ONE streamed gradient fold

def test_warm_rebuild_matches_f64_reference_and_shares_fold(monkeypatch):
    """f = K (alpha*y) - y from the tile stream matches the host-f64
    kernel evaluation, and every device fold routes through the ONE
    shared tile kernel — ops/ooc.ooc_fold_tile with want_dots=False
    (the dedup contract: no second Gram-pass implementation)."""
    import dpsvm_tpu.ops.ooc as ooc_mod

    x, y = make_blobs_binary(n=300, d=12, seed=5, sep=0.8)
    res = solve(x, y, CFG)
    a, _ = repair_seed(np.asarray(res.alpha, np.float64), y,
                       CFG.c_bounds())
    kp = _kp(CFG, 12)

    calls = []
    orig = ooc_mod.ooc_fold_tile

    def spy(*args, **kw):
        calls.append(kw)
        return orig(*args, **kw)

    monkeypatch.setattr(ooc_mod, "ooc_fold_tile", spy)
    f = warm_f_rebuild(x, y, a, kp, tile_rows=128)
    assert calls and all(k.get("want_dots") is False for k in calls)

    # Host-f64 reference: the one shared f64 kernel definition.
    from dpsvm_tpu.solver.reconstruct import gram_matvec_f64

    coef = a * np.asarray(y, np.float64)
    f_ref = gram_matvec_f64(x, coef, kp) - np.asarray(y, np.float64)
    np.testing.assert_allclose(f, f_ref, rtol=0, atol=5e-5)


def test_mesh_rebuild_bitwise_vs_single_chip():
    """The one-psum mesh rebuild reproduces the single-chip tile
    stream BIT-FOR-BIT: the one-hot psum gather is f32-exact, and the
    per-row fold contracts over the same q_block operands in both
    forms."""
    x, y = make_blobs_binary(n=700, d=12, seed=9, sep=0.8)
    rng = np.random.default_rng(0)
    seed = rng.uniform(0.0, 1.5, size=700) * (rng.random(700) < 0.2)
    a, _ = repair_seed(seed, y, (1.5, 1.5))
    kp = _kp(CFG, 12)
    f_single = warm_f_rebuild(x, y, a, kp, tile_rows=128)
    f_mesh = warm_rebuild_mesh(x, y, a, kp, num_devices=8)
    np.testing.assert_array_equal(f_single, f_mesh)


# ------------------------------- warm-vs-cold model agreement (mnist)

def test_warm_vs_cold_same_model_mnist_shape():
    """The increment retrain on mnist-shaped synth (d=784): warm solve
    seeded from the previous generation's SVs reaches the same model as
    the cold solve of the increment — within tolerance — for fewer
    pairs."""
    rng = np.random.default_rng(11)
    d, n0, n1 = 784, 192, 96
    centers = rng.normal(size=(2, d)) * 0.35

    def draw(n):
        lab = rng.integers(0, 2, size=n)
        xs = (centers[lab] + rng.normal(size=(n, d))).astype(np.float32)
        return xs, np.where(lab > 0, 1, -1).astype(np.int32)

    x0, y0 = draw(n0)
    xf, yf = draw(n1)
    cfg = SVMConfig(c=1.0, epsilon=1e-3, max_iter=50_000)
    kp = _kp(cfg, d)
    base = solve(x0, y0, cfg)
    m0 = SVMModel.from_dense(x0, y0, base.alpha, base.b, kp)

    x_inc = np.concatenate([np.asarray(m0.sv_x, np.float32), xf])
    y_inc = np.concatenate([np.asarray(m0.sv_y, np.int32), yf])
    cold = solve(x_inc, y_inc, cfg)
    warm = solve(x_inc, y_inc, cfg, warm_start=seed_from_model(m0))
    assert warm.converged and cold.converged
    assert warm.iterations < cold.iterations  # the perf claim, in small
    assert warm.stats["warm_start"]["seed_rows"] > 0

    import importlib

    predict = importlib.import_module("dpsvm_tpu.predict")
    mc = SVMModel.from_dense(x_inc, y_inc, cold.alpha, cold.b, kp)
    mw = SVMModel.from_dense(x_inc, y_inc, warm.alpha, warm.b, kp)
    xt, _ = draw(128)
    agree = float(np.mean(predict.predict(mc, xt)
                          == predict.predict(mw, xt)))
    assert agree >= 0.97


# --------------------------------------------------- cascade merging

def test_cascade_partition_covers_exactly_once():
    from dpsvm_tpu.solver.cascade import cascade_partition

    for n, b in [(1000, 256), (256, 256), (257, 256), (5, 64)]:
        blocks = cascade_partition(n, b)
        allidx = np.concatenate(blocks)
        assert sorted(allidx.tolist()) == list(range(n))
        sizes = {len(blk) for blk in blocks}
        assert max(sizes) - min(sizes) <= 1  # strided => balanced


def test_cascade_solve_agrees_with_flat_solve():
    from dpsvm_tpu.solver.cascade import cascade_solve

    x, y = make_blobs_binary(n=400, d=10, seed=13, sep=0.8)
    cfg = SVMConfig(c=1.0, epsilon=1e-3, max_iter=50_000)
    kp = _kp(cfg, 10)
    flat = solve(x, y, cfg)
    res, st = cascade_solve(x, y, cfg, block_rows=128)
    assert res.converged
    assert st["blocks"] and st["final_iterations"] >= 0
    assert res.stats["cascade"] is st

    import importlib

    predict = importlib.import_module("dpsvm_tpu.predict")
    mf = SVMModel.from_dense(x, y, flat.alpha, flat.b, kp)
    mc = SVMModel.from_dense(x, y, res.alpha, res.b, kp)
    xt, _ = make_blobs_binary(n=200, d=10, seed=14, sep=0.8)
    agree = float(np.mean(predict.predict(mf, xt)
                          == predict.predict(mc, xt)))
    assert agree >= 0.97


def test_cascade_degenerates_to_single_warm_solve(data):
    """Increments at or under block_rows run as ONE warm solve — no
    block stage, one seeded final solve (the cli learn default)."""
    from dpsvm_tpu.solver.cascade import cascade_solve

    x, y = data
    res, st = cascade_solve(x, y, CFG, block_rows=4096)
    assert len(st["blocks"]) <= 1
    flat = solve(x, y, CFG)
    _assert_bitwise(flat, res)  # seedless degenerate IS the cold solve


# ------------------------------------------------- warm C-sweep walk

def test_svc_c_sweep_warm_walk_matches_cold():
    from dpsvm_tpu.estimators import svc_c_sweep

    x, y = make_blobs_binary(n=160, d=8, seed=17, sep=0.8)
    cs = [2.0, 0.5, 1.0]  # unsorted: results must come back in Cs order
    cold = svc_c_sweep(x, y, cs, gamma=0.2, tol=1e-3, backend="single")
    warm = svc_c_sweep(x, y, cs, gamma=0.2, tol=1e-3, backend="single",
                       warm=True)
    assert [e.C for e in warm] == cs
    for ec, ew in zip(cold, warm):
        agree = float(np.mean(ec.predict(x) == ew.predict(x)))
        assert agree >= 0.95
