"""Fused fold+select block rounds (ops/pallas_fold_select.py).

Correctness on CPU via Pallas interpret mode (config.fused_fold=True);
the real-TPU Mosaic lowering is exercised by tools/tpu_smoke.py.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve

BASE = SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3, max_iter=200_000,
                 engine="block", working_set_size=32)


def _plain(cfg):
    return cfg.replace(fused_fold=False)


def _fused(cfg):
    return cfg.replace(fused_fold=True)


@pytest.mark.parametrize("selection", ["mvp", "second_order"])
def test_fused_matches_plain_optimum(blobs_medium, selection):
    x, y = blobs_medium
    cfg = BASE.replace(selection=selection)
    rp = solve(x, y, _plain(cfg))
    rf = solve(x, y, _fused(cfg))
    assert rp.converged and rf.converged
    # Different (both exact-extrema) candidate recall patterns => round
    # sequences differ, but the optimum must match: compare dual state.
    np.testing.assert_allclose(rf.alpha, rp.alpha, atol=5e-2)
    assert rf.b == pytest.approx(rp.b, abs=5e-3)
    assert abs(rf.n_sv - rp.n_sv) <= max(3, 0.03 * rp.n_sv)


def test_fused_matches_per_pair_reference(blobs_small):
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16)
    rf = solve(x, y, _fused(cfg))
    rx = solve(x, y, SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3,
                               max_iter=200_000))
    assert rf.converged and rx.converged
    np.testing.assert_allclose(rf.alpha, rx.alpha, atol=5e-2)
    assert rf.b == pytest.approx(rx.b, abs=5e-3)


def test_fused_class_weights(blobs_small):
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, weight_pos=2.0, weight_neg=0.5)
    rf = solve(x, y, _fused(cfg))
    rp = solve(x, y, _plain(cfg))
    assert rf.converged and rp.converged
    np.testing.assert_allclose(rf.alpha, rp.alpha, atol=5e-2)
    assert rf.b == pytest.approx(rp.b, abs=5e-3)


def test_fused_budget_mode_exact_pairs(blobs_medium):
    # The headline bench's regime: exactly max_iter pair updates.
    x, y = blobs_medium
    cfg = BASE.replace(budget_mode=True, max_iter=1000, inner_iters=50)
    rf = solve(x, y, _fused(cfg))
    assert rf.iterations == 1000


def test_fused_compensated_carry(blobs_small):
    # At extreme C the dual face is degenerate: different (exact) round
    # sequences land on different alphas, so compare what is determined —
    # the decision function (from the exact f64 gradient) and b.
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.reconstruct import gram_matvec_f64

    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, c=2000.0, gamma=0.05,
                       compensated=True)
    rf = solve(x, y, _fused(cfg))
    rp = solve(x, y, _plain(cfg))
    assert rf.converged and rp.converged

    kp = KernelParams("rbf", cfg.gamma)

    def dec(r):
        f64 = gram_matvec_f64(x, np.asarray(r.alpha, np.float64) * y, kp)
        return f64 - r.b

    agree = np.mean(np.sign(dec(rf)) == np.sign(dec(rp)))
    assert agree >= 0.995
    assert rf.b == pytest.approx(rp.b, abs=5e-2)


def test_fused_with_reconstruction_legs(blobs_small):
    # The extreme-C accuracy mode composes with the fused rounds.
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, c=2000.0, gamma=0.05,
                       compensated=True, reconstruct_every=40_000,
                       max_iter=400_000)
    rf = solve(x, y, _fused(cfg))
    assert rf.converged
    assert rf.stats["true_gap"] <= 2 * cfg.epsilon + 1e-9


def test_fused_auto_falls_back_small_n():
    # q/2 > n/128: every slot cannot find a candidate row; auto must
    # fall back to the plain path rather than compile a broken top_k.
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=200, d=6, seed=1, sep=1.5)
    cfg = BASE.replace(working_set_size=128)  # h=64 > 200/128
    r = solve(x, y, cfg.replace(fused_fold=None))
    assert r.converged


def test_fold_select_kernel_unit():
    """Direct kernel check against a NumPy oracle."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_fold_select import (assemble_working_set,
                                                  fold_select)

    rng = np.random.default_rng(4)
    n, c = 2048, 1.5
    f = rng.normal(size=n).astype(np.float32)
    delta = rng.normal(size=n).astype(np.float32) * 0.1
    alpha = rng.uniform(0, c, size=n).astype(np.float32)
    alpha[rng.random(n) < 0.3] = 0.0
    alpha[rng.random(n) < 0.3] = c
    y = np.where(rng.random(n) < 0.5, 1.0, -1.0).astype(np.float32)
    valid = np.ones(n, np.float32)
    valid[-100:] = 0.0

    shp = (n // 128, 128)
    f_new, _, upv, upi, lov, loi = fold_select(
        jnp.asarray(f.reshape(shp)), None,
        jnp.asarray(alpha.reshape(shp)), jnp.asarray(y.reshape(shp)),
        jnp.asarray(valid.reshape(shp)), jnp.asarray(delta.reshape(shp)),
        c, interpret=True)
    np.testing.assert_allclose(np.asarray(f_new).ravel(), f + delta,
                               rtol=1e-6)

    fn = f + delta
    up = np.where(y > 0, alpha < c, alpha > 0) & (valid > 0)
    low = np.where(y > 0, alpha > 0, alpha < c) & (valid > 0)
    f_up = np.where(up, fn, np.inf)
    f_low = np.where(low, fn, -np.inf)
    w, slot_ok, b_hi, b_lo = assemble_working_set(upv, upi, lov, loi, 8)
    assert float(b_hi) == pytest.approx(float(f_up.min()), rel=1e-6)
    assert float(b_lo) == pytest.approx(float(f_low.max()), rel=1e-6)
    # The global extrema's indices must be among the working set.
    assert int(np.argmin(f_up)) in np.asarray(w)[np.asarray(slot_ok)]
    assert int(np.argmax(f_low)) in np.asarray(w)[np.asarray(slot_ok)]


def test_fused_mesh_matches_single_chip(blobs_medium):
    """The mesh fused runner (per-shard fold+select pass + gathered exact
    global top-h) must land on the single-chip optimum."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    cfg = BASE.replace(working_set_size=16, fused_fold=True)
    r1 = solve(x, y, cfg)
    r8 = solve_mesh(x, y, cfg, num_devices=8)
    assert r1.converged and r8.converged
    np.testing.assert_allclose(r8.alpha, r1.alpha, atol=5e-2)
    assert r8.b == pytest.approx(r1.b, abs=5e-3)


def test_fused_mesh_compensated(blobs_small):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    cfg = BASE.replace(working_set_size=8, compensated=True,
                       fused_fold=True)
    rp = solve_mesh(x, y, cfg.replace(fused_fold=False), num_devices=4)
    rf = solve_mesh(x, y, cfg, num_devices=4)
    assert rp.converged and rf.converged
    np.testing.assert_allclose(rf.alpha, rp.alpha, atol=5e-2)
    assert rf.b == pytest.approx(rp.b, abs=5e-3)


def test_fused_mesh_budget_mode(blobs_medium):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    cfg = BASE.replace(budget_mode=True, max_iter=600, inner_iters=50,
                       working_set_size=16, fused_fold=True)
    r = solve_mesh(x, y, cfg, num_devices=8)
    assert r.iterations == 600
