"""bench.py round-over-round regression gate (VERDICT round-5 item 1,
second half): drift-normalized comparison against the latest committed
BENCH_r*.json. Pure-function tests — no device work."""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root for bench.py

import bench


def _write(tmp_path, name, parsed, wrap=True):
    doc = {"parsed": parsed} if wrap else parsed
    (tmp_path / name).write_text(json.dumps(doc))


def _cur(pps=700_000, cal=0.5):
    return {"pairs_per_second": pps,
            "session_calibration": {"best_of_5_seconds": cal}}


def test_no_baseline(tmp_path):
    assert bench._regression_gate(_cur(), str(tmp_path)) == {
        "regression_gate": "NO_BASELINE"}


def test_latest_artifact_wins(tmp_path):
    _write(tmp_path, "BENCH_r05.json", {"pairs_per_second": 1,
                                        "session_calibration":
                                        {"best_of_5_seconds": 0.5}})
    _write(tmp_path, "BENCH_r06.json", {"pairs_per_second": 700_000,
                                        "session_calibration":
                                        {"best_of_5_seconds": 0.5}})
    out = bench._regression_gate(_cur(), str(tmp_path))
    assert out["previous_artifact"] == "BENCH_r06.json"
    assert out["regression_gate"] == "PASS"


def test_pass_within_band_after_normalization(tmp_path):
    # This session is 10% SLOWER (calibration 0.55 vs 0.5): a raw -12%
    # pairs/s reading normalizes to ~-3% => PASS, not a regression.
    _write(tmp_path, "BENCH_r06.json", {"pairs_per_second": 700_000,
                                        "session_calibration":
                                        {"best_of_5_seconds": 0.5}})
    out = bench._regression_gate(_cur(pps=616_000, cal=0.55),
                                 str(tmp_path))
    assert out["regression_gate"] == "PASS"
    assert abs(out["normalized_delta"]) < 0.05
    # ...while the same raw numbers WITHOUT the drift would FLAG:
    out_raw = bench._regression_gate(_cur(pps=616_000, cal=0.5),
                                     str(tmp_path))
    assert out_raw["regression_gate"] == "FLAG"


def test_flag_beyond_band(tmp_path):
    _write(tmp_path, "BENCH_r06.json", {"pairs_per_second": 700_000,
                                        "session_calibration":
                                        {"best_of_5_seconds": 0.5}})
    out = bench._regression_gate(_cur(pps=500_000), str(tmp_path))
    assert out["regression_gate"] == "FLAG"
    assert out["normalized_delta"] < -bench._REGRESSION_BAND


def test_no_calibration_in_previous_artifact(tmp_path):
    # Pre-round-6 artifacts (e.g. the committed BENCH_r05.json) carry no
    # session_calibration: the delta reports RAW and informational.
    _write(tmp_path, "BENCH_r06.json", {"pairs_per_second": 623_782})
    out = bench._regression_gate(_cur(), str(tmp_path))
    assert out["regression_gate"] == "NO_CALIBRATION"
    assert "raw_delta" in out


def _mesh_cur(pps=1_000_000, cal=0.5):
    return {"mesh_pairs_per_second": pps,
            "session_calibration": {"best_of_5_seconds": cal}}


def test_multichip_gate_skips_metricless_driver_records(tmp_path):
    """The MULTICHIP family mixes the driver's {rc, ok} run records
    with metric-bearing mesh-bench records: the gate must baseline
    against the newest artifact that CARRIES the metric, not go blind
    because the newest file is a run record (ISSUE 4 satellite)."""
    _write(tmp_path, "MULTICHIP_r04.json", _mesh_cur(), wrap=False)
    _write(tmp_path, "MULTICHIP_r05.json",
           {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
            "tail": ""}, wrap=False)
    out = bench._regression_gate(_mesh_cur(), str(tmp_path),
                                 pattern="MULTICHIP_r*.json",
                                 key="mesh_pairs_per_second")
    assert out["previous_artifact"] == "MULTICHIP_r04.json"
    assert out["regression_gate"] == "PASS"


def test_multichip_gate_no_metric_anywhere(tmp_path):
    # Only driver run records exist (the committed state today):
    # NO_BASELINE, not a crash — the first metric-bearing artifact
    # becomes the baseline.
    _write(tmp_path, "MULTICHIP_r05.json",
           {"n_devices": 8, "rc": 0, "ok": True}, wrap=False)
    out = bench._regression_gate(_mesh_cur(), str(tmp_path),
                                 pattern="MULTICHIP_r*.json",
                                 key="mesh_pairs_per_second")
    assert out == {"regression_gate": "NO_BASELINE"}


def test_gate_skips_corrupt_artifacts(tmp_path):
    # A truncated artifact (driver killed mid-write) is skipped, never
    # raised: the gate still finds an older healthy baseline, and with
    # no healthy candidate at all reports NO_BASELINE.
    _write(tmp_path, "MULTICHIP_r04.json", _mesh_cur(), wrap=False)
    (tmp_path / "MULTICHIP_r05.json").write_text('{"rc": 0, "ok"')
    out = bench._regression_gate(_mesh_cur(), str(tmp_path),
                                 pattern="MULTICHIP_r*.json",
                                 key="mesh_pairs_per_second")
    assert out["previous_artifact"] == "MULTICHIP_r04.json"
    (tmp_path / "MULTICHIP_r04.json").write_text("{trunc")
    out = bench._regression_gate(_mesh_cur(), str(tmp_path),
                                 pattern="MULTICHIP_r*.json",
                                 key="mesh_pairs_per_second")
    assert out == {"regression_gate": "NO_BASELINE"}


def test_multichip_gate_flags_mesh_regression(tmp_path):
    _write(tmp_path, "MULTICHIP_r06.json", _mesh_cur(), wrap=False)
    out = bench._regression_gate(_mesh_cur(pps=700_000), str(tmp_path),
                                 pattern="MULTICHIP_r*.json",
                                 key="mesh_pairs_per_second")
    assert out["regression_gate"] == "FLAG"
    assert out["normalized_delta"] < -bench._REGRESSION_BAND


def test_device_kind_mismatch_refused(tmp_path):
    """ISSUE 14 satellite: a v5e run must not be drift-normalized
    against a CPU-harness baseline (the calibration kernel cancels
    session speed, not hardware) — cross-kind comparisons report the
    raw delta as informational and adjudicate nothing."""
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "device_kind": "cpu"})
    cur = {**_cur(pps=3_000_000), "device_kind": "TPU v5 lite"}
    out = bench._regression_gate(cur, str(tmp_path))
    assert out["regression_gate"] == "DEVICE_MISMATCH"
    assert out["previous_device_kind"] == "cpu"
    assert "raw_delta" in out and "normalized_delta" not in out


def test_device_kind_match_compares(tmp_path):
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "device_kind": "TPU v5 lite"})
    out = bench._regression_gate(
        {**_cur(), "device_kind": "TPU v5 lite"}, str(tmp_path))
    assert out["regression_gate"] == "PASS"


def test_device_kind_legacy_cpu_artifacts_derive_and_compare(tmp_path):
    # Legacy CPU-harness artifacts (no device_kind stamp, device
    # string 'TFRT_CPU_0' — every baseline CI gates against) derive
    # kind 'cpu' and keep adjudicating cpu runs...
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "device": "TFRT_CPU_0"})
    out = bench._regression_gate(
        {**_cur(), "device_kind": "cpu"}, str(tmp_path))
    assert out["regression_gate"] == "PASS"
    # ...and refuse a stamped TPU run (the satellite's core scenario:
    # the FIRST v5e run must not be normalized against a CPU-harness
    # baseline, without waiting for one stamped artifact per family).
    out = bench._regression_gate(
        {**_cur(pps=3_000_000), "device_kind": "TPU v5 lite"},
        str(tmp_path))
    assert out["regression_gate"] == "DEVICE_MISMATCH"


def test_device_kind_unknown_baseline_refused(tmp_path):
    # A baseline with NO device information at all (BENCH_r01-r05;
    # r03-r05 were real TPU sessions) cannot rule out a cross-kind
    # comparison: raw delta reported, nothing adjudicated — even for
    # a cpu current run (the baseline might be the TPU one).
    _write(tmp_path, "BENCH_r06.json", _cur())
    out = bench._regression_gate(
        {**_cur(), "device_kind": "cpu"}, str(tmp_path))
    assert out["regression_gate"] == "DEVICE_UNKNOWN"
    assert out["previous_device_kind"] is None
    assert "raw_delta" in out and "normalized_delta" not in out
    # An UNSTAMPED current (legacy caller) still compares as before.
    out = bench._regression_gate(_cur(), str(tmp_path))
    assert out["regression_gate"] == "PASS"


def test_bare_artifact_shape(tmp_path):
    # Bare (unwrapped) result dicts parse too.
    _write(tmp_path, "BENCH_r06.json",
           {"pairs_per_second": 700_000,
            "session_calibration": {"best_of_5_seconds": 0.5}},
           wrap=False)
    out = bench._regression_gate(_cur(), str(tmp_path))
    assert out["regression_gate"] == "PASS"


def test_topology_mismatch_refused(tmp_path):
    """ISSUE 16 satellite: a 2-replica run "beating" a 1-replica
    baseline is the horizontal-scaling claim, not a regression verdict
    — the gate refuses cross-topology comparisons with the raw delta
    as informational, for replica count and mesh width alike."""
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "replicas": 1, "union_mesh_devices": 1})
    out = bench._regression_gate(
        {**_cur(pps=1_300_000), "replicas": 2,
         "union_mesh_devices": 1}, str(tmp_path))
    assert out["regression_gate"] == "TOPOLOGY_MISMATCH"
    assert out["previous_topology"] == {"replicas": 1,
                                        "union_mesh_devices": 1}
    assert out["current_topology"] == {"replicas": 2,
                                       "union_mesh_devices": 1}
    assert "raw_delta" in out and "normalized_delta" not in out
    # mesh width alone also refuses
    out = bench._regression_gate(
        {**_cur(), "replicas": 1, "union_mesh_devices": 8},
        str(tmp_path))
    assert out["regression_gate"] == "TOPOLOGY_MISMATCH"


def test_topology_legacy_artifacts_derive_single_chip(tmp_path):
    """Artifacts predating the stamps (every BENCH_SERVE_r01/r02) ran
    one engine on one device by construction: absent fields derive to
    (1, 1) and keep adjudicating same-topology runs instead of
    refusing history."""
    _write(tmp_path, "BENCH_r06.json", _cur())  # no topology stamp
    out = bench._regression_gate(
        {**_cur(), "replicas": 1, "union_mesh_devices": 1},
        str(tmp_path))
    assert out["regression_gate"] == "PASS"
    # and a stamped 2-replica run against the legacy baseline refuses
    out = bench._regression_gate(
        {**_cur(pps=1_300_000), "replicas": 2}, str(tmp_path))
    assert out["regression_gate"] == "TOPOLOGY_MISMATCH"
    assert out["previous_topology"] == {"replicas": 1,
                                        "union_mesh_devices": 1}


def test_topology_match_still_adjudicates(tmp_path):
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "replicas": 2, "union_mesh_devices": 1})
    out = bench._regression_gate(
        {**_cur(), "replicas": 2, "union_mesh_devices": 1},
        str(tmp_path))
    assert out["regression_gate"] == "PASS"


# ------------------------------------------ union-storage gate (ISSUE 17)

def test_storage_mismatch_refused(tmp_path):
    """A throughput delta between runs staged at different union
    storages is an apples-to-oranges comparison: the gate refuses with
    STORAGE_MISMATCH and both stamps, reporting the raw delta
    informationally (the TOPOLOGY_MISMATCH discipline)."""
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "union_storage": "f32"})
    out = bench._regression_gate(
        {**_cur(pps=1_300_000), "union_storage": "int8"},
        str(tmp_path))
    assert out["regression_gate"] == "STORAGE_MISMATCH"
    assert out["previous_union_storage"] == "f32"
    assert out["current_union_storage"] == "int8"
    assert "raw_delta" in out and "normalized_delta" not in out


def test_storage_legacy_artifacts_derive_f32(tmp_path):
    """Artifacts predating the stamp ran f32 unions by construction:
    absent derives to 'f32' and same-storage runs keep adjudicating
    instead of refusing history."""
    _write(tmp_path, "BENCH_r06.json", _cur())  # no storage stamp
    out = bench._regression_gate(
        {**_cur(), "union_storage": "f32"}, str(tmp_path))
    assert out["regression_gate"] == "PASS"
    out = bench._regression_gate(
        {**_cur(pps=1_300_000), "union_storage": "int8"},
        str(tmp_path))
    assert out["regression_gate"] == "STORAGE_MISMATCH"
    assert out["previous_union_storage"] == "f32"


def test_storage_match_still_adjudicates(tmp_path):
    _write(tmp_path, "BENCH_r06.json",
           {**_cur(), "union_storage": "int8"})
    out = bench._regression_gate(
        {**_cur(), "union_storage": "int8"}, str(tmp_path))
    assert out["regression_gate"] == "PASS"
