"""Single-chip jitted SMO engine: parity vs the NumPy oracle and LibSVM."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.predict import accuracy, decision_function
from dpsvm_tpu.solver.reference import smo_reference
from dpsvm_tpu.solver.smo import solve


CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=32, chunk_iters=256)


def test_jit_engine_matches_numpy_oracle(blobs_small):
    x, y = blobs_small
    res_jit = solve(x, y, CFG)
    res_np = smo_reference(x, y, CFG)
    assert res_jit.converged and res_np.converged
    # Identical algorithm, same deterministic tie-breaks -> near-identical
    # trajectories; alphas may differ slightly via fp reassociation.
    assert abs(res_jit.iterations - res_np.iterations) <= max(
        5, 0.05 * res_np.iterations)
    assert abs(res_jit.b - res_np.b) < 5e-3
    assert abs(res_jit.n_sv - res_np.n_sv) <= max(3, 0.03 * res_np.n_sv)
    np.testing.assert_allclose(res_jit.alpha, res_np.alpha, atol=2e-2)


def test_jit_engine_matches_libsvm(blobs_small):
    from sklearn.svm import SVC
    x, y = blobs_small
    res = solve(x, y, CFG)
    sk = SVC(C=CFG.c, kernel="rbf", gamma=CFG.gamma, tol=CFG.epsilon).fit(x, y)
    assert abs(res.n_sv - len(sk.support_)) <= max(3, int(0.03 * len(sk.support_)))
    model = SVMModel.from_dense(x, y, res.alpha, res.b, KernelParams("rbf", CFG.gamma))
    np.testing.assert_allclose(
        decision_function(model, x), sk.decision_function(x), atol=5e-2)
    assert accuracy(model, x, y) == pytest.approx(sk.score(x, y), abs=0.01)


def test_cache_does_not_change_result(blobs_small):
    x, y = blobs_small
    res_cached = solve(x, y, CFG.replace(cache_lines=64))
    res_nocache = solve(x, y, CFG.replace(cache_lines=0))
    assert res_cached.iterations == res_nocache.iterations
    np.testing.assert_allclose(res_cached.alpha, res_nocache.alpha, atol=1e-6)
    assert res_cached.b == pytest.approx(res_nocache.b, abs=1e-6)
    # And the cache actually gets hits (SMO revisits its active set).
    assert res_cached.stats["cache_hit_rate"] > 0.3


def test_chunk_size_invariance(blobs_small):
    # Convergence must not depend on the host observation cadence.
    x, y = blobs_small
    r1 = solve(x, y, CFG.replace(chunk_iters=64))
    r2 = solve(x, y, CFG.replace(chunk_iters=4096))
    assert r1.iterations == r2.iterations
    np.testing.assert_allclose(r1.alpha, r2.alpha, atol=1e-6)


def test_max_iter_cap(blobs_small):
    x, y = blobs_small
    res = solve(x, y, CFG.replace(max_iter=7, chunk_iters=3))
    assert res.iterations == 7
    assert not res.converged


def test_callback_fires(blobs_small):
    x, y = blobs_small
    seen = []
    solve(x, y, CFG.replace(chunk_iters=50),
          callback=lambda it, bh, bl, st: seen.append(it))
    assert seen and seen[-1] >= seen[0]


def test_linear_kernel_engine(blobs_small):
    x, y = blobs_small
    cfg = CFG.replace(kernel="linear", gamma=None, max_iter=200_000,
                      c=0.1)
    res = solve(x, y, cfg)
    res_np = smo_reference(x, y, cfg)
    assert res.converged
    assert abs(res.b - res_np.b) < 5e-2


@pytest.mark.parametrize("engine", ["xla", "block"])
@pytest.mark.parametrize("selection", ["mvp", "second_order"])
def test_budget_mode_runs_exact_budget(blobs_small, engine, selection):
    """config.budget_mode disables the stopping test: the solver executes
    exactly max_iter pair updates (the bench.py measured-at-the-reference-
    budget regime) and still reports the honest stopping rule at the real
    epsilon on the final state. second_order is the rule whose post-optimum
    rounds can run out of eligible partners — the has_j gate must keep the
    forced no-ops off the dual equality constraint (solver/block.py)."""
    x, y = blobs_small
    budget = 2000
    cfg = CFG.replace(engine=engine, selection=selection, cache_lines=0,
                      max_iter=budget, budget_mode=True)
    res = solve(x, y, cfg)
    assert res.iterations == budget
    # The convergence run needs fewer pairs than the budget, so the
    # budget run passed the optimum; its alpha must still be a feasible
    # box point with the dual equality constraint intact (the forced
    # post-optimum steps stay on the constraint line — measured drift is
    # ~1e-6, the 1e-4 bound is 100x slack while the has_j bug it guards
    # against drifts by O(C)).
    assert res.alpha.min() >= 0.0 and res.alpha.max() <= CFG.c + 1e-6
    assert abs(float(np.sum(res.alpha * y))) < 1e-4


def test_callback_abort_stops_solve(blobs_small):
    """A truthy callback return aborts at the chunk boundary (the
    stall-stop hook tools/parity_covtype.py uses)."""
    x, y = blobs_small
    seen = []

    def stop_after_two(it, bh, bl, st):
        seen.append(it)
        return len(seen) >= 2

    res = solve(x, y, CFG.replace(chunk_iters=50, max_iter=100_000),
                callback=stop_after_two)
    assert len(seen) == 2
    assert not res.converged
    assert res.iterations == seen[-1] < 100_000
