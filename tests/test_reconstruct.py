"""Compensated gradient carry + f64 reconstruction legs (extreme C).

The round-3 finding these features productize: at the reference's covtype
stress config (c=2048, reference Makefile:77) the fp32 incremental
gradient drifts until the carried stopping rule is meaningless (measured
carried gap 0.005 vs true 1.1 — PARITY.md). config.compensated defers the
per-update rounding (solver/smo.py kahan_add); config.reconstruct_every
certifies convergence on an exact float64 host reconstruction
(solver/reconstruct.py).
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.ops.select import extrema_np
from dpsvm_tpu.solver.reconstruct import gram_matvec_f64
from dpsvm_tpu.solver.smo import solve


def _stress(n=400, d=12, seed=7):
    """Overlapping blobs at extreme C: large alphas, slow convergence."""
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=n, d=d, seed=seed, sep=0.6)


STRESS = SVMConfig(c=5000.0, gamma=0.05, epsilon=1e-3, max_iter=400_000)


def _true_f(x, y, alpha, cfg):
    kp = KernelParams(cfg.kernel, cfg.resolve_gamma(x.shape[1]),
                      cfg.degree, cfg.coef0)
    y64 = np.asarray(y, np.float64)
    return gram_matvec_f64(x, np.asarray(alpha, np.float64) * y64,
                           kp, cfg.dtype) - y64


def test_kahan_add_removes_accumulation_error():
    """The mechanism behind config.compensated: a million tiny fp32
    increments into a large value lose ~half their mass plain and lose
    nothing compensated (solver/smo.py kahan_add)."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.solver.smo import kahan_add

    f0 = jnp.full((4,), 1e4, jnp.float32)
    delta = jnp.full((4,), 1e-3, jnp.float32)

    def body(i, carry):
        f, err, fp = carry
        f, err = kahan_add(f, err, delta)
        return f, err, fp + delta

    f, err, fp = jax.lax.fori_loop(
        0, 1_000_000, body, (f0, jnp.zeros_like(f0), f0))
    true = 1e4 + 1e-3 * 1e6
    assert abs(float((f - err)[0]) - true) < 1e-3
    assert abs(float(fp[0]) - true) > 1.0  # the plain carry really loses


def test_compensated_drift_not_worse_extreme_c():
    """At extreme C the compensated carry must track the exact f64
    gradient at least as well as the plain carry (on TPU the dominant
    drift term is matmul precision, handled by config.matmul_precision;
    compensation removes the accumulation term)."""
    x, y = _stress()
    cfg = STRESS.replace(max_iter=6000)
    res_plain = solve(x, y, cfg)
    res_comp = solve(x, y, cfg.replace(compensated=True))
    err_plain = np.max(np.abs(res_plain.stats["f"]
                              - _true_f(x, y, res_plain.alpha, cfg)))
    err_comp = np.max(np.abs(res_comp.stats["f"]
                             - _true_f(x, y, res_comp.alpha, cfg)))
    assert err_comp < max(1.5 * err_plain, 1e-4)
    assert err_comp < 2e-3


def test_precision_resolution():
    assert SVMConfig().resolve_precision() is None
    assert SVMConfig(compensated=True).resolve_precision() == "highest"
    assert SVMConfig(reconstruct_every=10_000).resolve_precision() == "highest"
    assert SVMConfig(compensated=True,
                     matmul_precision="default").resolve_precision() is None
    assert SVMConfig(matmul_precision="high").resolve_precision() == "high"


def test_compensated_same_optimum_moderate_c(blobs_small):
    """At moderate C compensation must not change the answer."""
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    r0 = solve(x, y, cfg)
    r1 = solve(x, y, cfg.replace(compensated=True))
    assert r0.converged and r1.converged
    np.testing.assert_allclose(r0.alpha, r1.alpha, atol=2e-2)
    assert r1.b == pytest.approx(r0.b, abs=5e-3)


@pytest.mark.parametrize("engine,selection", [
    ("xla", "mvp"), ("xla", "second_order"), ("block", "second_order"),
])
def test_reconstruct_legs_converge_extreme_c(engine, selection):
    """One solve() call closes the TRUE gap at extreme C (the round-3
    harness needed an external script for this)."""
    x, y = _stress()
    cfg = STRESS.replace(engine=engine, selection=selection,
                         compensated=True, reconstruct_every=50_000)
    res = solve(x, y, cfg)
    assert res.converged
    assert res.stats["reconstructions"] >= 1
    assert res.stats["true_gap"] <= 2 * cfg.epsilon + 1e-9
    # Certify independently: the reported extrema must match an exact
    # f64 reconstruction of the returned alpha.
    f64 = _true_f(x, y, res.alpha, cfg)
    bh, bl = extrema_np(f64, res.alpha, y, cfg.c_bounds())
    assert bl - bh <= 2 * cfg.epsilon + 1e-6
    assert res.b == pytest.approx((bh + bl) / 2.0, abs=1e-4)


def test_reconstruct_matches_oracle_extreme_c():
    """The reconstructed solve agrees with LibSVM at the stress C."""
    from sklearn.svm import SVC

    x, y = _stress()
    cfg = STRESS.replace(selection="second_order", compensated=True,
                         reconstruct_every=50_000)
    res = solve(x, y, cfg)
    sk = SVC(C=cfg.c, kernel="rbf", gamma=cfg.gamma,
             tol=2 * cfg.epsilon).fit(x, y)
    dec = _true_f(x, y, res.alpha, cfg) + y - res.b
    agree = np.mean(np.sign(dec) == np.sign(sk.decision_function(x)))
    assert agree >= 0.995


def test_reconstruct_mesh_matches_single_chip():
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = _stress(n=320)
    cfg = STRESS.replace(compensated=True, reconstruct_every=40_000)
    r1 = solve(x, y, cfg)
    r8 = solve_mesh(x, y, cfg, num_devices=8)
    assert r1.converged and r8.converged
    np.testing.assert_allclose(r8.alpha, r1.alpha, atol=2e-2)
    assert r8.b == pytest.approx(r1.b, abs=1e-3)


def test_reconstruct_svr_linear_term():
    """The SVR reduction supplies f_init != -y; the reconstruction must
    recover its linear term (solver/reconstruct.py _linear_term) instead
    of assuming the C-SVC one."""
    from dpsvm_tpu.models.svr import train_svr

    rng = np.random.default_rng(5)
    x = rng.normal(size=(240, 6)).astype(np.float32)
    z = (np.sin(x[:, 0]) + 0.1 * rng.normal(size=240)).astype(np.float32)
    cfg = SVMConfig(c=10.0, gamma=0.5, epsilon=1e-3, max_iter=200_000)
    m0, r0 = train_svr(x, z, cfg, svr_epsilon=0.1, backend="single")
    m1, r1 = train_svr(x, z, cfg.replace(compensated=True,
                                         reconstruct_every=30_000),
                       svr_epsilon=0.1, backend="single")
    assert r0.converged and r1.converged
    np.testing.assert_allclose(m1.predict(x), m0.predict(x), atol=5e-3)


def test_reconstruct_checkpoint_resume(tmp_path):
    """Leg checkpoints restart from certified (reconstructed) state."""
    x, y = _stress(n=320)
    ck = str(tmp_path / "legs.npz")
    cfg = STRESS.replace(compensated=True, reconstruct_every=40_000,
                         checkpoint_every=1)
    res = solve(x, y, cfg, checkpoint_path=ck)
    assert res.converged
    res2 = solve(x, y, cfg, checkpoint_path=ck, resume=True)
    assert res2.converged
    # The resumed run starts at the certified optimum: little extra work.
    assert res2.iterations - res.iterations < cfg.reconstruct_every
    np.testing.assert_allclose(res2.alpha, res.alpha, atol=2e-2)


def test_config_validation():
    with pytest.raises(ValueError):
        SVMConfig(reconstruct_every=1000, budget_mode=True)
    with pytest.raises(ValueError):
        SVMConfig(compensated=True, engine="pallas")
    with pytest.raises(ValueError):
        SVMConfig(reconstruct_every=-1)


def test_f64_prediction_fixes_extreme_c_signs():
    """The fp32 prediction trap (PARITY.md): at extreme C, fp32 decision
    accumulation loses signs that float64 evaluation recovers; the risk
    estimator separates the regimes."""
    from sklearn.svm import SVC

    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.predict import decision_function, decision_risk

    x, y = _stress(n=500)
    cfg = STRESS.replace(selection="second_order", compensated=True,
                         reconstruct_every=50_000)
    res = solve(x, y, cfg)
    kp = KernelParams("rbf", cfg.gamma)
    model = SVMModel.from_dense(x, y, res.alpha, res.b, kp)
    sk = SVC(C=cfg.c, kernel="rbf", gamma=cfg.gamma,
             tol=2 * cfg.epsilon).fit(x, y)

    d64 = decision_function(model, x, precision="float64")
    agree64 = np.mean(np.sign(d64) == np.sign(sk.decision_function(x)))
    assert agree64 >= 0.995
    np.testing.assert_allclose(
        decision_function(model, x), d64, atol=10 * decision_risk(model)
        + 1e-4)
    # Risk separates regimes: extreme C >> moderate C.
    from dpsvm_tpu.train import train

    m_easy, _ = train(x, y, SVMConfig(c=1.0, gamma=0.1), backend="single")
    assert decision_risk(model) > 10 * decision_risk(m_easy)
    with pytest.raises(ValueError):
        decision_function(model, x, precision="float16")


@pytest.mark.parametrize("kind,degree,coef0", [
    ("linear", 3, 0.0), ("poly", 2, 1.0), ("sigmoid", 3, 0.5),
])
def test_gram_matvec_f64_all_kernels(kind, degree, coef0):
    """The f64 host algebra must match the device kernel definition for
    every feature-kernel family (it certifies their convergence too)."""
    from dpsvm_tpu.ops.kernels import kernel_matrix

    rng = np.random.default_rng(2)
    x = rng.normal(size=(96, 5)).astype(np.float32)
    coef = rng.normal(size=96).astype(np.float64)
    coef[rng.random(96) < 0.4] = 0.0
    kp = KernelParams(kind, 0.3, degree, coef0)
    got = gram_matvec_f64(x, coef, kp)
    want = np.asarray(kernel_matrix(x, x, kp), np.float64) @ coef
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # And at arbitrary query points.
    q = rng.normal(size=(17, 5)).astype(np.float32)
    got_q = gram_matvec_f64(x, coef, kp, queries=q.astype(np.float64))
    want_q = np.asarray(kernel_matrix(q, x, kp), np.float64) @ coef
    np.testing.assert_allclose(got_q, want_q, rtol=1e-5, atol=1e-5)


def test_gram_matvec_f64_precomputed_rejects_queries():
    kp = KernelParams("precomputed")
    K = np.eye(8, dtype=np.float32)
    with pytest.raises(ValueError, match="precomputed"):
        gram_matvec_f64(K, np.ones(8), kp, queries=np.ones((2, 8)))
