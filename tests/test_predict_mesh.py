"""Mesh-parallel inference vs single-device decision function."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.predict import decision_function, decision_function_mesh
from dpsvm_tpu.solver.smo import solve


@pytest.fixture(scope="module")
def trained(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, cache_lines=16)
    res = solve(x, y, cfg)
    return SVMModel.from_dense(x, y, res.alpha, res.b, KernelParams("rbf", 0.1)), x


@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_mesh_decision_matches_single(trained, n_dev):
    model, x = trained
    single = decision_function(model, x)
    mesh = decision_function_mesh(model, x, num_devices=n_dev)
    np.testing.assert_allclose(mesh, single, rtol=1e-4, atol=1e-4)


def test_mesh_decision_blocked(trained):
    model, x = trained
    got = decision_function_mesh(model, x, num_devices=4, block=64)
    np.testing.assert_allclose(got, decision_function(model, x),
                               rtol=1e-4, atol=1e-4)


def test_mesh_decision_empty(trained):
    model, _ = trained
    out = decision_function_mesh(model, np.zeros((0, model.num_features)),
                                 num_devices=2)
    assert out.shape == (0,)
