"""Structural verification of the mesh cost model from compiled HLO
(VERDICT round-4 item 4).

docs/SCALING.md's per-round ICI term claims the mesh block round emits
exactly: one all_gather pair carrying the (2, h) f32 candidate values +
(2, h) i32 candidate ids, and psum traffic totalling (q, d) + (q, 5)
f32 — the working-set row recovery. t_ici's LATENCY is unmeasurable
without real ICI, but the OP COUNT and PAYLOAD BYTES are facts of the
compiled program: this test compiles one mesh block chunk at the
covtype shape (n=500k over 8 virtual devices) and asserts them from
the optimized HLO text, so the cost model can never silently drift
from the code.
"""

import jax
import jax.numpy as jnp

# The collective parser lives in the tpulint fact extractor now
# (ISSUE 5): one definition shared by these pins, test_pipelined.py,
# and the budget linter. The payload arithmetic below is unchanged —
# same facts, same strictness, now through the shared extractor.
from dpsvm_tpu.analysis.hlo_facts import collective_ops as _collective_ops
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner
from dpsvm_tpu.parallel.mesh import make_data_mesh
from dpsvm_tpu.solver.block import BlockState

N, D, Q = 500_000, 54, 512
H = Q // 2
P_DEV = 8


def test_mesh_block_round_collectives_match_scaling_model():
    mesh = make_data_mesh(P_DEV)
    kp = KernelParams("rbf", 0.03125)
    runner = make_block_chunk_runner(
        mesh, kp, (2048.0, 2048.0), 1e-3, 1e-12, Q, 1024,
        rounds_per_chunk=1, inner_impl="xla")

    n_loc = N // P_DEV
    sds = jax.ShapeDtypeStruct
    state = BlockState(
        alpha=sds((N,), jnp.float32), f=sds((N,), jnp.float32),
        b_hi=sds((), jnp.float32), b_lo=sds((), jnp.float32),
        pairs=sds((), jnp.int32), rounds=sds((), jnp.int32))
    text = runner.lower(
        sds((N, D), jnp.float32), sds((N,), jnp.float32),
        sds((N,), jnp.float32), sds((N,), jnp.float32),
        sds((N,), jnp.bool_), state, sds((), jnp.int32),
    ).compile().as_text()

    gathers = _collective_ops(text, "all-gather")
    reduces = _collective_ops(text, "all-reduce")
    others = (_collective_ops(text, "all-to-all")
              + _collective_ops(text, "collective-permute"))

    # The round body must emit NO collectives beyond the claimed two
    # kinds (reduce-scatter would show as all-reduce variants; permute/
    # all-to-all would be a different algorithm entirely).
    assert not others, others

    # Claim 1: ONE all_gather dispatch sequence per round carrying the
    # (2, h) f32 candidate values and (2, h) i32 ids. XLA may keep them
    # as two ops or combine into one tuple-shaped op; either way the
    # RESULT payload per device is P * 2h * 4 bytes per operand.
    assert 1 <= len(gathers) <= 2, "\n".join(g[0] for g in gathers)
    gather_sizes = sorted(s for _, sizes in gathers for _, s in sizes)
    assert gather_sizes == [P_DEV * 2 * H * 4, P_DEV * 2 * H * 4], \
        (gather_sizes, gathers)

    # Claim 2: psum traffic totals exactly (q, d) + (q, 5) f32 — the
    # masked working-set row + scalar recovery. (The combiner may merge
    # the two psums; totals are what the model charges.)
    reduce_total = sum(s for _, sizes in reduces for _, s in sizes)
    assert reduce_total == Q * (D + 5) * 4, (reduce_total, reduces)
    assert 1 <= len(reduces) <= 2, "\n".join(r[0] for r in reduces)


# ---- shard-parallel working sets (ISSUE 4) --------------------------
#
# Compiled at a small shape (op structure is shape-independent, like
# test_pipelined.py's mesh claim) so the CPU compile stays cheap. The
# shapes are tpulint's canonical manifest shapes, so these pins and the
# committed budgets (dpsvm_tpu/analysis/budgets/shardlocal_chunk.json)
# describe the SAME compiled program.

from dpsvm_tpu.analysis import manifest as _mf

N_S, D_S, Q_S = _mf.N, _mf.D, _mf.Q
R_SYNC, INNER_S = _mf.R_SYNC, _mf.INNER
H_S = Q_S // 2


def _compile_runner(make, *args, **kw):
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import BlockState

    runner = make(*args, **kw)
    sds = jax.ShapeDtypeStruct
    state = BlockState(
        alpha=sds((N_S,), jnp.float32), f=sds((N_S,), jnp.float32),
        b_hi=sds((), jnp.float32), b_lo=sds((), jnp.float32),
        pairs=sds((), jnp.int32), rounds=sds((), jnp.int32))
    return runner.lower(
        sds((N_S, D_S), jnp.float32), sds((N_S,), jnp.float32),
        sds((N_S,), jnp.float32), sds((N_S,), jnp.float32),
        sds((N_S,), jnp.bool_), state, sds((), jnp.int32),
    ).compile().as_text()


def test_shardlocal_sync_collectives_and_comms_win():
    """The shard-local engine's comms contract (ISSUE 4 acceptance),
    pinned from compiled HLO:

      * ZERO selection all_gathers per local round — the compiled chunk
        carries exactly ONE all_gather (the per-sync (P, R*q, d+3)
        touched-rows exchange) and ONE all-reduce (the (2,) f32 max
        stopping handoff) for a whole R-round sync window, independent
        of R;
      * collective DISPATCHES per potential pair drop >= P x vs the
        global runner (measured here: ~3PR/2 = 24x at P=8, R=4);
      * payload BYTES per potential pair DROP, but NOT by >= P x: the
        touched rows must cross the interconnect exactly once either
        way, so the analytic ceiling is (2P + d + 5)/(d + 3) — ~1.7x at
        this shape, ~1.3x at covtype's d=54. The issue's >= P x bytes
        hope is REFUTED by this accounting (recorded as the honest
        negative in docs/SCALING.md round-7); the engine's win is chain
        parallelism plus dispatch-latency amortization, not bandwidth.
    """
    from dpsvm_tpu.parallel.dist_block import (
        make_block_chunk_runner, make_block_shardlocal_chunk_runner)
    from dpsvm_tpu.parallel.mesh import make_data_mesh

    mesh = make_data_mesh(P_DEV)
    kp = KernelParams("rbf", 0.1)
    text_sl = _compile_runner(
        make_block_shardlocal_chunk_runner, mesh, kp, (5.0, 5.0), 1e-3,
        1e-12, Q_S, INNER_S, rounds_per_chunk=R_SYNC,
        sync_rounds=R_SYNC, inner_impl="xla")
    text_g = _compile_runner(
        make_block_chunk_runner, mesh, kp, (5.0, 5.0), 1e-3, 1e-12,
        Q_S, INNER_S, rounds_per_chunk=1, inner_impl="xla")

    gathers = _collective_ops(text_sl, "all-gather")
    reduces = _collective_ops(text_sl, "all-reduce")
    others = (_collective_ops(text_sl, "all-to-all")
              + _collective_ops(text_sl, "collective-permute"))
    assert not others, others

    # ONE touched-rows all_gather per sync: (P, R*q, d+3) f32.
    assert len(gathers) == 1, "\n".join(g[0] for g in gathers)
    gather_bytes = sum(s for _, sizes in gathers for _, s in sizes)
    assert gather_bytes == P_DEV * R_SYNC * Q_S * (D_S + 3) * 4, \
        (gather_bytes, gathers)
    # ONE (2,) f32 max-allreduce stopping handoff per sync.
    assert len(reduces) == 1, "\n".join(r[0] for r in reduces)
    reduce_bytes = sum(s for _, sizes in reduces for _, s in sizes)
    assert reduce_bytes == 2 * 4, (reduce_bytes, reduces)

    # Per-potential-pair accounting vs the global runner at the same
    # shape: the global round's collectives buy `inner` pairs (one
    # replicated chain); the shard-local sync's buy P * R * inner (P
    # concurrent chains for R rounds).
    g_gathers = _collective_ops(text_g, "all-gather")
    g_reduces = _collective_ops(text_g, "all-reduce")
    g_ops = len(g_gathers) + len(g_reduces)
    g_bytes = sum(s for _, sizes in g_gathers + g_reduces
                  for _, s in sizes)
    assert g_bytes == 2 * P_DEV * 2 * H_S * 4 + Q_S * (D_S + 5) * 4, \
        (g_bytes, g_gathers, g_reduces)

    pairs_g = INNER_S
    pairs_sl = P_DEV * R_SYNC * INNER_S
    dispatch_ratio = (g_ops / pairs_g) / (2 / pairs_sl)
    byte_ratio = (g_bytes / pairs_g) / (
        (gather_bytes + reduce_bytes) / pairs_sl)
    assert dispatch_ratio >= P_DEV, (dispatch_ratio, g_ops)
    # Bytes per pair DO drop (the model's (2P+d+5)/(d+3) = 1.67 here)...
    assert byte_ratio >= 1.5, byte_ratio
    # ...but the >= P x hope is analytically impossible — pin the honest
    # ceiling so the SCALING.md claim can never silently inflate.
    assert byte_ratio <= (2 * P_DEV + D_S + 5) / (D_S + 3) + 0.01, \
        byte_ratio
