"""nu-SVC / nu-SVR: parity against sklearn (LibSVM's Solver_NU) and the
nu-property guarantees. No reference equivalent — these complete the
LibSVM model-family matrix on the TPU engine."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.nusvm import train_nusvc, train_nusvr
from dpsvm_tpu.predict import decision_function

CFG = SVMConfig(gamma=0.15, epsilon=1e-4, max_iter=300_000)


@pytest.fixture(scope="module")
def blobs():
    from dpsvm_tpu.data.synth import make_blobs_binary
    return make_blobs_binary(n=400, d=10, seed=3, sep=1.0)


def test_nusvc_matches_sklearn(blobs):
    from sklearn.svm import NuSVC
    x, y = blobs
    m, res = train_nusvc(x, y, nu=0.3, config=CFG, backend="single")
    sk = NuSVC(nu=0.3, gamma=0.15, tol=1e-4).fit(x, y)
    assert res.converged
    assert abs(m.n_sv - len(sk.support_)) <= max(3, 0.03 * len(sk.support_))
    ours = decision_function(m, x)
    theirs = sk.decision_function(x)
    np.testing.assert_allclose(ours, theirs, atol=8e-2)
    assert float(np.mean(np.sign(ours) == y)) == pytest.approx(
        sk.score(x, y), abs=0.01)


def test_nusvc_nu_property(blobs):
    """nu upper-bounds the margin-error fraction and lower-bounds the SV
    fraction (Scholkopf)."""
    x, y = blobs
    n = x.shape[0]
    for nu in (0.2, 0.5):
        m, res = train_nusvc(x, y, nu=nu, config=CFG, backend="single")
        assert res.converged
        sv_frac = m.n_sv / n
        assert sv_frac >= nu - 0.05
        margin_err = float(np.mean(y * decision_function(m, x) < 1 - 1e-3))
        assert margin_err <= nu + 0.05


def test_nusvc_infeasible_nu():
    x = np.random.default_rng(0).normal(size=(50, 4)).astype(np.float32)
    y = np.ones(50, np.int32)
    y[:5] = -1  # minority class of 5 -> nu > 2*5/50 = 0.2 infeasible
    with pytest.raises(ValueError, match="infeasible"):
        train_nusvc(x, y, nu=0.5, config=CFG, backend="single")
    with pytest.raises(ValueError, match="both classes"):
        train_nusvc(x, np.ones(50, np.int32), nu=0.1, config=CFG)


def test_nusvr_matches_sklearn(blobs):
    from sklearn.svm import NuSVR
    x, _ = blobs
    rng = np.random.default_rng(1)
    z = (np.sin(x[:, 0] * 2) + 0.1 * rng.normal(size=x.shape[0])).astype(np.float32)
    m, res = train_nusvr(x, z, nu=0.4, c=2.0, config=CFG, backend="single")
    sk = NuSVR(nu=0.4, C=2.0, gamma=0.15, tol=1e-4).fit(x, z)
    assert res.converged
    np.testing.assert_allclose(m.predict(x), sk.predict(x), atol=5e-2)
    # The adaptive tube width is part of the solution — compare it too
    # (LibSVM prints it as "epsilon"; ours rides in stats).
    assert res.stats["nu_tube_eps"] > 0


def test_nusvc_mesh_matches_single(blobs):
    """The distributed per-class selection must reproduce the single-chip
    nu solution (same deterministic tie-breaks)."""
    x, y = blobs
    m1, r1 = train_nusvc(x, y, nu=0.3, config=CFG, backend="single")
    m8, r8 = train_nusvc(x, y, nu=0.3, config=CFG, backend="mesh",
                         num_devices=8)
    assert r8.converged
    assert abs(r8.iterations - r1.iterations) <= max(2, 0.02 * r1.iterations)
    np.testing.assert_allclose(decision_function(m8, x),
                               decision_function(m1, x), atol=1e-3)


def test_nusvr_mesh_matches_single(blobs):
    x, _ = blobs
    rng = np.random.default_rng(1)
    z = (np.sin(x[:, 0] * 2) + 0.1 * rng.normal(size=x.shape[0])).astype(np.float32)
    m1, r1 = train_nusvr(x, z, nu=0.4, c=2.0, config=CFG, backend="single")
    m8, r8 = train_nusvr(x, z, nu=0.4, c=2.0, config=CFG, backend="mesh",
                         num_devices=8)
    assert r8.converged
    np.testing.assert_allclose(m8.predict(x), m1.predict(x), atol=1e-3)


def test_nusvc_block_engine_matches_xla(blobs):
    """The block engine's per-class-quarter selection + per-class-pair
    subproblem reaches the same nu-SVC solution as the per-pair engine."""
    x, y = blobs
    m1, r1 = train_nusvc(x, y, nu=0.3, config=CFG, backend="single")
    mb, rb = train_nusvc(x, y, nu=0.3,
                         config=CFG.replace(engine="block",
                                            working_set_size=32),
                         backend="single")
    assert rb.converged
    assert rb.stats["outer_rounds"] > 0
    assert abs(mb.n_sv - m1.n_sv) <= max(3, 0.03 * m1.n_sv)
    np.testing.assert_allclose(decision_function(mb, x),
                               decision_function(m1, x), atol=8e-2)
    assert rb.stats["nu_r"] == pytest.approx(r1.stats["nu_r"], rel=1e-2)


def test_nusvr_block_engine_matches_xla(blobs):
    x, _ = blobs
    rng = np.random.default_rng(1)
    z = (np.sin(x[:, 0] * 2) + 0.1 * rng.normal(size=x.shape[0])).astype(np.float32)
    m1, r1 = train_nusvr(x, z, nu=0.4, c=2.0, config=CFG, backend="single")
    mb, rb = train_nusvr(x, z, nu=0.4, c=2.0,
                         config=CFG.replace(engine="block",
                                            working_set_size=32),
                         backend="single")
    assert rb.converged
    np.testing.assert_allclose(mb.predict(x), m1.predict(x), atol=5e-2)
    assert rb.stats["nu_tube_eps"] == pytest.approx(
        r1.stats["nu_tube_eps"], abs=2e-2)


def test_nusvc_block_mesh_matches_single(blobs):
    """Distributed block engine under the nu rule (per-class quarters via
    all_gather, per-class pmin/pmax stopping gap)."""
    x, y = blobs
    cfg = CFG.replace(engine="block", working_set_size=32)
    m1, r1 = train_nusvc(x, y, nu=0.3, config=cfg, backend="single")
    m8, r8 = train_nusvc(x, y, nu=0.3, config=cfg, backend="mesh",
                         num_devices=8)
    assert r8.converged
    np.testing.assert_allclose(decision_function(m8, x),
                               decision_function(m1, x), atol=8e-2)


def test_nu_estimators(blobs):
    from dpsvm_tpu.estimators import NuSVC as OurNuSVC, NuSVR as OurNuSVR
    from sklearn.svm import NuSVC, NuSVR
    x, y = blobs
    ours = OurNuSVC(nu=0.3, gamma=0.15, tol=1e-4).fit(x, y)
    sk = NuSVC(nu=0.3, gamma=0.15, tol=1e-4).fit(x, y)
    assert ours.score(x, y) == pytest.approx(sk.score(x, y), abs=0.01)

    rng = np.random.default_rng(1)
    z = (np.sin(x[:, 0] * 2) + 0.1 * rng.normal(size=x.shape[0])).astype(np.float32)
    oursr = OurNuSVR(nu=0.4, C=2.0, gamma=0.15, tol=1e-4).fit(x, z)
    skr = NuSVR(nu=0.4, C=2.0, gamma=0.15, tol=1e-4).fit(x, z)
    assert oursr.score(x, z) == pytest.approx(skr.score(x, z), abs=0.01)

    # sklearn clone round-trip (BaseEstimator contract).
    from sklearn.base import clone
    clone(ours)
    clone(oursr)


def test_nusvc_checkpoint_resume(tmp_path, blobs):
    x, y = blobs
    path = str(tmp_path / "nusvc.npz")
    cfg = CFG.replace(checkpoint_every=16, chunk_iters=16, max_iter=48)
    m1, r1 = train_nusvc(x, y, nu=0.3, config=cfg, backend="single",
                         checkpoint_path=path)
    assert not r1.converged
    cfg2 = cfg.replace(max_iter=300_000)
    m2, r2 = train_nusvc(x, y, nu=0.3, config=cfg2, backend="single",
                         checkpoint_path=path, resume=True)
    assert r2.converged and r2.iterations > r1.iterations
    m0, r0 = train_nusvc(x, y, nu=0.3, config=CFG, backend="single")
    np.testing.assert_allclose(decision_function(m2, x),
                               decision_function(m0, x), atol=5e-3)


def test_nu_fallback_warning_names_requested_and_effective(blobs):
    """ROADMAP item 4 / ISSUE 9 satellite: the nu trainers must NAME
    the fast paths they fall back from instead of silently training on
    the plain engine. The message carries both the requested engine
    and each dropped knob."""
    x, y = blobs
    cfg = CFG.replace(engine="block", pair_batch=2, max_iter=20_000)
    with pytest.warns(UserWarning,
                      match=r"train_nusvc runs selection='nu' .* "
                            r"requested engine='block'.*falls back "
                            r"from: pair_batch=2"):
        m, res = train_nusvc(x, y, nu=0.3, config=cfg, backend="single")
    assert res.converged  # the fallback still trains correctly

    cfg_ooc = CFG.replace(engine="block", ooc=True, ooc_tile_rows=256,
                          max_iter=20_000)
    with pytest.warns(UserWarning, match=r"falls back from: ooc"):
        train_nusvc(x, y, nu=0.3, config=cfg_ooc, backend="single")

    z = x[:, 0].astype(np.float32)
    with pytest.warns(UserWarning,
                      match=r"train_nusvr .*falls back from: "
                            r"pipeline_rounds"):
        train_nusvr(x, z, nu=0.4, c=2.0,
                    config=CFG.replace(engine="block",
                                       pipeline_rounds=True,
                                       max_iter=20_000),
                    backend="single")


def test_nu_no_warning_when_nothing_dropped(blobs):
    """A plain config trains silently — the warning is for genuinely
    requested-and-dropped fast paths only."""
    import warnings

    x, y = blobs
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        train_nusvc(x, y, nu=0.3,
                    config=CFG.replace(max_iter=20_000),
                    backend="single")
