"""Replica fleet + mesh union group tests (ISSUE 16).

Two scale axes, one contract:

* SCALE-DOWN — the mesh-sharded union group
  (dpsvm_tpu/serving/engine_core.py): union rows sharded across the
  vdev mesh with a psum over partial decision columns, pinned BITWISE
  against the single-chip group for the exact-in-f32 linear case and
  allclose for rbf.
* SCALE-OUT — the replica fleet (dpsvm_tpu/serving/replicas.py) behind
  one front door (serving/server.py): lockstep model admin over the
  shared registry journal (cross-replica swap consistency), rolling
  restart of one replica under sustained wire load with zero lost or
  duplicated frames, per-replica drain/resume lifecycle refusals, and
  the serving_replica_*/serving_fleet_* metrics families.

Budget discipline: tiny models, small bucket ladders, short sustained-
load windows gated by the device-floor emulation knob; no new
interpret-mode Pallas compiles (tier-1 sits near its ceiling)."""

import threading
import time

import numpy as np
import pytest

from dpsvm_tpu.config import ServeConfig, SVMConfig
from dpsvm_tpu.models.multiclass import train_multiclass
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.serving import ReplicaFleet, ServeClient, ServeServer
from dpsvm_tpu.serving import ServingEngine
from dpsvm_tpu.serving.dispatch import ServingEngine as _Engine

CFG = SVMConfig(c=5.0, gamma=0.25, epsilon=1e-3, chunk_iters=256)
D = 5


@pytest.fixture(scope="module")
def two_files(tmp_path_factory):
    """v1/v2 model files trained on DIFFERENT subsets (distinct unions
    — the realistic retrain swap), plus query features."""
    tmp = tmp_path_factory.mktemp("replica_models")
    rng = np.random.default_rng(23)
    xs, ys = [], []
    for k in range(3):
        c = np.zeros(D, np.float32)
        c[k] = 2.5
        xs.append(rng.normal(size=(48, D)).astype(np.float32) * 0.7 + c)
        ys.append(np.full(48, k))
    x, y = np.concatenate(xs), np.concatenate(ys)
    m1, _ = train_multiclass(x[::2], y[::2], CFG, strategy="ovr")
    m2, _ = train_multiclass(x[1::2], y[1::2], CFG, strategy="ovr")
    p1, p2 = str(tmp / "m_v1.npz"), str(tmp / "m_v2.npz")
    m1.save(p1)
    m2.save(p2)
    return p1, p2, x


def _fleet(tmp_path, replicas=2, **kw):
    """(fleet, server) on a loopback port with a shared journal."""
    kw.setdefault("buckets", (16, 64))
    kw.setdefault("deadline_ms", None)
    kw.setdefault("journal_path", str(tmp_path / "registry.journal"))
    cfg = ServeConfig(listen="127.0.0.1:0", replicas=replicas, **kw)
    fleet = ReplicaFleet(cfg)
    return fleet, ServeServer(fleet)


def _no_net_threads(deadline_s=10.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        left = [t.name for t in threading.enumerate()
                if t.name.startswith("dpsvm-net")]
        if not left:
            return []
        time.sleep(0.02)
    return left


# ------------------------------------------------------ mesh union group

def test_mesh_union_group_bitwise_vs_single_chip():
    """The tentpole pin: with the linear kernel and small-integer
    SVs/alphas/queries (every partial sum exact in f32), the mesh
    union group — union rows sharded across the 8-vdev mesh, partial
    decision columns combined by ONE psum — must be BITWISE identical
    to the single-chip group. Sharding may reorder nothing: each
    device owns a contiguous padded row block and the psum adds
    exactly the per-shard partials the single matmul would have
    accumulated."""
    rng = np.random.default_rng(0)
    n, d = 40, 12
    x = rng.integers(-4, 5, size=(n, d)).astype(np.float32)
    y = np.where(np.arange(n) % 2 == 0, 1.0, -1.0).astype(np.float32)
    alpha = rng.integers(0, 4, size=n).astype(np.float32)
    m = SVMModel.from_dense(x, y, alpha, b=3.0,
                            kernel=KernelParams("linear"))
    q = rng.integers(-4, 5, size=(17, d)).astype(np.float32)

    e1 = _Engine(ServeConfig(buckets=(32,), num_devices=1))
    e8 = _Engine(ServeConfig(buckets=(32,), num_devices=8))
    try:
        e1.register("m", m)
        e8.register("m", m)
        d1 = e1.decision(q, "m")
        d8 = e8.decision(q, "m")
        np.testing.assert_array_equal(d1, d8)  # bitwise
        group = next(iter(e8._groups.values()))
        assert group.mesh_devices == 8
        assert e8.snapshot()["union_mesh_devices"] == 8
        assert e1.snapshot()["union_mesh_devices"] == 1
    finally:
        e1.close()
        e8.close()


def test_mesh_union_group_rbf_allclose():
    """rbf sums are not exact in f32, so the mesh pin is allclose —
    the general-kernel contract behind the bitwise linear pin."""
    rng = np.random.default_rng(7)
    d = 12
    m = SVMModel.from_dense(
        rng.random((64, d)).astype(np.float32),
        np.where(np.arange(64) % 2 == 0, 1.0, -1.0),
        rng.random(64).astype(np.float32), b=0.25,
        kernel=KernelParams("rbf", 0.3))
    q = rng.random((23, d)).astype(np.float32)
    ea = _Engine(ServeConfig(buckets=(32,), num_devices=1))
    eb = _Engine(ServeConfig(buckets=(32,), num_devices=4))
    try:
        ea.register("m", m)
        eb.register("m", m)
        np.testing.assert_allclose(ea.decision(q, "m"),
                                   eb.decision(q, "m"),
                                   rtol=1e-4, atol=1e-5)
    finally:
        ea.close()
        eb.close()


# ---------------------------------------------------- fleet model admin

def test_fleet_lockstep_registration_and_journal(two_files, tmp_path):
    """register/swap fan out to every replica at the SAME version, and
    the shared journal holds the whole-set snapshot any replica would
    write (the N byte-identical writes are idempotent)."""
    p1, p2, _ = two_files
    fleet, srv = _fleet(tmp_path)
    try:
        e = fleet.register("m", p1)
        assert e.version == 1
        assert [g.registry.get("m").version
                for g in fleet.engines] == [1, 1]
        e = fleet.swap("m", p2)
        assert e.version == 2
        assert [g.registry.get("m").version
                for g in fleet.engines] == [2, 2]
        # a cold engine rehydrates from the one shared journal to the
        # exact versions the fleet serves
        cold = ServingEngine(ServeConfig(
            buckets=(16, 64),
            journal_path=str(tmp_path / "registry.journal")))
        try:
            assert cold._rehydrated == ["m"]
            assert cold.registry.get("m").version == 2
        finally:
            cold.close()
    finally:
        srv.close()
        fleet.close()
    assert _no_net_threads() == []


def test_drain_replica_lifecycle_refusals(two_files, tmp_path):
    """Per-replica drain: out-of-range raises; draining the LAST live
    replica is refused (that is server drain's job); resume restores
    eligibility so the other replica can then park."""
    p1, _, _ = two_files
    fleet, srv = _fleet(tmp_path)
    try:
        fleet.register("m", p1)
        with pytest.raises(ValueError, match="out of range"):
            srv.drain_replica(9)
        out = srv.drain_replica(0)
        assert out["parked"] is True
        with pytest.raises(RuntimeError, match="last live replica"):
            srv.drain_replica(1)
        srv.resume_replica(0)
        assert srv.drain_replica(1)["parked"] is True
        srv.resume_replica(1)
        # traffic still lands after the cycle
        with ServeClient(srv.host, srv.port) as cli:
            v = cli.request(np.zeros((2, D), np.float32), model="m")
        assert v.verdict == "served"
    finally:
        srv.close()
        fleet.close()
    assert _no_net_threads() == []


# --------------------------------------------- cross-replica swap / load

def _load_clients(srv, n_clients, stop, records, errors, rows_lo=4,
                  rows_hi=17):
    """Closed-loop wire clients until `stop`; each records
    (t_started, verdict, version) per request. Synchronous protocol:
    every request ends in exactly one verdict or one exception —
    the client-side half of the zero-lost/zero-dup ledger."""

    def _loop(idx):
        rng = np.random.default_rng(900 + idx)
        try:
            with ServeClient(srv.host, srv.port, seed=idx) as cli:
                while not stop.is_set():
                    rows = rng.random(
                        (int(rng.integers(rows_lo, rows_hi)), D),
                        dtype=np.float32)
                    t0 = time.monotonic()
                    v = cli.request(rows, model="m")
                    records[idx].append((t0, v.verdict, v.version))
        except Exception as e:  # noqa: BLE001 — ledgered, asserted ==[]
            errors.append((idx, repr(e)))

    threads = [threading.Thread(target=_loop, args=(i,),
                                name=f"test-rep-client-{i}")
               for i in range(n_clients)]
    for t in threads:
        t.start()
    return threads


def test_cross_replica_swap_consistency_under_load(two_files, tmp_path):
    """Hot swap against a 2-replica fleet under sustained wire load:
    in-flight work finishes on the old version, every request STARTED
    after swap() returned answers from the new version on whichever
    replica served it, both replicas carry post-swap traffic, and
    afterwards every replica's decision surface is identical to a
    reference engine serving v2."""
    p1, p2, x = two_files
    fleet, srv = _fleet(tmp_path, device_floor_us_per_row=150.0)
    stop = threading.Event()
    records = [[] for _ in range(3)]
    errors = []
    try:
        fleet.register("m", p1)
        threads = _load_clients(srv, 3, stop, records, errors)
        time.sleep(0.3)  # v1 traffic provably in flight
        entry = fleet.swap("m", p2)
        t_swapped = time.monotonic()
        assert entry.version == 2
        time.sleep(0.4)  # v2 traffic on both replicas
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        assert errors == []
        flat = [r for rec in records for r in rec]
        assert flat and all(v == "served" for _, v, _ in flat)
        versions = {ver for _, _, ver in flat}
        assert versions == {1, 2}, versions  # old finished, new took over
        late = [ver for t0, _, ver in flat if t0 > t_swapped]
        assert late and all(ver == 2 for ver in late)
        per_rep = srv.replica_snapshot()
        assert all(s["verdicts"]["served"] > 0 for s in per_rep)
        snap = srv.drain()
        assert snap["frames_accepted"] == sum(snap["verdicts"].values())
        # every replica now answers EXACTLY like a v2 reference engine
        q = np.asarray(x[:8], np.float32)
        ref = ServingEngine(ServeConfig(buckets=(16, 64)))
        try:
            ref.register("m", p2)
            expect = ref.decision(q, "m")
            for eng in fleet.engines:
                assert eng.registry.get("m").version == 2
                np.testing.assert_array_equal(eng.decision(q, "m"),
                                              expect)
        finally:
            ref.close()
    finally:
        stop.set()
        srv.close()
        fleet.close()
    assert _no_net_threads() == []


def test_rolling_restart_zero_lost_frames(two_files, tmp_path):
    """Rolling restart under sustained load: drain replica 0 through
    the front door while its peer keeps serving, replace its engine
    with a fresh one rehydrated from the shared journal, resume — and
    the ledgers must balance exactly: zero client exceptions, every
    request ends in one explicit served verdict, client-side served
    count == server-side served verdicts, frames_accepted == sum of
    all verdicts, and the restarted replica provably serves again."""
    p1, _, _ = two_files
    fleet, srv = _fleet(tmp_path, device_floor_us_per_row=150.0)
    stop = threading.Event()
    records = [[] for _ in range(3)]
    errors = []
    try:
        fleet.register("m", p1)
        old = fleet.engines[0]
        threads = _load_clients(srv, 3, stop, records, errors)
        time.sleep(0.25)  # offered load provably in flight
        fresh = fleet.restart_replica(0, timeout_s=60.0)
        served_at_restart = \
            srv.replica_snapshot()[0]["verdicts"]["served"]
        assert fresh is fleet.engines[0] and fresh is not old
        assert fresh._rehydrated == ["m"]  # journal, not re-register
        assert fresh.registry.get("m").version == 1
        time.sleep(0.6)  # post-restart traffic must reach replica 0
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        assert errors == []
        flat = [r for rec in records for r in rec]
        assert flat and all(v == "served" for _, v, _ in flat)
        per_rep = srv.replica_snapshot()
        assert per_rep[0]["verdicts"]["served"] > served_at_restart, \
            "restarted replica never served again"
        snap = srv.drain()
        # the zero-lost / zero-duplicated ledger, both directions
        assert snap["verdicts"]["served"] == len(flat)
        assert snap["frames_accepted"] == sum(snap["verdicts"].values())
    finally:
        stop.set()
        srv.close()
        fleet.close()
    assert _no_net_threads() == []


# ------------------------------------------------------------- telemetry

def test_fleet_metrics_families(two_files, tmp_path):
    """One scrape, whole fleet: serving_fleet_* aggregates with rep
    labels plus the front door's serving_replica_* and serving_net_*
    families."""
    p1, _, _ = two_files
    fleet, srv = _fleet(tmp_path)
    try:
        fleet.register("m", p1)
        with ServeClient(srv.host, srv.port) as cli:
            for _ in range(3):
                v = cli.request(np.ones((2, D), np.float32), model="m")
                assert v.verdict == "served"
        text = fleet.render_openmetrics()
        assert "serving_fleet_replicas 2" in text
        for fam in ("serving_fleet_requests_total",
                    "serving_fleet_rows_total",
                    "serving_fleet_dispatches_total",
                    "serving_replica_queue_rows",
                    "serving_replica_inflight_tickets",
                    "serving_replica_draining",
                    "serving_replica_verdicts_total"):
            assert f'{fam}{{rep="0"}}' in text or \
                f'rep="0"' in text.split(fam, 1)[1][:200], fam
            assert f'rep="1"' in text, fam
        assert "serving_net_frames_accepted" in text
        routing = srv.replica_snapshot()
        assert [s["replica"] for s in routing] == [0, 1]
        assert sum(s["verdicts"]["served"] for s in routing) == 3
    finally:
        srv.close()
        fleet.close()
    assert _no_net_threads() == []


def test_single_replica_families_present():
    """The serving_replica_* families exist even at replicas=1 (the
    dashboard contract does not change shape when the fleet grows)."""
    from dpsvm_tpu.obs import export as om

    eng = ServingEngine(ServeConfig(buckets=(16,),
                                    listen="127.0.0.1:0"))
    srv = ServeServer(eng)
    try:
        text = om.render(srv.net_families())
        assert "serving_replica_queue_rows" in text
        assert 'rep="0"' in text
    finally:
        srv.close()
        eng.close()
    assert _no_net_threads() == []
