"""Data-prep converter tests (reference scripts C12 equivalents)."""

import numpy as np

from dpsvm_tpu.data.converters import (
    libsvm_to_csv,
    mnist_to_odd_even,
    parse_libsvm,
)
from dpsvm_tpu.data.loader import load_csv


def test_parse_libsvm_dense_expansion(tmp_path):
    src = tmp_path / "a.libsvm"
    src.write_text(
        "+1 3:1 11:1 14:1\n"
        "-1 1:0.5 4:2\n"
        "+1 2:1\n")
    x, y = parse_libsvm(str(src), num_features=14)
    assert x.shape == (3, 14)
    np.testing.assert_array_equal(y, [1, -1, 1])
    assert x[0, 2] == 1 and x[0, 10] == 1 and x[0, 13] == 1
    assert x[1, 0] == 0.5 and x[1, 3] == 2
    assert x[2].sum() == 1 and x[2, 1] == 1


def test_libsvm_to_csv_roundtrip(tmp_path):
    src = tmp_path / "a.libsvm"
    src.write_text("+1 1:1 3:1\n-1 2:1\n")
    dst = str(tmp_path / "a.csv")
    n, d = libsvm_to_csv(str(src), dst, num_features=3)
    assert (n, d) == (2, 3)
    x, y = load_csv(dst)
    np.testing.assert_array_equal(y, [1, -1])
    np.testing.assert_allclose(x, [[1, 0, 1], [0, 1, 0]])


def test_mnist_odd_even_relabel():
    digits = np.array([0, 1, 2, 3, 7, 8])
    x = np.full((6, 4), 127.5)
    xs, y = mnist_to_odd_even(x, digits)
    np.testing.assert_array_equal(y, [1, -1, 1, -1, -1, 1])
    np.testing.assert_allclose(xs, 0.5)


def test_converters_cli(tmp_path):
    """The module is directly runnable, like the reference's prep
    scripts (scripts/convert_adult.py, convert_mnist_to_odd_even.py)."""
    from dpsvm_tpu.data.converters import main

    src = tmp_path / "a.libsvm"
    src.write_text("+1 1:0.5 3:1\n-1 2:2\n")
    dst = tmp_path / "a.csv"
    assert main(["adult", str(src), str(dst), "--num-features", "4"]) == 0
    x, y = load_csv(str(dst))
    assert x.shape == (2, 4)
    np.testing.assert_array_equal(y, [1, -1])

    msrc = tmp_path / "digits.csv"
    msrc.write_text("0,127.5,0\n3,255,255\n")
    mdst = tmp_path / "evenodd.csv"
    assert main(["mnist_even_odd", str(msrc), str(mdst)]) == 0
    x, y = load_csv(str(mdst))
    np.testing.assert_array_equal(y, [1, -1])
    np.testing.assert_allclose(x[0], [0.5, 0.0])
