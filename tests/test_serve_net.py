"""Network front door tests (dpsvm_tpu/serving/{wire,server,client} —
ISSUE 15): wire-codec round trips and refusals, socket-path parity
with the model layer, clock-skew-safe deadline-budget propagation,
admission rejects with retry hints, the client's compute-safe retry
policy, slow-reader/slow-writer bounds, seeded protocol fuzz (no
wedge, no thread leak, counters reconcile), graceful drain under
offered load, and the `cli serve --listen` path.

Budget discipline: plain sockets + one tiny module-scoped model; no
new interpret-mode Pallas compiles (tier-1 sits near its ceiling)."""

import socket
import struct
import threading
import time
import types

import numpy as np
import pytest

from dpsvm_tpu.config import ServeConfig, SVMConfig
from dpsvm_tpu.models.multiclass import (decision_matrix,
                                         predict_multiclass,
                                         train_multiclass)
from dpsvm_tpu.serving import ServeClient, ServeServer, ServingEngine
from dpsvm_tpu.serving import wire
from dpsvm_tpu.serving.client import (ConnectError, ConnectionDropped,
                                      SendAborted, ServerDraining)
from dpsvm_tpu.testing import faults

CFG = SVMConfig(c=5.0, gamma=0.25, epsilon=1e-3, chunk_iters=256)


@pytest.fixture(scope="module")
def tiny_model():
    rng = np.random.default_rng(17)
    xs, ys = [], []
    for k in range(3):
        c = np.zeros(5, np.float32)
        c[k] = 2.5
        xs.append(rng.normal(size=(45, 5)).astype(np.float32) * 0.7 + c)
        ys.append(np.full(45, k))
    x, y = np.concatenate(xs), np.concatenate(ys)
    model, _ = train_multiclass(x, y, CFG, strategy="ovr")
    return model, x


def _served(**kw):
    """(engine, server) with a small bucket ladder."""
    kw.setdefault("buckets", (16, 64))
    eng = ServingEngine(ServeConfig(**kw))
    return eng, ServeServer(eng)


def _no_net_threads(deadline_s=10.0):
    """All dpsvm-net threads gone (the zero-leak acceptance)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        left = [t.name for t in threading.enumerate()
                if t.name.startswith("dpsvm-net")]
        if not left:
            return []
        time.sleep(0.02)
    return left


# ------------------------------------------------------------- wire codec

def test_wire_request_verdict_roundtrip():
    rows = np.arange(12, dtype=np.float32).reshape(3, 4) / 7
    frame = wire.pack_request(42, rows, "mnist", 125.5,
                              want_decision=True)
    ftype, length = wire.parse_header(frame[:wire.HEADER_BYTES],
                                      max_payload=1 << 20)
    assert ftype == wire.T_REQUEST
    req = wire.parse_request(frame[wire.HEADER_BYTES:])
    assert (req.req_id, req.model, req.want_decision) == (42, "mnist",
                                                          True)
    assert req.budget_ms == 125.5
    np.testing.assert_array_equal(req.rows, rows)  # bitwise through >f4
    # no-deadline and no-model ride the sentinel encodings
    bare = wire.parse_request(wire.pack_request(
        1, rows, None, None)[wire.HEADER_BYTES:])
    assert bare.model is None and bare.budget_ms is None

    lab = np.array([1, -1, 7], np.int32)
    v = wire.parse_verdict(wire.pack_verdict(
        9, "late", model="m", version=3, latency_ms=12.25,
        labels=lab)[wire.HEADER_BYTES:])
    assert (v.verdict, v.model, v.version) == ("late", "m", 3)
    np.testing.assert_array_equal(v.labels, lab)
    dec = np.linspace(-2, 2, 6, dtype=np.float32).reshape(2, 3)
    v2 = wire.parse_verdict(wire.pack_verdict(
        8, "served", decision=dec)[wire.HEADER_BYTES:])
    np.testing.assert_array_equal(v2.decision, dec)  # bitwise
    v3 = wire.parse_verdict(wire.pack_verdict(
        7, "rejected", retry_after_ms=80,
        message="queue full")[wire.HEADER_BYTES:])
    assert (v3.retry_after_ms, v3.message) == (80, "queue full")
    assert v3.labels is None and v3.decision is None


def test_wire_header_refusals():
    with pytest.raises(wire.WireError, match="magic"):
        wire.parse_header(b"XX\x01\x01\x00\x00\x00\x00", 1 << 20)
    with pytest.raises(wire.WireError, match="version"):
        wire.parse_header(b"DS\x09\x01\x00\x00\x00\x00", 1 << 20)
    with pytest.raises(wire.WireError, match="frame type"):
        wire.parse_header(b"DS\x01\x77\x00\x00\x00\x00", 1 << 20)
    with pytest.raises(wire.WireError, match="exceeds"):
        # the hostile length prefix is refused BEFORE any allocation
        wire.parse_header(struct.pack("!2sBBI", b"DS", 1,
                                      wire.T_REQUEST, 1 << 31), 1 << 20)
    with pytest.raises(wire.WireError, match="carries"):
        # declared shape disagrees with the payload bytes
        good = wire.pack_request(1, np.zeros((2, 3), np.float32), "m",
                                 None)
        wire.parse_request(good[wire.HEADER_BYTES:-4])
    # hostile payload CONTENT surfaces as WireError too (never a raw
    # UnicodeDecodeError/struct.error escaping the containment)
    bad_name = (struct.pack("!IBdH", 1, 0, -1.0, 2) + b"\xff\xfe"
                + struct.pack("!II", 0, 0))
    with pytest.raises(wire.WireError, match="UTF-8"):
        wire.parse_request(bad_name)
    bad_verdict = (struct.pack("!IBIdIH", 1, 0, 0, 0.0, 0, 2)
                   + b"\xff\xfe" + struct.pack("!BI", 0, 0))
    with pytest.raises(wire.WireError, match="malformed VERDICT"):
        wire.parse_verdict(bad_verdict)
    with pytest.raises(wire.WireError, match="shorter"):
        wire.parse_verdict(b"\x00\x01")
    # the new net seams are part of the DPSVM_FAULTS grammar
    plan = faults.FaultPlan.parse(
        "net_accept,net_conn_drop@2,net_read_stall,net_partial_write")
    assert len(plan.specs) == 4


# --------------------------------------------------------- socket parity

def test_socket_roundtrip_parity(tiny_model):
    model, x = tiny_model
    eng, srv = _served()
    try:
        eng.register("m", model)
        q = np.asarray(x[:10], np.float32)
        with ServeClient(srv.host, srv.port) as cli:
            v = cli.request(q, model="m")
            assert v.verdict == "served" and v.version == 1
            np.testing.assert_array_equal(
                v.labels, predict_multiclass(model, q))
            np.testing.assert_allclose(
                cli.decision(q, model="m"), decision_matrix(model, q),
                rtol=1e-5, atol=1e-5)
            # single registered model: the bare (no-name) route works
            assert cli.request(q).verdict == "served"
        snap = srv.net_snapshot()
        assert snap["frames_accepted"] == 3
        assert snap["verdicts"]["served"] == 3
    finally:
        srv.close()
        eng.close()
    assert _no_net_threads() == []


def test_unknown_model_and_bad_width_fail_not_retry(tiny_model):
    """Request-level failures are explicit 'failed' verdicts — never
    retried (the frame is wrong, not the wire), never a dead
    connection."""
    model, x = tiny_model
    eng, srv = _served()
    try:
        eng.register("m", model)
        with ServeClient(srv.host, srv.port) as cli:
            v = cli.request(np.zeros((2, 5), np.float32), model="ghost")
            assert v.verdict == "failed" and "ghost" in v.message
            assert cli.last_attempts == 1  # failed is NEVER retried
            v = cli.request(np.zeros((2, 9), np.float32), model="m")
            assert v.verdict == "failed" and "(n, 5)" in v.message
            # the connection survived both
            assert cli.request(np.zeros((2, 5), np.float32),
                               model="m").verdict == "served"
    finally:
        srv.close()
        eng.close()


# ---------------------------------------------------- deadline propagation

def test_deadline_budget_propagation(tiny_model):
    """THE CLOCK CONTRACT: the wire carries a remaining BUDGET, and
    the server anchors it to its own clock by passing it VERBATIM as
    submit's relative deadline_ms — the client's wall clock never
    enters. A negative/absent budget falls back to the server's
    configured default."""
    model, x = tiny_model
    eng, srv = _served(deadline_ms=777.0)
    try:
        eng.register("m", model)
        seen = []
        orig = eng.submit

        def _spy(rows, model=None, **kw):
            seen.append(kw.get("deadline_ms", "absent"))
            return orig(rows, model=model, **kw)

        eng.submit = _spy
        q = np.zeros((2, 5), np.float32)
        with ServeClient(srv.host, srv.port) as cli:
            cli.request(q, model="m", deadline_ms=123.0)
            cli.request(q, model="m")  # no budget -> server default
        # the client ships its REMAINING budget (anchor minus elapsed,
        # which includes the connect) — a duration, never a timestamp:
        # whatever arrives is <= the caller's budget and far from any
        # wall-clock-looking number.
        assert 60.0 < seen[0] <= 123.0, seen
        assert seen[1] == "absent"  # engine applies config.deadline_ms
    finally:
        del eng.submit
        srv.close()
        eng.close()


def test_expired_budget_gets_explicit_verdict(tiny_model):
    """A zero remaining budget is still ANSWERED: the engine sheds it
    at batch forming with an explicit 'expired' wire verdict (never a
    silent drop, never silent service)."""
    model, x = tiny_model
    eng, srv = _served()
    try:
        eng.register("m", model)
        with ServeClient(srv.host, srv.port) as cli:
            v = cli.request(np.zeros((2, 5), np.float32), model="m",
                            deadline_ms=0.0)
            assert v.verdict == "expired"
            assert v.labels is None
        assert srv.net_snapshot()["verdicts"]["expired"] == 1
        assert eng.expired.value == 1  # the engine counted it too
    finally:
        srv.close()
        eng.close()


# -------------------------------------------------------------- admission

def test_admission_rejects_with_retry_hint(tiny_model):
    """Saturation becomes an immediate 'rejected' verdict with a
    retry_after_ms hint — never unbounded buffering, never a blocked
    pump. Deterministic: the engine's pump is held, so queued rows
    provably sit at the bound when the second request arrives."""
    model, x = tiny_model
    eng, srv = _served(admission_max_rows=8)
    try:
        eng.register("m", model)
        eng.pump_real = eng.pump
        eng.pump = lambda: 0  # hold the engine: queue cannot drain
        first = {}

        def _first():
            with ServeClient(srv.host, srv.port, seed=1) as c:
                first["v"] = c.request(np.zeros((8, 5), np.float32),
                                       model="m")

        th = threading.Thread(target=_first)
        th.start()
        deadline = time.monotonic() + 10
        while eng.scheduler.queue_rows < 8 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.scheduler.queue_rows == 8  # admitted, held
        with ServeClient(srv.host, srv.port, seed=2,
                         reject_retries=0) as cli:
            v = cli.request(np.zeros((2, 5), np.float32), model="m")
        assert v.verdict == "rejected"
        assert v.retry_after_ms > 0
        assert "admission" in v.message
        eng.pump = eng.pump_real  # release: the held request completes
        th.join(timeout=60)
        assert not th.is_alive()
        assert first["v"].verdict == "served"
        assert srv.net_snapshot()["verdicts"]["rejected"] == 1
    finally:
        eng.pump = eng.pump_real
        srv.close()
        eng.close()


def test_client_retries_rejected_with_hint_backoff(tiny_model):
    """The retry policy's positive half: 'rejected' IS retried (the
    server promised it did no work), honoring the retry_after hint,
    and succeeds once the saturation clears."""
    model, x = tiny_model
    eng, srv = _served(admission_max_rows=8, admission_retry_ms=20.0)
    try:
        eng.register("m", model)
        eng.pump_real = eng.pump
        eng.pump = lambda: 0
        filler = {}

        def _fill():
            with ServeClient(srv.host, srv.port, seed=3) as c:
                filler["v"] = c.request(np.zeros((8, 5), np.float32),
                                        model="m")

        th = threading.Thread(target=_fill)
        th.start()
        deadline = time.monotonic() + 10
        while eng.scheduler.queue_rows < 8 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        release = threading.Timer(0.25, lambda: setattr(
            eng, "pump", eng.pump_real))
        release.start()
        with ServeClient(srv.host, srv.port, seed=4, reject_retries=8,
                         backoff_s=0.02) as cli:
            v = cli.request(np.zeros((2, 5), np.float32), model="m")
            assert v.verdict == "served"
            assert cli.last_attempts > 1  # it really was rejected first
            assert cli.verdicts_observed["rejected"] >= 1
        th.join(timeout=60)
        release.join()
        assert filler["v"].verdict == "served"
    finally:
        eng.pump = eng.pump_real
        srv.close()
        eng.close()


def test_connect_retry_bounded():
    """Connect failures retry with bounded backoff, then raise — and
    a server that never existed cannot have done work, so this is the
    one place retrying is unconditionally safe."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    cli = ServeClient("127.0.0.1", port, connect_retries=2,
                      backoff_s=0.01, timeout_s=2.0)
    with pytest.raises(ConnectError, match="after 3 attempts"):
        cli.request(np.zeros((1, 5), np.float32), model="m")


# ------------------------------------------------- slow peers, both ways

def test_send_with_deadline_bounds_stalled_reader():
    """The whole-frame write deadline: a peer that stops reading
    cannot hold a writer past conn_write_timeout_ms (socket timeouts
    alone bound one syscall, not a trickled frame)."""
    from dpsvm_tpu.serving.server import _send_with_deadline

    a, b = socket.socketpair()
    try:
        a.settimeout(5.0)  # the front door's precondition: timeout mode
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        t0 = time.monotonic()
        with pytest.raises(socket.timeout, match="exceeded"):
            _send_with_deadline(a, b"\x00" * (4 << 20), 0.3)
        assert time.monotonic() - t0 < 5.0
    finally:
        a.close()
        b.close()


def test_slow_reader_outbox_bound_kills_only_that_conn(tiny_model,
                                                      monkeypatch):
    """A reader stalled long enough to back up its outbox costs
    exactly its own connection: killed, verdicts counted
    undeliverable, every other client unaffected."""
    from dpsvm_tpu.serving import server as server_mod

    monkeypatch.setattr(server_mod, "OUTBOX_FRAMES", 2)
    real_send = server_mod._send_with_deadline
    monkeypatch.setattr(
        server_mod, "_send_with_deadline",
        lambda sock, data, t: (time.sleep(0.15),
                               real_send(sock, data, t))[1])
    model, x = tiny_model
    eng, srv = _served()
    try:
        eng.register("m", model)
        # a raw pipelining client that never reads its verdicts
        sock = socket.create_connection((srv.host, srv.port),
                                        timeout=10)
        q = np.zeros((2, 5), np.float32)
        for i in range(8):
            sock.sendall(wire.pack_request(i + 1, q, "m", None))
        deadline = time.monotonic() + 20
        while srv.net_snapshot()["conns_killed"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        snap = srv.net_snapshot()
        assert snap["conns_killed"] == 1, snap
        assert snap["undeliverable_total"] > 0, snap
        sock.close()
        # …and a healthy client is untouched
        with ServeClient(srv.host, srv.port, seed=9) as cli:
            assert cli.request(q, model="m").verdict == "served"
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------------------ protocol fuzz

def test_protocol_fuzz_never_wedges(tiny_model):
    """The satellite's seeded fuzz generator, in-suite: truncated
    frames, hostile length prefixes, wrong magic, garbage, mid-frame
    disconnects — the server never wedges, never leaks a thread, and
    the error/abort counters reconcile EXACTLY with what was sent."""
    from tools.loadgen import _fuzz_burst

    model, x = tiny_model
    eng, srv = _served()
    try:
        eng.register("m", model)
        before = srv.net_snapshot()
        sent = _fuzz_burst(srv.host, srv.port, seed=3)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            snap = srv.net_snapshot()
            if (snap["protocol_errors"] - before["protocol_errors"]
                    == sent["protocol"]
                    and snap["conns_aborted"] - before["conns_aborted"]
                    == sent["aborted"]
                    and snap["open_connections"] == 0):
                break
            time.sleep(0.02)
        snap = srv.net_snapshot()
        assert snap["protocol_errors"] == sent["protocol"], snap
        assert snap["conns_aborted"] == sent["aborted"], snap
        assert snap["frames_accepted"] == 0, snap
        assert snap["conns_opened"] == snap["conns_closed"], snap
        # a garbage client gets the ERROR frame before the close
        sock = socket.create_connection((srv.host, srv.port),
                                        timeout=10)
        head = wire.recv_exact(sock, wire.HEADER_BYTES)
        assert wire.parse_header(head, 1 << 20)[0] == wire.T_HELLO
        wire.recv_exact(sock, wire.parse_header(head, 1 << 20)[1])
        sock.sendall(b"XXgarbage-frame!")
        head = wire.recv_exact(sock, wire.HEADER_BYTES)
        ftype, length = wire.parse_header(head, 1 << 20)
        assert ftype == wire.T_ERROR
        _, msg = wire.parse_error(wire.recv_exact(sock, length))
        assert "magic" in msg
        sock.close()
        # the engine itself never noticed
        with ServeClient(srv.host, srv.port, seed=5) as cli:
            assert cli.request(np.zeros((2, 5), np.float32),
                               model="m").verdict == "served"
    finally:
        srv.close()
        eng.close()
    assert _no_net_threads() == []


# ------------------------------------------------------------------ drain

def test_graceful_drain_under_load(tiny_model, tmp_path):
    """SIGTERM semantics: under sustained offered load, drain yields
    ONLY explicit outcomes (verdicts, a drain-rejected verdict, a
    GOODBYE, or a refused reconnect — never a reset without a
    verdict), conserves the frame accounting, and leaves zero server
    threads."""
    from dpsvm_tpu.serving.client import ServeClient as SC

    model, x = tiny_model
    jp = str(tmp_path / "registry.journal")
    eng, srv = _served(journal_path=jp, deadline_ms=2000.0)
    outcomes = []

    def _loop(idx):
        cli = SC(srv.host, srv.port, seed=idx, reject_retries=0,
                 connect_retries=1, backoff_s=0.01)
        rng = np.random.default_rng(idx)
        try:
            for _ in range(10_000):
                rows = rng.random((int(rng.integers(1, 9)), 5),
                                  dtype=np.float32)
                try:
                    v = cli.request(rows, model="m", deadline_ms=2000.0)
                    if v.verdict == "rejected":
                        outcomes.append("drain_rejected")
                        return
                except ServerDraining:
                    outcomes.append("goodbyed")
                    return
                except ConnectError:
                    outcomes.append("connect_refused")
                    return
                except (ConnectionDropped, SendAborted) as e:
                    outcomes.append(f"IMPLICIT:{type(e).__name__}")
                    return
            outcomes.append("exhausted")
        finally:
            cli.close()

    try:
        # model saved to disk so the journal records it
        mp = str(tmp_path / "m.npz")
        model.save(mp)
        eng.register("m", mp)
        threads = [threading.Thread(target=_loop, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # offered load provably in flight
        snap = srv.drain()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        assert len(outcomes) == 3 and not any(
            o.startswith("IMPLICIT") or o == "exhausted"
            for o in outcomes), outcomes
        # conservation held right through the drain
        assert snap["frames_accepted"] == sum(snap["verdicts"].values())
        assert snap["goodbyes_sent"] >= 1 or \
            "goodbyed" not in outcomes
        # post-drain connects are refused, not reset mid-request
        with pytest.raises(ConnectError):
            SC(srv.host, srv.port, connect_retries=0,
               timeout_s=2.0).request(np.zeros((1, 5), np.float32),
                                      model="m")
        # drain twice is idempotent
        assert srv.drain()["frames_accepted"] == \
            snap["frames_accepted"]
    finally:
        srv.close()
        eng.close()
    assert _no_net_threads() == []


# ------------------------------------------------------------ CLI surface

def test_cli_serve_listen_roundtrip(tiny_model, tmp_path):
    """`cli serve --listen` end to end in-process: the run loop serves
    wire clients until the stop event (the signal handler's seam),
    then drains and closes the engine."""
    from dpsvm_tpu import cli as cli_mod

    model, x = tiny_model
    mp = str(tmp_path / "m.npz")
    model.save(mp)
    config = ServeConfig(buckets=(16, 64), listen="127.0.0.1:0")
    engine = ServingEngine(config)
    engine.register("m", mp)
    args = types.SimpleNamespace(quiet=True)
    stop = threading.Event()
    rc = {}

    def _run():
        rc["rc"] = cli_mod._serve_listen(args, engine, config,
                                         stop_event=stop)

    th = threading.Thread(target=_run)
    th.start()
    deadline = time.monotonic() + 10
    while engine._front is None and time.monotonic() < deadline:
        time.sleep(0.01)
    srv = engine._front
    assert srv is not None
    q = np.asarray(x[:4], np.float32)
    with ServeClient(srv.host, srv.port) as cli:
        np.testing.assert_array_equal(cli.predict(q, model="m"),
                                      predict_multiclass(model, q))
    stop.set()
    th.join(timeout=60)
    assert not th.is_alive() and rc["rc"] == 0
    assert engine._closed  # the listen loop owns engine teardown
    assert _no_net_threads() == []


def test_cli_listen_bad_spec(capsys):
    from dpsvm_tpu import cli as cli_mod

    rc = cli_mod.main(["serve", "--listen", "nohostport",
                       "--registry", "m=/dev/null"])
    assert rc == 2
    assert "listen" in capsys.readouterr().err


def test_serve_config_net_validation():
    with pytest.raises(ValueError, match="listen"):
        ServeConfig(listen="9100")  # no host
    with pytest.raises(ValueError, match="admission_max_rows"):
        ServeConfig(admission_max_rows=0)
    with pytest.raises(ValueError, match="max_pending"):
        ServeConfig(admission_max_rows=1 << 20)
    with pytest.raises(ValueError, match="conn_read_timeout_ms"):
        ServeConfig(conn_read_timeout_ms=0)
    with pytest.raises(ValueError, match="max_frame_bytes"):
        ServeConfig(max_frame_bytes=16)
    assert ServeConfig(listen="0.0.0.0:9100").listen_addr() == \
        ("0.0.0.0", 9100)


# --------------------------------------------- /metrics + runlog surfaces

def test_net_families_on_metrics_and_snapshot(tiny_model):
    """The front door's counters ride the ENGINE's /metrics exposition
    and snapshot() (one scrape, one truth — the chaos reconciliation
    could be done from a scrape alone)."""
    import urllib.request

    model, x = tiny_model
    eng, srv = _served(metrics_port=0)
    try:
        eng.register("m", model)
        with ServeClient(srv.host, srv.port, seed=1,
                         reject_retries=0) as cli:
            cli.request(np.zeros((2, 5), np.float32), model="m")
        with urllib.request.urlopen(eng.exporter.url,
                                    timeout=10) as resp:
            text = resp.read().decode()
        assert "serving_net_frames_accepted_total 1" in text
        assert 'serving_net_verdicts_total{verdict="served"} 1' in text
        assert "serving_net_protocol_errors_total 0" in text
        snap = eng.snapshot()
        assert snap["net"]["frames_accepted"] == 1
        assert snap["net"]["verdicts"]["served"] == 1
    finally:
        srv.close()
        eng.close()
