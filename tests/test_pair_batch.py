"""pair_batch=2: batched disjoint-pair subproblem steps.

Contracts (see SVMConfig.pair_batch and ops/pallas_subproblem.py): same
fixed point as pair_batch=1 (every batched slot is an exact descent step
on a violating pair, so the standard decomposition convergence argument
is unchanged), exact dual feasibility, deterministic budget accounting
(attempted second slots count even when gated to no-ops — the
second_order counted-no-op precedent), and Pallas/XLA implementation
parity. Trajectories are NOT comparable to pair_batch=1 (the pair
sequence differs by construction).
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=10.0, gamma=0.15, epsilon=1e-3, max_iter=200_000,
                engine="block", working_set_size=64, pair_batch=2)


def dual_objective(x, y, alpha, kp):
    K = np.asarray(kernel_matrix(x, x, kp))
    ay = alpha * y
    return alpha.sum() - 0.5 * ay @ K @ ay


def test_same_optimum_as_single_pair(blobs_medium):
    x, y = blobs_medium
    kp = KernelParams("rbf", CFG.gamma)
    r1 = solve(x, y, CFG.replace(pair_batch=1))
    r2 = solve(x, y, CFG)
    assert r2.converged
    obj1 = dual_objective(x, y, r1.alpha, kp)
    obj2 = dual_objective(x, y, r2.alpha, kp)
    assert obj2 == pytest.approx(obj1, rel=1e-4)
    assert r2.b == pytest.approx(r1.b, abs=5e-3)
    assert abs(r2.n_sv - r1.n_sv) <= max(3, 0.02 * r1.n_sv)


def test_feasibility_and_conservation(blobs_medium):
    x, y = blobs_medium
    cfg = CFG.replace(weight_pos=1.5, weight_neg=0.75)
    r = solve(x, y, cfg)
    assert r.converged
    a = np.asarray(r.alpha)
    c_i = np.where(y > 0, cfg.c * cfg.weight_pos, cfg.c * cfg.weight_neg)
    assert a.min() >= 0.0
    assert np.all(a <= c_i + 1e-5)
    # The pair algebra conserves sum alpha_i y_i exactly per update.
    assert abs(float(np.dot(a, y))) < 1e-3


@pytest.mark.parametrize("budget", [999, 12344, 12345])
def test_budget_mode_exact_pair_count(blobs_small, budget):
    """Odd budgets exercise the second-slot (t1 < limit) gate: the batch
    must stop at exactly the budget, never one past it."""
    x, y = blobs_small
    r = solve(x, y, CFG.replace(budget_mode=True, max_iter=budget))
    assert int(r.iterations) == budget


def test_pallas_xla_subproblem_parity():
    """The interpret-mode Pallas kernel and the XLA while_loop implement
    the SAME batched semantics: identical pair counts and alphas on a
    random subproblem driven to its local optimum."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import _solve_subproblem

    rng = np.random.default_rng(0)
    q, c = 64, 4.0
    g = rng.normal(size=(q, 18)).astype(np.float32)
    kb = np.exp(-0.1 * ((g[:, None] - g[None, :]) ** 2).sum(-1))
    kd = np.ones(q, np.float32)
    y = np.where(rng.random(q) > 0.5, 1.0, -1.0).astype(np.float32)
    alpha = np.clip(rng.normal(1.0, 1.0, q), 0, c).astype(np.float32)
    K = kb * 1.0
    f = ((alpha * y) @ K - y).astype(np.float32)
    ok = np.ones(q, np.float32)
    ok[-5:] = 0.0  # dead filler slots must stay untouched
    args = (jnp.asarray(kb, jnp.float32), jnp.asarray(alpha),
            jnp.asarray(y), jnp.asarray(f), jnp.asarray(kd),
            jnp.asarray(ok), jnp.int32(5000))
    a_p, t_p = solve_subproblem_pallas(*args, c, 1e-3, 1e-12, rule="mvp",
                                       interpret=True, pair_batch=2)
    a_x, _, t_x = _solve_subproblem(
        args[0], args[4], args[5] > 0, args[1], args[2], args[3], c,
        1e-3, 1e-12, args[6], rule="mvp", pair_batch=2)
    assert int(t_p) == int(t_x)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_x),
                               rtol=1e-5, atol=1e-6)
    # Dead slots: exact no-touch.
    np.testing.assert_array_equal(np.asarray(a_p)[-5:], alpha[-5:])


@pytest.mark.parametrize("pb", [4])
def test_pair_batch4_block(blobs_medium, pb):
    """Round-5 extension: the subproblem batches up to 4 stale-ranked
    disjoint pairs per trip — same fixed point, exact feasibility,
    budget-exact counting (the generalized slot loop in
    ops/pallas_subproblem.py / solver/block.py)."""
    x, y = blobs_medium
    kp = KernelParams("rbf", CFG.gamma)
    r1 = solve(x, y, CFG.replace(pair_batch=1))
    r4 = solve(x, y, CFG.replace(pair_batch=pb))
    assert r4.converged
    obj1 = dual_objective(x, y, r1.alpha, kp)
    obj4 = dual_objective(x, y, r4.alpha, kp)
    assert obj4 == pytest.approx(obj1, rel=1e-4)
    a = np.asarray(r4.alpha)
    assert a.min() >= 0.0 and a.max() <= CFG.c + 1e-5
    assert abs(float(a @ y)) < 1e-2
    rb = solve(x, y, CFG.replace(pair_batch=pb, budget_mode=True,
                                 max_iter=4001))
    assert int(rb.iterations) == 4001


def test_pallas_xla_subproblem_parity_pb4():
    """Pallas/XLA parity for the 4-slot batch (interpret mode)."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import _solve_subproblem

    rng = np.random.default_rng(1)
    q, c = 128, 4.0
    g = rng.normal(size=(q, 12)).astype(np.float32)
    kb = np.exp(-0.1 * ((g[:, None] - g[None, :]) ** 2).sum(-1))
    kd = np.ones(q, np.float32)
    y = np.where(rng.random(q) > 0.5, 1.0, -1.0).astype(np.float32)
    alpha = np.clip(rng.normal(1.0, 1.0, q), 0, c).astype(np.float32)
    f = ((alpha * y) @ kb - y).astype(np.float32)
    ok = np.ones(q, np.float32)
    args = (jnp.asarray(kb, jnp.float32), jnp.asarray(alpha),
            jnp.asarray(y), jnp.asarray(f), jnp.asarray(kd),
            jnp.asarray(ok), jnp.int32(5000))
    a_p, t_p = solve_subproblem_pallas(*args, c, 1e-3, 1e-12, rule="mvp",
                                       interpret=True, pair_batch=4)
    a_x, _, t_x = _solve_subproblem(
        args[0], args[4], args[5] > 0, args[1], args[2], args[3], c,
        1e-3, 1e-12, args[6], rule="mvp", pair_batch=4)
    assert int(t_p) == int(t_x)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_x),
                               rtol=1e-5, atol=1e-6)


def test_second_slot_progress(blobs_small):
    """The batch must actually converge in fewer inner trips than it
    counts pairs: with pair_batch=2 a converged solve's pair count stays
    within ~2x of the single-pair count (it would blow past it if the
    second slot did junk updates that undo progress)."""
    x, y = blobs_small
    r1 = solve(x, y, CFG.replace(pair_batch=1))
    r2 = solve(x, y, CFG)
    assert r2.converged
    assert int(r2.iterations) <= 2.5 * int(r1.iterations)


def test_mesh_pair_batch(blobs_small):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    kp = KernelParams("rbf", CFG.gamma)
    r1 = solve(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=8)
    assert rm.converged
    obj1 = dual_objective(x, y, r1.alpha, kp)
    objm = dual_objective(x, y, rm.alpha, kp)
    assert objm == pytest.approx(obj1, rel=1e-4)


def test_validation():
    with pytest.raises(ValueError):
        SVMConfig(pair_batch=3)
    # Round 5: engine='xla' pair_batch>1 is the micro-batch executor
    # (tests/test_micro_batch.py), no longer rejected.
    SVMConfig(engine="xla", pair_batch=2)
    with pytest.raises(ValueError):
        SVMConfig(engine="block", selection="second_order", pair_batch=2)
    # fused-fold + active-set compositions stay legal (pair_batch lives
    # inside the shared subproblem, below both).
    SVMConfig(engine="block", pair_batch=2, active_set_size=256)
    SVMConfig(engine="block", pair_batch=2, fused_fold=True)


def test_active_set_pair_batch(blobs_medium):
    x, y = blobs_medium
    kp = KernelParams("rbf", CFG.gamma)
    r1 = solve(x, y, CFG.replace(pair_batch=1))
    ra = solve(x, y, CFG.replace(active_set_size=256))
    assert ra.converged
    obj1 = dual_objective(x, y, r1.alpha, kp)
    obja = dual_objective(x, y, ra.alpha, kp)
    assert obja == pytest.approx(obj1, rel=1e-4)


def test_estimators_expose_pair_batch(blobs_small):
    """sklearn-facade estimators accept and clone the pair_batch knob."""
    from dpsvm_tpu.estimators import SVC

    x, y = blobs_small
    est = SVC(C=5.0, gamma=0.2, engine="block", working_set_size=32,
              pair_batch=2)
    try:
        from sklearn.base import clone
        est = clone(est)
        assert est.pair_batch == 2
    except ImportError:
        pass
    est.fit(x, y)
    assert est.score(x, y) > 0.8
