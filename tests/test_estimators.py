"""sklearn-compatible estimator facade (dpsvm_tpu.estimators): parity
against sklearn's own SVC/SVR/OneClassSVM and compatibility with sklearn
model-selection tooling (clone / GridSearchCV / cross_val_score)."""

import numpy as np
import pytest

from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.estimators import SVC, SVR, OneClassSVM


def _sk_svc_proba_oracle(x, y, **kw):
    """Build AND fit the sklearn SVC(probability=True) ORACLE,
    version-guarded (VERDICT round-5 item 8): sklearn deprecates the
    in-estimator Platt path with a FutureWarning at 1.9 (removal slated
    for 1.11, pointing at CalibratedClassifierCV). The oracle must stay
    the SAME estimator across versions — swapping in
    CalibratedClassifierCV would change the calibration protocol being
    compared against — so on >= 1.9 the deprecation warning is filtered
    around this construction+fit only, keeping tier-1 warning-free
    without masking any other warning. When 1.11 actually removes the
    parameter this helper is the one place that needs the
    CalibratedClassifierCV port."""
    import sklearn
    from sklearn.svm import SVC as SkSVC

    ver = tuple(int(v) for v in sklearn.__version__.split(".")[:2])
    if ver < (1, 9):
        return SkSVC(probability=True, **kw).fit(x, y)
    import warnings

    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", category=FutureWarning,
                                message=".*probability.*")
        return SkSVC(probability=True, **kw).fit(x, y)


@pytest.fixture(scope="module")
def binary_xy():
    x, y = make_blobs_binary(n=600, d=10, seed=3, sep=1.6)
    return x, y


@pytest.fixture(scope="module")
def multi_xy():
    rng = np.random.default_rng(11)
    centers = rng.normal(scale=3.0, size=(3, 8))
    x = np.concatenate([
        centers[k] + rng.normal(scale=1.0, size=(150, 8)) for k in range(3)
    ]).astype(np.float32)
    y = np.repeat([4, 7, 9], 150)  # arbitrary labels on purpose
    return x, y


def test_svc_binary_matches_sklearn(binary_xy):
    from sklearn.svm import SVC as SkSVC
    x, y = binary_xy
    ours = SVC(C=5.0, gamma=0.1, tol=1e-3).fit(x, y)
    theirs = SkSVC(C=5.0, gamma=0.1, tol=1e-3).fit(x, y)
    assert ours.score(x, y) == pytest.approx(theirs.score(x, y), abs=0.01)
    assert abs(int(ours.n_support_.sum()) - int(theirs.n_support_.sum())) \
        <= max(3, int(0.03 * theirs.n_support_.sum()))
    np.testing.assert_allclose(
        ours.decision_function(x[:50]), theirs.decision_function(x[:50]),
        atol=5e-2)


def test_svc_accepts_01_labels(binary_xy):
    x, y = binary_xy
    y01 = (y > 0).astype(int)
    est = SVC(C=5.0, gamma=0.1).fit(x, y01)
    pred = est.predict(x)
    assert set(np.unique(pred)) <= {0, 1}
    # Label encoding must not change the model: same accuracy as +-1.
    ref = SVC(C=5.0, gamma=0.1).fit(x, np.where(y01 > 0, 1, -1))
    assert est.score(x, y01) == pytest.approx(
        ref.score(x, np.where(y01 > 0, 1, -1)), abs=1e-6)


def test_svc_multiclass(multi_xy):
    from sklearn.svm import SVC as SkSVC
    x, y = multi_xy
    for strategy in ("ovr", "ovo"):
        est = SVC(C=5.0, gamma=0.1, strategy=strategy).fit(x, y)
        assert set(np.unique(est.predict(x))) <= {4, 7, 9}
        sk = SkSVC(C=5.0, gamma=0.1).fit(x, y)
        assert est.score(x, y) == pytest.approx(sk.score(x, y), abs=0.03)
    assert est.decision_function(x[:10]).shape == (10, 3)


def test_svc_ovo_decision_function_is_per_class(multi_xy):
    # sklearn's default decision_function_shape='ovr': one column per
    # class even for OvO, where the pairwise count (k(k-1)/2) differs
    # from k as soon as k >= 4.
    rng = np.random.default_rng(2)
    centers = rng.normal(scale=3.5, size=(4, 6))
    x = np.concatenate([
        centers[k] + rng.normal(scale=1.0, size=(80, 6)) for k in range(4)
    ]).astype(np.float32)
    y = np.repeat([0, 1, 2, 3], 80)
    est = SVC(C=5.0, gamma=0.1, strategy="ovo").fit(x, y)
    d = est.decision_function(x[:17])
    assert d.shape == (17, 4)  # classes, not the 6 pairs
    # argmax of the folded scores must agree with predict everywhere.
    np.testing.assert_array_equal(
        est.classes_[np.argmax(d, axis=1)], est.predict(x[:17]))


def test_svc_class_weight_balanced_matches_sklearn(binary_xy):
    from sklearn.svm import SVC as SkSVC
    x, y = binary_xy
    # Imbalance the data, then ask both to rebalance.
    keep = np.concatenate([np.where(y < 0)[0][:60], np.where(y > 0)[0]])
    xi, yi = x[keep], y[keep]
    ours = SVC(C=5.0, gamma=0.1, class_weight="balanced").fit(xi, yi)
    theirs = SkSVC(C=5.0, gamma=0.1, class_weight="balanced").fit(xi, yi)
    assert ours.score(xi, yi) == pytest.approx(theirs.score(xi, yi), abs=0.02)


def test_svc_clone_and_gridsearch(binary_xy):
    from sklearn.base import clone
    from sklearn.model_selection import GridSearchCV
    x, y = binary_xy
    est = SVC(gamma=0.1)
    est2 = clone(est)
    assert est2.get_params()["gamma"] == 0.1
    gs = GridSearchCV(SVC(gamma=0.1, tol=1e-2), {"C": [0.5, 5.0]}, cv=2)
    gs.fit(x[:300], y[:300])
    assert gs.best_params_["C"] in (0.5, 5.0)


def test_svr_matches_sklearn(binary_xy):
    from sklearn.svm import SVR as SkSVR
    x, _ = binary_xy
    rng = np.random.default_rng(5)
    z = np.sin(x[:, 0]) + 0.1 * x[:, 1] + 0.05 * rng.standard_normal(len(x))
    ours = SVR(C=2.0, gamma=0.2, epsilon=0.1).fit(x, z)
    theirs = SkSVR(C=2.0, gamma=0.2, epsilon=0.1).fit(x, z)
    assert ours.score(x, z) == pytest.approx(theirs.score(x, z), abs=0.05)


def test_oneclass_outlier_fraction(binary_xy):
    x, _ = binary_xy
    est = OneClassSVM(nu=0.2, gamma=0.2).fit(x)
    frac_out = float((est.predict(x) < 0).mean())
    assert frac_out <= 0.2 + 0.05
    assert est.decision_function(x).shape == (len(x),)


def test_gamma_scale_matches_sklearn_definition(binary_xy):
    from sklearn.svm import SVC as SkSVC
    x, y = binary_xy
    ours = SVC(C=1.0, gamma="scale").fit(x, y)
    theirs = SkSVC(C=1.0, gamma="scale").fit(x, y)
    np.testing.assert_allclose(
        ours.decision_function(x[:30]), theirs.decision_function(x[:30]),
        atol=5e-2)


def test_predict_proba_binary_calibrated(binary_xy):
    from sklearn.svm import SVC as SkSVC
    x, y = binary_xy
    est = SVC(C=5.0, gamma=0.1, probability=True).fit(x, y)
    p = est.predict_proba(x)
    assert p.shape == (len(x), 2)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-9)
    # Probabilities must rank like the decision values (monotone sigmoid)
    d = est.decision_function(x)
    order = np.argsort(d)
    assert np.all(np.diff(p[order, 1]) >= -1e-12)
    # And calibration quality should be in sklearn's ballpark (Brier score).
    sk = _sk_svc_proba_oracle(x, y, C=5.0, gamma=0.1, random_state=0)
    t = (y > 0).astype(np.float64)
    brier_ours = float(np.mean((p[:, 1] - t) ** 2))
    brier_sk = float(np.mean((sk.predict_proba(x)[:, 1] - t) ** 2))
    assert brier_ours <= brier_sk + 0.02


def test_predict_proba_multiclass(multi_xy):
    x, y = multi_xy
    est = SVC(C=5.0, gamma=0.1, probability=True).fit(x, y)
    p = est.predict_proba(x)
    assert p.shape == (len(x), 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-9)
    # argmax(proba) should agree with predict for the vast majority
    agree = (est.classes_[np.argmax(p, axis=1)] == est.predict(x)).mean()
    assert agree > 0.95


def test_predict_proba_requires_flag(binary_xy):
    x, y = binary_xy
    est = SVC(C=1.0, gamma=0.1).fit(x, y)
    # Hidden via available_if when probability=False (sklearn.SVC
    # semantics: hasattr is False, the access raises AttributeError).
    assert not hasattr(est, "predict_proba")
    with pytest.raises(AttributeError, match="predict_proba"):
        est.predict_proba(x)
