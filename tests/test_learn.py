"""The continuous-learning loop (dpsvm_tpu/learn.py — ISSUE 18):
stream ingestion, warm generation-over-generation retraining, hot-swap
publishing into a live serving engine, and the obs surface (generation
events, `learn` report column, /metrics counters)."""

import json
import os

import numpy as np
import pytest

from dpsvm_tpu import learn
from dpsvm_tpu.config import ServeConfig, SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams

CFG = SVMConfig(c=1.0, gamma=1.0 / 6, epsilon=1e-3, max_iter=50_000)
KP = KernelParams(CFG.kernel, 1.0 / 6, CFG.degree, CFG.coef0)


def _stream(gens=2, rows=160, d=6, seed=0, drift=0.15):
    return learn.synthetic_stream(seed, d, rows, gens, drift)


# ------------------------------------------------------------ streams

def test_synthetic_stream_shapes_and_drift():
    incs = list(_stream(gens=3, rows=50, d=4))
    assert len(incs) == 3
    for x, y in incs:
        assert x.shape == (50, 4) and y.shape == (50,)
        assert set(np.unique(y)) <= {-1, 1}
    # drift: the increments are NOT identical draws
    assert not np.array_equal(incs[0][1], incs[1][1])


def test_file_stream_chunks_and_validation(tmp_path):
    x = np.arange(30, dtype=np.float32).reshape(10, 3)
    y = np.array([0, 1] * 5)
    p = tmp_path / "stream.npz"
    np.savez(p, x=x, y=y)
    chunks = list(learn.file_stream(str(p), 4))
    assert [c[0].shape[0] for c in chunks] == [4, 4, 2]
    np.testing.assert_array_equal(np.concatenate([c[0] for c in chunks]),
                                  x)
    assert set(np.unique(np.concatenate([c[1] for c in chunks]))) == {-1, 1}

    np.savez(tmp_path / "bad.npz", x=x, y=np.arange(10) % 3)
    with pytest.raises(ValueError, match="binary-only"):
        list(learn.file_stream(str(tmp_path / "bad.npz"), 4))
    np.savez(tmp_path / "short.npz", x=x, y=y[:5])
    with pytest.raises(ValueError, match="rows"):
        list(learn.file_stream(str(tmp_path / "short.npz"), 4))


# ----------------------------------------------------- the warm loop

def test_run_learn_warm_generations_save_pairs(tmp_path):
    """Two drifting generations with a MEASURED cold baseline: the
    warm retrain (seeded from gen 0's SVs) spends fewer pairs than the
    cold solve of the same increment."""
    summary = learn.run_learn(_stream(gens=2, rows=200), CFG,
                              str(tmp_path / "models"), KP,
                              cold_baseline=True)
    assert summary["generations"] == 2
    g0, g1 = summary["gens"]
    assert g0["seed_sv"] == 0 and not g0["estimated"]
    assert g1["seed_sv"] > 0 and not g1["estimated"]
    assert g1["rows"] == g1["seed_sv"] + 200  # concat(prev SVs, fresh)
    assert g1["pairs_saved"] == g1["pairs_cold"] - g1["pairs"]
    assert g1["pairs_saved"] > 0
    assert summary["pairs_saved_total"] == g1["pairs_saved"]
    # one model file per generation, loadable by the registry layer
    for g in (0, 1):
        assert os.path.exists(tmp_path / "models" / f"gen_{g:04d}.npz")


def test_run_learn_estimated_baseline_flagged(tmp_path):
    """Without --cold-baseline the cold pairs are RATE-ESTIMATED from
    generation 0 — and must be flagged, never read as a measurement."""
    summary = learn.run_learn(_stream(gens=2, rows=120), CFG,
                              str(tmp_path / "m"), KP,
                              cold_baseline=False)
    g1 = summary["gens"][1]
    assert g1["estimated"] is True
    g0 = summary["gens"][0]
    rate = g0["pairs"] / g0["rows"]
    assert g1["pairs_cold"] == int(round(rate * g1["rows"]))


# ------------------------------------- publishing: hot swap, no drops

def test_run_learn_publishes_with_zero_downtime(tmp_path):
    """The serving integration: every generation is published through
    register/swap, the post-swap probe answers ok, requests IN FLIGHT
    across the swap are neither dropped nor failed, and the
    per-generation counters land on the engine's /metrics registry."""
    from dpsvm_tpu.serving import ServingEngine

    eng = ServingEngine(ServeConfig(buckets=(16, 64)))
    inflight = {}
    done = {}
    orig_drain = eng.drain

    def drain_accumulating():
        # run_learn's per-generation probe drains too — fold every
        # drained ticket into one ledger so none is "lost" to the test.
        out = orig_drain()
        done.update(out)
        return out

    eng.drain = drain_accumulating

    def hammer(g, model, info):
        # Enqueue WITHOUT draining: these ride across the next swap.
        for i in range(3):
            q = np.asarray(model.sv_x[:4], np.float32)
            inflight[eng.submit(q, model="learn")] = g
        eng.pump()

    try:
        summary = learn.run_learn(_stream(gens=3, rows=120), CFG,
                                  str(tmp_path / "m"), KP,
                                  cold_baseline=True, engine=eng,
                                  model_name="learn",
                                  on_generation=hammer)
        eng.drain()
    finally:
        eng.close()

    assert summary["generations"] == 3
    assert all(g["probe_verdict"] == "ok" for g in summary["gens"])
    assert eng.hot_swaps.value == 2  # gen 0 registers, 1 and 2 swap
    # zero downtime: every in-flight ticket answered, none failed
    for t, g in inflight.items():
        assert t in done, f"ticket from gen {g} dropped across swap"
        assert done[t].verdict == "ok"
    snap = eng.metrics.snapshot()
    assert snap["learn.generations_total"] == 3
    assert snap["learn.pairs_total"] == summary["pairs_total"]
    assert snap["learn.pairs_saved_total"] == summary["pairs_saved_total"]


# --------------------------------------------------- obs: runlog + report

def test_generation_events_and_learn_report_column(tmp_path, monkeypatch):
    """DPSVM_OBS=1: the loop writes one `learn` runlog with a
    `generation` event per model, summarize_run surfaces the learn
    fields, and `cli obs report` renders the learn column."""
    from dpsvm_tpu.obs import analyze

    monkeypatch.setenv("DPSVM_OBS", "1")
    monkeypatch.chdir(tmp_path)
    learn.run_learn(_stream(gens=2, rows=120), CFG,
                    str(tmp_path / "m"), KP, cold_baseline=True)
    runs = analyze.load_runs([str(tmp_path / "obs_runs")])
    (run,) = [r for r in runs if r.manifest["tool"] == "learn"]
    events = [e for e in run.events if e.get("name") == "generation"]
    assert len(events) == 2
    for e in events:
        for k in ("gen", "rows", "seed_sv", "sv", "pairs", "pairs_cold",
                  "pairs_saved", "estimated"):
            assert k in e
    s = analyze.summarize_run(run)
    assert s["generations"] == 2
    assert s["learn_seed_sv_last"] == events[-1]["seed_sv"] > 0
    assert s["learn_pairs_saved"] == events[-1]["pairs_saved"]
    assert s["learn_estimated"] is False
    json.dumps(s)

    txt = analyze.render_report([s])
    assert "learn" in txt
    assert f"gen=2 seed={s['learn_seed_sv_last']}" in txt


# ----------------------------------------------------------- the CLI

def test_cli_learn_smoke(tmp_path, monkeypatch, capsys):
    """`cli learn --smoke` — the make learn_smoke / tier-1 shape: two
    generations, measured cold baseline, in-process engine, asserts
    pairs saved > 0 and post-swap probes serve."""
    monkeypatch.chdir(tmp_path)
    assert learn.run_cli(["--smoke",
                          "--model-dir", str(tmp_path / "m")]) == 0
    out = capsys.readouterr().out
    assert "learn smoke PASS" in out
    assert "saved" in out


def test_cli_forwards_learn(tmp_path, monkeypatch, capsys):
    from dpsvm_tpu import cli

    monkeypatch.chdir(tmp_path)
    rc = cli.main(["learn", "--generations", "2", "--rows", "96",
                   "--d", "4", "--cold-baseline", "--json",
                   "--model-dir", str(tmp_path / "m")])
    assert rc == 0
    out = capsys.readouterr().out
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["generations"] == 2
    assert payload["gens"][1]["seed_sv"] > 0
