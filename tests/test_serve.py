"""Serving engine unit tests (dpsvm_tpu/serve.py PredictServer):
bucket routing, startup warm-up, micro-batch merging, decision_risk
float64 auto-routing, bf16 storage guard, and the mesh-sharded union."""

import warnings

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig, ServeConfig
from dpsvm_tpu.models.multiclass import (MulticlassSVM, decision_matrix,
                                         predict_multiclass,
                                         train_multiclass)
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.serve import PredictServer, offered_load_sweep

CFG = SVMConfig(c=5.0, gamma=0.25, epsilon=1e-3, chunk_iters=256)


@pytest.fixture(scope="module")
def three_class():
    rng = np.random.default_rng(31)
    xs, ys = [], []
    for k in range(3):
        c = np.zeros(5, np.float32)
        c[k] = 2.5
        xs.append(rng.normal(size=(70, 5)).astype(np.float32) * 0.7 + c)
        ys.append(np.full(70, k))
    x, y = np.concatenate(xs), np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.fixture(scope="module", params=["ovr", "ovo"])
def served(request, three_class):
    x, y = three_class
    m, _ = train_multiclass(x, y, CFG, strategy=request.param)
    return m, x


def _binary_model(n_sv=40, d=6, coef_scale=1.0, seed=0):
    rng = np.random.default_rng(seed)
    return SVMModel(
        sv_x=rng.normal(size=(n_sv, d)).astype(np.float32),
        sv_alpha=(rng.random(n_sv).astype(np.float32) + 0.01)
        * coef_scale,
        sv_y=np.where(rng.random(n_sv) < 0.5, 1, -1).astype(np.int32),
        b=0.05, kernel=KernelParams("rbf", 0.3))


# -------------------------------------------------------------- routing

def test_decision_matches_model_layer(served):
    m, x = served
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    q = np.asarray(x[:50], np.float32)
    np.testing.assert_allclose(srv.decision(q), decision_matrix(m, q),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(srv.predict(q),
                                  predict_multiclass(m, q))


def test_bucket_routing(served):
    m, x = served
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    srv.decision(np.asarray(x[:5], np.float32))
    assert srv.stats["bucket_counts"] == {16: 1, 64: 0}
    assert srv.stats["padded_rows"] == 11
    srv.decision(np.asarray(x[:40], np.float32))
    assert srv.stats["bucket_counts"] == {16: 1, 64: 1}
    # Beyond the largest bucket: loop over it (64 + 64, second padded).
    srv.decision(np.asarray(x[:100], np.float32))
    assert srv.stats["bucket_counts"] == {16: 1, 64: 3}
    assert srv.stats["rows"] == 145


def test_warm_start_precompiles_every_bucket(served):
    from dpsvm_tpu.serve import _dense_batch_factory
    m, x = served
    srv = PredictServer(m, ServeConfig(buckets=(16, 64, 128)))
    assert sorted(srv.stats["warm_seconds"]) == [16, 64, 128]
    # The warm-up's whole point: live requests never trace/compile a
    # new executor — every bucket shape is already in the jit cache.
    fn = _dense_batch_factory()
    before = fn._cache_size()
    srv.decision(np.asarray(x[:10], np.float32))
    srv.decision(np.asarray(x[:60], np.float32))
    srv.decision(np.asarray(x[:100], np.float32))
    assert fn._cache_size() == before
    assert srv.stats["dispatches"] == 3


def test_rejects_wrong_width(served):
    m, _ = served
    srv = PredictServer(m, ServeConfig(buckets=(16,), warm_start=False))
    with pytest.raises(ValueError):
        srv.decision(np.zeros((4, 3), np.float32))


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(buckets=(24,))  # not a power of two
    with pytest.raises(ValueError):
        ServeConfig(buckets=(64, 16))  # not ascending
    with pytest.raises(ValueError):
        ServeConfig(dtype="float16")
    with pytest.raises(ValueError):
        ServeConfig(max_pending=8, buckets=(16,))


# ---------------------------------------------------------- micro-batch

def test_micro_batch_merges_requests(served):
    m, x = served
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    q = np.asarray(x[:14], np.float32)
    full = srv.decision(q)
    d0 = dict(srv.stats)
    t1 = srv.enqueue(q[:3])
    t2 = srv.enqueue(q[3:8])
    t3 = srv.enqueue(q[8:14])
    out = srv.flush()
    # Three requests, ONE merged bucket dispatch.
    assert srv.stats["dispatches"] == d0["dispatches"] + 1
    assert srv.stats["requests"] == 3
    np.testing.assert_array_equal(out[t1], full[:3])
    np.testing.assert_array_equal(out[t2], full[3:8])
    np.testing.assert_array_equal(out[t3], full[8:14])
    assert srv.flush() == {}  # queue drained


def test_max_pending_forces_flush(served):
    m, x = served
    srv = PredictServer(m, ServeConfig(buckets=(16,), max_pending=16))
    q = np.asarray(x[:12], np.float32)
    srv.enqueue(q)
    d = srv.stats["dispatches"]
    srv.enqueue(q)  # crosses 16 pending rows -> forced early flush
    assert srv.stats["dispatches"] > d
    out = srv.flush()
    assert sorted(out) == [0, 1]
    np.testing.assert_array_equal(out[0], out[1])


# --------------------------------------------------------- f64 routing

def test_f64_auto_routing_extreme_coef():
    """A model whose decision_risk crosses the threshold must be served
    from the exact host float64 path — its decisions match
    predict.decision_function(precision='float64') and NOT the noisy
    fp32 evaluation."""
    from dpsvm_tpu.predict import decision_function, decision_risk

    big = _binary_model(n_sv=600, d=8, coef_scale=6e5, seed=2)
    assert decision_risk(big) >= 0.1
    srv = PredictServer(big, ServeConfig(buckets=(32,)))
    assert srv.stats["f64_columns"] == 1
    rng = np.random.default_rng(1)
    q = rng.normal(size=(20, 8)).astype(np.float32)
    np.testing.assert_allclose(
        srv.decision(q)[:, 0],
        decision_function(big, q, precision="float64").astype(
            np.float32), rtol=1e-6)
    # Forcing float32 serves the device path instead.
    srv32 = PredictServer(big, ServeConfig(buckets=(32,),
                                           precision="float32"))
    assert srv32.stats["f64_columns"] == 0


def test_moderate_model_stays_on_device(served):
    m, _ = served
    srv = PredictServer(m, ServeConfig(buckets=(16,)))
    assert srv.stats["f64_columns"] == 0


def test_binary_model_labels(three_class):
    from dpsvm_tpu.predict import predict as predict_binary
    from dpsvm_tpu.solver.smo import solve
    x, y = three_class
    y_pm = np.where(y == 1, 1, -1).astype(np.int32)
    res = solve(x, y_pm, CFG)
    model = SVMModel.from_dense(x, y_pm, res.alpha, res.b,
                                KernelParams("rbf", 0.25))
    srv = PredictServer(model, ServeConfig(buckets=(64, 256)))
    np.testing.assert_array_equal(srv.predict(x),
                                  predict_binary(model, x))


# ------------------------------------------------------------- bf16

def test_bf16_storage_close_and_guarded(served):
    m, x = served
    q = np.asarray(x[:30], np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # moderate coefs: no warning
        srv = PredictServer(m, ServeConfig(buckets=(32,),
                                           dtype="bfloat16"))
    np.testing.assert_allclose(srv.decision(q), decision_matrix(m, q),
                               rtol=0.05, atol=0.05)


def test_bf16_guard_warns_on_risky_coefficients():
    big = _binary_model(n_sv=500, d=8, coef_scale=100.0, seed=4)
    with pytest.warns(UserWarning, match="bfloat16"):
        PredictServer(big, ServeConfig(buckets=(16,),
                                       precision="float32",
                                       dtype="bfloat16",
                                       warm_start=False))


# --------------------------------------------------------------- mesh

@pytest.mark.parametrize("n_dev", [2, 8])
def test_mesh_sharded_union_matches_single(served, n_dev):
    m, x = served
    q = np.asarray(x[:40], np.float32)
    srv = PredictServer(m, ServeConfig(buckets=(64,),
                                       num_devices=n_dev))
    np.testing.assert_allclose(srv.decision(q), decision_matrix(m, q),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(srv.predict(q),
                                  predict_multiclass(m, q))


# -------------------------------------------------------------- sweep

def test_offered_load_sweep_shape(served):
    m, _ = served
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    rec = offered_load_sweep(srv, [1, 4, 16], 24, group=6)
    assert rec["requests"] == 24
    assert rec["rows_per_second"] > 0
    for key in ("p50", "p95", "p99"):
        assert rec["request_latency"][key] >= 0
    assert rec["bucket_latency"]  # at least one bucket saw dispatches


def test_all_empty_ensemble_served():
    kp = KernelParams("rbf", 0.25)
    models = [SVMModel(sv_x=np.zeros((0, 4), np.float32),
                       sv_alpha=np.zeros((0,), np.float32),
                       sv_y=np.zeros((0,), np.int32), b=b0, kernel=kp)
              for b0 in (0.5, -0.25)]
    m = MulticlassSVM(classes=np.arange(2), models=models,
                      strategy="ovr")
    srv = PredictServer(m, ServeConfig(buckets=(16,)))
    dec = srv.decision(np.zeros((3, 4), np.float32))
    np.testing.assert_array_equal(
        dec, np.broadcast_to([-0.5, 0.25], (3, 2)).astype(np.float32))


def test_bucket_cap_trims_oversized_buckets(served, monkeypatch):
    """The per-dispatch kernel tile is budget-bounded: buckets whose
    (bucket, S) tile would cross the budget are trimmed at construction
    (a covtype-scale union must not OOM during warm-up)."""
    import dpsvm_tpu.serve as serve_mod
    m, _ = served
    s_rows = int(m.compacted.sv_union.shape[0])
    # Shrink the budget so only buckets <= 32 survive for THIS union.
    monkeypatch.setattr(serve_mod, "_TILE_BUDGET_ELEMS", s_rows * 32)
    srv = PredictServer(m, ServeConfig(buckets=(16, 64, 4096)))
    assert srv.buckets == (16,)
    assert sorted(srv.stats["warm_seconds"]) == [16]
    # Still serves batches beyond the trimmed top bucket (loops it).
    dec = srv.decision(np.zeros((40, srv.d), np.float32))
    assert dec.shape == (40, srv.k)
    assert srv.stats["bucket_counts"][16] == 3


def test_f64_routed_columns_see_unquantized_queries():
    """The exact-path contract (predict.py: no fp32 quantization of the
    queries) holds through the server: float64 queries reach the
    risk-routed columns unrounded."""
    from dpsvm_tpu.predict import decision_function

    big = _binary_model(n_sv=600, d=8, coef_scale=6e5, seed=2)
    srv = PredictServer(big, ServeConfig(buckets=(32,),
                                         warm_start=False))
    rng = np.random.default_rng(3)
    # Queries with structure below f32 resolution: exact evaluation at
    # the raw f64 values differs from the f32-rounded ones.
    q64 = (rng.normal(size=(16, 8)) * (1 + 1e-9)).astype(np.float64)
    want = decision_function(big, q64, precision="float64")
    np.testing.assert_allclose(srv.decision(q64)[:, 0], want,
                               rtol=1e-6)
    t = srv.enqueue(q64)  # the queue keeps the caller's dtype too
    np.testing.assert_allclose(srv.flush()[t][:, 0], want, rtol=1e-6)
