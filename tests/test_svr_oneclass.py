"""SVR and one-class SVM: parity vs sklearn (LibSVM) and round trips.

These model families have no reference equivalent (the reference trains
binary C-SVC only); the oracle is LibSVM via sklearn, the same oracle the
reference cites for its SV-count parity claim (README.md:27).
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.oneclass import OneClassModel, train_oneclass
from dpsvm_tpu.models.svr import SVRModel, train_svr


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(400, 6)).astype(np.float32)
    z = (np.sin(x[:, 0]) + 0.5 * x[:, 1] ** 2
         + 0.1 * rng.normal(size=400)).astype(np.float32)
    return x, z


@pytest.fixture(scope="module")
def novelty_data():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(500, 8)).astype(np.float32)
    x[:25] += 6.0  # outlier cluster
    return x


CFG = SVMConfig(c=10.0, gamma=0.5, epsilon=1e-3, chunk_iters=512)


def test_svr_matches_libsvm(reg_data):
    from sklearn.svm import SVR

    x, z = reg_data
    m, res = train_svr(x, z, CFG, svr_epsilon=0.1, backend="single")
    assert res.converged
    sk = SVR(C=10.0, gamma=0.5, epsilon=0.1, tol=1e-3).fit(x, z)
    assert abs(m.n_sv - len(sk.support_)) <= max(3, 0.03 * len(sk.support_))
    pred = m.predict(x)
    np.testing.assert_allclose(pred, sk.predict(x), atol=5e-3)
    assert abs(m.b - (-sk.intercept_[0])) < 5e-3


def test_svr_mesh_matches_single(reg_data):
    """Mesh and single-chip SVR converge to the same solution. (Unlike
    C-SVC — test_dist_smo asserts iteration-exact trajectories there — the
    2n duplicated-point expansion is sensitive to 1-ulp FMA/fusion
    differences between the full and per-shard f-update lowerings, so the
    assertion here is solution-level, not trajectory-level.)"""
    x, z = reg_data
    m1, r1 = train_svr(x, z, CFG, svr_epsilon=0.1, backend="single")
    m4, r4 = train_svr(x, z, CFG, svr_epsilon=0.1, backend="mesh")
    assert abs(r4.iterations - r1.iterations) <= 0.05 * r1.iterations
    np.testing.assert_allclose(m4.predict(x), m1.predict(x), atol=5e-3)
    assert abs(m4.b - m1.b) < 5e-3
    assert abs(m4.n_sv - m1.n_sv) <= max(3, 0.03 * m1.n_sv)


def test_svr_tube_property(reg_data):
    """At convergence, free SVs (0 < |coef| < C) sit ON the eps-tube and
    non-SVs strictly inside it (KKT conditions of the SVR dual)."""
    x, z = reg_data
    eps_tube = 0.2
    m, res = train_svr(x, z, CFG, svr_epsilon=eps_tube, backend="single")
    resid_sv = np.abs(m.predict(m.sv_x) - _targets_for(m.sv_x, x, z))
    free = (np.abs(m.coef) > 1e-4) & (np.abs(m.coef) < CFG.c - 1e-4)
    tol = 2 * CFG.epsilon + 5e-3
    # Free SVs: |residual| == eps_tube (they sit on the tube boundary).
    assert free.any()
    np.testing.assert_allclose(resid_sv[free], eps_tube, atol=tol)
    # Non-SVs: strictly inside the tube.
    resid = np.abs(m.predict(x) - z)
    sv_rows = {tuple(r) for r in np.round(m.sv_x, 5).tolist()}
    non_sv = np.array([tuple(r) not in sv_rows
                       for r in np.round(x, 5).tolist()])
    assert np.all(resid[non_sv] <= eps_tube + tol)


def _targets_for(rows, x, z):
    """Look up the training target of each (unique) row in `rows`."""
    index = {tuple(r): t for r, t in zip(np.round(x, 5).tolist(), z)}
    return np.asarray([index[tuple(r)] for r in np.round(rows, 5).tolist()],
                      np.float32)


def test_svr_save_load_roundtrip(reg_data, tmp_path):
    x, z = reg_data
    m, _ = train_svr(x, z, CFG, svr_epsilon=0.1, backend="single")
    p = str(tmp_path / "svr.npz")
    m.save(p)
    m2 = SVRModel.load(p)
    np.testing.assert_allclose(m2.predict(x[:50]), m.predict(x[:50]), atol=1e-6)
    with pytest.raises(ValueError):
        m.save(str(tmp_path / "svr.txt"))


def test_svr_input_validation(reg_data):
    x, z = reg_data
    with pytest.raises(ValueError):
        train_svr(x, z[:10], CFG)
    with pytest.raises(ValueError):
        train_svr(x, z, CFG, svr_epsilon=-1.0)
    with pytest.raises(ValueError):
        train_svr(x, z, CFG, backend="bogus")


def test_oneclass_matches_libsvm(novelty_data):
    from sklearn.svm import OneClassSVM

    x = novelty_data
    cfg = SVMConfig(gamma=0.1, epsilon=1e-3, chunk_iters=512)
    m, res = train_oneclass(x, nu=0.1, config=cfg, backend="single")
    assert res.converged
    sk = OneClassSVM(nu=0.1, gamma=0.1, tol=1e-3).fit(x)
    assert abs(m.n_sv - len(sk.support_)) <= max(3, 0.03 * len(sk.support_))
    df = m.decision_function(x)
    np.testing.assert_allclose(df, sk.decision_function(x), atol=5e-3)
    # Predictions agree away from the boundary (within-tolerance flips are
    # expected exactly at |decision| ~ tol).
    clear = np.abs(sk.decision_function(x)) > 1e-2
    assert np.all(m.predict(x)[clear] == sk.predict(x)[clear])


def test_oneclass_nu_property(novelty_data):
    """nu upper-bounds the training outlier fraction and lower-bounds the
    SV fraction (Scholkopf's nu-property), up to boundary slack."""
    x = novelty_data
    n = x.shape[0]
    cfg = SVMConfig(gamma=0.1, epsilon=1e-3, chunk_iters=512)
    for nu in (0.05, 0.2):
        m, res = train_oneclass(x, nu=nu, config=cfg, backend="single")
        frac_out = float((m.decision_function(x) < -1e-3).mean())
        assert frac_out <= nu + 5.0 / n
        assert m.n_sv >= nu * n - 5


def test_oneclass_mesh_matches_single(novelty_data):
    # Solution-level parity (trajectories can shift by one near selection
    # ties when XLA's per-shard lowering differs by a final ulp — same
    # slack as the C-SVC mesh tests in test_dist_smo).
    x = novelty_data
    cfg = SVMConfig(gamma=0.1, epsilon=1e-3, chunk_iters=512)
    m1, r1 = train_oneclass(x, nu=0.1, config=cfg, backend="single")
    m4, r4 = train_oneclass(x, nu=0.1, config=cfg, backend="mesh")
    assert abs(r4.iterations - r1.iterations) <= 0.02 * r1.iterations + 1
    np.testing.assert_allclose(r4.alpha, r1.alpha, rtol=0, atol=1e-3)
    assert m4.rho == pytest.approx(m1.rho, abs=1e-3)


def test_oneclass_save_load_roundtrip(novelty_data, tmp_path):
    x = novelty_data
    cfg = SVMConfig(gamma=0.1, epsilon=1e-3, chunk_iters=512)
    m, _ = train_oneclass(x, nu=0.1, config=cfg, backend="single")
    p = str(tmp_path / "oc.npz")
    m.save(p)
    m2 = OneClassModel.load(p)
    np.testing.assert_allclose(m2.decision_function(x[:50]),
                               m.decision_function(x[:50]), atol=1e-6)


def test_oneclass_input_validation(novelty_data):
    with pytest.raises(ValueError):
        train_oneclass(novelty_data, nu=0.0)
    with pytest.raises(ValueError):
        train_oneclass(novelty_data, nu=1.5)


def test_equality_constraint_conserved(novelty_data):
    """The dual equality constraint sum_i alpha_i y_i = const must hold
    exactly(ish) at convergence. The reference's sequential double clip
    violates it when the second clip triggers (see pair_alpha_update);
    one-class — whose alphas START at the bound — is the regression test."""
    x = novelty_data
    n = x.shape[0]
    cfg = SVMConfig(gamma=0.1, epsilon=1e-3, chunk_iters=512)
    for nu in (0.1, 0.15):
        m, res = train_oneclass(x, nu=nu, config=cfg, backend="single")
        assert abs(float(res.alpha.sum()) - nu * n) < 1e-2


def test_csvc_equality_constraint_conserved():
    """Same invariant for C-SVC: sum alpha_i y_i stays 0."""
    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.solver.smo import solve

    x, y = make_blobs_binary(n=600, d=10, seed=5, sep=1.0)  # overlapping
    res = solve(x, y, SVMConfig(c=5.0, gamma=0.3, chunk_iters=512))
    assert abs(float((res.alpha * y).sum())) < 1e-2
