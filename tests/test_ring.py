"""Ring-overlapped mesh candidate exchange + bf16 Gram training path
(ISSUE 11; ops/ring.py, config.ring_exchange / config.bf16_gram).

The acceptance battery: interpret-mode ring exchange produces a
BIT-IDENTICAL training trajectory to the all_gather path on the tier-1
2-device CPU mesh (every runner it wires into: global, pipelined,
shard-local — plus second_order and the compensated carry), the
device-form tpulint contract is mutation-verified (a stray per-hop XLA
collective or an extra bf16 convert must DRIFT the committed budget),
the bf16-Gram gate accepts/refuses per problem with the refusal loud in
stats AND as a warning, and the config/CLI surface validates the
documented compositions. Heavy 8-device legs are `slow` (the
test_shardlocal.py discipline).
"""

import copy
import warnings

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.solver.smo import solve

BASE = SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3, max_iter=200_000,
                 engine="block", working_set_size=16, chunk_iters=64)


def _pair(x, y, cfg, num_devices=2):
    """(ring off, ring on) mesh solves with full per-chunk observation
    streams, for bitwise trajectory comparison."""
    obs_off, obs_on = [], []

    def cb(sink):
        return lambda it, bh, bl, st: sink.append((it, bh, bl)) and None

    r0 = solve_mesh(x, y, cfg.replace(ring_exchange=False),
                    num_devices=num_devices, callback=cb(obs_off))
    r1 = solve_mesh(x, y, cfg.replace(ring_exchange=True),
                    num_devices=num_devices, callback=cb(obs_on))
    return r0, r1, obs_off, obs_on


def _assert_bitwise(r0, r1, obs_off, obs_on):
    assert obs_off == obs_on
    assert r1.iterations == r0.iterations
    np.testing.assert_array_equal(r1.alpha, r0.alpha)
    np.testing.assert_array_equal(r1.stats["f"], r0.stats["f"])
    assert (r1.b_hi, r1.b_lo) == (r0.b_hi, r0.b_lo)
    assert r1.stats.get("ring_exchange") is True
    assert "ring_exchange" not in r0.stats


# ---- bit-identical trajectories, tier-1 2-device mesh ---------------


def test_ring_global_runner_bitwise(blobs_small):
    """The plain (global working set) runner: ring-carried candidates +
    rows must reproduce the all_gather + psum trajectory bit for bit —
    observation stream, alpha, f, extrema, pair counts."""
    x, y = blobs_small
    _assert_bitwise(*_pair(x, y, BASE))


def test_ring_second_order_compensated_bitwise(blobs_small):
    """The ring exchange is selection-rule- and carry-agnostic: WSS2
    partner picking reads the same Gram block, and the Kahan residual
    rides the fold untouched (the ring only moves SELECTION data)."""
    x, y = blobs_small
    cfg = BASE.replace(selection="second_order", compensated=True)
    _assert_bitwise(*_pair(x, y, cfg))


def test_ring_pipelined_runner_bitwise(blobs_small):
    """Pipelined rounds: the prefetch's gather + row psum become the
    ring pass; the (q, 2) handoff psum stays. Same trajectory pin."""
    x, y = blobs_small
    _assert_bitwise(*_pair(x, y, BASE.replace(pipeline_rounds=True)))


def test_ring_shardlocal_runner_bitwise(blobs_small):
    """Shard-local sync: the in-kernel per-hop fold (ops/ring.py
    ring_fold_window) must match the all_gather + rotation-fori fold
    bitwise — same fold order, same kahan step, output-dim-only
    tiling — including the pair-count lane reduction and the endgame
    demotion trajectory (the demoted global runner rides the ring
    too)."""
    x, y = blobs_small
    cfg = BASE.replace(local_working_sets=2, sync_rounds=2)
    r0, r1, a, b = _pair(x, y, cfg)
    _assert_bitwise(r0, r1, a, b)
    assert r0.stats["shardlocal_demoted"] == r1.stats["shardlocal_demoted"]


# ---- 8-device legs (slow: several mesh solves) ----------------------


@pytest.mark.slow
def test_ring_8dev_bitwise_all_runners(blobs_medium):
    """The full-width mesh: 7-hop rings across every wired runner stay
    bit-identical (hop count, slot rotation and fold order all change
    with P — the 2-device pin alone would not exercise mid-ring
    forwarding)."""
    x, y = blobs_medium
    cfg = BASE.replace(working_set_size=32, inner_iters=64)
    _assert_bitwise(*_pair(x, y, cfg, num_devices=8))
    _assert_bitwise(*_pair(x, y, cfg.replace(pipeline_rounds=True),
                           num_devices=8))
    _assert_bitwise(*_pair(
        x, y, cfg.replace(local_working_sets=2, sync_rounds=2,
                          compensated=True), num_devices=8))


# ---- tpulint device-form contract, mutation-verified ----------------


def test_device_form_facts_catch_stray_hop_collective():
    """The extractor side of the acceptance criterion: the device-form
    walk counts XLA collective primitives through shard_map, loops AND
    pallas kernel jaxprs — a psum smuggled next to the ring is seen;
    the clean ring body reads zero collectives and nonzero DMA hops."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from dpsvm_tpu.analysis.hlo_facts import device_form_facts
    from dpsvm_tpu.ops.ring import ring_gather
    from dpsvm_tpu.parallel.mesh import DATA_AXIS

    mesh = Mesh(np.array(jax.devices()[:8]), (DATA_AXIS,))

    def clean(blk):
        return ring_gather(blk, 8, interpret=False)

    def mutated(blk):
        out = ring_gather(blk, 8, interpret=False)
        return out + lax.psum(blk, DATA_AXIS)[None]  # the stray hop sum

    spec = P(DATA_AXIS)
    arg = jnp.zeros((16, 8), jnp.float32)

    def facts(fn):
        mapped = shard_map(fn, mesh=mesh, in_specs=spec,
                           out_specs=P(None, DATA_AXIS), check_rep=False)
        return device_form_facts(jax.make_jaxpr(mapped)(arg))

    f_clean, f_mut = facts(clean), facts(mutated)
    assert f_clean["xla_collective_total"] == 0
    assert f_clean["dma_starts"] > 0
    assert f_mut["xla_collectives"]["psum"] == 1
    assert f_mut["xla_collective_total"] == 1


def test_ring_budgets_drift_on_mutation():
    """The budget side: re-extracted ring facts PASS against the
    committed budgets, and the two mutations the acceptance criterion
    names — a stray per-hop XLA collective in the device form, an
    extra f32<->bf16 convert in the bf16-Gram body — each flip the
    verdict to DRIFT naming the fact path."""
    import jax

    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.extract import entry_facts
    from dpsvm_tpu.analysis.manifest import (block_chunk_bf16gram,
                                             mesh_chunk_ring,
                                             require_devices)

    require_devices()
    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        pytest.skip(f"budgets generated under jax {gen}, running "
                    f"{jax.__version__} (the pinned CI job is the gate)")

    ring = entry_facts(mesh_chunk_ring())
    assert budget.check_entry("mesh_chunk_ring", ring)["verdict"] \
        == budget.PASS
    mut = copy.deepcopy(ring)
    df = mut["units"]["chunk"]["device_form"]
    df["xla_collectives"]["psum"] += 1
    df["xla_collective_total"] += 1
    res = budget.check_entry("mesh_chunk_ring", mut)
    assert res["verdict"] == budget.DRIFT
    assert any("device_form" in d[0] for d in res["diffs"])

    bfg = entry_facts(block_chunk_bf16gram())
    assert budget.check_entry("block_chunk_bf16gram", bfg)["verdict"] \
        == budget.PASS
    mut2 = copy.deepcopy(bfg)
    mut2["units"]["chunk"]["dtypes"]["f32_to_bf16_converts"] += 1
    res2 = budget.check_entry("block_chunk_bf16gram", mut2)
    assert res2["verdict"] == budget.DRIFT
    assert any("f32_to_bf16" in d[0] for d in res2["diffs"])


# ---- bf16 Gram gate -------------------------------------------------


def test_bf16_gram_accepts_and_matches_bf16_dtype(blobs_small):
    """An accepting gate (C=5 on benign blobs: risk ~ 5e-3) must train
    EXACTLY as dtype='bfloat16' would — same storage rounding, same
    trajectory — with the decision recorded in stats and no warning."""
    x, y = blobs_small
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        rg = solve(x, y, BASE.replace(bf16_gram=True))
    st = rg.stats["bf16_gram"]
    assert st["active"] is True and "note" not in st
    assert 0.0 < st["risk"] <= st["threshold"]
    rb = solve(x, y, BASE.replace(dtype="bfloat16"))
    np.testing.assert_array_equal(rg.alpha, rb.alpha)
    assert rg.iterations == rb.iterations


def test_bf16_gram_refuses_loudly_and_stays_f32(blobs_small):
    """A refusing gate (extreme C amplifies storage rounding past the
    threshold) must leave the solve bit-identical to plain float32,
    carry the loud note in stats AND raise a warning — never a silent
    fallback."""
    x, y = blobs_small
    hot = BASE.replace(c=4096.0, max_iter=4000)
    with pytest.warns(UserWarning, match="bf16_gram REFUSED"):
        rg = solve(x, y, hot.replace(bf16_gram=True))
    st = rg.stats["bf16_gram"]
    assert st["active"] is False
    assert "REFUSED" in st["note"] and "float32" in st["note"]
    assert st["risk"] > st["threshold"]
    rf = solve(x, y, hot)
    np.testing.assert_array_equal(rg.alpha, rf.alpha)
    assert rg.iterations == rf.iterations


def test_bf16_gram_mesh_and_ring_compose(blobs_small):
    """The mesh path runs the same gate (sharding the bf16-stored X),
    and the ring exchange carries bf16-originated rows widened to f32
    exactly like the psum path — the two tentpole halves compose."""
    x, y = blobs_small
    cfg = BASE.replace(bf16_gram=True, ring_exchange=True)
    rm = solve_mesh(x, y, cfg, num_devices=2)
    assert rm.stats["bf16_gram"]["active"] is True
    assert rm.stats["ring_exchange"] is True
    rs = solve_mesh(x, y, BASE.replace(dtype="bfloat16"), num_devices=2)
    np.testing.assert_array_equal(rm.alpha, rs.alpha)


def test_bf16_gram_fleet_gate_covers_per_problem_c(blobs_small):
    """One fleet, one storage dtype: the gate judges the LARGEST box
    bound any problem runs under, so a single extreme-C problem refuses
    bf16 for the whole fleet (per-problem C overrides included)."""
    from dpsvm_tpu.solver.fleet import FleetProblem, solve_fleet

    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=4000,
                    bf16_gram=True)
    probs = [FleetProblem(y=y), FleetProblem(y=-y)]
    res = solve_fleet(x, probs, cfg)
    assert all(r.stats["bf16_gram"]["active"] for r in res)
    with pytest.warns(UserWarning, match="REFUSED for the fleet"):
        res_hot = solve_fleet(
            x, [FleetProblem(y=y), FleetProblem(y=-y, c=4096.0)], cfg)
    assert all(not r.stats["bf16_gram"]["active"] for r in res_hot)


def test_bf16_gram_resident_memo_keys_on_effective_dtype(blobs_small):
    """The resident-Gram memo must key on the EFFECTIVE storage dtype:
    a bf16_gram solve whose gate accepted builds its Gram from
    bf16-rounded features while config.dtype still reads 'float32' —
    it must neither reuse a plain f32 solve's cached Gram (claiming
    bf16 while training exact) nor poison the f32 entry for later
    solves on the same host array."""
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                    gram_resident=True)
    r_ref = solve(x, y, cfg)                     # seeds the f32 memo
    r_bf = solve(x, y, cfg.replace(bf16_gram=True))
    assert r_bf.stats["bf16_gram"]["active"] is True
    r_bfd = solve(x, y, cfg.replace(dtype="bfloat16"))
    # True bf16 behavior, not a silent hit on the f32 entry...
    np.testing.assert_array_equal(r_bf.alpha, r_bfd.alpha)
    # ...and the f32 entry is uncorrupted afterwards.
    r_f32 = solve(x, y, cfg)
    np.testing.assert_array_equal(r_f32.alpha, r_ref.alpha)


# ---- config / CLI surface -------------------------------------------


def test_ring_exchange_validation():
    with pytest.raises(ValueError, match="block-engine"):
        SVMConfig(engine="xla", ring_exchange=True)
    with pytest.raises(ValueError, match="feature kernels"):
        SVMConfig(engine="block", ring_exchange=True,
                  kernel="precomputed")
    with pytest.raises(ValueError, match="ooc"):
        SVMConfig(engine="block", ring_exchange=True, ooc=True)
    with pytest.raises(ValueError, match="active_set_size"):
        SVMConfig(engine="block", ring_exchange=True, active_set_size=64)
    with pytest.raises(ValueError, match="fused_fold"):
        SVMConfig(engine="block", ring_exchange=True, fused_fold=True)
    # The documented compositions construct fine.
    SVMConfig(engine="block", ring_exchange=True, pipeline_rounds=True)
    SVMConfig(engine="block", ring_exchange=True, local_working_sets=2,
              sync_rounds=4, compensated=True)


def test_bf16_gram_validation():
    with pytest.raises(ValueError, match="feature kernels"):
        SVMConfig(bf16_gram=True, kernel="precomputed")
    with pytest.raises(ValueError, match="bfloat16"):
        SVMConfig(bf16_gram=True, dtype="bfloat16")
    with pytest.raises(ValueError, match="ooc"):
        SVMConfig(bf16_gram=True, engine="block", ooc=True)
    SVMConfig(bf16_gram=True)  # plain request is valid on any engine


def test_nu_fallback_names_ring_exchange(blobs_small):
    """The nu trainers keep the all_gather path (per-class quarters);
    a configured ring_exchange must be NAMED in the fallback warning,
    not silently dropped (the PR 8 loud-fallback discipline)."""
    from dpsvm_tpu.models.nusvm import train_nusvc

    x, y = blobs_small
    cfg = SVMConfig(engine="block", ring_exchange=True, epsilon=1e-2,
                    max_iter=2000)
    with pytest.warns(UserWarning, match="ring_exchange"):
        train_nusvc(x, y, 0.3, cfg, backend="single")


def test_cli_ring_and_bf16_flags(tmp_path):
    """--ring-exchange / --bf16-gram reach SVMConfig and train a model
    end to end (mesh backend for the ring; single-chip for the gate)."""
    from dpsvm_tpu.cli import main
    from dpsvm_tpu.data.loader import save_csv
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=240, d=8, seed=5, sep=2.0)
    train_p = str(tmp_path / "train.csv")
    save_csv(train_p, x, y)
    rc = main(["train", "-f", train_p, "-m", str(tmp_path / "m1.npz"),
               "-c", "5", "-g", "0.1", "--engine", "block",
               "--backend", "mesh", "--num-devices", "2",
               "--ring-exchange", "on", "-q"])
    assert rc == 0
    rc = main(["train", "-f", train_p, "-m", str(tmp_path / "m2.npz"),
               "-c", "5", "-g", "0.1", "--engine", "block",
               "--backend", "single", "--bf16-gram", "-q"])
    assert rc == 0
