"""Class-weighted C (LibSVM -w1/-w-1) across every engine.

The weighted branches (`c_of`, weighted up/low masks, per-variable box
bounds in the pair clip) statically collapse to the unweighted program at
equal weights, so the default-weight parity tests exercise none of them.
This file pins every engine's weighted path against the NumPy oracle and
LibSVM, plus the weight-neutralization contracts of the SVR/one-class
frontends (their synthetic +-1 labels are not classes)."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.reference import smo_reference
from dpsvm_tpu.solver.smo import solve

WCFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                 weight_pos=2.0, weight_neg=0.5, chunk_iters=256)


def _assert_matches_oracle(res, ref, y, cfg):
    assert res.converged and ref.converged
    assert res.b == pytest.approx(ref.b, abs=5e-3)
    np.testing.assert_allclose(res.alpha, ref.alpha, atol=5e-2)
    cp, cn = cfg.c_bounds()
    bound = np.where(np.asarray(y) > 0, cp, cn)
    assert np.all(np.asarray(res.alpha) <= bound + 1e-5)


@pytest.mark.parametrize("cfg", [
    WCFG,
    WCFG.replace(selection="second_order"),
    WCFG.replace(cache_lines=32),
], ids=["mvp", "wss2", "cached"])
def test_single_chip_weighted_matches_oracle(blobs_small, cfg):
    x, y = blobs_small
    ref = smo_reference(x, y, WCFG)
    res = solve(x, y, cfg)
    if cfg.selection == "second_order":
        # WSS2 picks different pairs; compare optima, not trajectories.
        assert res.converged and ref.converged
        assert res.b == pytest.approx(ref.b, abs=2e-2)
        cp, cn = cfg.c_bounds()
        bound = np.where(np.asarray(y) > 0, cp, cn)
        assert np.all(np.asarray(res.alpha) <= bound + 1e-5)
    else:
        _assert_matches_oracle(res, ref, y, cfg)


def test_pallas_weighted_matches_oracle(blobs_small):
    x, y = blobs_small
    ref = smo_reference(x, y, WCFG)
    res = solve(x, y, WCFG.replace(engine="pallas"))
    _assert_matches_oracle(res, ref, y, WCFG)


def test_mesh_weighted_matches_oracle(blobs_small):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh
    x, y = blobs_small
    ref = smo_reference(x, y, WCFG)
    res = solve_mesh(x, y, WCFG, num_devices=4)
    _assert_matches_oracle(res, ref, y, WCFG)


def test_weighted_matches_libsvm_class_weight(blobs_small):
    from sklearn.svm import SVC
    x, y = blobs_small
    res = solve(x, y, WCFG)
    sk = SVC(C=1.0, kernel="rbf", gamma=0.1, tol=1e-3,
             class_weight={1: 2.0, -1: 0.5}).fit(x, y)
    assert abs(res.n_sv - len(sk.support_)) <= max(3, int(0.05 * len(sk.support_)))


def test_svr_ignores_class_weights(blobs_small):
    # SVR's 2n expansion labels are bookkeeping; weights must not skew
    # the alpha vs alpha* boxes.
    from dpsvm_tpu.models.svr import train_svr
    x, _ = blobs_small
    rng = np.random.default_rng(0)
    z = np.sin(x[:, 0]) + 0.05 * rng.standard_normal(x.shape[0])
    cfg = SVMConfig(c=1.0, gamma=0.5, epsilon=1e-3, max_iter=100_000)
    m_plain, r_plain = train_svr(x, z, cfg, backend="single")
    m_w, r_w = train_svr(x, z, cfg.replace(weight_pos=3.0, weight_neg=0.25),
                         backend="single")
    assert r_w.iterations == r_plain.iterations
    np.testing.assert_allclose(r_w.alpha, r_plain.alpha, atol=1e-6)


def test_oneclass_ignores_class_weights(blobs_small):
    # The OCSVM box is [0, 1] by definition; weight_pos must not rescale
    # it below the nu-constrained alpha_init.
    from dpsvm_tpu.models.oneclass import train_oneclass
    x, _ = blobs_small
    cfg = SVMConfig(gamma=0.2, epsilon=1e-3, max_iter=100_000)
    m_plain, r_plain = train_oneclass(x, nu=0.3, config=cfg, backend="single")
    m_w, r_w = train_oneclass(
        x, nu=0.3, config=cfg.replace(weight_pos=0.5), backend="single")
    assert r_w.converged
    np.testing.assert_allclose(r_w.alpha, r_plain.alpha, atol=1e-6)
    assert np.asarray(r_w.alpha).max() <= 1.0 + 1e-6
