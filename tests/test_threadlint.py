"""threadlint (ISSUE 20): the static concurrency-contract analyzer.

Three layers, mirroring tests/test_tpulint.py's discipline for the HLO
budgets:

* extractor sanity — the fact families over the LIVE tree contain the
  load-bearing inventory (the serving locks, the five dpsvm- threads,
  the cross-thread handoffs, the fault seams);
* contract mechanics — deny-by-default diffing, allow-prefix
  semantics, byte-deterministic regeneration that preserves allow
  lists and the handoff->seam map;
* mutation verification — the analyzer is only evidence if deliberate
  regressions trip it: a deleted ``with self._lock:`` must surface as
  GUARDED_BY drift, a reversed nested acquire as an ORDER cycle, an
  unnamed thread as a LIFECYCLE violation. Mutations are injected via
  the ``sources`` override; the tree is never touched.

Everything here is host-only (pure AST) — no jax, no devices.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path

from dpsvm_tpu.analysis import concurrency_facts as cf
from dpsvm_tpu.analysis import threadlint as tl

REPO = Path(__file__).resolve().parent.parent
REGISTRY = "dpsvm_tpu/serving/registry.py"
EXPORT = "dpsvm_tpu/obs/export.py"


def _facts(overrides=None):
    return cf.extract_concurrency_facts(
        sources=cf.load_sources(root=REPO, overrides=overrides))


def _check(overrides=None):
    return tl.run_check(
        sources=cf.load_sources(root=REPO, overrides=overrides))


def _verdicts(results):
    return {r["family"]: r["verdict"] for r in results}


# ------------------------------------------------------------------
# extractor sanity over the live tree
# ------------------------------------------------------------------
def test_extractor_finds_the_serving_locks():
    facts = _facts()
    locks = facts["guarded_by"]["locks"]
    for lock in ("Scheduler._lock", "ServeServer._life",
                 "ServeServer._rep_lock", "ModelRegistry._lock",
                 "MetricsExporter._close_lock", "faults._plan_lock",
                 "_NetStats.lock"):
        assert lock in locks, f"extractor lost {lock}"
    # The RLock is recorded as such (its self-edges are legal).
    assert locks["ServeServer._life"]["kind"] == "RLock"


def test_extractor_guards_the_fixed_seed_findings():
    """The seed-run true positives fixed in this PR must now read as
    guarded: regressing any of them is contract drift, but the facts
    themselves are the first line of evidence."""
    attrs = _facts()["guarded_by"]["attrs"]
    for attr, lock in (
            ("Scheduler._seq", "Scheduler._lock"),
            ("Scheduler.queue_rows", "Scheduler._lock"),
            ("Scheduler._entry_refs", "Scheduler._lock"),
            ("ServeServer._rep_parked", "ServeServer._rep_lock"),
            ("ServeServer._rep_draining", "ServeServer._rep_lock"),
            ("faults._PLAN", "faults._plan_lock"),
            ("MetricsExporter._closed",
             "MetricsExporter._close_lock")):
        f = attrs[attr]
        assert f["writes_unguarded"] == 0, (attr, f)
        assert lock in f["locks"], (attr, f)


def test_extractor_thread_inventory():
    threads = _facts()["thread_lifecycle"]["threads"]
    names = sorted(t["name"] for t in threads.values())
    assert names == ["dpsvm-dispatch-watchdog", "dpsvm-metrics-*",
                     "dpsvm-net-accept", "dpsvm-net-pump*",
                     "dpsvm-net-writer-*"]
    for site, t in threads.items():
        assert t["named_ok"], site
        assert t["daemon"] or t["joined"], site


def test_extractor_handoffs_and_seams():
    sc = _facts()["seam_coverage"]
    assert "lock_stall" in sc["seams"]  # this PR's fault seam
    assert ("dpsvm_tpu/serving/server.py::ServeServer._read_loop::"
            "_inbox.put") in sc["handoffs"]


def test_no_lock_order_cycles_in_tree():
    lo = _facts()["lock_order"]
    assert lo["cycles"] == []
    # The committed canonical order covers every lock in the graph.
    in_edges = {x for e in lo["edges"] for x in e.split(" -> ")}
    assert in_edges <= set(lo["order"])


# ------------------------------------------------------------------
# contract mechanics
# ------------------------------------------------------------------
def test_clean_tree_passes_committed_contracts():
    code, lines, results = _check()
    assert code == 0, "\n".join(lines)
    assert set(_verdicts(results).values()) == {tl.PASS}


def test_regeneration_is_deterministic_and_drift_free(tmp_path):
    """Two regenerations are byte-identical, and both match the
    committed contracts exactly — the CI drift gate's property."""
    work = tmp_path / "contracts"
    shutil.copytree(tl.CONTRACT_DIR, work)
    srcs = cf.load_sources(root=REPO)
    tl.write_contracts(sources=srcs, contracts_dir=work)
    first = {p.name: p.read_bytes() for p in sorted(work.iterdir())}
    tl.write_contracts(sources=srcs, contracts_dir=work)
    second = {p.name: p.read_bytes() for p in sorted(work.iterdir())}
    assert first == second
    for fam in tl.FAMILIES:
        committed = (tl.CONTRACT_DIR / f"{fam}.json").read_bytes()
        assert first[f"{fam}.json"] == committed, fam


def test_diff_facts_leaf_semantics():
    exp = {"a": {"b": 1, "c": [1, 2]}, "d": 4}
    act = {"a": {"b": 2, "c": [1, 2]}, "e": 5}
    got = tl.diff_facts(exp, act)
    assert got == [("a.b", 1, 2), ("d", 4, tl.ABSENT),
                   ("e", tl.ABSENT, 5)]


def test_allow_is_prefix_matched_and_deny_by_default():
    facts = {"guarded_by": {"locks": {}, "attrs": {}}}
    contract = {"facts": {"locks": {}, "attrs": {"X.y": 1}},
                "allow": []}
    r = tl.check_family("guarded_by", facts, contract)
    assert r["verdict"] == tl.DRIFT and len(r["denied"]) == 1
    contract["allow"] = [{"path": "guarded_by.attrs.X.",
                          "reason": "test"}]
    r = tl.check_family("guarded_by", facts, contract)
    assert r["verdict"] == tl.PASS and len(r["allowed"]) == 1


def test_missing_contract_fails_closed(tmp_path):
    code, lines, results = tl.run_check(
        sources=cf.load_sources(root=REPO),
        contracts_dir=tmp_path / "nowhere")
    assert code == 1
    assert set(_verdicts(results).values()) == {tl.MISSING}


def test_unmapped_handoff_is_denied(tmp_path):
    """Seam coverage is deny-by-default: drop one committed map entry
    and the corresponding handoff must FAIL the check."""
    work = tmp_path / "contracts"
    shutil.copytree(tl.CONTRACT_DIR, work)
    p = work / "seam_coverage.json"
    c = json.loads(p.read_text())
    victim = ("dpsvm_tpu/serving/server.py::ServeServer._read_loop::"
              "_inbox.put")
    del c["map"][victim]
    p.write_text(json.dumps(c, indent=2, sort_keys=True) + "\n")
    code, lines, results = tl.run_check(
        sources=cf.load_sources(root=REPO), contracts_dir=work)
    assert code == 1
    seam = next(r for r in results if r["family"] == "seam_coverage")
    assert seam["verdict"] == tl.VIOLATION
    assert any(victim in rec[0] for rec in seam["denied"])


# ------------------------------------------------------------------
# mutation verification — the analyzer must catch what it claims to
# ------------------------------------------------------------------
def test_mutation_deleted_lock_is_guarded_by_drift():
    """Remove registry.attach_journal's ``with self._lock:`` (the
    indentation-preserving ``if True:`` swap): the journal-attach
    writes flip to unguarded and the guarded_by family must fail."""
    src = (REPO / REGISTRY).read_text()
    assert src.count("with self._lock:") >= 5
    mutated = src.replace("with self._lock:", "if True:", 1)
    code, lines, results = _check({REGISTRY: mutated})
    assert code == 1
    v = _verdicts(results)
    assert v["guarded_by"] != tl.PASS
    gb = next(r for r in results if r["family"] == "guarded_by")
    assert any("ModelRegistry._journal" in rec[0]
               for rec in gb["denied"]), gb["denied"]


def test_mutation_reversed_nesting_is_order_cycle():
    """Inject a pair of methods acquiring _lock/_journal_lock in
    OPPOSING nested order: the acquired-while-holding graph gains a
    cycle and the lock_order family must fail with a cycle finding."""
    src = (REPO / REGISTRY).read_text()
    anchor = "def __len__(self) -> int:"
    assert src.count(anchor) == 1
    mutant = (
        "def _tl_forward(self):\n"
        "        with self._lock:\n"
        "            with self._journal_lock:\n"
        "                pass\n\n"
        "    def _tl_backward(self):\n"
        "        with self._journal_lock:\n"
        "            with self._lock:\n"
        "                pass\n\n"
        "    " + anchor)
    code, lines, results = _check(
        {REGISTRY: src.replace(anchor, mutant, 1)})
    assert code == 1
    lo = next(r for r in results if r["family"] == "lock_order")
    assert lo["verdict"] != tl.PASS
    assert any("cycles" in rec[0] for rec in lo["denied"])
    # The facts themselves carry the cycle (both locks named in it).
    facts = _facts({REGISTRY: src.replace(anchor, mutant, 1)})
    assert any("ModelRegistry._lock" in c
               and "ModelRegistry._journal_lock" in c
               for c in facts["lock_order"]["cycles"])


def test_mutation_unnamed_thread_is_lifecycle_failure():
    """Strip the exporter thread's dpsvm- name: the lifecycle family
    must fail on the naming rule (watchdog-readability contract)."""
    src = (REPO / EXPORT).read_text()
    victim = 'name=f"dpsvm-metrics-{self.port}", daemon=True'
    assert victim in src
    mutated = src.replace(victim, "daemon=True", 1)
    code, lines, results = _check({EXPORT: mutated})
    assert code == 1
    lf = next(r for r in results if r["family"] == "thread_lifecycle")
    assert lf["verdict"] != tl.PASS
    assert any("MetricsExporter.__init__" in rec[0] and
               rec[0].endswith(".name") for rec in lf["denied"])
