"""Shard-parallel working sets (config.local_working_sets;
parallel/dist_block.py make_block_shardlocal_chunk_runner).

Correctness battery for ISSUE 4's tentpole: bit-exact reduction to the
current mesh engine at local_working_sets=1, CPU-mesh (8 virtual
devices) trajectory convergence to the per-pair oracle optimum, the
endgame demotion to the exact global runner, the budget/knob
validation surface, and the cross-shard staleness regimes (heavy
bound-saturation, class weights, compensated carry, uneven rows). The
heavy 8-device legs are `slow`; tier-1 keeps a cheap 2-device smoke
(ISSUE 4 CI-budget satellite).
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.solver.smo import solve

BASE = SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3, max_iter=200_000,
                 engine="block", working_set_size=32)


def _sl(cfg, r=2):
    return cfg.replace(local_working_sets=2, sync_rounds=r)


# ---- bit-exact reduction (acceptance criterion) ---------------------


def test_bitexact_reduction_at_local_working_sets_1(blobs_medium):
    """local_working_sets=1, sync_rounds=1 IS the current engine:
    solve_mesh routes to make_block_chunk_runner, so the trajectory —
    alpha, f, extrema, pair counts, every chunk boundary — must be
    BIT-identical to the default (auto) config's."""
    x, y = blobs_medium
    obs_a, obs_b = [], []

    def cb(sink):
        return lambda it, bh, bl, st: sink.append((it, bh, bl)) and None

    cfg = BASE.replace(inner_iters=64, chunk_iters=64)
    r1 = solve_mesh(x, y, cfg.replace(local_working_sets=1,
                                      sync_rounds=1),
                    num_devices=8, callback=cb(obs_a))
    r0 = solve_mesh(x, y, cfg, num_devices=8, callback=cb(obs_b))
    assert r1.converged and r0.converged
    assert r1.iterations == r0.iterations
    assert obs_a == obs_b
    np.testing.assert_array_equal(r1.alpha, r0.alpha)
    np.testing.assert_array_equal(r1.stats["f"], r0.stats["f"])
    assert (r1.b_hi, r1.b_lo) == (r0.b_hi, r0.b_lo)
    # The reduction really did route around the shard-local engine.
    assert "shardlocal_demoted" not in r1.stats


# ---- tier-1 smoke (2 devices, small set) ----------------------------


def test_shardlocal_two_device_smoke(blobs_small):
    """Cheap tier-1 leg: 2 concurrent shard chains reach the per-pair
    oracle optimum (the endgame demotion owns the exact tail)."""
    x, y = blobs_small
    rm = solve_mesh(x, y, _sl(BASE.replace(working_set_size=16)),
                    num_devices=2)
    rx = solve(x, y, SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3,
                               max_iter=200_000))
    assert rm.converged and rx.converged
    np.testing.assert_allclose(rm.alpha, rx.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rx.b, abs=5e-3)


def test_shardlocal_demotion_reports_and_converges(blobs_small):
    """The endgame demotion is observable (stats) and final convergence
    is exact: `converged` comes from the demoted global runner's own
    stopping rule, never from a shard-local window's stale view."""
    x, y = blobs_small
    rm = solve_mesh(x, y, _sl(BASE.replace(working_set_size=16), r=4),
                    num_devices=2)
    assert rm.converged
    assert "shardlocal_demoted" in rm.stats
    # On every pinned set the local chains starve before the global gap
    # closes (the last violating pair straddles shards), so the exact
    # tail must have engaged.
    assert rm.stats["shardlocal_demoted"] is True


def test_shardlocal_validation():
    with pytest.raises(ValueError, match="block-engine"):
        SVMConfig(engine="xla", local_working_sets=2)
    with pytest.raises(ValueError, match="budget_mode"):
        SVMConfig(engine="block", local_working_sets=2, budget_mode=True)
    with pytest.raises(ValueError, match="active_set_size"):
        SVMConfig(engine="block", local_working_sets=2,
                  active_set_size=64)
    with pytest.raises(ValueError, match="pipeline_rounds"):
        SVMConfig(engine="block", local_working_sets=2,
                  pipeline_rounds=True)
    with pytest.raises(ValueError, match="feature kernels"):
        SVMConfig(engine="block", local_working_sets=2,
                  kernel="precomputed")
    with pytest.raises(ValueError, match="local_working_sets"):
        SVMConfig(engine="block", local_working_sets=0)
    with pytest.raises(ValueError, match="sync_rounds"):
        SVMConfig(engine="block", sync_rounds=0)
    # sync_rounds without the shard-local engine would silently no-op.
    with pytest.raises(ValueError, match="local_working_sets >= 2"):
        SVMConfig(engine="block", sync_rounds=4)
    with pytest.raises(ValueError, match="local_working_sets >= 2"):
        SVMConfig(engine="block", sync_rounds=4, local_working_sets=1)
    # Legal shapes.
    SVMConfig(engine="block", local_working_sets=1)
    SVMConfig(engine="block", local_working_sets=2, sync_rounds=8)
    SVMConfig(engine="xla", local_working_sets=None, sync_rounds=1)


def test_shardlocal_runner_rejects_unsupported():
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.parallel.dist_block import (
        make_block_shardlocal_chunk_runner)
    from dpsvm_tpu.parallel.mesh import make_data_mesh

    with pytest.raises(ValueError, match="feature kernels"):
        make_block_shardlocal_chunk_runner(
            make_data_mesh(2), KernelParams("precomputed"), (1.0, 1.0),
            1e-3, 1e-12, 16, 32, 4)
    with pytest.raises(ValueError, match="selection"):
        make_block_shardlocal_chunk_runner(
            make_data_mesh(2), KernelParams("rbf", 0.1), (1.0, 1.0),
            1e-3, 1e-12, 16, 32, 4, selection="nu")


def test_shardlocal_with_reconstruction_legs(blobs_small):
    """The extreme-C accuracy mode composes: legs run shard-local with
    the endgame demotion, convergence is judged on the reconstructed
    f64 gap, and the hybrid block->per-pair tail switch resets the
    shard-local knobs with the other block-only ones
    (solver/reconstruct.py)."""
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, c=200.0, gamma=0.05,
                       compensated=True, reconstruct_every=40_000,
                       max_iter=400_000, local_working_sets=2,
                       sync_rounds=2)
    r = solve_mesh(x, y, cfg, num_devices=2)
    assert r.converged
    assert r.stats["true_gap"] <= 2 * cfg.epsilon + 1e-9


def test_shardlocal_nusvc_falls_back_cleanly(blobs_small):
    """A user config with local_working_sets=2 must not crash the nu
    trainers (per-class selection keeps the plain mesh runner), and
    since ISSUE 9 the fallback is NAMED, not silent: the trainer warns
    with the requested engine and the dropped knob."""
    from dpsvm_tpu.models.nusvm import train_nusvc

    x, y = blobs_small
    with pytest.warns(UserWarning,
                      match=r"falls back from: local_working_sets"):
        model, res = train_nusvc(x, y, nu=0.3,
                                 config=_sl(BASE.replace(gamma=0.1)),
                                 backend="mesh", num_devices=2)
    assert res.converged


# ---- 8-device trajectory legs (slow: several mesh solves) -----------


@pytest.mark.slow
@pytest.mark.parametrize("sync_rounds", [1, 4])
def test_shardlocal_mesh_matches_oracle(blobs_medium, sync_rounds):
    """8 concurrent chains, R in {1, 4}: the shard-local path must reach
    the oracle duality gap (converged == the refreshed exact stopping
    rule) and optimum within the mesh tolerance, at a bounded pair
    inflation — the kappa docs/SCALING.md's round-7 projection charges
    for cross-shard staleness."""
    x, y = blobs_medium
    rp = solve(x, y, BASE)
    rm = solve_mesh(x, y, _sl(BASE, r=sync_rounds), num_devices=8)
    assert rp.converged and rm.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)
    # Pair-inflation guard: staleness costs pairs, not correctness —
    # but a runaway here would invalidate the scaling story. The 8x
    # bound is loose (measured ~3x on this set, recorded in SCALING.md).
    assert rm.iterations <= 8 * rp.iterations


@pytest.mark.slow
def test_shardlocal_heavy_saturation_regime(blobs_medium):
    """Tiny C drives most alphas to the bound within a few windows, so
    cross-shard staleness routinely selects rows another shard's sync
    just saturated — the regime the selection masks' own-alpha
    re-derivation must keep safe."""
    x, y = blobs_medium
    cfg = _sl(BASE.replace(c=0.05, working_set_size=16), r=4)
    rm = solve_mesh(x, y, cfg, num_devices=8)
    rp = solve(x, y, BASE.replace(c=0.05, working_set_size=16))
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-3)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)
    assert np.mean(np.isclose(rp.alpha, 0.05)) > 0.5


@pytest.mark.slow
def test_shardlocal_class_weights(blobs_medium):
    x, y = blobs_medium
    cfg = _sl(BASE.replace(weight_pos=2.0, weight_neg=0.5), r=2)
    rm = solve_mesh(x, y, cfg, num_devices=8)
    rp = solve(x, y, BASE.replace(weight_pos=2.0, weight_neg=0.5))
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)


@pytest.mark.slow
def test_shardlocal_compensated_and_second_order(blobs_medium):
    """The Kahan carry shards like f (sync folds run compensated), and
    the WSS2 pairing rule rides the same shard-local selection."""
    x, y = blobs_medium
    cfg = _sl(BASE.replace(compensated=True, selection="second_order"),
              r=2)
    rm = solve_mesh(x, y, cfg, num_devices=8)
    rp = solve(x, y, BASE.replace(selection="second_order"))
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)


@pytest.mark.slow
def test_shardlocal_uneven_rows(blobs_medium):
    """n not divisible by the device count: pad rows are masked out of
    every shard-local selection and carry zero fold coefficients."""
    x, y = blobs_medium
    x, y = x[:1199], y[:1199]
    rm = solve_mesh(x, y, _sl(BASE, r=2), num_devices=8)
    rp = solve(x, y, BASE)
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
