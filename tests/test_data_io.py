"""CSV loader (native + NumPy fallback) and model serialization tests."""

import numpy as np
import pytest

from dpsvm_tpu.data.loader import load_csv, save_csv, _load_csv_numpy
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.utils import native


@pytest.fixture()
def csv_file(tmp_path):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(37, 5)).astype(np.float32)
    y = np.where(rng.random(37) < 0.5, 1, -1).astype(np.int32)
    path = str(tmp_path / "data.csv")
    save_csv(path, x, y)
    return path, x, y


def test_load_csv_roundtrip(csv_file):
    path, x, y = csv_file
    x2, y2 = load_csv(path)
    np.testing.assert_allclose(x2, x, rtol=1e-6)
    np.testing.assert_array_equal(y2, y)


def test_load_csv_with_declared_shape(csv_file):
    path, x, y = csv_file
    x2, y2 = load_csv(path, num_rows=20, num_features=5)
    assert x2.shape == (20, 5)
    np.testing.assert_allclose(x2, x[:20], rtol=1e-6)


def test_load_csv_shape_mismatch_raises(csv_file):
    path, *_ = csv_file
    with pytest.raises(ValueError):
        load_csv(path, num_rows=1000)
    with pytest.raises(ValueError):
        load_csv(path, num_features=64)


def test_native_parser_matches_numpy(csv_file):
    path, x, y = csv_file
    parser = native.get_fastcsv()
    if parser is None:
        pytest.skip("native toolchain unavailable")
    xn, yn = parser.parse(path)
    xp, yp = _load_csv_numpy(path, None)
    np.testing.assert_allclose(xn, xp, rtol=1e-6)
    np.testing.assert_array_equal(yn, yp)
    assert parser.shape(path) == (37, 6)


def test_native_parser_rejects_ragged_rows(tmp_path):
    # A short row must be an error, not a silent misalignment that eats
    # the next line's label (strtof skips newlines).
    parser = native.get_fastcsv()
    if parser is None:
        pytest.skip("native toolchain unavailable")
    path = str(tmp_path / "ragged.csv")
    with open(path, "w") as fh:
        fh.write("1,1.0,2.0,3.0\n")
        fh.write("-1,4.0\n")  # ragged: 2 of 3 features
        fh.write("1,5.0,6.0,7.0\n")
    with pytest.raises(IOError):
        parser.parse(path)


def test_non_rbf_text_save_refused(tmp_path):
    m = _model()
    m = SVMModel(m.sv_x, m.sv_alpha, m.sv_y, m.b, KernelParams("linear"))
    with pytest.raises(ValueError):
        m.save(str(tmp_path / "m.txt"))
    m.save(str(tmp_path / "m.npz"))  # npz path accepts any kernel


def _model():
    rng = np.random.default_rng(4)
    return SVMModel(
        sv_x=rng.normal(size=(11, 4)).astype(np.float32),
        sv_alpha=rng.random(11).astype(np.float32) + 0.01,
        sv_y=np.where(rng.random(11) < 0.5, 1, -1).astype(np.int32),
        b=0.731,
        kernel=KernelParams("rbf", gamma=0.25),
    )


def test_model_text_roundtrip(tmp_path):
    m = _model()
    path = str(tmp_path / "model.txt")
    m.save(path)
    m2 = SVMModel.load(path)
    np.testing.assert_allclose(m2.sv_x, m.sv_x, rtol=1e-6)
    np.testing.assert_allclose(m2.sv_alpha, m.sv_alpha, rtol=1e-6)
    np.testing.assert_array_equal(m2.sv_y, m.sv_y)
    assert m2.b == pytest.approx(m.b, rel=1e-6)
    assert m2.kernel.gamma == pytest.approx(0.25, rel=1e-6)


def test_model_npz_roundtrip(tmp_path):
    m = _model()
    m = SVMModel(m.sv_x, m.sv_alpha, m.sv_y, m.b,
                 KernelParams("poly", gamma=0.5, degree=4, coef0=1.5))
    path = str(tmp_path / "model.npz")
    m.save(path)
    m2 = SVMModel.load(path)
    np.testing.assert_allclose(m2.sv_x, m.sv_x)
    assert m2.kernel == m.kernel
    assert m2.b == pytest.approx(m.b, rel=1e-6)


def test_model_loads_seq_style_single_header(tmp_path):
    # seq.cpp:295-321 writes gamma but NO b line (reference bug B6); the
    # loader must accept that legacy layout with b = 0.
    path = str(tmp_path / "legacy.txt")
    with open(path, "w") as fh:
        fh.write("0.5\n")
        fh.write("0.25,1,1.0,2.0\n")
        fh.write("0.75,-1,3.0,4.0\n")
    m = SVMModel.load(path)
    assert m.b == 0.0
    assert m.n_sv == 2
    assert m.kernel.gamma == 0.5
    np.testing.assert_allclose(m.sv_x, [[1, 2], [3, 4]])


def test_from_dense_filters_zero_alpha():
    x = np.eye(4, dtype=np.float32)
    y = np.array([1, -1, 1, -1], np.int32)
    alpha = np.array([0.0, 0.5, 0.0, 1.0], np.float32)
    m = SVMModel.from_dense(x, y, alpha, 0.1, KernelParams("rbf", 1.0))
    assert m.n_sv == 2
    np.testing.assert_array_equal(m.sv_y, [-1, -1])


@pytest.fixture()
def libsvm_file(tmp_path):
    rng = np.random.default_rng(7)
    x = np.round(rng.random((30, 6)), 4).astype(np.float32)
    x[x < 0.4] = 0.0  # sparsity so some idx:val tokens are omitted
    y = np.where(rng.random(30) < 0.5, 1, -1).astype(np.int32)
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as fh:
        for row, lab in zip(x, y):
            toks = [f"{j + 1}:{v}" for j, v in enumerate(row) if v != 0]
            fh.write(("+1" if lab > 0 else "-1") + " " + " ".join(toks) + "\n")
    return path, x, y


def test_sniff_format(csv_file, libsvm_file):
    from dpsvm_tpu.data.loader import sniff_format

    assert sniff_format(csv_file[0]) == "csv"
    assert sniff_format(libsvm_file[0]) == "libsvm"


def test_load_data_libsvm_matches_converted_csv(tmp_path, libsvm_file):
    """Direct LIBSVM loading must equal the convert-then-load path (the
    reference's offline scripts/convert_adult.py workflow)."""
    from dpsvm_tpu.data.converters import libsvm_to_csv
    from dpsvm_tpu.data.loader import load_data

    path, x, y = libsvm_file
    x1, y1 = load_data(path, num_features=6)  # auto-sniffed
    csv_path = str(tmp_path / "conv.csv")
    libsvm_to_csv(path, csv_path, num_features=6)
    x2, y2 = load_data(csv_path)
    np.testing.assert_allclose(x1, x2, atol=1e-6)
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_allclose(x1, x, atol=1e-6)
    np.testing.assert_array_equal(y1, y)
    # Row bound honored; regression targets rejected with a clear error.
    xr, yr = load_data(path, num_rows=10, num_features=6)
    assert xr.shape == (10, 6)
    with pytest.raises(ValueError, match="regression"):
        load_data(path, float_labels=True)


def test_sniff_format_label_only_first_row(tmp_path):
    """A legal LIBSVM row with no nonzero features is a bare label —
    sniffing must look past it instead of misreading the file as CSV."""
    from dpsvm_tpu.data.loader import load_data, sniff_format

    p = str(tmp_path / "lead.libsvm")
    with open(p, "w") as fh:
        fh.write("-1\n+1 2:0.5 3:1.0\n-1 1:0.25\n")
    assert sniff_format(p) == "libsvm"
    x, y = load_data(p)
    assert x.shape == (3, 3)
    np.testing.assert_array_equal(y, [-1, 1, -1])
    assert x[0].sum() == 0.0
