"""CLI end-to-end tests: the reference's train->model->test flow
(Makefile run targets) through `python -m dpsvm_tpu.cli`."""

import numpy as np
import pytest

from dpsvm_tpu.cli import main
from dpsvm_tpu.data.loader import save_csv
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.utils.native import get_seqsmo


@pytest.fixture(scope="module")
def csvs(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    x, y = make_blobs_binary(n=500, d=12, seed=21, sep=2.5)
    train_p = str(d / "train.csv")
    test_p = str(d / "test.csv")
    save_csv(train_p, x[:400], y[:400])
    save_csv(test_p, x[400:], y[400:])
    return train_p, test_p, str(d)


def test_train_then_test_roundtrip(csvs, capsys):
    train_p, test_p, d = csvs
    model_p = d + "/model.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "-e", "0.001", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "converged at iteration" in out
    assert "model saved" in out

    rc = main(["test", "-f", test_p, "-m", model_p])
    assert rc == 0
    out = capsys.readouterr().out
    acc = float(out.split("test accuracy: ")[1].split()[0])
    assert acc > 0.85


def test_train_class_weight_flags(csvs, capsys):
    # LibSVM-style -w1/-w-1 must reach the solver (weighted C changes the
    # iterate count vs the unweighted run on the same data).
    train_p, _, d = csvs
    model_p = d + "/wmodel.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "-w1", "2.0", "-w-1", "0.5", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    it_w = int(out.split("converged at iteration ")[1].split()[0])
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    it_plain = int(out.split("converged at iteration ")[1].split()[0])
    assert it_w != it_plain


def test_train_with_declared_shapes_and_npz(csvs, capsys):
    train_p, test_p, d = csvs
    model_p = d + "/model.npz"
    rc = main(["train", "-f", train_p, "-m", model_p, "-a", "12", "-x", "400",
               "-c", "5", "-g", "0.1", "--backend", "mesh",
               "--num-devices", "4", "-q"])
    assert rc == 0
    rc = main(["test", "-f", test_p, "-m", model_p])
    assert rc == 0
    acc = float(capsys.readouterr().out.split("test accuracy: ")[1].split()[0])
    assert acc > 0.85


def test_checkpoint_resume_cli(csvs, capsys):
    train_p, _, d = csvs
    model_p = d + "/model_ck.txt"
    ck = d + "/solver.ckpt.npz"
    # Run a few iterations only, checkpointing.
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "-n", "40", "--chunk-iters", "20", "--checkpoint", ck,
               "--checkpoint-every", "20", "--backend", "single", "-q"])
    assert rc == 0
    import os
    assert os.path.exists(ck)
    # Resume to convergence.
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "--checkpoint", ck, "--resume", "--backend", "single", "-q"])
    assert rc == 0
    assert "converged" in capsys.readouterr().out


def test_smoke_command(capsys):
    rc = main(["smoke", "--num-devices", "4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "matvec OK" in out and "psum OK" in out


def test_metrics_jsonl(csvs):
    import json
    train_p, _, d = csvs
    mpath = d + "/metrics.jsonl"
    main(["train", "-f", train_p, "-m", d + "/m.txt", "-c", "5", "-g", "0.1",
          "--chunk-iters", "100", "--metrics-jsonl", mpath,
          "--backend", "single", "-q"])
    recs = [json.loads(ln) for ln in open(mpath)]
    assert recs
    assert {"iteration", "gap", "sv_estimate", "iters_per_sec"} <= recs[0].keys()


def test_multihost_flags_invoke_initialize(csvs, monkeypatch):
    """--coordinator-address etc. must call initialize_multihost before
    training (the mpirun --hostfile equivalent, SURVEY.md 5.8)."""
    train_p, _, d = csvs
    calls = []
    import dpsvm_tpu.parallel.mesh as mesh_mod
    monkeypatch.setattr(
        mesh_mod, "initialize_multihost",
        lambda addr, nproc, pid: calls.append((addr, nproc, pid)))
    rc = main(["train", "-f", train_p, "-m", d + "/mh.txt", "-c", "5",
               "-g", "0.1", "--backend", "single", "-q",
               "--coordinator-address", "localhost:1234",
               "--num-processes", "1", "--process-id", "0"])
    assert rc == 0
    assert calls == [("localhost:1234", 1, 0)]


@pytest.mark.skipif(get_seqsmo() is None,
                    reason="native toolchain unavailable")
def test_native_backend_cli(csvs, capsys):
    train_p, test_p, d = csvs
    rc = main(["train", "-f", train_p, "-m", d + "/nat.txt", "-c", "5",
               "-g", "0.1", "--backend", "native", "-q"])
    assert rc == 0
    rc = main(["test", "-f", test_p, "-m", d + "/nat.txt"])
    assert rc == 0
    assert "test accuracy" in capsys.readouterr().out


def test_train_cli_block_engine(csvs, capsys):
    train_p, test_p, d = csvs
    model_p = d + "/model_blk.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "--engine", "block", "--working-set-size", "16",
               "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "converged at iteration" in out


def test_train_cli_pipelined_rounds(csvs, capsys):
    """--pipeline-rounds on routes the block engine through the
    pipelined chunk runner (and off/auto stay legal)."""
    train_p, test_p, d = csvs
    model_p = d + "/model_pipe.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g",
               "0.1", "--engine", "block", "--working-set-size", "16",
               "--pipeline-rounds", "on", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "converged at iteration" in out
    # Non-block engine + forced pipelining is a clean config error.
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5",
               "--engine", "xla", "--pipeline-rounds", "on",
               "--backend", "single", "-q"])
    assert rc == 2


def test_train_cli_svm_types(csvs, capsys, tmp_path):
    """LibSVM's -s svm_type role: every problem type trains and evaluates
    through the CLI."""
    train_p, test_p, d = csvs

    # nu-SVC: classifier flow, text model.
    mp = str(tmp_path / "nusvc.txt")
    rc = main(["train", "-f", train_p, "-m", mp, "-t", "nu-svc",
               "--nu", "0.3", "-g", "0.1", "--backend", "single", "-q"])
    assert rc == 0
    rc = main(["test", "-f", test_p, "-m", mp])
    assert rc == 0
    out = capsys.readouterr().out
    acc = float(out.split("test accuracy: ")[1].split()[0])
    assert acc > 0.85

    # eps-SVR and nu-SVR: regression flow, .npz model, RMSE/R2 metrics.
    for t, name in [("eps-svr", "esvr"), ("nu-svr", "nsvr")]:
        mp = str(tmp_path / f"{name}.npz")
        rc = main(["train", "-f", train_p, "-m", mp, "-t", t,
                   "-g", "0.1", "-c", "5", "--backend", "single", "-q"])
        assert rc == 0
        rc = main(["test", "-f", test_p, "-m", mp])
        assert rc == 0
        out = capsys.readouterr().out
        assert "RMSE" in out
        # +-1 labels as regression targets: a CLI-flow smoke check, not a
        # solver-quality bar (that lives in test_nusvm/test_svr_oneclass).
        r2 = float(out.split("R2: ")[1].split()[0])
        assert r2 > 0.3

    # one-class: inlier-fraction flow.
    mp = str(tmp_path / "oc.npz")
    rc = main(["train", "-f", train_p, "-m", mp, "-t", "one-class",
               "--nu", "0.2", "-g", "0.1", "--backend", "single", "-q"])
    assert rc == 0
    rc = main(["test", "-f", test_p, "-m", mp])
    assert rc == 0
    out = capsys.readouterr().out
    assert "inlier fraction" in out


def test_train_cli_svr_model_extension_coerced(csvs, capsys, tmp_path):
    train_p, _, _ = csvs
    mp = str(tmp_path / "svr_model.txt")  # wrong extension on purpose
    rc = main(["train", "-f", train_p, "-m", mp, "-t", "eps-svr",
               "-g", "0.1", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "models use the .npz format" in out
    import os
    assert os.path.exists(mp + ".npz")


def test_train_cli_libsvm_format(csvs, capsys, tmp_path):
    """A sparse LIBSVM-format file trains directly (auto-sniffed), no
    offline conversion step — and matches the CSV-trained model."""
    import numpy as np

    from dpsvm_tpu.data.loader import load_csv

    train_p, _, d = csvs
    x, y = load_csv(train_p)
    lib_p = str(tmp_path / "train.libsvm")
    with open(lib_p, "w") as fh:
        for row, lab in zip(x, y):
            toks = [f"{j + 1}:{v}" for j, v in enumerate(row)]
            fh.write(("+1" if lab > 0 else "-1") + " " + " ".join(toks) + "\n")
    m_csv = str(tmp_path / "m_csv.txt")
    m_lib = str(tmp_path / "m_lib.txt")
    common = ["-c", "5", "-g", "0.1", "--backend", "single", "-q"]
    assert main(["train", "-f", train_p, "-m", m_csv] + common) == 0
    assert main(["train", "-f", lib_p, "-m", m_lib] + common) == 0
    capsys.readouterr()
    from dpsvm_tpu.models.svm_model import SVMModel

    a, b = SVMModel.load(m_csv), SVMModel.load(m_lib)
    assert a.sv_x.shape == b.sv_x.shape
    assert abs(a.b - b.b) < 1e-5
    np.testing.assert_allclose(a.sv_alpha, b.sv_alpha, atol=1e-5)


def test_test_cli_libsvm_narrower_file_uses_model_width(csvs, capsys, tmp_path):
    """A sparse LIBSVM test file whose trailing features are all zero has
    a smaller max index than the model's width (the canonical a9a.t case);
    the test command must default the feature dim to the model's."""
    import numpy as np

    from dpsvm_tpu.data.loader import load_csv

    train_p, _, d = csvs
    model_p = str(tmp_path / "m.txt")
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    x, y = load_csv(train_p)
    lib_p = str(tmp_path / "test_narrow.libsvm")
    with open(lib_p, "w") as fh:
        for row, lab in zip(x[:50], y[:50]):
            # Omit the last feature column entirely -> max index = d-1.
            toks = [f"{j + 1}:{v}" for j, v in enumerate(row[:-1])]
            fh.write(("+1" if lab > 0 else "-1") + " " + " ".join(toks) + "\n")
    assert main(["test", "-f", lib_p, "-m", model_p]) == 0
    out = capsys.readouterr().out
    assert "test accuracy:" in out


def test_probability_roundtrip(csvs, capsys):
    """-b 1: train fits Platt calibration, model round-trips it through
    .npz, test -b 1 reports log-loss and -o writes probabilities."""
    train_p, test_p, d = csvs
    model_p = d + "/pmodel.txt"  # auto-switched to .npz
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g", "0.1",
               "-b", "1", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "platt calibration: A=" in out
    assert "pmodel.txt.npz" in out

    pred_p = d + "/pred.txt"
    rc = main(["test", "-f", test_p, "-m", model_p + ".npz", "-b", "1",
               "-o", pred_p])
    assert rc == 0
    out = capsys.readouterr().out
    assert "platt-calibrated" in out
    ll = float(out.split("test log-loss: ")[1].split()[0])
    # This fixture's test accuracy is ~0.87, so a CALIBRATED model sits
    # near ll ~ 0.5 (measured 0.51); > 0.7 would mean the fit is broken.
    assert 0.0 < ll < 0.7
    rows = open(pred_p).read().strip().splitlines()
    assert rows[0] == "label p(+1)"
    probs = np.array([float(r.split()[1]) for r in rows[1:]])
    assert len(probs) == 100 and (probs >= 0).all() and (probs <= 1).all()
    # Probabilities must actually separate the classes.
    labels = np.array([int(r.split()[0]) for r in rows[1:]])
    assert probs[labels > 0].mean() > 0.7 and probs[labels < 0].mean() < 0.3


def test_probability_flag_rejections(csvs, capsys):
    train_p, test_p, d = csvs
    # -b on a non-classifier type fails loudly before loading data.
    rc = main(["train", "-f", train_p, "-m", d + "/x.npz", "-t", "eps-svr",
               "-b", "1", "-q"])
    assert rc == 2
    assert "classifiers only" in capsys.readouterr().err
    # test -b 1 against an uncalibrated model fails loudly.
    model_p = d + "/nopro.txt"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    rc = main(["test", "-f", test_p, "-m", model_p, "-b", "1"])
    assert rc == 2
    assert "no Platt calibration" in capsys.readouterr().err


def test_test_width_mismatch_policy(csvs, capsys):
    """A test file WIDER than the model must not be silently truncated
    (ADVICE round 2): CSV errors (with the -a escape hatch), and the
    explicit -a truncates with a warning."""
    train_p, test_p, d = csvs
    model_p = d + "/wm.txt"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    # Build a wider test csv (2 junk columns appended).
    import numpy as np
    from dpsvm_tpu.data.loader import load_csv
    x, y = load_csv(test_p)
    wide_p = d + "/wide.csv"
    save_csv(wide_p, np.hstack([x, np.ones((len(y), 2), np.float32)]), y)
    rc = main(["test", "-f", wide_p, "-m", model_p])
    assert rc == 2
    err = capsys.readouterr().err
    assert "14 features" in err and "expects 12" in err
    # Explicit -a = consent: truncates, warns, evaluates.
    rc = main(["test", "-f", wide_p, "-m", model_p, "-a", "12"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "warning" in cap.err
    assert "test accuracy" in cap.out


def test_loader_error_is_clean_diagnostic(csvs, capsys):
    """An unloadable file prints a one-line error + --format hint, not a
    traceback (ADVICE round 2)."""
    train_p, test_p, d = csvs
    bad_p = d + "/bad.libsvm"
    with open(bad_p, "w") as fh:
        fh.write("1 1:not_a_number\n-1 2:0.5\n")
    rc = main(["train", "-f", bad_p, "-m", d + "/x.txt", "-q"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "could not load" in err and "--format" in err


def test_probability_nusvc(csvs, capsys):
    """-b 1 with -t nu-svc: CV folds must refit the nu dual (the
    calibration plane comes from nu-SVC decision values)."""
    train_p, test_p, d = csvs
    model_p = d + "/nupro"
    rc = main(["train", "-f", train_p, "-m", model_p, "-t", "nu-svc",
               "--nu", "0.3", "-g", "0.1", "-b", "1",
               "--backend", "single", "-q"])
    assert rc == 0
    assert "platt calibration" in capsys.readouterr().out
    rc = main(["test", "-f", test_p, "-m", model_p + ".npz", "-b", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "test log-loss" in out


def test_precomputed_kernel_roundtrip(capsys, tmp_path):
    """LibSVM -t 4 through the CLI: train on a square Gram CSV, test on
    K(test, train) rows, predictions written with -o. Accuracy must match
    the feature-space rbf run that generated the Gram."""
    from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix

    x, y = make_blobs_binary(n=260, d=8, seed=33, sep=2.0)
    xtr, ytr, xte, yte = x[:200], y[:200], x[200:], y[200:]
    kp = KernelParams("rbf", 0.2)
    k_tr = np.asarray(kernel_matrix(xtr, xtr, kp))
    k_te = np.asarray(kernel_matrix(xte, xtr, kp))
    gram_p = str(tmp_path / "gram.csv")
    test_p = str(tmp_path / "gramtest.csv")
    model_p = str(tmp_path / "pc.npz")
    out_p = str(tmp_path / "pred.txt")
    save_csv(gram_p, k_tr, ytr)
    save_csv(test_p, k_te, yte)

    rc = main(["train", "-f", gram_p, "-m", model_p, "--kernel",
               "precomputed", "-c", "5", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "model saved" in out
    n_sv = int(out.split("support vectors: ")[1].split()[0])

    rc = main(["test", "-f", test_p, "-m", model_p, "-o", out_p])
    assert rc == 0
    out = capsys.readouterr().out
    acc = float(out.split("test accuracy: ")[1].split()[0])
    preds = np.loadtxt(out_p)
    assert preds.shape == (60,)
    assert acc == pytest.approx(float(np.mean(preds == yte)), abs=1e-4)

    # Oracle: the same problem in feature space.
    from dpsvm_tpu.cli import main as _m
    fmodel = str(tmp_path / "feat.txt")
    ftr, fte = str(tmp_path / "ftr.csv"), str(tmp_path / "fte.csv")
    save_csv(ftr, xtr, ytr)
    save_csv(fte, xte, yte)
    rc = _m(["train", "-f", ftr, "-m", fmodel, "--kernel", "rbf",
             "-g", "0.2", "-c", "5", "--backend", "single", "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    n_sv_f = int(out.split("support vectors: ")[1].split()[0])
    rc = _m(["test", "-f", fte, "-m", fmodel])
    assert rc == 0
    acc_f = float(capsys.readouterr().out.split("test accuracy: ")[1].split()[0])
    assert abs(n_sv - n_sv_f) <= max(2, 0.02 * n_sv_f)
    assert acc == pytest.approx(acc_f, abs=0.02)


def test_precomputed_kernel_cli_rejections(capsys, tmp_path):
    x, y = make_blobs_binary(n=40, d=6, seed=3, sep=2.0)
    p = str(tmp_path / "notsquare.csv")
    save_csv(p, x, y)
    rc = main(["train", "-f", p, "-m", str(tmp_path / "m.npz"),
               "--kernel", "precomputed", "-q"])
    assert rc == 2  # not a square Gram
    err = capsys.readouterr().err
    assert "square" in err
    rc = main(["train", "-f", p, "-m", str(tmp_path / "m.npz"),
               "--kernel", "precomputed", "-t", "eps-svr", "-q"])
    assert rc == 2
    rc = main(["train", "-f", p, "-m", str(tmp_path / "m.npz"),
               "--kernel", "precomputed", "-b", "1", "-q"])
    assert rc == 2
    rc = main(["train", "-f", p, "-m", str(tmp_path / "m.npz"),
               "--kernel", "precomputed", "--engine", "pallas", "-q"])
    assert rc == 2  # config rejection surfaces as a clean error
    assert "error:" in capsys.readouterr().err


def test_svr_oneclass_output_flags(capsys, tmp_path):
    """ADVICE r3: -o must write predictions for SVR and one-class models
    too, and -b must fail loudly on them."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=(150, 5)).astype(np.float32)
    z = (x[:, 0] * 2.0).astype(np.float32)
    svr_train = str(tmp_path / "svr.csv")
    save_csv(svr_train, x, z)
    svr_model = str(tmp_path / "svr.npz")
    rc = main(["train", "-f", svr_train, "-m", svr_model, "-t", "eps-svr",
               "-c", "10", "-g", "0.3", "--backend", "single", "-q"])
    assert rc == 0
    capsys.readouterr()
    out_p = str(tmp_path / "svrpred.txt")
    rc = main(["test", "-f", svr_train, "-m", svr_model, "-o", out_p])
    assert rc == 0
    assert "predictions written" in capsys.readouterr().out
    preds = np.loadtxt(out_p)
    assert preds.shape == (150,)
    assert np.corrcoef(preds, z)[0, 1] > 0.9
    # -b 1 on a non-classifier model: loud error, not silence.
    rc = main(["test", "-f", svr_train, "-m", svr_model, "-b", "1"])
    assert rc == 2
    assert "not applicable" in capsys.readouterr().err


def test_cross_validation_classifier(csvs, capsys):
    """LibSVM svm-train -v: held-out accuracy line, no model written."""
    train_p, _, d = csvs
    model_p = d + "/cv_model.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g",
               "0.1", "--backend", "single", "-q", "-v", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Cross Validation Accuracy = " in out
    acc = float(out.split("Cross Validation Accuracy = ")[1].split("%")[0])
    assert acc > 85.0
    import os
    assert not os.path.exists(model_p)  # -v writes no model (LibSVM)


def test_cross_validation_svr(tmp_path, capsys):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(240, 6)).astype(np.float32)
    z = (x @ rng.normal(size=6) + 0.05 * rng.normal(size=240)).astype(
        np.float32)
    train_p = str(tmp_path / "svr.csv")
    save_csv(train_p, x, z)
    rc = main(["train", "-f", train_p, "-m", str(tmp_path / "m.npz"),
               "-t", "eps-svr", "-c", "10", "--kernel", "linear",
               "--backend", "single", "-q", "-v", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Cross Validation Mean squared error = " in out
    assert "Cross Validation Squared correlation coefficient = " in out
    r2 = float(out.split("coefficient = ")[1].split()[0])
    assert r2 > 0.9


def test_cross_validation_errors(csvs, capsys):
    train_p, _, d = csvs
    assert main(["train", "-f", train_p, "-m", d + "/x.txt", "-q",
                 "-v", "1"]) == 2
    assert main(["train", "-f", train_p, "-m", d + "/x.npz", "-q",
                 "-t", "one-class", "-v", "3"]) == 2


def test_cross_validation_stratified_imbalanced(tmp_path, capsys):
    """svm-train stratifies -v folds: a 12-positive/288-negative set must
    complete 5-fold CV (unstratified random folds could drop all
    positives from a training complement)."""
    rng = np.random.default_rng(9)
    x = rng.normal(size=(300, 8)).astype(np.float32)
    y = np.full(300, -1, np.int32)
    y[:12] = 1
    x[y > 0] += 3.0
    p = str(tmp_path / "imb.csv")
    save_csv(p, x, y)
    rc = main(["train", "-f", p, "-m", str(tmp_path / "m.txt"), "-c", "5",
               "-g", "0.2", "--backend", "single", "-q", "-v", "5"])
    assert rc == 0
    out = capsys.readouterr().out
    acc = float(out.split("Cross Validation Accuracy = ")[1].split("%")[0])
    assert acc > 90.0


def test_cross_validation_multiclass(multi_csvs, capsys):
    """svm-train -v supports multiclass files (stratified CV over the
    OvO reduction); the refusal was an ADVICE round-4 parity gap."""
    import os

    train_p, _, d = multi_csvs
    model_p = d + "/cv_multi.npz"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g",
               "0.1", "--backend", "single", "-q", "-v", "3",
               "--multiclass", "ovo"])
    assert rc == 0
    out = capsys.readouterr().out
    acc = float(out.split("Cross Validation Accuracy = ")[1].split("%")[0])
    assert acc > 90.0
    assert not os.path.exists(model_p)  # -v writes no model (LibSVM)


def test_fold_split_remainders_rotate():
    """np.array_split gives remainders to the lowest fold indices; the
    stratified split rotates per class so fold sizes stay balanced
    (ADVICE round-4). 3 classes x 100 members over 7 folds: every fold
    within +-2 of the mean."""
    from dpsvm_tpu.cli import _fold_split

    y = np.repeat([0, 1, 2], 100)
    folds = _fold_split(y, 7, seed=0, stratify=True)
    sizes = sorted(len(f) for f in folds)
    assert sum(sizes) == 300
    assert sizes[-1] - sizes[0] <= 2


def test_cross_validation_conflicting_flags(csvs, capsys):
    """-v must fail loudly on flags it cannot honor, never drop them."""
    train_p, _, d = csvs
    rc = main(["train", "-f", train_p, "-m", d + "/x.npz", "-q",
               "-v", "3", "-b", "1"])
    assert rc == 2
    assert "does not compose" in capsys.readouterr().err
    rc = main(["train", "-f", train_p, "-m", d + "/x.txt", "-q",
               "-v", "3", "--checkpoint", d + "/ck.npz", "--resume"])
    assert rc == 2


@pytest.fixture(scope="module")
def multi_csvs(tmp_path_factory):
    """3-class blobs with labels {0, 1, 2} (not ±1)."""
    d = tmp_path_factory.mktemp("cli_multi")
    rng = np.random.default_rng(3)
    centers = np.array([[0.0] * 8, [4.0] * 8, [-4.0] * 8], np.float32)
    y = rng.integers(0, 3, 360).astype(np.int32)
    x = centers[y] + rng.normal(size=(360, 8)).astype(np.float32)
    train_p, test_p = str(d / "tr.csv"), str(d / "te.csv")
    save_csv(train_p, x[:300], y[:300])
    save_csv(test_p, x[300:], y[300:])
    return train_p, test_p, str(d)


@pytest.mark.parametrize("strategy", ["ovr", "ovo"])
def test_multiclass_cli_roundtrip(multi_csvs, capsys, strategy):
    """LibSVM's svm-train trains arbitrary-labelled multiclass files
    transparently; so does the CLI (OvR/OvO reduction, .npz model)."""
    train_p, test_p, d = multi_csvs
    model_p = d + f"/m_{strategy}.npz"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5", "-g",
               "0.1", "--backend", "single", "-q",
               "--multiclass", strategy])
    assert rc == 0
    out = capsys.readouterr().out
    assert "model saved" in out
    preds_p = d + f"/preds_{strategy}.txt"
    rc = main(["test", "-f", test_p, "-m", model_p, "-o", preds_p])
    assert rc == 0
    out = capsys.readouterr().out
    assert f"{strategy}" in out
    acc = float(out.split("test accuracy: ")[1].split()[0])
    assert acc > 0.95
    labels = {int(v) for v in open(preds_p).read().split()}
    assert labels <= {0, 1, 2}


@pytest.mark.parametrize("pb", ["4", "8"])
def test_train_cli_pair_batch_4_and_8(csvs, capsys, pb):
    """--pair-batch 4/8 runnable end-to-end (the CLI hard-coded
    choices=[1,2] although the config accepts {1,2,4,8} — VERDICT
    round-5 weak #2)."""
    train_p, test_p, d = csvs
    model_p = d + f"/pb{pb}.txt"
    rc = main(["train", "-f", train_p, "-m", model_p, "-c", "5",
               "-g", "0.1", "--pair-batch", pb, "--backend", "single",
               "-q"])
    assert rc == 0
    assert "converged at iteration" in capsys.readouterr().out
    rc = main(["test", "-f", test_p, "-m", model_p])
    assert rc == 0
    acc = float(capsys.readouterr().out
                .split("test accuracy: ")[1].split()[0])
    assert acc > 0.85


def test_train_cli_pair_batch_8_block_rejected(csvs, capsys):
    """pair_batch=8 exists only on the per-pair micro executor; with
    --engine block the config's clean diagnostic must surface (exit 2,
    no traceback)."""
    train_p, _, d = csvs
    rc = main(["train", "-f", train_p, "-m", d + "/x.txt",
               "--pair-batch", "8", "--engine", "block", "-q"])
    assert rc == 2
    assert "block subproblem" in capsys.readouterr().err


def test_multiclass_cli_fleet_size_flag(multi_csvs, capsys):
    """--fleet-size reaches the config: fleet-routed OvO prints the
    fleet trainer's per-submodel lines; --fleet-size 1 keeps the
    sequential path."""
    train_p, _, d = multi_csvs
    rc = main(["train", "-f", train_p, "-m", d + "/fleet.npz", "-c", "5",
               "-g", "0.1", "--backend", "single", "--multiclass", "ovo",
               "--fleet-size", "4"])
    assert rc == 0
    assert "[fleet ovo" in capsys.readouterr().out
    rc = main(["train", "-f", train_p, "-m", d + "/seq.npz", "-c", "5",
               "-g", "0.1", "--backend", "single", "--multiclass", "ovo",
               "--fleet-size", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[fleet ovo" not in out and "[ovo" in out


def test_multiclass_cli_guards(multi_csvs, capsys):
    train_p, _, d = multi_csvs
    rc = main(["train", "-f", train_p, "-m", d + "/x.npz", "-q",
               "-b", "1"])
    assert rc == 2
    assert "does not compose" in capsys.readouterr().err
    rc = main(["train", "-f", train_p, "-m", d + "/x.npz", "-q",
               "-t", "nu-svc"])
    assert rc == 2


def test_multiclass_libsvm_format_and_binary_01(tmp_path, capsys):
    """Arbitrary integer labels load from BOTH file formats (LibSVM's
    svm-train consumes sparse files); a 2-label non-±1 file trains a
    SINGLE binary submodel (the ovo pair), not two OvR mirrors."""
    rng = np.random.default_rng(1)
    y3 = rng.integers(0, 3, 180)
    c3 = np.array([[0.0] * 4, [4.0] * 4, [-4.0] * 4], np.float32)
    x3 = c3[y3] + rng.normal(size=(180, 4)).astype(np.float32)
    p = tmp_path / "mc.libsvm"
    p.write_text("\n".join(
        f"{y3[i]} " + " ".join(f"{j + 1}:{v:.4f}"
                               for j, v in enumerate(x3[i]))
        for i in range(180)) + "\n")
    rc = main(["train", "-f", str(p), "-m", str(tmp_path / "m.npz"),
               "-c", "5", "-g", "0.3", "--backend", "single"])
    assert rc == 0
    assert "3 classes" in capsys.readouterr().out

    y2 = rng.integers(0, 2, 150)
    x2 = (np.where(y2[:, None] > 0, 2.5, -2.5)
          + rng.normal(size=(150, 5))).astype(np.float32)
    p2 = str(tmp_path / "b01.csv")
    save_csv(p2, x2, y2)
    rc = main(["train", "-f", p2, "-m", str(tmp_path / "b.npz"),
               "-c", "5", "-g", "0.2", "--backend", "single"])
    assert rc == 0
    assert "1 binary submodel" in capsys.readouterr().out
    rc = main(["test", "-f", p2, "-m", str(tmp_path / "b.npz")])
    assert rc == 0
    acc = float(capsys.readouterr().out.split("test accuracy: ")[1].split()[0])
    assert acc > 0.97
    # -w1/-w-1 would rotate per submodel: refused loudly.
    rc = main(["train", "-f", p2, "-m", str(tmp_path / "w.npz"),
               "-w1", "2.0", "--backend", "single"])
    assert rc == 2


def test_binary_model_rejects_mismatched_test_labels(csvs, tmp_path, capsys):
    """A binary ±1 model scored against 0/1-labelled data would print a
    meaningless accuracy; the test command must refuse instead."""
    train_p, _, d = csvs
    model_p = d + "/guard_model.txt"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    from dpsvm_tpu.data.loader import load_csv
    x, y = load_csv(train_p)
    bad_p = str(tmp_path / "bad01.csv")
    save_csv(bad_p, x, (y > 0).astype(np.int32))  # {0, 1} labels
    assert main(["test", "-f", bad_p, "-m", model_p]) == 2
    assert "binary +-1 model" in capsys.readouterr().err


def test_libsvm_inf_label_clean_error(tmp_path):
    from dpsvm_tpu.data.converters import parse_libsvm

    p = tmp_path / "bad.libsvm"
    p.write_text("inf 1:0.5\n")
    with pytest.raises(ValueError, match="int32 class label"):
        parse_libsvm(str(p))
    p.write_text("9999999999999 1:0.5\n")
    with pytest.raises(ValueError, match="int32 class label"):
        parse_libsvm(str(p))


def test_multiclass_test_guards(multi_csvs, tmp_path, capsys):
    """Multiclass test path refuses -g and out-of-vocabulary labels."""
    train_p, test_p, d = multi_csvs
    model_p = d + "/guard_mc.npz"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    assert main(["test", "-f", test_p, "-m", model_p, "-g", "0.5"]) == 2
    assert "-g does not apply" in capsys.readouterr().err
    from dpsvm_tpu.data.loader import load_csv
    x, y = load_csv(test_p)
    bad_p = str(tmp_path / "shifted.csv")
    save_csv(bad_p, x, y + 1)  # labels {1,2,3} vs model's {0,1,2}
    assert main(["test", "-f", bad_p, "-m", model_p]) == 2
    assert "not among the model's classes" in capsys.readouterr().err


def test_libsvm_zero_based_index_rejected(tmp_path):
    from dpsvm_tpu.data.converters import parse_libsvm

    p = tmp_path / "zb.libsvm"
    p.write_text("1 0:1.5 1:0.3\n")
    with pytest.raises(ValueError, match="1-based"):
        parse_libsvm(str(p))


def test_serve_cli_server_bench(multi_csvs, capsys):
    """`serve --server-bench` on a trained multiclass bundle: offered-
    load sweep JSON on stdout, server summary on stderr."""
    import json

    train_p, _, d = multi_csvs
    model_p = d + "/serve_mc.npz"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    assert main(["serve", "-m", model_p, "--buckets", "16,64",
                 "--server-bench", "--requests", "24"]) == 0
    cap = capsys.readouterr()
    assert "server ready" in cap.err and "SV union" in cap.err
    rec = json.loads(cap.out)
    assert rec["requests"] == 24
    assert rec["rows_per_second"] > 0
    assert {"p50", "p95", "p99"} <= set(rec["request_latency"])


def test_serve_cli_stdin_loop(multi_csvs, capsys, monkeypatch):
    """Default serve mode: feature rows on stdin -> one label per line,
    micro-batched through the pre-compiled buckets."""
    import io

    train_p, test_p, d = multi_csvs
    model_p = d + "/serve_mc2.npz"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    from dpsvm_tpu.data.loader import load_csv
    from dpsvm_tpu.models.multiclass import (MulticlassSVM,
                                             predict_multiclass)
    x, y = load_csv(test_p)
    lines = "\n".join(",".join(repr(float(v)) for v in row)
                      for row in x[:10]) + "\n"
    capsys.readouterr()
    monkeypatch.setattr("sys.stdin", io.StringIO(lines))
    assert main(["serve", "-m", model_p, "--buckets", "16"]) == 0
    cap = capsys.readouterr()
    got = np.asarray([int(t) for t in cap.out.split()])
    want = predict_multiclass(MulticlassSVM.load(model_p), x[:10])
    np.testing.assert_array_equal(got, want)
    assert "served 10 rows" in cap.err


def test_serve_cli_rejects_unservable_model(tmp_path, capsys):
    p = str(tmp_path / "svr.npz")
    np.savez_compressed(p, model_type="svr")
    assert main(["serve", "-m", p]) == 2
    assert "cannot serve a svr model" in capsys.readouterr().err


def test_test_cli_precision_flag(csvs, capsys):
    """test --precision float64 runs the exact host path; --precision
    auto on an extreme-|coef| model prints the routing note (the
    PARITY.md footgun made opt-out)."""
    train_p, test_p, d = csvs
    model_p = d + "/prec.npz"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    assert main(["test", "-f", test_p, "-m", model_p,
                 "--precision", "float64"]) == 0
    acc64 = float(capsys.readouterr().out
                  .split("test accuracy: ")[1].split()[0])
    assert acc64 > 0.85

    # Hand-build an extreme-|coef| model: auto must announce f64 routing.
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    rng = np.random.default_rng(0)
    big = SVMModel(
        sv_x=rng.normal(size=(600, 12)).astype(np.float32),
        sv_alpha=(rng.random(600).astype(np.float32) + 0.01) * 6e5,
        sv_y=np.where(rng.random(600) < 0.5, 1, -1).astype(np.int32),
        b=0.0, kernel=KernelParams("rbf", 0.1))
    big_p = d + "/big.npz"
    big.save(big_p)
    assert main(["test", "-f", test_p, "-m", big_p]) == 0
    cap = capsys.readouterr()
    assert "exact float64 evaluation" in cap.err
    capsys.readouterr()
    assert main(["test", "-f", test_p, "-m", big_p,
                 "--precision", "float32"]) == 0
    assert "float64" not in capsys.readouterr().err


def test_test_cli_precision_rejected_for_multiclass(multi_csvs, capsys):
    """--precision (non-auto) on a multiclass bundle fails loudly — the
    wiring lives on the binary path only (the same convention as -g and
    -b 1 on inapplicable models)."""
    train_p, test_p, d = multi_csvs
    model_p = d + "/prec_mc.npz"
    assert main(["train", "-f", train_p, "-m", model_p, "-c", "5",
                 "-g", "0.1", "--backend", "single", "-q"]) == 0
    capsys.readouterr()
    assert main(["test", "-f", test_p, "-m", model_p,
                 "--precision", "float64"]) == 2
    assert "--precision float64 applies to binary" \
        in capsys.readouterr().err
    assert main(["test", "-f", test_p, "-m", model_p]) == 0  # auto OK
