"""Measured autotuner (ISSUE 14): probe determinism, DeviceProfile
round-trip + version-skew refusal, gate-resolution provenance in
SolveResult.stats and the runlog manifest, the no-profile bitwise
fallback, the tpulint zero-HLO-effect contract with a profile
installed, and the report-only bucket suggestion.

Budget notes (the tier-1 suite is tight): everything here runs on
existing fixtures at tiny shapes, the probe passes use the smoke
scale with a FAKE clock (no real timing loops beyond the solver work
itself), and no interpret-mode Pallas kernel is compiled — the probes
exercised are the XLA-only ones (pipeline, serve_buckets)."""

import json

import numpy as np
import pytest

import jax

from dpsvm_tpu.autotune import (DeviceProfile, ProfileError,
                                load_profile, run_probes, stable_view,
                                use_profile)
from dpsvm_tpu.autotune.profile import (PROFILE_SCHEMA, active_profile,
                                        gate_decision, profile_path,
                                        slug)
from dpsvm_tpu.config import ObsConfig, SVMConfig


class FakeClock:
    """Deterministic timer: every interval reads as exactly `step`
    seconds, so two same-seed probe passes produce byte-identical
    records (including the measured fields)."""

    def __init__(self, step: float = 1e-3):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return self.t


def _mk_profile(decisions, jax_version=None, device_kind="cpu",
                ratio=0.5, authoritative=True):
    """Hand-built profile for gate tests (no probe pass needed)."""
    probes = {}
    for knob, dec in decisions.items():
        name = {"pipeline_rounds": "pipeline",
                "pipeline_rounds_mesh": "pipeline_mesh",
                "local_working_sets": "shardlocal",
                "ring_exchange": "ring",
                "fused_round": "fused_round"}[knob]
        probes[name] = {"probe": name, "knob": knob, "seed": 0,
                        "shapes": {"n": 1024, "d": 16, "q": 16},
                        "a_seconds": 1.0, "b_seconds": ratio,
                        "ratio": ratio, "threshold": 0.9,
                        "authoritative": authoritative,
                        "verdict": bool(dec)}
    return DeviceProfile(
        device_kind=device_kind, backend="cpu", n_devices=8,
        jax=jax_version or jax.__version__,
        utc="2026-08-04T00:00:00Z", git_sha="deadbeef", seed=0,
        probes=probes, decisions=dict(decisions))


ALL_OFF = {"pipeline_rounds": False, "pipeline_rounds_mesh": False,
           "local_working_sets": False, "ring_exchange": False,
           "fused_round": False}

CFG = SVMConfig(engine="block", working_set_size=16, epsilon=1e-2)


# ------------------------------------------------------- probe passes

def test_probe_pass_deterministic_and_runlogged(tmp_path):
    """Same seed -> same runlog probe records (stable fields AND, with
    the fake clock, the measured fields) and the same stable profile
    view; records are schema'd through the shared runlog substrate."""
    from dpsvm_tpu.obs.runlog import read_runlog

    ocfg = ObsConfig(enabled=True, runlog_dir=str(tmp_path))
    profs = [run_probes(knobs=["pipeline", "serve_buckets"], seed=3,
                        smoke=True, timer=FakeClock(),
                        obs_config=ocfg, verbose=False)
             for _ in range(2)]
    assert stable_view(profs[0]) == stable_view(profs[1])
    assert profs[0].probes == profs[1].probes  # fake clock: bytewise
    # CPU probes are never authoritative -> decisions match the
    # hand-measured OFF defaults by construction (serve_buckets is a
    # graduated knob now, pinned False off-TPU — the honesty rule).
    assert profs[0].decisions == {"pipeline_rounds": False,
                                  "serve_buckets": False}

    path, = tmp_path.glob("autotune-*.jsonl")
    recs = read_runlog(str(path))
    probe_recs = [r for r in recs if r["kind"] == "probe"]
    assert len(probe_recs) == 4  # 2 probes x 2 passes
    for r in probe_recs:
        assert {"schema", "run", "probe", "knob", "shapes", "seed",
                "verdict", "authoritative"} <= r.keys()
    by_run = {}
    for r in probe_recs:
        by_run.setdefault(r["run"], []).append(
            {k: v for k, v in r.items() if k not in ("run",)})
    a, b = by_run.values()
    assert a == b  # the record streams themselves are identical
    # The manifest/final envelope every runlog tool shares.
    assert [r["kind"] for r in recs if r["kind"] != "probe"] \
        == ["manifest", "final"] * 2


# --------------------------------------- profile persistence contract

def test_profile_round_trip(tmp_path):
    prof = _mk_profile(ALL_OFF)
    p = prof.save(str(tmp_path / "cpu.json"))
    back = load_profile(p)
    assert back.decisions == prof.decisions
    assert back.probes == prof.probes
    assert back.jax == prof.jax and back.device_kind == "cpu"
    assert back.path == p
    # Strict JSON on disk (no NaN/Infinity literals).
    json.loads(open(p).read(), parse_constant=lambda s: (_ for _ in
                                                         ()).throw(
        ValueError(f"non-strict JSON constant {s}")))


def test_profile_schema_refusal(tmp_path):
    prof = _mk_profile(ALL_OFF)
    p = prof.save(str(tmp_path / "cpu.json"))
    doc = json.load(open(p))
    doc["schema"] = PROFILE_SCHEMA + 1
    open(p, "w").write(json.dumps(doc))
    with pytest.raises(ProfileError):
        load_profile(p)
    # Malformed shapes are hard errors too, never half-applied.
    open(p, "w").write(json.dumps({"schema": PROFILE_SCHEMA}))
    with pytest.raises(ProfileError):
        load_profile(p)
    # Malformed FIELD values surface as ProfileError (the refusal
    # contract), never a TypeError crashing a solve path.
    doc = _mk_profile(ALL_OFF).to_json()
    doc["n_devices"] = None
    open(p, "w").write(json.dumps(doc))
    with pytest.raises(ProfileError, match="malformed"):
        load_profile(p)


def test_honesty_rule_enforced_at_load(tmp_path):
    """A True decision must be backed by an authoritative True-verdict
    probe AT LOAD TIME, not just at write time — a hand-edited or
    corrupted committed artifact that violates the honesty rule is
    refused whole, never half-applied."""
    good = _mk_profile({**ALL_OFF, "ring_exchange": True})
    p = good.save(str(tmp_path / "cpu.json"))
    load_profile(p)  # authoritative True-verdict backing: loads clean

    # Decision True but the backing probe is non-authoritative.
    doc = good.to_json()
    doc["probes"]["ring"]["authoritative"] = False
    (tmp_path / "cpu.json").write_text(json.dumps(doc))
    with pytest.raises(ProfileError, match="honesty"):
        load_profile(p)

    # Decision True with no probe record for the knob at all.
    doc = good.to_json()
    del doc["probes"]["ring"]
    (tmp_path / "cpu.json").write_text(json.dumps(doc))
    with pytest.raises(ProfileError, match="honesty"):
        load_profile(p)


def test_malformed_profile_refused_on_solve_path(tmp_path, monkeypatch):
    doc = _mk_profile(ALL_OFF).to_json()
    doc["seed"] = "not-an-int"
    (tmp_path / "cpu.json").write_text(json.dumps(doc))
    monkeypatch.setenv("DPSVM_AUTOTUNE_DIR", str(tmp_path))
    monkeypatch.delenv("DPSVM_AUTOTUNE_PROFILE", raising=False)
    with pytest.warns(UserWarning, match="refused"):
        assert active_profile("cpu") is None
    assert gate_decision("pipeline_rounds", device_kind="cpu") is None


def test_skipped_probe_leaves_knob_undecided():
    """A skipped probe (e.g. the ring probe on a 1-device host) must
    NOT write a decision — recording False would masquerade as a
    measured verdict and override the defaults for the whole device
    kind."""
    from dpsvm_tpu.autotune.probes import PROBE_KNOBS, _skip_record
    from dpsvm_tpu.autotune.probes import ProbeContext, run_probes
    import dpsvm_tpu.autotune.probes as probes_mod

    ctx = ProbeContext(smoke=True)
    rec = _skip_record("ring", ctx, "needs >= 2 devices")
    assert rec["verdict"] is False and rec["skipped"]
    # Run the registry with the ring probe forced to skip.
    orig = probes_mod.PROBES["ring"]
    probes_mod.PROBES["ring"] = lambda c: _skip_record(
        "ring", c, "forced skip (test)")
    try:
        prof = run_probes(knobs=["ring"], smoke=True,
                          timer=FakeClock(), verbose=False)
    finally:
        probes_mod.PROBES["ring"] = orig
    assert PROBE_KNOBS["ring"] == "ring_exchange"
    assert "ring_exchange" not in prof.decisions
    assert gate_decision_from(prof, "ring_exchange") is None


def gate_decision_from(prof, knob):
    """gate_decision through an installed profile (helper)."""
    with use_profile(prof):
        return gate_decision(knob, device_kind=prof.device_kind)


def test_version_skew_refusal(tmp_path, monkeypatch):
    """A profile stamped by a different jax major.minor is treated as
    absent (gates fall back to defaults), not half-applied."""
    stale = _mk_profile({"pipeline_rounds": True}, jax_version="9.9.0")
    stale.save(str(tmp_path / "cpu.json"))
    monkeypatch.setenv("DPSVM_AUTOTUNE_DIR", str(tmp_path))
    monkeypatch.delenv("DPSVM_AUTOTUNE_PROFILE", raising=False)
    with pytest.warns(UserWarning, match="jax"):
        assert active_profile("cpu") is None
    assert gate_decision("pipeline_rounds", device_kind="cpu") is None
    # Same file restamped with the RUNNING jax loads fine.
    fresh = _mk_profile({"pipeline_rounds": True})
    fresh.save(str(tmp_path / "cpu.json"))
    got = active_profile("cpu")
    assert got is not None and got.decisions["pipeline_rounds"]


def test_partial_run_merges_existing_profile(tmp_path):
    """A `--knobs` subset pass merges OVER the existing profile for
    the device kind (fresh records win, unmeasured knobs keep their
    decisions) instead of silently replacing it — re-probing one knob
    must never drop every other measured decision back to the OFF
    defaults. Blending across device kinds or a jax skew refuses."""
    from dpsvm_tpu.autotune import _merge_partial

    base = _mk_profile(ALL_OFF)
    p = base.save(str(tmp_path / "cpu.json"))
    fresh = _mk_profile({"ring_exchange": True}, ratio=0.4)
    merged = _merge_partial(fresh, p)
    assert merged.decisions == {**ALL_OFF, "ring_exchange": True}
    assert set(merged.probes) == set(base.probes)  # nothing dropped
    assert merged.probes["ring"]["ratio"] == 0.4  # fresh record wins
    assert merged.probes["pipeline"] == base.probes["pipeline"]

    # A SKIPPED fresh probe (e.g. the ring probe on a 1-device
    # session) must not clobber the measured record while its decision
    # survives — the old record stays, so the profile never shows a
    # True decision backed by a 'skipped' probe.
    rich = _mk_profile({**ALL_OFF, "ring_exchange": True})
    pr = rich.save(str(tmp_path / "rich.json"))
    import dataclasses as _dc
    skip_pass = _dc.replace(
        _mk_profile({}),
        probes={"ring": {"probe": "ring", "knob": "ring_exchange",
                         "seed": 0, "shapes": {},
                         "skipped": "needs >= 2 devices",
                         "authoritative": False, "verdict": False}},
        decisions={})
    merged2 = _merge_partial(skip_pass, pr)
    assert merged2.probes["ring"] == rich.probes["ring"]  # measured kept
    assert merged2.decisions["ring_exchange"] is True

    stale = _mk_profile(ALL_OFF, jax_version="9.9.0")
    ps = stale.save(str(tmp_path / "stale.json"))
    with pytest.raises(ProfileError, match="version-skewed"):
        _merge_partial(fresh, ps)

    other = _mk_profile(ALL_OFF, device_kind="TPU v5e")
    po = other.save(str(tmp_path / "other.json"))
    with pytest.raises(ProfileError, match="refusing"):
        _merge_partial(fresh, po)


def test_full_pass_merges_skipped_over_measured(tmp_path):
    """The save-path policy: a FULL `make autotune` pass also merges —
    a 1-device session of a measured kind skips its mesh probes, and
    a blind overwrite would silently drop the pod-measured
    authoritative decisions for those knobs. An incompatible (jax-
    skewed) existing file refuses a partial pass but is replaced by a
    full pass (regeneration)."""
    import dataclasses as _dc

    from dpsvm_tpu.autotune import _maybe_merge

    pod = _mk_profile({**ALL_OFF, "ring_exchange": True})
    p = pod.save(str(tmp_path / "cpu.json"))
    # Fresh FULL pass on a 1-device host: ring skipped, no decision.
    one_dev = _dc.replace(
        _mk_profile({k: False for k in ALL_OFF
                     if k != "ring_exchange"}),
        probes={**{n: r for n, r in
                   _mk_profile(ALL_OFF).probes.items() if n != "ring"},
                "ring": {"probe": "ring", "knob": "ring_exchange",
                         "seed": 0, "shapes": {},
                         "skipped": "needs >= 2 devices",
                         "authoritative": False, "verdict": False}})
    merged = _maybe_merge(one_dev, p, partial=False)
    assert merged.decisions["ring_exchange"] is True  # pod verdict kept
    assert merged.probes["ring"] == pod.probes["ring"]  # measured kept

    # Skewed existing file: full pass replaces, partial refuses.
    stale = _mk_profile(ALL_OFF, jax_version="9.9.0")
    ps = stale.save(str(tmp_path / "stale.json"))
    fresh = _mk_profile(ALL_OFF)
    assert _maybe_merge(fresh, ps, partial=False) is fresh
    with pytest.raises(ProfileError, match="version-skewed"):
        _maybe_merge(fresh, ps, partial=True)


def test_device_kind_mismatch_refusal(tmp_path, monkeypatch):
    other = _mk_profile(ALL_OFF, device_kind="TPU v5e")
    p = other.save(str(tmp_path / "cpu.json"))
    monkeypatch.setenv("DPSVM_AUTOTUNE_PROFILE", p)
    with pytest.warns(UserWarning, match="measured on"):
        assert active_profile("cpu") is None
    assert slug("TPU v5e") == "tpu-v5e"
    assert profile_path("TPU v5e").endswith("tpu-v5e.json")


# ------------------------------------------- gate resolution contract

def test_gate_provenance_in_stats_and_manifest(blobs_small, tmp_path):
    """With a profile installed, every consulted auto gate's
    resolution (profile file, probe ratio, threshold) appears in
    SolveResult.stats['autotune'] AND the runlog manifest."""
    from dpsvm_tpu.obs.runlog import read_runlog
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    prof = _mk_profile(ALL_OFF)
    prof.save(str(tmp_path / "prof.json"))
    installed = load_profile(str(tmp_path / "prof.json"))
    cfg = CFG.replace(obs=ObsConfig(enabled=True,
                                    runlog_dir=str(tmp_path)))
    with use_profile(installed):
        res = solve(x, y, cfg)
    at = res.stats["autotune"]
    assert at["device_kind"] == "cpu"
    gates = at["gates"]
    assert set(gates) == {"pipeline_rounds", "fused_round"}
    for knob, g in gates.items():
        assert g["source"] == "profile" and g["decision"] is False
        assert g["profile"].endswith("prof.json")
        assert g["ratio"] == 0.5 and g["threshold"] == 0.9
    path, = tmp_path.glob("solve-*.jsonl")
    man, = [r for r in read_runlog(str(path)) if r["kind"] == "manifest"]
    assert man["autotune"]["gates"] == gates


def test_no_profile_bitwise_fallback(blobs_small):
    """The acceptance contract: an all-False profile changes DECISIONS
    never PROGRAMS — the trajectory is bitwise the no-profile one, and
    provenance says where each decision came from."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    with use_profile(None):
        r0 = solve(x, y, CFG)
    with use_profile(_mk_profile(ALL_OFF)):
        r1 = solve(x, y, CFG)
    np.testing.assert_array_equal(r0.alpha, r1.alpha)
    assert r0.iterations == r1.iterations
    assert r0.stats["autotune"]["gates"]["pipeline_rounds"]["source"] \
        == "default"
    assert r1.stats["autotune"]["gates"]["pipeline_rounds"]["source"] \
        == "profile"


def test_profile_verdict_flips_gate(blobs_small):
    """A True verdict actually routes the solve: pipeline_rounds=None
    resolves ON from the profile (the measured-crossover flip the
    whole subsystem exists for), exactly (same optimum)."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    with use_profile(None):
        base = solve(x, y, CFG)
    with use_profile(_mk_profile({**ALL_OFF,
                                  "pipeline_rounds": True})):
        res = solve(x, y, CFG)
    g = res.stats["autotune"]["gates"]["pipeline_rounds"]
    assert g["source"] == "profile" and g["decision"] is True
    assert res.converged
    # Exactness: the pipelined engine reaches the same optimum (the
    # corrected-gradient contract) — decisions change the route, not
    # the destination.
    assert abs(res.b - base.b) < 5e-2
    # An EXPLICIT knob always wins over the profile.
    with use_profile(_mk_profile({**ALL_OFF,
                                  "pipeline_rounds": True})):
        forced = solve(x, y, CFG.replace(pipeline_rounds=False))
    assert "pipeline_rounds" not in forced.stats.get(
        "autotune", {}).get("gates", {})


def test_mesh_gate_provenance(blobs_small):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    with use_profile(_mk_profile(ALL_OFF)):
        res = solve_mesh(x, y, CFG, num_devices=8)
    gates = res.stats["autotune"]["gates"]
    # The mesh consults the MESH pipeline knob — the single-chip
    # probe's verdict must not adjudicate the structurally different
    # mesh pipelined engine.
    assert {"pipeline_rounds_mesh", "local_working_sets",
            "ring_exchange"} <= set(gates)
    assert "pipeline_rounds" not in gates
    assert all(g["source"] == "profile" for g in gates.values())
    assert res.converged


def test_shardlocal_auto_gate_requires_multidevice(blobs_small):
    """A kind-wide measured local_working_sets=True (taken on P>=2)
    must not engage the shard-local engine on a 1-device mesh — the
    pure-sync-overhead regime the probe itself refuses to measure.
    The gate is structurally guarded, not even consulted."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    with use_profile(_mk_profile({**ALL_OFF,
                                  "local_working_sets": True})):
        res = solve_mesh(x, y, CFG, num_devices=1)
    gates = res.stats["autotune"]["gates"]
    assert "local_working_sets" not in gates
    assert "shardlocal_demoted" not in res.stats
    assert res.converged


# ------------------------------------------------ zero-HLO-effect pin

def test_tpulint_zero_hlo_with_profile_installed():
    """The committed-budget contract with a profile INSTALLED: the
    manifest's lowered facts are identical under use_profile and still
    PASS the committed budget — the autotuner cannot change a compiled
    program, only which one a solve picks."""
    from dpsvm_tpu.analysis import budget
    from dpsvm_tpu.analysis.extract import entry_facts
    from dpsvm_tpu.analysis.manifest import (block_chunk_single,
                                             require_devices)

    require_devices()
    gen = budget.budget_jax_version()
    if gen is not None and gen != jax.__version__:
        pytest.skip(f"budgets generated under jax {gen}, running "
                    f"{jax.__version__} (the pinned CI job is the gate)")
    with use_profile(None):
        plain = entry_facts(block_chunk_single())
    with use_profile(_mk_profile(ALL_OFF)):
        installed = entry_facts(block_chunk_single())
    assert plain == installed
    assert budget.check_entry("block_chunk_single",
                              installed)["verdict"] == budget.PASS


# --------------------------------------------- bucket suggestion (obs)

def test_suggest_buckets_pure():
    from dpsvm_tpu.serving.dispatch import suggest_buckets

    cur = (16, 64, 256, 1024, 4096)
    out = suggest_buckets([], cur)
    assert out["suggested_buckets"] is None

    # Traffic of small requests through a coarse ladder: suggestion
    # right-sizes and the projected occupancy must not get worse.
    rows = [3, 5, 9, 12, 20, 28, 33, 60] * 16
    out = suggest_buckets(rows, cur)
    assert out["suggested_buckets"][-1] == 4096  # top bucket kept
    assert all(b & (b - 1) == 0 for b in out["suggested_buckets"])
    assert out["projected_occupancy"]["suggested"] \
        >= out["projected_occupancy"]["current"]
    assert out["observed_rows"]["dispatches"] == len(rows)
    assert "report-only" in out["note"]

    # Rows at the bucket edges stay in their bucket (occupancy 1.0).
    out2 = suggest_buckets([16] * 8, cur)
    assert out2["projected_occupancy"]["suggested"] == 1.0


def test_engine_reports_bucket_suggestion_and_gauge():
    """The engine's own telemetry: batch_rows feeds the suggestion and
    the /metrics exposition carries the report-only gauge."""
    from dpsvm_tpu.config import ServeConfig
    from dpsvm_tpu.serving import ServingEngine
    from tools.bench_serve import _synthetic_multiclass

    eng = ServingEngine(ServeConfig(buckets=(16, 64), warm_start=False))
    try:
        eng.register("m", _synthetic_multiclass(3, 8, 64, 0.5, "ovr",
                                                0.5, seed=2))
        rng = np.random.default_rng(0)
        for _ in range(4):
            eng.submit(rng.random((3, 8), dtype=np.float32), model="m")
        eng.drain()
        sug = eng.bucket_suggestion()
        assert sug["suggested_buckets"] is not None
        assert sug["current_buckets"] == [16, 64]
        text = eng.render_openmetrics()
        assert "serving_suggested_bucket{" in text
    finally:
        eng.close()
