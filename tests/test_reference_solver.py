"""NumPy oracle solver vs LibSVM (sklearn.svm.SVC) — the external parity
oracle the reference validated against by hand (README: "same number of
Support Vectors as LibSVM")."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.predict import accuracy, decision_function
from dpsvm_tpu.solver.reference import duality_gap, smo_reference


def _sk_svc(x, y, cfg: SVMConfig):
    from sklearn.svm import SVC
    gamma = cfg.resolve_gamma(x.shape[1])
    m = SVC(C=cfg.c, kernel=cfg.kernel, gamma=gamma, tol=cfg.epsilon,
            degree=cfg.degree, coef0=cfg.coef0)
    m.fit(x, y)
    return m


def test_oracle_matches_libsvm_on_blobs(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    res = smo_reference(x, y, cfg)
    assert res.converged
    sk = _sk_svc(x, y, cfg)

    # Support-vector count parity (the reference's headline check).
    assert abs(res.n_sv - len(sk.support_)) <= max(3, int(0.03 * len(sk.support_)))

    # Intercept: sklearn's decision is sum a_y K + intercept_; ours is
    # sum a_y K - b, so b ~ -intercept_.
    assert abs(res.b - (-sk.intercept_[0])) < 5e-2

    # Same objective: dual coefficients should agree closely.
    model = SVMModel.from_dense(x, y, res.alpha, res.b,
                                KernelParams("rbf", 0.1))
    ours = decision_function(model, x)
    theirs = sk.decision_function(x)
    np.testing.assert_allclose(ours, theirs, atol=5e-2)

    assert accuracy(model, x, y) == pytest.approx(sk.score(x, y), abs=0.01)


def test_oracle_kkt_and_gap(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=2.0, gamma=0.2, epsilon=1e-3, max_iter=100_000)
    res = smo_reference(x, y, cfg)
    assert res.converged
    alpha, f = res.alpha, res.stats["f"]
    c = cfg.c

    # 0 <= alpha <= C always.
    assert alpha.min() >= 0.0 and alpha.max() <= c + 1e-6

    # KKT at tolerance: b_lo - b_hi <= 2 eps.
    assert res.b_lo - res.b_hi <= 2 * cfg.epsilon + 1e-6

    # Duality gap (revived seq.cpp:352-376) is small and non-negative.
    gap = duality_gap(alpha, y, f, c, res.b)
    dual_obj = float(alpha.sum())
    assert gap >= -1e-3
    assert gap <= 0.05 * max(1.0, dual_obj)


def test_oracle_dual_objective_matches_libsvm(blobs_small):
    # The modified-SMO variant (like the reference, seq.cpp:243-246) clips
    # both pair alphas to [0, C] independently, so sum(alpha*y) == 0 is NOT
    # an invariant here — but the converged dual objective must still agree
    # with LibSVM's optimum.
    from sklearn.metrics.pairwise import rbf_kernel
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    res = smo_reference(x, y, cfg)
    assert res.converged
    sk = _sk_svc(x, y, cfg)

    k = rbf_kernel(x, x, gamma=0.1)

    def dual_obj(alpha):
        ay = alpha * y
        return float(alpha.sum() - 0.5 * ay @ k @ ay)

    ours = dual_obj(res.alpha.astype(np.float64))
    alpha_sk = np.zeros(len(y))
    alpha_sk[sk.support_] = np.abs(sk.dual_coef_[0])
    theirs = dual_obj(alpha_sk)
    assert ours == pytest.approx(theirs, rel=0.02)


def test_oracle_empty_iset_guard():
    # Single-class data: at alpha=0 the I_low set is empty (no y=+1 with
    # alpha>0, no y=-1 at all). Without the guard, argmax over the all-inf
    # masked f reads a finite junk value and the solver performs a bogus
    # pair update; with it, the iterate is recognized as optimal at once
    # (mirrors native/seqsmo.cpp's i_hi<0 || i_lo<0 break).
    rng = np.random.default_rng(7)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.ones(64, np.int32)
    res = smo_reference(x, y, SVMConfig(c=1.0, gamma=0.1, max_iter=1000))
    assert res.converged
    assert res.iterations == 0
    assert np.all(res.alpha == 0.0)


@pytest.mark.parametrize("kernel", ["linear", "poly", "sigmoid"])
def test_oracle_other_kernels_converge(blobs_small, kernel):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.05, kernel=kernel, degree=2, coef0=1.0,
                    epsilon=1e-3, max_iter=200_000)
    res = smo_reference(x, y, cfg)
    assert res.converged
    gamma = cfg.resolve_gamma(x.shape[1])
    model = SVMModel.from_dense(
        x, y, res.alpha, res.b, KernelParams(kernel, gamma, 2, 1.0))
    sk = _sk_svc(x, y, cfg.replace(gamma=gamma))
    # Accuracy should be in the same ballpark as libsvm's.
    assert accuracy(model, x, y) >= sk.score(x, y) - 0.03
