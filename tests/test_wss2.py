"""Second-order (WSS2) working-set selection tests: same optimum as the
reference-parity MVP rule, matching distributed trajectories."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=32, chunk_iters=256, selection="second_order")


def test_wss2_reaches_same_solution_as_mvp(blobs_small):
    x, y = blobs_small
    r2 = solve(x, y, CFG)
    r1 = solve(x, y, CFG.replace(selection="mvp"))
    assert r2.converged
    # Different trajectory, same optimum.
    assert abs(r2.b - r1.b) < 5e-2
    assert abs(r2.n_sv - r1.n_sv) <= max(3, 0.05 * r1.n_sv)
    assert r2.alpha.sum() == pytest.approx(r1.alpha.sum(), rel=0.02)


def test_wss2_matches_libsvm(blobs_small):
    from sklearn.svm import SVC
    x, y = blobs_small
    r = solve(x, y, CFG)
    sk = SVC(C=CFG.c, kernel="rbf", gamma=CFG.gamma, tol=CFG.epsilon).fit(x, y)
    assert abs(r.n_sv - len(sk.support_)) <= max(3, int(0.05 * len(sk.support_)))
    assert abs(r.b - (-sk.intercept_[0])) < 5e-2


@pytest.mark.parametrize("n_dev", [2, 8])
def test_wss2_mesh_matches_single_chip(blobs_small, n_dev):
    x, y = blobs_small
    r1 = solve(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=n_dev)
    assert rm.converged == r1.converged
    assert rm.iterations == r1.iterations
    assert rm.n_sv == r1.n_sv
    np.testing.assert_allclose(rm.alpha, r1.alpha, atol=1e-4)


def test_wss2_single_class_eligibility_guard():
    # Construct a state where no eligible j exists at some iteration end:
    # a tiny separable problem converges without the degenerate-update
    # no-op corrupting alpha.
    x = np.array([[0.0, 0], [0, 1], [5, 5], [5, 6]], np.float32)
    y = np.array([1, 1, -1, -1], np.int32)
    r = solve(x, y, CFG.replace(cache_lines=2, chunk_iters=8))
    assert r.converged
    assert (r.alpha >= 0).all() and (r.alpha <= CFG.c + 1e-6).all()
