"""Working-set selection unit tests vs brute-force I-set construction."""

import numpy as np
import jax.numpy as jnp

from dpsvm_tpu.ops.select import low_mask, select_working_set, up_mask


def _brute_sets(alpha, y, c):
    """Literal transcription of the Keerthi I-set definitions
    (seq.cpp:469-493) for cross-checking the mask algebra."""
    n = len(alpha)
    i_up, i_low = [], []
    for i in range(n):
        a, yi = alpha[i], y[i]
        in_i0 = 0 < a < c
        if in_i0 or (a == 0 and yi == 1) or (a == c and yi == -1):
            i_up.append(i)
        if in_i0 or (a == c and yi == 1) or (a == 0 and yi == -1):
            i_low.append(i)
    return i_up, i_low


def test_masks_match_brute_force():
    rng = np.random.default_rng(5)
    c = 2.0
    n = 200
    y = np.where(rng.random(n) < 0.5, 1, -1).astype(np.int32)
    # Mix of interior, 0, and C alphas.
    alpha = rng.choice([0.0, c, 0.7, 1.3], size=n).astype(np.float32)
    up_b, low_b = _brute_sets(alpha, y, c)
    up = np.asarray(up_mask(jnp.asarray(alpha), jnp.asarray(y), c))
    low = np.asarray(low_mask(jnp.asarray(alpha), jnp.asarray(y), c))
    assert sorted(np.nonzero(up)[0].tolist()) == up_b
    assert sorted(np.nonzero(low)[0].tolist()) == low_b


def test_select_picks_extrema():
    rng = np.random.default_rng(9)
    n = 500
    c = 1.0
    y = np.where(rng.random(n) < 0.5, 1, -1).astype(np.int32)
    alpha = rng.choice([0.0, c, 0.4], size=n).astype(np.float32)
    f = rng.normal(size=n).astype(np.float32)
    i_up, b_hi, i_low, b_lo = select_working_set(
        jnp.asarray(f), jnp.asarray(alpha), jnp.asarray(y), c)
    up_b, low_b = _brute_sets(alpha, y, c)
    assert int(i_up) == min(up_b, key=lambda i: (f[i], i))
    assert int(i_low) == min(low_b, key=lambda i: (-f[i], i))
    assert float(b_hi) == f[int(i_up)]
    assert float(b_lo) == f[int(i_low)]


def test_select_respects_valid_mask():
    # Padding rows carry extreme f values but must never be chosen.
    f = np.array([0.5, -9.0, 0.1, 9.0], np.float32)
    alpha = np.zeros(4, np.float32)
    y = np.array([1, 1, -1, -1], np.int32)
    valid = jnp.asarray([True, False, True, False])
    i_up, b_hi, i_low, b_lo = select_working_set(
        jnp.asarray(f), jnp.asarray(alpha), jnp.asarray(y), 1.0, valid)
    assert int(i_up) == 0 and float(b_hi) == np.float32(0.5)
    assert int(i_low) == 2 and float(b_lo) == np.float32(0.1)


def test_select_first_index_tie_break():
    f = np.array([1.0, -2.0, -2.0, 3.0, 3.0], np.float32)
    alpha = np.array([0.5] * 5, np.float32)
    y = np.ones(5, np.int32)
    i_up, _, i_low, _ = select_working_set(
        jnp.asarray(f), jnp.asarray(alpha), jnp.asarray(y), 1.0)
    assert int(i_up) == 1
    assert int(i_low) == 3
