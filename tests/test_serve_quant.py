"""int8 quantized serving hot path (ISSUE 17): the calibrated
union-storage guard (accept / refuse / fallback / auto semantics,
generalized over every feature kernel family), decision parity of the
dequant-fused int8 executor against the f32 path within the guard's
own bound, the mesh-sharded int8 union, mixed-storage union groups on
the v2 engine across a hot swap, the profile-gated bucket auto-apply,
and the committed int8 budget's mutation drift."""

import copy
import types
import warnings

import numpy as np
import pytest

from dpsvm_tpu.config import ServeConfig, SVMConfig
from dpsvm_tpu.models.multiclass import (decision_matrix,
                                         predict_multiclass,
                                         train_multiclass)
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import (BF16_RISK_THRESHOLD, KernelParams,
                                   dequantize_rows_int8,
                                   quantize_rows_int8,
                                   storage_perturbation)
from dpsvm_tpu.serve import (DEFAULT_BUCKETS, PredictServer,
                             resolve_buckets, resolve_union_storage,
                             stage_union_host, union_nbytes)
from dpsvm_tpu.serving import ServingEngine

KERNELS = {
    "linear": KernelParams("linear"),
    "rbf": KernelParams("rbf", 0.3),
    "poly": KernelParams("poly", 0.2, 3, 1.0),
    "sigmoid": KernelParams("sigmoid", 0.1, 0, 0.25),
}


def _binary(kp, n_sv=60, d=6, coef_scale=1.0, seed=0):
    rng = np.random.default_rng(seed)
    return SVMModel(
        sv_x=rng.normal(size=(n_sv, d)).astype(np.float32),
        sv_alpha=(rng.random(n_sv).astype(np.float32) + 0.01)
        * coef_scale,
        sv_y=np.where(rng.random(n_sv) < 0.5, 1, -1).astype(np.int32),
        b=0.05, kernel=kp)


@pytest.fixture(scope="module")
def three_class():
    rng = np.random.default_rng(31)
    xs, ys = [], []
    for k in range(3):
        c = np.zeros(5, np.float32)
        c[k] = 2.5
        xs.append(rng.normal(size=(70, 5)).astype(np.float32) * 0.7 + c)
        ys.append(np.full(70, k))
    x, y = np.concatenate(xs), np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.fixture(scope="module")
def trained(three_class):
    x, y = three_class
    cfg = SVMConfig(c=5.0, gamma=0.25, epsilon=1e-3, chunk_iters=256)
    m, _ = train_multiclass(x, y, cfg, strategy="ovr")
    return m, x


# ------------------------------------------ guard: accept per family

@pytest.mark.parametrize("kind", sorted(KERNELS))
def test_int8_accepted_and_close_per_kernel_family(kind):
    """A moderate-coefficient model accepts int8 on EVERY feature
    kernel family (the guard is no longer rbf-only), and the quantized
    decisions track the f32 path within the guard's own calibrated
    risk bound."""
    m = _binary(KERNELS[kind], coef_scale=0.05, seed=3)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # acceptance must be silent
        srv = PredictServer(m, ServeConfig(buckets=(32,),
                                           union_storage="int8"))
    assert srv.union_storage == "int8"
    guard = srv.stats["storage_guard"]
    assert guard["requested"] == "int8"
    assert guard["effective"] == "int8"
    assert guard["risks"]["int8"] <= guard["threshold"]

    from dpsvm_tpu.predict import decision_function

    rng = np.random.default_rng(5)
    q = rng.normal(size=(48, 6)).astype(np.float32)
    ref = np.asarray(decision_function(m, q)).ravel()
    got = np.asarray(srv.decision(q)).ravel()
    # The guard's contract: decision-sum perturbation is bounded by
    # max-column ||coef||_1 * p90|dK| (risk). Query quantization adds
    # one more rounding of the same magnitude — 4x covers the p90->max
    # gap of the sampled bound on every family here.
    tol = max(4.0 * guard["risks"]["int8"], 1e-4)
    assert np.max(np.abs(got - ref)) <= tol
    # Sign agreement wherever f32 is confidently off zero.
    confident = np.abs(ref) > tol
    assert np.array_equal(np.sign(got[confident]),
                          np.sign(ref[confident]))


def test_int8_refused_falls_back_loudly():
    """The bound ADJUDICATES for int8: a risky (large-coefficient)
    model is refused with a loud warning and falls back to the widest
    narrower storage the same bound accepts."""
    big = _binary(KERNELS["rbf"], n_sv=500, d=8, coef_scale=100.0,
                  seed=4)
    with pytest.warns(UserWarning, match="REFUSED"):
        srv = PredictServer(big, ServeConfig(buckets=(16,),
                                             union_storage="int8",
                                             warm_start=False))
    assert srv.union_storage in ("bf16", "f32")
    guard = srv.stats["storage_guard"]
    assert guard["requested"] == "int8"
    assert guard["effective"] != "int8"
    assert guard["risks"]["int8"] > BF16_RISK_THRESHOLD
    assert guard["note"].startswith("union_storage='int8' REFUSED")


def test_auto_picks_narrowest_silently(trained):
    """'auto' is a request to pick, not a promise: the narrowest
    accepted storage stages with NO warning either way."""
    m, _ = trained
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        srv = PredictServer(m, ServeConfig(buckets=(32,),
                                           union_storage="auto"))
    assert srv.union_storage == "int8"  # moderate model: int8 accepted

    big = _binary(KERNELS["rbf"], n_sv=500, d=8, coef_scale=100.0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        srv2 = PredictServer(big, ServeConfig(buckets=(16,),
                                              union_storage="auto",
                                              warm_start=False))
    assert srv2.union_storage == "f32"  # both narrow storages refused
    assert "auto storage" in srv2.stats["storage_guard"]["note"]


def test_precomputed_and_empty_unions_stay_f32():
    """No feature rows to round: precomputed-kernel ensembles and
    empty unions resolve to f32 whatever was requested."""
    pre = types.SimpleNamespace(
        sv_union=np.ones((5, 4), np.float32),
        coef=np.ones((5, 1), np.float32))
    st, entry = resolve_union_storage(pre, KernelParams("precomputed"),
                                      "int8")
    assert st == "f32" and "no feature rows" in entry["note"]

    empty = types.SimpleNamespace(
        sv_union=np.zeros((0, 4), np.float32),
        coef=np.zeros((0, 1), np.float32))
    st, entry = resolve_union_storage(empty, KernelParams("rbf", 0.5),
                                      "auto")
    assert st == "f32" and "no feature rows" in entry["note"]


def test_unknown_storage_rejected(trained):
    m, _ = trained
    with pytest.raises(ValueError, match="unknown union storage"):
        resolve_union_storage(m.compacted, KernelParams("rbf", 0.5),
                              "fp4")


# ------------------------------------- staging algebra + byte account

def test_stage_union_host_int8_invariants():
    """Staged int8 rows round-trip through the published algebra:
    values = round(row/scale) in [-127, 127], scale = max|row|/127,
    and the squared norms come from the DEQUANTIZED rows the dot
    operands actually carry (norms-from-rounded discipline)."""
    rng = np.random.default_rng(11)
    sv = rng.normal(size=(40, 7)).astype(np.float32) * \
        rng.gamma(1.0, 5.0, size=(40, 1)).astype(np.float32)
    sv[3] = 0.0  # all-zero row: scale must be 1.0, not 0/0
    store, scales, sq = stage_union_host(sv, "int8")
    assert store.dtype == np.int8 and scales.dtype == np.float32
    q, s = quantize_rows_int8(sv)
    np.testing.assert_array_equal(store, q)
    np.testing.assert_array_equal(scales, s)
    assert s[3] == 1.0 and not store[3].any()
    deq = dequantize_rows_int8(q, s)
    np.testing.assert_allclose(sq, (deq * deq).sum(1), rtol=1e-6)
    # Per-row quantization error is bounded by scale/2 per element.
    assert np.max(np.abs(deq - sv)) <= (s.max() / 2) + 1e-6
    # The gauge arithmetic: int8 rows + f32 scales vs 4-byte rows.
    # The near-4x cut needs d large enough to amortize the per-row
    # scale (at covtype's d=54: 58 bytes/row vs 216).
    assert union_nbytes("int8", 40, 7) == 40 * 7 + 4 * 40
    assert union_nbytes("f32", 40, 7) == 40 * 7 * 4
    assert union_nbytes("int8", 40, 54) * 3 < union_nbytes("f32", 40, 54)


def test_storage_perturbation_orders():
    """The sampler the guard scales: int8 perturbs at least as much as
    bf16 on the same pair population, and f32 is exactly zero."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(300, 8)).astype(np.float32)
    kp = KernelParams("rbf", 0.4)
    assert storage_perturbation(x, kp, "f32") == 0.0
    b = storage_perturbation(x, kp, "bf16")
    i = storage_perturbation(x, kp, "int8")
    assert 0.0 < b and 0.0 < i
    with pytest.raises(ValueError, match="unknown union storage"):
        storage_perturbation(x, kp, "fp8")


# ----------------------------------------------------------- mesh path

def test_mesh_int8_matches_single_device(trained):
    """The mesh-sharded int8 union (rows AND scales sharded together,
    one psum) answers within float tolerance of the single-device int8
    executor — quantization adds converts, never collectives or
    drift."""
    m, x = trained
    q = np.asarray(x[:40], np.float32)
    single = PredictServer(m, ServeConfig(buckets=(64,),
                                          union_storage="int8"))
    mesh = PredictServer(m, ServeConfig(buckets=(64,), num_devices=8,
                                        union_storage="int8"))
    assert single.union_storage == mesh.union_storage == "int8"
    np.testing.assert_allclose(mesh.decision(q), single.decision(q),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(mesh.predict(q),
                                  predict_multiclass(m, q))


# ------------------------------------------- v2 engine: mixed storage

def test_engine_mixed_storage_groups_and_hot_swap(trained):
    """One engine, one requested storage, two verdicts: the guard
    resolves per MODEL, the storage token is part of the union-group
    key (different verdicts stage in different groups), and a hot swap
    between storage dtypes restages correctly."""
    m, x = trained
    risky = _binary(KERNELS["rbf"], n_sv=500, d=5, coef_scale=100.0,
                    seed=9)
    eng = ServingEngine(ServeConfig(buckets=(16, 64),
                                    union_storage="int8"))
    try:
        eng.register("good", m)
        with pytest.warns(UserWarning, match="REFUSED"):
            eng.register("risky", risky)
        snap = eng.snapshot()
        assert snap["union_storage"]["good"] == "int8"
        assert snap["union_storage"]["risky"] in ("bf16", "f32")
        assert snap["quantized_unions"] >= 1
        # Different storages NEVER share a union group.
        stores = {g.union_storage for g in eng._groups.values()}
        assert "int8" in stores and len(eng._groups) >= 2

        q = np.asarray(x[:30], np.float32)
        np.testing.assert_allclose(eng.decision(q, model="good"),
                                   decision_matrix(m, q),
                                   rtol=0.02, atol=0.02)

        # Swap "good" for a risky retrain: the new version's guard
        # refuses int8 and the entry restages under the wider key.
        risky5 = _binary(KernelParams("rbf", 0.25), n_sv=400, d=5,
                         coef_scale=100.0, seed=12)
        with pytest.warns(UserWarning, match="REFUSED"):
            eng.swap("good", risky5)
        snap = eng.snapshot()
        assert snap["union_storage"]["good"] != "int8"
        assert eng.hot_swaps.value == 1
    finally:
        eng.close()


# ------------------------------------------ profile-gated auto-apply

def _serve_buckets_profile(verdict, authoritative=True):
    import jax

    from dpsvm_tpu.autotune import DeviceProfile

    return DeviceProfile(
        device_kind="cpu", backend="cpu", n_devices=8,
        jax=jax.__version__, utc="2026-08-04T00:00:00Z",
        git_sha="deadbeef", seed=0,
        probes={"serve_buckets": {
            "probe": "serve_buckets", "knob": "serve_buckets",
            "seed": 0, "shapes": {"s_rows": 256},
            "a_seconds": 1.0, "b_seconds": 0.5, "ratio": 0.5,
            "threshold": 0.9, "authoritative": authoritative,
            "verdict": bool(verdict)}},
        decisions={"serve_buckets": bool(verdict)})


def test_resolve_buckets_provenance():
    """Explicit config ALWAYS wins (no profile consulted); buckets=None
    consults the graduated serve_buckets gate; no profile means
    default ladder with auto_apply False."""
    from dpsvm_tpu.autotune import use_profile

    ladder, prov = resolve_buckets(ServeConfig(buckets=(16, 64)))
    assert ladder == (16, 64) and prov["source"] == "config"
    assert "auto_apply" not in prov

    with use_profile(None):
        ladder, prov = resolve_buckets(ServeConfig(buckets=None))
    assert ladder == DEFAULT_BUCKETS
    assert prov["source"] == "default" and prov["auto_apply"] is False

    with use_profile(_serve_buckets_profile(True)):
        ladder, prov = resolve_buckets(ServeConfig(buckets=None))
    assert ladder == DEFAULT_BUCKETS  # the ladder STARTS default
    assert prov["source"] == "profile" and prov["auto_apply"] is True

    with use_profile(_serve_buckets_profile(False)):
        _, prov = resolve_buckets(ServeConfig(buckets=None))
    assert prov["auto_apply"] is False  # honesty rule: CPU pins False


def test_engine_auto_applies_buckets_between_legs(trained):
    """buckets=None + an authoritative pays-verdict profile: the
    engine applies its own occupancy suggestion at the drain() leg
    boundary, records the applied ladder in the provenance, and keeps
    answering correctly from the restaged groups."""
    from dpsvm_tpu.autotune import use_profile

    m, x = trained
    q = np.asarray(x[:3], np.float32)
    with use_profile(_serve_buckets_profile(True)):
        eng = ServingEngine(ServeConfig(buckets=None))
        try:
            assert eng.bucket_provenance["auto_apply"] is True
            eng.register("m", m)
            for _ in range(6):  # 3-row traffic under a 16.. ladder
                eng.decision(q)
            eng.drain()
            prov = eng.snapshot()["bucket_provenance"]
            assert prov["applied_buckets"] == \
                prov["suggestion"]["suggested_buckets"]
            assert prov["applied_buckets"][0] == 4  # pow2 above p25=3
            assert tuple(prov["applied_buckets"]) == eng._bucket_ladder
            # The restaged ladder still serves the same answers.
            np.testing.assert_allclose(eng.decision(q),
                                       decision_matrix(m, q),
                                       rtol=1e-5, atol=1e-5)
        finally:
            eng.close()


def test_engine_explicit_buckets_never_auto_apply(trained):
    """An explicit ladder is an operator decision: no auto-apply even
    with the pays-verdict profile installed."""
    from dpsvm_tpu.autotune import use_profile

    m, x = trained
    q = np.asarray(x[:3], np.float32)
    with use_profile(_serve_buckets_profile(True)):
        eng = ServingEngine(ServeConfig(buckets=(16, 64)))
        try:
            eng.register("m", m)
            for _ in range(6):
                eng.decision(q)
            eng.drain()
            assert eng.maybe_apply_bucket_suggestion() is None
            prov = eng.snapshot()["bucket_provenance"]
            assert prov["source"] == "config"
            assert "applied_buckets" not in prov
            assert eng._bucket_ladder == (16, 64)
        finally:
            eng.close()


# --------------------------------------------- budget mutation drift

def test_int8_budget_pins_convert_structure(tmp_path):
    """The committed serve_bucket_int8 budget is mutation-sensitive:
    re-extracted facts PASS against a fresh write, and perturbing an
    int8 convert count (as an extra quantization point would) DRIFTs
    naming the exact fact."""
    from dpsvm_tpu.analysis import budget, manifest
    from dpsvm_tpu.analysis.extract import entry_facts

    facts = entry_facts(manifest.serve_bucket_int8())
    dt = facts["units"]["batch"]["dtypes"]
    # The algebra's exact quantization points (manifest docstring).
    assert dt["f32_to_int8_converts"] == 2
    assert dt["int8_to_f32_converts"] == 1
    assert dt["i32_to_f32_converts"] == 1
    budget.write_budget("serve_bucket_int8", facts, tmp_path)
    assert budget.check_entry("serve_bucket_int8", facts,
                              tmp_path)["verdict"] == budget.PASS

    drifted = copy.deepcopy(facts)
    drifted["units"]["batch"]["dtypes"]["f32_to_int8_converts"] += 1
    res = budget.check_entry("serve_bucket_int8", drifted, tmp_path)
    assert res["verdict"] == budget.DRIFT
    assert any(p == "units.batch.dtypes.f32_to_int8_converts"
               for p, _, _ in res["diffs"])
