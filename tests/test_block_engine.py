"""Blockwise working-set (decomposition) engine: same optimum as the
per-pair engines, KKT at convergence, and XLA/Pallas subproblem parity.

The block engine takes a different path through iterate space (pairs are
restricted to the current working set between refreshes) so trajectories
are NOT comparable — the contracts tested here are about the fixed point:
identical dual objective, intercept, decision function and KKT residuals.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, max_iter=200_000)


def dual_objective(x, y, alpha, kp):
    K = np.asarray(kernel_matrix(x, x, kp))
    ay = alpha * y
    return alpha.sum() - 0.5 * ay @ K @ ay


def kkt_violation(x, y, alpha, c_pos, c_neg, kp):
    """max over I_up/I_low pairs of (b_lo - b_hi): <= 2 eps at convergence."""
    K = np.asarray(kernel_matrix(x, x, kp))
    f = (alpha * y) @ K - y
    c_i = np.where(y > 0, c_pos, c_neg)
    up = np.where(y > 0, alpha < c_i - 1e-9, alpha > 1e-9)
    low = np.where(y > 0, alpha > 1e-9, alpha < c_i - 1e-9)
    return f[low].max() - f[up].min()


@pytest.mark.parametrize("q", [8, 32, 128])
def test_block_matches_per_pair_optimum(blobs_small, q):
    x, y = blobs_small
    kp = KernelParams("rbf", CFG.gamma)
    r_ref = solve(x, y, CFG)
    r_blk = solve(x, y, CFG.replace(engine="block", working_set_size=q))
    assert r_blk.converged
    assert r_blk.stats["outer_rounds"] > 0
    obj_ref = dual_objective(x, y, r_ref.alpha, kp)
    obj_blk = dual_objective(x, y, r_blk.alpha, kp)
    assert obj_blk == pytest.approx(obj_ref, rel=1e-4)
    assert r_blk.b == pytest.approx(r_ref.b, abs=5e-3)
    # Equality constraint conserved exactly by the pair algebra.
    assert abs(np.dot(r_blk.alpha, y)) < 1e-3


def test_block_kkt_at_convergence(blobs_medium):
    x, y = blobs_medium
    cfg = CFG.replace(engine="block", working_set_size=64)
    r = solve(x, y, cfg)
    assert r.converged
    viol = kkt_violation(x, y, r.alpha, cfg.c, cfg.c, KernelParams("rbf", cfg.gamma))
    assert viol <= 2 * cfg.epsilon + 1e-4


def test_block_linear_kernel(blobs_small):
    x, y = blobs_small
    cfg = CFG.replace(kernel="linear", engine="block", working_set_size=32)
    r_blk = solve(x, y, cfg)
    r_ref = solve(x, y, cfg.replace(engine="xla"))
    assert r_blk.converged and r_ref.converged
    kp = KernelParams("linear", cfg.gamma)
    assert dual_objective(x, y, r_blk.alpha, kp) == pytest.approx(
        dual_objective(x, y, r_ref.alpha, kp), rel=1e-4)


def test_block_class_weights(blobs_small):
    x, y = blobs_small
    cfg = CFG.replace(weight_pos=2.0, weight_neg=0.5,
                      engine="block", working_set_size=32)
    r = solve(x, y, cfg)
    assert r.converged
    # Box respected per class.
    cp, cn = cfg.c_bounds()
    assert np.all(r.alpha[y > 0] <= cp + 1e-5)
    assert np.all(r.alpha[y < 0] <= cn + 1e-5)
    viol = kkt_violation(x, y, r.alpha, cp, cn, KernelParams("rbf", cfg.gamma))
    assert viol <= 2 * cfg.epsilon + 1e-4


def test_block_q_larger_than_n():
    from dpsvm_tpu.data.synth import make_blobs_binary
    x, y = make_blobs_binary(n=40, d=5, seed=0, sep=1.0)
    r = solve(x, y, CFG.replace(engine="block", working_set_size=512))
    assert r.converged


def test_pallas_subproblem_matches_xla(blobs_small):
    """The on-core Pallas subproblem solve (interpret mode on CPU) must
    reproduce the XLA while_loop subproblem exactly."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import _solve_subproblem, select_block

    x, y = blobs_small
    kp = KernelParams("rbf", 0.2)
    n = x.shape[0]
    rng = np.random.default_rng(0)
    alpha = np.clip(rng.normal(0.5, 0.5, n), 0, CFG.c).astype(np.float32)
    K = np.asarray(kernel_matrix(x, x, kp))
    f = ((alpha * y) @ K - y).astype(np.float32)

    q = 32
    w, ok, _, _ = select_block(jnp.asarray(f), jnp.asarray(alpha),
                         jnp.asarray(y, jnp.float32), CFG.c, q)
    w_np = np.asarray(w)
    kb_w = jnp.asarray(K[np.ix_(w_np, w_np)].astype(np.float32))
    kd_w = jnp.asarray(np.diag(K)[w_np].astype(np.float32))
    a_w = jnp.asarray(alpha[w_np])
    y_w = jnp.asarray(y[w_np].astype(np.float32))
    f_w = jnp.asarray(f[w_np])

    a_xla, _, t_xla = _solve_subproblem(
        kb_w, kd_w, ok, a_w, y_w, f_w, CFG.c, CFG.epsilon, CFG.tau,
        jnp.int32(64))
    a_pl, t_pl = solve_subproblem_pallas(
        kb_w, a_w, y_w, f_w, kd_w, ok.astype(jnp.float32), jnp.int32(64),
        CFG.c, CFG.epsilon, CFG.tau, interpret=True)
    assert int(t_xla) == int(t_pl)
    np.testing.assert_allclose(np.asarray(a_xla), np.asarray(a_pl),
                               rtol=1e-6, atol=1e-7)


def test_block_wss2_matches_per_pair_optimum(blobs_small):
    """engine='block' + selection='second_order' (WSS2 j-selection inside
    the subproblem, nearly free since K(W,W) is resident) reaches the same
    fixed point as the per-pair engine."""
    x, y = blobs_small
    kp = KernelParams("rbf", CFG.gamma)
    r_ref = solve(x, y, CFG)
    r_w2 = solve(x, y, CFG.replace(engine="block", working_set_size=32,
                                   selection="second_order"))
    assert r_w2.converged
    assert r_w2.stats["outer_rounds"] > 0
    assert dual_objective(x, y, r_w2.alpha, kp) == pytest.approx(
        dual_objective(x, y, r_ref.alpha, kp), rel=1e-4)
    assert r_w2.b == pytest.approx(r_ref.b, abs=5e-3)
    viol = kkt_violation(x, y, r_w2.alpha, CFG.c, CFG.c, kp)
    assert viol <= 2 * CFG.epsilon + 1e-4


@pytest.mark.parametrize("rule", ["mvp", "second_order", "nu"])
def test_pallas_subproblem_rules_match_xla(blobs_small, rule):
    """Every subproblem pairing rule must agree between the XLA while_loop
    and the Pallas kernel (interpret mode on CPU): same pair count, same
    final alpha."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas
    from dpsvm_tpu.solver.block import _solve_subproblem, select_block

    x, y = blobs_small
    kp = KernelParams("rbf", 0.2)
    n = x.shape[0]
    rng = np.random.default_rng(1)
    alpha = np.clip(rng.normal(0.5, 0.5, n), 0, CFG.c).astype(np.float32)
    K = np.asarray(kernel_matrix(x, x, kp))
    f = ((alpha * y) @ K - y).astype(np.float32)

    q = 32
    w, ok, _, _ = select_block(jnp.asarray(f), jnp.asarray(alpha),
                         jnp.asarray(y, jnp.float32), CFG.c, q,
                         rule=rule)
    w_np = np.asarray(w)
    kb_w = jnp.asarray(K[np.ix_(w_np, w_np)].astype(np.float32))
    kd_w = jnp.asarray(np.diag(K)[w_np].astype(np.float32))
    a_w = jnp.asarray(alpha[w_np])
    y_w = jnp.asarray(y[w_np].astype(np.float32))
    f_w = jnp.asarray(f[w_np])

    a_xla, _, t_xla = _solve_subproblem(
        kb_w, kd_w, ok, a_w, y_w, f_w, CFG.c, CFG.epsilon, CFG.tau,
        jnp.int32(64), rule=rule)
    a_pl, t_pl = solve_subproblem_pallas(
        kb_w, a_w, y_w, f_w, kd_w, ok.astype(jnp.float32), jnp.int32(64),
        CFG.c, CFG.epsilon, CFG.tau, rule=rule, interpret=True)
    assert int(t_xla) > 0
    assert int(t_xla) == int(t_pl)
    np.testing.assert_allclose(np.asarray(a_xla), np.asarray(a_pl),
                               rtol=1e-6, atol=1e-7)


def test_block_checkpoint_resume(tmp_path, blobs_small):
    x, y = blobs_small
    path = str(tmp_path / "blk.npz")
    cfg = CFG.replace(engine="block", working_set_size=16,
                      checkpoint_every=32, chunk_iters=32, max_iter=64)
    r1 = solve(x, y, cfg, checkpoint_path=path)
    assert not r1.converged  # capped
    cfg2 = cfg.replace(max_iter=200_000)
    r2 = solve(x, y, cfg2, checkpoint_path=path, resume=True)
    assert r2.converged
    assert r2.iterations > r1.iterations
    # Resumed run still reaches the right optimum.
    r_ref = solve(x, y, CFG)
    kp = KernelParams("rbf", CFG.gamma)
    assert dual_objective(x, y, r2.alpha, kp) == pytest.approx(
        dual_objective(x, y, r_ref.alpha, kp), rel=1e-3)


def test_block_respects_max_iter_cap(blobs_small):
    """Total pair updates must never exceed max_iter (the inner budget is
    clamped to the remaining global budget each round)."""
    x, y = blobs_small
    r = solve(x, y, CFG.replace(engine="block", working_set_size=64,
                                max_iter=10))
    assert r.iterations == 10
    assert not r.converged


def test_select_block_filler_does_not_mask_low_candidates():
    """When I_up runs short, top_k filler indices must not shadow live
    low-half violators (regression: the dup mask compared against filler
    slots and could hide the global max violator)."""
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import select_block

    # 8 points: only idx 5 in I_up (y=+1, alpha<C); idx 0 is the top
    # I_low violator (y=-1, alpha<C, largest f).
    y = jnp.asarray([-1.0, -1.0, -1.0, -1.0, -1.0, 1.0, -1.0, -1.0])
    alpha = jnp.asarray([0.0] * 8)
    f = jnp.asarray([5.0, 1.0, 1.0, 1.0, 1.0, -3.0, 1.0, 1.0])
    w, ok, _, _ = select_block(f, alpha, y, 1.0, 8)
    w, ok = map(lambda a: list(map(int, a)), (w, ok))
    # idx 0 must be a LIVE low-half slot.
    low_live = [wi for wi, oki in zip(w[4:], ok[4:]) if oki]
    assert 0 in low_live


def test_active_block_matches_plain_optimum(blobs_medium):
    """The active-set (shrinking) variant must reach the SAME optimum as
    the plain block engine — shrinking defers the non-active rows' linear
    f updates, it never changes the math — across small/large active sets
    (m >= n still restricts each side to m/2 slots) and reconcile
    cadences, with and without class weights."""
    x, y = blobs_medium
    base = CFG.replace(engine="block", working_set_size=32)
    rb = solve(x, y, base)

    def obj(r):
        a, f = r.alpha, r.stats["f"]
        return float(a.sum() - 0.5 * np.sum(a * y * (f + y)))

    for m, k in [(64, 4), (256, 2), (4096, 8)]:
        ra = solve(x, y, base.replace(active_set_size=m, reconcile_rounds=k))
        assert ra.converged
        # Both engines stop at eps-approximate optima via different pair
        # sequences, so borderline SVs may legitimately differ by a few.
        assert abs(ra.n_sv - rb.n_sv) <= max(2, 0.01 * rb.n_sv)
        assert abs(ra.b - rb.b) < 5e-3
        assert abs(obj(ra) - obj(rb)) <= 1e-3 * abs(obj(rb))

    w = base.replace(weight_pos=2.0, weight_neg=0.5)
    rw = solve(x, y, w)
    ra = solve(x, y, w.replace(active_set_size=128, reconcile_rounds=8))
    assert ra.converged
    assert abs(obj(ra) - obj(rw)) <= 1e-3 * abs(obj(rw))


def test_active_block_budget_cap_exact(blobs_medium):
    """Shrinking must respect max_iter exactly (the inner limit is
    clamped to the remaining budget), and a budget exit must report
    refreshed, non-stale extrema (extrema_np path)."""
    from dpsvm_tpu.ops.select import extrema_np

    x, y = blobs_medium
    r = solve(x, y, CFG.replace(engine="block", working_set_size=32,
                                active_set_size=64, max_iter=37))
    assert r.iterations == 37
    assert not r.converged
    b_hi, b_lo = extrema_np(r.stats["f"], r.alpha, y, CFG.c)
    assert r.b_hi == b_hi and r.b_lo == b_lo


def test_active_block_rejected_on_nonblock_engines():
    """Loud failures, not silent ignores: shrinking needs the block
    engine's cycle structure (mesh acceptance is covered in
    test_dist_smo.py)."""
    import pytest

    from dpsvm_tpu.config import SVMConfig

    with pytest.raises(ValueError, match="block-engine knob"):
        SVMConfig(engine="xla", active_set_size=64)


def test_select_block_extrema_match_canonical_selectors():
    """The b_hi/b_lo riding select_block's top-k pass ARE the stopping
    extrema: they must equal select_working_set(_nu)'s over randomized
    states (bound-saturated alphas included), and the host-side
    extrema_np refresh must agree with both (regression guard: a sign or
    axis slip here would silently burn the iteration budget — the device
    loop would never see the gap close)."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.select import (extrema_np, select_working_set,
                                      select_working_set_nu)
    from dpsvm_tpu.solver.block import select_block

    rng = np.random.default_rng(5)
    for seed in range(6):
        n = 160
        c = (4.0, 2.5) if seed % 2 else 3.0
        cp, cn = c if isinstance(c, tuple) else (c, c)
        y = np.where(rng.random(n) > 0.5, 1.0, -1.0).astype(np.float32)
        # Mass at both bounds so I-set membership edges are exercised.
        alpha = rng.choice(
            [0.0, 1.0, -1.0], n, p=[0.4, 0.3, 0.3]).astype(np.float32)
        alpha = np.where(alpha < 0, np.where(y > 0, cp, cn),
                         np.where(alpha > 0, rng.random(n) *
                                  np.where(y > 0, cp, cn), 0.0))
        alpha = alpha.astype(np.float32)
        f = rng.normal(0, 2, n).astype(np.float32)
        fj, aj, yj = map(jnp.asarray, (f, alpha, y))

        _, bh_ref, _, bl_ref = select_working_set(fj, aj, yj, c)
        _, _, bh, bl = select_block(fj, aj, yj, c, 16)
        assert float(bh) == float(bh_ref) and float(bl) == float(bl_ref)
        assert extrema_np(f, alpha, y, c) == (float(bh_ref), float(bl_ref))

        _, bh_ref, _, bl_ref = select_working_set_nu(fj, aj, yj, c)
        _, _, bh, bl = select_block(fj, aj, yj, c, 16, rule="nu")
        assert float(bh) == float(bh_ref) and float(bl) == float(bl_ref)
        assert extrema_np(f, alpha, y, c, rule="nu") == (
            float(bh_ref), float(bl_ref))

    # Empty I_up: every +1 point at its bound, every -1 point at 0 —
    # extrema must read as a closed gap (inf sentinels), not junk.
    y = np.array([1.0, 1.0, -1.0, -1.0], np.float32)
    alpha = np.array([3.0, 3.0, 0.0, 0.0], np.float32)
    f = np.arange(4, dtype=np.float32)
    _, _, bh, bl = select_block(*map(jnp.asarray, (f, alpha, y)), 3.0, 4)
    assert float(bh) == np.inf
    assert extrema_np(f, alpha, y, 3.0)[0] == np.inf


def test_reductions_compose_with_block_engine(blobs_small):
    """SVR (2n-variable expansion), one-class (alpha starting AT the
    bound) and multiclass all run on the block engine via alpha_init/
    f_init and reach the same optimum as the per-pair engine."""
    from dpsvm_tpu.models.multiclass import train_multiclass
    from dpsvm_tpu.models.oneclass import train_oneclass
    from dpsvm_tpu.models.svr import train_svr

    x, y = blobs_small
    rng = np.random.default_rng(5)
    z = np.sin(x[:, 0]) + 0.1 * rng.normal(size=x.shape[0]).astype(np.float32)

    cfg = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, max_iter=200_000)
    cfg_blk = cfg.replace(engine="block", working_set_size=16)

    m_x, r_x = train_svr(x, z, cfg, backend="single")
    m_b, r_b = train_svr(x, z, cfg_blk, backend="single")
    assert r_b.converged
    np.testing.assert_allclose(m_b.predict(x), m_x.predict(x), atol=5e-2)

    o_x, s_x = train_oneclass(x, nu=0.3, config=cfg, backend="single")
    o_b, s_b = train_oneclass(x, nu=0.3, config=cfg_blk, backend="single")
    assert s_b.converged
    # Same dual optimum: objective 1/2 a^T K a (sum alpha is conserved by
    # construction, so compare the part that distinguishes optima), plus
    # the offset and decision values.
    K = np.asarray(kernel_matrix(x, x, KernelParams("rbf", cfg.gamma)))
    assert 0.5 * s_b.alpha @ K @ s_b.alpha == pytest.approx(
        0.5 * s_x.alpha @ K @ s_x.alpha, rel=1e-4)
    assert o_b.rho == pytest.approx(o_x.rho, abs=5e-3)
    np.testing.assert_allclose(o_b.decision_function(x),
                               o_x.decision_function(x), atol=5e-3)

    # Multiclass (3 synthetic classes) through the same engine config.
    from dpsvm_tpu.models.multiclass import predict_multiclass

    y3 = (np.asarray(y) > 0).astype(int) + (x[:, 0] > 0.5).astype(int)
    mc_b, _ = train_multiclass(x, y3, cfg_blk, strategy="ovr",
                               backend="single")
    mc_x, _ = train_multiclass(x, y3, cfg, strategy="ovr", backend="single")
    agree = float(np.mean(predict_multiclass(mc_b, x) == predict_multiclass(mc_x, x)))
    assert agree > 0.98
