"""Mid-scale LibSVM parity (reference README.md:27: "same number of
Support Vectors as LibSVM") at the reference's own pinned hyperparameters
(reference Makefile:74,86), beyond the toy sizes of the other tests.

SV-count parity is sensitive near the alpha bounds precisely at scale
(SURVEY.md section 7.3 item 3) — these runs are the in-suite guard for
that; the full 8-10k harness with real-TPU single-chip runs is
`python tools/parity.py` (writes PARITY.md, including the methodology:
duplicate-merged SV counts, SV assertion at the reference parity claim's
eps=0.001, decision-sign agreement at the pinned configs).

Marked slow: several minutes of CPU; deselect with `-m "not slow"`.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.predict import decision_function
from dpsvm_tpu.solver.smo import solve

SV_TOL = 0.01
SIGN_TOL = 0.998

pytestmark = pytest.mark.slow


def _fit_libsvm(x, y, cfg):
    from sklearn.svm import SVC
    return SVC(C=cfg.c, gamma=cfg.gamma, tol=cfg.epsilon,
               cache_size=1000).fit(x, y)


def _merged_sv(alpha, group):
    """SV count after summing alpha over duplicate (row, label) groups —
    with duplicated rows the dual optimum is a face and the raw per-row
    count is solver-path-dependent (see tools/parity.py)."""
    s = np.zeros(group.max() + 1)
    np.add.at(s, group, np.abs(alpha))
    return int((s > 0).sum())


def _dup_groups(x, y):
    _, inv = np.unique(x, axis=0, return_inverse=True)
    return inv.astype(np.int64) * 2 + (y > 0)


def _check_agreement(x, y, cfg, sk, res):
    assert res.converged
    kp = KernelParams("rbf", cfg.resolve_gamma(x.shape[1]))
    model = SVMModel.from_dense(x, y, res.alpha, res.b, kp)
    dec = decision_function(model, x)
    agree = float(np.mean(np.sign(dec) == np.sign(sk.decision_function(x))))
    assert agree >= SIGN_TOL, f"decision-sign agreement {agree:.4f}"


def _check_sv_parity(x, y, sk, res):
    group = _dup_groups(x, y)
    a_sk = np.zeros(len(y))
    a_sk[sk.support_] = np.abs(sk.dual_coef_[0])
    ours = _merged_sv(res.alpha, group)
    theirs = _merged_sv(a_sk, group)
    assert abs(ours - theirs) <= SV_TOL * theirs, (
        f"merged SV count {ours} vs LibSVM {theirs}")


MNIST_PINNED = SVMConfig(c=10.0, gamma=0.125, epsilon=0.01,
                         max_iter=2_000_000, engine="block",
                         working_set_size=128)
MNIST_CLAIM = MNIST_PINNED.replace(epsilon=1e-3)


@pytest.fixture(scope="module")
def mnist_shaped():
    from dpsvm_tpu.data.synth import make_mnist_like
    x, y = make_mnist_like(n=4000, d=784, seed=7, noise=0.1)
    return x, y


@pytest.fixture(scope="module")
def mnist_sk_pinned(mnist_shaped):
    x, y = mnist_shaped
    return _fit_libsvm(x, y, MNIST_PINNED)


@pytest.fixture(scope="module")
def adult_shaped():
    from dpsvm_tpu.data.synth import make_adult_like
    x, y = make_adult_like(n=4000, d=123, seed=13)
    cfg = SVMConfig(c=100.0, gamma=0.5, epsilon=1e-3, max_iter=2_000_000)
    return x, y, cfg, _fit_libsvm(x, y, cfg)


@pytest.mark.parametrize("backend", ["single", "mesh8"])
def test_mnist_shaped_pinned_agreement(mnist_shaped, mnist_sk_pinned,
                                       backend):
    """Reference MNIST config (c=10 gamma=0.125 eps=0.01, Makefile:74):
    judged on decision agreement — the loose eps leaves the SV set
    underdetermined (see tools/parity.py)."""
    x, y = mnist_shaped
    if backend == "mesh8":
        res = solve_mesh(x, y, MNIST_PINNED, num_devices=8)
    else:
        res = solve(x, y, MNIST_PINNED)
    _check_agreement(x, y, MNIST_PINNED, mnist_sk_pinned, res)


def test_mnist_shaped_sv_parity_at_claim_eps(mnist_shaped):
    """SV-count parity at eps=0.001 — the tolerance of the reference's
    own "same number of SVs as LibSVM" claim (README.md:23,27)."""
    x, y = mnist_shaped
    sk = _fit_libsvm(x, y, MNIST_CLAIM)
    res = solve(x, y, MNIST_CLAIM)
    _check_agreement(x, y, MNIST_CLAIM, sk, res)
    _check_sv_parity(x, y, sk, res)


def test_adult_shaped_per_pair_parity(adult_shaped):
    x, y, cfg, sk = adult_shaped
    res = solve(x, y, cfg)  # engine="xla": reference-parity per-pair path
    _check_agreement(x, y, cfg, sk, res)
    _check_sv_parity(x, y, sk, res)


@pytest.mark.parametrize("backend", ["single", "mesh8"])
def test_adult_shaped_block_parity(adult_shaped, backend):
    x, y, cfg, sk = adult_shaped
    bcfg = cfg.replace(engine="block", working_set_size=128)
    if backend == "mesh8":
        res = solve_mesh(x, y, bcfg, num_devices=8)
    else:
        res = solve(x, y, bcfg)
    _check_agreement(x, y, bcfg, sk, res)
    _check_sv_parity(x, y, sk, res)
