"""Multiclass (OvR / OvO) reduction tests vs sklearn's multiclass SVC."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.multiclass import (
    MulticlassSVM,
    accuracy_multiclass,
    predict_multiclass,
    train_multiclass,
)

CFG = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, max_iter=100_000,
                cache_lines=32, chunk_iters=256)


@pytest.fixture(scope="module")
def three_class():
    rng = np.random.default_rng(17)
    n_per = 150
    centers = np.array([[2.0, 0, 0, 0], [0, 2.0, 0, 0], [0, 0, 2.0, 0]],
                       np.float32)
    xs, ys = [], []
    for k in range(3):
        xs.append(rng.normal(size=(n_per, 4)).astype(np.float32) * 0.8 + centers[k])
        ys.append(np.full(n_per, k + 3))  # labels 3,4,5: not 0-based on purpose
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.mark.parametrize("strategy", ["ovr", "ovo"])
def test_multiclass_matches_sklearn_accuracy(three_class, strategy):
    from sklearn.svm import SVC
    x, y = three_class
    xtr, ytr, xte, yte = x[:360], y[:360], x[360:], y[360:]
    m, results = train_multiclass(xtr, ytr, CFG, strategy=strategy)
    assert all(r.converged for r in results)
    acc = accuracy_multiclass(m, xte, yte)
    sk = SVC(C=CFG.c, gamma=CFG.gamma, tol=CFG.epsilon).fit(xtr, ytr)
    assert acc >= sk.score(xte, yte) - 0.03
    # predictions carry the original (non-contiguous) labels
    assert set(np.unique(predict_multiclass(m, xte))) <= {3, 4, 5}


def test_multiclass_model_count(three_class):
    x, y = three_class
    m_ovr, _ = train_multiclass(x[:300], y[:300], CFG, strategy="ovr")
    assert len(m_ovr.models) == 3
    m_ovo, _ = train_multiclass(x[:300], y[:300], CFG, strategy="ovo")
    assert len(m_ovo.models) == 3


def test_multiclass_save_load_roundtrip(three_class, tmp_path):
    x, y = three_class
    m, _ = train_multiclass(x[:300], y[:300], CFG, strategy="ovr")
    p = str(tmp_path / "mc.npz")
    m.save(p)
    m2 = MulticlassSVM.load(p)
    np.testing.assert_array_equal(m2.classes, m.classes)
    assert m2.strategy == "ovr"
    np.testing.assert_array_equal(
        predict_multiclass(m2, x[300:]), predict_multiclass(m, x[300:]))


def test_multiclass_rejects_single_class():
    x = np.zeros((10, 3), np.float32)
    y = np.ones(10, np.int32)
    with pytest.raises(ValueError):
        train_multiclass(x, y, CFG)
