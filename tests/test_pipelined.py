"""Pipelined block rounds (config.pipeline_rounds; solver/block.py
run_chunk_block_pipelined, parallel/dist_block.py pipelined runner).

Correctness battery for ISSUE 2's tentpole: CPU bit-exactness against
the unpipelined engine at single-round chunk cadence (where the two
engines are algebraically identical programs), same-optimum parity where
the round sequences legitimately diverge (stale selection), the handoff
invalidation gating, the Pallas pre-fold selection kernel, and the
8-virtual-device mesh dryrun with the overlapped collectives.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve

BASE = SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3, max_iter=200_000,
                 engine="block", working_set_size=32)


def _plain(cfg):
    return cfg.replace(pipeline_rounds=False)


def _piped(cfg):
    return cfg.replace(pipeline_rounds=True)


@pytest.mark.parametrize("selection", ["mvp", "second_order"])
def test_pipelined_matches_plain_optimum(blobs_medium, selection):
    x, y = blobs_medium
    cfg = BASE.replace(selection=selection)
    rp = solve(x, y, _plain(cfg))
    rq = solve(x, y, _piped(cfg))
    assert rp.converged and rq.converged
    # Stale selection reorders the rounds (and usually costs extra
    # pairs) but the optimum must match: compare dual state.
    np.testing.assert_allclose(rq.alpha, rp.alpha, atol=5e-2)
    assert rq.b == pytest.approx(rp.b, abs=5e-3)
    assert abs(rq.n_sv - rp.n_sv) <= max(3, 0.03 * rp.n_sv)


def test_pipelined_bit_exact_at_single_round_chunks(blobs_small):
    """At rounds_per_chunk=1 the pipelined engine IS the plain engine:
    each chunk's seed prefetch selects from the same entry state the
    plain body selects from, the handoff gathers untouched values, and
    the live-mask gate is the identity (selection only admits I_up/I_low
    members and nothing ran in between). Trajectories must be
    BIT-identical — alpha, f, extrema and pair counts at every chunk
    boundary."""
    x, y = blobs_small
    obs_p, obs_q = [], []

    def cb(sink):
        return lambda it, bh, bl, st: sink.append((it, bh, bl)) and None

    # chunk_iters == inner_iters => rounds_per_chunk = 1; the callback
    # forces observed chunking (and records the boundary scalars).
    cfg = BASE.replace(working_set_size=16, inner_iters=32,
                       chunk_iters=32)
    rp = solve(x, y, _plain(cfg), callback=cb(obs_p))
    rq = solve(x, y, _piped(cfg), callback=cb(obs_q))
    assert rp.converged and rq.converged
    assert rp.iterations == rq.iterations
    assert obs_p == obs_q
    np.testing.assert_array_equal(rq.alpha, rp.alpha)
    np.testing.assert_array_equal(rq.stats["f"], rp.stats["f"])
    assert (rq.b_hi, rq.b_lo) == (rp.b_hi, rp.b_lo)


def test_pipelined_matches_per_pair_reference(blobs_small):
    x, y = blobs_small
    rq = solve(x, y, _piped(BASE.replace(working_set_size=16)))
    rx = solve(x, y, SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3,
                               max_iter=200_000))
    assert rq.converged and rx.converged
    np.testing.assert_allclose(rq.alpha, rx.alpha, atol=5e-2)
    assert rq.b == pytest.approx(rx.b, abs=5e-3)


def test_pipelined_heavy_invalidation_regime(blobs_medium):
    """Mixed-convergence stress for the handoff gate: tiny C drives most
    alphas to the box bound within a few rounds, so prefetched
    candidates are routinely saturated out of I_up/I_low by the time
    they are handed to the subproblem. The gated engine must still reach
    the per-pair optimum."""
    x, y = blobs_medium
    cfg = BASE.replace(c=0.05, working_set_size=16)
    rq = solve(x, y, _piped(cfg))
    rp = solve(x, y, _plain(cfg))
    assert rq.converged and rp.converged
    np.testing.assert_allclose(rq.alpha, rp.alpha, atol=5e-3)
    assert rq.b == pytest.approx(rp.b, abs=5e-3)
    # The regime really is bound-saturated (the point of the test).
    assert np.mean(np.isclose(rp.alpha, 0.05)) > 0.5


def test_handoff_invalidation_masks_saturated_candidates():
    """Unit semantics of the handoff gate (ops/select.py
    candidate_live_mask): a staged candidate whose alpha the in-flight
    round moved to a bound it cannot leave drops out of the working set
    — masked, never recomputed."""
    from dpsvm_tpu.ops.select import candidate_live_mask
    import jax.numpy as jnp

    c = 2.0
    y_w = jnp.asarray([1.0, 1.0, -1.0, -1.0, 1.0])
    # Selected while free; the previous round then moved slots 1/3 to
    # their bounds.
    alpha_now = jnp.asarray([0.5, c, 0.7, 0.0, 0.0])
    live = np.asarray(candidate_live_mask(alpha_now, y_w, c))
    # With a SCALAR C every in-box (alpha, y) stays in I_up u I_low
    # (a=C keeps I_low membership via a>0; a=0 keeps I_up via a<C), so
    # the gate is the identity — the re-rank inside the subproblem does
    # the violation-ordering work. The gate BITES where a slot can
    # leave both sets: degenerate class-weighted boxes and dead filler.
    assert live.all()
    # Degenerate weighted box: c_neg=0 pins y=-1 rows at alpha=0 into
    # NEITHER set (a>0 false, a<c_neg false) — exactly those drop.
    live_w = np.asarray(candidate_live_mask(alpha_now, y_w, (c, 0.0)))
    np.testing.assert_array_equal(live_w, [True, True, True, False,
                                           True])


def test_pipelined_class_weights(blobs_small):
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, weight_pos=2.0,
                       weight_neg=0.5)
    rq = solve(x, y, _piped(cfg))
    rp = solve(x, y, _plain(cfg))
    assert rq.converged and rp.converged
    np.testing.assert_allclose(rq.alpha, rp.alpha, atol=5e-2)
    assert rq.b == pytest.approx(rp.b, abs=5e-3)


def test_pipelined_budget_mode_exact_pairs(blobs_medium):
    x, y = blobs_medium
    cfg = BASE.replace(budget_mode=True, max_iter=1000, inner_iters=50)
    rq = solve(x, y, _piped(cfg))
    assert rq.iterations == 1000


def test_pipelined_compensated_carry(blobs_small):
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.reconstruct import gram_matvec_f64

    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, c=2000.0, gamma=0.05,
                       compensated=True)
    rq = solve(x, y, _piped(cfg))
    rp = solve(x, y, _plain(cfg))
    assert rq.converged and rp.converged
    kp = KernelParams("rbf", cfg.gamma)

    def dec(r):
        f64 = gram_matvec_f64(x, np.asarray(r.alpha, np.float64) * y, kp)
        return f64 - r.b

    agree = np.mean(np.sign(dec(rq)) == np.sign(dec(rp)))
    assert agree >= 0.995
    assert rq.b == pytest.approx(rp.b, abs=5e-2)


def test_pipelined_with_reconstruction_legs(blobs_small):
    # The extreme-C accuracy mode composes with pipelined rounds (and
    # the hybrid tail switch resets pipeline_rounds with the other
    # block-only knobs).
    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, c=2000.0, gamma=0.05,
                       compensated=True, reconstruct_every=40_000,
                       max_iter=400_000, pipeline_rounds=True)
    rq = solve(x, y, cfg)
    assert rq.converged
    assert rq.stats["true_gap"] <= 2 * cfg.epsilon + 1e-9


def test_pipelined_precomputed_kernel(blobs_small):
    """The prefetch's Gram-block build degenerates to a column gather on
    a precomputed kernel — parity against the plain engine there too."""
    x, y = blobs_small
    g = x @ x.T  # linear Gram
    cfg = BASE.replace(kernel="precomputed", working_set_size=16)
    rq = solve(g, y, _piped(cfg))
    rp = solve(g, y, _plain(cfg))
    assert rq.converged and rp.converged
    np.testing.assert_allclose(rq.alpha, rp.alpha, atol=5e-2)
    assert rq.b == pytest.approx(rp.b, abs=5e-3)


def test_select_rows_kernel_matches_oracle():
    """ops/pallas_fold_select.py select_rows (the pre-fold selection
    variant, interpret mode): per-row candidates and assembled extrema
    against a NumPy oracle of the I_up/I_low algebra."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_fold_select import (assemble_working_set,
                                                  select_rows)

    rng = np.random.default_rng(5)
    n, c = 1024, 1.5
    shp = (n // 128, 128)
    f = rng.normal(size=n).astype(np.float32)
    alpha = rng.uniform(0, c, size=n).astype(np.float32)
    alpha[rng.random(n) < 0.3] = 0.0
    alpha[rng.random(n) < 0.2] = c
    y = np.where(rng.random(n) < 0.5, 1.0, -1.0).astype(np.float32)
    valid = np.ones(n, np.float32)
    valid[1000:] = 0.0

    upv, upi, lov, loi = select_rows(
        jnp.asarray(f.reshape(shp)), jnp.asarray(alpha.reshape(shp)),
        jnp.asarray(y.reshape(shp)), jnp.asarray(valid.reshape(shp)),
        c, interpret=True)

    up = np.where(y > 0, alpha < c, alpha > 0) & (valid > 0)
    low = np.where(y > 0, alpha > 0, alpha < c) & (valid > 0)
    f_up = np.where(up, f, np.inf).reshape(shp)
    f_low = np.where(low, f, -np.inf).reshape(shp)
    np.testing.assert_array_equal(np.asarray(upv), f_up.min(axis=1))
    np.testing.assert_array_equal(np.asarray(lov), f_low.max(axis=1))
    # ids: the LOWEST flat id achieving each row extremum (tie-break).
    for r in range(shp[0]):
        if np.isfinite(f_up[r].min()):
            assert np.asarray(upi)[r] == r * 128 + int(
                np.argmin(f_up[r]))
        if np.isfinite(f_low[r].max()):
            assert np.asarray(loi)[r] == r * 128 + int(
                np.argmax(f_low[r]))
    # Assembled extrema are the exact global stopping pair.
    w, ok, b_hi, b_lo = assemble_working_set(upv, upi, lov, loi, 8)
    assert float(b_hi) == np.where(up, f, np.inf).min()
    assert float(b_lo) == np.where(low, f, -np.inf).max()


def test_pipeline_rounds_validation():
    with pytest.raises(ValueError, match="block-engine"):
        SVMConfig(engine="xla", pipeline_rounds=True)
    with pytest.raises(ValueError, match="active_set_size"):
        SVMConfig(engine="block", pipeline_rounds=True,
                  active_set_size=64)
    # auto (None) and off are legal anywhere.
    SVMConfig(engine="xla", pipeline_rounds=None)
    SVMConfig(engine="xla", pipeline_rounds=False)


def test_pipelined_nusvc_falls_back_cleanly(blobs_small):
    """A user config with pipeline_rounds=True must not crash the nu
    trainers (they switch to the per-class selection rule, which the
    pipelined engine does not implement), and since ISSUE 9 the
    fallback is NAMED: the trainer warns with the dropped knob."""
    import pytest

    from dpsvm_tpu.models.nusvm import train_nusvc

    x, y = blobs_small
    with pytest.warns(UserWarning,
                      match=r"falls back from: pipeline_rounds"):
        model = train_nusvc(x, y, nu=0.3,
                            config=BASE.replace(pipeline_rounds=True,
                                                gamma=0.1))
    assert model is not None


# ---- mesh (8 virtual devices) --------------------------------------


def test_pipelined_mesh_matches_single_chip(blobs_medium):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    cfg = BASE.replace(selection="second_order")
    rp = solve(x, y, _plain(cfg))
    rm = solve_mesh(x, y, _piped(cfg), num_devices=8)
    assert rp.converged and rm.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)


def test_pipelined_mesh_compensated(blobs_small):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    cfg = BASE.replace(working_set_size=16, compensated=True)
    rm = solve_mesh(x, y, _piped(cfg), num_devices=8)
    rp = solve(x, y, _plain(cfg))
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)
    assert rm.b == pytest.approx(rp.b, abs=5e-3)


def test_pipelined_mesh_budget_mode(blobs_medium):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    cfg = BASE.replace(budget_mode=True, max_iter=1000, inner_iters=50)
    rm = solve_mesh(x, y, _piped(cfg), num_devices=8)
    assert rm.iterations == 1000


def test_pipelined_mesh_uneven_rows(blobs_medium):
    """n not divisible by the device count: pad rows masked from the
    prefetch selection and the handoff psum alike."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    x, y = x[:1199], y[:1199]
    rm = solve_mesh(x, y, _piped(BASE), num_devices=8)
    rp = solve(x, y, _plain(BASE))
    assert rm.converged and rp.converged
    np.testing.assert_allclose(rm.alpha, rp.alpha, atol=5e-2)


def test_pipelined_mesh_rejects_precomputed(blobs_small):
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.parallel.dist_block import (
        make_block_pipelined_chunk_runner)
    from dpsvm_tpu.parallel.mesh import make_data_mesh

    with pytest.raises(ValueError, match="feature kernels"):
        make_block_pipelined_chunk_runner(
            make_data_mesh(2), KernelParams("precomputed"), (1.0, 1.0),
            1e-3, 1e-12, 16, 32, 4)


def test_pipelined_mesh_round_collectives():
    """Structural claim behind the overlap story (docs/SCALING.md
    pipelined model): the pipelined mesh round still emits exactly one
    all_gather dispatch sequence (candidate values + ids) and the SAME
    total psum payload as the plain round — q*(d+5) f32, now split
    (q, d) + (q, 3) prefetched (overlappable) plus the (q, 2) handoff
    (serial) — and nothing else. Asserted from compiled HLO like
    test_hlo_collectives.py, at a small shape (op structure is
    shape-independent)."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.hlo_facts import collective_ops as _collective_ops
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.parallel.dist_block import (
        make_block_pipelined_chunk_runner)
    from dpsvm_tpu.parallel.mesh import make_data_mesh
    from dpsvm_tpu.solver.block import BlockState

    n, d, q, p_dev = 4096, 24, 64, 8
    h = q // 2
    mesh = make_data_mesh(p_dev)
    runner = make_block_pipelined_chunk_runner(
        mesh, KernelParams("rbf", 0.1), (5.0, 5.0), 1e-3, 1e-12, q, 128,
        rounds_per_chunk=1, inner_impl="xla")
    sds = jax.ShapeDtypeStruct
    state = BlockState(
        alpha=sds((n,), jnp.float32), f=sds((n,), jnp.float32),
        b_hi=sds((), jnp.float32), b_lo=sds((), jnp.float32),
        pairs=sds((), jnp.int32), rounds=sds((), jnp.int32))
    text = runner.lower(
        sds((n, d), jnp.float32), sds((n,), jnp.float32),
        sds((n,), jnp.float32), sds((n,), jnp.float32),
        sds((n,), jnp.bool_), state, sds((), jnp.int32),
    ).compile().as_text()

    gathers = _collective_ops(text, "all-gather")
    reduces = _collective_ops(text, "all-reduce")
    others = (_collective_ops(text, "all-to-all")
              + _collective_ops(text, "collective-permute"))
    assert not others, others
    # The compiled text holds the SEED prefetch (outside the loop: one
    # all_gather pair + the (q, d)+(q, 3) psum) AND the loop body (one
    # all_gather pair + (q, d)+(q, 3) prefetch psum + (q, 2) handoff
    # psum). Payload accounting:
    gather_sizes = sorted(s for _, sizes in gathers for _, s in sizes)
    assert gather_sizes == [p_dev * 2 * h * 4] * 4, \
        (gather_sizes, gathers)
    reduce_total = sum(s for _, sizes in reduces for _, s in sizes)
    assert reduce_total == q * (d + 3) * 4 + q * (d + 5) * 4, \
        (reduce_total, reduces)
