"""One-HBM-pass fused round (ops/pallas_round.py, ISSUE 12).

The contract under test is BITWISE: every stage the fused round replaces
(gather, Gram, kernel rows, fold contraction, selection) is exact, so
whole solve trajectories under config.fused_round=True must equal the
stock fused engine's (config.fused_fold=True) bit for bit — across both
selection rules, the compensated carry, padded tails (non-multiple-of-
128 n) and all-invalid tail tiles. Correctness on CPU via Pallas
interpret mode; the real Mosaic lowering is tools/tpu_smoke.py's job.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve

BASE = SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3, max_iter=200_000,
                 engine="block", working_set_size=16)


def _blobs_padded():
    """n=700 pads to 1024: non-multiple-of-128 n, a partial tile AND
    all-invalid tail tiles, converging in few rounds — the padding
    contract at tier-1 cost (the suite rides close to its wall-clock
    ceiling; see the ROADMAP tier-1 timeout notes)."""
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=700, d=10, seed=5, sep=1.1)


def _bitwise_equal(ra, rb):
    return (np.array_equal(ra.alpha, rb.alpha)
            and np.array_equal(ra.stats["f"], rb.stats["f"])
            and ra.iterations == rb.iterations
            and ra.b == rb.b
            and ra.b_hi == rb.b_hi and ra.b_lo == rb.b_lo
            and ra.stats["outer_rounds"] == rb.stats["outer_rounds"])


@pytest.mark.parametrize("selection", ["mvp", "second_order"])
@pytest.mark.parametrize("compensated", [False, True])
def test_fused_round_bitwise_vs_stock_fused(selection, compensated):
    x, y = _blobs_padded()
    cfg = BASE.replace(selection=selection, compensated=compensated)
    rf = solve(x, y, cfg.replace(fused_fold=True))
    rr = solve(x, y, cfg.replace(fused_round=True))
    assert rf.converged and rr.converged
    assert _bitwise_equal(rf, rr)


def test_fused_round_bitwise_two_block_rows(blobs_medium):
    """One medium case where n pads to 2048 (two 1024-row kernel tiles):
    the multi-tile streaming path of gather_gram/fold_rows_select rides
    a full trajectory, not just the fuzz chunks."""
    x, y = blobs_medium
    cfg = BASE.replace(compensated=True)
    rf = solve(x, y, cfg.replace(fused_fold=True))
    rr = solve(x, y, cfg.replace(fused_round=True))
    assert rf.converged and rr.converged
    assert _bitwise_equal(rf, rr)


def test_fused_round_class_weights(blobs_small):
    x, y = blobs_small
    cfg = BASE.replace(weight_pos=2.0, weight_neg=0.5)
    rf = solve(x, y, cfg.replace(fused_fold=True))
    rr = solve(x, y, cfg.replace(fused_round=True))
    assert rf.converged and rr.converged
    assert _bitwise_equal(rf, rr)


def test_fused_round_pair_batch():
    x, y = _blobs_padded()
    cfg = BASE.replace(pair_batch=2)
    rf = solve(x, y, cfg.replace(fused_fold=True))
    rr = solve(x, y, cfg.replace(fused_round=True))
    assert rf.converged and rr.converged
    assert _bitwise_equal(rf, rr)


def test_fused_round_budget_mode_exact_pairs():
    x, y = _blobs_padded()
    cfg = BASE.replace(budget_mode=True, max_iter=1000, inner_iters=50,
                       fused_round=True)
    rr = solve(x, y, cfg)
    assert rr.iterations == 1000


def test_fused_round_matches_per_pair_reference(blobs_small):
    """Optimum-quality anchor: the bitwise pin above only proves
    equality with the fused engine; this pins both to the per-pair
    reference optimum."""
    x, y = blobs_small
    rr = solve(x, y, BASE.replace(fused_round=True))
    rx = solve(x, y, SVMConfig(c=5.0, gamma=0.1, epsilon=1e-3,
                               max_iter=200_000))
    assert rr.converged and rx.converged
    np.testing.assert_allclose(rr.alpha, rx.alpha, atol=5e-2)
    assert rr.b == pytest.approx(rx.b, abs=5e-3)


def test_fused_round_auto_falls_back_small_n():
    """q/2 > n_pad/128 (the q-vs-n-pad collision): every slot cannot
    find a per-128-row candidate, so the engine must fall back to the
    plain path — even when fused_round=True forces the knob (same
    silent-fallback contract as fused_fold=True)."""
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=200, d=6, seed=1, sep=1.5)
    cfg = BASE.replace(working_set_size=128)  # h=64 > 1024/128
    r = solve(x, y, cfg.replace(fused_round=True))
    assert r.converged


def test_fused_round_config_validation():
    with pytest.raises(ValueError, match="block-engine"):
        SVMConfig(engine="xla", fused_round=True)
    with pytest.raises(ValueError, match="feature kernels"):
        SVMConfig(engine="block", kernel="precomputed", fused_round=True)
    with pytest.raises(ValueError, match="pipeline_rounds"):
        SVMConfig(engine="block", fused_round=True, pipeline_rounds=True)
    with pytest.raises(ValueError, match="active_set_size"):
        SVMConfig(engine="block", fused_round=True, active_set_size=64)
    with pytest.raises(ValueError, match="ooc"):
        SVMConfig(engine="block", fused_round=True, ooc=True)
    with pytest.raises(ValueError, match="gram_resident"):
        SVMConfig(engine="block", fused_round=True, gram_resident=True)


def test_cli_fused_round_flag(tmp_path):
    """--fused-round on reaches SVMConfig.fused_round=True through the
    train entrypoint (and trains a working model)."""
    from dpsvm_tpu import cli
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=120, d=6, seed=2, sep=1.5)
    f = tmp_path / "train.csv"
    np.savetxt(f, np.column_stack([y, x]), delimiter=",", fmt="%.6f")
    model = tmp_path / "m.model"
    rc = cli.main(["train", "-f", str(f), "-m", str(model),
                   "-a", "6", "-x", "120", "--engine", "block",
                   "--working-set-size", "8", "--fused-round", "on",
                   "--backend", "single", "--quiet"])
    assert rc == 0
    assert model.with_suffix(model.suffix).exists() or model.exists()


# ------------------------------------------------------- kernel units

def test_gather_gram_kernel_unit():
    """gather_gram against the stock stage oracles, bitwise: the
    in-kernel row gather must move jnp.take's exact bits and the tiled
    kernel-row/Gram algebra must match kernel_rows / kernel_from_dots
    element for element."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_from_dots,
                                       kernel_rows)
    from dpsvm_tpu.ops.pallas_round import gather_gram

    rng = np.random.default_rng(7)
    n, d, q = 2048, 24, 16
    kp = KernelParams("rbf", 0.1)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    x_sq = jnp.einsum("nd,nd->n", x, x)
    w = jnp.asarray(rng.integers(0, n, q).astype(np.int32))
    qsq = jnp.take(x_sq, w)

    k_rows, kb = jax.jit(gather_gram,
                         static_argnames=("kp", "interpret"))(
        x, w, x_sq, qsq, kp, interpret=True)

    qx = jnp.take(x, w, axis=0)
    k_oracle = kernel_rows(x, x_sq, qx, qsq, kp)
    dots_w = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
    kb_oracle = kernel_from_dots(dots_w, qsq, qsq, kp)
    assert jnp.array_equal(k_rows, k_oracle)
    assert jnp.array_equal(kb, kb_oracle)


@pytest.mark.parametrize("kind", ["linear", "poly"])
def test_gather_gram_other_kernels(kind):
    """The in-kernel kernel_from_dots call serves every feature-kernel
    family, not just rbf."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import KernelParams, kernel_rows
    from dpsvm_tpu.ops.pallas_round import gather_gram

    rng = np.random.default_rng(3)
    n, d, q = 1024, 8, 8
    kp = KernelParams(kind, 0.5, 2, 0.25)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    x_sq = jnp.einsum("nd,nd->n", x, x)
    w = jnp.asarray(rng.integers(0, n, q).astype(np.int32))
    qsq = jnp.take(x_sq, w)
    k_rows, _ = jax.jit(gather_gram,
                        static_argnames=("kp", "interpret"))(
        x, w, x_sq, qsq, kp, interpret=True)
    k_oracle = kernel_rows(x, x_sq, jnp.take(x, w, axis=0), qsq, kp)
    assert jnp.array_equal(k_rows, k_oracle)


@pytest.mark.parametrize("compensated", [False, True])
def test_fold_rows_select_kernel_unit(compensated):
    """fold_rows_select against the stock two-stage oracle, bitwise:
    in-kernel coef @ K(W,:) + fold_select must equal the XLA
    contraction followed by the fold_select kernel."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.pallas_fold_select import fold_select
    from dpsvm_tpu.ops.pallas_round import fold_rows_select

    rng = np.random.default_rng(4)
    n, q, c = 2048, 16, 1.5
    shp = (n // 128, 128)
    k_rows = jnp.asarray(rng.normal(size=(q, n)).astype(np.float32))
    coef = jnp.asarray(rng.normal(size=(q,)).astype(np.float32) * 0.1)
    f = jnp.asarray(rng.normal(size=n).astype(np.float32).reshape(shp))
    err = jnp.asarray((rng.normal(size=n) * 1e-4).astype(
        np.float32).reshape(shp)) if compensated else None
    alpha = np.clip(rng.normal(0.5, 0.5, n), 0, c).astype(np.float32)
    y = np.where(rng.random(n) < 0.5, 1.0, -1.0).astype(np.float32)
    valid = np.ones(n, np.float32)
    valid[-200:] = 0.0
    a2d = jnp.asarray(alpha.reshape(shp))
    y2d = jnp.asarray(y.reshape(shp))
    v2d = jnp.asarray(valid.reshape(shp))

    got = fold_rows_select(k_rows, coef, f, err, a2d, y2d, v2d, c,
                           compensated=compensated, interpret=True)
    delta2d = (coef @ k_rows).reshape(shp)
    want = fold_select(f, err, a2d, y2d, v2d, delta2d, c,
                       compensated=compensated, interpret=True)
    for g, w in zip(got, want):
        if g is None:
            assert w is None
        else:
            assert jnp.array_equal(g, w)


# ---------------------------------------------------------- shape fuzz

def test_fused_round_shape_fuzz():
    """Satellite: random (n, d, q) — including q at the n-pad candidate
    ceiling and all-invalid tail tiles — chunk trajectories bitwise
    equal to the stock fused round body (run_chunk_block_fused), state
    field by state field."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       squared_norms)
    from dpsvm_tpu.solver.block import (BlockState, run_chunk_block_fused,
                                        run_chunk_block_fusedround)

    rng = np.random.default_rng(0)
    cases = [
        # (n, d, q, selection, compensated)
        (700, 5, 8, "mvp", False),       # pads to 1024, big dead tail
        (1024, 3, 16, "second_order", False),  # exact multiple, tiny d
        (1100, 17, 16, "mvp", True),     # unaligned n AND d
        (2000, 9, 32, "second_order", True),   # q at the 2048/128=16/side cap
        (1025, 7, 4, "mvp", False),      # one row past the block edge
    ]
    for n, d, q, selection, compensated in cases:
        n_pad = -(-n // 1024) * 1024
        x = np.zeros((n_pad, d), np.float32)
        x[:n] = rng.normal(size=(n, d)).astype(np.float32)
        y = np.ones((n_pad,), np.float32)
        y[:n] = np.where(rng.random(n) < 0.5, 1.0, -1.0)
        valid = np.zeros((n_pad,), bool)
        valid[:n] = True
        c = float(rng.uniform(0.5, 8.0))
        kp = KernelParams("rbf", float(rng.uniform(0.05, 0.5)))
        xj = jnp.asarray(x)
        yj = jnp.asarray(y)
        x_sq = squared_norms(xj)
        kd = kernel_diag(x_sq, kp)
        vj = jnp.asarray(valid)
        alpha0 = np.zeros((n_pad,), np.float32)
        # a warm, partially-bound start exercises the box masks
        alpha0[:n] = np.clip(rng.normal(0.3 * c, 0.3 * c, n), 0, c)
        f0 = np.asarray(-y, np.float32)
        f0[:n] += rng.normal(0, 0.3, n).astype(np.float32)
        st = BlockState(
            alpha=jnp.asarray(alpha0), f=jnp.asarray(f0),
            b_hi=jnp.float32(-1e9), b_lo=jnp.float32(1e9),
            pairs=jnp.int32(0), rounds=jnp.int32(0),
            f_err=jnp.zeros((n_pad,), jnp.float32) if compensated
            else None)
        kw = dict(kp=kp, c=(c, c), eps=1e-3, tau=1e-12, q=q,
                  inner_iters=q, rounds_per_chunk=2,
                  inner_impl="xla", interpret=True, selection=selection)
        a = run_chunk_block_fused(xj, yj, x_sq, kd, vj, st,
                                  jnp.int32(10 ** 6), **kw)
        b = run_chunk_block_fusedround(xj, yj, x_sq, kd, vj, st,
                                       jnp.int32(10 ** 6), **kw)
        case = (n, d, q, selection, compensated)
        assert np.array_equal(a.alpha, b.alpha), case
        assert np.array_equal(a.f, b.f), case
        assert float(a.b_hi) == float(b.b_hi), case
        assert float(a.b_lo) == float(b.b_lo), case
        assert int(a.pairs) == int(b.pairs), case
        assert int(a.rounds) == int(b.rounds), case
        if compensated:
            assert np.array_equal(a.f_err, b.f_err), case
