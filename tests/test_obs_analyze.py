"""Runlog analytics (dpsvm_tpu/obs/analyze — ISSUE 8): summaries,
stall-window detection, per-phase diff attribution, tail, and the
`cli obs` surface. Pure JSONL readers — no device work; runlogs are
synthesized through the real RunLog writer so the reader is exercised
against the schema the spine actually emits."""

import json

import pytest

import bench
from dpsvm_tpu.obs import analyze
from dpsvm_tpu.obs.runlog import RunLog


def _write_solve_run(path, pairs_per_chunk=(100, 100, 100),
                     gaps=(1.0, 0.5, 0.01), device_s=0.1,
                     phase_seconds=None, tool="solve",
                     converged=True, compiles=0):
    """One synthetic solve run through the REAL writer."""
    log = RunLog(str(path), tool, meta={"n": 1000, "d": 8,
                                        "engine": "block"})
    total = 0
    for i, (p, g) in enumerate(zip(pairs_per_chunk, gaps)):
        total += p
        log.record("chunk", pairs=total, pairs_delta=p, b_hi=-g / 2,
                   b_lo=g / 2, gap=g, device_seconds=device_s,
                   dispatch=i + 1)
    for i in range(compiles):
        log.record("compile", entrypoint="solver/chunk",
                   shape="n=1000 d=8", seconds=0.5)
    ph = phase_seconds or {"setup": 0.2,
                           "solve": device_s * len(pairs_per_chunk),
                           "observe": 0.01, "finalize": 0.02}
    log.finish(iterations=total, converged=converged,
               phase_seconds=ph)
    return log.run_id


# ------------------------------------------------------- summaries

def test_summary_throughput_and_gap(tmp_path):
    p = tmp_path / "solve-1.jsonl"
    _write_solve_run(p)
    (run,) = analyze.load_runs([str(p)])
    s = analyze.summarize_run(run)
    assert s["tool"] == "solve" and s["engine"] == "block"
    assert s["pairs"] == 300 and s["chunks"] == 3
    assert s["device_seconds"] == pytest.approx(0.3)
    assert s["pairs_per_second"] == 1000
    assert s["gap_first"] == 1.0 and s["gap_last"] == 0.01
    assert s["stalls"] == {"count": 0, "longest": 0}
    assert s["converged"] is True and s["finished"] is True
    assert s["compiles"] == 0
    json.dumps(s)  # JSON-able


def test_summary_detects_stall_windows(tmp_path):
    """Chunks whose gap stops shrinking form stall windows — the
    working-set-cycling diagnostic."""
    p = tmp_path / "solve-1.jsonl"
    _write_solve_run(p, pairs_per_chunk=(10,) * 6,
                     gaps=(1.0, 0.5, 0.5, 0.5, 0.2, 0.2))
    (run,) = analyze.load_runs([str(p)])
    s = analyze.summarize_run(run)
    # 0.5->0.5->0.5 is one 2-chunk window; 0.2->0.2 a second 1-chunk.
    assert s["stalls"] == {"count": 2, "longest": 2}


def test_directory_and_tool_filter(tmp_path):
    _write_solve_run(tmp_path / "solve-1.jsonl")
    _write_solve_run(tmp_path / "fleet-1.jsonl", tool="fleet")
    runs = analyze.load_runs([str(tmp_path)])
    assert {r.manifest["tool"] for r in runs} == {"solve", "fleet"}
    assert analyze.runlog_paths([str(tmp_path)]) == sorted(
        str(tmp_path / n) for n in ("fleet-1.jsonl", "solve-1.jsonl"))
    with pytest.raises(FileNotFoundError):
        analyze.runlog_paths([str(tmp_path / "absent.jsonl")])


def test_report_renders_text_and_md(tmp_path):
    _write_solve_run(tmp_path / "solve-1.jsonl", compiles=2)
    runs = analyze.load_runs([str(tmp_path)])
    summaries = [analyze.summarize_run(r) for r in runs]
    txt = analyze.render_report(summaries)
    assert "solve" in txt and "pairs/s" in txt
    assert "2 compile(s)" in txt
    md = analyze.render_report(summaries, md=True)
    assert md.splitlines()[0].startswith("| tool |")
    assert md.splitlines()[1].startswith("|---")


def test_report_renders_shrink_column(tmp_path):
    """A run whose final record carries the shrunken-stream fields
    (ISSUE 19) gets a populated shrink column — view fraction, recon
    count, skipped tiles/bytes, and the demotion tag; runs without
    shrinking render '-'."""
    log = RunLog(str(tmp_path / "solve-1.jsonl"), "solve",
                 meta={"n": 4096, "d": 54, "engine": "block"})
    log.record("chunk", pairs=100, pairs_delta=100, gap=0.5,
               device_seconds=0.1, dispatch=1, tiles=4,
               tiles_skipped=12, shrink_active=True)
    log.finish(iterations=100, converged=True, ooc_shrink=True,
               shrink_active_fraction=0.125, shrink_reconstructions=3,
               shrink_demoted=True, tiles_skipped=12,
               tile_bytes_skipped=64 * 2**20)
    _write_solve_run(tmp_path / "solve-2.jsonl")  # no shrinking
    summaries = [analyze.summarize_run(r)
                 for r in analyze.load_runs([str(tmp_path)])]
    txt = analyze.render_report(summaries)
    assert "act=0.12" in txt and "rec=3" in txt
    assert "skip=12t" in txt and "0.06GiB" in txt and "dem" in txt
    shrunk = next(s for s in summaries if s["ooc_shrink"])
    plain = next(s for s in summaries if not s["ooc_shrink"])
    assert analyze._report_row(plain)[
        [h for h, _ in analyze._REPORT_COLS].index("shrink")] == "-"
    assert shrunk["tiles_skipped"] == 12


# ------------------------------------------------------------- diff

def _summary_for(tmp_path, name, **kw):
    p = tmp_path / name
    _write_solve_run(p, **kw)
    (run,) = analyze.load_runs([str(p)])
    return analyze.summarize_run(run)


def test_diff_attributes_injected_solve_slowdown(tmp_path):
    """Acceptance (ISSUE 8): a synthetically injected per-phase
    slowdown is attributed to the CORRECT phase."""
    base = {"setup": 0.2, "solve": 1.0, "observe": 0.05,
            "finalize": 0.02}
    slow = dict(base, solve=1.8)  # inject: solve phase +0.8s
    a = _summary_for(tmp_path, "solve-a.jsonl", phase_seconds=base)
    b = _summary_for(tmp_path, "solve-b.jsonl", phase_seconds=slow)
    d = analyze.diff_runs(a, b)
    assert d["attributed_phase"] == "solve"
    assert d["phase_deltas"]["solve"] == pytest.approx(0.8)
    assert d["total_delta_seconds"] == pytest.approx(0.8)
    assert d["attributed_share"] == pytest.approx(1.0)
    # ... and an observe-phase injection lands on observe, even with
    # noise elsewhere.
    noisy = dict(base, observe=0.55, setup=0.21)
    c = _summary_for(tmp_path, "solve-c.jsonl", phase_seconds=noisy)
    d2 = analyze.diff_runs(a, c)
    assert d2["attributed_phase"] == "observe"
    txt = analyze.render_diff(d2)
    assert "attribution: phase 'observe'" in txt


def test_diff_share_sane_with_offsetting_phases(tmp_path):
    """Offsetting phases (setup slower, solve faster) are the case
    attribution exists for: the share is of the GROSS movement, so it
    can never exceed 100% (review fix)."""
    a = _summary_for(tmp_path, "solve-a.jsonl",
                     phase_seconds={"setup": 1.0, "solve": 5.0})
    b = _summary_for(tmp_path, "solve-b.jsonl",
                     phase_seconds={"setup": 3.0, "solve": 3.5})
    d = analyze.diff_runs(a, b)
    assert d["attributed_phase"] == "setup"
    assert d["total_delta_seconds"] == pytest.approx(0.5)
    assert d["attributed_share"] == pytest.approx(2.0 / 3.5, abs=1e-4)
    assert d["attributed_share"] <= 1.0
    assert "gross movement" in analyze.render_diff(d)


def test_diff_reports_pairs_per_second_and_compiles(tmp_path):
    a = _summary_for(tmp_path, "solve-a.jsonl", device_s=0.1)
    b = _summary_for(tmp_path, "solve-b.jsonl", device_s=0.2,
                     compiles=3)
    d = analyze.diff_runs(a, b)
    assert d["pairs_per_second_delta"] == pytest.approx(-0.5)
    assert d["compile_delta"] == 3
    json.dumps(d)


def test_pick_run_prefers_last_finished(tmp_path):
    p = tmp_path / "solve-1.jsonl"
    r1 = _write_solve_run(p)
    r2 = _write_solve_run(p)
    # An OPEN third run (no final record) must not win.
    log = RunLog(str(p), "solve")
    log.record("chunk", pairs=1, pairs_delta=1, gap=1.0,
               device_seconds=0.1, dispatch=1)
    open_id = log.run_id
    runs = analyze.load_runs([str(p)])
    assert analyze.pick_run(runs).run_id == r2
    assert analyze.pick_run(runs, run_id=r1).run_id == r1
    assert analyze.pick_run(runs, run_id=open_id).run_id == open_id
    with pytest.raises(KeyError):
        analyze.pick_run(runs, run_id="nope")
    log.finish()


# ------------------------------------------------------------- tail

def test_tail_last_records(tmp_path):
    p = tmp_path / "solve-1.jsonl"
    _write_solve_run(p)
    lines = analyze.tail_records(str(p), 2)
    assert len(lines) == 2
    assert "final" in lines[-1] and "iterations=300" in lines[-1]
    assert "chunk" in lines[0]
    # n <= 0 means zero records, not the whole stream ([-0:] footgun).
    assert analyze.tail_records(str(p), 0) == []
    assert analyze.tail_records(str(p), -3) == []


def test_pick_run_orders_by_manifest_utc_not_filename(tmp_path,
                                                      monkeypatch):
    """A dir can hold solve-400.jsonl written AFTER solve-5000.jsonl
    (pids don't sort by time): 'last finished run' must follow the
    manifest utc stamp, not lexical file order."""
    import time as time_mod

    from dpsvm_tpu.obs import runlog as runlog_mod

    real_strftime = time_mod.strftime

    def _at(stamp):
        monkeypatch.setattr(
            runlog_mod.time, "strftime",
            lambda fmt, *a, _s=stamp: _s if "%Y" in fmt
            else real_strftime(fmt, *a))

    _at("2026-08-04T10:00:00Z")  # older run, lexically LATER file
    _write_solve_run(tmp_path / "solve-5000.jsonl")
    _at("2026-08-04T11:00:00Z")  # newer run, lexically earlier file
    newer = _write_solve_run(tmp_path / "solve-400.jsonl")
    runs = analyze.load_runs([str(tmp_path)])
    assert analyze.pick_run(runs).run_id == newer


# -------------------------------------------------------------- CLI

def test_cli_obs_report_and_diff(tmp_path, capsys):
    from dpsvm_tpu import cli

    _write_solve_run(tmp_path / "solve-a.jsonl",
                     phase_seconds={"setup": 0.1, "solve": 1.0,
                                    "observe": 0.01, "finalize": 0.01})
    _write_solve_run(tmp_path / "solve-b.jsonl",
                     phase_seconds={"setup": 0.1, "solve": 2.0,
                                    "observe": 0.01, "finalize": 0.01})
    rc = cli.main(["obs", "report", str(tmp_path), "--md"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("| tool |") and "solve" in out

    rc = cli.main(["obs", "diff", str(tmp_path / "solve-a.jsonl"),
                   str(tmp_path / "solve-b.jsonl"), "--json"])
    assert rc == 0
    d = json.loads(capsys.readouterr().out)
    assert d["attributed_phase"] == "solve"

    rc = cli.main(["obs", "tail", str(tmp_path / "solve-a.jsonl"),
                   "-n", "3"])
    assert rc == 0
    assert len(capsys.readouterr().out.strip().splitlines()) == 3

    assert cli.main(["obs", "report", str(tmp_path / "nope.jsonl")]) \
        == 2
    # A directory where a file is expected is the one-line-error exit-2
    # contract too, not an IsADirectoryError traceback (review fix).
    assert cli.main(["obs", "tail", str(tmp_path)]) == 2
    # ... and a glob matching only a subdirectory reports no-runlog.
    (tmp_path / "sub.jsonl").mkdir()
    assert cli.main(["obs", "report", str(tmp_path / "sub.*")]) == 2


def test_cli_obs_report_json_lines(tmp_path, capsys):
    from dpsvm_tpu import cli

    _write_solve_run(tmp_path / "solve-a.jsonl")
    rc = cli.main(["obs", "report", str(tmp_path), "--json"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    assert rows and rows[0]["pairs"] == 300


# ----------------------------------------- bench per-phase gate ties

def test_bench_gate_flags_injected_phase_regression(tmp_path):
    """bench.py's gate extension (ISSUE 8): a per-phase slowdown is
    FLAGged and named even when the headline metric stays in band."""
    prev = {"pairs_per_second": 700_000,
            "session_calibration": {"best_of_5_seconds": 0.5},
            "phase_seconds": {"setup": 1.0, "solve": 5.0,
                              "observe": 0.2, "finalize": 0.1}}
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(prev))
    cur = {"pairs_per_second": 690_000,  # headline well in band
           "session_calibration": {"best_of_5_seconds": 0.5},
           "phase_seconds": {"setup": 1.6, "solve": 5.05,
                             "observe": 0.2, "finalize": 0.1}}
    out = bench._regression_gate(cur, str(tmp_path))
    assert out["regression_gate"] == "PASS"
    assert out["phase_gate"] == "FLAG"
    assert out["phase_flags"] == ["setup"]
    assert out["phase_deltas"]["setup"] == pytest.approx(0.6, abs=0.01)
    assert out["phase_deltas"]["solve"] == pytest.approx(0.01,
                                                         abs=0.001)


def test_bench_gate_phase_normalization_and_noise_floor(tmp_path):
    prev = {"pairs_per_second": 700_000,
            "session_calibration": {"best_of_5_seconds": 0.5},
            "phase_seconds": {"setup": 1.0, "solve": 5.0,
                              "observe": 0.002, "finalize": 0.1}}
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(prev))
    # 10% slower session (calibration 0.55): raw +12% solve seconds
    # normalize back into band -> PASS...
    cur = {"pairs_per_second": 630_000,
           "session_calibration": {"best_of_5_seconds": 0.55},
           "phase_seconds": {"setup": 1.1, "solve": 5.6,
                             "observe": 0.02, "finalize": 0.11}}
    out = bench._regression_gate(cur, str(tmp_path))
    assert out["phase_gate"] == "PASS"
    # ...observe grew 10x but carried 0.04% of the run: noise floor
    # keeps it out of the flags (it still shows in the deltas).
    assert "observe" not in out["phase_flags"]
    assert out["phase_deltas"]["observe"] > 1.0


def test_bench_gate_no_phase_data_is_silent(tmp_path):
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(
        {"pairs_per_second": 700_000,
         "session_calibration": {"best_of_5_seconds": 0.5}}))
    cur = {"pairs_per_second": 700_000,
           "session_calibration": {"best_of_5_seconds": 0.5},
           "phase_seconds": {"setup": 1.0, "solve": 5.0}}
    out = bench._regression_gate(cur, str(tmp_path))
    assert "phase_gate" not in out  # pre-PR8 baseline: no phase data
