"""Batched multi-problem fleet executor (solver/fleet.py).

Contract under test: every fleet member runs the reference-parity
per-pair MVP trajectory — same selection rule, same pair algebra, same
f-update association — so per-problem (alpha, b, iterations, n_sv) must
match a sequential ``solve()`` of the same (sub)problem; finished
problems freeze bit-exactly while stragglers run; OvO-style row masks
are equivalent to explicit subset copies; and the multiclass /
C-sweep routers produce the sequential path's models in a fraction of
the dispatches.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.fleet import (FleetProblem, _fleet_bucket,
                                    fleet_chunks, solve_fleet)
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, max_iter=100_000)


def _blobs(n=300, d=10, seed=3, sep=1.2):
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=n, d=d, seed=seed, sep=sep)


def test_single_problem_trajectory_parity():
    """A fleet of one IS the sequential per-pair engine: identical
    iteration count, alpha, b, and convergence flag (the kernel-row
    matmul shape differs, so allow float32 round-off — on the CPU
    backend it lands bit-exact)."""
    x, y = _blobs()
    ref = solve(x, y, CFG)
    res = solve_fleet(x, [FleetProblem(y=y)], CFG)[0]
    assert res.converged and ref.converged
    assert res.iterations == ref.iterations
    assert abs(res.b - ref.b) < 5e-3
    np.testing.assert_allclose(res.alpha, ref.alpha, rtol=1e-4, atol=1e-5)
    assert res.n_sv == ref.n_sv


def test_mixed_convergence_freezes_finished_problems():
    """One problem converges two orders of magnitude before the other;
    the early finisher's state must be EXACTLY its solo solution (frozen
    by the gated no-op updates), and its iteration count must not grow
    while the straggler runs."""
    x, y = _blobs(sep=3.0)  # wide margin: converges in few pairs
    xh, yh = _blobs(seed=9, sep=0.25)  # barely separated: many more pairs
    # Shared rows: problem 0 = easy labels, problem 1 = hard labels on
    # the hard data. Share X by concatenating and masking disjoint rows.
    x_all = np.concatenate([x, xh])
    n = len(x)
    mask_easy = np.arange(2 * n) < n
    y0 = np.concatenate([y, np.ones(n, np.int32)])
    y1 = np.concatenate([np.ones(n, np.int32), yh])
    res = solve_fleet(x_all, [
        FleetProblem(y=y0, row_mask=mask_easy),
        FleetProblem(y=y1, row_mask=~mask_easy, c=500.0),
    ], CFG)
    ref0 = solve(x, y, CFG)
    ref1 = solve(xh, yh, CFG.replace(c=500.0))
    assert ref1.iterations > 3 * ref0.iterations  # genuinely mixed
    for res_j, ref_j in ((res[0], ref0), (res[1], ref1)):
        assert res_j.converged
        assert res_j.iterations == ref_j.iterations
        assert abs(res_j.b - ref_j.b) < 5e-3
        np.testing.assert_allclose(res_j.alpha, ref_j.alpha,
                                   rtol=1e-4, atol=1e-5)


def test_row_masks_equal_explicit_subset_copies():
    """OvO's masked-subset problems vs sequential solves on explicit
    x[mask] copies: the returned alpha is subset-aligned and must agree
    per problem."""
    rng = np.random.default_rng(11)
    n_per = 120
    centers = np.array([[2.0, 0, 0], [0, 2.0, 0], [0, 0, 2.0]], np.float32)
    xs = [rng.normal(size=(n_per, 3)).astype(np.float32) * 0.7 + c
          for c in centers]
    x = np.concatenate(xs)
    lab = np.repeat(np.arange(3), n_per)
    problems, refs = [], []
    for a in range(3):
        for b in range(a + 1, 3):
            mask = (lab == a) | (lab == b)
            ypm = np.where(lab == a, 1, -1).astype(np.int32)
            problems.append(FleetProblem(y=ypm, row_mask=mask))
            refs.append(solve(x[mask], ypm[mask], CFG))
    res = solve_fleet(x, problems, CFG)
    for r, ref in zip(res, refs):
        assert r.converged
        assert r.alpha.shape == ref.alpha.shape  # subset-aligned
        assert r.iterations == ref.iterations
        assert abs(r.b - ref.b) < 5e-3
        np.testing.assert_allclose(r.alpha, ref.alpha, rtol=1e-4,
                                   atol=1e-5)


def test_per_problem_c_sweep_matches_sequential():
    """Per-problem C rides a traced (k, 2) value: every C in one
    compiled executor, each matching its sequential solve."""
    x, y = _blobs(sep=0.8)
    cs = [0.5, 2.0, 8.0, 32.0]
    res = solve_fleet(x, [FleetProblem(y=y, c=c) for c in cs], CFG)
    assert all(r.dispatches == res[0].dispatches for r in res)
    for c, r in zip(cs, res):
        ref = solve(x, y, CFG.replace(c=c))
        assert r.converged
        assert r.iterations == ref.iterations
        assert abs(r.b - ref.b) < 5e-3
        assert r.alpha.max() <= c + 1e-5


def test_class_weights_apply_per_problem():
    x, y = _blobs(sep=0.8)
    cfg = CFG.replace(weight_pos=2.0, weight_neg=0.5)
    res = solve_fleet(x, [FleetProblem(y=y)], cfg)[0]
    ref = solve(x, y, cfg)
    assert res.converged
    cp, cn = cfg.c_bounds()
    assert res.alpha[y > 0].max() <= cp + 1e-5
    assert res.alpha[y < 0].max() <= cn + 1e-5
    assert abs(res.b - ref.b) < 5e-3


def test_budget_mode_refreshes_extrema():
    x, y = _blobs(sep=0.6)
    cfg = CFG.replace(budget_mode=True, max_iter=500)
    res = solve_fleet(x, [FleetProblem(y=y), FleetProblem(y=-y)], cfg)
    for r in res:
        assert r.iterations == 500
        # budget exit reports the HONEST gap at the real epsilon
        assert np.isfinite(r.b_hi) and np.isfinite(r.b_lo)


def test_fleet_bucket_pads_with_dummies():
    """3 problems bucket to 4; the dummy slot must not perturb results
    or deadlock the loop."""
    assert _fleet_bucket(3) == 4
    assert _fleet_bucket(16) == 16
    assert _fleet_bucket(45 % 16) == 16  # OvO tail chunk 13 -> 16
    x, y = _blobs()
    res = solve_fleet(x, [FleetProblem(y=y), FleetProblem(y=-y),
                          FleetProblem(y=y, c=2.0)], CFG)
    assert len(res) == 3
    assert all(r.converged for r in res)
    assert res[0].stats["fleet"]["bucket"] == 4


def test_fleet_chunks_cover_in_order():
    items = list(range(45))
    chunks = fleet_chunks(items, 16)
    assert [len(c) for c in chunks] == [16, 16, 13]
    assert [i for c in chunks for i in c] == items


def test_validation_errors():
    x, y = _blobs(n=50)
    with pytest.raises(ValueError, match="MVP"):
        solve_fleet(x, [FleetProblem(y=y)],
                    CFG.replace(selection="second_order"))
    with pytest.raises(ValueError, match="accuracy"):
        solve_fleet(x, [FleetProblem(y=y)], CFG.replace(compensated=True))
    with pytest.raises(ValueError, match="shape"):
        solve_fleet(x, [FleetProblem(y=y[:10])], CFG)
    with pytest.raises(ValueError, match="masked labels"):
        solve_fleet(x, [FleetProblem(y=np.arange(50))], CFG)
    with pytest.raises(ValueError, match="power of two"):
        SVMConfig(fleet_size=5)
    with pytest.raises(ValueError, match="power of two"):
        SVMConfig(fleet_size=128)
    assert solve_fleet(x, [], CFG) == []


def test_multiclass_router_fleet_matches_sequential():
    """train_multiclass(use_fleet=True) must produce the sequential
    path's submodels (same SV sets, same predictions) in fewer
    dispatches — both strategies."""
    from dpsvm_tpu.models.multiclass import predict_multiclass, train_multiclass

    rng = np.random.default_rng(5)
    n_per = 100
    centers = np.array([[2.0, 0, 0, 0], [0, 2.0, 0, 0], [0, 0, 2.0, 0]],
                       np.float32)
    x = np.concatenate([
        rng.normal(size=(n_per, 4)).astype(np.float32) * 0.8 + c
        for c in centers])
    y = np.repeat([3, 4, 5], n_per)
    for strategy in ("ovr", "ovo"):
        m_f, r_f = train_multiclass(x, y, CFG, strategy=strategy,
                                    backend="single", use_fleet=True)
        m_s, r_s = train_multiclass(x, y, CFG, strategy=strategy,
                                    backend="single", use_fleet=False)
        assert all(r.converged for r in r_f)
        assert len(r_f) == len(r_s)
        for a, b in zip(r_f, r_s):
            assert abs(a.b - b.b) < 5e-3
            assert a.n_sv == b.n_sv
        np.testing.assert_array_equal(predict_multiclass(m_f, x),
                                      predict_multiclass(m_s, x))
        disp_fleet = sum(r.dispatches for r in r_f
                         if r.stats["fleet"]["index"] == 0)
        disp_seq = sum(r.dispatches for r in r_s)
        assert disp_fleet < disp_seq


def test_multiclass_router_force_raises_on_ineligible():
    from dpsvm_tpu.models.multiclass import train_multiclass

    x = np.random.default_rng(0).normal(size=(60, 3)).astype(np.float32)
    y = np.repeat([0, 1, 2], 20)
    with pytest.raises(ValueError, match="use_fleet=True"):
        train_multiclass(x, y, CFG.replace(engine="block"),
                         strategy="ovr", backend="single", use_fleet=True)


def test_multiclass_router_respects_mesh_auto():
    """On the 8-virtual-device platform, backend='auto' resolves to the
    mesh — the fleet must NOT hijack it (sequential mesh solves)."""
    from dpsvm_tpu.models.multiclass import _fleet_eligible

    assert not _fleet_eligible(CFG, "auto", None, None)
    assert _fleet_eligible(CFG, "single", None, None)
    assert not _fleet_eligible(CFG, "single", None, trainer=object())
    assert not _fleet_eligible(CFG.replace(fleet_size=1), "single", None,
                               None)


def test_svc_c_sweep_estimator_facade():
    from dpsvm_tpu.estimators import SVC, svc_c_sweep

    x, y = _blobs(sep=0.8)
    cs = [0.5, 4.0]
    # backend='single' is the explicit opt-in: the test platform shows
    # 8 virtual devices, where 'auto' (= maybe-mesh) is refused.
    swept = svc_c_sweep(x, y, cs, gamma=0.2, tol=1e-3, backend="single")
    assert [e.C for e in swept] == cs
    for c, est in zip(cs, swept):
        solo = SVC(C=c, gamma=0.2, tol=1e-3, backend="single").fit(x, y)
        assert est.score(x, y) == pytest.approx(solo.score(x, y),
                                                abs=0.02)
        np.testing.assert_array_equal(est.n_support_, solo.n_support_)
    with pytest.raises(ValueError, match="binary-only"):
        svc_c_sweep(x, np.arange(len(y)) % 3, [1.0], backend="single")
    with pytest.raises(ValueError, match="single-chip"):
        svc_c_sweep(x, y, [1.0])  # auto on an 8-device host
    with pytest.raises(ValueError, match="single-chip"):
        svc_c_sweep(x, y, [1.0], backend="mesh")
    with pytest.raises(ValueError, match="fleet executor"):
        svc_c_sweep(x, y, [1.0], backend="single", engine="block")


def test_fleet_device_dryrun_multi_device():
    """8-virtual-device dryrun: the fleet must run (and agree) on ANY
    explicit device of the platform mesh — placement must not leak into
    results (the same guarantee the sequential solver's deterministic
    tie-breaks give the mesh engines)."""
    import jax

    devs = jax.devices()
    assert len(devs) >= 8  # conftest forces the 8-device CPU platform
    x, y = _blobs(n=200)
    base = solve_fleet(x, [FleetProblem(y=y)], CFG, device=devs[0])[0]
    for d in (devs[3], devs[7]):
        r = solve_fleet(x, [FleetProblem(y=y)], CFG, device=d)[0]
        assert r.iterations == base.iterations
        assert r.b == base.b
        np.testing.assert_array_equal(r.alpha, base.alpha)
