"""Serving engine v2 tests (dpsvm_tpu/serving — ISSUE 10): registry
versioning + atomic hot swap under sustained enqueue, corrupted-npz
rejection, EDF scheduling + deadline-miss accounting, union-group
coalescing across models, async-dispatch parity with the model layer,
observability surfaces, and the scrape-during-close ordering contract."""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dpsvm_tpu.config import ObsConfig, ServeConfig, SVMConfig
from dpsvm_tpu.models.multiclass import (MulticlassSVM, decision_matrix,
                                         predict_multiclass,
                                         train_multiclass)
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.serving import (ModelLoadError, ModelRegistry,
                               ServingEngine, load_model_file)

CFG = SVMConfig(c=5.0, gamma=0.25, epsilon=1e-3, chunk_iters=256)


@pytest.fixture(scope="module")
def three_class():
    rng = np.random.default_rng(31)
    xs, ys = [], []
    for k in range(3):
        c = np.zeros(5, np.float32)
        c[k] = 2.5
        xs.append(rng.normal(size=(70, 5)).astype(np.float32) * 0.7 + c)
        ys.append(np.full(70, k))
    x, y = np.concatenate(xs), np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.fixture(scope="module")
def two_versions(three_class):
    """(v1 model, v2 model, x): same problem, different C — different
    SVs, so v1/v2 have DIFFERENT unions (the realistic retrain swap)."""
    x, y = three_class
    m1, _ = train_multiclass(x, y, CFG, strategy="ovr")
    m2, _ = train_multiclass(x, y, CFG.replace(c=1.5), strategy="ovr")
    return m1, m2, x


@pytest.fixture()
def model_files(two_versions, tmp_path):
    m1, m2, _ = two_versions
    p1, p2 = str(tmp_path / "v1.npz"), str(tmp_path / "v2.npz")
    m1.save(p1)
    m2.save(p2)
    return p1, p2


def _engine(**kw):
    kw.setdefault("buckets", (16, 64))
    return ServingEngine(ServeConfig(**kw))


# ------------------------------------------------------------- registry

def test_engine_parity_with_model_layer(two_versions):
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", m1)
    q = np.asarray(x[:50], np.float32)
    np.testing.assert_allclose(eng.decision(q), decision_matrix(m1, q),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(eng.predict(q),
                                  predict_multiclass(m1, q))
    eng.close()


def test_registry_versioning_and_routing(model_files, two_versions):
    p1, p2 = model_files
    m1, m2, x = two_versions
    eng = _engine()
    e1 = eng.register("m", p1)
    assert (e1.version, e1.source) == (1, p1)
    e2 = eng.swap("m", p2)
    assert e2.version == 2
    assert eng.registry.get("m") is e2
    # post-swap requests answer from v2
    q = np.asarray(x[:20], np.float32)
    np.testing.assert_allclose(eng.decision(q), decision_matrix(m2, q),
                               rtol=1e-5, atol=1e-5)
    assert eng.hot_swaps.value == 1
    with pytest.raises(KeyError, match="no model"):
        eng.swap("typo", p1)
    with pytest.raises(KeyError, match="name required"):
        eng.register("second", p1) and eng.registry.get(None)
    eng.close()


def test_hot_swap_under_sustained_enqueue(model_files, two_versions):
    """The acceptance contract: requests keep arriving while the swap
    happens — zero failed/dropped across it, every pre-swap request
    answered by v1 (no stale-model reads the OTHER way either: nothing
    submitted after the flip may see v1)."""
    p1, p2 = model_files
    m1, m2, x = two_versions
    eng = _engine()
    eng.register("m", p1)
    q = np.asarray(x, np.float32)
    ref1, ref2 = decision_matrix(m1, q), decision_matrix(m2, q)

    tickets = {}
    for i in range(10):  # sustained enqueue, interleaved with pumping
        tickets[eng.submit(q[i * 4:(i + 1) * 4])] = ("v1", i)
        if i % 3 == 0:
            eng.pump()
    eng.swap("m", p2)  # atomic flip mid-stream
    for i in range(10, 20):
        tickets[eng.submit(q[i * 4:(i + 1) * 4])] = ("v2", i)
        if i % 3 == 0:
            eng.pump()
    done = eng.drain()

    assert sorted(done) == sorted(tickets)  # zero dropped
    for ticket, (want, i) in tickets.items():
        res = done[ticket]
        assert res.verdict == "ok"  # zero failed
        ref = ref1 if want == "v1" else ref2
        assert res.version == (1 if want == "v1" else 2)
        np.testing.assert_allclose(res.decision,
                                   ref[i * 4:(i + 1) * 4],
                                   rtol=1e-5, atol=1e-5)
    eng.close()


def test_labels_use_serving_version_across_swap(two_versions):
    """Requests queued before a swap were answered by the OLD entry's
    columns; their labels must fold through THAT entry — a fresh
    registry lookup would apply the new version's class set/strategy
    to the wrong column count (here: 3-class OvR -> binary)."""
    m1, _, x = two_versions
    y_pm = np.where(np.arange(len(x)) % 2 == 0, 1, -1).astype(np.int32)
    rng = np.random.default_rng(5)
    binary = SVMModel(
        sv_x=np.asarray(x[:40], np.float32),
        sv_alpha=rng.random(40).astype(np.float32) + 0.01,
        sv_y=y_pm[:40], b=0.1, kernel=KernelParams("rbf", 0.3))
    eng = _engine()
    eng.register("m", m1)
    q = np.asarray(x[:6], np.float32)
    want = predict_multiclass(m1, q)
    t_old = eng.submit(q)          # queued against the 3-column v1
    eng.swap("m", binary)          # live model is now 1-column binary
    t_new = eng.submit(q)
    done = eng.drain()
    assert done[t_old].decision.shape == (6, 3)
    np.testing.assert_array_equal(done[t_old].labels(), want)
    assert done[t_new].decision.shape == (6, 1)
    assert set(np.unique(done[t_new].labels())) <= {-1, 1}
    eng.close()


def test_corrupted_npz_leaves_prior_version_serving(model_files,
                                                    two_versions,
                                                    tmp_path):
    p1, _ = model_files
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", p1)
    q = np.asarray(x[:20], np.float32)
    ref = decision_matrix(m1, q)

    # Truncated zip (driver killed mid-write).
    raw = open(p1, "rb").read()
    p_trunc = str(tmp_path / "trunc.npz")
    with open(p_trunc, "wb") as fh:
        fh.write(raw[:len(raw) // 2])
    with pytest.raises(ModelLoadError):
        eng.swap("m", p_trunc)

    # Partial npz: loadable zip, missing member arrays.
    p_partial = str(tmp_path / "partial.npz")
    np.savez(p_partial, model_type="multiclass", strategy="ovr",
             classes=np.arange(3), n_models=3)  # no m{i}_* payloads
    with pytest.raises(ModelLoadError):
        eng.swap("m", p_partial)

    # Garbage bytes.
    p_junk = str(tmp_path / "junk.npz")
    with open(p_junk, "wb") as fh:
        fh.write(b"not a zip at all")
    with pytest.raises(ModelLoadError):
        eng.swap("m", p_junk)

    # The prior version never stopped serving, and stayed v1.
    assert eng.registry.get("m").version == 1
    np.testing.assert_allclose(eng.decision(q), ref, rtol=1e-5,
                               atol=1e-5)
    assert eng.hot_swaps.value == 0
    eng.close()


def test_load_model_file_rejects_unservable(tmp_path):
    p = str(tmp_path / "svr.npz")
    np.savez(p, model_type="svr")
    with pytest.raises(ModelLoadError, match="svr"):
        load_model_file(p)


def test_registry_prepare_failure_is_atomic(model_files):
    """A prepare hook that raises (staging OOM, warm-up failure) must
    leave the registry untouched."""
    p1, p2 = model_files
    calls = []
    fail_next = [False]

    def prepare(entry):
        calls.append(entry.version)
        if fail_next[0]:
            fail_next[0] = False
            raise RuntimeError("synthetic staging failure")

    reg = ModelRegistry(prepare=prepare)
    reg.register("m", p1)
    fail_next[0] = True
    with pytest.raises(RuntimeError):
        reg.register("m", p2)
    assert reg.get("m").version == 1
    assert calls == [1, 2]
    # The failed attempt did not burn the version: retry lands on 2.
    assert reg.register("m", p2).version == 2


# ---------------------------------------------------- deadlines and EDF

def test_expired_request_counted_not_silently_served(two_versions):
    """A request admitted past its deadline is shed with an explicit
    verdict and counted — never silently served late."""
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", m1)
    t = eng.submit(np.asarray(x[:4], np.float32), deadline_ms=1e-4)
    time.sleep(0.005)  # deadline passes while queued
    done = eng.drain()
    assert done[t].verdict == "expired"
    assert done[t].decision is None
    assert done[t].deadline_missed
    assert eng.deadline_misses.value == 1
    assert eng.expired.value == 1
    assert eng.snapshot()["per_model"]["m"]["expired"] == 1
    eng.close()


def test_late_completion_counts_as_miss(two_versions, monkeypatch):
    """A request dispatched in time but COMPLETED past its deadline is
    served (real decision rows) and still counted as a miss."""
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", m1)
    q = np.asarray(x[:4], np.float32)
    ref = decision_matrix(m1, q)
    t = eng.submit(q, deadline_ms=50.0)
    # Make completion observably late without racing the dispatch:
    # stall between forming and completing.
    orig = eng._dispatcher._materialize

    def slow(item, _orig=orig):
        time.sleep(0.08)
        return _orig(item)

    monkeypatch.setattr(eng._dispatcher, "_materialize", slow)
    done = eng.drain()
    assert done[t].verdict == "late"
    np.testing.assert_allclose(done[t].decision, ref, rtol=1e-5,
                               atol=1e-5)
    assert eng.deadline_misses.value == 1
    assert eng.expired.value == 0  # served, not shed
    eng.close()


def test_edf_orders_batch_forming(two_versions):
    """Tight-deadline requests ride the next dispatch even when they
    arrived last (earliest-deadline-first forming)."""
    m1, _, x = two_versions
    eng = _engine(buckets=(16,))  # one 16-row bucket: forming must pick
    eng.register("m", m1)
    q = np.asarray(x, np.float32)
    loose = [eng.submit(q[i * 8:(i + 1) * 8], deadline_ms=10_000.0)
             for i in range(2)]  # 16 rows: fills the bucket alone
    tight = eng.submit(q[16:24], deadline_ms=500.0)  # arrives LAST
    eng.pump()  # forms exactly one bucket
    eng.pump()  # completes it (double-buffer: collect on next step)
    done = eng.results()
    assert tight in done  # the tight request rode the first dispatch
    assert not all(t in done for t in loose)
    eng.drain()
    eng.close()


def test_backpressure_bounds_queue(two_versions):
    m1, _, x = two_versions
    eng = _engine(buckets=(16,), max_pending=32)
    eng.register("m", m1)
    q = np.asarray(x[:8], np.float32)
    for _ in range(12):  # 96 rows >> max_pending
        eng.submit(q)
        assert eng.scheduler.queue_rows < 32 + q.shape[0]
    eng.drain()
    eng.close()


# ----------------------------------------------------------- coalescing

def test_union_sharing_models_coalesce(two_versions):
    """Two registered models with byte-identical unions answer from ONE
    bucket dispatch — and each request still gets its own model's
    columns exactly."""
    m1, _, x = two_versions
    eng = _engine()
    eng.register("a", m1)
    eng.register("b", m1)  # same union bytes -> same group
    q = np.asarray(x[:30], np.float32)
    ref = decision_matrix(m1, q)
    d0 = eng._dispatches
    ta = eng.submit(q[:10], model="a")
    tb = eng.submit(q[10:30], model="b")
    done = eng.drain()
    assert eng._dispatches == d0 + 1  # ONE coalesced dispatch
    assert eng.coalesced.value == 1
    np.testing.assert_allclose(done[ta].decision, ref[:10],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(done[tb].decision, ref[10:30],
                               rtol=1e-5, atol=1e-5)
    eng.close()


def test_distinct_unions_do_not_coalesce(two_versions):
    m1, m2, x = two_versions
    eng = _engine()
    eng.register("a", m1)
    eng.register("b", m2)
    q = np.asarray(x[:8], np.float32)
    d0 = eng._dispatches
    ta = eng.submit(q, model="a")
    tb = eng.submit(q, model="b")
    done = eng.drain()
    assert eng._dispatches == d0 + 2
    assert eng.coalesced.value == 0
    np.testing.assert_allclose(done[ta].decision, decision_matrix(m1, q),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(done[tb].decision, decision_matrix(m2, q),
                               rtol=1e-5, atol=1e-5)
    eng.close()


def test_oversized_request_loops_top_bucket(two_versions):
    m1, _, x = two_versions
    eng = _engine(buckets=(16,))
    eng.register("m", m1)
    q = np.asarray(np.tile(x[:30], (2, 1)), np.float32)  # 60 rows > 16
    ref = decision_matrix(m1, q)
    t = eng.submit(q)
    done = eng.drain()
    np.testing.assert_allclose(done[t].decision, ref, rtol=1e-5,
                               atol=1e-5)
    eng.close()


def test_binary_model_and_f64_routing(three_class):
    """Binary models serve through the engine; a risk-routed model's
    columns come from the exact host float64 path — float64 queries
    stay unquantized (the serve.py exact-path contract)."""
    from dpsvm_tpu.predict import decision_function

    rng = np.random.default_rng(2)
    big = SVMModel(
        sv_x=rng.normal(size=(600, 8)).astype(np.float32),
        sv_alpha=(rng.random(600).astype(np.float32) + 0.01) * 6e5,
        sv_y=np.where(rng.random(600) < 0.5, 1, -1).astype(np.int32),
        b=0.05, kernel=KernelParams("rbf", 0.3))
    eng = _engine(buckets=(32,))
    entry = eng.register("big", big)
    assert entry.f64_cols.size == 1
    q64 = (rng.normal(size=(16, 8)) * (1 + 1e-9)).astype(np.float64)
    want = decision_function(big, q64, precision="float64")
    t = eng.submit(q64)
    done = eng.drain()
    np.testing.assert_allclose(done[t].decision[:, 0], want, rtol=1e-6)
    eng.close()


def test_empty_union_served():
    kp = KernelParams("rbf", 0.25)
    models = [SVMModel(sv_x=np.zeros((0, 4), np.float32),
                       sv_alpha=np.zeros((0,), np.float32),
                       sv_y=np.zeros((0,), np.int32), b=b0, kernel=kp)
              for b0 in (0.5, -0.25)]
    m = MulticlassSVM(classes=np.arange(2), models=models,
                      strategy="ovr")
    eng = _engine(buckets=(16,))
    eng.register("empty", m)
    dec = eng.decision(np.zeros((3, 4), np.float32))
    np.testing.assert_array_equal(
        dec, np.broadcast_to([-0.5, 0.25], (3, 2)).astype(np.float32))
    eng.close()


def test_engine_mesh_config_and_bad_width(two_versions):
    # num_devices>1 is no longer a refusal: it engages the mesh union
    # group (ISSUE 16; the bitwise pin lives in test_serve_replicas).
    m1, _, x = two_versions
    mesh_eng = ServingEngine(ServeConfig(buckets=(16,), num_devices=2))
    try:
        mesh_eng.register("m", m1)
        assert mesh_eng.snapshot()["union_mesh_devices"] == 2
    finally:
        mesh_eng.close()
    eng = _engine()
    eng.register("m", m1)
    with pytest.raises(ValueError, match="must be"):
        eng.submit(np.zeros((4, 3), np.float32))
    eng.close()


# -------------------------------------------------------- observability

def test_metrics_and_openmetrics_labels(two_versions):
    m1, m2, x = two_versions
    eng = _engine(metrics_port=0)
    eng.register("a", m1)
    eng.register("b", m2)
    q = np.asarray(x[:12], np.float32)
    eng.submit(q, model="a")
    eng.submit(q, model="b", deadline_ms=1e-4)
    time.sleep(0.002)
    eng.drain()
    eng.swap("a", m2)
    snap = eng.snapshot()
    assert snap["hot_swaps"] == 1
    assert snap["per_model"]["b"]["deadline_misses"] == 1
    assert snap["batch_occupancy"]["count"] >= 1
    assert snap["queue_depth"] == 0

    with urllib.request.urlopen(eng.exporter.url, timeout=10) as resp:
        text = resp.read().decode()
    assert text.endswith("# EOF\n")
    assert 'serving_requests_total{model="a"} 1' in text
    assert 'serving_deadline_misses_total{model="b"} 1' in text
    assert 'serving_hot_swaps_total{model="a"} 1' in text
    assert 'serving_model_version{model="a"} 2' in text
    assert "serving_batch_occupancy" in text
    # queue-depth gauge appears once work is queued
    eng.submit(q, model="b")
    with urllib.request.urlopen(eng.exporter.url, timeout=10) as resp:
        text = resp.read().decode()
    assert 'serving_queue_depth{model="b"} 1' in text
    eng.drain()
    eng.close()


def test_serve_runlog_and_report_columns(two_versions, tmp_path):
    """The serve run log records per-dispatch chunk records plus the
    hot-swap event, and `cli obs report` surfaces the engine columns
    (deadline misses / swaps / occupancy)."""
    from dpsvm_tpu.obs.analyze import load_runs, render_report, summarize_run

    m1, m2, x = two_versions
    eng = _engine(obs=ObsConfig(enabled=True,
                                runlog_dir=str(tmp_path)))
    eng.register("m", m1)
    q = np.asarray(x[:20], np.float32)
    eng.submit(q)
    eng.drain()
    eng.swap("m", m2)
    eng.submit(q, deadline_ms=1e-4)
    time.sleep(0.002)
    eng.drain()
    path = eng._obs.path
    eng.close()

    runs = load_runs([path])
    assert len(runs) == 1
    s = summarize_run(runs[0])
    assert s["tool"] == "serve"
    assert s["deadline_misses"] == 1
    assert s["hot_swaps"] == 1
    assert s["pairs"] == 20  # chunk rows ride the pairs fields
    assert s["batch_occupancy_mean"] is not None
    assert [e for e in s["events"] if e == "hot_swap"]
    txt = render_report([s])
    assert "miss=1 swap=1" in txt
    # solver-run rows render "-" in the serve column (no crash)
    assert "serve" in txt.splitlines()[0]


# ------------------------------------------------- scrape-during-close

def _hammer_scrapes(url, stop, errors, bodies):
    while not stop.is_set():
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                body = resp.read().decode()
                if resp.status != 200 or not body.endswith("# EOF\n"):
                    errors.append(("bad response", resp.status,
                                   body[-50:]))
                bodies.append(len(body))
        except (urllib.error.URLError, ConnectionError, OSError):
            pass  # clean refusal after shutdown — the contract


def test_scrape_racing_engine_close(two_versions):
    """A scrape concurrent with ServingEngine.close() gets a full
    exposition, the # EOF stub, or a clean connection error — never a
    half-torn-down read or a 500."""
    m1, _, x = two_versions
    eng = _engine(metrics_port=0)
    eng.register("m", m1)
    eng.submit(np.asarray(x[:8], np.float32))
    eng.drain()
    url = eng.exporter.url
    stop, errors, bodies = threading.Event(), [], []
    threads = [threading.Thread(target=_hammer_scrapes,
                                args=(url, stop, errors, bodies))
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # scrapes in flight
    eng.close()
    time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors
    assert bodies  # the hammer actually scraped while live


def test_scrape_racing_engine_drain(two_versions):
    """The ISSUE 15 extension of the close-race contract to DRAIN: a
    scrape concurrent with an active drain() under sustained submits
    reads complete live expositions throughout — drain never flips
    the _closing stub and never tears instrument state."""
    m1, _, x = two_versions
    eng = _engine(metrics_port=0)
    eng.register("m", m1)
    q = np.asarray(x[:4], np.float32)
    url = eng.exporter.url
    stop, errors, bodies = threading.Event(), [], []
    hammer = threading.Thread(target=_hammer_scrapes,
                              args=(url, stop, errors, bodies))
    hammer.start()
    try:
        for _ in range(6):  # sustained submit -> drain cycles
            for _ in range(8):
                eng.submit(q)
            eng.drain()
        time.sleep(0.05)
    finally:
        stop.set()
        hammer.join(timeout=5)
    assert not errors, errors
    assert bodies  # scrapes really ran during the drain windows
    eng.close()


def test_close_during_active_drain_is_idempotent(two_versions):
    """ISSUE 15 satellite: close() arriving DURING an active drain()
    waits for it on the lifecycle lock and tears down exactly once;
    drain() after close is a no-op; double-close is a no-op — every
    interleaving of the double-shutdown is safe."""
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", m1)
    q = np.asarray(x, np.float32)
    tickets = [eng.submit(q[i * 4:(i + 1) * 4]) for i in range(12)]
    done = {}
    started = threading.Event()

    real_pump = eng.pump

    def _pump_marked():
        started.set()  # close() below provably races an ACTIVE drain
        return real_pump()

    eng.pump = _pump_marked

    def _drain():
        done.update(eng.drain())

    th = threading.Thread(target=_drain)
    th.start()
    started.wait(timeout=10)
    eng.close()  # races the active drain; must wait, then close once
    th.join(timeout=60)
    assert not th.is_alive()
    assert eng._closed
    assert sorted(done) == sorted(tickets)  # the drain finished first
    assert all(r.verdict == "ok" for r in done.values())
    # post-close drain/close are no-ops, not errors
    assert eng.drain() == {}
    eng.close()


def test_journal_write_fsyncs_before_rename(two_versions, tmp_path,
                                            monkeypatch):
    """ISSUE 15 satellite: the registry journal's atomic rewrite must
    be DURABLE — tmp fsynced before the rename, directory after —
    or the PR 13 crash-recovery guarantee stops at process kills and
    silently excludes power loss."""
    import os
    import stat

    m1, _, _ = two_versions
    jp = str(tmp_path / "registry.journal")
    calls = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(os, "fsync", lambda fd: (
        calls.append(("fsync",
                      "dir" if stat.S_ISDIR(os.fstat(fd).st_mode)
                      else "file")), real_fsync(fd))[1])
    monkeypatch.setattr(os, "replace", lambda a, b: (
        calls.append(("replace", os.path.basename(b))),
        real_replace(a, b))[1])
    eng = _engine(journal_path=jp)
    p1 = str(tmp_path / "v1.npz")
    m1.save(p1)
    calls.clear()  # isolate the register's journal write
    eng.register("m", p1)
    journal_calls = [c for i, c in enumerate(calls)
                     if c[0] == "fsync"
                     or c[1] == "registry.journal"]
    assert journal_calls, calls
    order = [k for k, _ in journal_calls]
    assert order.index("fsync") < order.index("replace"), calls
    kinds = [d for k, d in journal_calls if k == "fsync"]
    assert "file" in kinds and "dir" in kinds, calls
    assert journal_calls[-1] == ("fsync", "dir"), calls
    eng.close()


def test_scrape_racing_predict_server_close(two_versions):
    """The same ordering contract on the v1 PredictServer (the ISSUE 10
    close()-vs-exporter satellite): endpoint down FIRST, in-flight
    renders answer the stub, never a half-torn-down registry read."""
    from dpsvm_tpu.serve import PredictServer

    m1, _, x = two_versions
    srv = PredictServer(m1, ServeConfig(buckets=(16,), metrics_port=0))
    srv.decision(np.asarray(x[:8], np.float32))
    url = srv.exporter.url
    stop, errors, bodies = threading.Event(), [], []
    threads = [threading.Thread(target=_hammer_scrapes,
                                args=(url, stop, errors, bodies))
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    srv.close()
    time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors
    assert bodies


# ---------------------------------- crash recovery + watchdog (ISSUE 13)

def test_journal_replay_after_simulated_crash(model_files, two_versions,
                                              tmp_path):
    """An engine with a registry journal dies (no close(), nothing
    flushed — the journal was written atomically at register/swap
    time); a new engine on the same journal replays the EXACT live
    set: same names, same versions, decisions identical per model."""
    p1, p2 = model_files
    _, _, x = two_versions
    jp = str(tmp_path / "registry.journal")
    eng = _engine(journal_path=jp)
    eng.register("m", p1)
    eng.swap("m", p2)          # version 2 is the live one
    eng.register("aux", p1)
    q = np.asarray(x[:16], np.float32)
    pre_m = eng.decision(q, model="m")
    pre_aux = eng.decision(q, model="aux")
    del eng  # crash: close() never runs

    eng2 = _engine(journal_path=jp)
    assert sorted(eng2._rehydrated) == ["aux", "m"]
    assert eng2.registry.get("m").version == 2
    assert eng2.registry.get("aux").version == 1
    np.testing.assert_array_equal(eng2.decision(q, model="m"), pre_m)
    np.testing.assert_array_equal(eng2.decision(q, model="aux"),
                                  pre_aux)
    # an unregister shrinks the journal too
    eng2.unregister("aux")
    eng2.close()
    eng3 = _engine(journal_path=jp)
    assert eng3.registry.names() == ["m"]
    eng3.close()


def test_journal_skips_object_models_and_refuses_corrupt(two_versions,
                                                         tmp_path):
    """Object-registered models are not journalable (nothing to
    replay); a corrupt journal file refuses construction LOUDLY."""
    import json

    m1, _, _ = two_versions
    jp = str(tmp_path / "registry.journal")
    eng = _engine(journal_path=jp)
    eng.register("obj", m1)  # in-memory object: journaled nowhere
    eng.close()
    assert json.load(open(jp))["models"] == {}
    eng2 = _engine(journal_path=jp)  # replays to an empty (valid) set
    assert eng2._rehydrated == []
    eng2.close()
    with open(jp, "w") as fh:
        fh.write('{"format_version": 1, "models": {tor')  # torn write
    with pytest.raises(ValueError, match="journal"):
        _engine(journal_path=jp)


def test_failed_replay_releases_port_and_sinks(tmp_path):
    """A journal replay failure aborts construction AFTER the metrics
    exporter bound its port and the compile sink registered — close()
    is unreachable on a half-built engine, so __init__ itself must
    tear those down: a supervisor retrying construction on a fixed
    port must see the REAL error again, not EADDRINUSE, and sinks
    must not accumulate per attempt."""
    import json
    import socket

    from dpsvm_tpu.obs import compilelog
    from dpsvm_tpu.serving.registry import ModelLoadError

    jp = str(tmp_path / "registry.journal")
    with open(jp, "w") as fh:
        json.dump({"format_version": 1, "models": {
            "ghost": {"source": str(tmp_path / "missing.npz"),
                      "version": 3}}}, fh)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    n_sinks = len(compilelog._sinks)
    for _ in range(2):  # second attempt would EADDRINUSE on a leak
        with pytest.raises(ModelLoadError):
            _engine(journal_path=jp, metrics_port=port)
    assert len(compilelog._sinks) == n_sinks


def test_corrupted_swap_seam_leaves_live_serving(model_files,
                                                 two_versions):
    """The swap_corrupt fault seam: the registry load reads
    deterministically corrupted bytes — the swap must be refused via
    the REAL validation path and the live version keeps serving."""
    from dpsvm_tpu.serving import ModelLoadError
    from dpsvm_tpu.testing import faults

    p1, p2 = model_files
    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", p1)
    q = np.asarray(x[:12], np.float32)
    ref = eng.decision(q)
    with faults.install(faults.FaultPlan.parse("swap_corrupt")) as plan:
        with pytest.raises(ModelLoadError):
            eng.swap("m", p2)
    assert plan.fired["swap_corrupt"] == 1
    assert eng.registry.get("m").version == 1
    np.testing.assert_array_equal(eng.decision(q), ref)
    eng.close()


def test_dispatch_fault_fails_batch_and_engine_survives(two_versions):
    """serve_dispatch seam: a raising dispatch fails THAT batch with
    explicit 'failed' verdicts + per-model counters; the next batch
    serves normally."""
    from dpsvm_tpu.testing import faults

    m1, _, x = two_versions
    eng = _engine()
    eng.register("m", m1)
    q = np.asarray(x[:12], np.float32)
    ref = eng.decision(q)
    with faults.install(
            faults.FaultPlan.parse("serve_dispatch@1")) as plan:
        ticket = eng.submit(q, model="m")
        done = eng.drain()
    assert plan.fired["serve_dispatch"] == 1
    res = done[ticket]
    assert res.verdict == "failed" and res.failed
    assert res.decision is None and res.labels() is None
    assert eng.dispatch_failures.value == 1
    assert eng.snapshot()["per_model"]["m"]["dispatch_failures"] == 1
    np.testing.assert_array_equal(eng.decision(q), ref)
    eng.close()


def test_failed_segment_chain_stops_dispatching(two_versions):
    """An oversized request whose mid-chain segment fails must not
    keep dispatching the remaining segments: the chain is dead, the
    request already carries its 'failed' verdict, and further device
    work would be pure waste."""
    from dpsvm_tpu.testing import faults

    m1, _, x = two_versions
    eng = _engine()  # buckets (16, 64): 200 rows = 4 segments
    eng.register("m", m1)
    big = np.repeat(np.asarray(x[:20], np.float32), 10, axis=0)
    assert big.shape[0] == 200
    with faults.install(
            faults.FaultPlan.parse("serve_dispatch@2")) as plan:
        ticket = eng.submit(big, model="m")
        done = eng.drain()
    assert done[ticket].verdict == "failed"
    assert eng.dispatch_failures.value == 1  # ONE failure, not four
    # Segment 2's issue failed the chain; segments 3 and 4 were never
    # dispatched (every dispatch passes the seam, so arrivals count
    # them).
    assert plan.arrivals["serve_dispatch"] == 2, plan.arrivals
    # and the engine still serves
    assert eng.decision(np.asarray(x[:8], np.float32)) is not None
    eng.close()


def test_watchdog_bounds_wedged_dispatch(two_versions, monkeypatch):
    """The dispatch watchdog (ServeConfig.dispatch_timeout_ms): a
    stalled materialization fails within the bound — explicit verdict,
    watchdog counter — and the pump keeps serving with the watchdog
    still armed."""
    from dpsvm_tpu.testing import faults

    monkeypatch.setattr(faults, "STALL_SECONDS", 3.0)
    m1, _, x = two_versions
    eng = _engine(dispatch_timeout_ms=150.0)
    eng.register("m", m1)
    q = np.asarray(x[:12], np.float32)
    ref = eng.decision(q)  # healthy (and timeout-supervised) baseline
    with faults.install(
            faults.FaultPlan.parse("serve_stall@1")) as plan:
        ticket = eng.submit(q, model="m")
        t0 = time.perf_counter()
        done = eng.drain()
        bounded = time.perf_counter() - t0
    assert plan.fired["serve_stall"] == 1
    assert done[ticket].verdict == "failed"
    assert bounded < 2.0, bounded  # the 3s stall never blocked us
    assert eng.watchdog_trips.value == 1
    np.testing.assert_array_equal(eng.decision(q), ref)
    eng.close()


def test_scrape_during_watchdog_race(two_versions, monkeypatch):
    """A /metrics scrape concurrent with a watchdog-supervised stall
    must see complete expositions throughout — including the
    serving_dispatch_failures family once the trip lands — and the
    engine must finish the drain bounded."""
    from dpsvm_tpu.testing import faults

    monkeypatch.setattr(faults, "STALL_SECONDS", 3.0)
    m1, _, x = two_versions
    eng = _engine(dispatch_timeout_ms=200.0, metrics_port=0)
    eng.register("m", m1)
    q = np.asarray(x[:12], np.float32)
    url = eng.exporter.url
    stop, errors, bodies = threading.Event(), [], []
    hammer = threading.Thread(target=_hammer_scrapes,
                              args=(url, stop, errors, bodies))
    hammer.start()
    try:
        with faults.install(faults.FaultPlan.parse("serve_stall@1")):
            ticket = eng.submit(q, model="m")
            done = eng.drain()
        time.sleep(0.05)  # at least one post-trip scrape
    finally:
        stop.set()
        hammer.join(timeout=5)
    assert not errors, errors
    assert bodies  # scrapes really ran during the stall window
    assert done[ticket].verdict == "failed"
    with urllib.request.urlopen(url, timeout=10) as resp:
        text = resp.read().decode()
    assert 'serving_dispatch_failures_total{model="m"} 1' in text
    assert "serving_watchdog_trips_total 1" in text
    eng.close()


# ----------------------------------------------------------- config/CLI

def test_deadline_config_validation():
    with pytest.raises(ValueError, match="deadline_ms"):
        ServeConfig(deadline_ms=0.0)
    with pytest.raises(ValueError, match="deadline_ms"):
        ServeConfig(deadline_ms=-5.0)
    assert ServeConfig(deadline_ms=100.0).deadline_ms == 100.0
    with pytest.raises(ValueError, match="dispatch_timeout_ms"):
        ServeConfig(dispatch_timeout_ms=0.0)
    with pytest.raises(ValueError, match="journal_path"):
        ServeConfig(journal_path="")
    assert ServeConfig(dispatch_timeout_ms=250.0).dispatch_timeout_ms \
        == 250.0


def test_cli_serve_registry_roundtrip(model_files, two_versions,
                                      capsys, monkeypatch, tmp_path):
    """`cli serve --registry` end to end in-process: route-prefixed
    rows, a mid-stream swap line, labels out in submit order."""
    import io

    from dpsvm_tpu import cli

    p1, p2 = model_files
    m1, _, x = two_versions
    want = predict_multiclass(m1, np.asarray(x[:3], np.float32))
    lines = ["m|" + ",".join(f"{v:.5f}" for v in row) for row in x[:3]]
    lines += ["", f"swap m={p2}", lines[0]]
    monkeypatch.setattr("sys.stdin", io.StringIO("\n".join(lines) + "\n"))
    rc = cli.main(["serve", "--registry", f"m={p1}",
                   "--deadline-ms", "5000", "--buckets", "16,64", "-q"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    assert out[:3] == [f"m {int(v)}" for v in want]
    assert len(out) == 4  # the post-swap row answered too


def test_cli_serve_registry_bad_spec(capsys):
    from dpsvm_tpu import cli

    rc = cli.main(["serve", "--registry", "noequals"])
    assert rc == 2
    assert "NAME=PATH" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Scheduler under CONCURRENT submit (ISSUE 20 satellite): the internal
# lock makes multi-threaded admission well-defined — seq numbers dense
# and FIFO, queue_rows/_entry_refs exact, EDF tie-break stable.
# ---------------------------------------------------------------------------
class _SchedEntry:
    """Minimal stand-in for LoadedModel: the scheduler only needs
    group_key() and hashability (refcount key)."""

    def __init__(self, name, key="g0"):
        self.name = name
        self._key = key

    def group_key(self, dtype):
        return (self._key, dtype)


def _sched():
    from dpsvm_tpu.serving.scheduler import Scheduler

    return Scheduler()


def test_scheduler_concurrent_submit_accounting_exact():
    """4 threads x 200 submits: seqs dense and unique, queue_rows and
    the per-entry refcounts exactly reconcile — the guarded-by
    contract (Scheduler._seq/queue_rows/_entry_refs under _lock)
    observed dynamically, not just statically."""
    sched = _sched()
    entries = [_SchedEntry(f"m{i}") for i in range(4)]
    per, rows_each = 200, 3
    start = threading.Barrier(4)

    def admit(entry, tid):
        start.wait()
        for i in range(per):
            sched.submit(entry, np.zeros((rows_each, 2), np.float32),
                         now=0.0, deadline_s=None,
                         ticket=tid * per + i, dtype="f32")

    threads = [threading.Thread(target=admit, args=(e, t),
                                name=f"dpsvm-test-admit-{t}")
               for t, e in enumerate(entries)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    n = 4 * per
    assert sched.queue_depth == n
    assert sched.queue_rows == n * rows_each
    assert sched.pending_entries() == set(entries)
    # Seqs are dense 1..n with no duplicates (no lost increments).
    batch, expired = sched.form(entries[0].group_key("f32"), now=0.0,
                                max_rows=10 ** 9)
    assert expired == []
    seqs = sorted(r.seq for r in batch)
    assert len(batch) == n and seqs == list(range(1, n + 1))
    assert sched.queue_rows == 0 and sched.pending_entries() == set()


def test_scheduler_edf_tiebreak_fifo_across_threads():
    """Equal deadlines pop in admission (seq) order even when the
    admissions raced on two threads; tighter deadlines still win."""
    sched = _sched()
    e = _SchedEntry("m")
    start = threading.Barrier(2)

    def admit(base):
        start.wait()
        for i in range(50):
            sched.submit(e, np.zeros((1, 2), np.float32), now=0.0,
                         deadline_s=5.0, ticket=base + i, dtype="f32")

    ts = [threading.Thread(target=admit, args=(k * 50,),
                           name=f"dpsvm-test-tie-{k}")
          for k in range(2)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    # One strictly-tighter request admitted LAST must pop FIRST.
    urgent = sched.submit(e, np.zeros((1, 2), np.float32), now=0.0,
                          deadline_s=1.0, ticket=999, dtype="f32")
    batch, expired = sched.form(e.group_key("f32"), now=0.0,
                                max_rows=10 ** 9)
    assert expired == []
    assert batch[0].ticket == urgent.ticket
    rest = [r.seq for r in batch[1:]]
    assert rest == sorted(rest)  # FIFO among the equal deadlines


def test_scheduler_expired_at_forming_exact_under_concurrency():
    """Requests already past deadline at form() time are shed exactly
    once with exact row/refcount accounting, under concurrent submit
    from two threads interleaved with a forming thread."""
    sched = _sched()
    live, dead = _SchedEntry("live", "g"), _SchedEntry("dead", "g")
    per = 120
    start = threading.Barrier(2)

    def admit(entry, deadline_s, base):
        start.wait()
        for i in range(per):
            sched.submit(entry, np.zeros((2, 2), np.float32), now=0.0,
                         deadline_s=deadline_s, ticket=base + i,
                         dtype="f32")

    ts = [threading.Thread(target=admit, args=(live, None, 0),
                           name="dpsvm-test-live"),
          threading.Thread(target=admit, args=(dead, 0.5, per),
                           name="dpsvm-test-dead")]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    # Form at now=1.0: every `dead` request (deadline 0.5) is expired.
    formed, shed = [], []
    key = live.group_key("f32")
    while True:
        batch, expired = sched.form(key, now=1.0, max_rows=7)
        formed.extend(batch)
        shed.extend(expired)
        if not batch and not expired:
            break
    assert len(formed) == per and len(shed) == per
    assert all(r.entry is live for r in formed)
    assert all(r.entry is dead for r in shed)
    assert sched.queue_rows == 0
    assert sched.pending_entries() == set()
    assert sched.queue_depth == 0
