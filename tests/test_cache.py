"""Unit tests for the functional LRU kernel-row cache (solver/cache.py),
exercising every hit/miss combination directly — the reference's cache
(cache.cu) has no tests at all."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpsvm_tpu.solver.cache import init_cache, lookup_pair


@pytest.fixture(scope="module")
def x():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(20, 4)).astype(np.float32))


def _lookup(cache, x, i_hi, i_lo, it):
    fn = jax.jit(lambda c, ih, il, t: lookup_pair(
        c, x, ih, il, x[ih], x[il], t))
    return fn(cache, jnp.int32(i_hi), jnp.int32(i_lo), jnp.int32(it))


def _expect_row(x, i):
    return np.asarray(x) @ np.asarray(x)[i]


def test_rows_correct_for_all_hit_miss_combos(x):
    cache = init_cache(4, 20)
    # 1) both miss
    r_hi, r_lo, cache, hits = _lookup(cache, x, 3, 7, 0)
    np.testing.assert_allclose(r_hi, _expect_row(x, 3), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 7), rtol=1e-5, atol=1e-6)
    assert int(hits) == 0
    # 2) hi hit, lo miss
    r_hi, r_lo, cache, hits = _lookup(cache, x, 3, 9, 1)
    np.testing.assert_allclose(r_hi, _expect_row(x, 3), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 9), rtol=1e-5, atol=1e-6)
    assert int(hits) == 1
    # 3) hi miss, lo hit
    r_hi, r_lo, cache, hits = _lookup(cache, x, 11, 7, 2)
    np.testing.assert_allclose(r_hi, _expect_row(x, 11), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 7), rtol=1e-5, atol=1e-6)
    assert int(hits) == 1
    # 4) both hit
    r_hi, r_lo, cache, hits = _lookup(cache, x, 9, 11, 3)
    np.testing.assert_allclose(r_hi, _expect_row(x, 9), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 11), rtol=1e-5, atol=1e-6)
    assert int(hits) == 2


def test_lru_evicts_least_recently_used(x):
    cache = init_cache(4, 20)
    # Fill all 4 lines: keys {0,1} then {2,3}.
    *_, cache, _ = _lookup(cache, x, 0, 1, 0)
    *_, cache, _ = _lookup(cache, x, 2, 3, 1)
    # Touch 0 and 1 (refresh), then insert {4,5}: evicts 2 and 3.
    *_, cache, _ = _lookup(cache, x, 0, 1, 2)
    *_, cache, _ = _lookup(cache, x, 4, 5, 3)
    keys = set(np.asarray(cache.keys).tolist())
    assert keys == {0, 1, 4, 5}
    # 0/1 must now be hits.
    *_, cache, hits = _lookup(cache, x, 0, 1, 4)
    assert int(hits) == 2


def test_double_miss_fills_two_distinct_lines(x):
    cache = init_cache(4, 20)
    *_, cache, _ = _lookup(cache, x, 6, 8, 0)
    keys = np.asarray(cache.keys)
    assert (keys == 6).sum() == 1
    assert (keys == 8).sum() == 1


def test_same_index_pair_is_consistent(x):
    # Degenerate i_hi == i_lo (possible at convergence boundary) must not
    # corrupt the cache or return mismatched rows.
    cache = init_cache(4, 20)
    r_hi, r_lo, cache, _ = _lookup(cache, x, 5, 5, 0)
    np.testing.assert_allclose(r_hi, r_lo, rtol=1e-6)
    np.testing.assert_allclose(r_hi, _expect_row(x, 5), rtol=1e-5, atol=1e-6)
    r_hi2, _, cache, hits = _lookup(cache, x, 5, 5, 1)
    np.testing.assert_allclose(r_hi2, _expect_row(x, 5), rtol=1e-5, atol=1e-6)


def test_cached_row_contents_survive_eviction_pressure(x):
    cache = init_cache(2, 20)
    for it, (a, b) in enumerate([(0, 1), (2, 3), (4, 5), (0, 2)]):
        r_hi, r_lo, cache, _ = _lookup(cache, x, a, b, it)
        np.testing.assert_allclose(r_hi, _expect_row(x, a), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r_lo, _expect_row(x, b), rtol=1e-5, atol=1e-6)
