"""Unit tests for the functional LRU kernel-row cache (solver/cache.py),
exercising every hit/miss combination directly — the reference's cache
(cache.cu) has no tests at all — plus the eviction/refresh FUZZ suite
(ISSUE 9): both the per-pair ``lookup_pair`` and the block-engine
``refresh_rows`` are replayed against a host-side reference LRU model
over randomized access sequences, so tie-breaking, victim exclusion
and the eviction counter are pinned, not just the happy paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpsvm_tpu.solver.cache import (init_cache, lookup_pair, probe_rows,
                                    refresh_rows)


@pytest.fixture(scope="module")
def x():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(20, 4)).astype(np.float32))


def _lookup(cache, x, i_hi, i_lo, it):
    fn = jax.jit(lambda c, ih, il, t: lookup_pair(
        c, x, ih, il, x[ih], x[il], t))
    return fn(cache, jnp.int32(i_hi), jnp.int32(i_lo), jnp.int32(it))


def _expect_row(x, i):
    return np.asarray(x) @ np.asarray(x)[i]


def test_rows_correct_for_all_hit_miss_combos(x):
    cache = init_cache(4, 20)
    # 1) both miss
    r_hi, r_lo, cache, hits = _lookup(cache, x, 3, 7, 0)
    np.testing.assert_allclose(r_hi, _expect_row(x, 3), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 7), rtol=1e-5, atol=1e-6)
    assert int(hits) == 0
    # 2) hi hit, lo miss
    r_hi, r_lo, cache, hits = _lookup(cache, x, 3, 9, 1)
    np.testing.assert_allclose(r_hi, _expect_row(x, 3), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 9), rtol=1e-5, atol=1e-6)
    assert int(hits) == 1
    # 3) hi miss, lo hit
    r_hi, r_lo, cache, hits = _lookup(cache, x, 11, 7, 2)
    np.testing.assert_allclose(r_hi, _expect_row(x, 11), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 7), rtol=1e-5, atol=1e-6)
    assert int(hits) == 1
    # 4) both hit
    r_hi, r_lo, cache, hits = _lookup(cache, x, 9, 11, 3)
    np.testing.assert_allclose(r_hi, _expect_row(x, 9), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_lo, _expect_row(x, 11), rtol=1e-5, atol=1e-6)
    assert int(hits) == 2


def test_lru_evicts_least_recently_used(x):
    cache = init_cache(4, 20)
    # Fill all 4 lines: keys {0,1} then {2,3}.
    *_, cache, _ = _lookup(cache, x, 0, 1, 0)
    *_, cache, _ = _lookup(cache, x, 2, 3, 1)
    # Touch 0 and 1 (refresh), then insert {4,5}: evicts 2 and 3.
    *_, cache, _ = _lookup(cache, x, 0, 1, 2)
    *_, cache, _ = _lookup(cache, x, 4, 5, 3)
    keys = set(np.asarray(cache.keys).tolist())
    assert keys == {0, 1, 4, 5}
    # 0/1 must now be hits.
    *_, cache, hits = _lookup(cache, x, 0, 1, 4)
    assert int(hits) == 2


def test_double_miss_fills_two_distinct_lines(x):
    cache = init_cache(4, 20)
    *_, cache, _ = _lookup(cache, x, 6, 8, 0)
    keys = np.asarray(cache.keys)
    assert (keys == 6).sum() == 1
    assert (keys == 8).sum() == 1


def test_same_index_pair_is_consistent(x):
    # Degenerate i_hi == i_lo (possible at convergence boundary) must not
    # corrupt the cache or return mismatched rows.
    cache = init_cache(4, 20)
    r_hi, r_lo, cache, _ = _lookup(cache, x, 5, 5, 0)
    np.testing.assert_allclose(r_hi, r_lo, rtol=1e-6)
    np.testing.assert_allclose(r_hi, _expect_row(x, 5), rtol=1e-5, atol=1e-6)
    r_hi2, _, cache, hits = _lookup(cache, x, 5, 5, 1)
    np.testing.assert_allclose(r_hi2, _expect_row(x, 5), rtol=1e-5, atol=1e-6)


def test_cached_row_contents_survive_eviction_pressure(x):
    cache = init_cache(2, 20)
    for it, (a, b) in enumerate([(0, 1), (2, 3), (4, 5), (0, 2)]):
        r_hi, r_lo, cache, _ = _lookup(cache, x, a, b, it)
        np.testing.assert_allclose(r_hi, _expect_row(x, a), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r_lo, _expect_row(x, b), rtol=1e-5, atol=1e-6)


# ----------------------------------------------- eviction/refresh fuzz

class _ModelLRU:
    """Host-side reference LRU with the device cache's exact
    semantics: lines carry (key, tick); victims are chosen by
    (tick, line-index) ascending — matching argmin/top_k's stable
    lowest-index tie-break — and initial ticks are the negative
    slot-ordered fill stamps of init_cache."""

    def __init__(self, lines: int):
        self.keys = [-1] * lines
        self.ticks = list(range(-lines, 0))

    def slot_of(self, key):
        return self.keys.index(key) if key in self.keys else None

    def lru_order(self, exclude=()):
        return sorted((i for i in range(len(self.keys))
                       if i not in exclude),
                      key=lambda i: (self.ticks[i], i))


def test_refresh_rows_fuzz_against_model(x):
    """Randomized working-set refreshes vs the reference model: per-
    slot hit flags, the eviction counter, the surviving key set and
    the tick stamps must all match, and every cached data row must
    hold its row's true dot products after every step."""
    rng = np.random.default_rng(0)
    lines, q, n = 8, 4, 20
    xs = np.asarray(x)
    for _ in range(3):  # a few independent sequences
        cache = init_cache(lines, n)
        model = _ModelLRU(lines)
        for step in range(1, 41):
            w = rng.choice(n, size=q, replace=False).astype(np.int32)
            ok = rng.random(q) > 0.2  # some dead filler slots
            rows = xs[w] @ xs.T  # (q, n) fresh dot rows
            new_cache, n_hits, n_evict = jax.jit(refresh_rows)(
                cache, jnp.asarray(w), jnp.asarray(ok),
                jnp.asarray(rows, jnp.float32), jnp.int32(step))
            # -- model step
            hits = [bool(o) and model.slot_of(int(k)) is not None
                    for k, o in zip(w, ok)]
            hit_slots = {model.slot_of(int(k))
                         for k, h in zip(w, hits) if h}
            victims = model.lru_order(exclude=hit_slots)
            m_evict = 0
            vi = 0
            for k, o, h in zip(w, ok, hits):
                if not o:
                    continue
                if h:
                    s = model.slot_of(int(k))
                else:
                    s = victims[vi]
                    vi += 1
                    if model.keys[s] != -1:
                        m_evict += 1
                    model.keys[s] = int(k)
                model.ticks[s] = step
            # -- compare
            assert int(n_hits) == sum(hits)
            assert int(n_evict) == m_evict
            np.testing.assert_array_equal(
                np.asarray(new_cache.keys), np.asarray(model.keys))
            np.testing.assert_array_equal(
                np.asarray(new_cache.ticks), np.asarray(model.ticks))
            for s, k in enumerate(model.keys):
                if k >= 0:
                    np.testing.assert_allclose(
                        np.asarray(new_cache.data)[s], xs[k] @ xs.T,
                        rtol=1e-5, atol=1e-6)
            cache = new_cache


def test_probe_rows_matches_membership(x):
    cache = init_cache(4, 20)
    *_, cache, _ = _lookup(cache, x, 3, 7, 0)
    w = jnp.asarray([3, 7, 9, 3], jnp.int32)
    ok = jnp.asarray([True, True, True, False])
    hit, slot = jax.jit(probe_rows)(cache.keys, w, ok)
    np.testing.assert_array_equal(np.asarray(hit),
                                  [True, True, False, False])
    keys = np.asarray(cache.keys)
    assert keys[int(slot[0])] == 3 and keys[int(slot[1])] == 7


def test_lookup_pair_fuzz_against_model(x):
    """The per-pair LRU replayed against the same reference model over
    randomized (i_hi, i_lo) sequences: per-step hit counts and the
    full per-line key/tick state must match. Model semantics mirror
    lookup_pair exactly — both probes and both victim choices read the
    PRE-update keys/ticks, the lo victim excludes the hi slot, and the
    lo write wins a same-slot conflict (stamps 2*it+1 / 2*it+2)."""
    rng = np.random.default_rng(1)
    lines, n = 4, 20
    cache = init_cache(lines, n)
    model = _ModelLRU(lines)
    for it in range(60):
        i_hi, i_lo = (int(v) for v in rng.choice(n, size=2))
        *_, cache, hits = _lookup(cache, x, i_hi, i_lo, it)
        # -- model step, all choices from the pre-update state
        pre_hit_hi = model.slot_of(i_hi) is not None
        pre_hit_lo = model.slot_of(i_lo) is not None
        s_hi = (model.slot_of(i_hi) if pre_hit_hi
                else model.lru_order()[0])
        s_lo = (model.slot_of(i_lo) if pre_hit_lo
                else model.lru_order(exclude={s_hi})[0])
        model.keys[s_hi] = i_hi
        model.keys[s_lo] = i_lo  # lo wins a same-slot conflict
        model.ticks[s_hi] = 2 * it + 1
        model.ticks[s_lo] = 2 * it + 2
        assert int(hits) == pre_hit_hi + pre_hit_lo
        np.testing.assert_array_equal(np.asarray(cache.keys),
                                      np.asarray(model.keys))
        np.testing.assert_array_equal(np.asarray(cache.ticks),
                                      np.asarray(model.ticks))


def test_refresh_rows_fuzz_across_reshrink_boundaries(x):
    """The shrunken-stream usage pattern (ISSUE 19) replayed against
    the reference model: the solver keeps cache keys GLOBAL row ids
    and, while a shrink cycle is open, PROBES the cache every round
    but never refreshes it (an in-cycle stream round computes partial
    dot rows, which must not poison the full-width LRU). The fuzz
    alternates full-stream phases (refresh vs model) with view phases
    (probe-only, working sets drawn from a re-drawn active view), and
    pins across every re-shrink boundary that (a) probe membership
    matches the model exactly, (b) probe-only rounds leave key/tick
    state and cached contents bit-unchanged, and (c) the first
    refresh after a cycle carries the model forward as if the cycle
    never touched the cache."""
    rng = np.random.default_rng(7)
    lines, q, n = 8, 4, 20
    xs = np.asarray(x)
    cache = init_cache(lines, n)
    model = _ModelLRU(lines)
    step = 0
    for phase in range(6):
        in_cycle = phase % 2 == 1
        # Re-shrink boundary: each view phase draws a fresh active
        # view (global ids — the cache never re-indexes).
        view = rng.choice(n, size=10, replace=False)
        for _ in range(8):
            step += 1
            pool = view if in_cycle else np.arange(n)
            w = rng.choice(pool, size=q, replace=False).astype(np.int32)
            ok = rng.random(q) > 0.2
            hit, slot = jax.jit(probe_rows)(cache.keys,
                                            jnp.asarray(w),
                                            jnp.asarray(ok))
            m_hits = [bool(o) and model.slot_of(int(k)) is not None
                      for k, o in zip(w, ok)]
            np.testing.assert_array_equal(np.asarray(hit), m_hits)
            for s, (k, h) in enumerate(zip(w, m_hits)):
                if h:
                    assert int(np.asarray(cache.keys)[int(slot[s])]) \
                        == int(k)
            if in_cycle:
                continue  # probe-only: the cycle never writes
            rows = xs[w] @ xs.T
            cache, n_hits, n_evict = jax.jit(refresh_rows)(
                cache, jnp.asarray(w), jnp.asarray(ok),
                jnp.asarray(rows, jnp.float32), jnp.int32(step))
            # -- model step (same semantics as the plain fuzz)
            hit_slots = {model.slot_of(int(k))
                         for k, h in zip(w, m_hits) if h}
            victims = model.lru_order(exclude=hit_slots)
            m_evict, vi = 0, 0
            for k, o, h in zip(w, ok, m_hits):
                if not o:
                    continue
                if h:
                    s = model.slot_of(int(k))
                else:
                    s = victims[vi]
                    vi += 1
                    if model.keys[s] != -1:
                        m_evict += 1
                    model.keys[s] = int(k)
                model.ticks[s] = step
            assert int(n_hits) == sum(m_hits)
            assert int(n_evict) == m_evict
            np.testing.assert_array_equal(np.asarray(cache.keys),
                                          np.asarray(model.keys))
            np.testing.assert_array_equal(np.asarray(cache.ticks),
                                          np.asarray(model.ticks))
        # Boundary invariant: contents are the true full-width rows
        # for every live line, cycle or not.
        for s, k in enumerate(model.keys):
            if k >= 0:
                np.testing.assert_allclose(
                    np.asarray(cache.data)[s], xs[k] @ xs.T,
                    rtol=1e-5, atol=1e-6)
