"""Covtype-scale smoke test (bounded iterations) and debug-mode checks."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.solver.smo import solve


def test_covtype_scale_bounded():
    # The reference's stress config is covtype: 500k x 54, c=2048
    # (Makefile:77). Run the real engine for a bounded number of
    # iterations at that shape to catch memory/indexing scale bugs; CPU
    # can't afford convergence here.
    rng = np.random.default_rng(0)
    n, d = 500_000, 54
    x = rng.normal(size=(n, d)).astype(np.float32) * 0.3
    y = np.where(x[:, 0] + 0.2 * rng.standard_normal(n) > 0, 1, -1).astype(np.int32)
    cfg = SVMConfig(c=2048.0, gamma=0.03125, epsilon=1e-3, max_iter=24,
                    cache_lines=8, chunk_iters=8)
    res = solve(x, y, cfg)
    assert res.iterations == 24
    assert np.isfinite(res.b_hi) and np.isfinite(res.b_lo)
    assert (res.alpha >= 0).all() and (res.alpha <= cfg.c).all()
    assert np.count_nonzero(res.alpha) >= 2  # work actually happened


def test_check_numerics_raises_on_bad_input(blobs_small):
    x, y = blobs_small
    x = x.copy()
    x[7, 3] = np.inf  # poisoned feature -> f goes non-finite
    cfg = SVMConfig(c=1.0, gamma=0.1, max_iter=100, chunk_iters=10,
                    cache_lines=8, check_numerics=True)
    with pytest.raises(FloatingPointError, match="non-finite"):
        solve(x, y, cfg)


def test_check_numerics_clean_run_unaffected(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, cache_lines=8, check_numerics=True)
    res = solve(x, y, cfg)
    assert res.converged
    res_m = solve_mesh(x, y, cfg, num_devices=4)
    assert res_m.converged
