"""Out-of-core block engine (config.ooc; solver/ooc.py — ISSUE 9).

The load-bearing claim is BIT-IDENTITY: on shapes where both fit, the
ooc solve — host-resident X, tile-streamed fold, host-driven rounds —
must reproduce the in-core block engine's trajectory exactly (same
alpha bits, same gradient bits, same pair count), including through a
memmap-backed X and the padded tail tile. Everything else (the block
cache's all-hit fast path, the budget contract, the obs counters) is
layered on top of that anchor.
"""

import os

import numpy as np
import pytest

from dpsvm_tpu.config import ObsConfig, SVMConfig
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=2.0, epsilon=1e-3, engine="block",
                working_set_size=64, max_iter=50_000)


@pytest.fixture(scope="module")
def data():
    return make_blobs_binary(n=1024, d=24, seed=11, sep=1.0)


@pytest.fixture(scope="module")
def incore(data):
    x, y = data
    return solve(x, y, CFG)


def _assert_bitwise(a, b):
    assert a.iterations == b.iterations
    assert a.converged == b.converged
    assert a.b_hi == b.b_hi and a.b_lo == b.b_lo
    np.testing.assert_array_equal(a.alpha, b.alpha)
    np.testing.assert_array_equal(a.stats["f"], b.stats["f"])


def test_ooc_bit_identical_to_incore(data, incore):
    x, y = data
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256))
    _assert_bitwise(incore, res)
    st = res.stats
    assert st["ooc"] and st["tiles_streamed"] > 0
    assert st["tile_bytes_h2d"] > 0
    assert st["outer_rounds"] > 1
    # Stream accounting: every stream round moves exactly n_pad rows.
    assert st["tiles_streamed"] % (1024 // 256) == 0


def test_ooc_memmap_backed_x(data, incore, tmp_path):
    """X as an np.memmap — the shape the ooc path exists for: the
    training matrix never fully materializes in host RAM either."""
    x, y = data
    path = tmp_path / "x.dat"
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=x.shape)
    mm[:] = x
    mm.flush()
    ro = np.memmap(path, dtype=np.float32, mode="r", shape=x.shape)
    res = solve(ro, y, CFG.replace(ooc=True, ooc_tile_rows=256))
    _assert_bitwise(incore, res)


def test_ooc_padded_tail_tile(data):
    """n not a multiple of tile_rows: the tail tile zero-pads and the
    padding is masked out of selection — trajectory still bit-matches
    the (unpadded) in-core engine on the same rows."""
    x, y = data
    x, y = x[:1000], y[:1000]
    ic = solve(x, y, CFG)
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256))
    _assert_bitwise(ic, res)


def test_ooc_compensated_bit_identical(data):
    x, y = data
    cfg = CFG.replace(compensated=True)
    ic = solve(x, y, cfg)
    res = solve(x, y, cfg.replace(ooc=True, ooc_tile_rows=256))
    _assert_bitwise(ic, res)


def test_ooc_block_cache_all_hit_rounds(data, incore):
    """With enough lines to hold every hot row, the selection's
    near-convergence concentration produces ALL-HIT rounds that skip
    the tile stream entirely — the cache's reason to exist. The
    trajectory must still land on the in-core optimum."""
    x, y = data
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256,
                                  ooc_cache_lines=1024))
    nostream = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256))
    assert res.stats["cached_rounds"] > 0
    assert res.stats["cache_hits"] > 0
    assert res.stats["cache_hit_rate"] > 0.5
    # All-hit rounds each save a full-n stream.
    assert res.stats["tiles_streamed"] < nostream.stats["tiles_streamed"]
    assert res.converged
    # The cached Gram/fold rows are the same dot products the stream
    # would recompute, so the trajectory stays on the same optimum.
    np.testing.assert_allclose(res.alpha, incore.alpha, atol=2e-4)
    assert abs(res.b - incore.b) < 5e-3


def test_ooc_cache_eviction_pressure(data):
    """Lines < distinct hot rows: evictions must be counted and the
    solve must stay exact (an evicted row is recomputed by the next
    stream, never served stale)."""
    x, y = data
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256,
                                  ooc_cache_lines=128))
    assert res.stats["cache_evictions"] > 0
    assert res.stats["cache_lookups"] >= res.stats["cache_hits"]
    assert res.converged


def test_ooc_budget_mode_exact_pairs(data):
    x, y = data
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=256,
                                  budget_mode=True, max_iter=2000))
    assert res.iterations == 2000


def test_ooc_runlog_carries_tile_and_cache_counters(data, tmp_path,
                                                    monkeypatch):
    """The ISSUE 9 CI leg: a small ooc solve under DPSVM_OBS=1 writes
    a run log whose chunk records carry the per-round tile counters
    and whose final record carries the stream/cache totals the
    Registry accumulated."""
    from dpsvm_tpu.obs.runlog import read_runlog, records_for

    monkeypatch.setenv("DPSVM_OBS", "1")
    monkeypatch.setenv("DPSVM_OBS_DIR", str(tmp_path))
    x, y = data
    res = solve(x, y, CFG.replace(
        ooc=True, ooc_tile_rows=256, ooc_cache_lines=1024,
        obs=ObsConfig(enabled=True, runlog_dir=str(tmp_path))))
    path = res.stats["obs_runlog"]
    assert os.path.dirname(path) == str(tmp_path)
    recs = read_runlog(path)
    run_id = res.stats["obs_run_id"]
    man = records_for(recs, run_id, "manifest")[0]
    assert man["ooc"] and man["ooc_tile_rows"] == 256
    chunks = records_for(recs, run_id, "chunk")
    assert chunks and all("tiles" in c and "cache_hits" in c
                          for c in chunks)
    assert sum(c["tiles"] for c in chunks) == res.stats["tiles_streamed"]
    fin = records_for(recs, run_id, "final")[0]
    for key in ("tiles_streamed", "tile_bytes_h2d", "cache_hits",
                "cache_lookups", "cache_hit_rate", "cache_evictions",
                "cached_rounds"):
        assert key in fin, key
    assert fin["tiles_streamed"] == res.stats["tiles_streamed"]
    m = fin["metrics"]
    assert m["solve.ooc_tiles_total"] == res.stats["tiles_streamed"]
    assert m["solve.cache_hits_total"] == res.stats["cache_hits"]
    assert m["solve.cache_lookups_total"] == res.stats["cache_lookups"]
    # ... and `cli obs report` surfaces the cache_hit_rate line.
    from dpsvm_tpu.obs.analyze import (load_runs, render_report,
                                       summarize_run)
    summary = [summarize_run(r) for r in load_runs([path])
               if r.run_id == run_id]
    assert summary and summary[0]["cache_hit_rate"] == pytest.approx(
        res.stats["cache_hit_rate"], abs=1e-6)
    table = render_report(summary)
    assert "cache" in table.splitlines()[0]
    assert f"{100 * res.stats['cache_hit_rate']:.1f}%" in table


def test_ooc_config_validation():
    with pytest.raises(ValueError, match="engine='block'"):
        SVMConfig(ooc=True, engine="xla")
    with pytest.raises(ValueError, match="feature kernels"):
        SVMConfig(ooc=True, engine="block", kernel="precomputed")
    with pytest.raises(ValueError, match="gram_resident"):
        SVMConfig(ooc=True, engine="block", gram_resident=True)
    # active_set_size with ooc is a ROUTE now (it sizes the shrunken
    # tile stream's active view, ISSUE 19) — only the contradiction
    # with a forced-off gate rejects.
    assert SVMConfig(ooc=True, engine="block",
                     active_set_size=256).active_set_size == 256
    with pytest.raises(ValueError, match="ooc_shrink=False"):
        SVMConfig(ooc=True, engine="block", active_set_size=256,
                  ooc_shrink=False)
    with pytest.raises(ValueError, match="ooc_shrink"):
        SVMConfig(engine="block", ooc_shrink=True)  # needs ooc=True
    with pytest.raises(ValueError, match="pipeline_rounds"):
        SVMConfig(ooc=True, engine="block", pipeline_rounds=True)
    with pytest.raises(ValueError, match="ooc_cache_lines"):
        SVMConfig(ooc=True, engine="block", working_set_size=128,
                  ooc_cache_lines=64)
    with pytest.raises(ValueError, match="ooc=True"):
        SVMConfig(engine="block", ooc_cache_lines=256)
    with pytest.raises(ValueError, match="global working set"):
        SVMConfig(ooc=True, engine="block", local_working_sets=2)


def test_train_auto_backend_keeps_shrink_single_chip(data):
    """train(backend='auto') with >1 visible device normally picks the
    mesh — but the shrunken stream and the ooc block cache are
    single-chip features, so requesting them must route to the single
    backend instead of the mesh rejecting the combination (the README
    --ooc-shrink quickstart line on a multi-device host)."""
    from dpsvm_tpu.train import train

    x, y = data
    cfg = CFG.replace(ooc=True, ooc_tile_rows=256, ooc_shrink=True,
                      active_set_size=256)
    model, res = train(x, y, cfg, backend="auto")
    assert res.stats["ooc_shrink"] is True
    assert "ooc_mesh" not in res.stats
    # Explicit mesh still rejects — auto rescues, it doesn't mask.
    from dpsvm_tpu.parallel.dist_smo import solve_mesh
    with pytest.raises(ValueError, match="single-chip"):
        solve_mesh(x, y, cfg, num_devices=2)


def test_ooc_mesh_bitwise_two_devices(data):
    """solve_mesh + config.ooc routes to the sharded tile stream
    (ISSUE 19 — it used to reject): each device folds its own row
    shard's tiles, the round joins on ONE (q, 5) psum, and the
    trajectory lands BITWISE on the single-chip ooc stream's."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = data
    cfg = CFG.replace(ooc=True, ooc_tile_rows=256)
    single = solve(x, y, cfg)
    mesh = solve_mesh(x, y, cfg, num_devices=2)
    _assert_bitwise(single, mesh)
    assert mesh.stats["ooc_mesh"] is True
    assert mesh.stats["ooc"] is True


def test_ooc_mesh_rejects_cache_and_shrink(data):
    """The mesh stream's non-compositions stay LOUD errors, not
    silent drops: the kernel-row cache is a single-chip HBM structure
    and the shrunken stream is host bookkeeping over one stream."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = data
    with pytest.raises(ValueError, match="ooc_cache_lines"):
        solve_mesh(x, y, CFG.replace(ooc=True, ooc_tile_rows=256,
                                     ooc_cache_lines=256),
                   num_devices=2)
    with pytest.raises(ValueError, match="shrunken"):
        solve_mesh(x, y, CFG.replace(ooc=True, ooc_tile_rows=256,
                                     ooc_shrink=True),
                   num_devices=2)


def test_ooc_shrink_converges_same_criterion(data, incore):
    """Shrunken stream (ISSUE 19): per-round tile fold walks only the
    active view's tiles, yet the FINAL model meets the same
    convergence criterion — cycle-start full selects are the only
    stopping decisions and the endgame demotes to the exact full
    stream. The trajectory legitimately differs from the full
    stream's (work is reordered), so the pin is the criterion plus
    model-level agreement, not bitwise equality."""
    x, y = data
    res = solve(x, y, CFG.replace(ooc=True, ooc_tile_rows=128,
                                  active_set_size=256))
    assert res.converged
    assert res.b_lo <= res.b_hi + 2.0 * CFG.epsilon + 1e-6
    st = res.stats
    assert st["ooc_shrink"] is True
    assert st["shrink_m"] == 256
    assert st["shrink_cycles"] >= 1
    assert st["shrink_reconstructions"] >= 1
    assert st["tiles_skipped"] > 0
    assert st["tile_bytes_skipped"] > 0
    assert st["shrink_tiles_in_cycle"] > 0
    # Model-level agreement with the in-core exact solve.
    assert abs(res.b - incore.b) < 0.05
    assert abs(res.n_sv - incore.n_sv) <= max(8, incore.n_sv // 10)


def test_ooc_shrink_resume_bitwise(data, tmp_path, monkeypatch):
    """Die mid-SHRINKING-solve (injected tile-put fault), resume from
    the periodic checkpoint: bitwise equal to the uninterrupted
    shrinking run. While shrinking, periodic saves land only at cycle
    boundaries (exact f, no live view) and carry the shrink latches —
    demotion, last cycle gap, stall streak — so the resumed run
    re-opens the next cycle from exactly the state the uninterrupted
    run had there. (A graceful callback abort instead CLOSES the open
    cycle early to leave an exact checkpoint — a correct state, but a
    reordered trajectory — so the bitwise pin is the kill path's.)"""
    import dpsvm_tpu.solver.smo as smo_mod
    from dpsvm_tpu.testing import faults

    monkeypatch.setattr(smo_mod, "_RETRY_BACKOFF_S", ())
    x, y = data
    cfg = CFG.replace(ooc=True, ooc_tile_rows=128, active_set_size=256,
                      checkpoint_every=256)
    full = solve(x, y, cfg)
    assert full.stats["shrink_cycles"] >= 1
    assert full.stats["tiles_skipped"] > 0
    p = str(tmp_path / "ooc.shrink.ck.npz")
    with faults.install(
            faults.FaultPlan.parse("ooc_tile_put@200")) as plan:
        res = solve(x, y, cfg, checkpoint_path=p)
    assert plan.fired["ooc_tile_put"] == 1
    assert res.stats["resumed_from"] > 0
    _assert_bitwise(full, res)


# ------------------------------ checkpoint/resume (ISSUE 13 tentpole)
# The pin standard is the module's own: BITWISE equality to the
# uninterrupted run — same alpha bits, same gradient bits, same pair
# count — which the v2 checkpoint's full carry (raw f + f_err lanes +
# round counter) makes possible.

def test_ooc_resume_bitwise(data, incore, tmp_path):
    """Abort mid-solve (forced checkpoint at the abort boundary), then
    resume: the final state must equal the uninterrupted trajectory's
    BITWISE — which is also bitwise-equal to the in-core engine."""
    x, y = data
    p = str(tmp_path / "ooc.ck.npz")
    cfg = CFG.replace(ooc=True, ooc_tile_rows=256,
                      checkpoint_every=1_000_000)  # only the abort saves
    part = solve(x, y, cfg, callback=lambda it, bh, bl, st: it >= 600,
                 checkpoint_path=p)
    assert not part.converged and part.iterations < incore.iterations
    res = solve(x, y, cfg, checkpoint_path=p, resume=True)
    assert res.stats["resumed_from"] == part.iterations
    _assert_bitwise(incore, res)


def test_ooc_resume_memmap_and_padded_tail(data, tmp_path):
    """The resume pin through BOTH hard cases at once: a memmap-backed
    X (never fully host-resident) at an n that leaves a zero-padded
    tail tile. Compensated, so the restored f_err lanes carry."""
    x, y = data
    x, y = x[:1000], y[:1000]  # 1000 % 256 != 0 -> padded tail
    cfg = CFG.replace(compensated=True)
    ic = solve(x, y, cfg)
    path = tmp_path / "x.dat"
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=x.shape)
    mm[:] = x
    mm.flush()
    ro = np.memmap(path, dtype=np.float32, mode="r", shape=x.shape)
    p = str(tmp_path / "ooc.ck.npz")
    ocfg = cfg.replace(ooc=True, ooc_tile_rows=256,
                       checkpoint_every=1_000_000)
    part = solve(ro, y, ocfg,
                 callback=lambda it, bh, bl, st: it >= 500,
                 checkpoint_path=p)
    assert not part.converged
    from dpsvm_tpu.utils.checkpoint import load_checkpoint_state
    st = load_checkpoint_state(p)
    assert st.format_version == 2 and st.f_err is not None
    assert st.rounds > 0
    res = solve(ro, y, ocfg, checkpoint_path=p, resume=True)
    _assert_bitwise(ic, res)


def test_ooc_tile_put_fault_retries_from_checkpoint(data, incore,
                                                    tmp_path,
                                                    monkeypatch):
    """An injected transient fault on a mid-stream tile device_put
    (the ooc_tile_put seam) retries from the periodic checkpoint and
    still lands bitwise on the uninterrupted optimum; the run log
    carries the fault/retry/resume trail."""
    import dpsvm_tpu.solver.smo as smo_mod
    from dpsvm_tpu.testing import faults

    monkeypatch.setattr(smo_mod, "_RETRY_BACKOFF_S", ())
    x, y = data
    p = str(tmp_path / "ooc.ck.npz")
    cfg = CFG.replace(ooc=True, ooc_tile_rows=256, checkpoint_every=256,
                      obs=ObsConfig(enabled=True,
                                    runlog_dir=str(tmp_path)))
    with faults.install(faults.FaultPlan.parse("ooc_tile_put@30")) as plan:
        res = solve(x, y, cfg, checkpoint_path=p)
    assert plan.fired["ooc_tile_put"] == 1
    assert res.stats["resumed_from"] > 0
    _assert_bitwise(incore, res)
    from dpsvm_tpu.obs.runlog import read_runlog, records_for
    events = records_for(read_runlog(res.stats["obs_runlog"]),
                         res.stats["obs_run_id"], "event")
    names = [e["name"] for e in events]
    assert "fault" in names and "retry" in names and "resume" in names


def test_ooc_cache_restarts_cold_on_resume(data, tmp_path):
    """Cache-ON resume is exact but NOT bitwise (the cold cache moves
    the all-hit rounds), and says so: stats['cache_cold_restart']."""
    x, y = data
    p = str(tmp_path / "ooc.ck.npz")
    cfg = CFG.replace(ooc=True, ooc_tile_rows=256, ooc_cache_lines=1024,
                      checkpoint_every=1_000_000)
    solve(x, y, cfg, callback=lambda it, bh, bl, st: it >= 600,
          checkpoint_path=p)
    res = solve(x, y, cfg, checkpoint_path=p, resume=True)
    assert res.converged
    assert res.stats["cache_cold_restart"] is True
