"""Real-TPU Mosaic lowering coverage, wired into pytest.

The suite's conftest pins every in-process test to the 8-device virtual
CPU platform, so Pallas kernels only ever run in interpret mode here.
This test re-execs tools/tpu_smoke.py in a subprocess with the default
(device) platform, exercising actual Mosaic lowering of
ops/pallas_subproblem.py across small and non-lane-aligned q (16, 40) and
every pairing rule, plus the fused per-pair engine — the surface
solve/solve_mesh auto-select on TPU for arbitrary clamped even q.

Skips cleanly when no TPU is reachable (the tool prints SKIP and exits 0
on non-TPU platforms). Deselect with `-m "not tpu"`.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.tpu
def test_pallas_lowering_on_device():
    env = dict(os.environ)
    # conftest appended the virtual-CPU-device flag to this process's env;
    # the subprocess must see the machine's default platform instead.
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f)
    env.pop("JAX_PLATFORMS", None)
    # Reachability preflight: a half-up device tunnel can HANG backend
    # init rather than fail it (observed 2026-08-03: `jax.devices()` in
    # the child blocked >90 s on the axon endpoint where the same probe
    # failed fast at session start). A hung tunnel is the same "no TPU
    # reachable" condition this test already skips on — detect it with a
    # short-timeout child instead of letting the 1800 s tool budget eat
    # the whole tier-1 wall clock.
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=60)
    except subprocess.TimeoutExpired:
        pytest.skip("device platform backend init hung (tunnel "
                    "unreachable); the tool's own SKIP path never ran")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_smoke.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    if "SKIP" in proc.stdout:
        pytest.skip("no TPU reachable from subprocess: "
                    + proc.stdout.strip().splitlines()[-1])
    assert "TPU SMOKE: PASS" in proc.stdout
