"""Telemetry spine (dpsvm_tpu/obs — ISSUE 7): strict no-op mode,
zero-HLO-effect, runlog schema round-trip, bounded histograms, serve
integration, and the bench reconciliation contract.

The load-bearing claims:
* DISABLED obs is free and invisible: shared null objects, bitwise-
  identical solver results, jaxpr-identical chunk executors.
* ENABLED obs never changes solver behavior: same chunk cadence, same
  dispatch count, same alpha — records ride existing observations.
* Everything bounded: histograms hold O(bins + window) regardless of
  observation count (the long-lived-server discipline).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dpsvm_tpu.config import ObsConfig, ServeConfig, SVMConfig
from dpsvm_tpu.obs import metrics as obs_metrics
from dpsvm_tpu.obs import run_obs, trace
from dpsvm_tpu.obs.metrics import Histogram, Registry
from dpsvm_tpu.obs.runlog import (SCHEMA_VERSION, RunLog, read_runlog,
                                  records_for)


@pytest.fixture(autouse=True)
def _isolated_obs(monkeypatch):
    """Every test here controls obs state explicitly: scrub the env
    opt-in and reset the default registry (restored afterwards)."""
    monkeypatch.delenv("DPSVM_OBS", raising=False)
    monkeypatch.delenv("DPSVM_OBS_DIR", raising=False)
    monkeypatch.setattr(obs_metrics, "_DEFAULT", None)
    yield


# ------------------------------------------------------ no-op mode

def test_disabled_span_is_shared_null():
    assert trace.span("a") is trace.span("b")  # no allocation
    with trace.span("solver/chunk"):
        pass  # and usable


def test_disabled_registry_hands_out_nulls():
    reg = Registry(enabled=False)
    c = reg.counter("x")
    c.add(5)
    h = reg.histogram("y")
    h.observe(1.0)
    assert c is reg.gauge("z") is h is obs_metrics.NULL
    assert h.percentiles() == {} and len(h) == 0
    assert reg.snapshot() == {}


def test_run_obs_disabled_is_shared_null(tmp_path):
    from dpsvm_tpu.obs import NULL_OBS

    cfg = SVMConfig()
    assert run_obs("solve", cfg) is NULL_OBS
    # ... and the null handle's surface is complete and inert.
    NULL_OBS.chunk(pairs=1, b_hi=0.0, b_lo=1.0, device_seconds=0.1,
                   dispatch=1)
    NULL_OBS.event("x")
    NULL_OBS.finish()
    assert not list(tmp_path.iterdir())


def test_solver_chunk_jaxpr_identical_with_obs_enabled(monkeypatch):
    """The zero-overhead-ops satellite: the compiled solver chunk is
    the SAME PROGRAM with observability on and off — obs never reaches
    trace time, so the jaxprs are string-identical."""
    from dpsvm_tpu.solver.block import BlockState, _run_chunk_block
    from dpsvm_tpu.ops.kernels import KernelParams

    n, d = 256, 8
    args = (jnp.zeros((n, d)), jnp.ones((n,)), jnp.zeros((n,)),
            jnp.ones((n,)), None,
            BlockState(alpha=jnp.zeros((n,)), f=jnp.ones((n,)),
                       b_hi=jnp.float32(-1.0), b_lo=jnp.float32(1.0),
                       pairs=jnp.int32(0), rounds=jnp.int32(0)),
            jnp.int32(1000))
    kw = dict(kp=KernelParams("rbf", 0.1), c=(1.0, 1.0), eps=1e-3,
              tau=1e-12, q=16, inner_iters=32, rounds_per_chunk=2,
              inner_impl="xla")

    def jaxpr():
        return str(jax.make_jaxpr(
            lambda *a: _run_chunk_block(*a, **kw))(*args))

    off = jaxpr()
    monkeypatch.setenv("DPSVM_OBS", "1")
    monkeypatch.setattr(obs_metrics, "_DEFAULT", None)
    assert obs_metrics.get_registry().enabled
    assert jaxpr() == off


def test_solve_bitwise_identical_and_same_dispatches(blobs_small,
                                                    tmp_path,
                                                    monkeypatch):
    """Enabling obs changes NO solver behavior: same alpha bits, same
    iteration count, same dispatch count, same chunk cadence."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    cfg = SVMConfig(c=2.0, epsilon=1e-3)
    r_off = solve(x, y, cfg)
    r_on = solve(x, y, cfg.replace(obs=ObsConfig(
        enabled=True, runlog_dir=str(tmp_path))))
    assert np.array_equal(r_off.alpha, r_on.alpha)
    assert r_off.iterations == r_on.iterations
    assert r_off.dispatches == r_on.dispatches
    assert "obs_run_id" in r_on.stats and "obs_run_id" not in r_off.stats


# ------------------------------------------------------ runlog schema

def test_runlog_schema_round_trip(tmp_path):
    cfg = SVMConfig(c=3.0, engine="block")
    path = str(tmp_path / "solve-test.jsonl")
    log = RunLog(path, "solve", config=cfg, meta={"n": 100, "d": 4})
    log.record("chunk", pairs=10, pairs_delta=10, b_hi=-1.0, b_lo=1.0,
               gap=2.0, device_seconds=0.5, dispatch=1)
    log.record("event", name="demotion", gap=0.1)
    log.span_sink({"kind": "span", "name": "solver/chunk",
                   "t": 1.0, "dur": 0.5})
    log.finish(iterations=10, converged=True)
    log.finish()  # idempotent

    recs = read_runlog(path)
    assert [r["kind"] for r in recs] == ["manifest", "chunk", "event",
                                         "span", "final"]
    assert all(r["schema"] == SCHEMA_VERSION for r in recs)
    assert len({r["run"] for r in recs}) == 1
    man = recs[0]
    # Config snapshot survives the trip (dataclass -> JSON -> dict).
    assert man["config"]["c"] == 3.0
    assert man["config"]["engine"] == "block"
    assert man["config"]["obs"] == {"enabled": False, "trace_dir": None,
                                    "runlog_dir": None}
    assert man["n"] == 100 and man["tool"] == "solve"
    assert {"git_sha", "jax", "backend", "n_devices"} <= man.keys()
    assert recs[-1]["iterations"] == 10


def test_runlog_reader_skips_future_schema_and_garbage(tmp_path):
    p = tmp_path / "x.jsonl"
    good = {"schema": SCHEMA_VERSION, "run": "1-1", "kind": "chunk"}
    future = {"schema": SCHEMA_VERSION + 1, "run": "1-1", "kind": "chunk"}
    p.write_text(json.dumps(good) + "\n" + json.dumps(future) + "\n"
                 + "not json at all\n"
                 + json.dumps({"no": "keys"}) + "\n"
                 + '{"schema": 1, "run": "t", "ki')  # truncated tail
    recs = read_runlog(str(p))
    assert recs == [good]


def test_runlog_interleaved_runs_separate_cleanly(tmp_path):
    """Two runs writing CONCURRENTLY to one per-(tool, pid) stream —
    records interleaved record-by-record, not run-by-run — must
    separate exactly by run id, and the analytics loader must yield
    both runs with their own chunks (ISSUE 8 satellite)."""
    from dpsvm_tpu.obs.analyze import load_runs

    path = str(tmp_path / "solve-interleaved.jsonl")
    l1 = RunLog(path, "solve")
    l2 = RunLog(path, "solve")  # opened before l1 finishes
    for i in range(3):
        l1.record("chunk", pairs=10 * (i + 1), pairs_delta=10,
                  gap=1.0 / (i + 1), device_seconds=0.1, dispatch=i + 1)
        l2.record("chunk", pairs=5 * (i + 1), pairs_delta=5,
                  gap=2.0 / (i + 1), device_seconds=0.2, dispatch=i + 1)
    l2.finish(iterations=15, converged=False)
    l1.finish(iterations=30, converged=True)

    recs = read_runlog(path)
    c1 = records_for(recs, l1.run_id, "chunk")
    c2 = records_for(recs, l2.run_id, "chunk")
    assert [c["pairs"] for c in c1] == [10, 20, 30]
    assert [c["pairs"] for c in c2] == [5, 10, 15]
    runs = load_runs([path])
    assert [r.run_id for r in runs] == [l1.run_id, l2.run_id]
    assert [len(r.chunks) for r in runs] == [3, 3]
    assert runs[0].final["converged"] is True
    assert runs[1].final["converged"] is False


def test_runlog_reader_skips_corrupted_mid_file_record(tmp_path):
    """A record corrupted in the MIDDLE of a stream (disk hiccup,
    partial overwrite) must cost exactly that record — everything
    before AND after it still parses (only the truncated-tail case was
    pinned before)."""
    p = tmp_path / "x.jsonl"
    a = {"schema": SCHEMA_VERSION, "run": "1-1", "kind": "chunk",
         "pairs": 1}
    b = {"schema": SCHEMA_VERSION, "run": "1-1", "kind": "chunk",
         "pairs": 2}
    c = {"schema": SCHEMA_VERSION, "run": "1-1", "kind": "final"}
    corrupt = json.dumps(b)[:17] + "\x00\x00garbage"
    p.write_text("\n".join([json.dumps(a), corrupt, json.dumps(b),
                            json.dumps(c)]) + "\n")
    recs = read_runlog(str(p))
    assert recs == [a, b, c]


def test_git_sha_follows_gitdir_pointer(tmp_path):
    """Worktree/submodule checkouts have .git as a FILE holding a
    `gitdir:` pointer; git_sha must follow it (relative or absolute)
    instead of logging "unknown" (ISSUE 8 satellite)."""
    from dpsvm_tpu.obs.runlog import git_sha

    sha = "deadbeef" * 5
    # The pointed-to git dir (the layout `git worktree add` creates).
    gd = tmp_path / "parent" / ".git" / "worktrees" / "wt"
    gd.mkdir(parents=True)
    (gd / "HEAD").write_text("ref: refs/heads/topic\n")
    (gd / "commondir").write_text("../..\n")
    common = tmp_path / "parent" / ".git"
    (common / "refs" / "heads").mkdir(parents=True)
    (common / "refs" / "heads" / "topic").write_text(sha + "\n")
    # The worktree root whose .git is a pointer FILE.
    wt = tmp_path / "wt"
    wt.mkdir()
    (wt / ".git").write_text(f"gitdir: {gd}\n")
    assert git_sha(str(wt)) == sha
    # Relative pointer resolves against the worktree root.
    (wt / ".git").write_text("gitdir: ../parent/.git/worktrees/wt\n")
    assert git_sha(str(wt)) == sha
    # Detached-HEAD worktree: HEAD holds the sha directly.
    (gd / "HEAD").write_text(sha + "\n")
    assert git_sha(str(wt)) == sha
    # ... and a normal .git DIRECTORY still resolves (regression).
    norm = tmp_path / "norm"
    (norm / ".git" / "refs" / "heads").mkdir(parents=True)
    (norm / ".git" / "HEAD").write_text("ref: refs/heads/main\n")
    (norm / ".git" / "refs" / "heads" / "main").write_text(sha + "\n")
    assert git_sha(str(norm)) == sha


def test_runlog_multiple_runs_share_a_file(tmp_path):
    path = str(tmp_path / "solve-shared.jsonl")
    l1 = RunLog(path, "solve")
    l1.record("chunk", pairs=1, pairs_delta=1)
    l1.finish()
    l2 = RunLog(path, "solve")
    l2.record("chunk", pairs=2, pairs_delta=2)
    l2.finish()
    recs = read_runlog(path)
    assert l1.run_id != l2.run_id
    assert [c["pairs"] for c in records_for(recs, l2.run_id, "chunk")] \
        == [2]


# ------------------------------------------------------ metrics bounds

def test_histogram_bounded_and_exact_window():
    h = Histogram("t", window=64)
    for v in np.linspace(0.001, 1.0, 1000):
        h.observe(float(v))
    assert h.count == 1000
    assert len(h) == 64  # ring bounded
    assert h._ring.shape == (64,)  # no growth
    p = h.percentiles()
    assert p["p50"] <= p["p95"] <= p["p99"]
    snap = h.snapshot()
    assert snap["count"] == 1000 and snap["min"] > 0
    json.dumps(snap)  # JSON-able
    assert int(sum(h._bins)) == 1000  # lifetime bins count everything


def test_histogram_percentiles_match_deque_semantics():
    """The recent-window percentile is exact over the last `window`
    samples — what the old serve deques provided."""
    h = Histogram("t", window=100)
    for v in range(1000):
        h.observe(float(v))
    assert h.percentiles((50,))["p50"] == pytest.approx(
        float(np.percentile(np.arange(900, 1000, dtype=float), 50)))


def test_counter_gauge_snapshot():
    reg = Registry(enabled=True)
    reg.counter("a").add(3)
    reg.counter("a").add(2)
    reg.gauge("b").set(7.5)
    assert reg.snapshot() == {"a": 5, "b": 7.5}


# ------------------------------------------------------ serve path

def _tiny_multiclass(d=6):
    from dpsvm_tpu.models.multiclass import train_multiclass

    rng = np.random.default_rng(0)
    x = rng.random((90, d), np.float32)
    y = np.arange(90) % 3
    m, _ = train_multiclass(x, y, SVMConfig(c=1.0, epsilon=1e-2),
                            strategy="ovr")
    return m, x


def test_serve_histograms_bounded_under_sustained_enqueue(tmp_path):
    from dpsvm_tpu.serve import PredictServer

    m, x = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(
        buckets=(16,), obs=ObsConfig(enabled=True,
                                     runlog_dir=str(tmp_path))))
    for _ in range(60):
        srv.enqueue(x[:4])
        srv.flush()
    h = srv.stats["bucket_seconds"][16]
    assert isinstance(h, Histogram)
    assert h.count == 60
    assert len(h) <= h.window and h._ring.shape == (h.window,)
    p = h.percentiles()
    assert p["p50"] <= p["p99"]
    srv.close()
    srv.close()  # idempotent
    recs = read_runlog(str(tmp_path / f"serve-{os.getpid()}.jsonl"))
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "manifest" and kinds[-1] == "final"
    final = recs[-1]
    assert final["bucket_seconds"]["16"]["count"] == 60
    assert final["dispatches"] == 60


def test_runobs_metrics_recorded_without_env_optin(tmp_path):
    """Obs enabled via config/--obs alone (DPSVM_OBS unset) must still
    record metrics: the final record's dump is the run's PRIVATE
    registry, not the env-gated ambient one."""
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.data.synth import make_blobs_binary

    x, y = make_blobs_binary(n=200, d=8, seed=1, sep=1.2)
    r = solve(x, y, SVMConfig(c=2.0, epsilon=1e-3, obs=ObsConfig(
        enabled=True, runlog_dir=str(tmp_path))))
    final = records_for(read_runlog(r.stats["obs_runlog"]),
                        r.stats["obs_run_id"], "final")[0]
    assert final["metrics"]["solve.pairs_total"] == r.iterations
    assert final["metrics"]["solve.dispatches_total"] == r.dispatches
    assert final["metrics"]["solve.chunk_seconds"]["count"] >= 1


def test_second_sweep_not_contaminated_by_first():
    """offered_load_sweep on a long-lived server reports ONLY its own
    sweep's observations (the histograms are lifetime instruments; the
    report is baseline-differenced + last-N-scoped)."""
    from dpsvm_tpu.serve import PredictServer, offered_load_sweep

    m, _ = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    offered_load_sweep(srv, [1, 4, 8], 24, group=4)
    rec2 = offered_load_sweep(srv, [1, 4, 8], 24, group=4)
    assert rec2["requests"] == 24
    total_disp = sum(r["dispatches"]
                     for r in rec2["bucket_latency"].values())
    # Dispatch counts are this sweep's delta, not server lifetime.
    assert total_disp < srv.stats["dispatches"]
    # Request percentiles cover exactly this sweep's 24 samples.
    assert rec2["request_latency"] == \
        srv.request_seconds.percentiles(last=24)


def test_offered_load_sweep_reports_from_shared_histograms():
    from dpsvm_tpu.serve import PredictServer, offered_load_sweep

    m, _ = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(buckets=(16, 64)))
    rec = offered_load_sweep(srv, [1, 4, 8], 24, group=4)
    lat = rec["request_latency"]
    assert {"p50", "p95", "p99"} <= lat.keys()
    assert lat["p50"] <= lat["p95"] <= lat["p99"]
    # Reported percentiles ARE the server histogram's, not a private
    # aggregation.
    assert lat == srv.request_seconds.percentiles()
    for b, row in rec["bucket_latency"].items():
        assert row["dispatches"] == \
            srv.stats["bucket_seconds"][int(b)].count
    json.dumps(rec)


# ----------------------------------------------- /metrics endpoint

def _scrape(url: str) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith(
            "application/openmetrics-text")
        return resp.read().decode("utf-8")


def _parse_openmetrics(text: str):
    """Tiny strict OpenMetrics reader: families declared exactly once,
    `# EOF` terminated, every sample line `name{labels} value`."""
    assert text.endswith("# EOF\n")
    types, samples = {}, {}
    for ln in text.splitlines():
        if ln == "# EOF":
            break
        if ln.startswith("# TYPE "):
            _, _, name, t = ln.split()
            assert name not in types, f"family {name} declared twice"
            types[name] = t
        elif ln and not ln.startswith("#"):
            key, val = ln.rsplit(" ", 1)
            samples[key] = float(val)
    return types, samples


def test_metrics_endpoint_matches_snapshot(tmp_path):
    """Acceptance (ISSUE 8): /metrics parses as OpenMetrics and its
    quantiles EQUAL PredictServer.snapshot()'s percentiles — one
    definition behind both surfaces."""
    from dpsvm_tpu.serve import PredictServer, offered_load_sweep

    m, _ = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(buckets=(16, 64),
                                       metrics_port=0))
    try:
        offered_load_sweep(srv, [1, 4, 8], 24, group=4)
        text = _scrape(srv.exporter.url)
        types, samples = _parse_openmetrics(text)
        assert types["serve_requests"] == "counter"
        assert types["serve_request_seconds"] == "summary"
        assert types["serve_slo_attainment"] == "gauge"
        snap = srv.snapshot()
        mdl = f'model="{srv.model_id}"'
        assert samples[f"serve_requests_total{{{mdl}}}"] \
            == snap["requests"]
        assert samples[f"serve_dispatches_total{{{mdl}}}"] \
            == snap["dispatches"]
        rq = snap["request_seconds"]
        for q, p in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            assert samples[
                f'serve_request_seconds{{{mdl},quantile="{q:g}"}}'] \
                == rq[p]
        assert samples[f"serve_request_seconds_count{{{mdl}}}"] \
            == rq["count"]
        for b, row in snap["bucket_seconds"].items():
            assert samples[
                f'serve_bucket_seconds{{bucket="{b}",'
                f'quantile="0.5"}}'] == row["p50"]
        # SLO attainment over the recent window (50 ms default: every
        # CPU-harness dispatch sits far under it).
        att = samples[f'serve_slo_attainment{{{mdl},slo_ms="50"}}']
        w = srv.request_seconds.window_values()
        assert att == float(np.mean(w <= 0.05))
        assert samples[f"serve_compiles_total{{{mdl}}}"] \
            == srv.compiles.value
        # Non-/metrics paths 404 (the endpoint is not a web app).
        import urllib.error
        import urllib.request
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                srv.exporter.url.replace("/metrics", "/admin"),
                timeout=10)
    finally:
        srv.close()
    srv.close()  # idempotent (exporter already stopped)


def test_metrics_endpoint_concurrent_scrape_under_enqueue():
    """Concurrent-scrape safety (ISSUE 8 satellite): a scraper
    hammering /metrics while the server sustains enqueue/flush traffic
    must see only complete, parseable expositions — the instruments
    are single-writer, readers tolerate a torn recent-window."""
    import threading

    from dpsvm_tpu.serve import PredictServer

    m, x = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(buckets=(16,), metrics_port=0))
    url = srv.exporter.url
    errors: list = []
    texts: list = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                texts.append(_scrape(url))
            except Exception as e:  # noqa: BLE001 - collected for assert
                errors.append(e)

    threads = [threading.Thread(target=scraper) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(80):
            srv.enqueue(x[:4])
            srv.flush()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        srv.close()
    assert not errors
    assert len(texts) >= 3
    for text in texts:
        types, samples = _parse_openmetrics(text)
        assert "serve_requests" in types


def test_serve_config_metrics_validation():
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ServeConfig(metrics_port=70000)
    with _pytest.raises(ValueError):
        ServeConfig(slo_ms=0)
    assert ServeConfig().metrics_port is None  # off by default


# ----------------------------------------------- compile accounting

def test_compile_records_in_solve_runlog(blobs_small, tmp_path):
    """An executor built during a live run yields a `compile` runlog
    record naming the dispatch label, plus the compiles_total counter
    in the final metrics dump. A UNIQUE static arg (epsilon) forces a
    genuinely fresh compile inside the observed solve."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    cfg = SVMConfig(c=2.0, epsilon=1.23456e-3, obs=ObsConfig(
        enabled=True, runlog_dir=str(tmp_path)))
    r = solve(x, y, cfg)
    recs = read_runlog(r.stats["obs_runlog"])
    compiles = records_for(recs, r.stats["obs_run_id"], "compile")
    assert compiles, "no compile records for a fresh-epsilon solve"
    assert any(c["entrypoint"] == "solver/chunk" for c in compiles)
    assert all(c["seconds"] > 0 for c in compiles)
    assert all("shape" in c for c in compiles)
    final = records_for(recs, r.stats["obs_run_id"], "final")[0]
    assert final["metrics"]["solve.compiles_total"] == len(compiles)
    # A warm re-solve of the SAME program records zero compiles.
    r2 = solve(x, y, cfg)
    recs2 = read_runlog(r2.stats["obs_runlog"])
    assert records_for(recs2, r2.stats["obs_run_id"], "compile") == []


def test_serve_compiles_not_cross_inflated():
    """Two live servers share the "serve/bucket*" label namespace; the
    per-server counter must attribute a compile to the server whose
    dispatch triggered it, not to every server alive (review fix)."""
    from dpsvm_tpu.serve import PredictServer

    # d=9 is this test's own shape: its bucket executors cannot be
    # warm from other tests, so srv2's warm() must compile.
    m, _ = _tiny_multiclass(d=9)
    srv1 = PredictServer(m, ServeConfig(buckets=(16,)))
    c1 = srv1.compiles.value
    srv2 = PredictServer(m, ServeConfig(buckets=(32,)))
    try:
        assert srv2.compiles.value >= 1  # its own warm-up compile
        assert srv1.compiles.value == c1  # not srv2's
    finally:
        srv1.close()
        srv2.close()


def test_server_collectable_without_close():
    """An API user who drops a server without close() (legal pre-PR8:
    close was 'a no-op when obs is disabled') must not leak it — the
    compile sink and the exporter's render callback hold the server
    WEAKLY (review fix; the RunObs discipline)."""
    import gc
    import weakref

    from dpsvm_tpu.serve import PredictServer

    m, _ = _tiny_multiclass()
    srv = PredictServer(m, ServeConfig(buckets=(16,), metrics_port=0))
    exporter = srv.exporter
    url = exporter.url
    r = weakref.ref(srv)
    del srv
    gc.collect()
    assert r() is None, "dropped server still referenced"
    # The orphan exporter thread degrades to an empty exposition
    # until process exit (daemon thread) — it must still answer.
    text = _scrape(url)
    assert text == "# EOF\n"
    exporter.close()


def test_compilelog_label_nesting_and_counter():
    from dpsvm_tpu.obs import compilelog

    base = compilelog.compiles_total()
    seen = []
    sink = lambda name, shape, secs: seen.append((name, shape))  # noqa: E731
    compilelog.add_sink(sink)
    try:
        import jax
        import jax.numpy as jnp

        with compilelog.label("outer"), \
                compilelog.label("test/inner", "(3,)"):
            jax.jit(lambda v: v * 3.14159 + 2.71828)(
                jnp.arange(3.0)).block_until_ready()
    finally:
        compilelog.remove_sink(sink)
    assert compilelog.compiles_total() > base
    assert ("test/inner", "(3,)") in seen
    # Exited labels must not leak onto later compiles.
    assert not compilelog._labels


# ------------------------------------------------- solver runlog facts

def test_solve_runlog_reconciles_with_result(blobs_small, tmp_path):
    """The bench acceptance contract at unit scale: per-chunk records
    sum EXACTLY (mod rounding) to the result's iterations and
    train_seconds — on a multi-chunk observed run."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    cfg = SVMConfig(c=2.0, epsilon=1e-3, chunk_iters=64,
                    obs=ObsConfig(enabled=True,
                                  runlog_dir=str(tmp_path)))
    r = solve(x, y, cfg, callback=lambda *a: None)  # observed cadence
    recs = read_runlog(r.stats["obs_runlog"])
    chunks = records_for(recs, r.stats["obs_run_id"], "chunk")
    assert len(chunks) == r.dispatches > 1
    assert sum(c["pairs_delta"] for c in chunks) == r.iterations
    assert sum(c["device_seconds"] for c in chunks) == pytest.approx(
        r.train_seconds, abs=1e-4)
    final = records_for(recs, r.stats["obs_run_id"], "final")[0]
    assert final["iterations"] == r.iterations
    assert final["converged"] == r.converged
    assert "metrics" in final
    # Gap trajectory is monotone-ish and ends converged.
    assert chunks[-1]["gap"] <= chunks[0]["gap"]


def test_phase_seconds_honest_shape(blobs_small):
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    r = solve(x, y, SVMConfig(c=2.0, epsilon=1e-3))
    ph = r.stats["phase_seconds"]
    assert set(ph) == {"setup", "solve", "observe", "finalize"}
    assert all(v >= 0 for v in ph.values())
    assert ph["solve"] == pytest.approx(r.train_seconds, abs=1e-5)


def test_mesh_solve_runlog(blobs_medium, tmp_path):
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_medium
    cfg = SVMConfig(c=2.0, epsilon=1e-3, engine="block",
                    working_set_size=16,
                    obs=ObsConfig(enabled=True,
                                  runlog_dir=str(tmp_path)))
    r = solve_mesh(x, y, cfg, num_devices=4)
    assert r.converged
    ph = r.stats["phase_seconds"]
    assert ph["solve"] == pytest.approx(r.train_seconds, abs=1e-5)
    recs = read_runlog(r.stats["obs_runlog"])
    man = records_for(recs, r.stats["obs_run_id"], "manifest")[0]
    assert man["n_devices"] == 4 and man["tool"] == "solve_mesh"
    chunks = records_for(recs, r.stats["obs_run_id"], "chunk")
    assert sum(c["pairs_delta"] for c in chunks) == r.iterations


# ------------------------------------------------------ trace session

def test_trace_session_collects_host_timeline(tmp_path):
    with trace.TraceSession() as sess:
        with trace.span("unit/stage"):
            pass
        with trace.span("unit/other"):
            with trace.span("unit/nested"):
                pass
    assert [e["name"] for e in sess.events] == \
        ["unit/stage", "unit/nested", "unit/other"]
    assert all(e["kind"] == "span" and e["dur"] >= 0
               for e in sess.events)
    # Session closed: spans are null again.
    assert trace.span("x") is trace.span("y")


def test_trace_sessions_attribute_to_innermost():
    """Concurrent/nested sessions each collect their OWN spans (the
    bench_serve two-servers case: run 2's events must not land in run
    1's log under run 1's id)."""
    with trace.TraceSession() as outer:
        with trace.TraceSession() as inner:
            with trace.span("inner/work"):
                pass
        with trace.span("outer/work"):
            pass
    assert [e["name"] for e in inner.events] == ["inner/work"]
    assert [e["name"] for e in outer.events] == ["outer/work"]
    assert trace.active_session() is None


def test_trace_sessions_interleaved_close():
    """Out-of-order close (server1 closed after server2 opened) must
    not break attribution or leak stack entries."""
    s1 = trace.TraceSession().__enter__()
    s2 = trace.TraceSession().__enter__()
    with trace.span("two"):
        pass
    s1.__exit__(None, None, None)
    with trace.span("still-two"):
        pass
    s2.__exit__(None, None, None)
    assert [e["name"] for e in s2.events] == ["two", "still-two"]
    assert s1.events == [] and trace.active_session() is None


def test_runobs_abort_path_clears_session_and_closes_log(tmp_path,
                                                         monkeypatch):
    """A solve that faults mid-loop never calls finish(); dropping the
    handle (what the fault-retry handler's frame release does) must
    close the run log AND exit the global trace session so later runs
    don't feed a dead one."""
    from dpsvm_tpu.obs import RunObs

    monkeypatch.setenv("DPSVM_OBS", "1")
    monkeypatch.setattr(obs_metrics, "_DEFAULT", None)
    o = RunObs("solve", meta={"n": 1}, directory=str(tmp_path))
    path = o.path
    assert trace.active_session() is not None
    o.chunk(pairs=5, b_hi=0.0, b_lo=1.0, device_seconds=0.1, dispatch=1)
    del o
    assert trace.active_session() is None
    recs = read_runlog(path)
    assert recs[-1]["kind"] == "final" and recs[-1]["aborted"] is True
    # ... and the normal path is unaffected + finish stays idempotent.
    o2 = RunObs("solve", directory=str(tmp_path))
    o2.finish(iterations=1)
    o2.finish(iterations=2)
    del o2
    finals = [r for r in read_runlog(path) if r["kind"] == "final"]
    assert finals[-1]["iterations"] == 1
    assert "aborted" not in finals[-1]


def test_trace_session_events_bounded(monkeypatch):
    monkeypatch.setattr(trace, "_MAX_EVENTS", 8)
    with trace.TraceSession() as sess:
        for i in range(20):
            with trace.span(f"s{i}"):
                pass
    assert len(sess.events) == 8 and sess.dropped == 12


# ------------------------------------------------------ CLI surface

def test_cli_train_obs_writes_runlog(tmp_path):
    from dpsvm_tpu import cli

    rng = np.random.default_rng(0)
    x = rng.normal(size=(80, 5)).astype(np.float32)
    y = np.where(x[:, 0] > 0, 1, -1)
    csv = tmp_path / "train.csv"
    csv.write_text("\n".join(
        ",".join([str(int(yi))] + [f"{v:.5f}" for v in row])
        for yi, row in zip(y, x)) + "\n")
    model = tmp_path / "m.txt"
    rc = cli.main(["train", "-f", str(csv), "-m", str(model), "-q",
                   "--obs", "--obs-dir", str(tmp_path / "runs")])
    assert rc == 0
    # backend auto routes to mesh on the 8-virtual-device harness; a
    # single-device box would write solve-*.jsonl — accept either.
    files = list((tmp_path / "runs").glob("solve*.jsonl"))
    assert len(files) == 1
    recs = read_runlog(str(files[0]))
    kinds = {r["kind"] for r in recs}
    assert {"manifest", "chunk", "final"} <= kinds


def test_bench_gate_skips_future_schema_artifacts(tmp_path):
    import bench

    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"pairs_per_second": 1000,
         "session_calibration": {"best_of_5_seconds": 0.5}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"pairs_per_second": 777, "schema_version": SCHEMA_VERSION + 1,
         "session_calibration": {"best_of_5_seconds": 0.5}}))
    path, doc = bench._latest_bench_artifact(str(tmp_path))
    assert path.endswith("BENCH_r01.json")
    assert doc["pairs_per_second"] == 1000


def test_bench_runlog_reconciliation(blobs_small, tmp_path):
    """bench._runlog_reconciliation against a real obs solve: the
    1%-acceptance field computes and passes."""
    import bench
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_small
    r = solve(x, y, SVMConfig(
        c=2.0, epsilon=1e-3, budget_mode=True, max_iter=400,
        obs=ObsConfig(enabled=True, runlog_dir=str(tmp_path))))
    pps = r.iterations / max(r.train_seconds, 1e-9)
    rec = bench._runlog_reconciliation(r, pps)
    assert rec["runlog_reconciles"] is True
    assert abs(rec["runlog_delta"]) <= 0.01
    assert rec["runlog"] == r.stats["obs_runlog"]
    # ... and the field set is empty without obs (no crash, no noise).
    r2 = solve(x, y, SVMConfig(c=2.0, epsilon=1e-3))
    assert bench._runlog_reconciliation(r2, 1.0) == {}


# ---------------------------------------------------------------------------
# MetricsExporter teardown ordering (ISSUE 20 satellite): close() is
# SERIALIZED — any caller that returns from close() may rely on the
# socket being unbound and the serving thread joined. The old
# flag-first idempotence let a second closer return mid-shutdown,
# so engine teardown proceeded believing the port and thread were
# gone (the last member of the scrape-during-close race family).
# ---------------------------------------------------------------------------
def test_exporter_concurrent_close_serialized():
    import threading
    import urllib.request

    from dpsvm_tpu.obs.export import MetricsExporter

    exp = MetricsExporter(lambda: "# EOF\n", port=0)
    # Prove it is live before the teardown race starts.
    assert b"# EOF" in urllib.request.urlopen(exp.url,
                                              timeout=5).read()
    alive_after_return = []
    start = threading.Barrier(3)

    def closer():
        start.wait()
        exp.close()
        # THE contract under test: once close() returns to ANY
        # caller, the serving thread is joined — no caller can
        # observe a half-torn-down exporter.
        alive_after_return.append(exp._thread.is_alive())

    ts = [threading.Thread(target=closer, name=f"dpsvm-test-close-{i}")
          for i in range(3)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert alive_after_return == [False, False, False]
    exp.close()  # still idempotent after the storm


def test_exporter_scrape_during_close_never_wedges():
    import threading
    import urllib.error
    import urllib.request

    from dpsvm_tpu.obs.export import MetricsExporter

    exp = MetricsExporter(lambda: "x 1\n# EOF\n", port=0)
    stop = threading.Event()
    outcomes = []

    def scrape_loop():
        while not stop.is_set():
            try:
                urllib.request.urlopen(exp.url, timeout=2).read()
                outcomes.append("ok")
            except (urllib.error.URLError, ConnectionError, OSError):
                outcomes.append("refused")  # post-close is fine

    th = threading.Thread(target=scrape_loop,
                          name="dpsvm-test-scrape")
    th.start()
    try:
        # Let scrapes land, then tear down mid-traffic.
        for _ in range(50):
            if "ok" in outcomes:
                break
            import time
            time.sleep(0.01)
        exp.close()
        assert not exp._thread.is_alive()
    finally:
        stop.set()
        th.join(timeout=5)
    assert not th.is_alive()
    assert "ok" in outcomes  # at least one scrape answered pre-close
