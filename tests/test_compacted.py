"""Shared-SV compacted multiclass inference (models/multiclass.py
CompactedEnsemble).

The contract under test: the compacted path evaluates ONE kernel matmul
against the SV union per query block (HLO-pinned) and is BIT-IDENTICAL
to the replicated stacked path on shared-kernel ensembles — the exact
contraction gathers each submodel's kernel values back into its own SV
order, so the per-model reduction sums identical terms in identical
order (pad slots are exact +0.0 in both paths)."""

import re

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.multiclass import (
    MulticlassSVM,
    _STACK_MEMO,
    compact_models,
    decision_matrix,
    predict_multiclass,
    train_multiclass,
    vote_matrix,
)
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams

CFG = SVMConfig(c=5.0, gamma=0.2, epsilon=1e-3, max_iter=100_000,
                chunk_iters=256)


@pytest.fixture(scope="module")
def four_class():
    rng = np.random.default_rng(23)
    xs, ys = [], []
    for k in range(4):
        c = np.zeros(6, np.float32)
        c[k] = 2.2
        xs.append(rng.normal(size=(90, 6)).astype(np.float32) * 0.8 + c)
        ys.append(np.full(90, k + 7))  # non-contiguous labels on purpose
    x, y = np.concatenate(xs), np.concatenate(ys)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.fixture(scope="module", params=["ovr", "ovo"])
def trained(request, four_class):
    x, y = four_class
    m, _ = train_multiclass(x[:300], y[:300], CFG,
                            strategy=request.param)
    return m, x


def _hand_model(rows, coefs, b, kp, rng):
    rows = np.asarray(rows, np.float32)
    coefs = np.asarray(coefs, np.float32)
    y = np.where(rng.random(len(coefs)) < 0.5, 1, -1).astype(np.int32)
    return SVMModel(sv_x=rows, sv_alpha=np.abs(coefs), sv_y=y,
                    b=float(b), kernel=kp)


# ------------------------------------------------------------- bit parity

def test_compacted_bit_identical_to_stacked(trained):
    m, x = trained
    q = np.asarray(x[280:420], np.float32)
    a = decision_matrix(m, q, path="stacked")
    b = decision_matrix(m, q, path="compacted")
    np.testing.assert_array_equal(a, b)
    # ...and the auto route IS the compacted path for shared kernels.
    np.testing.assert_array_equal(decision_matrix(m, q), b)


def test_compacted_dedup_is_real(trained):
    m, _ = trained
    ens = m.compacted
    assert ens is not None
    total = sum(mm.n_sv for mm in m.models)
    assert ens.n_union < total  # submodels genuinely share rows
    assert int(ens.counts.sum()) == total
    # The dense coefficient matrix scatters exactly the per-model coefs.
    assert np.count_nonzero(ens.coef) <= total


def test_compacted_blocked_queries_bit_identical(trained):
    m, x = trained
    q = np.asarray(x[:100], np.float32)
    np.testing.assert_array_equal(
        decision_matrix(m, q, block=16, path="compacted"),
        decision_matrix(m, q, path="stacked"))


def test_union_order_training_matrix_vs_byte_fallback(trained):
    """Compaction with and without the training matrix may order the
    union differently, but decisions are bit-identical either way (the
    gather re-establishes per-model order)."""
    m, x = trained
    with_x = compact_models(m.models, x_train=x[:300])
    without = compact_models(m.models)
    assert with_x.n_union == without.n_union
    me_with = MulticlassSVM(classes=m.classes, models=m.models,
                            strategy=m.strategy, compacted=with_x)
    me_wo = MulticlassSVM(classes=m.classes, models=m.models,
                          strategy=m.strategy, compacted=without)
    q = np.asarray(x[:64], np.float32)
    np.testing.assert_array_equal(
        decision_matrix(me_with, q, path="compacted"),
        decision_matrix(me_wo, q, path="compacted"))


@pytest.mark.parametrize("kind,kw", [("linear", {}),
                                     ("poly", {"degree": 3, "coef0": 1.0}),
                                     ("sigmoid", {"coef0": 0.5})])
def test_compacted_parity_other_kernels(kind, kw):
    rng = np.random.default_rng(5)
    kp = KernelParams(kind=kind, gamma=0.3, **kw)
    pool = rng.normal(size=(80, 7)).astype(np.float32)
    models = []
    for j in range(5):
        idx = np.sort(rng.choice(80, 30 + 5 * j, replace=False))
        models.append(_hand_model(pool[idx], rng.normal(size=len(idx)),
                                  rng.normal() * 0.1, kp, rng))
    m = MulticlassSVM(classes=np.arange(5), models=models,
                      strategy="ovr")
    q = rng.normal(size=(33, 7)).astype(np.float32)
    np.testing.assert_array_equal(decision_matrix(m, q, path="stacked"),
                                  decision_matrix(m, q, path="compacted"))


# ------------------------------------------------- degenerate submodels

def test_empty_sv_submodel():
    """A submodel that converged to zero SVs (degenerate split) must
    compact and evaluate: its column is exactly -b."""
    rng = np.random.default_rng(9)
    kp = KernelParams("rbf", 0.25)
    pool = rng.normal(size=(40, 5)).astype(np.float32)
    empty = SVMModel(sv_x=np.zeros((0, 5), np.float32),
                     sv_alpha=np.zeros((0,), np.float32),
                     sv_y=np.zeros((0,), np.int32), b=0.37, kernel=kp)
    full = _hand_model(pool[:20], rng.normal(size=20), -0.1, kp, rng)
    m = MulticlassSVM(classes=np.arange(2), models=[empty, full],
                      strategy="ovr")
    q = rng.normal(size=(17, 5)).astype(np.float32)
    dec = decision_matrix(m, q, path="compacted")
    np.testing.assert_array_equal(dec[:, 0],
                                  np.full(17, -0.37, np.float32))
    np.testing.assert_array_equal(dec,
                                  decision_matrix(m, q, path="stacked"))


def test_all_empty_ensemble():
    kp = KernelParams("rbf", 0.25)
    models = [SVMModel(sv_x=np.zeros((0, 4), np.float32),
                       sv_alpha=np.zeros((0,), np.float32),
                       sv_y=np.zeros((0,), np.int32), b=b0, kernel=kp)
              for b0 in (0.5, -0.25, 0.0)]
    m = MulticlassSVM(classes=np.arange(3), models=models,
                      strategy="ovr")
    ens = m.ensure_compacted()
    assert ens.n_union == 0
    dec = decision_matrix(m, np.zeros((6, 4), np.float32),
                          path="compacted")
    np.testing.assert_array_equal(
        dec, np.broadcast_to([-0.5, 0.25, 0.0],
                             (6, 3)).astype(np.float32))


def test_duplicate_rows_within_one_model():
    """Byte-identical duplicate SV rows inside ONE model: the dense
    coefficient matrix accumulates them, the exact gather keeps them
    separate — both must match the stacked evaluation."""
    rng = np.random.default_rng(3)
    kp = KernelParams("rbf", 0.5)
    row = rng.normal(size=(1, 6)).astype(np.float32)
    rows = np.concatenate([row, row, rng.normal(size=(3, 6))
                           .astype(np.float32)])
    ma = _hand_model(rows, rng.normal(size=5), 0.1, kp, rng)
    mb = _hand_model(rows[1:], rng.normal(size=4), -0.2, kp, rng)
    m = MulticlassSVM(classes=np.arange(2), models=[ma, mb],
                      strategy="ovr")
    assert m.ensure_compacted().n_union == 4  # 5+4 rows -> 4 unique
    q = rng.normal(size=(11, 6)).astype(np.float32)
    np.testing.assert_array_equal(decision_matrix(m, q, path="stacked"),
                                  decision_matrix(m, q, path="compacted"))


def test_mixed_kernels_fall_back_per_model():
    rng = np.random.default_rng(4)
    pool = rng.normal(size=(30, 5)).astype(np.float32)
    ma = _hand_model(pool[:10], rng.normal(size=10), 0.0,
                     KernelParams("rbf", 0.5), rng)
    mb = _hand_model(pool[10:20], rng.normal(size=10), 0.0,
                     KernelParams("linear", 1.0), rng)
    m = MulticlassSVM(classes=np.arange(2), models=[ma, mb],
                      strategy="ovr")
    assert m.ensure_compacted() is None
    q = rng.normal(size=(8, 5)).astype(np.float32)
    dec = decision_matrix(m, q)  # auto -> per-model loop
    assert dec.shape == (8, 2)
    with pytest.raises(ValueError):
        decision_matrix(m, q, path="compacted")
    with pytest.raises(ValueError):
        decision_matrix(m, q, path="stacked")


# ------------------------------------------------- format v2 round-trip

def test_roundtrip_v2_persists_compaction(trained, tmp_path):
    m, x = trained
    p = str(tmp_path / "mc2.npz")
    m.save(p)
    z = np.load(p)
    assert int(z["format_version"]) == 2
    assert "c_sv_union" in z and "c_coef" in z and "c_idx" in z
    m2 = MulticlassSVM.load(p)
    assert m2.compacted is not None
    np.testing.assert_array_equal(m2.compacted.sv_union,
                                  m.compacted.sv_union)
    np.testing.assert_array_equal(m2.compacted.coef, m.compacted.coef)
    q = np.asarray(x[:50], np.float32)
    np.testing.assert_array_equal(decision_matrix(m2, q),
                                  decision_matrix(m, q))


def test_loads_v1_file_and_rebuilds_compaction(trained, tmp_path):
    """A pre-compaction (format_version 1) bundle — per-model fields
    only — must load and rebuild the compaction at load time, with
    bit-identical decisions."""
    m, x = trained
    payload = {
        "format_version": 1, "model_type": "multiclass",
        "strategy": m.strategy, "classes": m.classes,
        "n_models": len(m.models),
    }
    for i, mm in enumerate(m.models):  # the v1 writer's field set
        payload[f"m{i}_sv_x"] = mm.sv_x
        payload[f"m{i}_sv_alpha"] = mm.sv_alpha
        payload[f"m{i}_sv_y"] = mm.sv_y
        payload[f"m{i}_b"] = np.float32(mm.b)
        payload[f"m{i}_kernel_kind"] = mm.kernel.kind
        payload[f"m{i}_gamma"] = np.float32(mm.kernel.gamma)
        payload[f"m{i}_degree"] = np.int32(mm.kernel.degree)
        payload[f"m{i}_coef0"] = np.float32(mm.kernel.coef0)
    p = str(tmp_path / "mc1.npz")
    np.savez_compressed(p, **payload)
    m1 = MulticlassSVM.load(p)
    assert m1.compacted is not None  # rebuilt at load
    q = np.asarray(x[:50], np.float32)
    np.testing.assert_array_equal(decision_matrix(m1, q),
                                  decision_matrix(m, q))
    np.testing.assert_array_equal(predict_multiclass(m1, q),
                                  predict_multiclass(m, q))


# ------------------------------------- Platt / vote consumers unchanged

def test_vote_matrix_through_compacted(four_class):
    x, y = four_class
    m, _ = train_multiclass(x[:300], y[:300], CFG, strategy="ovo")
    q = np.asarray(x[300:], np.float32)
    np.testing.assert_array_equal(vote_matrix(m, q, path="compacted"),
                                  vote_matrix(m, q, path="stacked"))
    pred = predict_multiclass(m, q)
    assert set(np.unique(pred)) <= set(m.classes.tolist())
    assert float(np.mean(pred == y[300:])) > 0.8


def test_platt_proba_through_compacted(four_class):
    from dpsvm_tpu.estimators import SVC
    x, y = four_class
    clf = SVC(C=5.0, gamma=0.2, probability=True,
              random_state=0).fit(x[:240], y[:240])
    p = clf.predict_proba(x[240:300])
    assert p.shape == (60, 4)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-9)
    assert np.isfinite(p).all()
    # Probability argmax should mostly agree with the raw prediction.
    lab = clf.classes_[np.argmax(p, axis=1)]
    assert float(np.mean(lab == clf.predict(x[240:300]))) > 0.9


# --------------------------------------------------------- HLO structure

def test_hlo_one_kernel_matmul_per_query_block(trained):
    """The compacted executor must contain exactly ONE feature-dim
    kernel matmul — the (nb, S, d) union product — and NO rank-3
    batched (k, nb, m_pad, d) product (the stacked path's shape). The
    coefficient contraction is the only other dot. Structure facts of
    the compiled program, platform-independent (the
    test_hlo_collectives.py discipline)."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.models.multiclass import _compacted_batch_factory

    m, x = trained
    ens = m.compacted
    k, m_pad = ens.idx.shape
    nb, d = 64, ens.sv_union.shape[1]
    sds = jax.ShapeDtypeStruct
    text = _compacted_batch_factory().lower(
        sds((nb, d), jnp.float32),
        sds((ens.sv_union.shape[0], d), jnp.float32),
        sds((k, m_pad), jnp.float32),
        sds((k, m_pad), jnp.int32),
        sds((k,), jnp.float32),
        kp=ens.kernel,
    ).compile().as_text()

    # Expressed through the shared tpulint extractor (ISSUE 5) — the
    # same facts the committed compacted_decision budget pins.
    from dpsvm_tpu.analysis.hlo_facts import dot_facts, dot_result_shapes

    dots = dot_result_shapes(text)
    # THE kernel matmul = the dot producing the (nb, S) kernel tile
    # (either orientation; S includes the trailing pad row). The
    # row-norm einsums also lower to dots but produce rank-1 results;
    # the coefficient contraction produces (k, nb).
    s_union = ens.sv_union.shape[0]
    ker = [shp for dt, shp in dots
           if dt == "f32" and shp in ((nb, s_union), (s_union, nb))]
    assert len(ker) == 1, dots or text[:2000]
    # No replicated stack product anywhere: a rank-3 batched dot (the
    # stacked path's (*, m_pad, d) product) must not exist, nor even a
    # rank-3 f32 stack TENSOR of that shape.
    assert dot_facts(text)["batched_rank3plus"] == 0, dots
    assert not re.search(rf"f32\[\d+,{m_pad},{d}\]", text)
    # Kernel matmul + coefficient contraction + at most the two
    # row-norm reductions.
    assert dot_facts(text)["count"] <= 4, dots


# ----------------------------------------------------- stacked-path memo

def test_stacked_decision_memoizes_device_stack(trained):
    """Repeated stacked-path calls on the same models must upload the
    (k, m_pad, d) stack ONCE (content-fingerprint memo, the _XDEV_MEMO
    discipline) — the fallback path stays honest in serving A/Bs."""
    import jax

    m, x = trained
    q = np.asarray(x[:40], np.float32)
    calls = {"n": 0}
    orig = jax.device_put

    def counting(v, *a, **kw):
        # Count host-ndarray uploads only (see
        # test_pad_bucketing.test_xdev_memo_reuses_across_solves).
        if isinstance(v, np.ndarray) and v.ndim == 3:
            calls["n"] += 1
        return orig(v, *a, **kw)

    _STACK_MEMO.clear()
    jax.device_put = counting
    try:
        decision_matrix(m, q, path="stacked")
        decision_matrix(m, q[:16], path="stacked")
        decision_matrix(m, q, path="stacked")
        assert calls["n"] == 1
    finally:
        jax.device_put = orig
        _STACK_MEMO.clear()


def test_stacked_memo_rebuilds_on_mutation(trained):
    """In-place mutation of a submodel's SVs must invalidate the memo
    (fingerprint mismatch), not serve stale rows."""
    m, x = trained
    q = np.asarray(x[:24], np.float32)
    _STACK_MEMO.clear()
    before = decision_matrix(m, q, path="stacked")
    mm = m.models[0]
    old = mm.sv_x.copy()
    try:
        mm.sv_x *= 2.0  # identity-preserving in-place rescale
        after = decision_matrix(m, q, path="stacked")
        assert not np.array_equal(before, after)
    finally:
        mm.sv_x[:] = old
        _STACK_MEMO.clear()
