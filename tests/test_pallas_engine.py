"""Pallas-engine solver (software-pipelined fused kernel) vs XLA engine.
Runs in interpret mode on CPU; compiles natively on TPU."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=32, chunk_iters=256, engine="pallas")


def test_pallas_engine_matches_xla(blobs_small):
    x, y = blobs_small
    rp = solve(x, y, CFG)
    rx = solve(x, y, CFG.replace(engine="xla"))
    assert rp.converged and rx.converged
    # The pipelined loop skips the reference's final degenerate update, so
    # the count may differ by one.
    assert abs(rp.iterations - rx.iterations) <= 1
    assert rp.b == pytest.approx(rx.b, abs=2e-3)
    assert rp.n_sv == rx.n_sv
    np.testing.assert_allclose(rp.alpha, rx.alpha, atol=5e-3)


def test_pallas_engine_padding_is_inert():
    # n chosen so heavy padding is exercised (n=300 pads to 8192): the
    # padded rows must never be selected, so the run matches the unpadded
    # XLA engine's trajectory and solution.
    from dpsvm_tpu.data.synth import make_blobs_binary
    x, y = make_blobs_binary(n=300, d=6, seed=9, sep=1.4)
    rp = solve(x, y, CFG)
    rx = solve(x, y, CFG.replace(engine="xla"))
    assert rp.alpha.shape == (300,)
    assert rp.converged
    assert abs(rp.iterations - rx.iterations) <= 1
    assert rp.n_sv == rx.n_sv
    np.testing.assert_allclose(rp.alpha, rx.alpha, atol=5e-3)
    assert rp.b == pytest.approx(rx.b, abs=2e-3)


def test_pallas_engine_no_cache(blobs_small):
    x, y = blobs_small
    rp = solve(x, y, CFG.replace(cache_lines=0))
    rx = solve(x, y, CFG)
    assert abs(rp.iterations - rx.iterations) <= 1
    np.testing.assert_allclose(rp.alpha, rx.alpha, atol=5e-3)


def test_pallas_requires_mvp():
    with pytest.raises(ValueError):
        SVMConfig(engine="pallas", selection="second_order")
