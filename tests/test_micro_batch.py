"""Micro-batched per-pair executor (engine='xla', pair_batch in {2,4,8};
solver/smo.py _run_chunk_micro).

Semantics contract (the pair_batch=2 precedent of solver/block.py,
generalized): stale rank-j selection, exact corrected-gradient updates,
same optimum as the single-pair engine, different pair sequence. These
tests pin the model-level equivalence, the budget-exact counting, and
the composition with the extreme-C accuracy stack.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve


def _blobs(n=600, d=8, seed=5, sep=1.0):
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=n, d=d, seed=seed, sep=sep)


BASE = SVMConfig(c=10.0, gamma=0.1, epsilon=1e-3, max_iter=400_000)


@pytest.mark.parametrize("k", [2, 4, 8])
def test_micro_matches_single_pair_optimum(k):
    x, y = _blobs()
    ref = solve(x, y, BASE)
    got = solve(x, y, BASE.replace(pair_batch=k))
    assert got.converged
    assert abs(got.b - ref.b) < 5e-3
    dec_r = ref.stats["f"] + y - ref.b
    dec_g = got.stats["f"] + y - got.b
    assert np.mean(np.sign(dec_r) == np.sign(dec_g)) > 0.995
    # The batch amortizes trips: convergence must not need (many) more
    # pair updates than single-pair (stale ranks are near-optimal pairs).
    assert got.iterations < 3 * ref.iterations


@pytest.mark.parametrize("k", [4, 8])
def test_budget_mode_lands_exactly_on_max_iter(k):
    """Slot gating keeps the pair counter budget-exact even when the
    budget is not a multiple of the batch."""
    x, y = _blobs(sep=0.6)
    budget = 10_001
    res = solve(x, y, BASE.replace(pair_batch=k, budget_mode=True,
                                   max_iter=budget))
    assert res.iterations == budget


def test_micro_with_gram_compensated_and_legs():
    """The full extreme-C tail stack in one call: resident Gram +
    micro-batch + Kahan carry + f64 reconstruction legs."""
    x, y = _blobs(sep=0.6)
    cfg = BASE.replace(c=2000.0, pair_batch=4, gram_resident=True,
                       compensated=True, reconstruct_every=50_000)
    res = solve(x, y, cfg)
    assert res.converged
    assert res.stats["true_gap"] <= 2 * cfg.epsilon


def test_micro_respects_class_weights():
    """The batched slots use per-class box bounds like every engine."""
    x, y = _blobs(sep=0.7)
    cfg = BASE.replace(weight_pos=2.0, weight_neg=0.5)
    ref = solve(x, y, cfg)
    got = solve(x, y, cfg.replace(pair_batch=4))
    assert got.converged
    cp, cn = cfg.c_bounds()
    assert got.alpha[y > 0].max() <= cp + 1e-5
    assert got.alpha[y < 0].max() <= cn + 1e-5
    assert abs(got.b - ref.b) < 1e-2


def test_validation_matrix():
    with pytest.raises(ValueError, match="1, 2, 4 or 8"):
        SVMConfig(pair_batch=3)
    with pytest.raises(ValueError, match="mvp"):
        SVMConfig(pair_batch=4, selection="second_order")
    with pytest.raises(ValueError, match="pallas"):
        SVMConfig(pair_batch=2, engine="pallas")
    with pytest.raises(ValueError, match="block subproblem"):
        SVMConfig(pair_batch=8, engine="block")
    # Legal: the block subproblem batches up to 4 slots.
    SVMConfig(pair_batch=2, engine="block")
    SVMConfig(pair_batch=4, engine="block")


def test_free_point_in_both_top_lists_cannot_livelock():
    """Regression (round-5 review): a FREE point sits in both I_up and
    I_low. When it is simultaneously the rank-0 LOW candidate and a
    mid-rank UP candidate, a global drop-the-low-copy dedup gates off
    the maximal violating pair — the only slot guaranteed to execute —
    and the loop spins in counted no-op trips to max_iter. The
    rank-ordered collision gating must instead EXECUTE pair 0.

    Crafted state: I_up top-3 = {0, 3, 1} by f, I_low rank-0 = 1 (free),
    so index 1 collides across the lists exactly as in the finding."""
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.smo import _run_chunk_micro, init_state

    n, c = 6, 10.0
    y = jnp.asarray(np.array([1, 1, 1, -1, -1, -1], np.float32))
    alpha = np.array([0.0, 5.0, 10.0, 10.0, 0.0, 0.0], np.float32)
    f = np.array([-2.0, -1.0, -5.0, -1.5, -1.9, -1.8], np.float32)
    x = jnp.eye(n, 4, dtype=jnp.float32)  # any features; rbf rows exist
    x_sq = jnp.sum(x * x, axis=1)
    kp = KernelParams("rbf", 0.5)
    st = init_state(n, y, 1)._replace(alpha=jnp.asarray(alpha),
                                      f=jnp.asarray(f))
    out = _run_chunk_micro(x, y, x_sq, jnp.ones((n,), jnp.float32), None,
                           st, jnp.int32(3), kp, (c, c), 1e-3, 1e-12,
                           chunk=3, k=3)
    # The maximal violating pair (0, 1) must have APPLIED: alpha moved.
    assert not np.allclose(np.asarray(out.alpha), alpha)
    assert int(out.it) >= 1


def test_toy_problem_smaller_than_pair_batch():
    """n < pair_batch must clamp the selection's top-k to n (ADVICE
    round-5, low) instead of dying in an obscure XLA trace error — and
    still converge to the tiny problem's optimum."""
    x = np.array([[0.0, 0.0], [1.0, 1.0], [0.2, 0.1], [0.9, 1.1]],
                 np.float32)
    y = np.array([-1, 1, -1, 1], np.int32)
    ref = solve(x, y, BASE)
    for k in (8, 4):
        got = solve(x, y, BASE.replace(pair_batch=k))
        assert got.converged
        assert abs(got.b - ref.b) < 1e-3


def test_micro_checkpoint_resume(tmp_path):
    """Chunked observation + checkpoint/resume work through the micro
    executor (iteration counting survives the round trip)."""
    x, y = _blobs(sep=0.6)
    ck = str(tmp_path / "micro.npz")
    cfg = BASE.replace(c=100.0, pair_batch=4, checkpoint_every=500,
                       chunk_iters=500, max_iter=1500, budget_mode=True)
    r1 = solve(x, y, cfg, checkpoint_path=ck)
    assert r1.iterations == 1500
    cfg2 = cfg.replace(max_iter=3000)
    r2 = solve(x, y, cfg2, checkpoint_path=ck, resume=True)
    assert r2.iterations == 3000
