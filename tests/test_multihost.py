"""Multi-host bring-up: 2 OS processes wired by jax.distributed — the
executable stand-in for the reference's `mpirun --hostfile` launch
(reference Makefile:74, hf:1-11), which its repo could only exercise on a
real 11-host cluster (SURVEY.md section 4: "multi-node testing without a
cluster: not supported").

Exercises parallel/mesh.py initialize_multihost + cross-process psum /
all_gather / a distributed block-engine chunk with process-local shards.
The harness lives in tools/multihost_check.py (also `make multihost_check`).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_distributed_bringup():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py")],
        cwd=REPO, capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    if "MULTIHOST CHECK: SKIP" in proc.stdout:
        # Bring-up succeeded but this jax build's CPU backend cannot run
        # cross-process computations (see tools/multihost_check.py) —
        # an environment capability limit, not a launcher regression.
        pytest.skip(proc.stdout.strip().splitlines()[-1])
    assert "MULTIHOST CHECK: PASS" in proc.stdout
