"""Test harness configuration.

Runs every test on CPU with 8 virtual XLA devices — the TPU-world
equivalent of "multi-node testing without a cluster" that the reference
lacks entirely (SURVEY.md section 4: its multi-rank behavior was only ever
exercised on a real 11-host cluster).

Must run before jax is imported anywhere.
"""

import os

# NOTE: in this image a sitecustomize hook imports jax at interpreter
# startup with JAX_PLATFORMS=axon (the tunneled TPU), so setting the env
# var here is too late — override through the live config instead. The
# XLA_FLAGS env is still honored because no backend has been initialized
# yet when conftest runs.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(scope="session")
def blobs_small():
    """Non-separable 2-class blobs: small enough for exact oracles."""
    from dpsvm_tpu.data.synth import make_blobs_binary
    return make_blobs_binary(n=300, d=10, seed=3, sep=1.2)


@pytest.fixture(scope="session")
def blobs_medium():
    from dpsvm_tpu.data.synth import make_blobs_binary
    return make_blobs_binary(n=1200, d=24, seed=11, sep=1.0)
