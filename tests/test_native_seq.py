"""Native C++ sequential SMO engine (native/seqsmo.cpp) vs the NumPy
oracle — both play the reference's seq.cpp / seq_test.cpp roles, so they
must agree on the whole solver trajectory, not just the optimum."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.predict import accuracy, decision_function
from dpsvm_tpu.solver.reference import smo_native, smo_reference
from dpsvm_tpu.utils.native import get_seqsmo

pytestmark = pytest.mark.skipif(
    get_seqsmo() is None, reason="native toolchain unavailable")


def test_native_matches_oracle_trajectory(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    ref = smo_reference(x, y, cfg)
    nat = smo_native(x, y, cfg)
    assert nat.converged and ref.converged
    # Same algorithm, same fp32 math -> near-identical trajectories. Exact
    # iteration equality is not guaranteed (x86 FMA contraction can flip
    # ties) but they must land within a hair of each other.
    assert abs(nat.iterations - ref.iterations) <= max(3, ref.iterations // 50)
    assert nat.b == pytest.approx(ref.b, abs=5e-3)
    assert abs(nat.n_sv - ref.n_sv) <= max(2, ref.n_sv // 25)
    np.testing.assert_allclose(nat.alpha, ref.alpha, atol=5e-2)


def test_native_class_weights_match_oracle(blobs_small):
    # Regression: the seqsmo ABI takes separate c_pos/c_neg bounds; a
    # binding that drops one shifts every following argument and the
    # solver silently diverges.
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, weight_pos=2.0, weight_neg=0.5,
                    epsilon=1e-3, max_iter=100_000)
    ref = smo_reference(x, y, cfg)
    nat = smo_native(x, y, cfg)
    assert nat.converged and ref.converged
    assert nat.b == pytest.approx(ref.b, abs=5e-3)
    np.testing.assert_allclose(nat.alpha, ref.alpha, atol=5e-2)
    cp, cn = cfg.c_bounds()
    bound = np.where(y > 0, cp, cn)
    assert np.all(nat.alpha <= bound + 1e-6)


def test_native_decision_matches_python_predict(blobs_small):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    nat = smo_native(x, y, cfg)
    kp = KernelParams("rbf", 0.1)
    model = SVMModel.from_dense(x, y, nat.alpha, nat.b, kp)
    want = decision_function(model, x[:64])
    eng = get_seqsmo()
    got = eng.decision(model.sv_x, model.dual_coef, model.b, x[:64],
                       gamma=kp.gamma, kernel=kp.kind)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-4)


@pytest.mark.parametrize("kernel", ["linear", "poly", "sigmoid"])
def test_native_other_kernels(blobs_small, kernel):
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.05, kernel=kernel, degree=2, coef0=1.0,
                    epsilon=1e-3, max_iter=200_000)
    ref = smo_reference(x, y, cfg)
    nat = smo_native(x, y, cfg)
    assert nat.converged
    gamma = cfg.resolve_gamma(x.shape[1])
    model = SVMModel.from_dense(
        x, y, nat.alpha, nat.b, KernelParams(kernel, gamma, 2, 1.0))
    ref_model = SVMModel.from_dense(
        x, y, ref.alpha, ref.b, KernelParams(kernel, gamma, 2, 1.0))
    assert accuracy(model, x, y) == pytest.approx(
        accuracy(ref_model, x, y), abs=0.02)


def test_train_backend_native(blobs_small):
    from dpsvm_tpu.train import train
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000)
    model, res = train(x, y, cfg, backend="native")
    assert res.converged
    assert res.stats["engine"] == "native-seqsmo"
    ref = smo_reference(x, y, cfg)
    ref_model = SVMModel.from_dense(x, y, ref.alpha, ref.b,
                                    KernelParams("rbf", 0.1))
    assert accuracy(model, x, y) == pytest.approx(
        accuracy(ref_model, x, y), abs=0.01)


def test_train_backend_native_rejects_overrides(blobs_small):
    from dpsvm_tpu.train import train
    x, y = blobs_small
    with pytest.raises(ValueError, match="fixed host engine"):
        train(x, y, SVMConfig(selection="second_order"), backend="native")
