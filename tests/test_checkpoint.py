"""Checkpoint/resume tests — the failure-recovery capability the
reference lacks entirely (SURVEY.md section 5.3)."""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=16, chunk_iters=64, checkpoint_every=64)


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ck.npz")
    alpha = np.arange(5, dtype=np.float32)
    f = -alpha
    save_checkpoint(p, alpha, f, 123, -0.5, 0.7, CFG)
    a2, f2, it, bh, bl, cfg = load_checkpoint(p)
    np.testing.assert_array_equal(a2, alpha)
    np.testing.assert_array_equal(f2, f)
    assert it == 123 and bh == pytest.approx(-0.5) and bl == pytest.approx(0.7)
    assert cfg.c == CFG.c and cfg.chunk_iters == CFG.chunk_iters


def test_checkpoint_v2_full_carry_roundtrip(tmp_path):
    """FORMAT_VERSION 2 (ISSUE 13): the ooc driver's full carry —
    f_err lanes + round counter — rides the same file; omitted extras
    read back as None/0."""
    from dpsvm_tpu.utils.checkpoint import (FORMAT_VERSION,
                                            load_checkpoint_state)

    p = str(tmp_path / "ck2.npz")
    alpha = np.arange(5, dtype=np.float32)
    save_checkpoint(p, alpha, -alpha, 99, -0.1, 0.2, CFG,
                    f_err=alpha * 1e-7, rounds=17)
    st = load_checkpoint_state(p)
    assert st.format_version == FORMAT_VERSION == 2
    np.testing.assert_array_equal(st.f_err, alpha * 1e-7)
    assert st.rounds == 17 and st.iteration == 99
    # extras omitted -> absent, not zero-filled
    save_checkpoint(p, alpha, -alpha, 99, -0.1, 0.2, CFG)
    st = load_checkpoint_state(p)
    assert st.f_err is None and st.rounds == 0
    # the v1-shaped reader stays valid on v2 files
    a2, f2, it, _, _, cfg = load_checkpoint(p)
    assert it == 99 and cfg.c == CFG.c


def test_v1_checkpoint_still_loads_and_resumes(blobs_small, tmp_path):
    """Back-compat (ISSUE 13): a FORMAT_VERSION 1 file — what every
    pre-v2 run wrote — still loads (f_err -> None, rounds -> 0) and
    still resumes an in-core solve to the uninterrupted optimum."""
    import dataclasses
    import json

    from dpsvm_tpu.utils.checkpoint import load_checkpoint_state

    x, y = blobs_small
    full = solve(x, y, CFG)
    part = solve(x, y, CFG.replace(max_iter=128))
    p = str(tmp_path / "v1.npz")
    # A v1 file exactly as the old writer produced it.
    np.savez_compressed(
        p, format_version=1,
        alpha=np.asarray(part.alpha, np.float32),
        f=np.asarray(part.stats["f"], np.float32),
        iteration=np.int64(part.iterations),
        b_hi=np.float32(part.b_hi), b_lo=np.float32(part.b_lo),
        config_json=json.dumps(dataclasses.asdict(CFG)))
    st = load_checkpoint_state(p)
    assert st.format_version == 1 and st.f_err is None and st.rounds == 0
    res = solve(x, y, CFG, checkpoint_path=p, resume=True)
    assert res.converged
    assert res.iterations == full.iterations
    np.testing.assert_allclose(res.alpha, full.alpha, atol=1e-4)
    # unknown future versions refuse loudly
    np.savez_compressed(str(tmp_path / "v9.npz"), format_version=9,
                        alpha=np.zeros(3, np.float32),
                        f=np.zeros(3, np.float32),
                        iteration=np.int64(0), b_hi=np.float32(0),
                        b_lo=np.float32(0),
                        config_json=json.dumps(dataclasses.asdict(CFG)))
    with pytest.raises(ValueError, match="unsupported checkpoint"):
        load_checkpoint_state(str(tmp_path / "v9.npz"))


def test_interrupted_run_resumes_to_same_answer(blobs_small, tmp_path):
    x, y = blobs_small
    p = str(tmp_path / "solver.npz")
    full = solve(x, y, CFG)
    # "Preempt" after 128 iterations...
    part = solve(x, y, CFG.replace(max_iter=128), checkpoint_path=p)
    assert part.iterations == 128 and not part.converged
    save_checkpoint(p, part.alpha, part.stats["f"], part.iterations,
                    part.b_hi, part.b_lo, CFG)
    # ...and resume to convergence: same final answer as the uninterrupted run.
    res = solve(x, y, CFG, checkpoint_path=p, resume=True)
    assert res.converged
    assert res.iterations == full.iterations
    np.testing.assert_allclose(res.alpha, full.alpha, atol=1e-4)
    assert res.b == pytest.approx(full.b, abs=1e-4)


def test_mesh_resumes_from_single_chip_checkpoint(blobs_small, tmp_path):
    # Solver state is backend-portable: a single-chip checkpoint restores
    # onto an 8-device mesh (alpha/f are global row vectors either way).
    x, y = blobs_small
    p = str(tmp_path / "solver.npz")
    part = solve(x, y, CFG.replace(max_iter=128))
    save_checkpoint(p, part.alpha, part.stats["f"], part.iterations,
                    part.b_hi, part.b_lo, CFG)
    full = solve(x, y, CFG)
    res = solve_mesh(x, y, CFG, num_devices=8, checkpoint_path=p, resume=True)
    assert res.converged
    # Cross-BACKEND resume asserts the same solution, with one iteration of
    # slack: XLA's per-shard f-update lowering can differ from the
    # full-array one by a final ulp, which near a selection tie lets the
    # mesh run stop one iteration earlier/later than single-chip.
    assert abs(res.iterations - full.iterations) <= 1
    np.testing.assert_allclose(res.alpha, full.alpha, atol=1e-4)
    assert res.b == pytest.approx(full.b, abs=1e-4)


def test_resume_refuses_mismatched_config(blobs_small, tmp_path):
    # Resuming under different hyper-parameters would silently corrupt the
    # solution (f was computed under the old kernel) — must refuse loudly.
    x, y = blobs_small
    p = str(tmp_path / "ck.npz")
    part = solve(x, y, CFG.replace(max_iter=64), checkpoint_path=p)
    save_checkpoint(p, part.alpha, part.stats["f"], part.iterations,
                    part.b_hi, part.b_lo, CFG)
    with pytest.raises(ValueError, match="gamma"):
        solve(x, y, CFG.replace(gamma=0.5), checkpoint_path=p, resume=True)
    with pytest.raises(ValueError, match="n="):
        solve(x[:100], y[:100], CFG, checkpoint_path=p, resume=True)


def test_periodic_checkpoint_written_during_solve(blobs_small, tmp_path):
    import os
    x, y = blobs_small
    p = str(tmp_path / "auto.npz")
    solve(x, y, CFG.replace(max_iter=200), checkpoint_path=p)
    assert os.path.exists(p)
    a, f, it, *_ = load_checkpoint(p)
    assert 0 < it <= 200
    assert a.shape == (x.shape[0],)


def test_callback_abort_forces_checkpoint(tmp_path, blobs_small):
    """An abort exit must persist the state it stopped at, even when the
    periodic cadence isn't due (the stall-stop scenario)."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.utils.checkpoint import load_checkpoint

    x, y = blobs_small
    path = str(tmp_path / "abort.npz")
    cfg = SVMConfig(c=1.0, gamma=0.1, max_iter=100_000, chunk_iters=64,
                    checkpoint_every=1_000_000)  # cadence never due
    res = solve(x, y, cfg, callback=lambda it, bh, bl, st: it >= 128,
                checkpoint_path=path)
    assert not res.converged and res.iterations < 100_000
    alpha, f, it, b_hi, b_lo, _ = load_checkpoint(path)
    assert it == res.iterations  # the abort state, not a stale cadence one
    import numpy as np
    np.testing.assert_array_equal(alpha, res.alpha)


# ----------------------- durability + retention (ISSUE 15 satellites)

def test_fsync_before_rename_ordering(tmp_path, monkeypatch):
    """The power-loss durability pin: the tmp file's bytes must be
    fsynced BEFORE the rename publishes its name, and the directory
    entry fsynced AFTER — otherwise tmp+rename only survives killed
    processes, not power loss."""
    import os
    import stat

    calls = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(os, "fsync", lambda fd: (
        calls.append(("fsync",
                      "dir" if stat.S_ISDIR(os.fstat(fd).st_mode)
                      else "file")), real_fsync(fd))[1])
    monkeypatch.setattr(os, "replace", lambda a, b: (
        calls.append(("replace", None)), real_replace(a, b))[1])
    save_checkpoint(str(tmp_path / "ck.npz"), np.zeros(3, np.float32),
                    np.zeros(3, np.float32), 1, 0.0, 0.0, CFG)
    kinds = [(k, d) for k, d in calls]
    assert ("fsync", "file") in kinds and ("fsync", "dir") in kinds
    assert kinds.index(("fsync", "file")) \
        < kinds.index(("replace", None)) \
        < kinds.index(("fsync", "dir")), calls


def test_retention_rotates_and_survives_mid_save_fault(tmp_path):
    """checkpoint_keep=K keeps K rotating generations, and the exact
    ckpt_truncate window (tmp written, rename never ran — AFTER the
    rotation moved the newest aside) still leaves an older restorable
    generation that resume falls back to with a loud warning."""
    import os

    from dpsvm_tpu.testing import faults
    from dpsvm_tpu.utils.checkpoint import (PeriodicCheckpointer,
                                            checkpoint_generations,
                                            load_checkpoint_state,
                                            resume_state)

    n = 4
    cfg = CFG.replace(checkpoint_every=1, checkpoint_keep=3)
    p = str(tmp_path / "ck.npz")
    ck = PeriodicCheckpointer(p, cfg)
    for it in (10, 20, 30, 40):  # 4 saves -> 3 kept, oldest dropped
        assert ck.save(it, np.full(n, it, np.float32),
                       np.zeros(n, np.float32), 1.0, -1.0)
    gens = checkpoint_generations(p)
    assert [os.path.basename(g) for g in gens] == \
        ["ck.npz", "ck.npz.1", "ck.npz.2"]
    assert [load_checkpoint_state(g).iteration for g in gens] == \
        [40, 30, 20]
    # the fault being recovered from corrupts the NEWEST generation:
    # rotation already moved 40 -> .1, then the save dies mid-window.
    with faults.install(faults.FaultPlan.parse("ckpt_truncate")) as plan:
        with pytest.raises(faults.FaultInjected):
            ck.save(50, np.full(n, 50, np.float32),
                    np.zeros(n, np.float32), 1.0, -1.0)
    assert plan.fired["ckpt_truncate"] == 1
    assert not os.path.exists(p)  # the rename never ran
    with pytest.warns(UserWarning, match="OLDER CHECKPOINT GENERATION"):
        st = resume_state(p, cfg, n)
    assert st.iteration == 40  # the pre-fault newest, from .1
    # a keep=1 checkpointer never rotates (the historical layout)
    ck1 = PeriodicCheckpointer(str(tmp_path / "flat.npz"),
                               CFG.replace(checkpoint_every=1))
    ck1.save(1, np.zeros(n, np.float32), np.zeros(n, np.float32), 0, 0)
    ck1.save(2, np.ones(n, np.float32), np.zeros(n, np.float32), 0, 0)
    assert checkpoint_generations(str(tmp_path / "flat.npz")) == \
        [str(tmp_path / "flat.npz")]
    # REDUCING keep prunes the now-out-of-retention suffixes — stale
    # generations must not become surprise fallback targets
    ck2 = PeriodicCheckpointer(p, cfg.replace(checkpoint_keep=2))
    ck2.save(60, np.full(n, 60, np.float32),
             np.zeros(n, np.float32), 1.0, -1.0)
    assert [os.path.basename(g) for g in checkpoint_generations(p)] \
        == ["ck.npz", "ck.npz.1"]
    with pytest.raises(ValueError, match=r"\[1, 99\]"):
        cfg.replace(checkpoint_keep=150)


def test_resume_falls_back_past_corrupt_generations(tmp_path):
    """Every corrupt generation is skipped with a loud warning; only
    when ALL are unloadable does resume refuse (never a silent fresh
    start); compatibility mismatches still refuse immediately."""
    from dpsvm_tpu.utils.checkpoint import (PeriodicCheckpointer,
                                            resume_state)

    n = 4
    cfg = CFG.replace(checkpoint_every=1, checkpoint_keep=3)
    p = str(tmp_path / "ck.npz")
    ck = PeriodicCheckpointer(p, cfg)
    for it in (10, 20, 30):
        ck.save(it, np.full(n, it, np.float32),
                np.zeros(n, np.float32), 1.0, -1.0)
    for path in (p, p + ".1"):  # newest TWO generations corrupt
        with open(path, "wb") as fh:
            fh.write(b"not an npz")
    # (pytest re-emits non-matching warnings, so the pattern covers
    # both the per-generation skips and the final fallback notice)
    with pytest.warns(UserWarning,
                      match="UNUSABLE|UNREADABLE|OLDER CHECKPOINT"):
        st = resume_state(p, cfg, n)
    assert st.iteration == 10  # the oldest survivor
    # hyper-parameter mismatch refuses loudly even with generations
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError, match="refusing to resume"):
            resume_state(p, cfg.replace(c=999.0), n)
    # all generations corrupt -> refuse, never silently start fresh
    with open(p + ".2", "wb") as fh:
        fh.write(b"junk")
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError, match="unloadable"):
            resume_state(p, cfg, n)
