"""Real-dataset parity leg (VERDICT gap 1) — ACTIVE only when
tools/fetch_real_data.py has produced the converted CSVs under data/;
skips cleanly otherwise (the TPU-reachability preflight contract: a
sealed environment must not fail, and the day egress exists the real
legs run with zero code changes — `make fetch_real_data` is the
activation switch)."""

import importlib.util
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _frd():
    spec = importlib.util.spec_from_file_location(
        "fetch_real_data", os.path.join(REPO, "tools",
                                        "fetch_real_data.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_F = _frd()


def _needs(*names):
    return pytest.mark.skipif(
        not _F.real_data_available(*names),
        reason="real dataset not fetched (run `make fetch_real_data` "
               "with egress to activate this leg)")


@pytest.mark.slow
@_needs("mnist_odd_even_train")
def test_real_mnist_odd_even_parity():
    """Real-MNIST even/odd on a subset: the trained model must track
    sklearn's SVC within the repo's usual tolerance — the real-data
    version of the synthetic parity claims."""
    from sklearn.svm import SVC

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.loader import load_csv
    from dpsvm_tpu.predict import accuracy
    from dpsvm_tpu.train import train

    x, y = load_csv(_F.CONVERTED["mnist_odd_even_train"], num_rows=3000)
    xtr, ytr, xte, yte = x[:2400], y[:2400], x[2400:], y[2400:]
    cfg = SVMConfig(c=10.0, gamma=0.125, epsilon=1e-2)
    model, res = train(xtr, ytr, cfg, backend="single")
    assert res.converged
    acc = accuracy(model, xte, yte)
    sk = SVC(C=10.0, gamma=0.125, tol=1e-2).fit(xtr, ytr)
    assert acc >= sk.score(xte, yte) - 0.02


@pytest.mark.slow
@_needs("mnist_digits_train")
def test_real_mnist_digits_compacted_serving():
    """10-digit real MNIST through the compacted multiclass path: the
    serving claim (one union matmul, bit parity, real SV sharing) on
    real data."""
    from dpsvm_tpu.config import ServeConfig, SVMConfig
    from dpsvm_tpu.data.loader import load_csv
    from dpsvm_tpu.models.multiclass import (accuracy_multiclass,
                                             decision_matrix,
                                             train_multiclass)
    from dpsvm_tpu.serve import PredictServer

    x, y = load_csv(_F.CONVERTED["mnist_digits_train"], num_rows=2000)
    m, _ = train_multiclass(x[:1500], y[:1500],
                            SVMConfig(c=10.0, gamma=0.05,
                                      epsilon=1e-2),
                            strategy="ovo", backend="single")
    ens = m.compacted
    assert ens is not None
    assert ens.n_union < sum(mm.n_sv for mm in m.models)  # real sharing
    q = np.asarray(x[1500:], np.float32)
    np.testing.assert_array_equal(
        decision_matrix(m, q, path="compacted"),
        decision_matrix(m, q, path="stacked"))
    srv = PredictServer(m, ServeConfig(buckets=(64, 512)))
    np.testing.assert_allclose(srv.decision(q), decision_matrix(m, q),
                               rtol=1e-4, atol=1e-4)
    assert accuracy_multiclass(m, q, y[1500:]) > 0.8


@pytest.mark.slow
@_needs("covtype_binary")
def test_real_covtype_binary_subset():
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.loader import load_csv
    from dpsvm_tpu.predict import accuracy
    from dpsvm_tpu.train import train

    x, y = load_csv(_F.CONVERTED["covtype_binary"], num_rows=5000)
    cfg = SVMConfig(c=10.0, gamma=0.5, epsilon=1e-2,
                    engine="block")
    model, res = train(x[:4000], y[:4000], cfg, backend="single")
    assert accuracy(model, x[4000:], y[4000:]) > 0.7
