"""Automatic fault recovery (SURVEY.md section 5.3).

The reference loses the entire run when an MPI rank dies. Here:
(a) a transient device-runtime fault inside solve()/solve_mesh() is
    retried automatically, resuming from the last checkpoint
    (solver/smo.py run_with_fault_retry);
(b) a killed PROCESS resumes from its checkpoint on relaunch to the
    identical optimum (subprocess SIGKILL test).

Faults are injected through the deterministic harness's ``dispatch``
seam (dpsvm_tpu/testing/faults.py — ISSUE 13; this file's old ad-hoc
``_run_chunk`` monkeypatch fixture migrated onto it), so the faulted
dispatch is the REAL host-loop boundary every backend shares. The one
remaining monkeypatch is the non-transient classification test, which
exercises the error-class filter itself — a seam that only ever raises
the transient class cannot cover it.
"""

import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import dpsvm_tpu.solver.smo as smo_mod
from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.smo import solve
from dpsvm_tpu.testing import faults

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                chunk_iters=64, checkpoint_every=64, retry_faults=2)


@pytest.fixture
def no_backoff(monkeypatch):
    monkeypatch.setattr(smo_mod, "_RETRY_BACKOFF_S", ())


def test_auto_retry_resumes_from_checkpoint(blobs_small, tmp_path,
                                            no_backoff):
    x, y = blobs_small
    full = solve(x, y, CFG.replace(retry_faults=0))
    p = str(tmp_path / "ck.npz")
    # The 3rd chunk dispatch of THIS solve faults (checkpoints exist
    # by then: the cadence saves every chunk at these settings).
    with faults.install(faults.FaultPlan.parse("dispatch@3")) as plan:
        res = solve(x, y, CFG, checkpoint_path=p)
    assert plan.fired["dispatch"] == 1  # the fault really fired
    assert res.converged
    # Checkpoint resume replays the identical trajectory: same optimum.
    np.testing.assert_allclose(res.alpha, full.alpha, atol=1e-5)
    assert res.b == pytest.approx(full.b, abs=1e-5)
    assert res.iterations == full.iterations


def test_auto_retry_without_checkpoint_restarts(blobs_small, no_backoff):
    # Unobserved solves run in ONE dispatch — fault it, and verify the
    # retry restarts (observed/chunked this time) and completes.
    x, y = blobs_small
    with faults.install(faults.FaultPlan.parse("dispatch@1")) as plan:
        res = solve(x, y, CFG.replace(checkpoint_every=0))
    assert plan.fired["dispatch"] == 1
    assert res.converged


def test_retry_never_resumes_stale_checkpoint(blobs_small, tmp_path,
                                              no_backoff):
    """A retry must not silently continue a PREVIOUS run's leftover
    checkpoint when this run (checkpoint_every=0, resume=False) never
    wrote one — that would replace the fresh training the caller asked
    for."""
    from dpsvm_tpu.utils.checkpoint import save_checkpoint

    x, y = blobs_small
    p = str(tmp_path / "stale.npz")
    cfg = CFG.replace(checkpoint_every=0)
    # A stale checkpoint from "some earlier run", nearly converged.
    prev = solve(x, y, cfg.replace(retry_faults=0))
    save_checkpoint(p, prev.alpha, prev.stats["f"],
                    prev.iterations - 1, prev.b_hi, prev.b_lo, cfg)
    with faults.install(faults.FaultPlan.parse("dispatch@1")) as plan:
        res = solve(x, y, cfg, checkpoint_path=p)
    assert plan.fired["dispatch"] == 1
    assert res.converged
    # Restarted from scratch, not from the stale state: full iteration
    # count, not the ~1 iteration a stale resume would report.
    assert res.iterations == prev.iterations


def test_retry_budget_exhausts(blobs_small, tmp_path, no_backoff):
    # Every attempt's first dispatch faults -> the budget (retry_faults
    # + 1 attempts) exhausts and the last fault propagates.
    x, y = blobs_small
    with faults.install(
            faults.FaultPlan.parse("dispatch@1x64")) as plan:
        with pytest.raises(jax.errors.JaxRuntimeError,
                           match="UNAVAILABLE"):
            solve(x, y, CFG, checkpoint_path=str(tmp_path / "ck.npz"))
    assert plan.fired["dispatch"] == CFG.retry_faults + 1


def test_nontransient_fault_propagates(blobs_small, no_backoff,
                                       monkeypatch):
    # Deliberately NOT a harness seam: this pins the transient-fault
    # CLASSIFIER (INVALID_ARGUMENT must not be retried), so the
    # injection must produce a non-transient error the seam never
    # raises.
    calls = {"n": 0}
    orig = smo_mod._run_chunk

    def faulty(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INVALID_ARGUMENT: a real bug, not the tunnel")
        return orig(*a, **kw)

    monkeypatch.setattr(smo_mod, "_run_chunk", faulty)
    x, y = blobs_small
    with pytest.raises(jax.errors.JaxRuntimeError,
                       match="INVALID_ARGUMENT"):
        solve(x, y, CFG)
    assert calls["n"] == 1  # no retry on deterministic errors


def test_mesh_auto_retry(blobs_small, tmp_path, no_backoff):
    """The mesh path shares the retry wrapper AND the dispatch seam
    (parallel/dist_smo.py chunk loop)."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    full = solve(x, y, CFG.replace(retry_faults=0))
    with faults.install(faults.FaultPlan.parse("dispatch@3")) as plan:
        res = solve_mesh(x, y, CFG, num_devices=8,
                         checkpoint_path=str(tmp_path / "ck.npz"))
    assert plan.fired["dispatch"] == 1
    assert res.converged
    np.testing.assert_allclose(res.alpha, full.alpha, atol=1e-4)


_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {repo!r})
from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.data.synth import make_blobs_binary
from dpsvm_tpu.solver.smo import solve

x, y = make_blobs_binary(n=1200, d=24, seed=11, sep=1.0)
cfg = SVMConfig(c=5.0, gamma=0.05, epsilon=1e-3, max_iter=100_000,
                chunk_iters=32, checkpoint_every=32, retry_faults=0)
slow = "--slow" in sys.argv
def cb(it, bh, bl, st):
    if slow:
        time.sleep(0.02)  # widen the kill window
res = solve(x, y, cfg, callback=cb, checkpoint_path={ck!r},
            resume=True)
np.savez({out!r}, alpha=res.alpha, b=res.b,
         iterations=res.iterations, converged=res.converged)
print("DONE", res.iterations, flush=True)
"""


def test_subprocess_kill_then_resume(tmp_path):
    """Kill a solving process mid-run (SIGKILL — nothing can be flushed);
    relaunching resumes from the periodic checkpoint and lands on the
    same optimum as an uninterrupted solve. (The ooc twin of this test
    — with a BITWISE final-state pin — runs in `make faults_smoke`.)"""
    from dpsvm_tpu.data.synth import make_blobs_binary
    from dpsvm_tpu.utils.hostenv import cleaned_cpu_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ck = str(tmp_path / "child.npz")
    out = str(tmp_path / "result.npz")
    code = _CHILD.format(repo=repo, ck=ck, out=out)
    env = cleaned_cpu_env(1)

    proc = subprocess.Popen([sys.executable, "-c", code, "--slow"], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    deadline = time.time() + 120
    try:
        while time.time() < deadline and not os.path.exists(ck):
            if proc.poll() is not None:
                pytest.fail("child finished before a checkpoint appeared: "
                            + proc.stderr.read().decode()[-500:])
            time.sleep(0.05)
        assert os.path.exists(ck), "no checkpoint within 120s"
        time.sleep(0.3)  # let it advance past the first checkpoint
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert not os.path.exists(out), "child should have died mid-run"

    # Relaunch (fast mode): resumes from the checkpoint, runs to the end.
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-800:]
    z = np.load(out)
    assert bool(z["converged"])

    # Ground truth: the uninterrupted solve on the same problem.
    x, y = make_blobs_binary(n=1200, d=24, seed=11, sep=1.0)
    full = solve(x, y, SVMConfig(c=5.0, gamma=0.05, epsilon=1e-3,
                                 max_iter=100_000))
    assert int(z["iterations"]) == full.iterations
    np.testing.assert_allclose(z["alpha"], full.alpha, atol=1e-4)
    assert float(z["b"]) == pytest.approx(full.b, abs=1e-4)
