"""Synthetic-generator contracts the benchmarks pin against
(bench.py's hard regime, tools/bench_multiclass.py's 10-class data)."""

import numpy as np

from dpsvm_tpu.data.synth import make_mnist_like, make_mnist_multiclass


def test_label_flip_is_seeded_and_proportional():
    x0, y0 = make_mnist_like(n=4000, d=64, seed=7, noise=0.1)
    x1, y1 = make_mnist_like(n=4000, d=64, seed=7, noise=0.1,
                             label_flip=0.10)
    np.testing.assert_array_equal(x0, x1)  # features untouched
    flipped = float(np.mean(y0 != y1))
    assert 0.07 < flipped < 0.13
    _, y2 = make_mnist_like(n=4000, d=64, seed=7, noise=0.1,
                            label_flip=0.10)
    np.testing.assert_array_equal(y1, y2)  # deterministic


def test_flip_zero_is_identity():
    _, y0 = make_mnist_like(n=1000, d=32, seed=3)
    _, y1 = make_mnist_like(n=1000, d=32, seed=3, label_flip=0.0)
    np.testing.assert_array_equal(y0, y1)


def test_multiclass_generator_matches_binary_geometry():
    """make_mnist_multiclass is make_mnist_like BEFORE the even/odd
    collapse: identical features, labels = prototype id mod n_classes
    (so even/odd of the 10-class label reproduces the binary label)."""
    xb, yb = make_mnist_like(n=3000, d=64, seed=7, noise=0.1)
    xm, ym = make_mnist_multiclass(n=3000, d=64, seed=7, noise=0.1)
    np.testing.assert_array_equal(xb, xm)
    assert set(np.unique(ym)) <= set(range(10))
    assert len(np.unique(ym)) == 10
    np.testing.assert_array_equal(np.where(ym % 2 == 0, 1, -1), yb)
