"""Distributed mesh solver tests on the virtual 8-device CPU mesh —
multi-device behavior without a pod, the capability the reference lacks
(SURVEY.md section 4: its multi-rank path needed the real 11-host cluster).
"""

import jax
import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.parallel.mesh import make_data_mesh, pad_rows
from dpsvm_tpu.solver.smo import solve as solve_single

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=16, chunk_iters=256)


def test_pad_rows():
    assert pad_rows(100, 8) % 8 == 0
    assert pad_rows(100, 8) >= 100
    assert pad_rows(64, 8, multiple=8) == 64
    # Reference bug B3 case: n=9, P=8 must NOT produce a negative shard.
    assert pad_rows(9, 8) == 8 * 8


def test_mesh_requires_enough_devices():
    with pytest.raises(ValueError):
        make_data_mesh(num_devices=len(jax.devices()) + 1)


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_mesh_matches_single_chip(blobs_small, n_dev):
    # Deterministic global-index tie-breaks -> the distributed run
    # normally retraces the single-chip trajectory iteration for
    # iteration; XLA's per-shard f-update lowering can differ by a final
    # ulp from the full-array one, which near a selection tie shifts the
    # stopping iteration by one. The guarantee asserted: same solution,
    # trajectory length within 1.
    x, y = blobs_small
    r1 = solve_single(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=n_dev)
    assert rm.converged == r1.converged
    assert abs(rm.iterations - r1.iterations) <= 1
    assert rm.b == pytest.approx(r1.b, abs=1e-4)
    assert rm.n_sv == r1.n_sv
    np.testing.assert_allclose(rm.alpha, r1.alpha, atol=1e-4)


def test_mesh_rerun_bit_identical(blobs_small):
    # Same config + same device count -> bit-identical reruns (functional
    # solver, no RNG, no atomics — unlike the reference's reduction-order-
    # dependent GPU path).
    x, y = blobs_small
    ra = solve_mesh(x, y, CFG, num_devices=8)
    rb = solve_mesh(x, y, CFG, num_devices=8)
    assert ra.iterations == rb.iterations
    np.testing.assert_array_equal(ra.alpha, rb.alpha)
    assert ra.b == rb.b


def test_mesh_uneven_rows(blobs_medium):
    # n = 1200 not divisible by 8: padding + valid masking must keep the
    # converged solution matching the single-chip run (mid-trajectory
    # states drift by accumulated ulps, so compare at convergence).
    x, y = blobs_medium
    r1 = solve_single(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=8)
    assert rm.stats["rows_padded"] > 0
    assert rm.converged and r1.converged
    assert abs(rm.iterations - r1.iterations) <= 0.02 * r1.iterations + 1
    assert rm.b == pytest.approx(r1.b, abs=1e-3)
    np.testing.assert_allclose(rm.alpha, r1.alpha, atol=2e-3)


def test_mesh_cache_independent_of_result(blobs_small):
    x, y = blobs_small
    r_nc = solve_mesh(x, y, CFG.replace(cache_lines=0), num_devices=4)
    r_c = solve_mesh(x, y, CFG.replace(cache_lines=32), num_devices=4)
    assert r_c.iterations == r_nc.iterations
    np.testing.assert_allclose(r_c.alpha, r_nc.alpha, atol=1e-5)
    assert r_c.stats["cache_hit_rate"] > 0.0


def test_train_api_mesh_backend(blobs_small):
    from dpsvm_tpu.train import train
    from dpsvm_tpu.predict import accuracy
    x, y = blobs_small
    model, res = train(x, y, CFG, backend="mesh", num_devices=8)
    assert res.converged
    assert accuracy(model, x, y) > 0.8


def test_mesh_rejects_single_chip_engines(blobs_small):
    x, y = blobs_small
    for engine in ("pallas", "block"):
        with pytest.raises(ValueError, match="single-chip"):
            solve_mesh(x, y, CFG.replace(engine=engine), num_devices=2)


def test_train_auto_backend_keeps_block_on_single_chip(blobs_small):
    """auto must not silently swap the block engine for the mesh per-pair
    engine on a multi-device host."""
    from dpsvm_tpu.train import train

    x, y = blobs_small
    model, res = train(x, y, CFG.replace(engine="block", cache_lines=0),
                       backend="auto")
    assert "outer_rounds" in res.stats  # ran the block engine
    assert "num_devices" not in res.stats  # not the mesh backend
