"""Distributed mesh solver tests on the virtual 8-device CPU mesh —
multi-device behavior without a pod, the capability the reference lacks
(SURVEY.md section 4: its multi-rank path needed the real 11-host cluster).
"""

import jax
import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.parallel.dist_smo import solve_mesh
from dpsvm_tpu.parallel.mesh import make_data_mesh, pad_rows
from dpsvm_tpu.solver.smo import solve as solve_single

CFG = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                cache_lines=16, chunk_iters=256)


def test_pad_rows():
    assert pad_rows(100, 8) % 8 == 0
    assert pad_rows(100, 8) >= 100
    assert pad_rows(64, 8, multiple=8) == 64
    # Reference bug B3 case: n=9, P=8 must NOT produce a negative shard.
    assert pad_rows(9, 8) == 8 * 8


def test_mesh_requires_enough_devices():
    with pytest.raises(ValueError):
        make_data_mesh(num_devices=len(jax.devices()) + 1)


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_mesh_matches_single_chip(blobs_small, n_dev):
    # Deterministic global-index tie-breaks -> the distributed run
    # normally retraces the single-chip trajectory iteration for
    # iteration; XLA's per-shard f-update lowering can differ by a final
    # ulp from the full-array one, which near a selection tie shifts the
    # stopping iteration by one. The guarantee asserted: same solution,
    # trajectory length within 1.
    x, y = blobs_small
    r1 = solve_single(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=n_dev)
    assert rm.converged == r1.converged
    assert abs(rm.iterations - r1.iterations) <= 1
    assert rm.b == pytest.approx(r1.b, abs=1e-4)
    assert rm.n_sv == r1.n_sv
    np.testing.assert_allclose(rm.alpha, r1.alpha, atol=1e-4)


def test_mesh_rerun_bit_identical(blobs_small):
    # Same config + same device count -> bit-identical reruns (functional
    # solver, no RNG, no atomics — unlike the reference's reduction-order-
    # dependent GPU path).
    x, y = blobs_small
    ra = solve_mesh(x, y, CFG, num_devices=8)
    rb = solve_mesh(x, y, CFG, num_devices=8)
    assert ra.iterations == rb.iterations
    np.testing.assert_array_equal(ra.alpha, rb.alpha)
    assert ra.b == rb.b


def test_mesh_uneven_rows(blobs_medium):
    # n = 1200 not divisible by 8: padding + valid masking must keep the
    # converged solution matching the single-chip run (mid-trajectory
    # states drift by accumulated ulps, so compare at convergence).
    x, y = blobs_medium
    r1 = solve_single(x, y, CFG)
    rm = solve_mesh(x, y, CFG, num_devices=8)
    assert rm.stats["rows_padded"] > 0
    assert rm.converged and r1.converged
    assert abs(rm.iterations - r1.iterations) <= 0.02 * r1.iterations + 1
    assert rm.b == pytest.approx(r1.b, abs=1e-3)
    np.testing.assert_allclose(rm.alpha, r1.alpha, atol=2e-3)


def test_mesh_cache_independent_of_result(blobs_small):
    x, y = blobs_small
    r_nc = solve_mesh(x, y, CFG.replace(cache_lines=0), num_devices=4)
    r_c = solve_mesh(x, y, CFG.replace(cache_lines=32), num_devices=4)
    assert r_c.iterations == r_nc.iterations
    np.testing.assert_allclose(r_c.alpha, r_nc.alpha, atol=1e-5)
    assert r_c.stats["cache_hit_rate"] > 0.0


def test_train_api_mesh_backend(blobs_small):
    from dpsvm_tpu.train import train
    from dpsvm_tpu.predict import accuracy
    x, y = blobs_small
    model, res = train(x, y, CFG, backend="mesh", num_devices=8)
    assert res.converged
    assert accuracy(model, x, y) > 0.8


def test_mesh_rejects_single_chip_engines(blobs_small):
    x, y = blobs_small
    with pytest.raises(ValueError, match="single-chip"):
        solve_mesh(x, y, CFG.replace(engine="pallas"), num_devices=2)


def test_train_auto_backend_runs_block_on_mesh(blobs_small):
    """auto + engine='block' on a multi-device host must run the
    DISTRIBUTED block engine, not silently fall back to per-pair."""
    from dpsvm_tpu.train import train

    x, y = blobs_small
    model, res = train(x, y, CFG.replace(engine="block", cache_lines=0),
                       backend="auto")
    assert "outer_rounds" in res.stats  # ran a block engine
    assert res.stats.get("num_devices", 0) > 1  # on the mesh


@pytest.mark.parametrize("n_dev", [2, 8])
def test_mesh_block_matches_single_chip_optimum(blobs_small, n_dev):
    """The distributed block engine must reach the same optimum as the
    single-chip solvers (trajectory parity is not promised for block
    engines; fixed-point parity is)."""
    from dpsvm_tpu.ops.kernels import kernel_matrix, KernelParams

    x, y = blobs_small
    cfg = CFG.replace(engine="block", working_set_size=32, cache_lines=0)
    r_mesh = solve_mesh(x, y, cfg, num_devices=n_dev)
    r_single = solve_single(x, y, CFG.replace(cache_lines=0))
    assert r_mesh.converged
    assert r_mesh.stats["outer_rounds"] > 0
    K = np.asarray(kernel_matrix(x, x, KernelParams("rbf", CFG.gamma)))

    def obj(a):
        ay = a * y
        return a.sum() - 0.5 * ay @ K @ ay

    assert obj(r_mesh.alpha) == pytest.approx(obj(r_single.alpha), rel=1e-4)
    assert r_mesh.b == pytest.approx(r_single.b, abs=5e-3)
    assert abs(np.dot(r_mesh.alpha, y)) < 1e-3


def test_mesh_block_uneven_rows(blobs_medium):
    """Padded rows must stay out of the working set and out of alpha."""
    x, y = blobs_medium
    n = 1111  # not divisible by 8
    x, y = x[:n], y[:n]
    cfg = CFG.replace(engine="block", working_set_size=16, cache_lines=0)
    r = solve_mesh(x, y, cfg, num_devices=8)
    assert r.converged
    assert r.alpha.shape == (n,)
    assert r.stats["rows_padded"] > 0


@pytest.mark.parametrize("engine", ["xla", "block"])
def test_mesh_budget_mode_exact_budget(blobs_medium, engine):
    """budget_mode on the mesh mirrors the single-chip contract: exactly
    max_iter pair updates, honest converged flag at the real epsilon."""
    x, y = blobs_medium
    budget = 1500
    cfg = CFG.replace(engine=engine, cache_lines=0, max_iter=budget,
                      budget_mode=True)
    r = solve_mesh(x, y, cfg, num_devices=8)
    assert r.iterations == budget
    assert r.alpha.min() >= 0.0 and r.alpha.max() <= CFG.c + 1e-6
    # Measured drift ~1e-6; the has_j-bug failure mode drifts by O(C).
    assert abs(float(np.dot(r.alpha, y))) < 1e-4


def test_mesh_active_block_matches_plain_optimum(blobs_medium):
    """Mesh shrinking (make_block_active_chunk_runner) must reach the
    same optimum as the plain mesh block engine and the single-chip
    solver — the cycle structure defers linear f updates, never changes
    the math. Mirrors test_block_engine.py
    test_active_block_matches_plain_optimum."""
    from dpsvm_tpu.solver.smo import solve

    x, y = blobs_medium

    def obj(r):
        return float(np.sum(r.alpha)
                     - 0.5 * np.sum(r.alpha * y * (r.stats["f"] + y)))

    base = CFG.replace(engine="block", working_set_size=32, cache_lines=0)
    rb = solve_mesh(x, y, base, num_devices=8)
    assert rb.converged
    for m, k in ((64, 4), (128, 8), (1200, 2)):
        ra = solve_mesh(x, y, base.replace(active_set_size=m,
                                           reconcile_rounds=k),
                        num_devices=8)
        assert ra.converged
        assert abs(ra.n_sv - rb.n_sv) <= max(2, 0.01 * rb.n_sv)
        assert abs(ra.b - rb.b) < 5e-3
        assert abs(obj(ra) - obj(rb)) <= 1e-3 * abs(obj(rb))
    # Cross-check against the single-chip active engine at one setting.
    rs = solve(x, y, base.replace(active_set_size=128, reconcile_rounds=8))
    ra = solve_mesh(x, y, base.replace(active_set_size=128,
                                       reconcile_rounds=8), num_devices=8)
    assert abs(obj(ra) - obj(rs)) <= 1e-3 * abs(obj(rs))


def test_mesh_active_block_budget_cap_exact(blobs_medium):
    """Mesh shrinking must respect max_iter exactly and report refreshed
    extrema on budget exits."""
    from dpsvm_tpu.ops.select import extrema_np

    x, y = blobs_medium
    r = solve_mesh(x, y, CFG.replace(engine="block", working_set_size=32,
                                     active_set_size=64, max_iter=37),
                   num_devices=8)
    assert r.iterations == 37
    assert not r.converged
    b_hi, b_lo = extrema_np(r.stats["f"], r.alpha, y, CFG.c)
    assert r.b_hi == b_hi and r.b_lo == b_lo


def test_mesh_active_block_device_counts(blobs_medium):
    """Same solution at 1/2/8 devices (solution-level: approx_max_k bin
    order may reorder mid-rank violators across device counts)."""
    x, y = blobs_medium
    cfg = CFG.replace(engine="block", working_set_size=32,
                      active_set_size=128, reconcile_rounds=4)

    def obj(r):
        return float(np.sum(r.alpha)
                     - 0.5 * np.sum(r.alpha * y * (r.stats["f"] + y)))

    rs = [solve_mesh(x, y, cfg, num_devices=p) for p in (1, 2, 8)]
    assert all(r.converged for r in rs)
    for r in rs[1:]:
        assert abs(obj(r) - obj(rs[0])) <= 1e-3 * abs(obj(rs[0]))
        assert abs(r.b - rs[0].b) < 5e-3


def test_mesh_block_solution_parity_midscale():
    """VERDICT r2 weak #6: pin 'single-chip block and mesh block reach
    the same solution' ABOVE toy scale. n=5000 mnist-shaped rows (the
    prior block mesh tests stop at n<=1200); solution-level comparison
    (approx_max_k bin order reorders mid-rank violators across device
    counts, so trajectories are not comparable — fixed points are)."""
    from dpsvm_tpu.data.synth import make_mnist_like

    x, y = make_mnist_like(n=5000, d=96, seed=3, noise=0.1)
    cfg = SVMConfig(c=10.0, gamma=0.125, epsilon=1e-2, max_iter=500_000,
                    engine="block", working_set_size=64, cache_lines=0)
    rs = solve_single(x, y, cfg)
    rm = solve_mesh(x, y, cfg, num_devices=8)
    assert rs.converged and rm.converged

    def obj(r):
        return float(np.sum(r.alpha)
                     - 0.5 * np.sum(r.alpha * y * (r.stats["f"] + y)))

    assert abs(obj(rm) - obj(rs)) <= 1e-3 * abs(obj(rs))
    # b = (b_lo + b_hi)/2 of an eps-approximate optimum: two solver
    # paths can sit anywhere in each other's 2*eps-wide stopping band,
    # so the honest bound is O(eps), not a fixed 5e-3 (measured 0.005).
    assert abs(rm.b - rs.b) < 2 * cfg.epsilon
    assert abs(rm.n_sv - rs.n_sv) <= max(3, 0.02 * rs.n_sv)
    assert abs(float(np.dot(rm.alpha, y))) < 1e-3
