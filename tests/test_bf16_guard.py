"""bf16 small-gamma/extreme-C footgun guard (VERDICT r3 weak item 4).

Measured failure it protects against (BENCH_COVTYPE.md): bfloat16 X
storage at the covtype stress config (c=2048, gamma=0.03125) silently
drops train accuracy 0.97 -> 0.59. The guard warns when
C * p90|K_exact - K_bf16| exceeds the calibrated threshold.
"""

import warnings

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import (BF16_RISK_THRESHOLD,
                                   bf16_rbf_perturbation)
from dpsvm_tpu.solver.smo import solve


def _covtype_shaped(n=4096):
    rng = np.random.default_rng(0)
    return (rng.normal(size=(n, 54)) * 0.3).astype(np.float32), \
        np.where(rng.normal(size=n) > 0, 1, -1).astype(np.int32)


def test_warns_on_covtype_stress_config():
    x, y = _covtype_shaped()
    cfg = SVMConfig(c=2048.0, gamma=0.03125, dtype="bfloat16",
                    max_iter=8, engine="block")
    with pytest.warns(UserWarning, match="bfloat16.*destroy|destroy.*quality"):
        solve(x, y, cfg)


def test_silent_on_mnist_shaped_config():
    from dpsvm_tpu.data.synth import make_mnist_like

    x, y = make_mnist_like(n=3000, d=784, seed=7, noise=0.1)
    cfg = SVMConfig(c=10.0, gamma=0.125, dtype="bfloat16", max_iter=8,
                    engine="block")
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        solve(x, y, cfg)


def test_silent_on_float32():
    x, y = _covtype_shaped(1024)
    cfg = SVMConfig(c=2048.0, gamma=0.03125, dtype="float32", max_iter=8)
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        solve(x, y, cfg)


def test_mesh_warns_too():
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = _covtype_shaped(2048)
    cfg = SVMConfig(c=2048.0, gamma=0.03125, dtype="bfloat16", max_iter=8)
    with pytest.warns(UserWarning, match="bfloat16"):
        solve_mesh(x, y, cfg, num_devices=8)


def test_risk_metric_separates_calibration_cases():
    x, _ = _covtype_shaped()
    risk_fail = 2048.0 * bf16_rbf_perturbation(x, 0.03125)
    assert risk_fail > BF16_RISK_THRESHOLD
    from dpsvm_tpu.data.synth import make_mnist_like
    xm, _ = make_mnist_like(n=3000, d=784, seed=7, noise=0.1)
    risk_pass = 10.0 * bf16_rbf_perturbation(xm, 0.125)
    assert risk_pass < BF16_RISK_THRESHOLD / 10
