"""Deterministic fault-injection harness + graceful degradation
(dpsvm_tpu/testing/faults.py — ISSUE 13).

Every fault-tolerance behavior is proven by a REAL injected fault
through a named seam: the checkpoint tmp+rename discipline under a
truncated write, the non-finite sentinel + safe-config demotion, the
obs fault/retry/demotion event trail and its `cli obs report` column,
and the one-time multi-host retry warning. The solver-loop retry
behaviors live in test_fault_recovery.py (migrated onto the same
seams); the ooc tile-put/resume pins in test_ooc.py; the serving
seams (journal, watchdog, corrupted swap) in test_serving.py.
"""

import os
import warnings

import numpy as np
import pytest

import dpsvm_tpu.solver.smo as smo_mod
from dpsvm_tpu.config import ObsConfig, SVMConfig
from dpsvm_tpu.solver.smo import NonFiniteTrajectory, solve
from dpsvm_tpu.testing import faults


@pytest.fixture
def no_backoff(monkeypatch):
    monkeypatch.setattr(smo_mod, "_RETRY_BACKOFF_S", ())


# --------------------------------------------------------- the harness

def test_plan_parse_and_deterministic_firing():
    plan = faults.FaultPlan.parse("dispatch@3, ooc_tile_put@2x2")
    assert [plan.arrive("dispatch") for _ in range(5)] == \
        [False, False, True, False, False]
    assert [plan.arrive("ooc_tile_put") for _ in range(4)] == \
        [False, True, True, False]
    assert plan.fired == {"dispatch": 1, "ooc_tile_put": 2}
    # Default @1: the first arrival fires.
    p2 = faults.FaultPlan.parse("ckpt_truncate")
    assert p2.arrive("ckpt_truncate") and not p2.arrive("ckpt_truncate")


def test_plan_rejects_typos_and_bad_counts():
    with pytest.raises(ValueError, match="unknown fault seam"):
        faults.FaultPlan.parse("dispatchh")
    with pytest.raises(ValueError, match="1-based"):
        faults.FaultPlan.parse("dispatch@0")
    with pytest.raises(ValueError, match="grammar"):
        faults.FaultPlan.parse("dispatch@@3")


def test_disarmed_is_inert_and_install_scopes():
    assert faults.active_plan() is None
    assert not faults.arrive("dispatch")
    plan = faults.FaultPlan.parse("dispatch@1")
    with faults.install(plan):
        assert faults.active_plan() is plan
        inner = faults.FaultPlan.parse("serve_stall@1")
        with faults.install(inner):
            assert faults.active_plan() is inner
        assert faults.active_plan() is plan
    assert faults.active_plan() is None


def test_env_activation(monkeypatch):
    monkeypatch.setenv("DPSVM_FAULTS", "nonfinite_obs@4")
    plan = faults.active_plan()
    assert plan is not None and plan.specs[0].at == 4
    # Same env string -> the SAME cached plan (arrival counts persist
    # across call sites, which is what makes @N meaningful).
    assert faults.active_plan() is plan
    monkeypatch.setenv("DPSVM_FAULTS", "")
    assert faults.active_plan() is None


def test_corruption_is_seeded_and_effective(tmp_path):
    src = str(tmp_path / "m.npz")
    np.savez_compressed(src, a=np.arange(4096, dtype=np.float32))
    c1 = faults.corrupt_npz_file(src, str(tmp_path / "c1.npz"), seed=3)
    c2 = faults.corrupt_npz_file(src, str(tmp_path / "c2.npz"), seed=3)
    assert open(c1, "rb").read() == open(c2, "rb").read()
    assert open(c1, "rb").read() != open(src, "rb").read()
    with pytest.raises(Exception):
        np.load(c1)["a"].sum()
    flip = faults.corrupt_npz_file(src, str(tmp_path / "f.npz"),
                                   seed=3, mode="flip")
    assert os.path.getsize(flip) == os.path.getsize(src)
    assert open(flip, "rb").read() != open(src, "rb").read()


# -------------------------------------- checkpoint-write preemption

def test_truncated_checkpoint_write_preserves_previous(tmp_path):
    """ckpt_truncate seam: the writer dies mid-save with a half-written
    tmp file — the atomic-rename discipline must leave the PREVIOUS
    checkpoint bit-for-bit intact and no wreckage behind."""
    from dpsvm_tpu.utils.checkpoint import (load_checkpoint_state,
                                            save_checkpoint)

    cfg = SVMConfig(c=1.0, gamma=0.1)
    p = str(tmp_path / "ck.npz")
    alpha = np.arange(6, dtype=np.float32)
    save_checkpoint(p, alpha, -alpha, 100, -0.5, 0.5, cfg)
    before = open(p, "rb").read()
    with faults.install(faults.FaultPlan.parse("ckpt_truncate")) as plan:
        with pytest.raises(faults.FaultInjected, match="preemption"):
            save_checkpoint(p, alpha * 2, -alpha, 200, 0.0, 0.0, cfg)
    assert plan.fired["ckpt_truncate"] == 1
    assert open(p, "rb").read() == before
    assert not [t for t in os.listdir(tmp_path) if t.endswith(".tmp")]
    assert load_checkpoint_state(p).iteration == 100


# ------------------------------------------- non-finite -> demotion

def test_nonfinite_obs_demotes_to_safe_config(blobs_small, no_backoff):
    """The graceful-degradation tentpole: a NaN surfacing in the
    chunk-boundary observation restarts the solve under the SAFE
    configuration (f32 storage here — the bf16 dtype is the dropped
    knob) with a loud warning, stats['demoted_faults'] and the exact
    f32 optimum."""
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, max_iter=100_000,
                    chunk_iters=128, dtype="bfloat16")
    ref = solve(x, y, cfg.replace(dtype="float32"),
                callback=lambda *a: None)
    with faults.install(faults.FaultPlan.parse("nonfinite_obs@2")) as plan, \
            warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = solve(x, y, cfg, callback=lambda *a: None)
    assert plan.fired["nonfinite_obs"] == 1
    assert res.stats["demoted_faults"] == 1
    assert res.stats["demotion"]["dropped"] == ["dtype=bfloat16"]
    assert any("DEMOTING" in str(m.message) for m in w)
    assert res.converged
    np.testing.assert_array_equal(res.alpha, ref.alpha)


def test_nonfinite_on_safe_config_fails_loudly(blobs_small, no_backoff):
    """An already-safe config has nothing to demote: the sentinel must
    PROPAGATE (a real numerics bug), never loop or return a silently
    corrupt 'converged' model."""
    x, y = blobs_small
    with faults.install(faults.FaultPlan.parse("nonfinite_obs@1")):
        with pytest.raises(NonFiniteTrajectory, match="non-finite"):
            solve(x, y, SVMConfig(c=1.0, gamma=0.1, chunk_iters=128),
                  callback=lambda *a: None)


def test_sentinel_sign_convention():
    """ops/select.py masks I_up with +inf (b_hi = min) and I_low with
    -inf (b_lo = max): the LEGITIMATE empty-side values b_hi=+inf /
    b_lo=-inf must pass (they correctly read converged), while the
    impossible signs — inf entries in f winning the min/max — must
    trip."""
    from dpsvm_tpu.solver.smo import check_obs_finite

    inf = float("inf")
    check_obs_finite(-1.0, 1.0, 0, "t")       # ordinary open gap
    check_obs_finite(inf, -inf, 0, "t")       # both sides empty: legit
    check_obs_finite(inf, 0.5, 0, "t")        # empty I_up: legit
    for bad in ((float("nan"), 1.0), (-1.0, float("nan")),
                (-inf, 1.0), (-1.0, inf)):
        with pytest.raises(NonFiniteTrajectory):
            check_obs_finite(bad[0], bad[1], 0, "t")


def test_nonfinite_state_never_checkpointed(tmp_path):
    """The observed extrema lag the fold by one round, so the blow-up
    round would otherwise persist NaN f under finite extrema — the
    writer must SKIP that save (keeping the last good checkpoint as
    the restore point) and resume must refuse a non-finite file."""
    from dpsvm_tpu.utils.checkpoint import (PeriodicCheckpointer,
                                            load_checkpoint_state,
                                            resume_state,
                                            save_checkpoint)

    cfg = SVMConfig(c=1.0, gamma=0.1, checkpoint_every=1)
    p = str(tmp_path / "ck.npz")
    ck = PeriodicCheckpointer(p, cfg)
    alpha = np.ones(4, np.float32)
    assert ck.save(10, alpha, -alpha, -0.5, 0.5)
    bad_f = np.array([0.0, np.nan, 0.0, 0.0], np.float32)
    with pytest.warns(UserWarning, match="SKIPPED"):
        assert not ck.save(20, alpha, bad_f, -0.5, 0.5)
    assert load_checkpoint_state(p).iteration == 10  # last good kept
    # A non-finite file (written by some other tool) refuses resume —
    # via the retention fallback's loud per-generation warning, since
    # a corrupt newest generation first tries the (absent) older ones.
    save_checkpoint(str(tmp_path / "bad.npz"), alpha, bad_f, 20,
                    -0.5, 0.5, cfg)
    with pytest.warns(UserWarning, match="UNUSABLE"), \
            pytest.raises(ValueError, match="non-finite"):
        resume_state(str(tmp_path / "bad.npz"), cfg, 4)


def test_mesh_nonfinite_obs_demotes(blobs_small, no_backoff):
    """The mesh loop carries the same sentinel + demotion backstop as
    the single-chip driver (a NaN gap must never read 'converged' on
    any backend)."""
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, chunk_iters=128,
                    dtype="bfloat16")
    ref = solve_mesh(x, y, cfg.replace(dtype="float32"), num_devices=2,
                     callback=lambda *a: None)
    with faults.install(faults.FaultPlan.parse("nonfinite_obs@2")) as plan, \
            warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = solve_mesh(x, y, cfg, num_devices=2,
                         callback=lambda *a: None)
    assert plan.fired["nonfinite_obs"] == 1
    assert res.stats["demoted_faults"] == 1
    assert any("DEMOTING" in str(m.message) for m in w)
    assert res.converged
    np.testing.assert_array_equal(res.alpha, ref.alpha)


def test_demote_to_safe_knob_inventory():
    from dpsvm_tpu.solver.block import demote_to_safe

    cfg, dropped = demote_to_safe(SVMConfig(
        engine="block", dtype="bfloat16", fused_fold=True))
    assert cfg.dtype == "float32" and cfg.fused_fold is False
    # auto (None) gates are pinned off but not reported as drops
    assert cfg.fused_round is False and cfg.pipeline_rounds is False
    assert dropped == ("dtype=bfloat16", "fused_fold")
    safe, none_dropped = demote_to_safe(SVMConfig(engine="block"))
    assert safe is None and none_dropped == ()


# -------------------------------------------------- obs event trail

def test_fault_retry_events_and_report_column(blobs_small, no_backoff,
                                              tmp_path, monkeypatch):
    """A retried fault leaves fault/retry event records in the (new
    attempt's) run log, and `cli obs report` renders them in the
    faults column."""
    from dpsvm_tpu.obs.analyze import (load_runs, render_report,
                                       summarize_run)
    from dpsvm_tpu.obs.runlog import read_runlog, records_for

    monkeypatch.setenv("DPSVM_OBS_DIR", str(tmp_path))
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, max_iter=100_000, chunk_iters=64,
                    retry_faults=2, obs=ObsConfig(
                        enabled=True, runlog_dir=str(tmp_path)))
    with faults.install(faults.FaultPlan.parse("dispatch@2")) as plan:
        res = solve(x, y, cfg, callback=lambda *a: None)
    assert plan.fired["dispatch"] == 1
    assert res.converged
    recs = read_runlog(res.stats["obs_runlog"])
    events = records_for(recs, res.stats["obs_run_id"], "event")
    names = [e["name"] for e in events]
    assert "fault" in names and "retry" in names
    fault_ev = next(e for e in events if e["name"] == "fault")
    assert "injected fault" in fault_ev["error"]
    summaries = [summarize_run(r)
                 for r in load_runs([res.stats["obs_runlog"]])
                 if r.run_id == res.stats["obs_run_id"]]
    assert summaries[0]["fault_events"]["fault"] == 1
    assert summaries[0]["fault_events"]["retry"] == 1
    table = render_report(summaries)
    assert "faults" in table.splitlines()[0]
    assert "f=1 r=1" in table


# ------------------------------------- multi-host retry-drop warning

def test_multihost_retry_drop_warns_once(blobs_small, monkeypatch):
    """dist_smo satellite: forcing retry_faults=0 on a multi-process
    pod must WARN (naming the relaunch-with---resume procedure), and
    only once per process — not once per submodel solve."""
    import jax

    import dpsvm_tpu.parallel.dist_smo as dist_mod
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(dist_mod, "_WARNED_MULTIHOST_RETRY", False)
    x, y = blobs_small
    cfg = SVMConfig(c=1.0, gamma=0.1, epsilon=1e-3, retry_faults=2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = solve_mesh(x, y, cfg, num_devices=2)
        solve_mesh(x, y, cfg, num_devices=2)  # second call: no repeat
    assert res.converged
    msgs = [str(m.message) for m in w
            if "retry_faults" in str(m.message)]
    assert len(msgs) == 1, msgs
    assert "--resume" in msgs[0] and "RELAUNCH" in msgs[0]
    # retry_faults=0 (or an explicit 0) never warns.
    monkeypatch.setattr(dist_mod, "_WARNED_MULTIHOST_RETRY", False)
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        solve_mesh(x, y, cfg.replace(retry_faults=0), num_devices=2)
    assert not [m for m in w2 if "retry_faults" in str(m.message)]
