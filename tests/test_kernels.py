"""Kernel math unit tests (vs sklearn.metrics.pairwise + hand values)."""

import numpy as np
import pytest

from dpsvm_tpu.ops.kernels import (
    KernelParams,
    kernel_from_dots,
    kernel_matrix,
    kernel_rows,
    row_dots,
    squared_norms,
)


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(40, 7)).astype(np.float32)
    b = rng.normal(size=(23, 7)).astype(np.float32)
    return a, b


def _sk(kind, a, b, gamma, degree, coef0):
    from sklearn.metrics import pairwise
    if kind == "rbf":
        return pairwise.rbf_kernel(a, b, gamma=gamma)
    if kind == "linear":
        return pairwise.linear_kernel(a, b)
    if kind == "poly":
        return pairwise.polynomial_kernel(a, b, degree=degree, gamma=gamma, coef0=coef0)
    if kind == "sigmoid":
        return pairwise.sigmoid_kernel(a, b, gamma=gamma, coef0=coef0)
    raise ValueError(kind)


@pytest.mark.parametrize("kind", ["rbf", "linear", "poly", "sigmoid"])
def test_kernel_matrix_matches_sklearn(xy, kind):
    a, b = xy
    p = KernelParams(kind=kind, gamma=0.3, degree=3, coef0=0.5)
    got = np.asarray(kernel_matrix(a, b, p))
    want = _sk(kind, a, b, 0.3, 3, 0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_squared_norms(xy):
    a, _ = xy
    np.testing.assert_allclose(
        np.asarray(squared_norms(a)), (a * a).sum(1), rtol=1e-5)


def test_row_dots_matches_matmul(xy):
    a, _ = xy
    q = a[[3, 17]]
    np.testing.assert_allclose(np.asarray(row_dots(a, q)), q @ a.T, rtol=1e-5)
    # single row
    np.testing.assert_allclose(np.asarray(row_dots(a, a[5])), a[5] @ a.T, rtol=1e-5)


@pytest.mark.parametrize("kind", ["rbf", "linear", "poly", "sigmoid"])
def test_kernel_rows_consistent_with_matrix(xy, kind):
    a, _ = xy
    p = KernelParams(kind=kind, gamma=0.7, degree=2, coef0=1.0)
    x_sq = np.asarray(squared_norms(a))
    q = a[[0, 9]]
    rows = np.asarray(kernel_rows(a, x_sq, q, x_sq[[0, 9]], p))
    full = np.asarray(kernel_matrix(q, a, p))
    np.testing.assert_allclose(rows, full, rtol=2e-5, atol=2e-5)


def test_rbf_diagonal_is_one(xy):
    a, _ = xy
    p = KernelParams(kind="rbf", gamma=0.5)
    k = np.asarray(kernel_matrix(a, a, p))
    np.testing.assert_allclose(np.diag(k), 1.0, atol=1e-6)


def test_kernel_from_dots_rbf_hand_value():
    # Two 1-d points u=0, v=2, gamma=0.25 -> exp(-0.25*4) = exp(-1).
    x = np.array([[0.0], [2.0]], np.float32)
    x_sq = (x * x).sum(1)
    dots = x @ x[1]
    k = np.asarray(kernel_from_dots(dots, x_sq, x_sq[1], KernelParams("rbf", 0.25)))
    np.testing.assert_allclose(k, [np.exp(-1.0), 1.0], rtol=1e-6)
