"""Resident-Gram acceleration (config.gram_resident) + the hybrid
block->per-pair tail switch in the reconstruction legs.

Both exist for the extreme-C tail regime (VERDICT round-4 item 1): the
per-pair engine is the only one measured to close extreme-C gaps, and on
a resident Gram its per-iteration kernel rows are gathers instead of
matvecs. On CPU the auto gate stays OFF (no memory budget is reported),
so these tests force the path and assert it solves the SAME problem the
feature path does.
"""

import numpy as np
import pytest

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.smo import _GRAM_MEMO, _resolve_gram, solve


def _blobs(n=600, d=8, seed=5, sep=1.0):
    from dpsvm_tpu.data.synth import make_blobs_binary

    return make_blobs_binary(n=n, d=d, seed=seed, sep=sep)


BASE = SVMConfig(c=10.0, gamma=0.1, epsilon=1e-3, max_iter=200_000)


@pytest.mark.parametrize("selection", ["mvp", "second_order"])
@pytest.mark.parametrize("kernel", ["rbf", "linear", "poly"])
def test_gram_matches_feature_path(selection, kernel):
    """Forced resident-Gram solves reach the same model as the feature
    path: the Gram rows hold exactly the kernel values the matvec path
    computes, so only float association can differ."""
    x, y = _blobs()
    cfg = BASE.replace(selection=selection, kernel=kernel)
    ref = solve(x, y, cfg)
    got = solve(x, y, cfg.replace(gram_resident=True))
    assert got.converged and ref.converged
    assert abs(got.b - ref.b) < 5e-3
    # Alpha agreement is loose by design: the optimum can be a face and
    # the exact vertex is solver-path-dependent (PARITY.md merged-SV
    # rationale); the decision function below is the real equivalence.
    np.testing.assert_allclose(got.alpha, ref.alpha, atol=0.1)
    # Same decision signs (the model-level equivalence that matters).
    dec_r = ref.stats["f"] + y
    dec_g = got.stats["f"] + y
    assert np.mean(np.sign(dec_r - ref.b) == np.sign(dec_g - got.b)) > 0.995


def test_gram_block_engine_forced():
    """gram_resident=True also runs under the block engine (the fold
    becomes a row gather of the resident Gram)."""
    x, y = _blobs()
    cfg = BASE.replace(engine="block", working_set_size=32)
    ref = solve(x, y, cfg)
    got = solve(x, y, cfg.replace(gram_resident=True))
    assert got.converged
    assert abs(got.b - ref.b) < 5e-3
    np.testing.assert_allclose(got.alpha, ref.alpha, atol=5e-2)


def test_gram_with_compensated_and_legs():
    """The extreme-C accuracy stack (compensated + reconstruct legs)
    composes with the resident Gram: certification runs on the original
    FEATURES (host f64), the device solve on the Gram."""
    x, y = _blobs(sep=0.6)
    cfg = BASE.replace(c=2000.0, compensated=True, reconstruct_every=50_000,
                       gram_resident=True)
    res = solve(x, y, cfg)
    assert res.converged
    assert res.stats["true_gap"] <= 2 * cfg.epsilon


def test_auto_gate_off_on_cpu():
    """CPU backends report no memory budget -> auto stays off; tiny n
    stays off regardless."""
    import jax

    from dpsvm_tpu.ops.kernels import KernelParams

    dev = jax.devices()[0]
    kp = KernelParams("rbf", 0.1)
    assert _resolve_gram(BASE, kp, 50_000, dev) is False
    assert _resolve_gram(BASE.replace(gram_resident=True), kp, 100, dev)
    assert not _resolve_gram(BASE.replace(gram_resident=False), kp, 10**9, dev)
    # precomputed kernels / pallas engine never enter gram mode.
    assert not _resolve_gram(BASE, KernelParams("precomputed"), 10**9, dev)


def test_gram_memo_reuses_across_legs():
    """Reconstruction legs pass the same host array; the second leg must
    not rebuild the Gram (memo keyed on object identity + config)."""
    from dpsvm_tpu.ops import kernels as K

    x, y = _blobs(n=300)
    calls = {"n": 0}
    orig = K.resident_gram

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    _GRAM_MEMO.clear()
    K.resident_gram = counting
    try:
        cfg = BASE.replace(gram_resident=True, compensated=True,
                           reconstruct_every=20_000)
        res = solve(np.asarray(x, np.float32), y, cfg)
        assert res.converged
        assert res.stats["legs"] >= 1
        assert calls["n"] == 1
    finally:
        K.resident_gram = orig
        _GRAM_MEMO.clear()


def test_config_validation():
    with pytest.raises(ValueError, match="pallas"):
        SVMConfig(engine="pallas", gram_resident=True)
    with pytest.raises(ValueError, match="precomputed"):
        SVMConfig(kernel="precomputed", gram_resident=True, cache_lines=0)
    with pytest.raises(ValueError, match="active-set"):
        SVMConfig(engine="block", active_set_size=64, gram_resident=True)


def test_gram_memo_evicts_when_host_array_dies():
    """The multi-GB device Gram must not outlive its host array (it
    would pin HBM against unrelated later work): the weakref finalizer
    drops the memo entry at collection."""
    import gc

    _GRAM_MEMO.clear()
    x, y = _blobs(n=300)
    x = np.asarray(x, np.float32)
    res = solve(x, y, BASE.replace(gram_resident=True))
    assert res.converged
    assert len(_GRAM_MEMO) == 1
    del x
    gc.collect()
    assert len(_GRAM_MEMO) == 0


def test_gram_memo_rebuilds_on_inplace_mutation():
    """Staleness regression (ADVICE round-5, medium): the memo keys on
    object identity, but `x *= s` keeps identity while changing content
    — the content fingerprint must force a rebuild so the solver never
    trains on a stale device Gram."""
    from dpsvm_tpu.ops import kernels as K

    _GRAM_MEMO.clear()
    x, y = _blobs(n=300)
    x = np.asarray(x, np.float32)
    calls = {"n": 0}
    orig = K.resident_gram

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    K.resident_gram = counting
    try:
        r1 = solve(x, y, BASE.replace(gram_resident=True))
        x *= 0.5  # in-place: same object, different kernel values
        r2 = solve(x, y, BASE.replace(gram_resident=True))
        assert calls["n"] == 2  # second solve rebuilt the Gram
        fresh = solve(x.copy(), y, BASE.replace(gram_resident=True))
        assert abs(r2.b - fresh.b) < 5e-4
        assert r2.iterations == fresh.iterations
        assert r1.iterations != r2.iterations or abs(r1.b - r2.b) > 0
    finally:
        K.resident_gram = orig
        _GRAM_MEMO.clear()


def test_xdev_memo_rebuilds_on_inplace_mutation():
    """Same staleness guard for the (x_dev, x_sq) memo the feature-path
    solves share (OvR multiclass, reconstruction legs)."""
    x, y = _blobs(n=300)
    x = np.asarray(x, np.float32)
    r1 = solve(x, y, BASE)
    x *= 0.5
    r2 = solve(x, y, BASE)
    fresh = solve(x.copy(), y, BASE)
    assert r2.iterations == fresh.iterations
    assert abs(r2.b - fresh.b) < 5e-4
    np.testing.assert_allclose(r2.alpha, fresh.alpha, rtol=1e-5,
                               atol=1e-6)
    # and the mutation genuinely changed the problem
    assert r1.iterations != r2.iterations or abs(r1.b - r2.b) > 0


def test_gram_memo_finalizer_does_not_evict_live_replacement():
    """Finalizer lifetime regression (ADVICE round-5, low): replace the
    memo entry for the same key with a NEW host array, then let the OLD
    array die — its finalizer must NOT evict the live entry (that would
    silently rebuild a multi-GB Gram on the next leg)."""
    import gc

    from dpsvm_tpu.ops import kernels as K

    _GRAM_MEMO.clear()
    x1, y = _blobs(n=300)
    x1 = np.asarray(x1, np.float32)
    x2 = (x1 * 0.5).astype(np.float32)  # same shape/dtype => same key
    solve(x1, y, BASE.replace(gram_resident=True))
    solve(x2, y, BASE.replace(gram_resident=True))  # replaces the entry
    assert len(_GRAM_MEMO) == 1
    del x1
    gc.collect()
    assert len(_GRAM_MEMO) == 1  # live x2 entry survived x1's finalizer
    calls = {"n": 0}
    orig = K.resident_gram

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    K.resident_gram = counting
    try:
        solve(x2, y, BASE.replace(gram_resident=True))
        assert calls["n"] == 0  # memo HIT: no rebuild
    finally:
        K.resident_gram = orig
        _GRAM_MEMO.clear()


def test_gram_memo_releases_evicted_payload_without_cyclic_gc():
    """An evicted entry's multi-GB payload must free by REFCOUNT the
    moment memo.clear() drops it — a finalizer closure holding the entry
    would form a cycle that keeps the old device Gram alive until the
    cyclic GC runs (never, under gc.disable())."""
    import gc
    import weakref

    from dpsvm_tpu.solver.smo import _memo_insert

    class Payload:  # weakref-able stand-in for the device Gram
        pass

    memo: dict = {}
    host1, host2 = np.zeros(4), np.zeros(4)
    p1, p2 = Payload(), Payload()
    dead = weakref.ref(p1)
    gc.disable()
    try:
        _memo_insert(memo, "k", host1, (p1,))
        del p1
        _memo_insert(memo, "k", host2, (p2,))  # evicts entry 1
        assert dead() is None  # released by refcount, no gc.collect()
    finally:
        gc.enable()
    # and the live entry still works + survives host1's death
    del host1
    gc.collect()
    assert len(memo) == 1 and memo["k"][2] is p2


def test_hybrid_switches_to_per_pair_on_block_stall():
    """solve_in_legs hands the tail to the per-pair engine when block
    legs stop cutting the true gap. Simulated stall: a base_solve that
    returns the start state untouched while cfg.engine == 'block' and
    delegates to the real solver once switched."""
    from dpsvm_tpu.solver.reconstruct import solve_in_legs

    x, y = _blobs(sep=0.8)
    calls = {"block": 0, "xla": 0}

    def base(xx, yy, cfg, callback=None, alpha_init=None, f_init=None,
             **kw):
        if cfg.engine == "block":
            calls["block"] += 1
            a0 = (np.zeros(len(yy), np.float32) if alpha_init is None
                  else np.asarray(alpha_init, np.float32))
            f0 = (np.asarray(-yy, np.float32) if f_init is None
                  else np.asarray(f_init, np.float32))
            return SolveResult(alpha=a0, b=0.0, b_hi=-1.0, b_lo=1.0,
                               iterations=cfg.max_iter, converged=False,
                               train_seconds=0.0, stats={"f": f0})
        calls["xla"] += 1
        return solve(xx, yy, cfg, callback=callback,
                     alpha_init=alpha_init, f_init=f_init, **kw)

    cfg = BASE.replace(c=500.0, engine="block", compensated=True,
                       reconstruct_every=100_000, max_iter=2_000_000)
    res = solve_in_legs(base, x, y, cfg)
    assert res.converged
    assert calls["xla"] >= 1
    # The stall is only detectable from the SECOND zero-progress block
    # leg (the first has no finite previous gap to compare against).
    assert calls["block"] == 2
    assert res.stats["hybrid_switch_pairs"] is not None


def test_block_without_stall_keeps_block_engine():
    """A block run whose legs converge healthily never switches."""
    x, y = _blobs()
    cfg = BASE.replace(engine="block", working_set_size=32,
                       compensated=True, reconstruct_every=500_000)
    res = solve(x, y, cfg)
    assert res.converged
    assert res.stats["hybrid_switch_pairs"] is None


def test_block_tail_doomed_heuristic_regimes():
    """The upfront regime gate (solver/reconstruct.py block_tail_doomed,
    VERDICT round-5 item 6 heuristic half) against the measured regimes
    its threshold was validated on. gram_budget_bytes is pinned to the
    v5e budget (0.7 * 16 GiB) so the decision is about C*n/d and the
    Gram fit, not this host's (unreported) memory."""
    from dpsvm_tpu.solver.reconstruct import block_tail_doomed

    v5e = int(0.7 * 16 * (1 << 30))

    def gate(c, n, d):
        return block_tail_doomed(SVMConfig(c=c), n, d,
                                 gram_budget_bytes=v5e)

    # covtype stress (block legs measured to CYCLE; PARITY.md): per-pair.
    assert gate(2048.0, 50_000, 54)
    # covtype-shaped moderate C (block healthy, BENCH_COVTYPE_SWEEP).
    assert not gate(10.0, 500_000, 54)
    # well-separated blobs (block healthy, BENCH_COVTYPE_SWEEP round-5).
    assert not gate(10.0, 500_000, 24)
    # adult-shaped (block healthy, PARITY.md).
    assert not gate(100.0, 32_561, 123)
    # full-covtype stress: C*n/d is far past the threshold but the
    # (n, n) Gram cannot fit — keep block legs + the reactive detector.
    assert not gate(2048.0, 500_000, 54)
    # Small problems never gate (resident-Gram auto floor).
    assert not gate(2048.0, 4_000, 10)


def test_hybrid_upfront_gate_starts_per_pair(monkeypatch):
    """When the regime gate fires, solve_in_legs never burns a block
    leg: every leg runs the per-pair engine and the stats record the
    upfront switch."""
    from dpsvm_tpu.solver import reconstruct as rec

    x, y = _blobs(sep=0.8)
    calls = {"block": 0, "xla": 0}

    def base(xx, yy, cfg, callback=None, alpha_init=None, f_init=None,
             **kw):
        calls[cfg.engine] += 1
        return solve(xx, yy, cfg, callback=callback,
                     alpha_init=alpha_init, f_init=f_init, **kw)

    monkeypatch.setattr(rec, "block_tail_doomed",
                        lambda *a, **k: True)
    cfg = BASE.replace(c=500.0, engine="block", compensated=True,
                       reconstruct_every=100_000, max_iter=2_000_000)
    res = rec.solve_in_legs(base, x, y, cfg)
    assert res.converged
    assert calls["block"] == 0 and calls["xla"] >= 1
    assert res.stats["hybrid_upfront"] is True
    assert res.stats["hybrid_switch_pairs"] == 0


def test_hybrid_upfront_gate_respects_heuristic(monkeypatch):
    """Below the C*n/d threshold the legs start on the block engine as
    before (the reactive detector remains the safety net)."""
    x, y = _blobs()
    cfg = BASE.replace(engine="block", working_set_size=32,
                       compensated=True, reconstruct_every=500_000)
    res = solve(x, y, cfg)
    assert res.converged
    assert res.stats["hybrid_upfront"] is False
