"""Fused Pallas update+select kernel vs a plain-jnp reference
(interpret mode on CPU; the same kernel compiles natively on TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots
from dpsvm_tpu.ops.pallas_fused import LANES, fused_update_select
from dpsvm_tpu.ops.select import select_working_set


def _reference(f, alpha, y, valid, d_hi, d_lo, x_sq, scalars, kp, c):
    k_hi = np.asarray(kernel_from_dots(jnp.asarray(d_hi), jnp.asarray(x_sq),
                                       jnp.float32(scalars[2]), kp))
    k_lo = np.asarray(kernel_from_dots(jnp.asarray(d_lo), jnp.asarray(x_sq),
                                       jnp.float32(scalars[3]), kp))
    f_new = f + scalars[0] * k_hi + scalars[1] * k_lo
    i_hi, b_hi, i_lo, b_lo = select_working_set(
        jnp.asarray(f_new), jnp.asarray(alpha), jnp.asarray(y), c,
        jnp.asarray(valid))
    return f_new, float(b_hi), int(i_hi), float(b_lo), int(i_lo)


@pytest.mark.parametrize("kind", ["rbf", "linear", "poly"])
@pytest.mark.parametrize("n_valid", [700, 1024])
def test_fused_matches_reference(kind, n_valid):
    rng = np.random.default_rng(3)
    rows = 8 * 2  # 2 blocks of 8 rows -> n_pad = 2048
    n_pad = rows * LANES
    block_rows = 8
    c = 1.5
    kp = KernelParams(kind=kind, gamma=0.3, degree=2, coef0=0.5)

    f = rng.normal(size=n_pad).astype(np.float32)
    alpha = rng.choice([0.0, c, 0.6], size=n_pad).astype(np.float32)
    y = rng.choice([-1.0, 1.0], size=n_pad).astype(np.float32)
    valid = np.zeros(n_pad, np.float32)
    valid[:n_valid] = 1
    d_hi = rng.normal(size=n_pad).astype(np.float32)
    d_lo = rng.normal(size=n_pad).astype(np.float32)
    x_sq = np.abs(rng.normal(size=n_pad)).astype(np.float32)
    scalars = np.array([0.37, -0.21, 1.3, 0.8], np.float32)

    shp = (rows, LANES)
    got_f, b_hi, i_hi, b_lo, i_lo = fused_update_select(
        jnp.asarray(f.reshape(shp)), jnp.asarray(alpha.reshape(shp)),
        jnp.asarray(y.reshape(shp)), jnp.asarray(valid.reshape(shp)),
        jnp.asarray(d_hi.reshape(shp)), jnp.asarray(d_lo.reshape(shp)),
        jnp.asarray(x_sq.reshape(shp)), jnp.asarray(scalars),
        kp, c, block_rows=block_rows, interpret=True)

    want_f, wb_hi, wi_hi, wb_lo, wi_lo = _reference(
        f, alpha, y, valid.astype(bool), d_hi, d_lo, x_sq, scalars, kp, c)

    np.testing.assert_allclose(np.asarray(got_f).ravel(), want_f,
                               rtol=1e-5, atol=1e-5)
    assert int(i_hi) == wi_hi
    assert int(i_lo) == wi_lo
    assert float(b_hi) == pytest.approx(wb_hi, rel=1e-5)
    assert float(b_lo) == pytest.approx(wb_lo, rel=1e-5)


def test_fused_tie_break_lowest_index():
    # Equal extrema in different blocks: the lower flat index must win,
    # matching jnp.argmin/argmax first-occurrence semantics.
    rows, block_rows = 16, 8
    n_pad = rows * LANES
    f = np.zeros(n_pad, np.float32)
    alpha = np.full(n_pad, 0.5, np.float32)
    y = np.ones(n_pad, np.float32)
    valid = np.ones(n_pad, np.float32)
    zeros = np.zeros(n_pad, np.float32)
    scalars = np.zeros(4, np.float32)
    shp = (rows, LANES)
    kp = KernelParams("linear")
    _, b_hi, i_hi, b_lo, i_lo = fused_update_select(
        jnp.asarray(f.reshape(shp)), jnp.asarray(alpha.reshape(shp)),
        jnp.asarray(y.reshape(shp)), jnp.asarray(valid.reshape(shp)),
        jnp.asarray(zeros.reshape(shp)), jnp.asarray(zeros.reshape(shp)),
        jnp.asarray(zeros.reshape(shp)), jnp.asarray(scalars),
        kp, 1.0, block_rows=block_rows, interpret=True)
    assert int(i_hi) == 0
    assert int(i_lo) == 0
