"""sklearn estimator-contract conformance (VERDICT round-4 item 8).

``sklearn.utils.estimator_checks.parametrize_with_checks`` runs the
library's own battery (get_params/set_params/clone round trips, fit
idempotency, input validation, attribute contracts, ...) over every
facade estimator. The documented skip list below marks contracts the
facade deliberately does not implement; everything else must pass — the
facade is a first-class surface (README sells GridSearchCV/Pipeline
composition).

Marked slow: the battery refits each estimator dozens of times at
varied tiny shapes, which costs minutes of XLA compiles on the CPU
platform (the quick `make test` loop deselects it; `make test_all`
runs it).
"""

import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn.utils.estimator_checks import parametrize_with_checks

from dpsvm_tpu.estimators import SVC, SVR, NuSVC, NuSVR, OneClassSVM

# Contracts the facade deliberately does not implement, with reasons.
# Keyed by substring of the check name; applied to every estimator.
_SKIPS = {
    "check_sample_weights": "fit() has no sample_weight (the solver's "
        "per-class weights cover LibSVM -w; per-row weights are not in "
        "the reference's problem class)",
    "check_estimator_sparse": "dense-only: the TPU solver's kernel rows "
        "are MXU matmuls over dense X; callers densify first",
}


def _expected_failures(estimator):
    return {name: reason for name, reason in _SKIPS.items()}


# Small max_iter keeps each refit cheap; the checks assert contracts,
# not solution quality. tol is left at default (checks never inspect
# convergence).
ESTIMATORS = [
    SVC(max_iter=20_000),
    NuSVC(max_iter=20_000),
    SVR(max_iter=20_000),
    NuSVR(max_iter=20_000),
    OneClassSVM(max_iter=20_000),
]


@pytest.mark.slow
@parametrize_with_checks(ESTIMATORS,
                         expected_failed_checks=_expected_failures)
def test_sklearn_estimator_contract(estimator, check):
    check(estimator)
