"""sklearn estimator-contract conformance (VERDICT round-4 item 8).

``sklearn.utils.estimator_checks.parametrize_with_checks`` runs the
library's own battery (get_params/set_params/clone round trips, fit
idempotency, input validation, attribute contracts, ...) over every
facade estimator. The documented skip list below marks contracts the
facade deliberately does not implement; everything else must pass — the
facade is a first-class surface (README sells GridSearchCV/Pipeline
composition).

Marked slow: the battery refits each estimator dozens of times at
varied tiny shapes, which costs minutes of XLA compiles on the CPU
platform (the quick `make test` loop deselects it; `make test_all`
runs it).
"""

import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn.utils.estimator_checks import parametrize_with_checks

from dpsvm_tpu.estimators import SVC, SVR, NuSVC, NuSVR, OneClassSVM

# Contracts the facade deliberately does not implement, with reasons
# (marked xfail, non-strict). Everything else in the battery passes:
# the sparse/NaN/1-D/complex/empty rejections, n_features_in_,
# NotFittedError ordering, OvO-multiclass NuSVC, the OneClassSVM
# outlier API and predict_proba's available_if gating were all
# implemented against this battery (round 5).
#
# Round 6 (VERDICT item 8): the three f32-invariance entries (NuSVC
# subset invariance; OneClassSVM subset + sample-order invariance) had
# been xpassing — the decision-function accumulation now lands inside
# the battery's atol on this platform — so they are PROMOTED to strict
# ordinary passes: a future regrouping regression fails loudly instead
# of flipping an unnoticed xfail marker. Only the genuinely-unimplemented
# contract remains expected-to-fail.
_EXPECTED = {
    "SVC": {
        "check_class_weight_classifiers":
            "per-class C for >2 classes needs per-row box bounds (the "
            "solver carries the binary +-1 weight pair, LibSVM -w "
            "parity); binary class_weight IS honored",
    },
}


def _expected_failures(estimator):
    return dict(_EXPECTED.get(type(estimator).__name__, {}))


# Small max_iter keeps each refit cheap; the checks assert contracts,
# not solution quality. tol is left at default (checks never inspect
# convergence).
ESTIMATORS = [
    SVC(max_iter=20_000),
    NuSVC(max_iter=20_000),
    SVR(max_iter=20_000),
    NuSVR(max_iter=20_000),
    OneClassSVM(max_iter=20_000),
]


@pytest.mark.slow
@parametrize_with_checks(ESTIMATORS,
                         expected_failed_checks=_expected_failures)
def test_sklearn_estimator_contract(estimator, check):
    check(estimator)
