// seqsmo — native sequential modified-SMO trainer + predictor.
//
// Native-runtime equivalent of the reference's CPU binaries: the
// sequential trainer seq.cpp (main loop seq.cpp:195-260, I-set selection
// seq.cpp:469-553, f update seq.cpp:378-386) and the CPU tester
// seq_test.cpp (decision sum, seq_test.cpp:187-210). The reference uses
// CBLAS saxpy/sdot per kernel evaluation; here rows are evaluated with
// plain tight loops that g++ -O3 auto-vectorizes, and the known reference
// bugs are fixed: eta is clamped (B2), b participates in prediction with
// one convention, f(x) = sum_j coef_j K(x_j, x) - b (B5/B6).
//
// This is the host-side correctness oracle and small-problem fast path;
// the TPU engines (solver/smo.py, parallel/dist_smo.py) are the scale
// path. C ABI, consumed via ctypes (dpsvm_tpu/utils/native.py).

#include <cmath>
#include <cstring>
#include <vector>

namespace {

// Kernel kinds, matching dpsvm_tpu.ops.kernels.KernelParams.kind order.
enum Kind { LINEAR = 0, RBF = 1, POLY = 2, SIGMOID = 3 };

inline float dot(const float* a, const float* b, long d) {
    float s = 0.0f;
    for (long j = 0; j < d; ++j) s += a[j] * b[j];
    return s;
}

inline float kernel_value(float dp, float qa_sq, float qb_sq, int kind,
                          float gamma, int degree, float coef0) {
    switch (kind) {
        case LINEAR: return dp;
        case RBF: {
            float sq = qa_sq + qb_sq - 2.0f * dp;
            if (sq < 0.0f) sq = 0.0f;
            return std::exp(-gamma * sq);
        }
        case POLY: return std::pow(gamma * dp + coef0, (float)degree);
        default: return std::tanh(gamma * dp + coef0);
    }
}

// K(x_i, .) against all n rows into out[n].
void kernel_row(const float* x, const float* x_sq, long n, long d, long i,
                int kind, float gamma, int degree, float coef0, float* out) {
    const float* xi = x + i * d;
    const float xi_sq = x_sq[i];
    for (long r = 0; r < n; ++r) {
        float dp = dot(x + r * d, xi, d);
        out[r] = kernel_value(dp, x_sq[r], xi_sq, kind, gamma, degree, coef0);
    }
}

}  // namespace

extern "C" {

// Train binary C-SVC by sequential modified SMO (Keerthi et al.
// "modification 2": global most-violating (I_up, I_low) pair, the
// algorithm of seq.cpp:195-260).
//
//   x      n*d row-major features, y  n labels in {-1,+1}
//   c_pos/c_neg  per-class box bounds C * w_{+1} / C * w_{-1} (equal for
//                the unweighted problem)
//   out_alpha[n], out_f[n] caller-allocated; out_scalars[4] receives
//   {b, b_hi, b_lo, converged(0/1)}.
// Returns iterations executed, or negative on error.
long seqsmo_train(const float* x, const int* y, long n, long d,
                  float c_pos, float c_neg, float gamma, float eps, float tau,
                  long max_iter, int kernel_kind, int degree, float coef0,
                  float* out_alpha, float* out_f, float* out_scalars) {
    if (n <= 0 || d <= 0 || max_iter < 0) return -1;
    std::vector<float> x_sq((size_t)n);
    for (long i = 0; i < n; ++i) x_sq[(size_t)i] = dot(x + i * d, x + i * d, d);

    float* alpha = out_alpha;
    float* f = out_f;
    std::memset(alpha, 0, sizeof(float) * (size_t)n);
    for (long i = 0; i < n; ++i) f[i] = -(float)y[i];  // f=-y at alpha=0

    std::vector<float> k_hi((size_t)n), k_lo((size_t)n);
    float b_hi = 0.0f, b_lo = 0.0f;
    long it = 0;
    bool converged = (max_iter == 0);
    while (it < max_iter) {
        // Most-violating pair over the Keerthi I-sets (seq.cpp:469-553):
        // I_up = {alpha<C, y=+1} U {alpha>0, y=-1}, I_low mirrored.
        long i_hi = -1, i_lo = -1;
        float f_hi = 0.0f, f_lo = 0.0f;
        for (long i = 0; i < n; ++i) {
            bool pos = y[i] > 0;
            float ci = pos ? c_pos : c_neg;
            bool up = pos ? (alpha[i] < ci) : (alpha[i] > 0.0f);
            bool low = pos ? (alpha[i] > 0.0f) : (alpha[i] < ci);
            if (up && (i_hi < 0 || f[i] < f_hi)) { f_hi = f[i]; i_hi = i; }
            if (low && (i_lo < 0 || f[i] > f_lo)) { f_lo = f[i]; i_lo = i; }
        }
        if (i_hi < 0 || i_lo < 0) { converged = true; break; }
        b_hi = f_hi;
        b_lo = f_lo;

        kernel_row(x, x_sq.data(), n, d, i_hi, kernel_kind, gamma, degree,
                   coef0, k_hi.data());
        kernel_row(x, x_sq.data(), n, d, i_lo, kernel_kind, gamma, degree,
                   coef0, k_lo.data());
        float eta = k_hi[(size_t)i_hi] + k_lo[(size_t)i_lo]
                    - 2.0f * k_hi[(size_t)i_lo];
        if (eta < tau) eta = tau;  // B2 fix (reference divides unguarded)

        float y_hi = (float)y[i_hi], y_lo = (float)y[i_lo];
        float c_hi = y[i_hi] > 0 ? c_pos : c_neg;
        float c_lo = y[i_lo] > 0 ? c_pos : c_neg;
        float a_hi_old = alpha[i_hi], a_lo_old = alpha[i_lo];
        // Pair update with the joint [L, H] clip; the reference's
        // sequential double clip (seq.cpp:237-250) can violate
        // sum alpha_i y_i (see solver/smo.py pair_alpha_update).
        float s = y_hi * y_lo;
        float w = a_hi_old + s * a_lo_old;
        float lo_b = s > 0.0f ? (w - c_hi > 0.0f ? w - c_hi : 0.0f)
                              : (-w > 0.0f ? -w : 0.0f);
        float hi_b = s > 0.0f ? (w < c_lo ? w : c_lo)
                              : (c_hi - w < c_lo ? c_hi - w : c_lo);
        float a_lo_new = a_lo_old + y_lo * (b_hi - b_lo) / eta;
        if (a_lo_new < lo_b) a_lo_new = lo_b;
        if (a_lo_new > hi_b) a_lo_new = hi_b;
        // Bound snap (see solver/smo.py pair_alpha_update: avoids the
        // c - 1ulp livelock); a_lo snaps BEFORE a_hi is derived from it
        // so conservation survives the snap.
        float snap_lo = 1e-6f * c_lo;
        float snap_hi = 1e-6f * c_hi;
        if (a_lo_new < snap_lo) a_lo_new = 0.0f;
        else if (a_lo_new > c_lo - snap_lo) a_lo_new = c_lo;
        float a_hi_new = a_hi_old + s * (a_lo_old - a_lo_new);
        if (a_hi_new < 0.0f) a_hi_new = 0.0f;
        if (a_hi_new > c_hi) a_hi_new = c_hi;
        if (a_hi_new < snap_hi) a_hi_new = 0.0f;
        else if (a_hi_new > c_hi - snap_hi) a_hi_new = c_hi;
        alpha[i_lo] = a_lo_new;
        alpha[i_hi] = a_hi_new;

        float dh = (a_hi_new - a_hi_old) * y_hi;
        float dl = (a_lo_new - a_lo_old) * y_lo;
        for (long i = 0; i < n; ++i)
            f[i] += dh * k_hi[(size_t)i] + dl * k_lo[(size_t)i];
        ++it;
        // do-while: test AFTER the update (seq.cpp:260).
        if (!(b_lo > b_hi + 2.0f * eps)) { converged = true; break; }
    }
    out_scalars[0] = 0.5f * (b_lo + b_hi);  // b (svmTrainMain.cpp:329)
    out_scalars[1] = b_hi;
    out_scalars[2] = b_lo;
    out_scalars[3] = converged ? 1.0f : 0.0f;
    return it;
}

// Decision function over m query rows:
//   out[i] = sum_j coef_j K(sv_x_j, q_i) - b     (coef_j = alpha_j * y_j)
// The seq_test.cpp:187-210 role, with b applied (the reference tester
// drops it, seq_test.cpp:197 — bug B5).
long seqsmo_decision(const float* sv_x, const float* coef, long n_sv, long d,
                     float gamma, int kernel_kind, int degree, float coef0,
                     float b, const float* q, long m, float* out) {
    if (n_sv <= 0 || d <= 0 || m < 0) return -1;
    std::vector<float> sv_sq((size_t)n_sv);
    for (long j = 0; j < n_sv; ++j)
        sv_sq[(size_t)j] = dot(sv_x + j * d, sv_x + j * d, d);
    for (long i = 0; i < m; ++i) {
        const float* qi = q + i * d;
        float q_sq = dot(qi, qi, d);
        float acc = 0.0f;
        for (long j = 0; j < n_sv; ++j) {
            float dp = dot(sv_x + j * d, qi, d);
            acc += coef[j] * kernel_value(dp, sv_sq[(size_t)j], q_sq,
                                          kernel_kind, gamma, degree, coef0);
        }
        out[i] = acc - b;
    }
    return m;
}

}  // extern "C"
