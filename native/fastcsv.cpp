// fastcsv — native CSV parser for the dpsvm_tpu data path.
//
// Native-runtime equivalent of the reference's C++ loader (parse.cpp:10-43),
// which parses "label,f1,...,fd" lines with iostream/stoi/stof. That design
// is correct but slow (stringstream per line); this one reads the whole file
// once and scans it with strtof, parsing ~100x faster, which matters because
// every training run front-loads a full-dataset parse (the reference parses
// the FULL csv on every MPI rank, svmTrainMain.cpp:180).
//
// Exposed as a plain C ABI consumed from Python via ctypes
// (dpsvm_tpu/utils/native.py). No pybind11 dependency.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// Inspect the file: number of data lines and number of comma-separated
// fields on the first non-empty line (label + d features -> d+1 fields).
// Returns 0 on success, negative on error.
int fastcsv_shape(const char* path, long* n_rows, long* n_fields) {
    FILE* fp = std::fopen(path, "rb");
    if (!fp) return -1;
    long rows = 0, fields = 0;
    bool counted_fields = false, line_has_data = false;
    std::vector<char> buf(1 << 20);
    size_t got;
    while ((got = std::fread(buf.data(), 1, buf.size(), fp)) > 0) {
        for (size_t i = 0; i < got; ++i) {
            char ch = buf[i];
            if (ch == '\n') {
                if (line_has_data) {
                    ++rows;
                    if (!counted_fields) { ++fields; counted_fields = true; }
                }
                line_has_data = false;
            } else if (ch != '\r') {
                line_has_data = true;
                if (!counted_fields && ch == ',') ++fields;
            }
        }
    }
    if (line_has_data) {
        ++rows;
        if (!counted_fields) { ++fields; }
    }
    std::fclose(fp);
    if (rows == 0 || fields < 2) return -2;
    *n_rows = rows;
    *n_fields = fields;
    return 0;
}

// Parse up to n_rows lines of "label,f1,...,fd" into caller-allocated
// x (n_rows * d floats, row-major) and y (n_rows ints), d = n_fields - 1.
// Returns number of rows parsed, or negative on error.
long fastcsv_parse(const char* path, long n_rows, long n_fields,
                   float* x, int* y) {
    FILE* fp = std::fopen(path, "rb");
    if (!fp) return -1;
    std::fseek(fp, 0, SEEK_END);
    long size = std::ftell(fp);
    std::fseek(fp, 0, SEEK_SET);
    std::vector<char> data((size_t)size + 1);
    if (std::fread(data.data(), 1, (size_t)size, fp) != (size_t)size) {
        std::fclose(fp);
        return -2;
    }
    std::fclose(fp);
    data[(size_t)size] = '\0';

    const long d = n_fields - 1;
    char* p = data.data();
    char* end_of_data = data.data() + size;
    long row = 0;
    while (row < n_rows && p < end_of_data) {
        // Skip blank lines.
        while (p < end_of_data && (*p == '\n' || *p == '\r')) ++p;
        if (p >= end_of_data) break;
        // Bound this row's parse to its own line: strtof/strtol skip
        // leading whitespace INCLUDING newlines, so a ragged (short) row
        // would otherwise silently consume the next line's label as a
        // feature and shift every subsequent row.
        char* line_end = p;
        while (line_end < end_of_data && *line_end != '\n') ++line_end;
        char saved = *line_end;
        *line_end = '\0';
        char* next = nullptr;
        y[row] = (int)std::strtol(p, &next, 10);
        if (next == p) { *line_end = saved; return -3; }
        p = next;
        float* xrow = x + row * d;
        for (long j = 0; j < d; ++j) {
            if (p >= line_end) { *line_end = saved; return -4; }  // ragged row
            if (*p == ',') ++p;
            xrow[j] = std::strtof(p, &next);
            if (next == p) { *line_end = saved; return -3; }
            p = next;
        }
        *line_end = saved;
        p = line_end;
        ++row;
    }
    return row;
}

// Write the text model format (gamma line, b line, then one
// "alpha,y,x1,...,xd" row per support vector — the layout of the
// reference's distributed writer, svmTrainMain.cpp:386-416). The Python
// fallback calls repr() per float (~15M calls for an MNIST-scale model);
// this writes with %.9g, which round-trips float32 exactly.
long fastmodel_write(const char* path, float gamma, float b,
                     const float* alpha, const int* y, const float* x,
                     long n_sv, long d) {
    FILE* fp = std::fopen(path, "wb");
    if (!fp) return -1;
    std::vector<char> iobuf(1 << 20);
    std::setvbuf(fp, iobuf.data(), _IOFBF, iobuf.size());
    std::fprintf(fp, "%.9g\n%.9g\n", (double)gamma, (double)b);
    for (long i = 0; i < n_sv; ++i) {
        std::fprintf(fp, "%.9g,%d", (double)alpha[i], y[i]);
        const float* row = x + i * d;
        for (long j = 0; j < d; ++j) {
            std::fprintf(fp, ",%.9g", (double)row[j]);
        }
        std::fputc('\n', fp);
    }
    if (std::fclose(fp) != 0) return -2;
    return n_sv;
}

}  // extern "C"
