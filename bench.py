"""Headline benchmark: MNIST-even-odd-scale RBF SMO training wall-clock.

Mirrors the reference's benchmark configuration (Makefile:74: n=60000,
d=784, C=10, gamma=0.125, eps=0.01, max_iter=100000) on a synthetic
MNIST-shaped dataset (the real CSV is not shipped in this environment;
dpsvm_tpu.data.synth.make_mnist_like generates a seeded stand-in with a
nontrivial margin structure).

Baseline (BASELINE.md): the reference trains real MNIST even-odd in 137 s
on 1x GTX 780 and 46 s on 10x GTX 780 over Ethernet MPI. vs_baseline
reported here is 46 / value — i.e. >1 means one TPU chip beats the
reference's ten-GPU cluster.

TWO runs, both measured on device:

* PRIMARY (the reported `value`): a budget-mode run that executes the
  reference's full max_iter=100,000 pair-update budget
  (config.budget_mode — the stopping test is disabled so the loop runs
  to the exact budget). Iteration counts to convergence differ between
  the synthetic set and real MNIST, so the honest apples-to-apples
  wall-clock is "time to execute the reference's own iteration budget",
  which this MEASURES (round 2 only projected it from pairs/s).
* SECONDARY (`seconds_to_convergence`): the same configuration run to
  the eps=0.01 stopping rule, with a solution-quality gate against an
  fp32 per-pair solve.

Timer placement matches the reference: its CycleTimer starts AFTER data
load, H2D copies and setup barriers and stops at convergence
(svmTrainMain.cpp:206-208 -> :312), so both values are
SolveResult.train_seconds — the on-device solve loop, excluding the
one-time host->device upload of X (which on this harness rides a network
tunnel the reference's PCIe copy never paid). Compilation is excluded on
both sides (CUDA kernels are prebuilt; the XLA chunk executor is warmed
first). Reported value is the best of three measured runs to absorb
first-execution device ramp and harness jitter.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus the
honesty fields (see the comment above the final print).
"""

from __future__ import annotations

import argparse
import json
import sys

N = 60_000
D = 784
BASELINE_10GPU_SECONDS = 46.0
REF_BUDGET = 100_000  # reference Makefile:74 --max-iter

# Telemetry schema embedded in every benchmark artifact this repo's
# tools emit (BENCH/MULTICHIP/SERVE/SMOKE *_r*.json) — the runlog
# module's version, so artifacts and run logs evolve together and
# _latest_bench_artifact can SKIP records newer than this build
# understands instead of crashing or mis-reading them.
def _schema_version() -> int:
    from dpsvm_tpu.obs.runlog import SCHEMA_VERSION

    return SCHEMA_VERSION


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="headline / mesh benchmark (see module docstring)")
    ap.add_argument("--mesh", action="store_true",
                    help="run the MULTICHIP mesh-path benchmark instead "
                         "of the single-chip headline")
    ap.add_argument("--ooc", action="store_true",
                    help="run the out-of-core streaming benchmark "
                         "(solver/ooc.py): host-resident X, double-"
                         "buffered tile stream + block cache, gated "
                         "against BENCH_OOC_r*.json")
    ap.add_argument("--fused-round", action="store_true",
                    help="run the one-HBM-pass fused-round benchmark "
                         "(ops/pallas_round.py, config.fused_round): "
                         "the fused round vs the stock fused engine at "
                         "the same budget, bitwise-checked, gated "
                         "against BENCH_FUSED_r*.json")
    ap.add_argument("--obs", action="store_true",
                    help="enable the telemetry spine: the timed solves "
                         "write a schema-versioned run log whose per-"
                         "chunk records the benchmark RECONCILES with "
                         "its own pairs/s (reported in the artifact); "
                         "zero effect on the measured programs")
    ap.add_argument("--obs-dir", default=None,
                    help="run-log directory (default obs_runs; env "
                         "DPSVM_OBS_DIR)")
    ap.add_argument("--trace-dir", default=None,
                    help="with --obs: capture a jax.profiler device "
                         "trace of the timed runs into this directory")
    return ap.parse_args(argv)


def _obs_config(args):
    """ObsConfig for the timed solves (None -> flag/env defaults)."""
    from dpsvm_tpu.config import ObsConfig

    if args is None:
        return ObsConfig()
    return ObsConfig(enabled=args.obs, trace_dir=args.trace_dir,
                     runlog_dir=args.obs_dir)


def _runlog_reconciliation(res, metric_pps: float) -> dict:
    """Cross-check the BENCH metric against the run log (ISSUE 7
    acceptance): sum the best run's per-chunk (pairs_delta,
    device_seconds) records and compare the implied pairs/s with the
    artifact's. Empty when the solve ran without obs."""
    path = res.stats.get("obs_runlog")
    run_id = res.stats.get("obs_run_id")
    if not path:
        return {}
    from dpsvm_tpu.obs.runlog import read_runlog, records_for

    chunks = records_for(read_runlog(path), run_id, "chunk")
    pairs = sum(c["pairs_delta"] for c in chunks)
    secs = sum(c["device_seconds"] for c in chunks)
    rl_pps = pairs / max(secs, 1e-9)
    delta = rl_pps / metric_pps - 1.0
    return {
        "runlog": path,
        "runlog_run_id": run_id,
        "runlog_chunk_records": len(chunks),
        "runlog_pairs_per_second": round(rl_pps),
        "runlog_delta": round(delta, 6),
        # 1% is the acceptance bound; in practice the two numbers are
        # the same sums modulo record rounding.
        "runlog_reconciles": bool(abs(delta) <= 0.01),
    }


def _device_fields() -> dict:
    """Device-identity stamp for every benchmark artifact (ISSUE 14
    satellite): the regression gate refuses to drift-normalize across
    device KINDS — calibration cancels session speed, not hardware —
    so artifacts must say what they were measured on."""
    import jax

    from dpsvm_tpu.autotune.profile import device_kind_of

    devs = jax.devices()
    return {
        "device": str(devs[0]),
        # The ONE device-kind keying rule, shared with profile
        # resolution and the solvers' gate provenance.
        "device_kind": device_kind_of(devs[0]),
        "n_devices": len(devs),
    }


def _artifact_device_kind(doc: dict):
    """A benchmark artifact's device kind: the explicit stamp, else
    derived from the recorded device string where UNAMBIGUOUS — the
    legacy CPU-harness artifacts all say 'TFRT_CPU_0'. TPU device
    strings stay None (kind granularity matters: a v4 baseline must
    not adjudicate a v5e run just because both say TPU)."""
    kind = doc.get("device_kind")
    if kind:
        return kind
    dev = str(doc.get("device") or "")
    return "cpu" if "cpu" in dev.lower() else None


def _artifact_topology(doc: dict) -> tuple:
    """A benchmark artifact's serving topology stamp (ISSUE 16):
    ``(replicas, union_mesh_devices)``. Artifacts predating the stamp
    are the single-engine single-chip layout by construction — every
    committed BENCH_SERVE_r01/r02 ran one engine on one device — so
    absent fields derive to (1, 1) and keep adjudicating against
    same-topology runs instead of refusing history."""
    return (int(doc.get("replicas") or 1),
            int(doc.get("union_mesh_devices") or 1))


def _artifact_storage(doc: dict) -> str:
    """A serving artifact's union storage stamp (ISSUE 17). Artifacts
    predating the stamp staged f32 unions by construction — every
    committed BENCH_SERVE_r01..r03 headline ran the f32 path — so an
    absent field derives to 'f32' and keeps adjudicating against
    same-storage runs instead of refusing history (the
    _artifact_topology precedent)."""
    return str(doc.get("union_storage") or "f32")


def _session_calibration() -> dict:
    """Fixed-reference-kernel measurement for THIS session (VERDICT
    round-5 weak #1): a pinned compute kernel whose FLOP count never
    changes across PRs, timed with the same block_until_ready discipline
    as the solver runs. Its best-of-5 device time is a property of the
    session (chip generation, runtime, tunnel state) and NOT of any
    solver code, so cross-session drift in the headline value can be
    attributed: if calibration moved too, the session changed; if
    calibration held, the regression is real. 16 chained 2048^2 f32
    matmuls ~ 275 GFLOP — big enough to be compute-bound, small enough
    to add < 1 s to the benchmark."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.default_rng(0)
                    .normal(size=(2048, 2048)).astype(np.float32) / 45.0)

    @jax.jit
    def chain(m):
        for _ in range(16):
            m = jnp.tanh(m @ m)
        return m

    chain(a).block_until_ready()  # compile outside the timer
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        chain(a).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return {
        "kernel": "16x tanh(2048x2048 f32 matmul), seed-0 operand",
        "best_of_5_seconds": round(best, 4),
    }


# Residual session jitter AFTER drift normalization (the calibration
# cancels first-order session speed; what remains is the ±10%-class
# run-to-run jitter both PROFILE.md and the round-4/5 within-session
# A/Bs observed). A normalized delta beyond this band is FLAGged as a
# real regression/improvement; inside it is PASS (noise).
_REGRESSION_BAND = 0.10

# Per-phase gate noise floor: a phase must have carried at least this
# fraction of the previous run's total before its delta can FLAG —
# the observe/finalize phases are milliseconds-scale on fast runs and
# a 2 ms -> 5 ms move is jitter, not a regression.
_PHASE_MIN_SHARE = 0.01


def _phase_gate(current: dict, prev: dict, drift: float) -> dict:
    """Per-phase regression check (ISSUE 8): compare the two artifacts'
    ``phase_seconds`` (SolveResult.stats, embedded since PR 8) with the
    same drift normalization as the headline — seconds MULTIPLY by the
    session ratio (a faster session re-expresses as more prev-session
    seconds) where throughput divides. A phase FLAGs when it got slower
    beyond the band AND carried a non-noise share of the previous total
    (_PHASE_MIN_SHARE). Empty dict when either artifact predates the
    phase clock."""
    prev_ph = prev.get("phase_seconds")
    cur_ph = current.get("phase_seconds")
    if not prev_ph or not cur_ph:
        return {}
    prev_total = sum(prev_ph.values())
    deltas, flags = {}, []
    for phase in sorted(set(prev_ph) | set(cur_ph)):
        p, c = prev_ph.get(phase, 0.0), cur_ph.get(phase, 0.0)
        if p <= 0:
            # A phase appearing from nothing can't normalize to a
            # ratio; report the raw seconds so it is visible.
            deltas[phase] = round(c * drift, 6) if c else 0.0
            continue
        delta = (c * drift) / p - 1.0
        deltas[phase] = round(delta, 4)
        if (delta > _REGRESSION_BAND
                and prev_total > 0
                and p / prev_total >= _PHASE_MIN_SHARE):
            flags.append(phase)
    return {
        "phase_deltas": deltas,
        "phase_flags": flags,
        "phase_gate": "FLAG" if flags else "PASS",
    }


def _latest_bench_artifact(root: str, pattern: str = "BENCH_r*.json",
                           key: str = None):
    """(path, parsed-dict) of the newest committed artifact matching
    `pattern`, or (None, None). Artifacts come in two shapes: the
    driver's wrapper {"parsed": {...}} and a bare result dict.

    When `key` is given, returns the newest artifact that CARRIES that
    metric: the MULTICHIP_r*.json family mixes driver-written
    {rc, ok, skipped} run records with metric-bearing mesh-bench
    records, and a metric-less newest file must not blind the gate to
    an older adjudicable baseline (ISSUE 4 satellite)."""
    import glob
    import os

    for path in sorted(glob.glob(os.path.join(root, pattern)),
                       reverse=True):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            # A truncated artifact (driver killed mid-write) must not
            # crash the gate — skip to the next candidate; the gate's
            # contract is NO_BASELINE, never an exception.
            continue
        doc = doc.get("parsed", doc)
        # Artifacts carry the telemetry schema_version (ISSUE 7); a
        # record written by a NEWER build is skipped explicitly —
        # fields this build doesn't understand must not be mis-read as
        # comparable. Absent field = pre-obs artifact = version 0,
        # always readable.
        try:
            if int(doc.get("schema_version", 0)) > _schema_version():
                continue
        except (TypeError, ValueError):
            continue
        if key is None or key in doc:
            return path, doc
    return None, None


def _regression_gate(current: dict, root: str,
                     pattern: str = "BENCH_r*.json",
                     key: str = "pairs_per_second") -> dict:
    """Round-over-round regression check (VERDICT round-5 item 1, second
    half): compare THIS run's throughput metric against the latest
    committed artifact, drift-normalized by the pinned session-
    calibration kernel so a slow tunnel hour cannot masquerade as a
    solver regression (and a fast one cannot hide it). Pure function of
    the two artifacts — unit-tested in tests/test_bench_gate.py.

    Generalized over (pattern, key) so every benchmark family gets the
    same cross-session adjudication: the headline solver bench uses the
    defaults (BENCH_r*.json, pairs_per_second); the serving bench gates
    BENCH_SERVE_r*.json on examples_per_second (tools/bench_serve.py);
    the mesh bench (`python bench.py --mesh`) gates MULTICHIP_r*.json
    on mesh_pairs_per_second, skipping the driver's metric-less
    {rc, ok} run records (ISSUE 4 satellite — mesh-path regressions
    become adjudicable like headline ones).

    Normalization: the calibration kernel's FLOPs never change, so
    (prev_calib_s / cur_calib_s) is the session speed ratio; dividing
    the current metric by it re-expresses the measurement in the
    PREVIOUS session's time units before comparing. Verdicts:
      PASS / FLAG      — |normalized delta| within / beyond the band
      NO_BASELINE      — first run (no committed artifact)
      NO_CALIBRATION   — previous artifact predates the calibration
                         field: the delta is reported RAW and
                         informational (cross-session drift cannot be
                         separated out)
      DEVICE_MISMATCH  — the artifacts were measured on different
                         device KINDS (ISSUE 14 satellite): the
                         calibration kernel cancels session speed,
                         not hardware, so the delta is reported RAW
                         and adjudicates nothing
      DEVICE_UNKNOWN   — the baseline predates the device_kind stamp
                         AND its kind cannot be derived from its
                         recorded device string (e.g. the TPU-session
                         BENCH_r03-r05): cross-kind normalization
                         cannot be ruled out, so the delta is RAW and
                         informational. Legacy CPU-harness baselines
                         (device 'TFRT_CPU_0') derive to 'cpu' and
                         keep adjudicating against cpu runs.
      TOPOLOGY_MISMATCH— the artifacts ran different serving
                         topologies (replicas or mesh width, ISSUE
                         16): a 2-replica run "beating" a 1-replica
                         baseline is the scaling claim, not a
                         regression verdict — delta RAW, adjudicates
                         nothing. Artifacts predating the stamps
                         derive to (1, 1).
      STORAGE_MISMATCH — the artifacts staged different union
                         storage dtypes (ISSUE 17): an int8 run vs
                         an f32 baseline is the quantization claim
                         (the artifact's own storage A/B leg), not a
                         regression verdict — delta RAW, adjudicates
                         nothing. Artifacts predating the
                         union_storage stamp derive to 'f32'."""
    path, prev = _latest_bench_artifact(root, pattern, key=key)
    if prev is None:
        return {"regression_gate": "NO_BASELINE"}
    out = {
        "previous_artifact": path.rsplit("/", 1)[-1],
        f"previous_{key}": prev[key],
    }
    cur_pps = current[key]
    # Device-kind refusal (ISSUE 14 satellite): the calibration kernel
    # separates SESSION speed, not HARDWARE — drift-normalizing a v5e
    # run against a CPU-harness baseline would spuriously FLAG (or
    # worse, spuriously PASS). Cross-kind comparisons report the raw
    # delta as informational and adjudicate nothing. Baselines
    # predating the device_kind stamp derive their kind from the
    # recorded device string where unambiguous ('TFRT_CPU_0' -> cpu —
    # every committed CPU-harness baseline CI gates against); a
    # baseline whose kind stays unknown refuses too (DEVICE_UNKNOWN),
    # because the refusal must protect the FIRST stamped device run,
    # not start one commit later.
    # Symmetric derivation: an unstamped CURRENT with a recognizable
    # device string must not bypass the refusal either.
    cur_kind = _artifact_device_kind(current)
    prev_kind = _artifact_device_kind(prev)
    if cur_kind and prev_kind != cur_kind:
        out.update({
            "regression_gate": ("DEVICE_UNKNOWN" if prev_kind is None
                                else "DEVICE_MISMATCH"),
            "previous_device_kind": prev_kind,
            "raw_delta": round(cur_pps / prev[key] - 1.0, 4),
        })
        return out
    # Topology refusal (ISSUE 16, same shape as the device-kind one):
    # the calibration kernel cancels session speed on ONE chip — it
    # says nothing about replica count or mesh width, so a 2-replica
    # run drift-normalized against a 1-replica baseline would
    # spuriously PASS its ~2x as "improvement" and bury the next real
    # regression under a moved baseline. Cross-topology deltas are
    # the SCALING claim (reported by the artifact's own frontier leg),
    # not a regression verdict: refuse with the raw delta.
    cur_topo = _artifact_topology(current)
    prev_topo = _artifact_topology(prev)
    if cur_topo != prev_topo:
        out.update({
            "regression_gate": "TOPOLOGY_MISMATCH",
            "previous_topology": {"replicas": prev_topo[0],
                                  "union_mesh_devices": prev_topo[1]},
            "current_topology": {"replicas": cur_topo[0],
                                 "union_mesh_devices": cur_topo[1]},
            "raw_delta": round(cur_pps / prev[key] - 1.0, 4),
        })
        return out
    # Storage refusal (ISSUE 17, same shape again): an int8 run
    # "beating" an f32 baseline is the quantization claim — the
    # artifact's own storage A/B leg reports it at matched shape —
    # not a regression verdict; and an int8 regression hidden under a
    # faster storage's moved baseline would be invisible. Cross-
    # storage deltas are RAW and adjudicate nothing. Artifacts
    # predating the union_storage stamp derive to 'f32'.
    cur_store = _artifact_storage(current)
    prev_store = _artifact_storage(prev)
    if cur_store != prev_store:
        out.update({
            "regression_gate": "STORAGE_MISMATCH",
            "previous_union_storage": prev_store,
            "current_union_storage": cur_store,
            "raw_delta": round(cur_pps / prev[key] - 1.0, 4),
        })
        return out
    prev_cal = (prev.get("session_calibration") or {}).get(
        "best_of_5_seconds")
    cur_cal = (current.get("session_calibration") or {}).get(
        "best_of_5_seconds")
    if not prev_cal or not cur_cal:
        out["regression_gate"] = "NO_CALIBRATION"
        out["raw_delta"] = round(cur_pps / prev[key] - 1.0, 4)
        return out
    drift = prev_cal / cur_cal  # >1: this session is FASTER than prev
    norm_pps = cur_pps / drift
    delta = norm_pps / prev[key] - 1.0
    out.update({
        "session_drift_ratio": round(drift, 4),
        f"normalized_{key}": round(norm_pps),
        "normalized_delta": round(delta, 4),
        "regression_band": _REGRESSION_BAND,
        "regression_gate": ("PASS" if abs(delta) <= _REGRESSION_BAND
                            else "FLAG"),
    })
    # Per-phase attribution (ISSUE 8): the headline can PASS while one
    # phase regressed and another improved — the phase gate names the
    # phase that moved, same band, same normalization.
    out.update(_phase_gate(current, prev, drift))
    return out


def mesh_main(args=None) -> int:
    """Mesh-path benchmark (`python bench.py --mesh`) — the MULTICHIP
    sibling of the headline bench (ISSUE 4 satellite). One budget-mode
    mesh block solve over every visible device at a covtype-shaped
    operating point, reported as mesh_pairs_per_second and gated
    against the latest metric-bearing MULTICHIP_r*.json with the same
    drift-normalized regression gate as the headline — so a mesh-path
    regression (collective regression, sharding regression, runner
    regression) is adjudicable across sessions instead of invisible
    behind the single-chip number. The driver's {rc, ok} MULTICHIP run
    records carry no metric and are skipped by the artifact scan.

    Uses the GLOBAL-working-set engine (the default mesh path):
    budget_mode promises an exact pair count, which the shard-local
    engine's concurrent spending cannot honor (config validation);
    shard-local throughput is measured by its own A/B probe
    (tools/profile_round.py --shardlocal)."""
    import os

    import jax

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.parallel.dist_smo import solve_mesh

    calibration = _session_calibration()
    print(f"[bench --mesh] session calibration: {json.dumps(calibration)}",
          file=sys.stderr)
    # covtype-shaped synthetic, scaled to a row count every harness can
    # hold (THE shared generator — autotune probes and
    # tools/profile_round.py measure the same data family; pinned
    # seed keeps committed artifacts reproducible).
    from dpsvm_tpu.data import make_covtype_like

    n, d = 65_536, 54
    x, y = make_covtype_like(n, d, seed=0)
    budget = 200_000
    cfg = SVMConfig(c=32.0, gamma=0.03125, epsilon=1e-3, engine="block",
                    working_set_size=256, budget_mode=True,
                    max_iter=budget, obs=_obs_config(args))
    n_dev = len(jax.devices())
    solve_mesh(x, y, cfg.replace(max_iter=64), num_devices=n_dev)  # warm
    runs = [solve_mesh(x, y, cfg, num_devices=n_dev) for _ in range(3)]
    best = min(runs, key=lambda r: r.train_seconds)
    if best.iterations < budget:
        # A broken budget contract must fail LOUDLY before a bogus
        # pairs/s is gated and printed (and must not vanish under -O
        # the way a bare assert would).
        print(f"[bench --mesh] ERROR: budget run executed "
              f"{best.iterations} < {budget} pairs — mesh budget "
              "contract broken; no result emitted", file=sys.stderr)
        return 1
    pps = best.iterations / max(best.train_seconds, 1e-9)
    result = {
        "metric": (f"synthetic covtype-shaped {n}x{d} RBF mesh block "
                   f"solve over {n_dev} devices, MEASURED at a "
                   f"{budget} pair-update budget"),
        "value": round(best.train_seconds, 3),
        "unit": "seconds",
        **_device_fields(),
        "pair_updates": int(best.iterations),
        "mesh_pairs_per_second": round(pps),
        # Per-phase wall clock of the best run (SolveResult.stats):
        # feeds the per-phase regression gate so a mesh regression is
        # attributed to setup/solve/observe/finalize, not just seen in
        # the headline.
        "phase_seconds": best.stats.get("phase_seconds"),
        "schema_version": _schema_version(),
        "session_calibration": calibration,
    }
    result.update(_runlog_reconciliation(best, pps))
    # Ring-exchange and bf16-Gram columns (ISSUE 11): the same budget
    # run through the DMA-ring exchange and through the gated bf16
    # storage flip, so MULTICHIP artifacts carry all three numbers and
    # the regression gate can adjudicate ring/bf16 throughput across
    # device sessions the moment the first device artifact lands (each
    # column gates independently; NO_BASELINE until then). One run each
    # — the variance-critical headline keeps its best-of-3.
    root = os.path.dirname(os.path.abspath(__file__))
    for col, vcfg in (
            ("ring", cfg.replace(ring_exchange=True)),
            ("bf16", cfg.replace(bf16_gram=True))):
        rv = solve_mesh(x, y, vcfg, num_devices=n_dev)
        if rv.iterations < budget:
            print(f"[bench --mesh] ERROR: {col} budget run executed "
                  f"{rv.iterations} < {budget} pairs", file=sys.stderr)
            return 1
        v_pps = rv.iterations / max(rv.train_seconds, 1e-9)
        key = f"{col}_pairs_per_second"
        result[key] = round(v_pps)
        result[f"{col}_seconds"] = round(rv.train_seconds, 3)
        if col == "ring":
            # Honesty flag: on a 1-device harness use_ring disengages
            # (no hops) and this column measured the gather path — a
            # device-session gate must not compare real ring numbers
            # against a mislabeled single-chip baseline.
            result["ring_exchange_active"] = bool(
                rv.stats.get("ring_exchange"))
        else:
            result["bf16_gram_active"] = bool(
                rv.stats.get("bf16_gram", {}).get("active"))
        vgate = _regression_gate({**result, key: round(v_pps)}, root,
                                 pattern="MULTICHIP_r*.json", key=key)
        result[f"{col}_gate"] = vgate.get("regression_gate")
        print(f"[bench --mesh] {col}: {rv.iterations} pairs in "
              f"{rv.train_seconds:.3f}s ({v_pps:.0f}/s); gate: "
              f"{vgate.get('regression_gate')}", file=sys.stderr)
    gate = _regression_gate(result, root,
                            pattern="MULTICHIP_r*.json",
                            key="mesh_pairs_per_second")
    result.update(gate)
    rl_note = (f"; runlog: {result['runlog']}"
               if result.get("runlog") else "")
    ph_note = (f"; phase gate: {gate['phase_gate']}"
               + (f" ({', '.join(gate['phase_flags'])})"
                  if gate.get("phase_flags") else "")
               if gate.get("phase_gate") else "")
    print(f"[bench --mesh] {n_dev} devices: {best.iterations} pairs in "
          f"{best.train_seconds:.3f}s ({pps:.0f}/s); gate: "
          f"{gate.get('regression_gate')}{ph_note}{rl_note}",
          file=sys.stderr)
    print(json.dumps(result))
    return 0


def ooc_main(args=None) -> int:
    """Out-of-core benchmark (`python bench.py --ooc`, ISSUE 9): one
    budget-mode ooc block solve — X host-resident, the per-round fold
    streamed over double-buffered tiles, the block cache live — at a
    covtype-shaped operating point sized for the CPU harness, reported
    as ooc_pairs_per_second and gated against the latest
    BENCH_OOC_r*.json with the same drift-normalized regression gate
    as the headline. The artifact embeds the stream/cache counters
    (tiles_streamed, tile_bytes_h2d, cache_hit_rate, cached_rounds)
    and, with --obs, reconciles against the run log whose chunk
    records carry the per-round tile/cache fields.

    A second, late-training leg (ISSUE 19) continues the budget model
    for the same budget again, warm-started on f-sorted rows, with the
    shrunken tile stream on vs off at identical budgets — recording
    tiles_skipped / bytes_streamed, the in-cycle byte cut, and a
    holdout-accuracy guard, gated on its own
    ooc_shrink_pairs_per_second key."""
    import os

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    calibration = _session_calibration()
    print(f"[bench --ooc] session calibration: {json.dumps(calibration)}",
          file=sys.stderr)
    from dpsvm_tpu.data import make_covtype_like

    n, d = 16_384, 54
    x, y = make_covtype_like(n, d, seed=0)
    budget = 50_000
    cfg = SVMConfig(c=32.0, gamma=0.03125, epsilon=1e-3, engine="block",
                    working_set_size=256, budget_mode=True,
                    max_iter=budget, ooc=True, ooc_tile_rows=4096,
                    ooc_cache_lines=1024, obs=_obs_config(args))
    solve(x, y, cfg.replace(max_iter=64))  # warm the executors
    runs = [solve(x, y, cfg) for _ in range(3)]
    best = min(runs, key=lambda r: r.train_seconds)
    if best.iterations < budget:
        print(f"[bench --ooc] ERROR: budget run executed "
              f"{best.iterations} < {budget} pairs — ooc budget "
              "contract broken; no result emitted", file=sys.stderr)
        return 1
    pps = best.iterations / max(best.train_seconds, 1e-9)
    st = best.stats
    result = {
        "metric": (f"synthetic covtype-shaped {n}x{d} RBF out-of-core "
                   f"block solve (host-resident X, "
                   f"tile_rows={cfg.ooc_tile_rows}, "
                   f"cache_lines={cfg.ooc_cache_lines}), MEASURED at a "
                   f"{budget} pair-update budget"),
        "value": round(best.train_seconds, 3),
        "unit": "seconds",
        **_device_fields(),
        "pair_updates": int(best.iterations),
        "ooc_pairs_per_second": round(pps),
        "tiles_streamed": st.get("tiles_streamed"),
        "tile_bytes_h2d": st.get("tile_bytes_h2d"),
        "cached_rounds": st.get("cached_rounds"),
        "cache_hits": st.get("cache_hits"),
        "cache_lookups": st.get("cache_lookups"),
        "cache_hit_rate": round(st.get("cache_hit_rate", 0.0), 6),
        "cache_evictions": st.get("cache_evictions"),
        "outer_rounds": st.get("outer_rounds"),
        "phase_seconds": st.get("phase_seconds"),
        "schema_version": _schema_version(),
        "session_calibration": calibration,
    }
    result.update(_runlog_reconciliation(best, pps))

    # ---- shrunken-stream continuation leg (ISSUE 19). The budget
    # model above is mid-training: the LATE-training phase is measured
    # by continuing it for the same pair budget, warm-started from its
    # alphas, on rows sorted by its gradient f — the selection ranks
    # rows by f-extremeness, so an f-sorted layout puts the working
    # sets at the two ENDS of the tile range and gives the tile-
    # granular skip the index locality a random layout never has. The
    # shrink arm and the full-stream arm run the IDENTICAL continuation
    # (same warm seed, same layout, same budget), so the byte columns
    # are apples-to-apples; the late-phase cut is
    # (in-cycle tiles + skipped) / in-cycle tiles with the cycle
    # reconstruction passes charged to the shrink arm.
    import numpy as np

    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix
    from dpsvm_tpu.solver.warmstart import WarmStart

    sv = best.alpha > 0
    kp = KernelParams(kind="rbf", gamma=cfg.gamma)
    km = np.asarray(kernel_matrix(jnp.asarray(x), jnp.asarray(x[sv]),
                                  kp))
    f_a = km @ (best.alpha[sv] * y[sv]) - y
    order = np.argsort(f_a)
    inv = np.empty(n, dtype=np.int64)
    inv[order] = np.arange(n)
    xs = np.ascontiguousarray(x[order])
    ys = np.ascontiguousarray(y[order])
    svi = np.nonzero(sv)[0]
    warm = WarmStart(alpha=best.alpha[svi], rows=inv[svi])
    shrink_m = 2048
    cont_tile = 512
    cfg_shrink = cfg.replace(ooc_tile_rows=cont_tile, ooc_shrink=True,
                             active_set_size=shrink_m)
    cfg_cont = cfg.replace(ooc_tile_rows=cont_tile)
    solve(xs, ys, cfg_shrink.replace(max_iter=64), warm_start=warm)
    solve(xs, ys, cfg_cont.replace(max_iter=64), warm_start=warm)
    shr = min([solve(xs, ys, cfg_shrink, warm_start=warm)
               for _ in range(2)], key=lambda r: r.train_seconds)
    cont = min([solve(xs, ys, cfg_cont, warm_start=warm)
                for _ in range(2)], key=lambda r: r.train_seconds)
    sst, cst = shr.stats, cont.stats
    s_pps = shr.iterations / max(shr.train_seconds, 1e-9)
    in_cyc = sst.get("shrink_tiles_in_cycle", 0)
    skipped = sst.get("tiles_skipped", 0)
    late_cut = ((in_cyc + skipped) / in_cyc) if in_cyc else 0.0
    # Model-quality guard: both arms spent the same budget from the
    # same warm point — holdout accuracy must agree (the shrunken
    # stream reorders work, it must not degrade the model).
    from dpsvm_tpu.data import make_covtype_like as _mk
    xh, yh = _mk(4096, d, seed=7)
    kmh = np.asarray(kernel_matrix(jnp.asarray(xh), jnp.asarray(xs),
                                   kp))

    def _acc(r):
        dec = kmh @ (r.alpha * ys) + r.b
        return float((np.sign(dec) == yh).mean())

    acc_s, acc_f = _acc(shr), _acc(cont)
    result.update({
        "ooc_shrink_pairs_per_second": round(s_pps),
        "tiles_skipped": skipped,
        "bytes_streamed": sst.get("tile_bytes_h2d"),
        "shrink": {
            "metric": (f"late-training continuation: {budget} more "
                       f"pairs warm-started from the budget model on "
                       f"f-sorted rows (tile_rows={cont_tile}, "
                       f"active_set_size={shrink_m}), shrink arm vs "
                       f"full-stream arm at the identical budget"),
            "active_set_size": shrink_m,
            "tile_rows": cont_tile,
            "pair_updates": int(shr.iterations),
            "seconds": round(shr.train_seconds, 3),
            "tiles_streamed": sst.get("tiles_streamed"),
            "tiles_skipped": skipped,
            "bytes_streamed": sst.get("tile_bytes_h2d"),
            "bytes_skipped": sst.get("tile_bytes_skipped"),
            "late_phase_tiles": in_cyc,
            "late_phase_byte_cut": round(late_cut, 3),
            "cycles": sst.get("shrink_cycles"),
            "reconstructions": sst.get("shrink_reconstructions"),
            "demoted": sst.get("shrink_demoted"),
            "holdout_accuracy": round(acc_s, 4),
            "full_arm": {
                "pair_updates": int(cont.iterations),
                "seconds": round(cont.train_seconds, 3),
                "tiles_streamed": cst.get("tiles_streamed"),
                "bytes_streamed": cst.get("tile_bytes_h2d"),
                "holdout_accuracy": round(acc_f, 4),
            },
        },
    })
    # The shrunken column gates against its OWN key: r01 carries no
    # ooc_shrink_pairs_per_second, so the first stamped run reads
    # NO_BASELINE instead of normalizing against full-stream rows
    # (and the device_kind stamp refuses cross-device adjudication).
    sgate = _regression_gate(result,
                             os.path.dirname(os.path.abspath(__file__)),
                             pattern="BENCH_OOC_r*.json",
                             key="ooc_shrink_pairs_per_second")
    result["shrink_gate"] = sgate.get("regression_gate")
    print(f"[bench --ooc] shrink continuation: {shr.iterations} pairs "
          f"in {shr.train_seconds:.3f}s ({s_pps:.0f}/s); "
          f"{sst.get('tiles_streamed')} tiles streamed / {skipped} "
          f"skipped (full arm {cst.get('tiles_streamed')}), late-phase "
          f"byte cut {late_cut:.2f}x, holdout {acc_s:.4f} vs "
          f"{acc_f:.4f}; gate: {sgate.get('regression_gate')}",
          file=sys.stderr)

    gate = _regression_gate(result,
                            os.path.dirname(os.path.abspath(__file__)),
                            pattern="BENCH_OOC_r*.json",
                            key="ooc_pairs_per_second")
    result.update(gate)
    rl_note = (f"; runlog: {result['runlog']}"
               if result.get("runlog") else "")
    print(f"[bench --ooc] {best.iterations} pairs in "
          f"{best.train_seconds:.3f}s ({pps:.0f}/s); "
          f"{st.get('tiles_streamed')} tiles streamed, cache hit rate "
          f"{100 * st.get('cache_hit_rate', 0.0):.1f}%, "
          f"{st.get('cached_rounds')} all-hit rounds; gate: "
          f"{gate.get('regression_gate')}{rl_note}", file=sys.stderr)
    print(json.dumps(result))
    return 0


def fused_main(args=None) -> int:
    """One-HBM-pass fused-round benchmark (`python bench.py
    --fused-round`, ISSUE 12): one budget-mode block solve through
    config.fused_round=True (ops/pallas_round.py — gather/Gram/kernel
    rows in one Pallas pass over X, fold+select in one pass over the
    O(n) vectors) at a covtype-shaped operating point, reported as
    fusedround_pairs_per_second and gated against the latest
    BENCH_FUSED_r*.json with the same drift-normalized regression gate
    as the headline. The stock fused engine (config.fused_fold=True)
    runs the identical budget as the A/B column, and the artifact
    embeds the BITWISE verdict between the two trajectories — the
    fused round's correctness contract, checked on every bench run.
    On the CPU harness the kernels run in interpret mode: the numbers
    are a structure/regression anchor, not the TPU claim (flip
    solver/block.py fused_round_pays only from a device run)."""
    import os

    import jax
    import numpy as np

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.solver.smo import solve

    calibration = _session_calibration()
    print(f"[bench --fused-round] session calibration: "
          f"{json.dumps(calibration)}", file=sys.stderr)
    from dpsvm_tpu.data import make_covtype_like

    n, d = 16_384, 54
    x, y = make_covtype_like(n, d, seed=0)
    budget = 50_000
    cfg = SVMConfig(c=32.0, gamma=0.03125, epsilon=1e-3, engine="block",
                    working_set_size=256, budget_mode=True,
                    max_iter=budget, fused_round=True,
                    obs=_obs_config(args))
    stock_cfg = cfg.replace(fused_round=False, fused_fold=True)
    solve(x, y, cfg.replace(max_iter=64))  # warm both executors
    solve(x, y, stock_cfg.replace(max_iter=64))
    runs = [solve(x, y, cfg) for _ in range(3)]
    best = min(runs, key=lambda r: r.train_seconds)
    if best.iterations < budget:
        print(f"[bench --fused-round] ERROR: budget run executed "
              f"{best.iterations} < {budget} pairs — budget contract "
              "broken; no result emitted", file=sys.stderr)
        return 1
    stock = min([solve(x, y, stock_cfg) for _ in range(2)],
                key=lambda r: r.train_seconds)
    pps = best.iterations / max(best.train_seconds, 1e-9)
    stock_pps = stock.iterations / max(stock.train_seconds, 1e-9)
    # The correctness contract rides the benchmark: the fused round's
    # trajectory is bitwise the stock fused engine's.
    bitwise = bool(np.array_equal(best.alpha, stock.alpha)
                   and best.iterations == stock.iterations)
    result = {
        "metric": (f"synthetic covtype-shaped {n}x{d} RBF one-HBM-pass "
                   f"fused-round block solve (config.fused_round), "
                   f"MEASURED at a {budget} pair-update budget, vs the "
                   f"stock fused engine at the same budget"),
        "value": round(best.train_seconds, 3),
        "unit": "seconds",
        **_device_fields(),
        "interpret_mode": jax.default_backend() != "tpu",
        "pair_updates": int(best.iterations),
        "fusedround_pairs_per_second": round(pps),
        "fused_pairs_per_second": round(stock_pps),
        "fused_seconds": round(stock.train_seconds, 3),
        "bitwise_vs_fused_fold": bitwise,
        "phase_seconds": best.stats.get("phase_seconds"),
        "schema_version": _schema_version(),
        "session_calibration": calibration,
    }
    if not bitwise:
        # A bitwise break is a correctness regression, not a perf
        # number — fail the leg loudly.
        print("[bench --fused-round] ERROR: fused-round trajectory "
              "diverged bitwise from the stock fused engine",
              file=sys.stderr)
        print(json.dumps(result))
        return 1
    result.update(_runlog_reconciliation(best, pps))
    gate = _regression_gate(result,
                            os.path.dirname(os.path.abspath(__file__)),
                            pattern="BENCH_FUSED_r*.json",
                            key="fusedround_pairs_per_second")
    result.update(gate)
    rl_note = (f"; runlog: {result['runlog']}"
               if result.get("runlog") else "")
    print(f"[bench --fused-round] {best.iterations} pairs in "
          f"{best.train_seconds:.3f}s ({pps:.0f}/s) vs stock fused "
          f"{stock.train_seconds:.3f}s ({stock_pps:.0f}/s), "
          f"bitwise={bitwise}; gate: {gate.get('regression_gate')}"
          f"{rl_note}", file=sys.stderr)
    print(json.dumps(result))
    return 0


def main(args=None) -> int:
    import jax

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synth import make_mnist_like
    from dpsvm_tpu.solver.smo import solve

    calibration = _session_calibration()
    print(f"[bench] session calibration: {json.dumps(calibration)}",
          file=sys.stderr)

    # noise pinned so the benchmark dataset is stable even if the
    # generator's default calibration changes.
    x, y = make_mnist_like(n=N, d=D, seed=7, noise=0.1)

    # Measured on v5e-1 (2026-07): the blockwise decomposition engine
    # (solver/block.py: top-q violator working set via approx_max_k,
    # on-core Pallas subproblem solve, one fused (n,q) fold per round)
    # runs this config far faster than the best per-pair engine — the
    # full-X kernel-row pass is amortized over hundreds of pair updates
    # instead of 1. bf16 X halves the per-round fold read (f and all
    # solver state stay float32); q=256 with the 2q inner budget measured
    # best in the tools/sweep_block.py grid (q=512/inner=1024 within
    # jitter). cache_lines=0: the working-set block IS the cache.
    config = SVMConfig(
        c=10.0, gamma=0.125, epsilon=0.01, max_iter=REF_BUDGET,
        cache_lines=0, engine="block", working_set_size=256,
        dtype="bfloat16", obs=_obs_config(args))
    # Budget run: inner=2048 (not the convergence run's 2q=512). The
    # dataset converges at ~7k pairs, so most of the 100k-pair budget
    # executes at the optimum either way; a larger inner budget amortizes
    # the ~0.2 ms fixed round cost over 4x the pairs and cuts the round
    # count 4x. Swept on-chip 2026-07-31 (best of 3, q x inner grid):
    # i=512 0.161 s / i=1024 0.154 / i=2048 0.135 / i=4096 0.133 — but
    # i=4096's dual objective lands 1.5% from the fp32 optimum, too close
    # to this file's 2% gate for run-to-run variance; i=2048 sits at
    # 0.24% with the same 0.13x-second class. The honest-eps convergence
    # run below keeps the measured-best 2q default.
    # pair_batch=2: two disjoint exact pair updates per serial inner-loop
    # trip (SVMConfig.pair_batch) — same-session A/B measured 0.176 s vs
    # 0.419 s at identical dual objective (the budget run is serial-chain
    # bound; batching halves trips per pair). The convergence run keeps
    # pair_batch=1 (measured a wash there — it is round-bound, not
    # chain-bound — and single-pair is the reference-parity semantics).
    # Inner re-swept under pair_batch=2 (same session, best of 3):
    # i2048 0.130 s at 0.24% off-optimum / i4096 0.123 s at 1.53% /
    # i8192 0.129 s at 9.3% — i2048 keeps 8x gate margin for 7 ms.
    budget_config = config.replace(budget_mode=True, inner_iters=2048,
                                   pair_batch=2)

    # Warm-up: compile BOTH chunk executors (budget_mode bakes a
    # different epsilon into the stopping test, so it is a different XLA
    # program; compilation costs ~4s that the timed runs must not pay —
    # the GPU baseline excludes CUDA compilation too). max_iter only caps
    # the traced loop counter, so 64 warm-up iterations compile
    # everything.
    solve(x, y, config.replace(max_iter=64))
    solve(x, y, budget_config.replace(max_iter=64))

    # Best of three: the tunneled dev harness shows tens-of-ms run-to-run
    # jitter that min-of-N absorbs (real local TPU runtimes don't).
    budget_runs = [solve(x, y, budget_config) for _ in range(3)]
    bres = min(budget_runs, key=lambda r: r.train_seconds)
    assert bres.iterations >= REF_BUDGET, bres.iterations
    budget_seconds = bres.train_seconds

    conv_runs = [solve(x, y, config) for _ in range(3)]
    res = min(conv_runs, key=lambda r: r.train_seconds)
    conv_seconds = res.train_seconds

    # HARD convergence regime (VERDICT round-4 item 9): the pinned
    # noise=0.1 dataset converges in ~7k pairs, which says more about
    # the generator's separability than the solver. A second pinned
    # dataset with 10% label flips is genuinely non-separable (every
    # flipped point becomes a bound SV), exercising the solver's soft-
    # margin tail. Same engine config with its own (much larger) pair
    # budget — the non-separable problem legitimately needs far more
    # than the easy regime's 100k cap; same oracle-quality gate below.
    hard_config = config.replace(max_iter=20_000_000)
    xh, yh = make_mnist_like(n=N, d=D, seed=7, noise=0.1, label_flip=0.10)
    solve(xh, yh, hard_config.replace(max_iter=64))  # warm the executor
    hard_runs = [solve(xh, yh, hard_config) for _ in range(3)]
    hres = min(hard_runs, key=lambda r: r.train_seconds)
    hard_seconds = hres.train_seconds

    # Solution-quality gate: the timed bf16/block run must reach the same
    # optimum as an fp32 per-pair-parity solve — the speedup must come
    # from the engine, never from silently converging somewhere looser.
    # Dual objective from the solver's own gradient (no n^2 matrix):
    # (Q a)_i = y_i (f_i + y_i)  =>  obj = sum(a) - 1/2 sum(a y (f + y)).
    def dual_obj(r):
        import numpy as np
        a, f = r.alpha, r.stats["f"]
        return float(a.sum() - 0.5 * np.sum(a * y * (f + y)))

    ref = solve(x, y, config.replace(engine="xla", dtype="float32"))
    assert res.converged, "convergence run did not converge"
    obj_t, obj_r = dual_obj(res), dual_obj(ref)
    assert abs(obj_t - obj_r) <= 0.005 * abs(obj_r), (obj_t, obj_r)
    assert abs(res.n_sv - ref.n_sv) <= 0.10 * ref.n_sv, (res.n_sv, ref.n_sv)

    # Hard-regime gate: same fp32 per-pair oracle discipline (dual_obj
    # closes over the EASY labels, so compute against yh inline).
    def dual_obj_h(r):
        import numpy as np
        a, f = r.alpha, r.stats["f"]
        return float(a.sum() - 0.5 * np.sum(a * yh * (f + yh)))

    # The hard oracle stays on the block engine at fp32 (per-pair xla
    # at this shape/pair-count would cost minutes per run; the EASY
    # gate above already pins block-vs-per-pair engine parity — this
    # gate isolates the bf16 storage risk on the harder data).
    refh = solve(xh, yh, hard_config.replace(dtype="float32"))
    assert hres.converged, "hard convergence run did not converge"
    obj_th, obj_rh = dual_obj_h(hres), dual_obj_h(refh)
    assert abs(obj_th - obj_rh) <= 0.005 * abs(obj_rh), (obj_th, obj_rh)
    assert abs(hres.n_sv - refh.n_sv) <= 0.10 * refh.n_sv, \
        (hres.n_sv, refh.n_sv)

    # The PRIMARY (budget) run gets its own gate: its forced post-optimum
    # steps oscillate around the optimum, so demand dual feasibility
    # (box + equality constraint — a drift here means corrupted updates)
    # and a dual objective within 2% of the fp32 reference optimum.
    import numpy as np
    assert bres.alpha.min() >= 0.0 and bres.alpha.max() <= config.c + 1e-5
    assert abs(float(np.dot(bres.alpha, y))) < 1e-2, "equality drift"
    obj_b = dual_obj(bres)
    assert abs(obj_b - obj_r) <= 0.02 * abs(obj_r), (obj_b, obj_r)

    pairs_per_second = bres.iterations / max(budget_seconds, 1e-9)
    print(
        f"[bench] device={jax.devices()[0]} budget: {bres.iterations} pairs "
        f"in {budget_seconds:.3f}s ({pairs_per_second:.0f}/s); convergence: "
        f"{res.iterations} pairs in {conv_seconds:.3f}s "
        f"(converged={res.converged} n_sv={res.n_sv}); hard (10% label "
        f"flip): {hres.iterations} pairs in {hard_seconds:.3f}s "
        f"(n_sv={hres.n_sv})",
        file=sys.stderr)

    # Honesty notes, embedded in the output rather than buried here:
    # the dataset is SYNTHETIC (real MNIST is not shipped in this image)
    # and its iteration count to convergence differs from real MNIST's,
    # so the PRIMARY value is the measured device time to execute the
    # reference's own 100k pair-update budget (reference Makefile:74) —
    # the iteration-budget-for-iteration-budget comparison that needs no
    # convergence-difficulty caveat. seconds_to_convergence is the
    # eps=0.01 run on this dataset (faster, but dataset-dependent).
    result = {
        "metric": (
            f"synthetic MNIST-even-odd-shaped 60kx784 RBF modified-SMO "
            f"training wall-clock, 1 chip, MEASURED at the reference's "
            f"full {REF_BUDGET} pair-update budget (ref baseline: 46 s "
            f"on 10x GTX780, max_iter=100000, ref Makefile:74; "
            f"convergence on this dataset is faster — see "
            f"seconds_to_convergence)"),
        "value": round(budget_seconds, 3),
        "unit": "seconds",
        **_device_fields(),
        "vs_baseline": round(BASELINE_10GPU_SECONDS / budget_seconds, 3),
        "pair_updates": int(bres.iterations),
        "pairs_per_second": round(pairs_per_second),
        "seconds_to_convergence": round(conv_seconds, 3),
        "pairs_to_convergence": int(res.iterations),
        "seconds_to_convergence_hard": round(hard_seconds, 3),
        "pairs_to_convergence_hard": int(hres.iterations),
        "n_sv_hard": int(hres.n_sv),
        "dataset": "synthetic make_mnist_like(n=60000, d=784, seed=7, noise=0.1)",
        "dataset_hard": ("synthetic make_mnist_like(n=60000, d=784, "
                         "seed=7, noise=0.1, label_flip=0.10) — "
                         "non-separable soft-margin regime"),
        # Per-phase wall clock of the PRIMARY run (ISSUE 8): the
        # regression gate compares these phase-by-phase, so a headline
        # PASS cannot hide a solve-phase regression paid for by a
        # faster setup (and vice versa).
        "phase_seconds": bres.stats.get("phase_seconds"),
        # Telemetry schema of this artifact (ISSUE 7): lets future
        # builds' _latest_bench_artifact skip incompatible records
        # explicitly instead of mis-reading them.
        "schema_version": _schema_version(),
        # Session drift separator (VERDICT weak #1): compare against the
        # same field in earlier BENCH_r*.json before reading any
        # cross-session delta as a solver regression.
        "session_calibration": calibration,
    }
    # Run-log reconciliation (with --obs): the per-chunk records of the
    # PRIMARY run must imply the same pairs/s this artifact reports.
    result.update(_runlog_reconciliation(bres, pairs_per_second))
    # Round-over-round regression gate vs the latest committed artifact
    # (drift-normalized via the calibration kernel; see _regression_gate).
    import os

    gate = _regression_gate(result, os.path.dirname(os.path.abspath(__file__)))
    result.update(gate)
    # The gate line carries the run-log path when --obs produced one
    # (ISSUE 7 satellite: the verdict and its telemetry substrate are
    # announced together).
    rl_note = (f"; runlog: {result['runlog']} "
               f"(reconciles={result['runlog_reconciles']})"
               if result.get("runlog") else "")
    if gate.get("regression_gate") in ("PASS", "FLAG"):
        ph_note = ""
        if gate.get("phase_gate"):
            ph_note = (f"; phase gate: {gate['phase_gate']}"
                       + (f" — {', '.join(gate['phase_flags'])} beyond "
                          f"band ({gate['phase_deltas']})"
                          if gate.get("phase_flags") else ""))
        print(f"[bench] regression gate: {gate['regression_gate']} — "
              f"drift-normalized {gate['normalized_pairs_per_second']} "
              f"pairs/s vs {gate['previous_pairs_per_second']} in "
              f"{gate['previous_artifact']} "
              f"(delta {100 * gate['normalized_delta']:+.1f}%, band "
              f"±{100 * _REGRESSION_BAND:.0f}%, session drift ratio "
              f"{gate['session_drift_ratio']}){ph_note}{rl_note}",
              file=sys.stderr)
    else:
        print(f"[bench] regression gate: "
              f"{gate.get('regression_gate')} "
              f"{'(raw delta %+.1f%%)' % (100 * gate['raw_delta']) if 'raw_delta' in gate else ''}"
              f"{rl_note}",
              file=sys.stderr)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    _args = _parse_args()
    sys.exit(mesh_main(_args) if _args.mesh
             else ooc_main(_args) if _args.ooc
             else fused_main(_args) if _args.fused_round else main(_args))
