"""Dataset converters — modern equivalents of the reference's Python 2
prep scripts (component C12).

* ``libsvm_to_csv``: sparse LIBSVM format -> dense ``label,f1,...,fd`` CSV
  (the role of scripts/convert_adult.py: Adult a9a with +/- labels and
  123 binary features).
* ``mnist_to_odd_even_csv``: MNIST-style (label, pixels) rows -> +-1
  even/odd labels with pixels scaled to [0, 1] (the role of
  scripts/convert_mnist_to_odd_even.py).
"""

from __future__ import annotations

import numpy as np


def parse_libsvm(path: str, num_features: int | None = None,
                 num_rows: int | None = None):
    """Parse sparse LIBSVM lines ``label idx:val idx:val ...`` (1-based
    indices) into dense arrays (x float32 (n,d), y int32 — integer
    class labels, +-1 in the common binary case). Reading
    stops after `num_rows` examples when given (matching load_csv's
    bounded read of the reference parser, parse.cpp:25)."""
    rows: list[dict[int, float]] = []
    labels: list[int] = []
    max_idx = 0
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            if num_rows is not None and len(rows) >= num_rows:
                break
            parts = line.split()
            if not parts:
                continue
            try:
                lab_val = float(parts[0])
            except ValueError:
                raise ValueError(
                    f"{path}:{lineno}: label token {parts[0]!r} is not "
                    "numeric (comment/header lines are not supported)") from None
            if lab_val.is_integer() and abs(lab_val) < 2 ** 31:
                # Arbitrary integer labels load (multiclass files train
                # through the CLI's OvR/OvO routing, LibSVM-style; the
                # +-1 convention is just the common binary case).
                # is_integer() is False for inf/nan, and the int32 bound
                # keeps np.asarray(labels, np.int32) exact — both would
                # otherwise escape as OverflowError tracebacks.
                labels.append(int(lab_val))
            else:
                raise ValueError(
                    f"{path}:{lineno}: label {parts[0]!r} is not an "
                    "int32 class label (LIBSVM-format regression "
                    "targets are not supported; convert to CSV)")
            feats = {}
            for tok in parts[1:]:
                idx_s, val_s = tok.split(":")
                idx = int(idx_s)
                if idx < 1:
                    # LIBSVM indices are 1-based; accepting idx=0 here
                    # would write x[i, -1] below (negative indexing) and
                    # silently scramble the last feature column.
                    raise ValueError(
                        f"{path}:{lineno}: feature index {idx} — LIBSVM "
                        "format is 1-based; re-index 0-based files "
                        "before loading")
                feats[idx] = float(val_s)
                max_idx = max(max_idx, idx)
            rows.append(feats)
    d = num_features or max_idx
    x = np.zeros((len(rows), d), np.float32)
    for i, feats in enumerate(rows):
        for idx, val in feats.items():
            if idx <= d:
                x[i, idx - 1] = val
    return x, np.asarray(labels, np.int32)


def libsvm_to_csv(src: str, dst: str, num_features: int | None = None) -> tuple[int, int]:
    """LIBSVM sparse file -> dense reference-format CSV. Returns (n, d)."""
    from dpsvm_tpu.data.loader import save_csv
    x, y = parse_libsvm(src, num_features)
    save_csv(dst, x, y)
    return x.shape


def mnist_to_odd_even(x: np.ndarray, digits: np.ndarray, scale: float = 255.0):
    """Digit labels -> +1 (even) / -1 (odd); pixels scaled by 1/scale —
    the relabelling convert_mnist_to_odd_even.py applies."""
    y = np.where(np.asarray(digits) % 2 == 0, 1, -1).astype(np.int32)
    return (np.asarray(x, np.float32) / scale), y


def mnist_to_odd_even_csv(src: str, dst: str) -> tuple[int, int]:
    """CSV of ``digit,p1,...,p784`` -> reference-format even/odd CSV."""
    from dpsvm_tpu.data.loader import load_csv, save_csv
    x, digits = load_csv(src)
    x, y = mnist_to_odd_even(x * 1.0, digits, scale=255.0)
    save_csv(dst, x, y)
    return x.shape


def main(argv=None) -> int:
    """CLI, matching the reference's scripts being directly runnable
    (scripts/convert_adult.py, scripts/convert_mnist_to_odd_even.py):

        python -m dpsvm_tpu.data.converters adult in.libsvm out.csv
        python -m dpsvm_tpu.data.converters mnist_even_odd in.csv out.csv
    """
    import argparse

    ap = argparse.ArgumentParser(prog="dpsvm_tpu.data.converters",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_a = sub.add_parser("adult",
                         help="sparse LIBSVM -> dense reference CSV")
    p_a.add_argument("src")
    p_a.add_argument("dst")
    p_a.add_argument("--num-features", type=int, default=None,
                     help="pad/clip feature width (default: max index "
                          "seen; the reference pins Adult to 123)")
    p_m = sub.add_parser("mnist_even_odd",
                         help="digit,pixels CSV -> +-1 even/odd CSV "
                              "with pixels scaled /255")
    p_m.add_argument("src")
    p_m.add_argument("dst")
    args = ap.parse_args(argv)
    if args.cmd == "adult":
        n, d = libsvm_to_csv(args.src, args.dst, args.num_features)
    else:
        n, d = mnist_to_odd_even_csv(args.src, args.dst)
    print(f"wrote {args.dst}: {n} rows x {d} features")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
