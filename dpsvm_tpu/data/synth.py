"""Deterministic synthetic datasets for tests and benchmarks.

The reference benchmarks on Adult/MNIST/covtype CSVs that are not shipped
with this repo (the mirror's data blob was stripped); these generators
produce datasets with controlled difficulty so benchmarks are reproducible
offline. Seeded NumPy only — no network, no files.
"""

from __future__ import annotations

import numpy as np


def make_blobs_binary(
    n: int,
    d: int,
    seed: int = 0,
    sep: float = 1.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Two Gaussian blobs with +-1 labels; `sep` controls overlap."""
    rng = np.random.default_rng(seed)
    y = np.where(rng.random(n) < 0.5, 1, -1).astype(np.int32)
    centers = rng.normal(size=(2, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    x = rng.normal(size=(n, d)).astype(np.float32)
    x += np.where(y[:, None] > 0, centers[0] * sep, centers[1] * sep)
    return x.astype(np.float32), y


def make_covtype_like(
    n: int,
    d: int = 54,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Covtype-shaped dense rows with a noisy first-feature decision
    rule — THE generator shared by bench.py's mesh/ooc/fused-round
    legs and the autotune probes (one definition, so a probe verdict
    and a BENCH artifact are measured on bitwise the same data family,
    and the committed seed-0 artifacts stay reproducible)."""
    rng = np.random.default_rng(seed)
    x = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    y = np.where(x[:, 0] + 0.2 * rng.standard_normal(n) > 0,
                 1, -1).astype(np.int32)
    return x, y


def make_mnist_like(
    n: int = 60_000,
    d: int = 784,
    seed: int = 7,
    n_prototypes: int = 20,
    noise: float = 0.1,
    label_flip: float = 0.0,
) -> tuple[np.ndarray, np.ndarray]:
    """An MNIST-even-odd-shaped stand-in: n x d in [0, 1], +-1 labels.

    Built as a mixture of `n_prototypes` smooth class prototypes (mimicking
    digit classes under the even/odd relabelling of
    scripts/convert_mnist_to_odd_even.py) plus pixel noise, so the RBF-SMO
    problem has a nontrivial margin structure and support-vector set, rather
    than being linearly separable.

    The default noise (0.1) is calibrated so pairwise distances give
    non-degenerate RBF values at the reference's MNIST gamma=0.125
    (mean K ~ 3e-2; ~40% of points end up support vectors). Larger noise
    at d=784 pushes all pairwise kernel values to ~0 (Gram ~ identity),
    which makes every point a support vector and the benchmark
    meaningless. Benchmark callers should pin `noise` explicitly.
    """
    rng, x, proto_ids = _mnist_features(n, d, seed, n_prototypes, noise)
    y = np.where(proto_ids % 2 == 0, 1, -1).astype(np.int32)
    if label_flip > 0.0:
        # Label noise is how this generator gets HARDER without touching
        # the feature geometry: raising pixel `noise` at d=784 collapses
        # all RBF values toward 0 (see above), while flipping a seeded
        # fraction of labels makes the problem genuinely non-separable —
        # every flipped point becomes a bound SV and the solver must
        # carve a soft margin around it (bench.py's hard convergence
        # regime).
        flips = rng.random(n) < label_flip
        y = np.where(flips, -y, y).astype(np.int32)
    return x.astype(np.float32), y


def _mnist_features(n, d, seed, n_prototypes, noise):
    """THE mnist-shaped feature geometry, shared by make_mnist_like and
    make_mnist_multiclass so the binary and multiclass benchmarks can
    never drift apart. Returns (rng, x, proto_ids) — rng is handed back
    so callers' extra draws (label flips) stay in the same stream."""
    rng = np.random.default_rng(seed)
    protos = rng.random((n_prototypes, d)).astype(np.float32)
    # Smooth the prototypes a little so nearby "pixels" correlate.
    k = 9
    kernel = np.ones(k, np.float32) / k
    for p in range(n_prototypes):
        protos[p] = np.convolve(protos[p], kernel, mode="same")
    proto_ids = rng.integers(0, n_prototypes, size=n)
    x = protos[proto_ids] + noise * rng.standard_normal((n, d)).astype(np.float32)
    np.clip(x, 0.0, 1.0, out=x)
    return rng, x.astype(np.float32), proto_ids


def make_mnist_multiclass(
    n: int = 60_000,
    d: int = 784,
    seed: int = 7,
    n_prototypes: int = 20,
    noise: float = 0.1,
    n_classes: int = 10,
) -> tuple[np.ndarray, np.ndarray]:
    """The make_mnist_like generator BEFORE the even/odd collapse: the
    same smoothed prototypes and pixel noise (shared _mnist_features),
    labelled by prototype id modulo `n_classes` — a 10-class
    MNIST-shaped stand-in for the multiclass benchmark (the reference
    pre-reduced real MNIST to even/odd offline,
    scripts/convert_mnist_to_odd_even.py; multiclass is THIS
    framework's capability extension)."""
    _, x, proto_ids = _mnist_features(n, d, seed, n_prototypes, noise)
    return x, (proto_ids % n_classes).astype(np.int32)


def make_adult_like(
    n: int = 32_561,
    d: int = 123,
    seed: int = 13,
    n_groups: int = 14,
    flip: float = 0.08,
    imbalance: float = 0.24,
) -> tuple[np.ndarray, np.ndarray]:
    """An Adult-a9a-shaped stand-in: n x d binary 0/1 features, +-1 labels.

    The real Adult encoding (scripts/convert_adult.py + the libsvm a9a
    preprocessing) one-hot expands 14 categorical attributes into 123
    binary columns, with ~24% positive labels. Mimicked here: d columns are
    partitioned into `n_groups` one-hot groups; each class draws each
    group's active column from a class-conditional categorical
    distribution, and a `flip` fraction of rows draw their ENTIRE feature
    vector from the other class's distributions (label noise ->
    non-separable, bound SVs exist at the reference's Adult config c=100
    gamma=0.5, reference Makefile:86).
    """
    rng = np.random.default_rng(seed)
    y = np.where(rng.random(n) < imbalance, 1, -1).astype(np.int32)
    # Per-ROW label noise: a `flip` fraction of rows draw their ENTIRE
    # feature vector from the other class's distributions — genuinely
    # conflicting points, so the problem is non-separable and bound SVs
    # exist (a per-group flip would only blend the classes). Calibrated at
    # flip=0.08, sharpness 4.0: LibSVM at the Adult config gets ~57% SVs,
    # ~98% train accuracy on 3k rows.
    cls = (y > 0).astype(int)
    noisy = rng.random(n) < flip
    cls = np.where(noisy, 1 - cls, cls)
    edges = np.linspace(0, d, n_groups + 1).astype(int)
    x = np.zeros((n, d), np.float32)
    for g in range(n_groups):
        lo, hi = edges[g], edges[g + 1]
        width = hi - lo
        # Sharp class-conditional categorical over this group's columns
        # (sharpness 4.0 keeps within-class rows close so the RBF Gram at
        # gamma=0.5 is far from identity, like the real one-hot data).
        logits = rng.normal(size=(2, width)) * 4.0
        probs = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
        cols = np.empty(n, np.int64)
        for c in (0, 1):
            m = cls == c
            cols[m] = rng.choice(width, size=int(m.sum()), p=probs[c])
        x[np.arange(n), lo + cols] = 1.0
    return x, y
