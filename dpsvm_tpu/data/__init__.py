from dpsvm_tpu.data.loader import load_csv, load_data, save_csv, sniff_format
from dpsvm_tpu.data.synth import (make_adult_like, make_blobs_binary,
                                  make_covtype_like, make_mnist_like)
from dpsvm_tpu.data.converters import (
    libsvm_to_csv,
    mnist_to_odd_even,
    mnist_to_odd_even_csv,
    parse_libsvm,
)

__all__ = [
    "load_csv",
    "load_data",
    "sniff_format",
    "save_csv",
    "make_adult_like",
    "make_blobs_binary",
    "make_covtype_like",
    "make_mnist_like",
    "libsvm_to_csv",
    "mnist_to_odd_even",
    "mnist_to_odd_even_csv",
    "parse_libsvm",
]
