from dpsvm_tpu.data.loader import load_csv, save_csv
from dpsvm_tpu.data.synth import make_blobs_binary, make_mnist_like

__all__ = ["load_csv", "save_csv", "make_blobs_binary", "make_mnist_like"]
