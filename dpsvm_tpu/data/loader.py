"""CSV data IO in the reference's format: ``label,f1,f2,...,fd`` per line.

Reference loader: parse.cpp:10-43 (C++ getline/stoi/stof into a flat
row-major float vector). Here the hot path is a native C++ parser
(native/fastcsv.cpp) loaded through ctypes, with a NumPy fallback; both
honour the same format and the reference's convention that the CLI-declared
(n, d) bound how much is read. Unlike the reference we can also infer the
shape from the file (SURVEY.md section 5.6 lists shape inference as an
intended improvement).
"""

from __future__ import annotations

import numpy as np

from dpsvm_tpu.utils import native


def load_csv(
    path: str,
    num_rows: int | None = None,
    num_features: int | None = None,
    float_labels: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Load ``label,f1,...,fd`` CSV -> (x (n,d) float32, y (n,)).

    Labels are int32 (the reference's +-1 classification convention,
    parse.cpp label stoi) unless ``float_labels`` is set — regression
    targets (SVR) keep the full float32 value.

    num_rows / num_features, when given, must match or bound the file
    contents (the reference requires both and reads exactly num_rows lines,
    parse.cpp:25); when omitted they are inferred.
    """
    # The native parser's ABI returns int32 labels (the reference's
    # convention); float regression targets must take the NumPy path.
    parser = None if float_labels else native.get_fastcsv()
    if parser is not None:
        x, y = parser.parse(path, num_rows)
    else:
        x, y = _load_csv_numpy(path, num_rows)
    if num_features is not None:
        if x.shape[1] < num_features:
            raise ValueError(
                f"{path}: file has {x.shape[1]} features, expected {num_features}")
        x = x[:, :num_features]
    if num_rows is not None and x.shape[0] < num_rows:
        raise ValueError(f"{path}: file has {x.shape[0]} rows, expected {num_rows}")
    y = y.astype(np.float32) if float_labels else y.astype(np.int32)
    return np.ascontiguousarray(x, np.float32), y


def _load_csv_numpy(path: str, num_rows: int | None):
    data = np.loadtxt(path, delimiter=",", dtype=np.float32,
                      max_rows=num_rows, ndmin=2)
    if data.size == 0:
        raise ValueError(f"{path}: empty data file")
    y = data[:, 0]  # float32; load_csv applies the label dtype policy
    x = data[:, 1:]
    return x, y


def save_csv(path: str, x: np.ndarray, y: np.ndarray) -> None:
    """Write the same ``label,f1,...,fd`` format (for tests / converters)."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    with open(path, "w") as fh:
        for i in range(x.shape[0]):
            fh.write(f"{int(y[i])}," + ",".join(repr(float(v)) for v in x[i]) + "\n")
