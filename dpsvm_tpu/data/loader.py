"""CSV data IO in the reference's format: ``label,f1,f2,...,fd`` per line.

Reference loader: parse.cpp:10-43 (C++ getline/stoi/stof into a flat
row-major float vector). Here the hot path is a native C++ parser
(native/fastcsv.cpp) loaded through ctypes, with a NumPy fallback; both
honour the same format and the reference's convention that the CLI-declared
(n, d) bound how much is read. Unlike the reference we can also infer the
shape from the file (SURVEY.md section 5.6 lists shape inference as an
intended improvement).
"""

from __future__ import annotations

import numpy as np

from dpsvm_tpu.utils import native


def sniff_format(path: str, max_lines: int = 32) -> str:
    """Detect "csv" vs "libsvm" from the leading non-empty lines: sparse
    LIBSVM rows carry ``idx:val`` tokens while the reference CSV always
    contains commas (parse.cpp:10-43). Several lines are examined because
    a legal LIBSVM row with no nonzero features is a bare label with
    neither marker; an undecided file (all label-only rows) falls back to
    csv."""
    seen = 0
    with open(path) as fh:
        for line in fh:
            if not line.strip():
                continue
            if ":" in line:
                return "libsvm"
            if "," in line:
                return "csv"
            seen += 1
            if seen >= max_lines:
                break
    return "csv"


def load_data(
    path: str,
    num_rows: int | None = None,
    num_features: int | None = None,
    float_labels: bool = False,
    fmt: str = "auto",
) -> tuple[np.ndarray, np.ndarray]:
    """Format-dispatching loader: the reference's dense CSV or the sparse
    LIBSVM format its prep scripts consume (scripts/convert_adult.py) —
    so `.libsvm`/`a9a`-style files train directly, no offline conversion
    step. fmt: "auto" (sniff), "csv", "libsvm"."""
    if fmt == "auto":
        fmt = sniff_format(path)
    if fmt == "csv":
        return load_csv(path, num_rows, num_features, float_labels)
    if fmt != "libsvm":
        raise ValueError(f"unknown data format {fmt!r} (csv | libsvm | auto)")
    if float_labels:
        raise ValueError(
            "LIBSVM-format regression targets are not supported; convert "
            "to CSV first (data/converters.py libsvm_to_csv converts any "
            "integer-labelled file; non-integer regression targets need "
            "an external conversion)")
    from dpsvm_tpu.data.converters import parse_libsvm

    x, y = parse_libsvm(path, num_features, num_rows=num_rows)
    if num_rows is not None and x.shape[0] < num_rows:
        raise ValueError(
            f"{path}: file has {x.shape[0]} rows, expected {num_rows}")
    return np.ascontiguousarray(x, np.float32), y


def load_csv(
    path: str,
    num_rows: int | None = None,
    num_features: int | None = None,
    float_labels: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Load ``label,f1,...,fd`` CSV -> (x (n,d) float32, y (n,)).

    Labels are int32 (the reference's +-1 classification convention,
    parse.cpp label stoi) unless ``float_labels`` is set — regression
    targets (SVR) keep the full float32 value.

    num_rows / num_features, when given, must match or bound the file
    contents (the reference requires both and reads exactly num_rows lines,
    parse.cpp:25); when omitted they are inferred.
    """
    # The native parser's ABI returns int32 labels (the reference's
    # convention); float regression targets must take the NumPy path.
    parser = None if float_labels else native.get_fastcsv()
    if parser is not None:
        x, y = parser.parse(path, num_rows)
    else:
        x, y = _load_csv_numpy(path, num_rows)
    if num_features is not None:
        if x.shape[1] < num_features:
            raise ValueError(
                f"{path}: file has {x.shape[1]} features, expected {num_features}")
        x = x[:, :num_features]
    if num_rows is not None and x.shape[0] < num_rows:
        raise ValueError(f"{path}: file has {x.shape[0]} rows, expected {num_rows}")
    y = y.astype(np.float32) if float_labels else y.astype(np.int32)
    return np.ascontiguousarray(x, np.float32), y


def _load_csv_numpy(path: str, num_rows: int | None):
    data = np.loadtxt(path, delimiter=",", dtype=np.float32,
                      max_rows=num_rows, ndmin=2)
    if data.size == 0:
        raise ValueError(f"{path}: empty data file")
    y = data[:, 0]  # float32; load_csv applies the label dtype policy
    x = data[:, 1:]
    return x, y


def save_csv(path: str, x: np.ndarray, y: np.ndarray) -> None:
    """Write the same ``label,f1,...,fd`` format (for tests / converters)."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    with open(path, "w") as fh:
        for i in range(x.shape[0]):
            fh.write(f"{int(y[i])}," + ",".join(repr(float(v)) for v in x[i]) + "\n")
