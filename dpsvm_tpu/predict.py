"""Batched jitted inference.

Replaces the reference's CPU tester (seq_test.cpp:187-210: a triple loop of
per-pair CBLAS kernel evaluations, O(n_test * n_sv * d) with no batching)
with one (n_test, d) x (d, n_sv) MXU matmul per block plus a reduction.

Decision convention: f(q) = sum_j alpha_j y_j K(x_j, q) - b (see
models/svm_model.py for how this resolves the reference's bug B5).
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix


@partial(jax.jit, static_argnames=("kp",))
def _decision_batch(q, sv_x, dual_coef, b, kp: KernelParams):
    k = kernel_matrix(q, sv_x, kp)
    return k @ dual_coef - b


def decision_function(model: SVMModel, q, block: int = 8192,
                      precision: str = "float32") -> np.ndarray:
    """f(q_i) for a batch of query points, blocked to bound HBM use.

    precision="float64" evaluates on the host in exact float64 instead —
    REQUIRED for trustworthy signs from extreme-C models: fp32
    accumulation noise over many large-|coef| terms swamps O(1) decision
    values (measured at the covtype stress config: an alpha matching
    LibSVM's SV count to 0.05% read 59% sign agreement under fp32
    evaluation and 99.99% under f64 — PARITY.md). decision_risk() gives
    a cheap a-priori estimate of when this matters;
    precision="auto" consults it and picks the path for you (the
    predict()/accuracy() default, so the PARITY.md 59%-sign-agreement
    footgun is opt-out rather than opt-in).
    """
    if precision == "auto":
        precision = resolve_precision(model)
    if precision == "float64":
        # No fp32 quantization of the queries on the exact path.
        return _decision_f64(model, q, block)
    if precision != "float32":
        raise ValueError(
            "precision must be 'auto', 'float32' or 'float64'")
    q = np.asarray(q, np.float32)
    # Shape bucketing, both operands. XLA executors are shape-keyed and
    # every fitted model has its OWN n_sv: multiclass prediction over k
    # (or k(k-1)/2) models would otherwise compile per model — measured
    # ~4 minutes of compiles for a 45-model OvO predict vs ~5 s of
    # actual evaluation (BENCH_MULTICLASS.md). SVs pad to the next
    # power of two with ZERO dual coefficients (zero contribution, at
    # most 2x padded FLOPs); the final partial query block pads to a
    # power of two the same way.
    n_sv, d = model.sv_x.shape
    m_pad = 1 << max(4, (max(n_sv, 1) - 1).bit_length())
    if m_pad != n_sv:
        sv_p = np.zeros((m_pad, d), np.float32)
        sv_p[:n_sv] = model.sv_x
        coef_p = np.zeros((m_pad,), np.float32)
        coef_p[:n_sv] = model.dual_coef
    else:
        sv_p, coef_p = model.sv_x, model.dual_coef
    sv_x = jnp.asarray(sv_p)
    coef = jnp.asarray(coef_p)
    b = jnp.float32(model.b)
    out = []
    for s in range(0, q.shape[0], block):
        qb = q[s:s + block]
        nb = qb.shape[0]
        nb_pad = 1 << max(4, (nb - 1).bit_length())
        if nb_pad != nb:
            qp = np.zeros((nb_pad, d), np.float32)
            qp[:nb] = qb
            qb = qp
        out.append(np.asarray(
            _decision_batch(jnp.asarray(qb), sv_x, coef, b,
                            model.kernel))[:nb])
    return np.concatenate(out) if out else np.zeros((0,), np.float32)


def _decision_f64(model: SVMModel, q, block: int) -> np.ndarray:
    """Host float64 decision evaluation — the single f64 kernel-algebra
    definition (solver/reconstruct.py gram_matvec_f64) applied at the
    query points."""
    from dpsvm_tpu.solver.reconstruct import gram_matvec_f64

    return gram_matvec_f64(
        model.sv_x, model.dual_coef, model.kernel, block=block,
        queries=np.asarray(q, np.float64)) - model.b


def decision_risk(model: SVMModel) -> float:
    """A-priori estimate of fp32 decision-evaluation noise: the random-
    walk accumulation error sqrt(n_sv) * eps_f32 * rms|coef| (kernel
    values <= O(1)). Compare to the decision margin that matters;
    values approaching ~0.1+ mean fp32 signs near the boundary are
    noise — use decision_function(..., precision='float64'). The
    measured covtype-stress case reads ~4 (59% fp32 sign agreement);
    moderate-C models read ~1e-4."""
    coef = np.asarray(model.dual_coef, np.float64)
    if coef.size == 0:
        return 0.0
    return float(np.sqrt(coef.size) * 2.0 ** -23
                 * np.sqrt(np.mean(coef ** 2)))


# decision_risk above this routes precision='auto' to the exact host
# float64 path. Calibrated between the measured covtype-stress case
# (risk ~4, 59% fp32 sign agreement — PARITY.md) and moderate-C models
# (~1e-4): by the time the random-walk noise estimate reaches 0.1,
# fp32 signs near an O(1) decision boundary are noise.
AUTO_F64_RISK = 0.1


def decision_risk_columns(coef) -> np.ndarray:
    """decision_risk per COLUMN of a (S, k) dual-coefficient matrix (the
    compacted multiclass / serving layout, models/multiclass.py
    CompactedEnsemble): sqrt(nnz_j) * eps_f32 * rms|nonzero coef_j|.
    Vectorized so the serving engine can risk-gate all k submodels in
    one pass."""
    coef = np.asarray(coef, np.float64)
    nnz = np.count_nonzero(coef, axis=0).astype(np.float64)
    sq = np.sum(coef ** 2, axis=0)
    rms = np.sqrt(sq / np.maximum(nnz, 1.0))
    return np.sqrt(nnz) * 2.0 ** -23 * rms


def resolve_precision(model: SVMModel, risk: float = None) -> str:
    """The evaluation path precision='auto' resolves to for this model
    (or for a precomputed `risk`): 'float64' when the a-priori fp32
    noise estimate crosses AUTO_F64_RISK, else 'float32'."""
    if risk is None:
        risk = decision_risk(model)
    return "float64" if risk >= AUTO_F64_RISK else "float32"


def predict(model: SVMModel, q, block: int = 8192,
            precision: str = "auto") -> np.ndarray:
    """Class labels in {-1, +1}. sign(0) maps to +1 (matches the reference's
    `dual >= 0` style checks, seq_test.cpp:199-203). precision defaults
    to 'auto': extreme-|coef| models (decision_risk >= AUTO_F64_RISK)
    evaluate exactly on the host in float64 — required for trustworthy
    labels there (see decision_function / decision_risk); everything
    else takes the fp32 device path unchanged."""
    d = decision_function(model, q, block, precision=precision)
    return np.where(d >= 0, 1, -1).astype(np.int32)


def accuracy(model: SVMModel, q, y, block: int = 8192,
             precision: str = "auto") -> float:
    """Fraction correct — the get_test_accuracy equivalent
    (seq_test.cpp:187-210). precision='auto' as in predict()."""
    pred = predict(model, q, block, precision=precision)
    return float(np.mean(pred == np.asarray(y)))


@functools.lru_cache(maxsize=16)
def _mesh_decision_executor(n_dev: int, kp: KernelParams):
    """Build (once per mesh-width/kernel) the jitted shard_mapped partial
    decision sum. jit caches by function identity, so the closure must not
    be rebuilt per call."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dpsvm_tpu.ops.kernels import kernel_rows, squared_norms
    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         mesh_shard_map)

    mesh = make_data_mesh(n_dev)

    def shard_fn(qb, sv_loc, coef_loc, sv_sq_loc):
        k = kernel_rows(sv_loc, sv_sq_loc, qb, squared_norms(qb), kp)
        return lax.psum(k @ coef_loc, DATA_AXIS)

    mapped = jax.jit(mesh_shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P()))
    return mesh, mapped


def decision_function_mesh(model: SVMModel, q, num_devices=None,
                           block: int = 8192) -> np.ndarray:
    """Mesh-parallel decision function: support vectors are row-sharded
    over the `data` axis (like training's X sharding) and per-device
    partial decision sums are combined with a psum — so inference memory
    also scales with device count. Query batches are replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpsvm_tpu.parallel.mesh import shard_padded_rows

    if num_devices is None:
        num_devices = len(jax.devices())
    mesh, mapped = _mesh_decision_executor(num_devices, model.kernel)
    q = np.asarray(q, np.float32)

    # The padded + sharded SV arrays are cached on the model instance so a
    # serving loop pays the host copies and H2D transfer once, not per call.
    prepared = getattr(model, "_mesh_prepared", None)
    if prepared is not None and prepared[0] == num_devices:
        sv_dev, coef_dev, sv_sq = prepared[1]
    else:
        sv = np.asarray(model.sv_x, np.float32)
        sv_dev = shard_padded_rows(mesh, sv)
        # padded rows have zero weight -> inert
        coef_dev = shard_padded_rows(mesh, model.dual_coef)
        sv_sq = shard_padded_rows(mesh, (sv * sv).sum(1, dtype=np.float32))
        model._mesh_prepared = (num_devices, (sv_dev, coef_dev, sv_sq))

    rep = NamedSharding(mesh, P())

    out = []
    for s in range(0, q.shape[0], block):
        qb = jax.device_put(jnp.asarray(q[s:s + block]), rep)
        out.append(np.asarray(mapped(qb, sv_dev, coef_dev, sv_sq)) - model.b)
    return np.concatenate(out) if out else np.zeros((0,), np.float32)
