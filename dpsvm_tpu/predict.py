"""Batched jitted inference.

Replaces the reference's CPU tester (seq_test.cpp:187-210: a triple loop of
per-pair CBLAS kernel evaluations, O(n_test * n_sv * d) with no batching)
with one (n_test, d) x (d, n_sv) MXU matmul per block plus a reduction.

Decision convention: f(q) = sum_j alpha_j y_j K(x_j, q) - b (see
models/svm_model.py for how this resolves the reference's bug B5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams, kernel_matrix


@partial(jax.jit, static_argnames=("kp",))
def _decision_batch(q, sv_x, dual_coef, b, kp: KernelParams):
    k = kernel_matrix(q, sv_x, kp)
    return k @ dual_coef - b


def decision_function(model: SVMModel, q, block: int = 8192) -> np.ndarray:
    """f(q_i) for a batch of query points, blocked to bound HBM use."""
    q = np.asarray(q, np.float32)
    sv_x = jnp.asarray(model.sv_x)
    coef = jnp.asarray(model.dual_coef)
    b = jnp.float32(model.b)
    out = []
    for s in range(0, q.shape[0], block):
        out.append(np.asarray(
            _decision_batch(jnp.asarray(q[s:s + block]), sv_x, coef, b, model.kernel)))
    return np.concatenate(out) if out else np.zeros((0,), np.float32)


def predict(model: SVMModel, q, block: int = 8192) -> np.ndarray:
    """Class labels in {-1, +1}. sign(0) maps to +1 (matches the reference's
    `dual >= 0` style checks, seq_test.cpp:199-203)."""
    d = decision_function(model, q, block)
    return np.where(d >= 0, 1, -1).astype(np.int32)


def accuracy(model: SVMModel, q, y, block: int = 8192) -> float:
    """Fraction correct — the get_test_accuracy equivalent
    (seq_test.cpp:187-210)."""
    pred = predict(model, q, block)
    return float(np.mean(pred == np.asarray(y)))
