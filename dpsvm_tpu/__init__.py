"""dpsvm_tpu — a TPU-native distributed SVM training framework.

A from-scratch JAX/XLA re-design of the capabilities of DPSVM (a CUDA +
OpenMPI distributed trainer for binary C-SVC via the modified-SMO algorithm;
reference: svmTrainMain.cpp / svmTrain.cu / seq.cpp in aung2phyowai/dpsvm).

Key differences from the reference (by design, TPU-first):

* The entire SMO iteration — working-set selection, kernel-row evaluation,
  alpha update and gradient (f) update — is a single ``jax.jit``-compiled
  ``lax.while_loop`` body on device; there is no per-iteration host
  round-trip (the reference syncs to the host every iteration).
* Distribution uses a ``jax.sharding.Mesh`` + ``shard_map`` over a ``data``
  axis with XLA collectives over ICI; the reference's per-iteration
  ``MPI_Allgather`` of working-set candidates becomes an ``all_gather`` of
  (value, index) pairs inside the compiled step.
* The training matrix X is fully row-sharded across devices (the reference
  replicates X on every GPU); working-set rows are recovered with a masked
  ``psum`` — memory scales with device count.
* The kernel-row LRU cache (reference: cache.cu) is a static-shape HBM
  array with functional (pure) bookkeeping, so it lives inside the jitted
  loop.
"""

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.models.svr import SVRModel, train_svr
from dpsvm_tpu.models.oneclass import OneClassModel, train_oneclass
from dpsvm_tpu.models.nusvm import train_nusvc, train_nusvr
from dpsvm_tpu.train import train
from dpsvm_tpu.predict import (decision_function, decision_risk,
                               predict, accuracy)
from dpsvm_tpu import data


def __getattr__(name):
    # PEP 562 lazy submodule: the estimator facade imports sklearn, which
    # solver-only users (CLI, mesh startup) should never pay for.
    if name == "estimators":
        import importlib
        return importlib.import_module("dpsvm_tpu.estimators")
    raise AttributeError(f"module 'dpsvm_tpu' has no attribute {name!r}")

__version__ = "0.1.0"

__all__ = [
    "SVMConfig",
    "SVMModel",
    "SVRModel",
    "train_svr",
    "OneClassModel",
    "train_oneclass",
    "train_nusvc",
    "train_nusvr",
    "train",
    "decision_function",
    "decision_risk",
    "predict",
    "accuracy",
    "data",
    "estimators",
    "__version__",
]
