"""Environment construction for subprocess re-execs that need a virtual
multi-device CPU JAX platform.

In this image a sitecustomize hook registers the (single-chip, tunneled)
axon TPU backend at interpreter startup, keyed on PALLAS_AXON_POOL_IPS;
once any backend initializes, the platform can no longer be switched
in-process. Every harness that wants an N-device CPU platform therefore
re-execs a child with this cleaned environment. Shared here so the
stripping rules live in exactly one place (used by
__graft_entry__.dryrun_multichip, tools/parity.py,
tools/multihost_check.py).
"""

from __future__ import annotations

import os
from typing import Optional


def cleaned_cpu_env(n_devices: int,
                    base: Optional[dict] = None) -> dict:
    """A copy of `base` (default os.environ) configured so a fresh Python
    child comes up as an ``n_devices``-device CPU JAX platform."""
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the axon startup hook
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    return env
