"""Structured training metrics & profiling hooks.

The reference's observability is printf-only, with the per-iteration
progress print commented out (svmTrainMain.cpp:237-239) and a `logs` dir
that is declared but never written (Makefile:12,68). This module provides
the structured equivalent SURVEY.md section 5.5 calls for: periodic
{iteration, b-gap, SV estimate, cache hit rate, iters/sec} records, an
optional JSONL sink, and jax.profiler trace capture (section 5.1).

NOTE (ISSUE 7): the repo-wide telemetry substrate now lives in
``dpsvm_tpu/obs`` (schema-versioned run logs, bounded registry
metrics, trace spans — enabled via ``config.obs`` / ``--obs`` /
``DPSVM_OBS=1``). This module remains the ``--metrics-jsonl`` callback
surface: a USER-CADENCE progress stream (it forces chunked
observation), whereas the obs run log rides whatever cadence the solve
already has and never changes behavior. ``profile_trace`` remains the
CLI's plain ``--trace-dir`` wrapper for runs without ``--obs``.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO, Optional

import numpy as np


class MetricsLogger:
    """Chunk-cadence metrics recorder; usable as the solver `callback`."""

    def __init__(self, sink: Optional[IO] = None, jsonl_path: Optional[str] = None,
                 print_every: int = 0, lookups_per_iter: int = 2):
        """`lookups_per_iter` is the engine's cache-lookup cadence: the
        per-pair engines (xla/pallas) probe the row cache twice per pair
        update (hi and lo rows, mirroring the reference's two
        lookup_cache calls per iteration, svmTrain.cu:203,238); the block
        engine never probes it (its working-set block is the reuse
        mechanism), so callers pass 0 and the rate reports as 0.0."""
        self.records: list[dict] = []
        self._lookups_per_iter = lookups_per_iter
        self._sink = sink
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._t0 = time.perf_counter()
        self._start_iter: Optional[int] = None  # set by on_start (resume-aware)
        self._last_iter: Optional[int] = None
        self._last_t = self._t0
        self._print_every = print_every

    def on_start(self, start_iter: int) -> None:
        """Called by the solver before the loop with the (possibly resumed)
        starting iteration, so rates don't count pre-resume history."""
        self._start_iter = start_iter
        self._last_iter = start_iter
        self._last_t = time.perf_counter()

    def __call__(self, iteration: int, b_hi: float, b_lo: float, state) -> None:
        now = time.perf_counter()
        if self._last_iter is None:  # solver didn't announce a start
            self._start_iter = self._last_iter = 0
        d_it = iteration - self._last_iter
        d_t = max(now - self._last_t, 1e-9)
        alpha = state.alpha
        hits = int(state.hits)  # counts this run only (not checkpointed)
        this_run_iters = iteration - (self._start_iter or 0)
        rec = {
            "iteration": iteration,
            "b_hi": b_hi,
            "b_lo": b_lo,
            "gap": b_lo - b_hi,
            "sv_estimate": int(np.asarray(alpha > 0).sum()),
            "cache_hits": hits,
            "cache_hit_rate": hits / max(
                self._lookups_per_iter * this_run_iters, 1),
            "iters_per_sec": d_it / d_t,
            "elapsed_sec": now - self._t0,
        }
        self.records.append(rec)
        self._last_iter, self._last_t = iteration, now
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._sink is not None:
            self._sink.write(
                f"iter={iteration} gap={rec['gap']:.6f} "
                f"sv~{rec['sv_estimate']} {rec['iters_per_sec']:.0f} it/s\n")

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """jax.profiler trace around a training run (SURVEY.md 5.1's TPU
    equivalent of the reference's commented-out CycleTimer probes)."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Simple named wall-clock phases with block_until_ready discipline —
    the CycleTimer (CycleTimer.h) role, minus the rdtsc fragility."""

    def __init__(self):
        self.phases: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str, *arrays):
        import jax
        t0 = time.perf_counter()
        try:
            yield
        finally:
            for a in arrays:
                jax.block_until_ready(a)
            self.phases[name] = self.phases.get(name, 0.0) + time.perf_counter() - t0
