"""ctypes bridge to the native runtime components under native/.

Compiles native/fastcsv.cpp on first use with g++ into
native/_build/fastcsv.so and binds it via ctypes (no pybind11 in this
environment). Every native component is optional: if the toolchain or the
shared object is unavailable, callers fall back to pure NumPy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")

_lock = threading.Lock()
_fastcsv_cache: list = []  # [] = untried, [None] = failed, [obj] = loaded


class FastCsv:
    """Typed wrapper over the fastcsv C ABI."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.fastcsv_shape.restype = ctypes.c_int
        lib.fastcsv_shape.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fastcsv_parse.restype = ctypes.c_long
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.fastmodel_write.restype = ctypes.c_long
        lib.fastmodel_write.argtypes = [
            ctypes.c_char_p,
            ctypes.c_float,
            ctypes.c_float,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
        ]

    def shape(self, path: str) -> tuple[int, int]:
        rows = ctypes.c_long()
        fields = ctypes.c_long()
        rc = self._lib.fastcsv_shape(path.encode(), ctypes.byref(rows), ctypes.byref(fields))
        if rc != 0:
            raise IOError(f"fastcsv_shape({path}) failed with code {rc}")
        return rows.value, fields.value

    def write_model(self, path: str, gamma: float, b: float,
                    alpha: np.ndarray, y: np.ndarray, x: np.ndarray) -> None:
        alpha = np.ascontiguousarray(alpha, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        x = np.ascontiguousarray(x, np.float32)
        n_sv, d = x.shape
        rc = self._lib.fastmodel_write(
            path.encode(), ctypes.c_float(gamma), ctypes.c_float(b),
            alpha.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_sv, d)
        if rc < 0:
            raise IOError(f"fastmodel_write({path}) failed with code {rc}")

    def parse(self, path: str, num_rows: int | None = None):
        rows, fields = self.shape(path)
        if num_rows is not None:
            rows = min(rows, num_rows)
        d = fields - 1
        x = np.empty((rows, d), np.float32)
        y = np.empty((rows,), np.int32)
        got = self._lib.fastcsv_parse(
            path.encode(), rows, fields,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        )
        if got < 0:
            raise IOError(f"fastcsv_parse({path}) failed with code {got}")
        return x[:got], y[:got]


def _build_fastcsv() -> str | None:
    src = os.path.join(_NATIVE_DIR, "fastcsv.cpp")
    if not os.path.exists(src):
        return None
    out = os.path.join(_BUILD_DIR, "fastcsv.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    return out


def get_fastcsv() -> FastCsv | None:
    """Return the native parser, building it if needed; None if unavailable."""
    with _lock:
        if not _fastcsv_cache:
            so = _build_fastcsv()
            if so is None:
                _fastcsv_cache.append(None)
            else:
                try:
                    _fastcsv_cache.append(FastCsv(ctypes.CDLL(so)))
                except (OSError, AttributeError):
                    # AttributeError: stale .so missing newer symbols —
                    # every native component must degrade to the
                    # NumPy/Python fallback, never crash the caller.
                    _fastcsv_cache.append(None)
        return _fastcsv_cache[0]
