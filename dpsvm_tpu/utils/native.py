"""ctypes bridge to the native runtime components under native/.

Compiles native/fastcsv.cpp on first use with g++ into
native/_build/fastcsv.so and binds it via ctypes (no pybind11 in this
environment). Every native component is optional: if the toolchain or the
shared object is unavailable, callers fall back to pure NumPy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")

_lock = threading.Lock()
_fastcsv_cache: list = []  # [] = untried, [None] = failed, [obj] = loaded
_seqsmo_cache: list = []


class FastCsv:
    """Typed wrapper over the fastcsv C ABI."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.fastcsv_shape.restype = ctypes.c_int
        lib.fastcsv_shape.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.fastcsv_parse.restype = ctypes.c_long
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.fastmodel_write.restype = ctypes.c_long
        lib.fastmodel_write.argtypes = [
            ctypes.c_char_p,
            ctypes.c_float,
            ctypes.c_float,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
        ]

    def shape(self, path: str) -> tuple[int, int]:
        rows = ctypes.c_long()
        fields = ctypes.c_long()
        rc = self._lib.fastcsv_shape(path.encode(), ctypes.byref(rows), ctypes.byref(fields))
        if rc != 0:
            raise IOError(f"fastcsv_shape({path}) failed with code {rc}")
        return rows.value, fields.value

    def write_model(self, path: str, gamma: float, b: float,
                    alpha: np.ndarray, y: np.ndarray, x: np.ndarray) -> None:
        alpha = np.ascontiguousarray(alpha, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        x = np.ascontiguousarray(x, np.float32)
        n_sv, d = x.shape
        rc = self._lib.fastmodel_write(
            path.encode(), ctypes.c_float(gamma), ctypes.c_float(b),
            alpha.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_sv, d)
        if rc < 0:
            raise IOError(f"fastmodel_write({path}) failed with code {rc}")

    def parse(self, path: str, num_rows: int | None = None):
        rows, fields = self.shape(path)
        if num_rows is not None:
            rows = min(rows, num_rows)
        d = fields - 1
        x = np.empty((rows, d), np.float32)
        y = np.empty((rows,), np.int32)
        got = self._lib.fastcsv_parse(
            path.encode(), rows, fields,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        )
        if got < 0:
            raise IOError(f"fastcsv_parse({path}) failed with code {got}")
        return x[:got], y[:got]


# Portable baseline flags on purpose: -march=native would pin the cached
# .so to the build host's ISA and a mismatch dies with an uncatchable
# SIGILL, violating the degrade-to-fallback contract above.
_CXX_FLAGS = ["-O3", "-shared", "-fPIC", "-std=c++17"]


def _build_so(stem: str) -> str | None:
    """Compile native/<stem>.cpp into native/_build/<stem>.so.

    Rebuilds when the source is newer OR the recorded compile flags differ
    (a sidecar <stem>.so.flags file fingerprints the command, so flag
    changes propagate without touching the source). Returns None on
    failure with the diagnostic recorded in _build_errors (runtime callers
    degrade to the NumPy path; `build_all` surfaces it)."""
    src = os.path.join(_NATIVE_DIR, f"{stem}.cpp")
    if not os.path.exists(src):
        _build_errors[stem] = f"source not found: {src}"
        return None
    out = os.path.join(_BUILD_DIR, f"{stem}.so")
    tag = out + ".flags"
    flags = " ".join(_CXX_FLAGS)
    fresh = os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src)
    if fresh:
        try:
            with open(tag) as fh:
                fresh = fh.read().strip() == flags
        except OSError:
            fresh = False
    if fresh:
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", *_CXX_FLAGS, src, "-o", out]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120, text=True)
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        _build_errors[stem] = f"{' '.join(cmd)}: {e}"
        return None
    if proc.returncode != 0:
        _build_errors[stem] = (
            f"{' '.join(cmd)} exited {proc.returncode}:\n{proc.stderr}")
        return None
    with open(tag, "w") as fh:
        fh.write(flags)
    _build_errors.pop(stem, None)
    return out


_build_errors: dict[str, str] = {}


def build_all() -> list[str]:
    """Build every native component; raises on any failure with the full
    compiler diagnostic (the `make native` entry point — unlike the lazy
    runtime path, a build target must not silently succeed)."""
    built = []
    for stem in ("fastcsv", "seqsmo"):
        so = _build_so(stem)
        if so:
            built.append(so)
    if _build_errors:
        detail = "\n".join(f"[{k}] {v}" for k, v in _build_errors.items())
        raise RuntimeError(f"native build failed:\n{detail}")
    return built


def _build_fastcsv() -> str | None:
    return _build_so("fastcsv")


_KERNEL_KINDS = {"linear": 0, "rbf": 1, "poly": 2, "sigmoid": 3}


class SeqSMO:
    """Typed wrapper over the seqsmo C ABI (native sequential trainer +
    predictor — the seq.cpp / seq_test.cpp runtime roles)."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.seqsmo_train.restype = ctypes.c_long
        lib.seqsmo_train.argtypes = [
            f32p, ctypes.POINTER(ctypes.c_int), ctypes.c_long, ctypes.c_long,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, f32p, f32p, f32p,
        ]
        lib.seqsmo_decision.restype = ctypes.c_long
        lib.seqsmo_decision.argtypes = [
            f32p, f32p, ctypes.c_long, ctypes.c_long,
            ctypes.c_float, ctypes.c_int, ctypes.c_int, ctypes.c_float,
            ctypes.c_float, f32p, ctypes.c_long, f32p,
        ]

    def train(self, x: np.ndarray, y: np.ndarray, *, c: float, gamma: float,
              epsilon: float, tau: float, max_iter: int, kernel: str = "rbf",
              degree: int = 3, coef0: float = 0.0,
              c_neg: float | None = None):
        """Returns (alpha, f, b, b_hi, b_lo, iterations, converged)."""
        x = np.ascontiguousarray(x, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        if x.ndim != 2:
            raise ValueError(f"x must be 2-D (n, d), got shape {x.shape}")
        n, d = x.shape
        if y.shape != (n,):
            raise ValueError(f"y must have shape ({n},), got {y.shape}")
        alpha = np.empty((n,), np.float32)
        f = np.empty((n,), np.float32)
        scalars = np.empty((4,), np.float32)
        f32p = ctypes.POINTER(ctypes.c_float)
        it = self._lib.seqsmo_train(
            x.ctypes.data_as(f32p),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, d, ctypes.c_float(c),
            ctypes.c_float(c if c_neg is None else c_neg),
            ctypes.c_float(gamma),
            ctypes.c_float(epsilon), ctypes.c_float(tau), max_iter,
            _KERNEL_KINDS[kernel], degree, ctypes.c_float(coef0),
            alpha.ctypes.data_as(f32p), f.ctypes.data_as(f32p),
            scalars.ctypes.data_as(f32p))
        if it < 0:
            raise ValueError(f"seqsmo_train failed with code {it}")
        return (alpha, f, float(scalars[0]), float(scalars[1]),
                float(scalars[2]), int(it), bool(scalars[3] > 0))

    def decision(self, sv_x: np.ndarray, coef: np.ndarray, b: float,
                 q: np.ndarray, *, gamma: float, kernel: str = "rbf",
                 degree: int = 3, coef0: float = 0.0) -> np.ndarray:
        sv_x = np.ascontiguousarray(sv_x, np.float32)
        coef = np.ascontiguousarray(coef, np.float32)
        q = np.ascontiguousarray(q, np.float32)
        if sv_x.ndim != 2 or q.ndim != 2:
            raise ValueError(
                f"sv_x and q must be 2-D, got {sv_x.shape} and {q.shape}")
        n_sv, d = sv_x.shape
        if q.shape[1] != d:
            raise ValueError(
                f"q feature dim {q.shape[1]} != support-vector dim {d}")
        if coef.shape != (n_sv,):
            raise ValueError(f"coef must have shape ({n_sv},), got {coef.shape}")
        m = q.shape[0]
        out = np.empty((m,), np.float32)
        f32p = ctypes.POINTER(ctypes.c_float)
        rc = self._lib.seqsmo_decision(
            sv_x.ctypes.data_as(f32p), coef.ctypes.data_as(f32p), n_sv, d,
            ctypes.c_float(gamma), _KERNEL_KINDS[kernel], degree,
            ctypes.c_float(coef0), ctypes.c_float(b),
            q.ctypes.data_as(f32p), m, out.ctypes.data_as(f32p))
        if rc < 0:
            raise ValueError(f"seqsmo_decision failed with code {rc}")
        return out


def get_seqsmo() -> SeqSMO | None:
    """Return the native sequential SMO engine; None if unavailable."""
    with _lock:
        if not _seqsmo_cache:
            so = _build_so("seqsmo")
            if so is None:
                _seqsmo_cache.append(None)
            else:
                try:
                    _seqsmo_cache.append(SeqSMO(ctypes.CDLL(so)))
                except (OSError, AttributeError):
                    _seqsmo_cache.append(None)
        return _seqsmo_cache[0]


def get_fastcsv() -> FastCsv | None:
    """Return the native parser, building it if needed; None if unavailable."""
    with _lock:
        if not _fastcsv_cache:
            so = _build_fastcsv()
            if so is None:
                _fastcsv_cache.append(None)
            else:
                try:
                    _fastcsv_cache.append(FastCsv(ctypes.CDLL(so)))
                except (OSError, AttributeError):
                    # AttributeError: stale .so missing newer symbols —
                    # every native component must degrade to the
                    # NumPy/Python fallback, never crash the caller.
                    _fastcsv_cache.append(None)
        return _fastcsv_cache[0]
