"""Solver-state checkpoint / resume.

The reference has NO training-state checkpointing (SURVEY.md section
5.3: an MPI rank death kills the job and all progress); only the final
model is persisted. Full solver state here is just {alpha, f,
iteration, b_hi, b_lo} plus config, so periodic checkpoints are nearly
free. Stored as .npz, written atomically (tmp + rename).

FORMAT_VERSION history:

* v1 — alpha / f / iteration / b_hi / b_lo / config. ``f`` is the
  EFFECTIVE gradient (the in-core drivers save ``f - f_err``), so a
  compensated resume restarts its Kahan residual at zero — correct,
  but not bit-identical to the uninterrupted trajectory.
* v2 (ISSUE 13) — adds the optional ``f_err`` compensated-residual
  lanes and the block/ooc ``rounds`` counter, the full out-of-core
  driver carry. With raw ``f`` and ``f_err`` both present, an ooc
  resume reproduces the uninterrupted trajectory BITWISE from the
  restore point (tests/test_ooc.py pins it). v1 files still load
  (``f_err`` -> None, ``rounds`` -> 0) for in-core resumes; v2 files
  without ``f_err`` behave exactly like v1. ISSUE 19 rides two more
  OPTIONAL keys on the same version — ``shrink_demoted`` (the ooc
  shrunken stream's endgame demotion is permanent, so a resume must
  not re-enter shrinking the uninterrupted run left) and
  ``shrink_gap`` (the last shrink-cycle-start KKT gap: the stall
  demotion compares successive cycle gaps, so a resume that forgot
  the previous one would skip a demotion the uninterrupted run takes
  and diverge from the bitwise pin) and ``shrink_stall`` (the
  consecutive-stalled-cycle count: demotion needs two stalls in a
  row, so a resume that reset the streak would demote later than the
  uninterrupted run). Absent keys mean "not shrinking" — older files
  resume exactly as before.

Injected-fault coverage (dpsvm_tpu/testing/faults.py): the
``ckpt_truncate`` seam kills a save between the tmp write and the
rename — the previous checkpoint must survive intact, which is the
whole point of the tmp+rename discipline.

DURABILITY (ISSUE 15 satellite): tmp+rename alone survives a killed
PROCESS but not power loss — without an fsync the rename can hit the
disk before the tmp file's data blocks, leaving a correctly-named
checkpoint full of garbage. Every atomic write here fsyncs the tmp
file BEFORE the rename and the parent directory AFTER it (the
directory entry itself must be durable); tests pin the ordering by
monkeypatching ``os.fsync``.

RETENTION (ISSUE 15 satellite): ``SVMConfig.checkpoint_keep = K``
keeps K rotating generations (``path`` newest, ``path.1`` …
``path.(K-1)`` oldest) so a checkpoint corrupted BY the fault being
recovered from still leaves an older restorable generation; resume
falls back to the newest loadable one with a loud warning.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import NamedTuple, Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig

FORMAT_VERSION = 2

#: versions load_checkpoint accepts (newer-than-known fails loudly —
#: silently dropping fields a future writer relied on could corrupt a
#: resume).
_READABLE_VERSIONS = (1, 2)


class CheckpointState(NamedTuple):
    """One loaded checkpoint. ``f_err`` is None for v1 files and
    uncompensated runs; ``rounds`` is 0 where the writer predates it."""

    alpha: np.ndarray
    f: np.ndarray
    iteration: int
    b_hi: float
    b_lo: float
    config: SVMConfig
    f_err: Optional[np.ndarray]
    rounds: int
    format_version: int
    shrink_demoted: bool = False
    shrink_gap: Optional[float] = None
    shrink_stall: int = 0


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: after an os.replace, the rename itself lives
    in the directory entry — without this a power loss can forget the
    rename while keeping the (already-fsynced) file data. Filesystems
    that refuse directory fsync (some network mounts) are skipped:
    they provide no such durability to lose."""
    try:
        dfd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def save_checkpoint(path: str, alpha, f, iteration: int, b_hi: float,
                    b_lo: float, config: SVMConfig, *, f_err=None,
                    rounds: Optional[int] = None,
                    shrink_demoted: Optional[bool] = None,
                    shrink_gap: Optional[float] = None,
                    shrink_stall: Optional[int] = None) -> None:
    """Atomic DURABLE write (tmp + fsync + rename + dir fsync) so
    neither a preemption mid-save nor a power loss right after the
    rename can leave a truncated or garbage checkpoint (fsync-before-
    rename is what makes the rename mean something). ``f_err``/
    ``rounds`` are the v2 extras (the ooc driver's full carry);
    ``shrink_demoted``/``shrink_gap`` the ooc shrunken stream's
    cycle-boundary carry (ISSUE 19); omitted fields are simply absent
    from the file."""
    from dpsvm_tpu.testing import faults

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        payload = dict(
            format_version=FORMAT_VERSION,
            alpha=np.asarray(alpha, np.float32),
            f=np.asarray(f, np.float32),
            iteration=np.int64(iteration),
            b_hi=np.float32(b_hi),
            b_lo=np.float32(b_lo),
            config_json=json.dumps(dataclasses.asdict(config)),
        )
        if f_err is not None:
            payload["f_err"] = np.asarray(f_err, np.float32)
        if rounds is not None:
            payload["rounds"] = np.int64(rounds)
        if shrink_demoted is not None:
            payload["shrink_demoted"] = np.bool_(shrink_demoted)
        if shrink_gap is not None:
            payload["shrink_gap"] = np.float64(shrink_gap)
        if shrink_stall is not None:
            payload["shrink_stall"] = np.int64(shrink_stall)
        with os.fdopen(fd, "wb") as fh:
            np.savez_compressed(fh, **payload)
            # Durability ordering: the tmp file's bytes must be ON
            # DISK before the rename publishes its name (tests pin
            # fsync-before-replace by monkeypatching os.fsync).
            fh.flush()
            os.fsync(fh.fileno())
        # Injected preemption point (ckpt_truncate seam): fires AFTER
        # the tmp bytes exist and BEFORE the rename — the previous
        # checkpoint at `path` must be untouched by the wreckage.
        faults.damage_checkpoint(tmp)
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint_state(path: str) -> CheckpointState:
    """Load any readable checkpoint version into the v2 state shape."""
    z = np.load(path, allow_pickle=False)
    version = int(z["format_version"])
    if version not in _READABLE_VERSIONS:
        raise ValueError(
            f"unsupported checkpoint version {version} (this build "
            f"reads {_READABLE_VERSIONS})")
    config = SVMConfig(**json.loads(str(z["config_json"])))
    return CheckpointState(
        alpha=z["alpha"].astype(np.float32),
        f=z["f"].astype(np.float32),
        iteration=int(z["iteration"]),
        b_hi=float(z["b_hi"]),
        b_lo=float(z["b_lo"]),
        config=config,
        f_err=(z["f_err"].astype(np.float32) if "f_err" in z.files
               else None),
        rounds=int(z["rounds"]) if "rounds" in z.files else 0,
        format_version=version,
        shrink_demoted=(bool(z["shrink_demoted"])
                        if "shrink_demoted" in z.files else False),
        shrink_gap=(float(z["shrink_gap"])
                    if "shrink_gap" in z.files else None),
        shrink_stall=(int(z["shrink_stall"])
                      if "shrink_stall" in z.files else 0),
    )


def load_checkpoint(path: str):
    """Returns (alpha, f, iteration, b_hi, b_lo, config) — the v1
    caller shape, valid for every readable version."""
    st = load_checkpoint_state(path)
    return (st.alpha, st.f, st.iteration, st.b_hi, st.b_lo, st.config)


class CheckpointCorrupt(ValueError):
    """A checkpoint that cannot be trusted (unreadable file or
    non-finite state) — the class the retention fallback skips past;
    COMPATIBILITY refusals (wrong n, wrong hyper-parameters) stay
    plain ValueError and always propagate: they are a caller error an
    older generation would share."""


def _check_integrity(st: CheckpointState, path: str) -> None:
    if not (np.isfinite(st.alpha).all() and np.isfinite(st.f).all()
            and (st.f_err is None or np.isfinite(st.f_err).all())):
        raise CheckpointCorrupt(
            f"checkpoint {path} holds non-finite solver state "
            "(corrupt or hand-edited — this repo's writers never "
            "persist non-finite state); refusing to resume it")


def _validate_restore(st: CheckpointState, path: str,
                      config: SVMConfig, n: int) -> None:
    """Refuse resumes that would silently corrupt the solution (the
    restored gradient f is only valid for the kernel/C it was computed
    under, and only for the same rows)."""
    if st.alpha.shape[0] != n:
        raise ValueError(
            f"checkpoint {path} holds state for n={st.alpha.shape[0]} "
            f"rows, but the current dataset has n={n}")
    _check_integrity(st, path)
    for field in ("c", "gamma", "kernel", "degree", "coef0", "epsilon"):
        if getattr(st.config, field) != getattr(config, field):
            raise ValueError(
                f"checkpoint {path} was written with {field}="
                f"{getattr(st.config, field)!r}, current run uses "
                f"{getattr(config, field)!r}; refusing to resume")


def checkpoint_generations(path: str) -> list:
    """The on-disk retention chain for `path`, NEWEST FIRST: the bare
    path, then the rotated ``.1``/``.2``/… generations
    (PeriodicCheckpointer's keep_last suffixes). Only existing files
    are returned."""
    cands = [path] + [f"{path}.{i}" for i in range(1, 100)]
    return [p for p in cands if os.path.exists(p)]


def resume_solver_state(path: Optional[str], config: SVMConfig, n: int):
    """Load + validate a solver checkpoint for resuming.

    Returns (alpha, f, iteration, b_hi, b_lo) or None when `path` is
    unset or missing. Raises ValueError when the checkpoint belongs to
    a different dataset size or incompatible hyper-parameters."""
    st = resume_state(path, config, n)
    if st is None:
        return None
    return st.alpha, st.f, st.iteration, st.b_hi, st.b_lo


def resume_state(path: Optional[str], config: SVMConfig,
                 n: int) -> Optional[CheckpointState]:
    """The full-carry resume (the ooc driver's entry): the validated
    CheckpointState including the v2 ``f_err``/``rounds`` extras, or
    None when `path` is unset and no generation of it exists.

    RETENTION FALLBACK (ISSUE 15 satellite): an unreadable or
    non-finite newest generation falls back — with a LOUD warning —
    to the next rotated generation (``path.1``, ``path.2``, …); only
    when every existing generation is corrupt does the resume fail.
    Compatibility refusals (wrong n, different hyper-parameters)
    propagate immediately: an older generation of the same run would
    refuse identically."""
    import warnings

    if not path:
        return None
    cands = checkpoint_generations(path)
    if not cands:
        return None
    last_err = None
    for cand in cands:
        try:
            st = load_checkpoint_state(cand)
            _check_integrity(st, cand)
        except ValueError as e:
            # CheckpointCorrupt, bad format_version, truncated npz
            # (np.load raises ValueError/OSError/BadZipFile subclasses
            # of these)…
            warnings.warn(
                f"checkpoint generation {cand!r} is UNUSABLE "
                f"({type(e).__name__}: {e}); trying the next "
                "retention generation", stacklevel=2)
            last_err = e
            continue
        except Exception as e:
            warnings.warn(
                f"checkpoint generation {cand!r} is UNREADABLE "
                f"({type(e).__name__}: {e}); trying the next "
                "retention generation", stacklevel=2)
            last_err = e
            continue
        _validate_restore(st, cand, config, n)
        if cand != path:
            warnings.warn(
                f"RESUMING FROM OLDER CHECKPOINT GENERATION {cand!r} "
                f"(newest {path!r} was missing or corrupt): up to "
                "checkpoint_every iterations of progress are being "
                "redone — expected after a fault that corrupted the "
                "newest generation, alarming otherwise", stacklevel=2)
        return st
    raise ValueError(
        f"every checkpoint generation of {path!r} is unloadable "
        f"({len(cands)} tried); refusing to silently start fresh — "
        f"remove them explicitly to do that (last error: {last_err})"
    ) from last_err


class PeriodicCheckpointer:
    """Chunk-cadence checkpoint trigger shared by all solver backends.

    ``config.checkpoint_keep = K`` (default 1 — the historical
    overwrite-in-place) keeps K rotating generations: each save first
    shifts ``path -> path.1 -> … -> path.(K-1)`` and then writes the
    new state at ``path``, so a save that dies mid-window (the
    ``ckpt_truncate`` seam: tmp written, rename never ran, or worse a
    power loss that mangles the newest file) still leaves an older
    restorable generation for ``resume_state``'s fallback."""

    def __init__(self, path: Optional[str], config: SVMConfig, start_iter: int = 0):
        self.path = path
        self.config = config
        self.every = config.checkpoint_every
        self.keep = getattr(config, "checkpoint_keep", 1)
        self.last = start_iter

    @property
    def active(self) -> bool:
        """Whether this checkpointer can ever save (callers use this to
        skip materialising device arrays on hot paths)."""
        return bool(self.path and self.every > 0)

    def due(self, iteration: int) -> bool:
        return self.active and iteration - self.last >= self.every

    def save(self, iteration: int, alpha, f, b_hi: float, b_lo: float,
             force: bool = False, f_err=None,
             rounds: Optional[int] = None,
             shrink_demoted: Optional[bool] = None,
             shrink_gap: Optional[float] = None,
             shrink_stall: Optional[int] = None) -> bool:
        """Save when the cadence is due, or unconditionally with
        ``force`` (abort exits: the state being stopped at must not
        exist only in memory). ``f_err``/``rounds`` ride through to
        the v2 payload when the caller carries them.

        NON-FINITE STATE IS NEVER PERSISTED: the block/ooc observed
        extrema lag the fold by one round, so the round that blows up
        the gradient would otherwise write a NaN checkpoint under
        finite-looking extrema — and the demotion path would then
        faithfully resume the corruption. Skipping the save keeps the
        LAST GOOD checkpoint as the restore point (the sentinel trips
        one observation later)."""
        if not (self.active and (force or self.due(iteration))):
            return False
        alpha = np.asarray(alpha)
        f = np.asarray(f)
        f_err = None if f_err is None else np.asarray(f_err)
        if not (np.isfinite(alpha).all() and np.isfinite(f).all()
                and (f_err is None or np.isfinite(f_err).all())):
            import warnings

            warnings.warn(
                f"checkpoint at iteration {iteration} SKIPPED: solver "
                "state holds non-finite values (gradient blow-up); the "
                "previous checkpoint is kept as the restore point",
                stacklevel=3)
            return False
        self._rotate()
        save_checkpoint(self.path, alpha, f, iteration, b_hi, b_lo,
                        self.config, f_err=f_err, rounds=rounds,
                        shrink_demoted=shrink_demoted,
                        shrink_gap=shrink_gap,
                        shrink_stall=shrink_stall)
        self.last = iteration
        return True

    def _rotate(self) -> None:
        """Shift the retention chain one slot older (newest last to
        move, so a crash mid-rotation still leaves a contiguous
        newest-first chain for the resume fallback), then prune
        generations past `keep` — stale suffixes left by a reduced
        keep must not become surprise fallback targets."""
        if self.keep > 1 and os.path.exists(self.path):
            for i in range(self.keep - 1, 0, -1):
                src = self.path if i == 1 else f"{self.path}.{i - 1}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i}")
        i = max(self.keep, 1)
        while os.path.exists(f"{self.path}.{i}"):
            os.unlink(f"{self.path}.{i}")
            i += 1
