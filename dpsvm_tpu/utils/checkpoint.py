"""Solver-state checkpoint / resume.

The reference has NO training-state checkpointing (SURVEY.md section 5.3:
an MPI rank death kills the job and all progress); only the final model is
persisted. Full solver state here is just {alpha, f, iteration, b_hi, b_lo}
plus config, so periodic checkpoints are nearly free. Stored as .npz.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig

FORMAT_VERSION = 1


def save_checkpoint(path: str, alpha, f, iteration: int, b_hi: float,
                    b_lo: float, config: SVMConfig) -> None:
    """Atomic write (tmp + rename) so a preemption mid-save never leaves a
    truncated checkpoint."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez_compressed(
                fh,
                format_version=FORMAT_VERSION,
                alpha=np.asarray(alpha, np.float32),
                f=np.asarray(f, np.float32),
                iteration=np.int64(iteration),
                b_hi=np.float32(b_hi),
                b_lo=np.float32(b_lo),
                config_json=json.dumps(dataclasses.asdict(config)),
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def resume_solver_state(path: Optional[str], config: SVMConfig, n: int):
    """Load + validate a solver checkpoint for resuming.

    Returns (alpha, f, iteration, b_hi, b_lo) or None when `path` is unset
    or missing. Raises ValueError when the checkpoint belongs to a
    different dataset size or incompatible hyper-parameters — resuming
    across those would silently corrupt the solution (the restored
    gradient f is only valid for the kernel/C it was computed under).
    """
    if not path or not os.path.exists(path):
        return None
    alpha, f, it, b_hi, b_lo, saved = load_checkpoint(path)
    if alpha.shape[0] != n:
        raise ValueError(
            f"checkpoint {path} holds state for n={alpha.shape[0]} rows, "
            f"but the current dataset has n={n}")
    for field in ("c", "gamma", "kernel", "degree", "coef0", "epsilon"):
        if getattr(saved, field) != getattr(config, field):
            raise ValueError(
                f"checkpoint {path} was written with {field}="
                f"{getattr(saved, field)!r}, current run uses "
                f"{getattr(config, field)!r}; refusing to resume")
    return alpha, f, it, b_hi, b_lo


class PeriodicCheckpointer:
    """Chunk-cadence checkpoint trigger shared by all solver backends."""

    def __init__(self, path: Optional[str], config: SVMConfig, start_iter: int = 0):
        self.path = path
        self.config = config
        self.every = config.checkpoint_every
        self.last = start_iter

    @property
    def active(self) -> bool:
        """Whether this checkpointer can ever save (callers use this to
        skip materialising device arrays on hot paths)."""
        return bool(self.path and self.every > 0)

    def due(self, iteration: int) -> bool:
        return self.active and iteration - self.last >= self.every

    def save(self, iteration: int, alpha, f, b_hi: float, b_lo: float,
             force: bool = False) -> bool:
        """Save when the cadence is due, or unconditionally with
        ``force`` (abort exits: the state being stopped at must not
        exist only in memory)."""
        if not (self.active and (force or self.due(iteration))):
            return False
        save_checkpoint(self.path, np.asarray(alpha), np.asarray(f),
                        iteration, b_hi, b_lo, self.config)
        self.last = iteration
        return True


def load_checkpoint(path: str):
    """Returns (alpha, f, iteration, b_hi, b_lo, config)."""
    z = np.load(path, allow_pickle=False)
    if int(z["format_version"]) != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {int(z['format_version'])}")
    config = SVMConfig(**json.loads(str(z["config_json"])))
    return (z["alpha"].astype(np.float32), z["f"].astype(np.float32),
            int(z["iteration"]), float(z["b_hi"]), float(z["b_lo"]), config)
