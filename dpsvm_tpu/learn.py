"""dpsvm_tpu.learn — the continuous-learning loop (ISSUE 18).

``cli learn`` runs the loop this repo's warm-start machinery exists
for: ingest a row stream, retrain each increment FROM THE PREVIOUS
GENERATION'S SUPPORT VECTORS plus the fresh rows (solver/cascade.py —
which degenerates to one warm-started solve for increments at or under
``--block-rows``), and publish every refreshed generation into a live
serving registry through the admin-thread hot swap — training never
blocks serving, and a scrape mid-swap sees either the old or the new
generation, never neither.

The increment layout is ``concat(prev.sv_x, fresh_rows)`` with the seed
``seed_from_model(prev)`` covering the head — exactly the carry format
solver/warmstart.py documents.  Each generation's pair count is A/B'd
against a cold solve of the same increment (``--cold-baseline``, forced
in ``--smoke``) or against the generation-0 pairs-per-row rate (an
ESTIMATE, flagged as such in the run log) so the ``generation`` obs
events always carry a pairs-saved figure.

Observability: one ``learn`` run-log stream (DPSVM_OBS=1) with a
``generation`` event per refreshed model (gen id, increment rows, seed
SV count, warm pairs, cold pairs / estimate, pairs saved) — surfaced as
the ``learn`` column in ``cli obs report`` — and, when publishing into
a serving engine, per-generation counters on that engine's /metrics
exposition (``learn_generations_total``, ``learn_pairs_total``,
``learn_pairs_saved_total``).

``--smoke`` is the CI shape (make learn_smoke): tiny synthetic drifting
stream, two generations, in-process engine, asserts warm-start saved
pairs > 0 and that a probe request served by the engine succeeds
immediately after the mid-stream hot swap.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Iterator, Optional, Tuple

import numpy as np

__all__ = ["synthetic_stream", "file_stream", "train_generation",
           "run_learn", "run_cli"]


# ----------------------------------------------------------- streams

def synthetic_stream(seed: int, d: int, rows: int, generations: int,
                     drift: float) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Drifting labelled row stream: the true separating direction
    rotates by `drift` radians per generation in the (0, 1) feature
    plane — the covariate-shift shape a deployed model retrains under.
    Yields `generations` increments of (x (rows, d) f32, y (rows,) ±1)."""
    rng = np.random.default_rng(seed)
    for g in range(generations):
        theta = g * float(drift)
        w = np.zeros(d, np.float64)
        w[0], w[1 % d] = np.cos(theta), np.sin(theta)
        x = rng.normal(size=(rows, d)).astype(np.float32)
        margin = x.astype(np.float64) @ w + 0.35 * rng.normal(size=rows)
        y = np.where(margin > 0, 1, -1).astype(np.int32)
        yield x, y


def file_stream(path: str, increment_rows: int
                ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Replay a recorded stream from an .npz with arrays ``x`` (n, d)
    and ``y`` (n,) in successive `increment_rows`-sized chunks (the
    final partial chunk included)."""
    z = np.load(path, allow_pickle=False)
    if "x" not in z or "y" not in z:
        raise ValueError(f"{path}: stream npz needs arrays 'x' and 'y'")
    x = np.asarray(z["x"], np.float32)
    y = np.asarray(z["y"])
    if x.shape[0] != y.shape[0]:
        raise ValueError(f"{path}: x has {x.shape[0]} rows, y {y.shape[0]}")
    uniq = np.unique(y)
    if uniq.shape[0] != 2:
        raise ValueError(f"{path}: learn is binary-only ({uniq.shape[0]} "
                         "classes in y)")
    y_pm = np.where(y == uniq.max(), 1, -1).astype(np.int32)
    for s in range(0, x.shape[0], int(increment_rows)):
        yield x[s:s + increment_rows], y_pm[s:s + increment_rows]


# ----------------------------------------------------------- training

def train_generation(prev_model, x_fresh, y_fresh, config, kp,
                     block_rows: int = 4096,
                     cold_baseline: bool = False,
                     cold_rate: Optional[float] = None):
    """Train one generation.  Generation 0 (prev_model None) is a cold
    solve of the fresh rows; later generations solve the increment
    ``concat(prev SVs, fresh)`` through the warm cascade.  Returns
    ``(model, info)`` where info carries gen accounting: rows, seed_sv,
    pairs, pairs_cold (measured or rate-estimated, ``estimated`` flag),
    pairs_saved, train_seconds."""
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.solver.cascade import cascade_solve
    from dpsvm_tpu.solver.smo import solve
    from dpsvm_tpu.solver.warmstart import seed_from_model

    t0 = time.perf_counter()
    if prev_model is None:
        res = solve(x_fresh, y_fresh, config)
        model = SVMModel.from_dense(x_fresh, y_fresh, res.alpha, res.b, kp)
        info = {"rows": int(x_fresh.shape[0]), "seed_sv": 0,
                "pairs": int(res.iterations),
                "pairs_cold": int(res.iterations), "pairs_saved": 0,
                "estimated": False, "sv": int(model.sv_x.shape[0]),
                "train_seconds": time.perf_counter() - t0}
        return model, info

    x_inc = np.concatenate([np.asarray(prev_model.sv_x, np.float32),
                            np.asarray(x_fresh, np.float32)])
    y_inc = np.concatenate([np.asarray(prev_model.sv_y, np.int32),
                            np.asarray(y_fresh, np.int32)])
    seed = seed_from_model(prev_model)
    res, st = cascade_solve(x_inc, y_inc, config, seed=seed,
                            block_rows=block_rows)
    pairs = int(st["total_iterations"])
    warm_seconds = time.perf_counter() - t0
    if cold_baseline:
        cold = solve(x_inc, y_inc, config)
        pairs_cold, estimated = int(cold.iterations), False
    else:
        # No baseline solve: estimate from the caller-tracked cold
        # pairs-per-row rate (generation 0's). Flagged — an estimate
        # must never read as a measurement downstream.
        rate = cold_rate if cold_rate else 1.0
        pairs_cold, estimated = int(round(rate * x_inc.shape[0])), True
    model = SVMModel.from_dense(x_inc, y_inc, res.alpha, res.b, kp)
    info = {"rows": int(x_inc.shape[0]),
            "seed_sv": int(prev_model.sv_x.shape[0]),
            "pairs": pairs, "pairs_cold": pairs_cold,
            "pairs_saved": pairs_cold - pairs, "estimated": estimated,
            "sv": int(model.sv_x.shape[0]),
            "train_seconds": warm_seconds}
    return model, info


# ----------------------------------------------------------- the loop

def run_learn(stream, config, model_dir: str, kp, block_rows: int = 4096,
              cold_baseline: bool = False, engine=None,
              model_name: str = "learn", probe_rows: int = 8,
              on_generation=None) -> dict:
    """Drive the loop over `stream` (an iterator of (x, y) increments).

    Publishes generation g's model file into `engine` (a
    serving.ServingEngine) when given: ``register`` for generation 0,
    the admin-thread ``swap`` for every later generation, and a probe
    ``submit``/``drain`` after each publish proving the engine serves
    across the swap.  Returns the loop summary dict."""
    from dpsvm_tpu.obs import run_obs

    obs = run_obs("learn", config,
                  meta={"engine": "learn", "block_rows": int(block_rows),
                        "cold_baseline": bool(cold_baseline),
                        "serving": engine is not None})
    os.makedirs(model_dir, exist_ok=True)
    model, cold_rate = None, None
    gens = []
    pairs_total = saved_total = 0
    try:
        for g, (x_fresh, y_fresh) in enumerate(stream):
            if x_fresh.shape[0] == 0:
                continue
            model, info = train_generation(
                model, x_fresh, y_fresh, config, kp,
                block_rows=block_rows, cold_baseline=cold_baseline,
                cold_rate=cold_rate)
            if g == 0:
                cold_rate = info["pairs"] / max(1, info["rows"])
            path = os.path.join(model_dir, f"gen_{g:04d}.npz")
            model.save(path)
            info["gen"] = g
            info["path"] = path
            pairs_total += info["pairs"]
            saved_total += max(0, info["pairs_saved"]) if g else 0
            if engine is not None:
                if g == 0:
                    engine.register(model_name, path)
                else:
                    engine.swap(model_name, path)
                # Serving probe: the generation is only "published" if
                # the engine actually serves it — a decision row back
                # from the freshly-swapped model, not just a registry
                # pointer flip.
                xp = np.asarray(x_fresh[:probe_rows], np.float32)
                t = engine.submit(xp, model=model_name)
                out = engine.drain().get(t)
                info["probe_verdict"] = out.verdict if out else "lost"
                engine.metrics.counter("learn.generations_total").add(1)
                engine.metrics.counter("learn.pairs_total").add(
                    info["pairs"])
                engine.metrics.counter("learn.pairs_saved_total").add(
                    max(0, info["pairs_saved"]))
            obs.event("generation", gen=g, rows=info["rows"],
                      seed_sv=info["seed_sv"], sv=info["sv"],
                      pairs=info["pairs"], pairs_cold=info["pairs_cold"],
                      pairs_saved=info["pairs_saved"],
                      estimated=info["estimated"])
            gens.append(info)
            if on_generation is not None:
                on_generation(g, model, info)
        summary = {"generations": len(gens), "pairs_total": pairs_total,
                   "pairs_saved_total": saved_total, "gens": gens,
                   "model_dir": model_dir}
        obs.finish(generations=len(gens), pairs=pairs_total,
                   pairs_saved=saved_total, converged=True)
        return summary
    except BaseException:
        obs.finish(aborted=True)
        raise


# ----------------------------------------------------------- CLI

def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dpsvm-tpu learn",
        description="continuous-learning loop: warm-start retraining "
                    "from the previous generation's support vectors, "
                    "published into a live serving registry")
    src = p.add_argument_group("stream")
    src.add_argument("--stream", default=None,
                     help=".npz with arrays x, y to replay as the row "
                          "stream (default: synthetic drifting stream)")
    src.add_argument("--increment-rows", type=int, default=512,
                     help="rows per increment when replaying --stream")
    src.add_argument("--generations", type=int, default=4)
    src.add_argument("--rows", type=int, default=512,
                     help="fresh rows per synthetic generation")
    src.add_argument("--d", type=int, default=16)
    src.add_argument("--drift", type=float, default=0.1,
                     help="radians the synthetic decision boundary "
                          "rotates per generation")
    src.add_argument("--seed", type=int, default=0)
    slv = p.add_argument_group("solver")
    slv.add_argument("--c", type=float, default=1.0)
    slv.add_argument("--gamma", type=float, default=None,
                     help="RBF gamma (default: 1/d)")
    slv.add_argument("--kernel", default="rbf")
    slv.add_argument("--tol", type=float, default=1e-3)
    slv.add_argument("--max-iter", type=int, default=200_000)
    slv.add_argument("--block-rows", type=int, default=4096,
                     help="cascade block size; increments at or under "
                          "it run as one warm solve")
    slv.add_argument("--cold-baseline", action="store_true",
                     help="also cold-solve each increment to MEASURE "
                          "pairs saved (default: estimate from the "
                          "gen-0 rate)")
    out = p.add_argument_group("publish")
    out.add_argument("--model-dir", default=None,
                     help="directory for per-generation model .npz "
                          "(default: ./learn_models)")
    out.add_argument("--serve", action="store_true",
                     help="publish generations into an in-process "
                          "serving engine via hot swap")
    out.add_argument("--metrics-port", type=int, default=None,
                     help="with --serve: OpenMetrics endpoint port "
                          "(0 = ephemeral)")
    out.add_argument("--json", action="store_true",
                     help="print the loop summary as JSON")
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: tiny drifting stream, two "
                        "generations, in-process engine, asserts "
                        "pairs saved > 0 and the post-swap probe "
                        "serves")
    return p


def run_cli(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    from dpsvm_tpu.config import ServeConfig, SVMConfig
    from dpsvm_tpu.ops.kernels import KernelParams

    if args.smoke:
        args.generations, args.rows, args.d = 2, 240, 6
        args.drift = max(args.drift, 0.1)
        args.cold_baseline = True
        args.serve = True
    gamma = args.gamma if args.gamma is not None else 1.0 / args.d
    cfg = SVMConfig(c=args.c, kernel=args.kernel, gamma=gamma,
                    epsilon=args.tol, max_iter=args.max_iter)
    kp = KernelParams(cfg.kernel, gamma, cfg.degree, cfg.coef0)

    if args.stream:
        stream = file_stream(args.stream, args.increment_rows)
    else:
        stream = synthetic_stream(args.seed, args.d, args.rows,
                                  args.generations, args.drift)
    model_dir = args.model_dir or os.path.join(os.getcwd(), "learn_models")

    engine = None
    if args.serve:
        from dpsvm_tpu.serving import ServingEngine

        engine = ServingEngine(ServeConfig(
            buckets=(64,), metrics_port=args.metrics_port))
    try:
        summary = run_learn(stream, cfg, model_dir, kp,
                            block_rows=args.block_rows,
                            cold_baseline=args.cold_baseline,
                            engine=engine)
    finally:
        if engine is not None:
            engine.close()

    for info in summary["gens"]:
        tag = "" if not info["estimated"] else " (est)"
        probe = (f" probe={info['probe_verdict']}"
                 if "probe_verdict" in info else "")
        print(f"gen {info['gen']}: rows={info['rows']} "
              f"seed_sv={info['seed_sv']} sv={info['sv']} "
              f"pairs={info['pairs']} cold={info['pairs_cold']}{tag} "
              f"saved={info['pairs_saved']}{probe}")
    print(f"learn: {summary['generations']} generations, "
          f"{summary['pairs_total']} pairs, "
          f"{summary['pairs_saved_total']} saved vs cold")
    if args.json:
        print(json.dumps(summary, default=str))

    if args.smoke:
        warm_gens = [i for i in summary["gens"] if i["gen"] > 0]
        assert warm_gens, "smoke needs at least one warm generation"
        saved = sum(i["pairs_saved"] for i in warm_gens)
        assert saved > 0, (
            f"warm-start smoke: expected pairs saved > 0 vs the "
            f"measured cold baseline, got {saved}")
        assert all(i.get("probe_verdict") == "ok" for i in warm_gens), (
            "post-swap serving probe failed: "
            + str([i.get("probe_verdict") for i in warm_gens]))
        print("learn smoke PASS: warm start saved "
              f"{saved} pairs across {len(warm_gens)} warm generation(s), "
              "post-swap probes ok")
    return 0


if __name__ == "__main__":
    sys.exit(run_cli())
