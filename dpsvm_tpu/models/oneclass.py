"""One-class SVM (novelty detection) on the same SMO engine.

No reference equivalent — capability extension via the Scholkopf nu-OCSVM
dual, which in the engine's generic form (min 1/2 a^T Q a + p^T a,
y in {+-1}, Q_ij = y_i y_j K_ij) is simply:

    y_i = +1 for all i,  p = 0,  0 <= a_i <= 1,  sum a_i = nu * n

The equality constraint's value is set by the START point (pair updates
conserve sum(alpha * y)): alpha_init puts the first floor(nu*n) points at
the upper bound and the fractional remainder on the next point — LibSVM's
own initialization. Since p = 0, the optimality indicator starts at
f_init = y * Q alpha_init = K @ alpha_init, one MXU matmul against the
initially-active columns.

Decision: g(q) = sum_i a_i K(x_i, q) - rho with rho = (b_lo + b_hi)/2 from
the engine (same convention as the classifier b); q is an inlier when
g(q) >= 0. Matches sklearn/LibSVM's decision_function = sum coef K - rho.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams, blocked_kernel_matvec
from dpsvm_tpu.solver.result import SolveResult


@dataclasses.dataclass
class OneClassModel:
    """Trained novelty detector: g(q) = sum_i coef_i K(x_i, q) - rho."""

    sv_x: np.ndarray  # (n_sv, d)
    coef: np.ndarray  # (n_sv,) alpha_i in (0, 1]
    rho: float
    kernel: KernelParams

    @property
    def n_sv(self) -> int:
        return int(self.sv_x.shape[0])

    def as_classifier_model(self) -> SVMModel:
        """View as an SVMModel (all-positive coefficients, b = rho) so the
        batched/mesh decision machinery in predict.py applies verbatim."""
        return SVMModel(sv_x=self.sv_x, sv_alpha=self.coef,
                        sv_y=np.ones(self.n_sv, np.int32), b=self.rho,
                        kernel=self.kernel)

    def decision_function(self, q, block: int = 8192) -> np.ndarray:
        from dpsvm_tpu.predict import decision_function
        return decision_function(self.as_classifier_model(), q, block)

    def predict(self, q, block: int = 8192) -> np.ndarray:
        """+1 = inlier, -1 = outlier (sklearn convention)."""
        return np.where(self.decision_function(q, block) >= 0, 1, -1).astype(np.int32)

    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            raise ValueError("one-class models use the .npz format")
        np.savez_compressed(
            path, format_version=1, model_type="oneclass",
            sv_x=self.sv_x, coef=self.coef, rho=np.float32(self.rho),
            **self.kernel.npz_fields())

    @classmethod
    def load(cls, path: str) -> "OneClassModel":
        z = np.load(path, allow_pickle=False)
        if str(z.get("model_type", "")) != "oneclass":
            raise ValueError(f"{path}: not a one-class model")
        return cls(
            sv_x=z["sv_x"].astype(np.float32),
            coef=z["coef"].astype(np.float32),
            rho=float(z["rho"]),
            kernel=KernelParams.from_npz(z))


def train_oneclass(
    x,
    nu: float = 0.5,
    config: SVMConfig = SVMConfig(),
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> tuple[OneClassModel, SolveResult]:
    """Fit nu-one-class SVM: nu bounds the outlier fraction from above and
    the SV fraction from below. config.c is ignored (the OCSVM box is
    [0, 1]); config.epsilon remains the convergence tolerance."""
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(one-class has no labels to pair with kernel rows); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    import jax

    x = np.asarray(x, np.float32)
    n, d = x.shape
    if not 0.0 < nu <= 1.0:
        raise ValueError("nu must be in (0, 1]")

    l = int(nu * n)
    alpha0 = np.zeros((n,), np.float32)
    alpha0[:l] = 1.0
    if l < n:
        alpha0[l] = nu * n - l

    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    f_init = blocked_kernel_matvec(x, alpha0, kp, config.dtype)
    y = np.ones((n,), np.int32)
    # The OCSVM box is exactly [0, 1]: neutralize the class weights along
    # with c, else weight_pos would silently rescale the box below the
    # alpha_init values and break the sum(alpha) = nu*n constraint.
    cfg = config.replace(c=1.0, weight_pos=1.0, weight_neg=1.0)

    if backend == "auto":
        backend = "mesh" if (num_devices or len(jax.devices())) > 1 else "single"
    if backend == "single":
        from dpsvm_tpu.solver.smo import solve
        result = solve(x, y, cfg, callback=callback,
                       alpha_init=alpha0, f_init=f_init,
                       checkpoint_path=checkpoint_path, resume=resume)
    elif backend == "mesh":
        from dpsvm_tpu.parallel.dist_smo import solve_mesh
        result = solve_mesh(x, y, cfg, num_devices=num_devices,
                            callback=callback, alpha_init=alpha0, f_init=f_init,
                            checkpoint_path=checkpoint_path, resume=resume)
    else:
        raise ValueError(f"unknown backend {backend!r} (one-class supports "
                         "'auto' | 'single' | 'mesh')")

    mask = result.alpha > 0
    model = OneClassModel(
        sv_x=np.ascontiguousarray(x[mask], np.float32),
        coef=result.alpha[mask].astype(np.float32),
        rho=float(result.b),
        kernel=kp)
    return model, result
