"""Epsilon-SVR (support-vector regression) on the same SMO engine.

No reference equivalent (the reference trains binary C-SVC only) — this is
a capability extension using the standard LibSVM reduction: the SVR dual

    min 1/2 (a - a*)^T K (a - a*) + eps sum(a + a*) - z^T (a - a*)
    s.t. sum(a - a*) = 0,  0 <= a_i, a*_i <= C

is the generic SMO problem over 2n variables with the feature rows
duplicated, pseudo-labels y = [+1]*n ++ [-1]*n (which makes
Q_ij = y_i y_j K_ij the required [[K, -K], [-K, K]] block structure), and
linear term p = [eps - z; eps + z]. The engine's optimality indicator
f = y * (Q alpha + p) therefore starts at f_init = [eps - z; -eps - z]
instead of -y, which is exactly the hook solver.smo.solve exposes; every
other part of the pipeline — working-set selection, the alpha-pair update,
kernel-row evaluation, mesh sharding — is reused unchanged.

The duplicated feature matrix costs 2x memory and 2x kernel-row time
versus an index-mapped formulation (a (2n)-problem kernel row is the
n-problem row tiled twice); acceptable because SVR problems are typically
much smaller than the classification workloads the engine is sized for.

Prediction: z_hat(q) = sum_i coef_i K(x_i, q) - b with
coef_i = a_i - a*_i, sharing the classifier's decision convention
(models/svm_model.py), so all of predict.py works on the flattened model.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.solver.result import SolveResult


@dataclasses.dataclass
class SVRModel:
    """Trained regressor: z_hat(q) = sum_i coef_i K(x_i, q) - b."""

    sv_x: np.ndarray  # (n_sv, d)
    coef: np.ndarray  # (n_sv,) signed dual coefficients a_i - a*_i, != 0
    b: float
    kernel: KernelParams

    @property
    def n_sv(self) -> int:
        return int(self.sv_x.shape[0])

    def as_classifier_model(self) -> SVMModel:
        """View as an SVMModel (sv_alpha = |coef|, sv_y = sign(coef)) so the
        batched/mesh decision machinery in predict.py applies verbatim."""
        sign = np.where(self.coef >= 0, 1, -1).astype(np.int32)
        return SVMModel(sv_x=self.sv_x, sv_alpha=np.abs(self.coef),
                        sv_y=sign, b=self.b, kernel=self.kernel)

    def predict(self, q, block: int = 8192) -> np.ndarray:
        """Regression estimates for query rows."""
        from dpsvm_tpu.predict import decision_function
        return decision_function(self.as_classifier_model(), q, block)

    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            raise ValueError("SVR models use the .npz format (the reference "
                             "text format encodes a classifier)")
        np.savez_compressed(
            path, format_version=1, model_type="svr",
            sv_x=self.sv_x, coef=self.coef, b=np.float32(self.b),
            **self.kernel.npz_fields())

    @classmethod
    def load(cls, path: str) -> "SVRModel":
        z = np.load(path, allow_pickle=False)
        if str(z.get("model_type", "")) != "svr":
            raise ValueError(f"{path}: not an SVR model")
        return cls(
            sv_x=z["sv_x"].astype(np.float32),
            coef=z["coef"].astype(np.float32),
            b=float(z["b"]),
            kernel=KernelParams.from_npz(z))


def train_svr(
    x,
    z,
    config: SVMConfig = SVMConfig(),
    svr_epsilon: float = 0.1,
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> tuple[SVRModel, SolveResult]:
    """Train epsilon-SVR: fit z ~ f(x) within an `svr_epsilon` tube.

    `config.epsilon` remains the SMO convergence tolerance; the tube width
    is this function's `svr_epsilon` (LibSVM's -p vs -e distinction).
    """
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(epsilon-SVR doubles the variable set); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    import jax

    x = np.asarray(x, np.float32)
    z = np.asarray(z, np.float32)
    n, d = x.shape
    if z.shape != (n,):
        raise ValueError(f"targets must be shape ({n},), got {z.shape}")
    if svr_epsilon < 0:
        raise ValueError("svr_epsilon must be >= 0")

    x2 = np.vstack([x, x])
    y2 = np.concatenate([np.ones(n, np.int32), -np.ones(n, np.int32)])
    f_init = np.concatenate([svr_epsilon - z, -svr_epsilon - z]).astype(np.float32)
    # SVR has a single C: the synthetic +-1 labels of the 2n-variable
    # expansion are bookkeeping, not classes, so class weights must not
    # asymmetrically bound the alpha vs alpha* halves.
    config = config.replace(weight_pos=1.0, weight_neg=1.0)

    if backend == "auto":
        backend = "mesh" if (num_devices or len(jax.devices())) > 1 else "single"
    if backend == "single":
        from dpsvm_tpu.solver.smo import solve
        result = solve(x2, y2, config, callback=callback, f_init=f_init,
                       checkpoint_path=checkpoint_path, resume=resume)
    elif backend == "mesh":
        from dpsvm_tpu.parallel.dist_smo import solve_mesh
        result = solve_mesh(x2, y2, config, num_devices=num_devices,
                            callback=callback, f_init=f_init,
                            checkpoint_path=checkpoint_path, resume=resume)
    else:
        raise ValueError(f"unknown backend {backend!r} (svr supports "
                         "'auto' | 'single' | 'mesh')")

    coef = result.alpha[:n] - result.alpha[n:]
    mask = coef != 0
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    model = SVRModel(
        sv_x=np.ascontiguousarray(x[mask], np.float32),
        coef=coef[mask].astype(np.float32),
        b=float(result.b),
        kernel=kp)
    return model, result
