"""Model container for precomputed-kernel C-SVC (LibSVM -t 4).

A precomputed-kernel model cannot carry SV feature rows (there are none;
the trainer consumed the user's Gram matrix directly — the reference CLI
heritage's -t 4 role, svmTrainMain.cpp:46-58 via LibSVM). It carries the
SUPPORT INDICES into the training set instead; prediction consumes rows
of K(test, train) and gathers the support columns, exactly how LibSVM's
svm-predict treats precomputed test files.
"""

from __future__ import annotations

import numpy as np


class PrecomputedSVCModel:
    """Binary C-SVC trained on a user-supplied Gram matrix.

    Attributes:
      sv_idx:  (n_sv,) int32 — indices of the support vectors into the
               TRAINING set (= the Gram column ids prediction gathers)
      coef:    (n_sv,) float32 — alpha_i * y_i at the support indices
      b:       float bias (decision = K[:, sv_idx] @ coef - b)
      n_train: training-set size (the width prediction inputs must have)
    """

    def __init__(self, sv_idx, coef, b: float, n_train: int):
        self.sv_idx = np.asarray(sv_idx, np.int32)
        self.coef = np.asarray(coef, np.float32)
        self.b = float(b)
        self.n_train = int(n_train)

    @classmethod
    def from_solution(cls, y, alpha, b: float) -> "PrecomputedSVCModel":
        y = np.asarray(y, np.float32)
        alpha = np.asarray(alpha, np.float32)
        idx = np.nonzero(alpha > 0)[0]
        return cls(idx, alpha[idx] * y[idx], b, len(y))

    @property
    def n_sv(self) -> int:
        return int(self.sv_idx.size)

    def decision_function(self, k_rows) -> np.ndarray:
        """Decision values from K(query, train) rows: (m, n_train) ->
        (m,). Only the support columns are read."""
        k_rows = np.asarray(k_rows, np.float32)
        if k_rows.ndim != 2 or k_rows.shape[1] != self.n_train:
            raise ValueError(
                f"precomputed prediction needs K(query, train) rows of "
                f"width {self.n_train}, got {k_rows.shape}")
        return k_rows[:, self.sv_idx].astype(np.float64) @ \
            self.coef.astype(np.float64) - self.b

    def predict(self, k_rows) -> np.ndarray:
        return np.where(self.decision_function(k_rows) >= 0, 1, -1) \
            .astype(np.int32)

    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            raise ValueError(
                "precomputed models use the .npz format (the reference "
                "text format stores SV feature rows, which do not exist)")
        np.savez(path, model_type="precomputed_svc",
                 sv_idx=self.sv_idx, coef=self.coef,
                 b=np.float32(self.b), n_train=np.int32(self.n_train))

    @classmethod
    def load(cls, path: str) -> "PrecomputedSVCModel":
        z = np.load(path, allow_pickle=False)
        if str(z.get("model_type", "")) != "precomputed_svc":
            raise ValueError(f"{path} is not a precomputed-kernel model")
        return cls(z["sv_idx"], z["coef"], float(z["b"]), int(z["n_train"]))
