from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.models.multiclass import (
    MulticlassSVM,
    accuracy_multiclass,
    predict_multiclass,
    train_multiclass,
)
from dpsvm_tpu.models.svr import SVRModel, train_svr
from dpsvm_tpu.models.oneclass import OneClassModel, train_oneclass

__all__ = [
    "SVMModel",
    "MulticlassSVM",
    "train_multiclass",
    "predict_multiclass",
    "accuracy_multiclass",
    "SVRModel",
    "train_svr",
    "OneClassModel",
    "train_oneclass",
]
