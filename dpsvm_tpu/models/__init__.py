from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.models.multiclass import (
    MulticlassSVM,
    accuracy_multiclass,
    predict_multiclass,
    train_multiclass,
)

__all__ = [
    "SVMModel",
    "MulticlassSVM",
    "train_multiclass",
    "predict_multiclass",
    "accuracy_multiclass",
]
