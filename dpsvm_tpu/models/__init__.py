from dpsvm_tpu.models.svm_model import SVMModel

__all__ = ["SVMModel"]
