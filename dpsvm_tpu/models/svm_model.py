"""Trained-model container and serialization.

The reference's model artifact (written by write_out_model,
svmTrainMain.cpp:386-416) is a text file:

    line 1:  gamma
    line 2:  b                      (distributed writer only)
    line 3+: alpha_i,y_i,x_i1,...,x_id   for every alpha_i != 0

with three format skews between its writers/readers (SURVEY.md bug B6:
seq.cpp:302 omits b; seq_test.cpp:267 assumes a 1-line header;
seq_test.cpp:197 ignores b at predict time). This module defines ONE
canonical behavior:

* ``save``/``load`` with a ``.txt`` path speak the distributed writer's
  2-line-header text format (gamma, b, then SV rows) and tolerate the seq
  writer's 1-line header on load, so models written by the reference can be
  consumed here.
* ``save``/``load`` with ``.npz`` use a richer binary format that also
  round-trips kernel family/degree/coef0 (the text format can only express
  RBF).
* The decision function is the standard modified-SMO convention
  f(q) = sum_j alpha_j y_j K(x_j, q) - b with b = (b_lo + b_hi)/2 —
  matching the reference trainer's own accuracy check (svmTrain.cu:652),
  resolving bug B5 in favor of the standard.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from dpsvm_tpu.ops.kernels import KernelParams


@dataclasses.dataclass
class SVMModel:
    sv_x: np.ndarray  # (n_sv, d) support vectors
    sv_alpha: np.ndarray  # (n_sv,) alpha_i > 0
    sv_y: np.ndarray  # (n_sv,) labels in {-1, +1}
    b: float
    kernel: KernelParams
    # Platt calibration plane (LibSVM -b 1; no reference equivalent):
    # P(y=+1 | f) = sigmoid(prob_a * f + prob_b), fit by models/platt.py.
    # None = uncalibrated. Carried by the .npz format only (the text
    # format is the reference's, svmTrainMain.cpp:386-416).
    prob_a: float | None = None
    prob_b: float | None = None

    @property
    def has_probability(self) -> bool:
        return self.prob_a is not None

    def predict_proba(self, q) -> np.ndarray:
        """P(y=+1) per row of q (requires Platt calibration)."""
        if not self.has_probability:
            raise ValueError(
                "model carries no Platt calibration; train with "
                "probability (cli: -b 1) first")
        from dpsvm_tpu.models.platt import platt_probability
        from dpsvm_tpu.predict import decision_function

        return platt_probability(decision_function(self, q),
                                 self.prob_a, self.prob_b)

    @property
    def n_sv(self) -> int:
        return int(self.sv_x.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.sv_x.shape[1])

    @property
    def dual_coef(self) -> np.ndarray:
        """alpha_j * y_j, the weights of the decision sum."""
        return (self.sv_alpha * self.sv_y).astype(np.float32)

    @classmethod
    def from_dense(cls, x, y, alpha, b, kernel: KernelParams) -> "SVMModel":
        """Extract support vectors (alpha > 0) from full training arrays.

        Equivalent of aggregate_sv (svmTrain.cu:595-627: thrust::remove_if
        on alpha <= 0 + host-side row gather).
        """
        alpha = np.asarray(alpha, np.float32)
        mask = alpha > 0
        return cls(
            sv_x=np.ascontiguousarray(np.asarray(x)[mask], np.float32),
            sv_alpha=alpha[mask],
            sv_y=np.asarray(y, np.int32)[mask],
            b=float(b),
            kernel=kernel,
        )

    # ------------------------------------------------------------------ io
    def npz_payload(self, prefix: str = "") -> dict:
        """The per-model .npz field set under a key prefix — ONE
        definition of the binary model serialization, shared by ``save``
        (prefix "") and the multiclass bundle writer (prefix "m{i}_",
        models/multiclass.py) so the two formats can never skew the way
        the reference's three text writers did (SURVEY.md bug B6)."""
        return {
            f"{prefix}sv_x": self.sv_x,
            f"{prefix}sv_alpha": self.sv_alpha,
            f"{prefix}sv_y": self.sv_y,
            f"{prefix}b": np.float32(self.b),
            **{f"{prefix}{k}": v
               for k, v in self.kernel.npz_fields().items()},
        }

    @classmethod
    def from_npz_payload(cls, z, prefix: str = "") -> "SVMModel":
        """Inverse of ``npz_payload`` over an opened npz mapping."""
        return cls(
            sv_x=z[f"{prefix}sv_x"].astype(np.float32),
            sv_alpha=z[f"{prefix}sv_alpha"].astype(np.float32),
            sv_y=z[f"{prefix}sv_y"].astype(np.int32),
            b=float(z[f"{prefix}b"]),
            kernel=KernelParams(
                kind=str(z[f"{prefix}kernel_kind"]),
                gamma=float(z[f"{prefix}gamma"]),
                degree=int(z[f"{prefix}degree"]),
                coef0=float(z[f"{prefix}coef0"]),
            ),
        )

    def save(self, path: str) -> None:
        if path.endswith(".npz"):
            prob = ({"prob_a": np.float64(self.prob_a),
                     "prob_b": np.float64(self.prob_b)}
                    if self.has_probability else {})
            np.savez_compressed(
                path,
                format_version=1,
                **self.npz_payload(),
                **prob,
            )
            return
        if self.kernel.kind != "rbf":
            raise ValueError(
                "the text model format only expresses RBF (reference format, "
                "svmTrainMain.cpp:386-416); save non-RBF models to .npz")
        if self.has_probability:
            raise ValueError(
                "the text model format cannot carry Platt calibration "
                "(reference format); save probability models to .npz")
        from dpsvm_tpu.utils import native
        writer = native.get_fastcsv()
        if writer is not None:
            writer.write_model(path, float(self.kernel.gamma), float(self.b),
                               self.sv_alpha, self.sv_y, self.sv_x)
            return
        with open(path, "w") as fh:
            fh.write(f"{self.kernel.gamma}\n")
            fh.write(f"{self.b}\n")
            for i in range(self.n_sv):
                row = ",".join(repr(float(v)) for v in self.sv_x[i])
                fh.write(f"{float(self.sv_alpha[i])!r},{int(self.sv_y[i])},{row}\n")

    @classmethod
    def load(cls, path: str) -> "SVMModel":
        if path.endswith(".npz"):
            z = np.load(path, allow_pickle=False)
            model = cls.from_npz_payload(z)
            if "prob_a" in z:
                model.prob_a = float(z["prob_a"])
                model.prob_b = float(z["prob_b"])
            return model
        return cls._load_text(path)

    @classmethod
    def _load_text(cls, path: str) -> "SVMModel":
        with open(path) as fh:
            lines = [ln.strip() for ln in fh if ln.strip()]
        if len(lines) < 2:
            raise ValueError(f"{path}: not a model file")
        gamma = float(lines[0])
        # 2-line header (distributed writer) vs 1-line header (seq writer):
        # an SV row has >= 3 comma-separated fields, a b line exactly one.
        if "," in lines[1]:
            b, first_sv = 0.0, 1
        else:
            b, first_sv = float(lines[1]), 2
        alphas, ys, xs = [], [], []
        for ln in lines[first_sv:]:
            parts = ln.split(",")
            alphas.append(float(parts[0]))
            ys.append(int(float(parts[1])))
            xs.append([float(v) for v in parts[2:]])
        return cls(
            sv_x=np.asarray(xs, np.float32),
            sv_alpha=np.asarray(alphas, np.float32),
            sv_y=np.asarray(ys, np.int32),
            b=b,
            kernel=KernelParams(kind="rbf", gamma=gamma),
        )
