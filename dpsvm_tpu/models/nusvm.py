"""nu-SVC and nu-SVR on the same SMO engine.

No reference equivalent (the reference trains binary C-SVC only) — these
complete the LibSVM model-family matrix (C-SVC / nu-SVC / epsilon-SVR /
nu-SVR / one-class) as capability extensions.

The nu duals (Scholkopf et al.) differ from the C forms in carrying TWO
equality constraints — one per (pseudo-)class — so pair updates must stay
inside a class. That is the only engine-level change: the trainers run the
standard solver with `selection="nu"` (per-class maximal-violating-pair,
ops/select.py select_working_set_nu; distributed variant in
parallel/dist_smo.py; the block engine's per-class-quarter variant in
solver/block.py select_block), a feasible warm start that fixes both constraint
values (pair updates conserve them exactly), and a LibSVM-style
rho/r readout from the final gradient:

  nu-SVC  (box [0,1], p=0):      per class, sum alpha = nu*n/2.
          After solving, r1/r2 = the free-SV average of grad per class
          (midpoint of the active-bound envelope if a class has no free
          SV); the solution is rescaled by r=(r1+r2)/2 so the margin is
          1:  dual_coef = alpha*y/r, b = -(r1-r2)/2 / r. (svm.cpp
          solve_nu_svc / Solver_NU::calculate_rho semantics.)
  nu-SVR  (2n expansion, p=[-z; z]): sum(alpha + alpha*) = C*n*nu,
          sum(alpha - alpha*) = 0. r1/r2 read the same way; under this
          module's grad = y*f convention the adaptive tube width comes
          out as eps = -(r1+r2)/2 and the offset b = (r1-r2)/2 — nu
          replaces the epsilon hyper-parameter of epsilon-SVR.

Validated against sklearn's NuSVC/NuSVR (LibSVM) in tests/test_nusvm.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.models.svr import SVRModel
from dpsvm_tpu.ops.kernels import KernelParams, blocked_kernel_matvec
from dpsvm_tpu.solver.result import SolveResult


def _solve(x, y, cfg, backend, num_devices, callback, alpha0, f_init,
           checkpoint_path=None, resume=False):
    import jax

    if backend == "auto":
        backend = "mesh" if (num_devices or len(jax.devices())) > 1 else "single"
    if backend == "single":
        from dpsvm_tpu.solver.smo import solve
        return solve(x, y, cfg, callback=callback,
                     alpha_init=alpha0, f_init=f_init,
                     checkpoint_path=checkpoint_path, resume=resume)
    if backend == "mesh":
        from dpsvm_tpu.parallel.dist_smo import solve_mesh
        return solve_mesh(x, y, cfg, num_devices=num_devices,
                          callback=callback, alpha_init=alpha0, f_init=f_init,
                          checkpoint_path=checkpoint_path, resume=resume)
    raise ValueError(f"unknown backend {backend!r} (nu trainers support "
                     "'auto' | 'single' | 'mesh')")


def _warn_nu_fallbacks(config: SVMConfig, trainer: str) -> None:
    """The nu duals' per-class selection keeps the PLAIN round body, so
    several fast paths a user may have configured are quietly unusable
    here (ROADMAP item 4 called the silence out). Name exactly what was
    requested and what actually runs — once, loudly, instead of a
    config that looks tuned but trains on the fallback."""
    dropped = []
    if config.ooc:
        dropped.append(
            "ooc (in-core solve)" if not (config.ooc_shrink
                                          or config.active_set_size)
            else "ooc + shrunken stream (in-core solve, no shrinking)")
    if config.pair_batch > 1:
        dropped.append(f"pair_batch={config.pair_batch} "
                       "(single-pair updates)")
    if config.pipeline_rounds:
        dropped.append("pipeline_rounds (plain serial rounds)")
    if config.fused_fold:
        dropped.append("fused_fold (plain fold + select)")
    if config.fused_round:
        dropped.append("fused_round (plain round body)")
    if config.local_working_sets is not None \
            and config.local_working_sets >= 2:
        dropped.append("local_working_sets (global working set)")
    if config.ring_exchange:
        dropped.append("ring_exchange (all_gather exchange — the nu "
                       "rule's per-class quarters keep the psum path)")
    if dropped:
        import warnings

        warnings.warn(
            f"{trainer} runs selection='nu' (per-class pairing) on the "
            f"requested engine={config.engine!r}; the effective engine "
            f"falls back from: {'; '.join(dropped)}",
            stacklevel=3)


def _capped_fill(count: int, total: float, cap: float) -> np.ndarray:
    """LibSVM warm-start walk, vectorized: assign `cap` per slot in order
    until `total` is exhausted, fractional remainder on the next slot."""
    return np.minimum(
        cap, np.maximum(0.0, total - np.arange(count) * cap)).astype(np.float32)


def _rho_r(f, alpha, y, c_cap, eps_box=1e-9):
    """(r1, r2) from the final state, per Solver_NU::calculate_rho.

    grad_i = y_i * f_i (the engine's f is y * grad). Per class: average
    grad over free SVs; a class with no free SV takes the midpoint of
    [max grad at upper bound, min grad at lower bound].
    """
    grad = y * f
    out = []
    for cls in (y > 0, y < 0):
        free = cls & (alpha > eps_box) & (alpha < c_cap - eps_box)
        if free.any():
            out.append(float(grad[free].mean()))
        else:
            at_upper = cls & (alpha >= c_cap - eps_box)
            at_lower = cls & (alpha <= eps_box)
            lb = float(grad[at_upper].max()) if at_upper.any() else -np.inf
            ub = float(grad[at_lower].min()) if at_lower.any() else np.inf
            out.append((ub + lb) / 2.0)
    return out[0], out[1]


def train_nusvc(
    x,
    y,
    nu: float = 0.5,
    config: SVMConfig = SVMConfig(),
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> tuple[SVMModel, SolveResult]:
    """Train binary nu-SVC: nu in (0, 1] bounds the margin-error fraction
    from above and the SV fraction from below. config.c is ignored (the
    nu-SVC box is [0, 1] before rescaling); labels must be +-1."""
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(the nu-SVC dual rescales alpha); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n, d = x.shape
    pos_idx = np.nonzero(y > 0)[0]
    neg_idx = np.nonzero(y < 0)[0]
    if len(pos_idx) == 0 or len(neg_idx) == 0:
        raise ValueError("nu-SVC needs both classes present")
    if not 0.0 < nu <= 1.0:
        raise ValueError("nu must be in (0, 1]")
    # Feasibility (sklearn raises the same way): each class must be able
    # to absorb nu*n/2 at alpha <= 1.
    if nu * n / 2.0 > min(len(pos_idx), len(neg_idx)) + 1e-12:
        raise ValueError("specified nu is infeasible")

    half = nu * n / 2.0
    alpha0 = np.zeros((n,), np.float32)
    alpha0[pos_idx] = _capped_fill(len(pos_idx), half, 1.0)
    alpha0[neg_idx] = _capped_fill(len(neg_idx), half, 1.0)

    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    # p = 0: the engine's indicator is f = y * Q alpha = K @ (alpha * y).
    f_init = blocked_kernel_matvec(x, alpha0 * y, kp, config.dtype)
    if config.engine == "pallas":
        raise ValueError(
            "engine='pallas' does not implement the per-class nu "
            "selection; use engine='xla' (per-pair) or engine='block' "
            "(decomposition with per-class quarters)")
    # pair_batch falls back to single-pair, pipeline_rounds to auto and
    # ooc to the in-core engines: all are mvp/second_order-only
    # features (SVMConfig) and must not make a legal user config crash
    # when this trainer switches the selection rule — the nu per-class
    # quarters keep the plain round. The fallback is NAMED, not silent
    # (_warn_nu_fallbacks; tests/test_nusvm.py pins the message).
    _warn_nu_fallbacks(config, "train_nusvc")
    cfg = config.replace(c=1.0, weight_pos=1.0, weight_neg=1.0,
                         selection="nu", pair_batch=1,
                         pipeline_rounds=None, ooc=False,
                         ooc_cache_lines=0)

    result = _solve(x, y, cfg, backend, num_devices, callback,
                    alpha0, f_init, checkpoint_path, resume)

    r1, r2 = _rho_r(result.stats["f"], result.alpha, y, 1.0)
    r = (r1 + r2) / 2.0
    if r <= 0:
        raise FloatingPointError(
            f"nu-SVC margin scale r={r} <= 0; solution degenerate "
            "(nu too large for this data?)")
    rho = (r1 - r2) / 2.0
    alpha_scaled = (result.alpha / r).astype(np.float32)

    mask = alpha_scaled > 0
    model = SVMModel(
        sv_x=np.ascontiguousarray(x[mask], np.float32),
        sv_alpha=alpha_scaled[mask],
        sv_y=y[mask].astype(np.int32),
        b=float(rho / r),  # SVMModel decision = sum a y K - b
        kernel=kp)
    # Keep the SolveResult self-consistent with every other trainer:
    # result.alpha/result.b reconstruct the model exactly the way the
    # C-SVC path's SVMModel.from_dense(x, y, alpha, b) would.
    result.alpha = alpha_scaled
    result.b = model.b
    # f = y * Q alpha is linear in alpha, so the same 1/r rescale keeps
    # the returned (alpha, f) pair internally consistent for consumers
    # that recompute the dual objective or KKT gap from them.
    result.stats["f"] = (result.stats["f"] / r).astype(np.float32)
    result.stats["nu_r"] = r
    result.stats["nu_rho"] = rho
    return model, result


def train_nusvr(
    x,
    z,
    nu: float = 0.5,
    c: Optional[float] = None,
    config: SVMConfig = SVMConfig(),
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> tuple[SVRModel, SolveResult]:
    """Train nu-SVR: nu replaces epsilon-SVR's tube width (the tube
    adapts so that at most a nu fraction of points fall outside it).
    `c` defaults to config.c."""
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(nu-SVR doubles the variable set); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    x = np.asarray(x, np.float32)
    z = np.asarray(z, np.float32)
    n, d = x.shape
    if z.shape != (n,):
        raise ValueError(f"targets must be shape ({n},), got {z.shape}")
    if not 0.0 < nu <= 1.0:
        raise ValueError("nu must be in (0, 1]")
    C = float(config.c if c is None else c)

    # 2n expansion (models/svr.py): pseudo-labels fix the block structure.
    x2 = np.vstack([x, x])
    y2 = np.concatenate([np.ones(n, np.int32), -np.ones(n, np.int32)])
    # Warm start (svm.cpp solve_nu_svr): alpha_i = alpha*_i walk C*n*nu/2
    # down the rows; symmetric start => K-part of the gradient is zero and
    # f_init = y * p with p = [-z; z], i.e. [-z; -z].
    total = C * n * nu / 2.0
    alpha0 = np.zeros((2 * n,), np.float32)
    a = _capped_fill(n, total, C)
    alpha0[:n] = a
    alpha0[n:] = a
    f_init = np.concatenate([-z, -z]).astype(np.float32)

    if config.engine == "pallas":
        raise ValueError(
            "engine='pallas' does not implement the per-class nu "
            "selection; use engine='xla' (per-pair) or engine='block' "
            "(decomposition with per-class quarters)")
    _warn_nu_fallbacks(config, "train_nusvr")
    cfg = config.replace(c=C, weight_pos=1.0, weight_neg=1.0,
                         selection="nu", pair_batch=1,
                         pipeline_rounds=None, ooc=False,
                         ooc_cache_lines=0)  # see train_nusvc
    result = _solve(x2, y2, cfg, backend, num_devices, callback,
                    alpha0, f_init, checkpoint_path, resume)

    r1, r2 = _rho_r(result.stats["f"], result.alpha,
                    y2.astype(np.float32), C)
    b = (r1 - r2) / 2.0
    result.b = float(b)
    # Under this module's grad = y*f convention the adaptive tube width
    # comes out as -(r1+r2)/2 (checked against LibSVM: inactive points'
    # residuals are bounded by exactly this value).
    result.stats["nu_tube_eps"] = -(r1 + r2) / 2.0

    coef = result.alpha[:n] - result.alpha[n:]
    mask = coef != 0
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    model = SVRModel(
        sv_x=np.ascontiguousarray(x[mask], np.float32),
        coef=coef[mask].astype(np.float32),
        b=float(b),
        kernel=kp)
    return model, result
