"""Platt probability calibration: P(y=+1 | f) = 1 / (1 + exp(A f + B)).

The reference emits raw decision values only (seq_test.cpp:187-210 prints
sign accuracy); LibSVM-class tools additionally offer calibrated
probabilities (-b 1). This implements the standard improved Platt fit
(Newton's method with backtracking on the regularized maximum-likelihood
objective, per Lin/Weng's note on Platt's algorithm) over held-in decision
values, and the pairwise-to-multiclass coupling is left to the caller
(OvR normalization in estimators.SVC).
"""

from __future__ import annotations

import numpy as np


def fit_platt(decision: np.ndarray, y: np.ndarray, max_iter: int = 100,
              tol: float = 1e-10) -> tuple[float, float]:
    """Fit (A, B) on decision values and +-1 labels.

    Uses the regularized targets t+ = (N+ + 1)/(N+ + 2), t- = 1/(N- + 2)
    so the fit is well-posed even when a class is tiny."""
    f = np.asarray(decision, np.float64)
    y = np.asarray(y)
    pos = y > 0
    n_pos = int(pos.sum())
    n_neg = int(len(y) - n_pos)
    if n_pos == 0 or n_neg == 0:
        raise ValueError("Platt calibration needs both classes present")
    t = np.where(pos, (n_pos + 1.0) / (n_pos + 2.0), 1.0 / (n_neg + 2.0))

    # Warm start: a plane whose p(f=0) is the (regularized) positive-class
    # prior. LibSVM's B0 = log((N-+1)/(N++1)) belongs to its
    # 1/(1+exp(Af+B)) form; under this module's p = sigmoid(a f + b) the
    # sign flips.
    a = 0.0
    b = np.log((n_pos + 1.0) / (n_neg + 1.0))

    def nll(a_, b_):
        z = a_ * f + b_
        # log(1 + e^z) - t*z, computed stably on both signs of z.
        return float(np.sum(np.logaddexp(0.0, z) - t * z))

    prev = nll(a, b)
    for _ in range(max_iter):
        z = a * f + b
        p = 1.0 / (1.0 + np.exp(-np.clip(z, -500, 500)))  # sigmoid(z)
        g_a = float(np.sum(f * (p - t)))
        g_b = float(np.sum(p - t))
        if abs(g_a) < tol and abs(g_b) < tol:
            break
        w = np.maximum(p * (1.0 - p), 1e-12)
        h_aa = float(np.sum(f * f * w)) + 1e-12
        h_ab = float(np.sum(f * w))
        h_bb = float(np.sum(w)) + 1e-12
        det = h_aa * h_bb - h_ab * h_ab
        da = -(h_bb * g_a - h_ab * g_b) / det
        db = -(-h_ab * g_a + h_aa * g_b) / det
        # Backtracking line search on the NLL.
        step = 1.0
        for _ in range(30):
            cand = nll(a + step * da, b + step * db)
            if cand < prev + 1e-4 * step * (g_a * da + g_b * db):
                a += step * da
                b += step * db
                prev = cand
                break
            step *= 0.5
        else:
            break
    return float(a), float(b)


def platt_probability(decision: np.ndarray, a: float, b: float) -> np.ndarray:
    """P(y=+1 | f) = sigmoid(a f + b), matching the fit's parameterization
    (classic Platt writes 1/(1+exp(A f + B)); that A is our -a)."""
    z = a * np.asarray(decision, np.float64) + b
    return 1.0 / (1.0 + np.exp(-np.clip(z, -500, 500)))


def platt_probability_matrix(decision: np.ndarray, ab) -> np.ndarray:
    """Per-column Platt probabilities for an (n, k) decision matrix —
    the multiclass layout decision_matrix / the serving engine produce.
    ``ab`` is a length-k sequence of (A, B) planes (one per column, the
    OvR calibration set estimators.SVC fits); one vectorized sigmoid
    replaces the per-column python loop."""
    dec = np.asarray(decision, np.float64)
    ab = np.asarray(ab, np.float64)
    if dec.ndim != 2 or ab.shape != (dec.shape[1], 2):
        raise ValueError(
            f"expected (n, k) decisions with k (A, B) rows; got "
            f"{dec.shape} and {ab.shape}")
    z = dec * ab[None, :, 0] + ab[None, :, 1]
    return 1.0 / (1.0 + np.exp(-np.clip(z, -500, 500)))


def fit_platt_cv(x, y_pm, config, backend: str = "auto",
                 num_devices=None, k: int = 5,
                 seed=0, train_fn=None) -> tuple[float, float]:
    """(A, B) from decision values on held-out folds, LibSVM-style: k-fold
    refits so the calibration never sees its own training residuals
    (in-sample |f| is biased toward the margin — measured on the CLI drive
    fixture: in-sample fit gives train log-loss 0.006 vs test 0.43; the
    CV fit's train and test losses agree). Shared by estimators.SVC and
    the CLI -b 1 flag. `seed` may be None for fresh-entropy fold shuffles
    (sklearn random_state=None semantics); the default 0 keeps the CLI
    deterministic."""
    from dpsvm_tpu.predict import decision_function
    from dpsvm_tpu.train import train

    if train_fn is None:
        # Default: binary C-SVC. Other families (nu-SVC) pass their own
        # trainer with the same (x, y, config, backend, num_devices) ->
        # (model, result) contract so folds refit the same dual.
        train_fn = train
    x = np.asarray(x, np.float32)
    y_pm = np.asarray(y_pm)
    k = max(2, int(k))
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y_pm))
    folds = np.array_split(perm, k)
    dec = np.empty(len(y_pm), np.float64)
    for i, held in enumerate(folds):
        tr = np.concatenate([f for j, f in enumerate(folds) if j != i])
        if len(np.unique(y_pm[tr])) < 2:
            raise ValueError(
                "probability calibration fold lost a class; lower the "
                "fold count or provide more data")
        m, _ = train_fn(x[tr], y_pm[tr], config, backend=backend,
                        num_devices=num_devices)
        dec[held] = decision_function(m, x[held])
    return fit_platt(dec, y_pm)
