"""Multiclass SVM via one-vs-rest / one-vs-one reductions.

Capability extension: the reference trains binary C-SVC only (labels are
+-1 straight from the CSV, parse.cpp:31); multiclass problems had to be
pre-reduced by hand (scripts/convert_mnist_to_odd_even.py collapses the 10
MNIST digits into even/odd for exactly this reason). Here the reduction is
part of the framework: K binary solvers (OvR) or K(K-1)/2 (OvO), each an
independent run of the same single-chip/mesh SMO engine.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.predict import decision_function


@dataclasses.dataclass
class MulticlassSVM:
    classes: np.ndarray  # (k,) sorted original labels
    models: list[SVMModel]  # OvR: k models; OvO: k(k-1)/2 in (i<j) order
    strategy: str  # "ovr" | "ovo"

    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            raise ValueError("multiclass models are saved as .npz")
        payload = {
            "format_version": 1,
            "model_type": "multiclass",  # cli test dispatches on this
            "strategy": self.strategy,
            "classes": self.classes,
            "n_models": len(self.models),
        }
        for i, m in enumerate(self.models):
            payload[f"m{i}_sv_x"] = m.sv_x
            payload[f"m{i}_sv_alpha"] = m.sv_alpha
            payload[f"m{i}_sv_y"] = m.sv_y
            payload[f"m{i}_b"] = np.float32(m.b)
            payload[f"m{i}_kernel_kind"] = m.kernel.kind
            payload[f"m{i}_gamma"] = np.float32(m.kernel.gamma)
            payload[f"m{i}_degree"] = np.int32(m.kernel.degree)
            payload[f"m{i}_coef0"] = np.float32(m.kernel.coef0)
        np.savez_compressed(path, **payload)

    @classmethod
    def load(cls, path: str) -> "MulticlassSVM":
        from dpsvm_tpu.ops.kernels import KernelParams
        z = np.load(path, allow_pickle=False)
        models = []
        for i in range(int(z["n_models"])):
            models.append(SVMModel(
                sv_x=z[f"m{i}_sv_x"].astype(np.float32),
                sv_alpha=z[f"m{i}_sv_alpha"].astype(np.float32),
                sv_y=z[f"m{i}_sv_y"].astype(np.int32),
                b=float(z[f"m{i}_b"]),
                kernel=KernelParams(
                    kind=str(z[f"m{i}_kernel_kind"]),
                    gamma=float(z[f"m{i}_gamma"]),
                    degree=int(z[f"m{i}_degree"]),
                    coef0=float(z[f"m{i}_coef0"]),
                ),
            ))
        return cls(classes=z["classes"], models=models, strategy=str(z["strategy"]))


def _fleet_eligible(config: SVMConfig, backend: str,
                    num_devices: Optional[int], trainer,
                    forced: bool = False) -> bool:
    """Whether this reduction routes through the batched fleet executor
    (solver/fleet.py) instead of K sequential solves.

    The fleet runs the single-chip per-pair MVP iteration, so routing is
    conservative: only the plain C-SVC trainer (trainer=None), on one
    device, with a config whose iteration semantics the fleet reproduces
    exactly. Anything else — custom trainers (nu duals), the mesh
    backend, accuracy-mode stacks, non-MVP selection — keeps the
    sequential path. `forced` (use_fleet=True) raises on disqualifying
    configs instead of silently falling back."""
    from dpsvm_tpu.solver.fleet import fleet_routing_reasons

    reasons = fleet_routing_reasons(config)
    if trainer is not None:
        reasons.append("a custom trainer is installed")
    if backend not in ("auto", "single"):
        reasons.append(f"backend={backend!r} (fleet is single-chip)")
    if config.fleet_size <= 1:
        reasons.append("fleet_size=1")
    if config.budget_mode:
        reasons.append("budget_mode pins per-solve pair budgets")
    if backend == "auto" and not reasons:
        import jax
        if (num_devices or len(jax.devices())) > 1:
            # auto prefers the mesh when >1 device is visible (train()'s
            # own rule); the fleet must not silently de-shard a problem
            # the user sized for the mesh. backend='single' opts in.
            reasons.append("auto backend resolves to the mesh "
                           "(pass backend='single' to batch the fleet)")
    if reasons and forced:
        raise ValueError(
            "use_fleet=True but the config cannot route through the "
            "fleet executor: " + "; ".join(reasons))
    return not reasons


def _train_multiclass_fleet(x, y, classes, config: SVMConfig,
                            strategy: str, verbose: bool):
    """The fleet-batched reduction: OvR's k problems (identical rows) or
    OvO's k(k-1)/2 masked problems run in ceil(K / fleet_size) dispatch
    sequences instead of K (solver/fleet.py). Model assembly is
    identical to the sequential path — each result's alpha covers
    exactly the problem's masked rows."""
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.fleet import FleetProblem, fleet_chunks, solve_fleet

    kp = KernelParams(config.kernel, config.resolve_gamma(x.shape[1]),
                      config.degree, config.coef0)
    if strategy == "ovr":
        problems = [
            FleetProblem(y=np.where(y == cl, 1, -1).astype(np.int32),
                         tag=("ovr", cl))
            for cl in classes]
    else:
        problems = []
        for a in range(len(classes)):
            for b in range(a + 1, len(classes)):
                mask = (y == classes[a]) | (y == classes[b])
                problems.append(FleetProblem(
                    y=np.where(y == classes[a], 1, -1).astype(np.int32),
                    row_mask=mask, tag=("ovo", classes[a], classes[b])))

    models: list[SVMModel] = []
    results = []
    for chunk in fleet_chunks(problems, config.fleet_size):
        chunk_results = solve_fleet(x, chunk, config)
        for p, res in zip(chunk, chunk_results):
            if p.row_mask is None:
                xs, ys = x, p.y
            else:
                xs = x[p.row_mask]
                ys = p.y[p.row_mask]
            models.append(SVMModel.from_dense(xs, ys, res.alpha, res.b, kp))
            results.append(res)
            if verbose:
                tag = p.tag
                name = (f"ovr class={tag[1]}" if tag[0] == "ovr"
                        else f"ovo {tag[1]} vs {tag[2]}")
                print(f"[fleet {name}] iters={res.iterations} "
                      f"n_sv={res.n_sv} "
                      f"(fleet of {res.stats['fleet']['size']}, "
                      f"{res.dispatches} dispatches)")
    return MulticlassSVM(classes=classes, models=models,
                         strategy=strategy), results


def train_multiclass(
    x,
    y,
    config: SVMConfig = SVMConfig(),
    strategy: str = "ovr",
    backend: str = "auto",
    num_devices: Optional[int] = None,
    verbose: bool = False,
    trainer=None,
    use_fleet: Optional[bool] = None,
) -> tuple[MulticlassSVM, list]:
    """Train a multiclass SVM; y may hold arbitrary integer labels.

    `trainer(x, y_pm, config, backend=..., num_devices=..., pad_to=...)
    -> (SVMModel, SolveResult)` swaps the binary solver under the
    reduction — the default is C-SVC ``train``; estimators.NuSVC passes
    a nu-SVC trainer so its multiclass reduction uses the nu duals per
    split.

    `use_fleet`: None (default) auto-routes eligible configs through the
    batched multi-problem executor (solver/fleet.py — all submodels
    train in ceil(K / fleet_size) dispatch sequences; see
    _fleet_eligible for the gate); True forces it (raising on
    disqualifying configs); False forces the sequential per-submodel
    path."""
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(each OvR/OvO split needs its own Gram sub-matrix); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    from dpsvm_tpu.train import train

    user_trainer = trainer  # the fleet gate needs the CALLER's trainer
    if trainer is None:
        def trainer(xx, yy, cfg, backend="auto", num_devices=None,
                    pad_to=None):
            return train(xx, yy, cfg, backend=backend,
                         num_devices=num_devices, pad_to=pad_to)

    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    classes = np.unique(y)
    if classes.shape[0] < 2:
        raise ValueError("need at least 2 classes")
    if classes.shape[0] == 2:
        # Degenerate case: the OvO reduction IS a single binary model
        # (one a<b pair); the OvR loop would train two mirror-image
        # submodels and pay double at fit and predict time.
        strategy = "ovo"

    if strategy in ("ovr", "ovo") and use_fleet is not False \
            and _fleet_eligible(config, backend, num_devices, user_trainer,
                                forced=use_fleet is True):
        return _train_multiclass_fleet(x, y, classes, config, strategy,
                                       verbose)

    models: list[SVMModel] = []
    results = []
    if strategy == "ovr":
        for k, cls_label in enumerate(classes):
            yk = np.where(y == cls_label, 1, -1).astype(np.int32)
            model, res = trainer(x, yk, config, backend=backend,
                                 num_devices=num_devices)
            if verbose:
                print(f"[ovr {k + 1}/{len(classes)}] class={cls_label} "
                      f"iters={res.iterations} n_sv={res.n_sv}")
            models.append(model)
            results.append(res)
    elif strategy == "ovo":
        for a in range(len(classes)):
            for b in range(a + 1, len(classes)):
                mask = (y == classes[a]) | (y == classes[b])
                xa = x[mask]
                ya = np.where(y[mask] == classes[a], 1, -1).astype(np.int32)
                # Shape bucketing: the k(k-1)/2 subsets all have slightly
                # different row counts, and XLA executors are shape-keyed
                # — without bucketing every pair pays a fresh compile.
                # Rounding up to the next power of two collapses them to
                # ~1-2 buckets (padding is masked out of selection;
                # solver/smo.py solve pad_to).
                bucket = 1 << (len(xa) - 1).bit_length()
                model, res = trainer(xa, ya, config, backend=backend,
                                     num_devices=num_devices,
                                     pad_to=bucket)
                if verbose:
                    print(f"[ovo {classes[a]} vs {classes[b]}] "
                          f"iters={res.iterations} n_sv={res.n_sv}")
                models.append(model)
                results.append(res)
    else:
        raise ValueError(f"unknown strategy {strategy!r}; use 'ovr' or 'ovo'")
    return MulticlassSVM(classes=classes, models=models, strategy=strategy), results


def predict_multiclass(m: MulticlassSVM, q, block: int = 8192) -> np.ndarray:
    """Predicted class labels for a batch of query points."""
    q = np.asarray(q, np.float32)
    k = len(m.classes)
    if m.strategy == "ovr":
        return m.classes[np.argmax(decision_matrix(m, q, block), axis=1)]
    # OvO majority vote; the sub-unit confidence term of vote_matrix only
    # ever breaks ties (it is bounded by 1/3 per class).
    return m.classes[np.argmax(vote_matrix(m, q, block), axis=1)]


def _stacked_batch_factory():
    """Module-level jitted stacked evaluator (built lazily so jax stays
    a deferred import here). jax.jit caches are keyed on the wrapper
    OBJECT: defining the jit inside _stacked_decision would retrace and
    recompile on every predict call — seconds each through a tunneled
    runtime (review finding, round 5)."""
    global _STACKED_BATCH
    if _STACKED_BATCH is not None:
        return _STACKED_BATCH
    from functools import partial

    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("kp",))
    def batch(qb, sv, coef, b, kp):
        dots = jnp.einsum("nd,kmd->knm", qb, sv,
                          preferred_element_type=jnp.float32)
        if kp.kind == "rbf":
            qsq = jnp.einsum("nd,nd->n", qb, qb)
            ssq = jnp.einsum("kmd,kmd->km", sv, sv)
            sq = jnp.maximum(qsq[None, :, None] + ssq[:, None, :]
                             - 2.0 * dots, 0.0)
            kv = jnp.exp(-kp.gamma * sq)
        elif kp.kind == "linear":
            kv = dots
        elif kp.kind == "poly":
            kv = (kp.gamma * dots + kp.coef0) ** kp.degree
        elif kp.kind == "sigmoid":
            kv = jnp.tanh(kp.gamma * dots + kp.coef0)
        else:
            raise ValueError(f"unknown kernel kind {kp.kind!r}")
        return (jnp.einsum("knm,km->kn", kv, coef) - b[:, None]).T

    _STACKED_BATCH = batch
    return batch


_STACKED_BATCH = None


def _stacked_decision(models, q, block: int) -> np.ndarray:
    """All submodels' decision values in ONE batched dispatch per query
    block: (n, n_models) float32.

    Per-model prediction costs a device round-trip per model per block —
    through a tunneled runtime that is ~1 s of latency each, and a
    45-model OvO predict spent minutes on ~90 dispatches while the
    actual MXU work was milliseconds (BENCH_MULTICLASS.md round 5).
    Here every model's SVs pad to the shared power-of-two bucket (zero
    dual coefficients contribute nothing), the stack evaluates as one
    (k, nb, m) batched einsum chain, and the dispatch count drops to
    n/block. All submodels share one kernel family by construction
    (train_multiclass replicates config)."""
    import jax.numpy as jnp

    kp = models[0].kernel
    d = models[0].sv_x.shape[1]
    m_pad = 1 << max(4, (max(mm.sv_x.shape[0] for mm in models) - 1)
                     .bit_length())
    k = len(models)
    sv = np.zeros((k, m_pad, d), np.float32)
    coef = np.zeros((k, m_pad), np.float32)
    b = np.zeros((k,), np.float32)
    for i, mm in enumerate(models):
        ns = mm.sv_x.shape[0]
        sv[i, :ns] = mm.sv_x
        coef[i, :ns] = mm.dual_coef
        b[i] = mm.b

    batch = _stacked_batch_factory()

    # Bound the (k, nb, m) kernel tile: shrink the query block so the
    # tile stays under ~1 GB regardless of model count / bucket size,
    # then round DOWN to a power of two — the per-block query pad below
    # rounds nb UP to a power of two, so a non-power-of-two cap would
    # let the PADDED tile overshoot the budget by up to 2x (ADVICE
    # round-5, low).
    blk = max(128, min(block, (1 << 28) // max(1, k * m_pad)))
    blk = 1 << (blk.bit_length() - 1)
    sv_d, coef_d, b_d = jnp.asarray(sv), jnp.asarray(coef), jnp.asarray(b)
    out = []
    q = np.asarray(q, np.float32)
    for s in range(0, q.shape[0], blk):
        qb = q[s:s + blk]
        nb = qb.shape[0]
        nb_pad = 1 << max(4, (nb - 1).bit_length())
        if nb_pad != nb:
            qp = np.zeros((nb_pad, d), np.float32)
            qp[:nb] = qb
            qb = qp
        out.append(np.asarray(batch(jnp.asarray(qb), sv_d, coef_d, b_d,
                                    kp))[:nb])
    return (np.concatenate(out) if out
            else np.zeros((0, k), np.float32))


def decision_matrix(m: MulticlassSVM, q, block: int = 8192) -> np.ndarray:
    """Raw decision values, one column per fitted model: (n, k) per-class
    scores for OvR, (n, k*(k-1)/2) pairwise columns (a<b order) for OvO."""
    q = np.asarray(q, np.float32)
    if len(m.models) > 1 and all(mm.kernel == m.models[0].kernel
                                 for mm in m.models):
        return _stacked_decision(m.models, q, block)
    return np.stack(
        [decision_function(mm, q, block) for mm in m.models], axis=1)


def vote_matrix(m: MulticlassSVM, q, block: int = 8192) -> np.ndarray:
    """(n, k) per-class scores for an OvO model: pairwise votes plus a
    sub-unit confidence term (sklearn's ovo->ovr transformation shape) so
    ties rank by margin while vote order is never overturned."""
    if m.strategy != "ovo":
        return decision_matrix(m, q, block)
    q = np.asarray(q, np.float32)
    k = len(m.classes)
    votes = np.zeros((q.shape[0], k), np.float64)
    conf = np.zeros((q.shape[0], k), np.float64)
    # One stacked device pass for all pairwise columns (see
    # _stacked_decision); the vote fold is host numpy.
    dec = decision_matrix(m, q, block).astype(np.float64)
    idx = 0
    for a in range(k):
        for b in range(a + 1, k):
            d = dec[:, idx]
            win_a = d >= 0
            votes[:, a] += win_a
            votes[:, b] += ~win_a
            conf[:, a] += d
            conf[:, b] -= d
            idx += 1
    return votes + conf / (3.0 * (np.abs(conf) + 1.0))


def accuracy_multiclass(m: MulticlassSVM, q, y, block: int = 8192) -> float:
    return float(np.mean(predict_multiclass(m, q, block) == np.asarray(y)))
