"""Multiclass SVM via one-vs-rest / one-vs-one reductions.

Capability extension: the reference trains binary C-SVC only (labels are
+-1 straight from the CSV, parse.cpp:31); multiclass problems had to be
pre-reduced by hand (scripts/convert_mnist_to_odd_even.py collapses the 10
MNIST digits into even/odd for exactly this reason). Here the reduction is
part of the framework: K binary solvers (OvR) or K(K-1)/2 (OvO), each an
independent run of the same single-chip/mesh SMO engine.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.predict import decision_function


@dataclasses.dataclass
class CompactedEnsemble:
    """Shared-SV compacted view of a multiclass ensemble.

    Every submodel's SVs are rows of the SAME training matrix (OvR trains
    on all rows, OvO on row subsets; ``SVMModel.from_dense`` gathers rows,
    never recomputes them), so the k replicated per-model SV stacks
    collapse into ONE union matrix plus a coefficient matrix — the
    union-of-SVs structure LIBSVM-family tools exploit for multiclass
    prediction. The whole multiclass decision becomes one
    ``K(Q, sv_union)`` kernel matmul for all k columns instead of k
    replicated ones (~k x fewer kernel FLOPs and bytes on the OvO hot
    path).

    Fields:
      sv_union  (S+1, d) f32  deduplicated SV rows (exact byte-identity)
                            plus ONE trailing all-zero PAD row — the
                            same zero-row padding the stacked path
                            uses, so a non-finite kernel value of a
                            real row (e.g. poly overflow) can never
                            leak inf*0=NaN through pad slots into
                            other submodels' columns. Empty when no
                            submodel has SVs.
      coef      (S+1, k) f32  dense dual-coefficient matrix: column j
                            holds submodel j's alpha*y at its rows'
                            union positions, zero elsewhere (duplicate
                            rows WITHIN a model accumulate; the pad
                            row is all-zero) — the serving engine's
                            ``K @ coef`` contraction operand
      b         (k,)   f32  per-submodel offsets
      idx       (k, m_pad) i32  submodel j's SVs as union positions, in
                            submodel j's OWN SV order (pad slots point
                            at the zero PAD row) — the
                            exact-contraction gather operand
      coef_pad  (k, m_pad) f32  submodel j's dual coefs in the same order
      counts    (k,)   i32  true n_sv per submodel (pad slots carry
                            coef 0 and contribute exact +0.0)
      kernel    shared KernelParams
    """

    sv_union: np.ndarray
    coef: np.ndarray
    b: np.ndarray
    idx: np.ndarray
    coef_pad: np.ndarray
    counts: np.ndarray
    kernel: object  # KernelParams (deferred import at module top-level)
    # Device residency: built once per ensemble object, evicted with it.
    # The arrays are treated as FROZEN after build (mutating them would
    # serve stale device copies; rebuild via compact_models instead).
    _device: tuple = dataclasses.field(default=None, repr=False,
                                       compare=False)

    @property
    def n_union(self) -> int:
        """Deduplicated REAL SV rows (excluding the trailing pad row)."""
        s = int(self.sv_union.shape[0])
        return max(0, s - 1)

    @property
    def n_models(self) -> int:
        return int(self.coef.shape[1])

    @property
    def m_pad(self) -> int:
        return int(self.idx.shape[1])

    def device_arrays(self):
        """(sv_union, coef_pad, idx, b) resident on device — uploaded
        once per ensemble, not per decision_matrix call (the serving
        residency contract; the dense ``coef`` operand is staged by the
        serving engine separately because it may live in a different
        storage dtype there)."""
        if self._device is None:
            import jax.numpy as jnp
            self._device = (jnp.asarray(self.sv_union),
                            jnp.asarray(self.coef_pad),
                            jnp.asarray(self.idx),
                            jnp.asarray(self.b))
        return self._device


def compact_models(models, x_train=None) -> CompactedEnsemble:
    """Deduplicate SV rows across submodels into a CompactedEnsemble.

    Exact row-identity dedup: rows match by raw float32 bytes. When the
    training matrix is available (train time) the union keeps
    training-row order — training rows are hashed once and SV rows map
    through that index; rows not found there (or with no ``x_train``,
    the load path) dedup by byte equality in first-seen order. Bit-level
    parity with the stacked path does NOT depend on union order: the
    exact contraction gathers each model's kernel values back into the
    model's own SV order (see _compacted_batch_factory)."""
    kp = models[0].kernel
    d = models[0].sv_x.shape[1]
    k = len(models)
    # Same padded height as _stacked_decision so the two contractions
    # sum identical term sequences (pad slots are exact zeros in both).
    m_pad = 1 << max(4, (max((mm.sv_x.shape[0] for mm in models),
                            default=1) - 1).bit_length())
    svs_list = []
    coef_pad = np.zeros((k, m_pad), np.float32)
    counts = np.zeros((k,), np.int32)
    b = np.zeros((k,), np.float32)
    for j, mm in enumerate(models):
        if mm.kernel != kp:
            raise ValueError(
                "compact_models needs all submodels on one shared kernel "
                f"(model 0 has {kp}, model {j} has {mm.kernel})")
        svs = np.ascontiguousarray(np.asarray(mm.sv_x, np.float32))
        svs_list.append(svs)
        counts[j] = svs.shape[0]
        b[j] = mm.b
        coef_pad[j, :svs.shape[0]] = mm.dual_coef

    def _void(a):
        """Rows as opaque byte scalars — C-speed exact row identity."""
        return np.ascontiguousarray(a).view(
            np.dtype((np.void, a.dtype.itemsize * d))).reshape(-1)

    total = int(counts.sum())
    if total == 0:
        return CompactedEnsemble(
            sv_union=np.zeros((0, d), np.float32),
            coef=np.zeros((0, k), np.float32), b=b,
            idx=np.zeros((k, m_pad), np.int32), coef_pad=coef_pad,
            counts=counts, kernel=kp)

    # Vectorized dedup (np.unique over void rows — no per-row Python
    # hashing; at MNIST-OvO scale the tobytes/dict formulation costs
    # seconds of pure-Python time per build).
    all_rows = np.concatenate([s for s in svs_list if len(s)])
    _, first_idx, inverse = np.unique(_void(all_rows),
                                      return_index=True,
                                      return_inverse=True)
    # np.unique sorts by bytes; re-rank to FIRST-SEEN order.
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(first_idx), np.int64)
    rank[order] = np.arange(len(first_idx))
    pos_of_row = rank[inverse.reshape(-1)]  # union position per SV row
    union_rows = all_rows[first_idx[order]]

    if x_train is not None:
        xt = np.ascontiguousarray(np.asarray(x_train, np.float32))
        if xt.ndim == 2 and xt.shape[1] == d and xt.shape[0]:
            # Reorder the union to training-row order where rows are
            # found in x_train (unmatched rows keep first-seen order at
            # the tail). One np.unique over both row sets yields the
            # join; no per-row hashing of the 60k x 784 matrix.
            both = np.concatenate([_void(xt), _void(union_rows)])
            _, inv2 = np.unique(both, return_inverse=True)
            inv2 = inv2.reshape(-1)
            tid, uid = inv2[:xt.shape[0]], inv2[xt.shape[0]:]
            sentinel = np.iinfo(np.int64).max
            tpos = np.full(int(inv2.max()) + 1, sentinel, np.int64)
            np.minimum.at(tpos, tid, np.arange(xt.shape[0]))
            order2 = np.argsort(tpos[uid], kind="stable")
            union_rows = union_rows[order2]
            rank2 = np.empty(len(order2), np.int64)
            rank2[order2] = np.arange(len(order2))
            pos_of_row = rank2[pos_of_row]

    # Trailing all-zero PAD row: pad slots of idx gather ITS kernel
    # value (times coef 0) — exactly the stacked path's zero-row
    # padding, so a non-finite kernel value of a real row never turns
    # into inf*0 = NaN in unrelated columns.
    s_real = union_rows.shape[0]
    sv_union = np.concatenate(
        [union_rows, np.zeros((1, d), np.float32)])
    idx = np.full((k, m_pad), s_real, np.int32)
    coef = np.zeros((s_real + 1, k), np.float32)
    off = 0
    for j, svs in enumerate(svs_list):
        nsv = svs.shape[0]
        pj = pos_of_row[off:off + nsv]
        idx[j, :nsv] = pj
        # scatter-add: in-model duplicate rows accumulate
        np.add.at(coef[:, j], pj, coef_pad[j, :nsv])
        off += nsv
    return CompactedEnsemble(sv_union=sv_union, coef=coef, b=b, idx=idx,
                             coef_pad=coef_pad, counts=counts, kernel=kp)


@dataclasses.dataclass
class MulticlassSVM:
    classes: np.ndarray  # (k,) sorted original labels
    models: list[SVMModel]  # OvR: k models; OvO: k(k-1)/2 in (i<j) order
    strategy: str  # "ovr" | "ovo"
    # Shared-SV compacted view (None until built or when submodels do not
    # share one kernel). Built once at train/load time, persisted in the
    # .npz format (version 2).
    compacted: Optional[CompactedEnsemble] = None

    def shared_kernel(self) -> bool:
        return bool(self.models) and all(
            mm.kernel == self.models[0].kernel for mm in self.models)

    def ensure_compacted(self, x_train=None) -> Optional[CompactedEnsemble]:
        """Build (once) and return the compacted view; None when the
        submodels do not share one kernel (mixed ensembles keep the
        stacked / per-model fallbacks)."""
        if self.compacted is None and self.shared_kernel():
            self.compacted = compact_models(self.models, x_train=x_train)
        return self.compacted

    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            raise ValueError("multiclass models are saved as .npz")
        # format_version 2 adds the persisted compacted arrays (c_*).
        # Backward compatible BOTH ways: a v1 reader ignores the c_* keys
        # (it only reads n_models/m{i}_*), and this reader rebuilds the
        # compaction when a v1 file has none.
        payload = {
            "format_version": 2,
            "model_type": "multiclass",  # cli test dispatches on this
            "strategy": self.strategy,
            "classes": self.classes,
            "n_models": len(self.models),
        }
        for i, m in enumerate(self.models):
            payload.update(m.npz_payload(f"m{i}_"))
        comp = self.ensure_compacted()
        if comp is not None:
            payload.update(
                c_sv_union=comp.sv_union, c_coef=comp.coef,
                c_coef_pad=comp.coef_pad, c_idx=comp.idx,
                c_counts=comp.counts, c_b=comp.b)
        np.savez_compressed(path, **payload)

    @classmethod
    def load(cls, path: str) -> "MulticlassSVM":
        z = np.load(path, allow_pickle=False)
        models = [SVMModel.from_npz_payload(z, f"m{i}_")
                  for i in range(int(z["n_models"]))]
        obj = cls(classes=z["classes"], models=models,
                  strategy=str(z["strategy"]))
        if "c_sv_union" in z and obj.shared_kernel():
            obj.compacted = CompactedEnsemble(
                sv_union=z["c_sv_union"].astype(np.float32),
                coef=z["c_coef"].astype(np.float32),
                b=z["c_b"].astype(np.float32),
                idx=z["c_idx"].astype(np.int32),
                coef_pad=z["c_coef_pad"].astype(np.float32),
                counts=z["c_counts"].astype(np.int32),
                kernel=models[0].kernel)
        else:
            # v1 file (or a mixed-kernel bundle): compaction happens once
            # at load, byte-equality dedup (no training matrix here).
            obj.ensure_compacted()
        return obj


def _fleet_eligible(config: SVMConfig, backend: str,
                    num_devices: Optional[int], trainer,
                    forced: bool = False) -> bool:
    """Whether this reduction routes through the batched fleet executor
    (solver/fleet.py) instead of K sequential solves.

    The fleet runs the single-chip per-pair MVP iteration, so routing is
    conservative: only the plain C-SVC trainer (trainer=None), on one
    device, with a config whose iteration semantics the fleet reproduces
    exactly. Anything else — custom trainers (nu duals), the mesh
    backend, accuracy-mode stacks, non-MVP selection — keeps the
    sequential path. `forced` (use_fleet=True) raises on disqualifying
    configs instead of silently falling back."""
    from dpsvm_tpu.solver.fleet import fleet_routing_reasons

    reasons = fleet_routing_reasons(config)
    if trainer is not None:
        reasons.append("a custom trainer is installed")
    if backend not in ("auto", "single"):
        reasons.append(f"backend={backend!r} (fleet is single-chip)")
    if config.fleet_size <= 1:
        reasons.append("fleet_size=1")
    if config.budget_mode:
        reasons.append("budget_mode pins per-solve pair budgets")
    if backend == "auto" and not reasons:
        import jax
        if (num_devices or len(jax.devices())) > 1:
            # auto prefers the mesh when >1 device is visible (train()'s
            # own rule); the fleet must not silently de-shard a problem
            # the user sized for the mesh. backend='single' opts in.
            reasons.append("auto backend resolves to the mesh "
                           "(pass backend='single' to batch the fleet)")
    if reasons and forced:
        raise ValueError(
            "use_fleet=True but the config cannot route through the "
            "fleet executor: " + "; ".join(reasons))
    return not reasons


def _train_multiclass_fleet(x, y, classes, config: SVMConfig,
                            strategy: str, verbose: bool):
    """The fleet-batched reduction: OvR's k problems (identical rows) or
    OvO's k(k-1)/2 masked problems run in ceil(K / fleet_size) dispatch
    sequences instead of K (solver/fleet.py). Model assembly is
    identical to the sequential path — each result's alpha covers
    exactly the problem's masked rows."""
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.fleet import FleetProblem, fleet_chunks, solve_fleet

    kp = KernelParams(config.kernel, config.resolve_gamma(x.shape[1]),
                      config.degree, config.coef0)
    if strategy == "ovr":
        problems = [
            FleetProblem(y=np.where(y == cl, 1, -1).astype(np.int32),
                         tag=("ovr", cl))
            for cl in classes]
    else:
        problems = []
        for a in range(len(classes)):
            for b in range(a + 1, len(classes)):
                mask = (y == classes[a]) | (y == classes[b])
                problems.append(FleetProblem(
                    y=np.where(y == classes[a], 1, -1).astype(np.int32),
                    row_mask=mask, tag=("ovo", classes[a], classes[b])))

    models: list[SVMModel] = []
    results = []
    for chunk in fleet_chunks(problems, config.fleet_size):
        chunk_results = solve_fleet(x, chunk, config)
        for p, res in zip(chunk, chunk_results):
            if p.row_mask is None:
                xs, ys = x, p.y
            else:
                xs = x[p.row_mask]
                ys = p.y[p.row_mask]
            models.append(SVMModel.from_dense(xs, ys, res.alpha, res.b, kp))
            results.append(res)
            if verbose:
                tag = p.tag
                name = (f"ovr class={tag[1]}" if tag[0] == "ovr"
                        else f"ovo {tag[1]} vs {tag[2]}")
                print(f"[fleet {name}] iters={res.iterations} "
                      f"n_sv={res.n_sv} "
                      f"(fleet of {res.stats['fleet']['size']}, "
                      f"{res.dispatches} dispatches)")
    mc = MulticlassSVM(classes=classes, models=models, strategy=strategy)
    # Compaction happens once at model build (the training matrix is at
    # hand, so the union keeps training-row order).
    mc.ensure_compacted(x_train=x)
    return mc, results


def train_multiclass(
    x,
    y,
    config: SVMConfig = SVMConfig(),
    strategy: str = "ovr",
    backend: str = "auto",
    num_devices: Optional[int] = None,
    verbose: bool = False,
    trainer=None,
    use_fleet: Optional[bool] = None,
) -> tuple[MulticlassSVM, list]:
    """Train a multiclass SVM; y may hold arbitrary integer labels.

    `trainer(x, y_pm, config, backend=..., num_devices=..., pad_to=...)
    -> (SVMModel, SolveResult)` swaps the binary solver under the
    reduction — the default is C-SVC ``train``; estimators.NuSVC passes
    a nu-SVC trainer so its multiclass reduction uses the nu duals per
    split.

    `use_fleet`: None (default) auto-routes eligible configs through the
    batched multi-problem executor (solver/fleet.py — all submodels
    train in ceil(K / fleet_size) dispatch sequences; see
    _fleet_eligible for the gate); True forces it (raising on
    disqualifying configs); False forces the sequential per-submodel
    path."""
    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' is implemented for binary C-SVC only "
            "(each OvR/OvO split needs its own Gram sub-matrix); the reduction would need "
            "a transformed Gram matrix, not transformed features")
    from dpsvm_tpu.train import train

    user_trainer = trainer  # the fleet gate needs the CALLER's trainer
    if trainer is None:
        def trainer(xx, yy, cfg, backend="auto", num_devices=None,
                    pad_to=None):
            return train(xx, yy, cfg, backend=backend,
                         num_devices=num_devices, pad_to=pad_to)

    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    classes = np.unique(y)
    if classes.shape[0] < 2:
        raise ValueError("need at least 2 classes")
    if classes.shape[0] == 2:
        # Degenerate case: the OvO reduction IS a single binary model
        # (one a<b pair); the OvR loop would train two mirror-image
        # submodels and pay double at fit and predict time.
        strategy = "ovo"

    if strategy in ("ovr", "ovo") and use_fleet is not False \
            and _fleet_eligible(config, backend, num_devices, user_trainer,
                                forced=use_fleet is True):
        return _train_multiclass_fleet(x, y, classes, config, strategy,
                                       verbose)

    models: list[SVMModel] = []
    results = []
    if strategy == "ovr":
        for k, cls_label in enumerate(classes):
            yk = np.where(y == cls_label, 1, -1).astype(np.int32)
            model, res = trainer(x, yk, config, backend=backend,
                                 num_devices=num_devices)
            if verbose:
                print(f"[ovr {k + 1}/{len(classes)}] class={cls_label} "
                      f"iters={res.iterations} n_sv={res.n_sv}")
            models.append(model)
            results.append(res)
    elif strategy == "ovo":
        for a in range(len(classes)):
            for b in range(a + 1, len(classes)):
                mask = (y == classes[a]) | (y == classes[b])
                xa = x[mask]
                ya = np.where(y[mask] == classes[a], 1, -1).astype(np.int32)
                # Shape bucketing: the k(k-1)/2 subsets all have slightly
                # different row counts, and XLA executors are shape-keyed
                # — without bucketing every pair pays a fresh compile.
                # Rounding up to the next power of two collapses them to
                # ~1-2 buckets (padding is masked out of selection;
                # solver/smo.py solve pad_to).
                bucket = 1 << (len(xa) - 1).bit_length()
                model, res = trainer(xa, ya, config, backend=backend,
                                     num_devices=num_devices,
                                     pad_to=bucket)
                if verbose:
                    print(f"[ovo {classes[a]} vs {classes[b]}] "
                          f"iters={res.iterations} n_sv={res.n_sv}")
                models.append(model)
                results.append(res)
    else:
        raise ValueError(f"unknown strategy {strategy!r}; use 'ovr' or 'ovo'")
    mc = MulticlassSVM(classes=classes, models=models, strategy=strategy)
    if mc.shared_kernel():
        mc.ensure_compacted(x_train=x)
    return mc, results


def predict_multiclass(m: MulticlassSVM, q, block: int = 8192) -> np.ndarray:
    """Predicted class labels for a batch of query points."""
    q = np.asarray(q, np.float32)
    k = len(m.classes)
    if m.strategy == "ovr":
        return m.classes[np.argmax(decision_matrix(m, q, block), axis=1)]
    # OvO majority vote; the sub-unit confidence term of vote_matrix only
    # ever breaks ties (it is bounded by 1/3 per class).
    return m.classes[np.argmax(vote_matrix(m, q, block), axis=1)]


def _stacked_batch_factory():
    """Module-level jitted stacked evaluator (built lazily so jax stays
    a deferred import here). jax.jit caches are keyed on the wrapper
    OBJECT: defining the jit inside _stacked_decision would retrace and
    recompile on every predict call — seconds each through a tunneled
    runtime (review finding, round 5)."""
    global _STACKED_BATCH
    if _STACKED_BATCH is not None:
        return _STACKED_BATCH
    from functools import partial

    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("kp",))
    def batch(qb, sv, coef, b, kp):
        dots = jnp.einsum("nd,kmd->knm", qb, sv,
                          preferred_element_type=jnp.float32)
        if kp.kind == "rbf":
            qsq = jnp.einsum("nd,nd->n", qb, qb)
            ssq = jnp.einsum("kmd,kmd->km", sv, sv)
            sq = jnp.maximum(qsq[None, :, None] + ssq[:, None, :]
                             - 2.0 * dots, 0.0)
            kv = jnp.exp(-kp.gamma * sq)
        elif kp.kind == "linear":
            kv = dots
        elif kp.kind == "poly":
            kv = (kp.gamma * dots + kp.coef0) ** kp.degree
        elif kp.kind == "sigmoid":
            kv = jnp.tanh(kp.gamma * dots + kp.coef0)
        else:
            raise ValueError(f"unknown kernel kind {kp.kind!r}")
        return (jnp.einsum("knm,km->kn", kv, coef) - b[:, None]).T

    _STACKED_BATCH = batch
    return batch


_STACKED_BATCH = None


def _compacted_batch_factory():
    """Module-level jitted compacted evaluator (lazy jax import; cached
    on the wrapper OBJECT — see _stacked_batch_factory for why)."""
    global _COMPACT_BATCH
    if _COMPACT_BATCH is not None:
        return _COMPACT_BATCH
    from functools import partial

    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("kp",))
    def batch(qb, sv, coef_pad, idx, b, kp):
        # ONE kernel matmul against the SV union for ALL k columns —
        # vs the stacked path's (k, n, m_pad) replicated chain — then an
        # EXACT contraction: gather each submodel's kernel values back
        # into ITS OWN SV order and contract exactly as the stacked
        # einsum does. The per-(model, query) reduction then sums
        # identical terms in the identical order (pad slots contribute
        # exact +0.0 in both), so the result is BIT-IDENTICAL to
        # _stacked_decision (pinned in tests/test_compacted.py) while
        # the kernel work shrank ~k x. The serving engine's dense
        # K @ coef contraction (serve.py) trades this bit guarantee for
        # the smaller (S, k) operand.
        #
        # Orientation per kernel family: bit-parity additionally needs
        # the contraction operand in the same PHYSICAL layout XLA
        # materializes for the stacked chain, and that choice differs by
        # kernel — the rbf/poly stacked chain materializes kernel values
        # (k, n, m)-contiguous, while linear/sigmoid keep the raw
        # (k*m, n) dot layout. Mirror each (the tests pin it; an XLA
        # upgrade that shifts a layout shows up as a parity failure, the
        # same contract as the repo's other compiled-program pins).
        from dpsvm_tpu.ops.kernels import kernel_from_dots

        qsq = jnp.einsum("nd,nd->n", qb, qb)
        ssq = jnp.einsum("sd,sd->s", sv, sv)
        if kp.kind in ("rbf", "poly"):
            dots = jnp.dot(qb, sv.T,
                           preferred_element_type=jnp.float32)  # (n, S)
            kv = kernel_from_dots(dots, ssq, qsq, kp)
            kg = kv[:, idx]  # (n, k, m_pad) gather — no recompute
            return (jnp.einsum("nkm,km->kn", kg, coef_pad)
                    - b[:, None]).T
        if kp.kind in ("linear", "sigmoid"):
            dots = jnp.dot(sv, qb.T,
                           preferred_element_type=jnp.float32)  # (S, n)
            kv = kernel_from_dots(dots, qsq, ssq, kp)
            kg = kv[idx]  # (k, m_pad, n) row gather
            return (jnp.einsum("kmn,km->kn", kg, coef_pad)
                    - b[:, None]).T
        raise ValueError(f"unknown kernel kind {kp.kind!r}")

    _COMPACT_BATCH = batch
    return batch


_COMPACT_BATCH = None


def _compacted_decision(ens: CompactedEnsemble, q, block: int) -> np.ndarray:
    """All submodels' decision values through the compacted path:
    (n, k) float32, bit-identical to _stacked_decision (tests pin it)."""
    import jax.numpy as jnp

    k, m_pad = ens.idx.shape
    s_union = int(ens.sv_union.shape[0])  # incl. the trailing pad row
    d = ens.sv_union.shape[1]
    if s_union == 0:
        # Degenerate all-empty ensemble: the decision is exactly -b.
        q = np.asarray(q, np.float32)
        return np.broadcast_to(-ens.b, (q.shape[0], k)).astype(np.float32)
    sv_d, coef_d, idx_d, b_d = ens.device_arrays()
    batch = _compacted_batch_factory()
    # Bound the LARGER of the round's two tiles — the (blk, k, m_pad)
    # gather tensor and the (blk, S) kernel tile — to ~1 GB, then round
    # DOWN to a power of two (same discipline as _stacked_decision: the
    # per-block query pad rounds UP, so a non-power-of-two cap could
    # overshoot 2x).
    blk = max(128, min(block, (1 << 28) // max(1, k * m_pad + s_union)))
    blk = 1 << (blk.bit_length() - 1)
    out = []
    q = np.asarray(q, np.float32)
    for s in range(0, q.shape[0], blk):
        qb = q[s:s + blk]
        nb = qb.shape[0]
        nb_pad = 1 << max(4, (nb - 1).bit_length())
        if nb_pad != nb:
            qp = np.zeros((nb_pad, d), np.float32)
            qp[:nb] = qb
            qb = qp
        out.append(np.asarray(batch(jnp.asarray(qb), sv_d, coef_d,
                                    idx_d, b_d, ens.kernel))[:nb])
    return (np.concatenate(out) if out
            else np.zeros((0, k), np.float32))


# Size-1 device-stack memo for the stacked FALLBACK path, with the
# _XDEV_MEMO/_GRAM_MEMO content-fingerprint discipline (solver/smo.py):
# repeated decision_matrix/vote_matrix calls must not re-upload the
# (k, m_pad, d) replicated stack (hundreds of MB at MNIST-OvO shape)
# per call. Keyed on the stack shape + kernel; validated by per-model
# content fingerprints so in-place mutation rebuilds instead of serving
# stale rows.
_STACK_MEMO: dict = {}


def _stacked_device_stack(models, kp, m_pad: int):
    import jax.numpy as jnp

    from dpsvm_tpu.solver.smo import _host_fingerprint

    k = len(models)
    d = models[0].sv_x.shape[1]
    key = (k, m_pad, d, kp)
    fps = tuple((_host_fingerprint(mm.sv_x),
                 _host_fingerprint(mm.sv_alpha),
                 _host_fingerprint(mm.sv_y), float(mm.b))
                for mm in models)
    ent = _STACK_MEMO.get(key)
    if ent is not None and ent[0] == fps:
        return ent[1]
    sv = np.zeros((k, m_pad, d), np.float32)
    coef = np.zeros((k, m_pad), np.float32)
    b = np.zeros((k,), np.float32)
    for i, mm in enumerate(models):
        ns = mm.sv_x.shape[0]
        sv[i, :ns] = mm.sv_x
        coef[i, :ns] = mm.dual_coef
        b[i] = mm.b
    dev = (jnp.asarray(sv), jnp.asarray(coef), jnp.asarray(b))
    _STACK_MEMO.clear()  # size-1 discipline: never hold two stacks
    _STACK_MEMO[key] = (fps, dev)
    return dev


def _stacked_decision(models, q, block: int) -> np.ndarray:
    """All submodels' decision values in ONE batched dispatch per query
    block: (n, n_models) float32.

    Per-model prediction costs a device round-trip per model per block —
    through a tunneled runtime that is ~1 s of latency each, and a
    45-model OvO predict spent minutes on ~90 dispatches while the
    actual MXU work was milliseconds (BENCH_MULTICLASS.md round 5).
    Here every model's SVs pad to the shared power-of-two bucket (zero
    dual coefficients contribute nothing), the stack evaluates as one
    (k, nb, m) batched einsum chain, and the dispatch count drops to
    n/block. All submodels share one kernel family by construction
    (train_multiclass replicates config)."""
    import jax.numpy as jnp

    kp = models[0].kernel
    d = models[0].sv_x.shape[1]
    m_pad = 1 << max(4, (max(mm.sv_x.shape[0] for mm in models) - 1)
                     .bit_length())
    k = len(models)
    sv_d, coef_d, b_d = _stacked_device_stack(models, kp, m_pad)

    batch = _stacked_batch_factory()

    # Bound the (k, nb, m) kernel tile: shrink the query block so the
    # tile stays under ~1 GB regardless of model count / bucket size,
    # then round DOWN to a power of two — the per-block query pad below
    # rounds nb UP to a power of two, so a non-power-of-two cap would
    # let the PADDED tile overshoot the budget by up to 2x (ADVICE
    # round-5, low).
    blk = max(128, min(block, (1 << 28) // max(1, k * m_pad)))
    blk = 1 << (blk.bit_length() - 1)
    out = []
    q = np.asarray(q, np.float32)
    for s in range(0, q.shape[0], blk):
        qb = q[s:s + blk]
        nb = qb.shape[0]
        nb_pad = 1 << max(4, (nb - 1).bit_length())
        if nb_pad != nb:
            qp = np.zeros((nb_pad, d), np.float32)
            qp[:nb] = qb
            qb = qp
        out.append(np.asarray(batch(jnp.asarray(qb), sv_d, coef_d, b_d,
                                    kp))[:nb])
    return (np.concatenate(out) if out
            else np.zeros((0, k), np.float32))


def decision_matrix(m: MulticlassSVM, q, block: int = 8192,
                    path: str = "auto") -> np.ndarray:
    """Raw decision values, one column per fitted model: (n, k) per-class
    scores for OvR, (n, k*(k-1)/2) pairwise columns (a<b order) for OvO.

    path: "auto" routes shared-kernel ensembles through the compacted
    SV-union path (ONE kernel matmul for all k columns; bit-identical to
    the stacked path) and mixed-kernel ensembles through the per-model
    loop. "compacted" / "stacked" force those paths (raising on mixed
    kernels — kept for A/B benchmarking, tools/bench_serve.py);
    "per_model" forces the sequential decision_function loop."""
    q = np.asarray(q, np.float32)
    shared = m.shared_kernel()
    if path == "auto":
        path = "compacted" if shared else "per_model"
    if path in ("compacted", "stacked") and not shared:
        raise ValueError(
            f"path={path!r} needs all submodels on one shared kernel; "
            "this ensemble mixes kernels (use path='per_model')")
    if path == "compacted":
        return _compacted_decision(m.ensure_compacted(), q, block)
    if path == "stacked":
        return _stacked_decision(m.models, q, block)
    if path != "per_model":
        raise ValueError(
            f"unknown path {path!r}; use 'auto', 'compacted', 'stacked' "
            "or 'per_model'")
    return np.stack(
        [decision_function(mm, q, block) for mm in m.models], axis=1)


def ovo_vote_fold(dec: np.ndarray, k: int) -> np.ndarray:
    """(n, k(k-1)/2) pairwise decision columns (a<b order) -> (n, k)
    vote+confidence scores. Host numpy fold shared by vote_matrix and
    the serving engine (serve.py): pairwise votes plus a sub-unit
    confidence term (sklearn's ovo->ovr transformation shape) so ties
    rank by margin while vote order is never overturned."""
    dec = np.asarray(dec, np.float64)
    votes = np.zeros((dec.shape[0], k), np.float64)
    conf = np.zeros((dec.shape[0], k), np.float64)
    idx = 0
    for a in range(k):
        for b in range(a + 1, k):
            d = dec[:, idx]
            win_a = d >= 0
            votes[:, a] += win_a
            votes[:, b] += ~win_a
            conf[:, a] += d
            conf[:, b] -= d
            idx += 1
    return votes + conf / (3.0 * (np.abs(conf) + 1.0))


def vote_matrix(m: MulticlassSVM, q, block: int = 8192,
                path: str = "auto") -> np.ndarray:
    """(n, k) per-class scores for an OvO model (see ovo_vote_fold)."""
    if m.strategy != "ovo":
        return decision_matrix(m, q, block, path=path)
    q = np.asarray(q, np.float32)
    # One compacted device pass for all pairwise columns (see
    # _compacted_decision); the vote fold is host numpy.
    return ovo_vote_fold(decision_matrix(m, q, block, path=path),
                         len(m.classes))


def accuracy_multiclass(m: MulticlassSVM, q, y, block: int = 8192) -> float:
    return float(np.mean(predict_multiclass(m, q, block) == np.asarray(y)))
