"""Persistent serving engine: compacted multiclass inference under
bucketed micro-batching.

The north star is serving heavy traffic, and the per-call inference
entry points pay two costs a persistent server must not: the SV
operands re-stage host->device per call (at MNIST-OvO shape the stacked
(k, m_pad, d) fallback is ~578 MB of f32 per upload), and every distinct
query-batch shape compiles a fresh XLA executor. ``PredictServer`` keeps
the compacted SV union (models/multiclass.py CompactedEnsemble) RESIDENT
on device, pre-compiles a small set of power-of-two query buckets at
startup, and micro-batches queued requests into the next bucket — so a
steady request stream costs one kernel matmul per merged batch and zero
compiles/uploads.

Decision algebra (the serving contraction): ``K(Q, sv_union) @ coef - b``
— ONE (n, S) kernel matmul for all k submodel columns plus a cheap
(S, k) coefficient contraction. This is the dense sibling of the
model-layer exact path (multiclass._compacted_decision, which gathers
per-model kernel values to stay bit-identical to the stacked fallback);
dense reduction order differs from the stacked path by ~1e-7 relative
(float32 associativity), which the risk router below covers where it
could matter.

Numerics routing: submodels whose a-priori fp32 noise estimate
(predict.decision_risk) crosses ``predict.AUTO_F64_RISK`` are evaluated
on the exact host float64 path instead (the PARITY.md
59%-sign-agreement footgun, auto-routed). bf16 SV storage (halved
union footprint/bandwidth, f32 accumulation) sits behind the existing
bf16 quality guard (ops/kernels.py bf16_rbf_perturbation).

Mesh variant: ``ServeConfig(num_devices>1)`` shards the SV union rows
over a data mesh (parallel/mesh.py shard_padded_rows — the same pattern
as predict._mesh_decision_executor) and psums partial decision columns,
so serving memory scales with device count.
"""

from __future__ import annotations

import functools
import time
import warnings
from functools import partial
from typing import Union

import numpy as np

from dpsvm_tpu.config import ServeConfig
from dpsvm_tpu.obs import compilelog, run_obs
from dpsvm_tpu.obs import export as openmetrics
from dpsvm_tpu.obs.metrics import Registry
from dpsvm_tpu.obs.trace import span
from dpsvm_tpu.models.multiclass import (CompactedEnsemble, MulticlassSVM,
                                         compact_models, ovo_vote_fold)
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.predict import AUTO_F64_RISK, decision_risk_columns

# Per-dispatch kernel-tile budget in f32 elements (~1 GB), matching the
# model-layer blocking discipline (multiclass._compacted_decision).
_TILE_BUDGET_ELEMS = 1 << 28

_DENSE_BATCH = None


def effective_buckets(buckets, s_rows: int) -> tuple:
    """Trim the configured power-of-two buckets so the per-dispatch
    (bucket, S) kernel tile stays under the ~1 GB budget — a
    covtype-scale union must shrink its large buckets instead of
    OOMing during warm-up. Shared by PredictServer and the v2 engine's
    union groups (serving/dispatch.py)."""
    cap = max(1, _TILE_BUDGET_ELEMS // max(1, s_rows))
    cap = 1 << (cap.bit_length() - 1)  # floor to a power of two
    return tuple(b for b in buckets if b <= cap) or (cap,)


#: The ladder ``buckets=None`` starts from — the ServeConfig default.
DEFAULT_BUCKETS = (16, 64, 256, 1024, 4096)


def resolve_buckets(config: ServeConfig, device_kind: str = ""):
    """``ServeConfig.buckets`` resolution (ISSUE 17 second axis — the
    solver's ``resolve_auto_gate`` discipline applied to the bucket
    ladder). Returns ``(ladder, provenance)``.

    An explicit tuple ALWAYS wins: ``{"source": "config"}``, no
    profile consulted, no auto-apply. ``buckets=None`` consults the
    installed DeviceProfile's ``serve_buckets`` probe (graduated from
    PR 14's report-only advice): the ladder starts at the default
    either way — the probe measures whether dispatch cost tracks the
    bucket on this device, not what traffic will arrive — and the
    provenance carries ``auto_apply``, True only on an AUTHORITATIVE
    pays verdict (the honesty rule: CPU-harness verdicts pin False,
    so CI never auto-applies). With ``auto_apply`` the v2 engine
    applies its own occupancy suggestion (engine_core.suggest_buckets)
    between serving legs, recording what it applied in the same
    provenance record."""
    if config.buckets is not None:
        return tuple(config.buckets), {
            "source": "config",
            "buckets": [int(b) for b in config.buckets]}
    from dpsvm_tpu.autotune.profile import gate_decision
    gd = gate_decision("serve_buckets", device_kind or None)
    if gd is None:
        return DEFAULT_BUCKETS, {
            "source": "default", "buckets": list(DEFAULT_BUCKETS),
            "auto_apply": False,
            "note": "no profile decision for serve_buckets; "
                    "default ladder"}
    return DEFAULT_BUCKETS, {
        "source": "profile", **gd,
        "buckets": list(DEFAULT_BUCKETS),
        "auto_apply": bool(gd["decision"])}


def stage_union_host(sv_f32: np.ndarray, storage: str):
    """Host-side union staging for one RESOLVED storage: returns
    ``(sv_store, sv_scale, sv_sq)`` — the rows in their storage dtype,
    the per-row f32 dequant scales (None except int8), and the squared
    norms computed from the ROUNDED/DEQUANTIZED values the dot
    operands actually carry (the serve.py norms discipline). ONE
    definition shared by PredictServer._stage and the v2 engine's
    UnionGroup."""
    if storage == "bf16":
        import ml_dtypes
        sv_store = sv_f32.astype(ml_dtypes.bfloat16)
        sv_sq = (sv_store.astype(np.float32) ** 2).sum(
            1, dtype=np.float32)
        return sv_store, None, sv_sq
    if storage == "int8":
        from dpsvm_tpu.ops.kernels import (dequantize_rows_int8,
                                           quantize_rows_int8)
        sv_q, scales = quantize_rows_int8(sv_f32)
        deq = dequantize_rows_int8(sv_q, scales)
        sv_sq = (deq * deq).sum(1, dtype=np.float32)
        return sv_q, scales, sv_sq
    if storage != "f32":
        raise ValueError(f"unknown union storage {storage!r}")
    return sv_f32, None, (sv_f32 * sv_f32).sum(1, dtype=np.float32)


def union_nbytes(storage: str, s_rows: int, d: int) -> int:
    """Resident union-operand bytes at a storage: rows plus (for int8)
    the per-row f32 dequant scales — the serving_union_bytes gauge's
    one definition, and the 4x-cut arithmetic the bench leg reports."""
    per_elem = {"f32": 4, "bf16": 2, "int8": 1}[storage]
    return s_rows * d * per_elem + (4 * s_rows
                                    if storage == "int8" else 0)


def resolve_union_storage(ens, kp, requested: str,
                          stacklevel: int = 4):
    """The ONE serving storage guard (ISSUE 17): decide what precision
    the SV union actually stages at for THIS model, given the
    REQUESTED ``ServeConfig.union_storage`` ('f32'|'bf16'|'int8'|
    'auto'). The decision-sum perturbation from storage rounding is
    bounded per column by ``||coef||_1 * |dK|``, so the risk scale is
    the max column L1 norm times the sampled p90 kernel perturbation
    (ops/kernels.storage_perturbation — bf16 cast or int8 per-row
    quantization round-trip, every feature kernel family), against the
    same calibrated threshold as training's bf16-Gram gate.

    Semantics per request:
      * 'f32'  — trivially accepted (no storage rounding).
      * 'bf16' — legacy warn-but-proceed (the pre-int8 dtype=
        'bfloat16' contract, pinned by tests): stages bf16 either
        way, with a LOUD warning + note when the bound refuses.
      * 'int8' — the bound ADJUDICATES: refused int8 falls back to
        the widest narrower storage the same bound accepts (bf16,
        else f32) with a loud warning + note — quantized serving is
        never silently wrong, and never silently degrades either.
      * 'auto' — narrowest storage the bound accepts (int8 -> bf16 ->
        f32), silently: auto is a request to pick, not a promise.

    Precomputed-kernel ensembles and empty unions have no feature rows
    to round — they resolve to 'f32'. Risk-routed f64 columns always
    see the unquantized union regardless (the _overwrite_f64 paths
    read ``ens.sv_union`` raw).

    Returns ``(effective_storage, entry)`` where ``entry`` is the
    JSON-able guard record (requested/effective/risks/threshold and a
    loud ``note`` on refusal) that staging merges into its stats.
    Shared by PredictServer._stage and the v2 engine's registration
    path (serving/dispatch._prepare_entry)."""
    from dpsvm_tpu.ops.kernels import (BF16_RISK_THRESHOLD,
                                       storage_perturbation)
    if requested not in ("f32", "bf16", "int8", "auto"):
        raise ValueError(f"unknown union storage {requested!r}")
    entry = {"requested": requested,
             "threshold": BF16_RISK_THRESHOLD}
    if requested == "f32":
        entry.update(effective="f32", risks={"f32": 0.0})
        return "f32", entry
    sv = np.asarray(ens.sv_union, np.float32)
    if kp.kind == "precomputed" or sv.shape[0] == 0:
        entry.update(effective="f32", risks={},
                     note="no feature rows to quantize (precomputed "
                          "kernel or empty union); union stays f32")
        return "f32", entry
    l1 = float(np.abs(ens.coef).sum(axis=0).max())
    risks: dict = {}

    def accepts(storage: str) -> bool:
        risks[storage] = round(
            l1 * storage_perturbation(sv, kp, storage), 6)
        return risks[storage] <= BF16_RISK_THRESHOLD

    if requested == "auto":
        for st in ("int8", "bf16"):
            if accepts(st):
                entry.update(effective=st, risks=risks)
                return st, entry
        entry.update(effective="f32", risks=risks,
                     note="auto storage: int8 and bf16 both exceed "
                          "the perturbation bound; union stays f32")
        return "f32", entry
    if accepts(requested):
        entry.update(effective=requested, risks=risks)
        return requested, entry
    if requested == "int8":
        effective = "bf16" if accepts("bf16") else "f32"
        note = (
            f"union_storage='int8' REFUSED for this model: max-column "
            f"||coef||_1 * p90|dK| = {risks['int8']:.4g} > "
            f"{BF16_RISK_THRESHOLD} — per-row int8 quantization at "
            f"this (coef, kernel, data) risks O(1) decision changes "
            f"(the training bf16 guard's amplification mechanism, "
            f"ops/kernels.py); union stays {effective}")
        entry.update(effective=effective, risks=risks, note=note)
        warnings.warn(note, stacklevel=stacklevel)
        return effective, entry
    # requested == "bf16": legacy warn-but-proceed contract.
    note = (
        f"dtype='bfloat16' is likely to perturb decision values "
        f"for this model: max-column ||coef||_1 * p90|dK| = "
        f"{risks['bf16']:.3f} > {BF16_RISK_THRESHOLD} (same "
        f"amplification mechanism as training's bf16 guard, "
        f"ops/kernels.py). Use dtype='float32' for this ensemble.")
    entry.update(effective="bf16", risks=risks, note=note)
    warnings.warn(note, stacklevel=stacklevel)
    return "bf16", entry


def warn_if_bf16_serving_risky(ens, kp, stacklevel: int = 4) -> None:
    """The serving analog of ops/kernels.warn_if_bf16_degrades,
    generalized off rbf-only onto every feature kernel family (ISSUE
    17 satellite — linear/poly/sigmoid serving previously skipped the
    guard silently): delegates to the shared storage guard's 'bf16'
    arm, which warns loudly when max-column ||coef||_1 * p90|dK|
    crosses the calibrated threshold."""
    if kp.kind == "precomputed":
        return
    resolve_union_storage(ens, kp, "bf16", stacklevel=stacklevel + 1)


def _dense_batch_factory():
    """Single-device jitted serving executor (lazy jax import; cached on
    the wrapper object so predict calls never retrace — the
    multiclass._stacked_batch_factory discipline)."""
    global _DENSE_BATCH
    if _DENSE_BATCH is not None:
        return _DENSE_BATCH
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import kernel_from_dots

    @partial(jax.jit, static_argnames=("kp",))
    def batch(qb, sv, sv_sq, coef, b, kp):
        # Queries round THROUGH the storage dtype (identity for f32):
        # with bf16 storage both dot operands are bf16 (halved MXU read
        # bandwidth) and the rbf norms must see the same rounded values
        # or the |q|^2 + |s|^2 - 2 q.s expansion is inconsistent.
        qc = qb.astype(sv.dtype)
        dots = jnp.dot(qc, sv.T, preferred_element_type=jnp.float32)
        qf = qc.astype(jnp.float32)
        kv = kernel_from_dots(dots, sv_sq,
                              jnp.einsum("nd,nd->n", qf, qf), kp)
        return kv @ coef - b[None, :]

    _DENSE_BATCH = batch
    return batch


_DENSE_BATCH_INT8 = None


def _dense_batch_int8_factory():
    """Single-device jitted int8 serving executor (ISSUE 17): the
    dequant-fused sibling of _dense_batch_factory. The union rows
    arrive PRE-quantized (staging-time, ops/kernels.quantize_rows_int8
    — symmetric per-row, f32 scales); queries quantize per-row ON
    DEVICE, the dot runs int8 x int8 on the MXU with i32 accumulation
    (EXACT — integer dots carry no rounding), and one fused rank-1
    rescale ``i32 * (t_q ⊗ s_sv)`` dequantizes straight into the f32
    decision algebra. HBM reads of the union are 1/4 of f32 storage.
    rbf norms come from the DEQUANTIZED values on both sides — the
    dot operands' values — or the |q|^2 + |s|^2 - 2 q.s expansion is
    inconsistent (the bf16 path's norms-from-ROUNDED-rows
    discipline); sv_sq is precomputed host-side from the dequantized
    union at staging."""
    global _DENSE_BATCH_INT8
    if _DENSE_BATCH_INT8 is not None:
        return _DENSE_BATCH_INT8
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import kernel_from_dots

    @partial(jax.jit, static_argnames=("kp",))
    def batch(qb, sv_q, sv_scale, sv_sq, coef, b, kp):
        qf = qb.astype(jnp.float32)
        t = jnp.max(jnp.abs(qf), axis=1) / 127.0
        t = jnp.where(t > 0, t, 1.0)
        q_q = jnp.clip(jnp.round(qf / t[:, None]),
                       -127, 127).astype(jnp.int8)
        idots = jnp.dot(q_q, sv_q.T, preferred_element_type=jnp.int32)
        dots = idots.astype(jnp.float32) * (t[:, None]
                                            * sv_scale[None, :])
        qd = q_q.astype(jnp.float32) * t[:, None]
        kv = kernel_from_dots(dots, sv_sq,
                              jnp.einsum("nd,nd->n", qd, qd), kp)
        return kv @ coef - b[None, :]

    _DENSE_BATCH_INT8 = batch
    return batch


@functools.lru_cache(maxsize=16)
def _mesh_serve_executor(n_dev: int, kp, dtype_str: str):
    """(mesh, mapped) for the union-sharded serving decision: each device
    holds S/n_dev union rows (+ matching coefficient rows) and computes a
    partial (n, k) contraction; one psum combines the columns. Cached per
    mesh-width/kernel/storage-dtype (jit caches by function identity —
    the predict._mesh_decision_executor discipline).

    ``dtype_str == 'int8'`` selects the quantized variant (ISSUE 17):
    the operand tuple gains the per-row f32 scales, which SHARD WITH
    their union row blocks (same P(DATA_AXIS) spec — scale i belongs
    to row i wherever that row lands); queries quantize per-row on
    device identically on every mesh member (replicated input, same
    values), the local dequant-fused partial contraction is the
    single-chip algebra on the local rows, and the psum combine is
    UNCHANGED. Pad rows are zeros with zero coefficient rows, so they
    stay inert exactly as in the f32/bf16 shardings (their scale pads
    to 0, zeroing the pad dots before the kernel map; the zero coef
    rows zero the contraction regardless)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dpsvm_tpu.ops.kernels import kernel_from_dots
    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         mesh_shard_map)

    mesh = make_data_mesh(n_dev)

    if dtype_str == "int8":
        def shard_fn_int8(qb, sv_q_loc, sv_scale_loc, sv_sq_loc,
                          coef_loc, b):
            qf = qb.astype(jnp.float32)
            t = jnp.max(jnp.abs(qf), axis=1) / 127.0
            t = jnp.where(t > 0, t, 1.0)
            q_q = jnp.clip(jnp.round(qf / t[:, None]),
                           -127, 127).astype(jnp.int8)
            idots = jnp.dot(q_q, sv_q_loc.T,
                            preferred_element_type=jnp.int32)
            dots = idots.astype(jnp.float32) * (
                t[:, None] * sv_scale_loc[None, :])
            qd = q_q.astype(jnp.float32) * t[:, None]
            kv = kernel_from_dots(dots, sv_sq_loc,
                                  jnp.einsum("nd,nd->n", qd, qd), kp)
            return lax.psum(kv @ coef_loc, DATA_AXIS) - b[None, :]

        mapped = jax.jit(mesh_shard_map(
            shard_fn_int8, mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P()),
            out_specs=P()))
        return mesh, mapped

    def shard_fn(qb, sv_loc, sv_sq_loc, coef_loc, b):
        qc = qb.astype(sv_loc.dtype)
        dots = jnp.dot(qc, sv_loc.T, preferred_element_type=jnp.float32)
        qf = qc.astype(jnp.float32)
        kv = kernel_from_dots(dots, sv_sq_loc,
                              jnp.einsum("nd,nd->n", qf, qf), kp)
        return lax.psum(kv @ coef_loc, DATA_AXIS) - b[None, :]

    mapped = jax.jit(mesh_shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P()))
    return mesh, mapped


class PredictServer:
    """Persistent multiclass/binary prediction server.

    Request path: ``enqueue(q) -> ticket`` queues query rows;
    ``flush()`` merges everything queued, pads to the smallest
    pre-compiled power-of-two bucket that fits, runs ONE device dispatch
    per bucket batch, and returns ``{ticket: decision rows}``.
    ``decision(q)`` / ``predict(q)`` are the synchronous one-request
    conveniences. All paths share the resident device operands staged at
    construction.
    """

    def __init__(self, model: Union[MulticlassSVM, SVMModel],
                 config: ServeConfig = ServeConfig()):
        self.config = config
        if isinstance(model, MulticlassSVM):
            ens = model.ensure_compacted()
            if ens is None:
                raise ValueError(
                    "PredictServer needs submodels sharing one kernel "
                    "(mixed-kernel ensembles have no SV union to share); "
                    "serve the submodels individually")
            self.classes = np.asarray(model.classes)
            self.strategy = model.strategy
        elif isinstance(model, SVMModel):
            ens = compact_models([model])
            self.classes = None
            self.strategy = "binary"
        else:
            raise TypeError(
                f"cannot serve a {type(model).__name__}; expected "
                "MulticlassSVM or SVMModel")
        self.ens: CompactedEnsemble = ens
        self.kp = ens.kernel
        self.d = int(ens.sv_union.shape[1])
        self.k = ens.n_models

        # --- float64 risk routing (per submodel column) -------------
        self.risks = decision_risk_columns(ens.coef)
        if config.precision == "auto":
            self.f64_cols = np.nonzero(self.risks >= AUTO_F64_RISK)[0]
        elif config.precision == "float64":
            self.f64_cols = np.arange(self.k)
        else:
            self.f64_cols = np.zeros((0,), np.int64)
        self._all_f64 = len(self.f64_cols) == self.k

        # --- effective buckets: explicit config wins; buckets=None
        # resolves through the DeviceProfile serve_buckets gate with
        # full provenance (resolve_buckets). Then cap the per-dispatch
        # (bucket, S) kernel tile at the same ~1 GB budget the
        # model-layer paths bound their tiles to
        # (multiclass._compacted_decision) — a covtype-scale union
        # must trim the large default buckets instead of OOMing
        # during warm-up.
        s_rows = int(self.ens.sv_union.shape[0])
        ladder, self.bucket_provenance = resolve_buckets(config)
        self.buckets = effective_buckets(ladder, s_rows)

        # --- device staging (once; resident for the server lifetime) -
        self._stage()

        # Always-on per-server instruments (dpsvm_tpu/obs/metrics): the
        # bounded-window histograms that replaced the old per-bucket
        # timing deques — same O(window) memory, lock-free observe on
        # the dispatch hot path, and ONE percentile definition shared
        # by offered_load_sweep, `cli serve --server-bench` and
        # tools/bench_serve.py.
        self.metrics = Registry(enabled=True)
        self.request_seconds = self.metrics.histogram(
            "serve.request_seconds")
        # Compile accounting (obs/compilelog.py): executors built while
        # this server lives — warm-up buckets, or the recompile a
        # config/shape bug would cause mid-traffic, which is exactly
        # what the exported `serve_compiles` counter exists to catch.
        self.compiles = self.metrics.counter("serve.compiles_total")
        # The sink holds the server WEAKLY (the RunObs discipline,
        # obs/__init__.py): a strong reference from the module-global
        # sink registry would keep an un-close()d server — and its
        # device-resident union — alive forever, and close() was never
        # mandatory before this counter existed. _in_dispatch scopes
        # the count to THIS server's own dispatches: compiles fire
        # synchronously on the dispatching thread, so another server's
        # warm-up (same "serve/bucket*" labels) lands while this flag
        # is False and is not counted.
        import weakref

        self._in_dispatch = False
        ref = weakref.ref(self)

        def _compile_sink(name, shape, secs, _ref=ref):
            srv = _ref()
            if srv is None:  # server GC'd without close(): self-evict
                compilelog.remove_sink(_compile_sink)
                return
            if srv._in_dispatch and name.startswith("serve/"):
                srv.compiles.add(1)

        self._compile_sink = _compile_sink
        compilelog.add_sink(self._compile_sink)
        self.stats = {
            "requests": 0, "rows": 0, "dispatches": 0, "padded_rows": 0,
            "buckets": self.buckets,
            "bucket_counts": {b: 0 for b in self.buckets},
            # Bounded per-bucket dispatch timings; percentiles come
            # from the histogram's recent window (the deque semantics,
            # now shared).
            "bucket_seconds": {
                b: self.metrics.histogram(f"serve.bucket_seconds.{b}")
                for b in self.buckets},
            "warm_seconds": {}, "f64_columns": len(self.f64_cols),
            # Storage guard outcome (resolve_union_storage): what the
            # union actually staged at, with the risk record — a
            # refused narrow request is never silent in the stats.
            "union_storage": self.union_storage,
            "storage_guard": self.storage_guard,
            "bucket_provenance": self.bucket_provenance,
        }
        # Run-log layer (off unless config.obs / DPSVM_OBS enables it):
        # manifest at construction; close() writes the final snapshot.
        self._obs = run_obs("serve", config,
                            meta={"k": self.k, "d": self.d,
                                  "n_union": int(self.ens.n_union),
                                  "strategy": self.strategy,
                                  "buckets": list(self.buckets),
                                  "f64_columns": len(self.f64_cols),
                                  "union_storage": self.union_storage})
        self._pending: list = []  # (ticket, (n, d) rows)
        self._pending_rows = 0
        self._done: dict = {}
        self._next_ticket = 0
        self._closing = False
        if config.warm_start:
            self.warm()
        # OpenMetrics endpoint (obs/export.py) — started LAST so a
        # scrape never sees a half-constructed server. None = off;
        # 0 = ephemeral port (tests / bench_serve self-scrape). The
        # render callback holds the server WEAKLY: the daemon thread
        # is a GC root, and a bound method would pin an un-close()d
        # server (and its device operands) for the process lifetime.
        self.exporter = None
        if config.metrics_port is not None:
            def _render(_ref=ref):
                srv = _ref()
                if srv is None or srv._closing:
                    # A scrape racing close(): answer the minimal valid
                    # exposition instead of reading state mid-teardown.
                    return "# EOF\n"
                return srv.render_openmetrics()

            self.exporter = openmetrics.MetricsExporter(
                _render, port=config.metrics_port,
                host=config.metrics_host)

    # ------------------------------------------------------------ staging
    def _stage(self) -> None:
        import jax.numpy as jnp

        cfg = self.config
        # The ONE storage guard (resolve_union_storage): what the
        # union actually stages at for THIS model — refused narrow
        # requests fall back loudly; auto picks the narrowest storage
        # the perturbation bound accepts.
        self.union_storage, self.storage_guard = resolve_union_storage(
            self.ens, self.kp, cfg.effective_union_storage(),
            stacklevel=5)
        sv = np.ascontiguousarray(self.ens.sv_union, np.float32)
        sv_store, sv_scale, sv_sq = stage_union_host(
            sv, self.union_storage)
        coef = np.ascontiguousarray(self.ens.coef, np.float32)
        b = np.ascontiguousarray(self.ens.b, np.float32)

        if self.ens.n_union == 0:
            self._call = None  # decision is exactly -b
            return
        if self._all_f64:
            self._call = None  # every column routes to the host path
            return
        if cfg.num_devices > 1:
            from dpsvm_tpu.parallel.mesh import (replicate_array,
                                                 shard_padded_rows)
            mesh, mapped = _mesh_serve_executor(cfg.num_devices, self.kp,
                                                self.union_storage)
            sv_d = shard_padded_rows(mesh, sv_store)
            sv_sq_d = shard_padded_rows(mesh, sv_sq)
            coef_d = shard_padded_rows(mesh, coef)  # pad rows: coef 0
            b_d = replicate_array(mesh, b)
            if self.union_storage == "int8":
                # Scales shard WITH their row blocks; pad scales are
                # zeros (inert — zero coef rows already silence pads).
                scale_d = shard_padded_rows(mesh, sv_scale)

                def call(qb, _m=mapped, _mesh=mesh):
                    return _m(replicate_array(_mesh, qb), sv_d,
                              scale_d, sv_sq_d, coef_d, b_d)
            else:
                def call(qb, _m=mapped, _mesh=mesh):
                    return _m(replicate_array(_mesh, qb),
                              sv_d, sv_sq_d, coef_d, b_d)
        else:
            sv_d = jnp.asarray(sv_store)
            sv_sq_d = jnp.asarray(sv_sq)
            coef_d = jnp.asarray(coef)
            b_d = jnp.asarray(b)
            if self.union_storage == "int8":
                batch = _dense_batch_int8_factory()
                scale_d = jnp.asarray(sv_scale)

                def call(qb, _kp=self.kp):
                    return batch(jnp.asarray(qb), sv_d, scale_d,
                                 sv_sq_d, coef_d, b_d, _kp)
            else:
                batch = _dense_batch_factory()

                def call(qb, _kp=self.kp):
                    return batch(jnp.asarray(qb), sv_d, sv_sq_d,
                                 coef_d, b_d, _kp)
        self._call = call

    # ------------------------------------------------------------- warmup
    def warm(self) -> dict:
        """Pre-compile every bucket executor on zero queries so the first
        live request never pays a compile. Returns {bucket: seconds}
        (first-call time, i.e. compile + execute)."""
        for bucket in self.buckets:
            t0 = time.perf_counter()
            self._run_bucket(np.zeros((bucket, self.d), np.float32),
                             bucket, warm=True)
            self.stats["warm_seconds"][bucket] = (time.perf_counter()
                                                  - t0)
        return dict(self.stats["warm_seconds"])

    # ----------------------------------------------------------- dispatch
    def _bucket_for(self, n: int) -> int:
        for bucket in self.buckets:
            if n <= bucket:
                return bucket
        return self.buckets[-1]

    def _run_bucket(self, qb: np.ndarray, bucket: int,
                    warm: bool = False) -> np.ndarray:
        """One device dispatch of a bucket-shaped (bucket, d) batch ->
        (bucket, k) float32 decision values (device columns only; f64
        columns are overwritten by the caller on the unpadded rows)."""
        if self._call is None:
            return np.broadcast_to(
                -self.ens.b, (qb.shape[0], self.k)).astype(np.float32)
        # The compile label is independent of the obs switch: the
        # always-on serve_compiles counter attributes executor builds
        # to their bucket even when no run log is live. _in_dispatch
        # scopes the sink to this server (see __init__).
        self._in_dispatch = True
        try:
            with compilelog.label(f"serve/bucket{bucket}",
                                  f"({bucket},{self.d})"), \
                    span(f"serve/bucket{bucket}"):
                t0 = time.perf_counter()
                out = np.asarray(self._call(qb))
                dt = time.perf_counter() - t0
        finally:
            self._in_dispatch = False
        if not warm:
            self.stats["bucket_seconds"][bucket].observe(dt)
        return out

    def decision(self, q) -> np.ndarray:
        """(n, k) decision columns for a query batch, synchronously,
        through the bucketed resident executors. Device columns see the
        queries quantized to float32 (their compute dtype); the
        risk-routed float64 columns see the CALLER'S dtype unquantized
        — the exact-path contract of predict.decision_function."""
        q_in = np.asarray(q)
        if q_in.ndim != 2 or q_in.shape[1] != self.d:
            raise ValueError(
                f"queries must be (n, {self.d}); got {q_in.shape}")
        q32 = np.asarray(q_in, np.float32)
        n = q32.shape[0]
        out = np.empty((n, self.k), np.float32)
        top = self.buckets[-1]
        s = 0
        while s < n:
            take = min(n - s, top)
            bucket = self._bucket_for(take)
            qb = q32[s:s + take]
            if take != bucket:
                qp = np.zeros((bucket, self.d), np.float32)
                qp[:take] = qb
                qb = qp
            out[s:s + take] = self._run_bucket(qb, bucket)[:take]
            self.stats["dispatches"] += 1
            self.stats["bucket_counts"][bucket] += 1
            self.stats["padded_rows"] += bucket - take
            s += take
        self.stats["rows"] += n
        if len(self.f64_cols):
            self._overwrite_f64(q_in, out)
        return out

    def _overwrite_f64(self, q: np.ndarray, out: np.ndarray) -> None:
        """Exact host float64 evaluation of the risk-routed columns
        (predict._decision_f64's algebra via the single shared f64
        kernel definition, solver/reconstruct.gram_matvec_f64)."""
        from dpsvm_tpu.solver.reconstruct import gram_matvec_f64
        q64 = np.asarray(q, np.float64)
        for j in self.f64_cols:
            out[:, j] = (gram_matvec_f64(self.ens.sv_union,
                                         self.ens.coef[:, j], self.kp,
                                         queries=q64)
                         - float(self.ens.b[j])).astype(np.float32)

    # ------------------------------------------------------------- labels
    def labels(self, dec: np.ndarray) -> np.ndarray:
        """Decision columns -> predicted labels (strategy-aware: OvR
        argmax, OvO vote fold, binary sign)."""
        if self.strategy == "binary":
            return np.where(dec[:, 0] >= 0, 1, -1).astype(np.int32)
        if self.strategy == "ovr":
            return self.classes[np.argmax(dec, axis=1)]
        return self.classes[np.argmax(
            ovo_vote_fold(dec, len(self.classes)), axis=1)]

    def predict(self, q) -> np.ndarray:
        return self.labels(self.decision(q))

    # -------------------------------------------------- micro-batch queue
    def enqueue(self, q) -> int:
        """Queue a request's query rows; returns its ticket. Requests
        merge into shared bucket dispatches at the next flush() (forced
        early when the queue crosses max_pending rows). The caller's
        dtype is kept (float64 requests stay exact on risk-routed
        columns; the merged batch promotes to the widest queued
        dtype)."""
        q = np.asarray(q)
        if q.ndim != 2 or q.shape[1] != self.d:
            raise ValueError(
                f"queries must be (n, {self.d}); got {q.shape}")
        ticket = self._next_ticket
        self._next_ticket += 1
        self._pending.append((ticket, q))
        self._pending_rows += q.shape[0]
        self.stats["requests"] += 1
        if self._pending_rows >= self.config.max_pending:
            self._done.update(self._flush_pending())
        return ticket

    def _flush_pending(self) -> dict:
        if not self._pending:
            return {}
        tickets = [t for t, _ in self._pending]
        sizes = [r.shape[0] for _, r in self._pending]
        merged = np.concatenate([r for _, r in self._pending])
        self._pending.clear()
        self._pending_rows = 0
        dec = self.decision(merged)
        out, s = {}, 0
        for t, n in zip(tickets, sizes):
            out[t] = dec[s:s + n]
            s += n
        return out

    def flush(self) -> dict:
        """Run everything queued (merged into bucket batches) and return
        {ticket: (n_i, k) decision rows} for every completed request,
        including any completed by a forced early flush."""
        done = self._done
        self._done = {}
        done.update(self._flush_pending())
        return done

    # ------------------------------------------------------- telemetry
    def snapshot(self) -> dict:
        """JSON-able stats: the plain counters plus every histogram's
        bounded snapshot (count/mean/min/max/p50/p95/p99/log2 bins) —
        the shape the serve run log's final record and the bench tools
        all consume."""
        out = {k: v for k, v in self.stats.items()
               if k not in ("bucket_seconds", "bucket_counts", "buckets")}
        out["buckets"] = list(self.buckets)
        out["bucket_counts"] = {str(b): c for b, c
                                in self.stats["bucket_counts"].items()}
        out["bucket_seconds"] = {
            str(b): h.snapshot()
            for b, h in self.stats["bucket_seconds"].items() if len(h)}
        out["request_seconds"] = self.request_seconds.snapshot()
        out["compiles"] = self.compiles.value
        return out

    def render_openmetrics(self) -> str:
        """The /metrics exposition (OpenMetrics 1.0 text): counters,
        latency summaries (quantiles = the SAME Histogram.percentiles()
        snapshot() reports — a scrape and a snapshot cannot disagree),
        per-model/per-bucket SLO-attainment gauges and the compile
        counter. Reads host-held instruments only — never a device
        dispatch. Callable directly; the HTTP thread
        (config.metrics_port, obs/export.py) serves it on GET."""
        om = openmetrics
        st = self.stats
        model_lb = {"model": self.model_id}
        slo_s = float(self.config.slo_ms) / 1e3
        slo_lb = {"slo_ms": f"{self.config.slo_ms:g}"}

        def attainment(hist) -> float:
            w = hist.window_values()
            return float(np.mean(w <= slo_s)) if w.size else 1.0

        fams = [
            om.counter("serve_requests", "requests enqueued",
                       st["requests"], model_lb),
            om.counter("serve_rows", "query rows served", st["rows"],
                       model_lb),
            om.counter("serve_dispatches", "device dispatches",
                       st["dispatches"], model_lb),
            om.counter("serve_padded_rows",
                       "bucket pad rows dispatched", st["padded_rows"],
                       model_lb),
            om.counter("serve_compiles",
                       "bucket executors compiled while serving",
                       self.compiles.value, model_lb),
            om.gauge("serve_pending_rows",
                     "rows queued for the next flush",
                     [(model_lb, self._pending_rows)]),
            om.gauge("serve_f64_columns",
                     "decision columns risk-routed to host float64",
                     [(model_lb, len(self.f64_cols))]),
            om.gauge("serve_sv_union_rows",
                     "resident SV-union rows",
                     [(model_lb, int(self.ens.n_union))]),
            om.gauge("serve_union_bytes",
                     "resident SV-union operand bytes at the staged "
                     "storage (rows + int8 dequant scales)",
                     [({**model_lb,
                        "union_storage": self.union_storage},
                       union_nbytes(self.union_storage,
                                    int(self.ens.sv_union.shape[0]),
                                    self.d))]),
        ]
        if len(self.request_seconds):
            fams.append(om.summary(
                "serve_request_seconds",
                "request latency (enqueue->flush), recent-window "
                "quantiles", self.request_seconds, labels=model_lb))
        fams.append(om.gauge(
            "serve_slo_attainment",
            "fraction of the recent request-latency window at or "
            "under the objective (1 = vacuous when empty)",
            [({**model_lb, **slo_lb},
              round(attainment(self.request_seconds), 6))]))
        disp = [("_total", {"bucket": str(b)}, c)
                for b, c in st["bucket_counts"].items()]
        fams.append(om.metric(
            "serve_bucket_dispatches", "counter",
            "device dispatches per query bucket", disp))
        bucket_att = []
        bucket_samples = []
        for b, h in st["bucket_seconds"].items():
            if not len(h):
                continue
            bucket_samples.extend(om.summary_samples(
                h, labels={"bucket": str(b)}))
            bucket_att.append(({"bucket": str(b), **slo_lb},
                               round(attainment(h), 6)))
        if bucket_samples:
            fams.append(om.metric(
                "serve_bucket_seconds", "summary",
                "per-dispatch device latency, recent-window "
                "quantiles", bucket_samples))
        if bucket_att:
            fams.append(om.gauge(
                "serve_bucket_slo_attainment",
                "fraction of the recent per-bucket dispatch window "
                "at or under the objective", bucket_att))
        return om.render(fams)

    @property
    def model_id(self) -> str:
        """The `model` label value on exported metrics."""
        return f"{self.strategy}-{self.k}"

    def close(self) -> None:
        """Finish the serve run log (no-op when obs is disabled or
        already closed), stop the /metrics endpoint and detach the
        compile sink; the device-resident operands stay usable.

        Ordering contract (ISSUE 10 satellite): the /metrics endpoint
        shuts down FIRST — before any state the render callback reads
        is torn down — and ``_closing`` makes a scrape already in
        flight on a handler thread answer the minimal valid exposition
        instead of racing the teardown. A scrape concurrent with
        close() therefore sees either a full exposition, the ``# EOF``
        stub, or a clean connection refusal — never a half-torn-down
        read (pinned by the scrape-during-close test)."""
        self._closing = True
        if self.exporter is not None:
            self.exporter.close()
        compilelog.remove_sink(self._compile_sink)
        self._obs.finish(**self.snapshot())


def offered_load_sweep(server: PredictServer, request_sizes,
                       n_requests: int, group: int = 8,
                       seed: int = 0) -> dict:
    """Drive the server with a stream of requests and report throughput
    and latency percentiles (overall per request, and per bucket from
    the server's own per-dispatch timings). `group` requests arrive
    together and share flush dispatches — the micro-batching win the
    sweep exists to measure. Shared by `cli.py serve --server-bench`
    and tools/bench_serve.py."""
    rng = np.random.default_rng(seed)
    sizes = rng.choice(np.asarray(request_sizes), n_requests)
    # Baselines: the histograms are SERVER-LIFETIME instruments (they
    # also feed the serve run log); this sweep's report must cover only
    # the observations THIS sweep adds, or a second sweep on the same
    # server would report percentiles/dispatches contaminated by the
    # first (`last=` scopes the shared window; counts are differenced).
    req_base = server.request_seconds.count
    bucket_base = {b: h.count
                   for b, h in server.stats["bucket_seconds"].items()}
    rows = 0
    t_start = time.perf_counter()
    for s in range(0, n_requests, group):
        batch_sizes = sizes[s:s + group]
        t0 = time.perf_counter()
        for n in batch_sizes:
            server.enqueue(rng.random((int(n), server.d),
                                      dtype=np.float32))
        server.flush()
        t1 = time.perf_counter()
        for _ in batch_sizes:
            server.request_seconds.observe(t1 - t0)
        rows += int(batch_sizes.sum())
    wall = time.perf_counter() - t_start

    # Percentiles come from the server's OWN shared histograms
    # (obs/metrics.Histogram recent-window semantics) — the same
    # instruments `cli serve --server-bench`, tools/bench_serve.py and
    # the serve run log report from — scoped to THIS sweep's
    # observations via the baselines above.
    per_bucket = {}
    for bucket, h in server.stats["bucket_seconds"].items():
        new = h.count - bucket_base[bucket]
        if new:
            per_bucket[str(bucket)] = {
                "dispatches": new, **h.percentiles(last=new)}
    return {
        "requests": int(n_requests), "rows": int(rows), "group": group,
        "wall_seconds": round(wall, 4),
        "rows_per_second": round(rows / max(wall, 1e-9)),
        "requests_per_second": round(n_requests / max(wall, 1e-9)),
        "request_latency": server.request_seconds.percentiles(
            last=server.request_seconds.count - req_base),
        "bucket_latency": per_bucket,
        "dispatches": server.stats["dispatches"],
        "padded_rows": server.stats["padded_rows"],
    }
