"""High-level training API: data in, SVMModel out.

The svmTrainMain.cpp main() equivalent, minus the launcher: picks the
single-chip or distributed (mesh) backend, runs the solver, extracts
support vectors, and optionally reports training accuracy.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.solver.result import SolveResult


def train(
    x,
    y,
    config: SVMConfig = SVMConfig(),
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> tuple[SVMModel, SolveResult]:
    """Train binary C-SVC with modified SMO.

    backend: "auto" | "single" | "mesh" | "reference".
      auto picks "mesh" when >1 device is visible, else "single".
    Labels must be in {-1, +1} (reference convention, parse.cpp label stoi).
    """
    import jax

    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    labels = set(np.unique(y).tolist())
    if labels != {-1, 1}:
        raise ValueError(
            f"labels must contain both classes -1 and +1, got {sorted(labels)}")

    if backend == "auto":
        try:
            from dpsvm_tpu.parallel import dist_smo  # noqa: F401
            mesh_available = True
        except ImportError:
            mesh_available = False
        multi = (num_devices or len(jax.devices())) > 1
        # The fused-pallas engine only exists in the single-chip solver.
        backend = ("mesh" if (multi and mesh_available and config.engine != "pallas")
                   else "single")

    if backend == "reference" and (config.engine != "xla"
                                   or config.selection != "mvp"):
        raise ValueError(
            "backend='reference' is the fixed NumPy oracle (MVP selection, "
            "host math); it cannot honor engine/selection overrides — drop "
            "them or pick another backend")

    if backend == "single":
        from dpsvm_tpu.solver.smo import solve
        result = solve(x, y, config, callback=callback,
                       checkpoint_path=checkpoint_path, resume=resume)
    elif backend == "mesh":
        from dpsvm_tpu.parallel.dist_smo import solve_mesh
        result = solve_mesh(x, y, config, num_devices=num_devices,
                            callback=callback, checkpoint_path=checkpoint_path,
                            resume=resume)
    elif backend == "reference":
        from dpsvm_tpu.solver.reference import smo_reference
        result = smo_reference(x, y, config)
    else:
        raise ValueError(f"unknown backend {backend!r}")

    gamma = config.resolve_gamma(x.shape[1])
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    model = SVMModel.from_dense(x, y, result.alpha, result.b, kp)
    return model, result
