"""High-level training API: data in, SVMModel out.

The svmTrainMain.cpp main() equivalent, minus the launcher: picks the
single-chip or distributed (mesh) backend, runs the solver, extracts
support vectors, and optionally reports training accuracy.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.models.svm_model import SVMModel
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.solver.result import SolveResult


def train(
    x,
    y,
    config: SVMConfig = SVMConfig(),
    backend: str = "auto",
    num_devices: Optional[int] = None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    pad_to: Optional[int] = None,
) -> tuple[SVMModel, SolveResult]:
    """Train binary C-SVC with modified SMO.

    backend: "auto" | "single" | "mesh" | "reference" | "native".
      auto picks "mesh" when >1 device is visible, else "single".
      "reference" is the NumPy oracle; "native" the C++ sequential engine
      (native/seqsmo.cpp) — both host-only, MVP selection.
    callback fires once per solver chunk; a TRUTHY return aborts the
      training cleanly at that chunk boundary (solver/smo.py solve
      docstring) — observation-only callbacks must return None.
    Labels must be in {-1, +1} (reference convention, parse.cpp label stoi).
    pad_to: shape-bucketing HINT (solver/smo.py solve) — OvO multiclass
      rounds its k(k-1)/2 subset sizes up to shared buckets so XLA
      compiles one executor per bucket, not per subset shape. Honored
      by the single-chip backend; the mesh/host backends manage their
      own shapes and ignore it (it never changes results).
    """
    import jax

    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    labels = set(np.unique(y).tolist())
    if labels != {-1, 1}:
        raise ValueError(
            f"labels must contain both classes -1 and +1, got {sorted(labels)}")

    if backend == "auto":
        try:
            from dpsvm_tpu.parallel import dist_smo  # noqa: F401
            mesh_available = True
        except ImportError:
            mesh_available = False
        multi = (num_devices or len(jax.devices())) > 1
        # The fused-pallas engine only exists in the single-chip solver;
        # auto must not silently swap it for a different mesh engine.
        # Likewise the ooc block cache and the shrunken tile stream are
        # single-chip: auto keeps those requests on the single backend
        # (explicit backend="mesh" still rejects the combination).
        single_only_ooc = config.ooc and (
            config.ooc_cache_lines > 0 or config.ooc_shrink
            or config.active_set_size > 0)
        backend = ("mesh" if (multi and mesh_available
                              and config.engine in ("xla", "block")
                              and not single_only_ooc)
                   else "single")

    if config.kernel == "precomputed":
        raise ValueError(
            "kernel='precomputed' models carry SV indices, not feature "
            "rows — the reference-format model file cannot represent "
            "them. Solve directly (dpsvm_tpu.solver.smo.solve) or use "
            "the sklearn facade (dpsvm_tpu.estimators.SVC)")
    if backend in ("reference", "native"):
        if config.engine != "xla" or config.selection != "mvp":
            raise ValueError(
                f"backend={backend!r} is a fixed host engine (MVP selection); "
                "it cannot honor engine/selection overrides — drop them or "
                "pick another backend")
        if checkpoint_path or resume:
            raise ValueError(
                f"backend={backend!r} does not support checkpoint/resume; "
                "use the 'single' or 'mesh' backend for long runs")

    if backend == "single":
        from dpsvm_tpu.solver.smo import solve
        result = solve(x, y, config, callback=callback,
                       checkpoint_path=checkpoint_path, resume=resume,
                       pad_to=pad_to)
    elif backend == "mesh":
        from dpsvm_tpu.parallel.dist_smo import solve_mesh
        result = solve_mesh(x, y, config, num_devices=num_devices,
                            callback=callback, checkpoint_path=checkpoint_path,
                            resume=resume)
    elif backend in ("reference", "native"):
        from dpsvm_tpu.solver.reference import smo_native, smo_reference
        fn = smo_reference if backend == "reference" else smo_native
        result = fn(x, y, config)
        if callback is not None:
            # Host engines run to completion in one shot; report one final
            # record so metrics sinks aren't silently empty. The namespace
            # mirrors the SMOState fields a chunk callback can rely on.
            from types import SimpleNamespace
            callback(result.iterations, result.b_hi, result.b_lo,
                     SimpleNamespace(
                         alpha=result.alpha, f=result.stats["f"],
                         b_hi=result.b_hi, b_lo=result.b_lo,
                         it=result.iterations, hits=0))
    else:
        raise ValueError(f"unknown backend {backend!r}")

    gamma = config.resolve_gamma(x.shape[1])
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    model = SVMModel.from_dense(x, y, result.alpha, result.b, kp)
    return model, result
