"""Replica fleet: N serving engines behind one front door (ISSUE 16).

One :class:`~dpsvm_tpu.serving.dispatch.ServingEngine` is single-driver
by design — one pump thread owns admission, dispatch and routing, and
on the device side one engine drives one accelerator (or one mesh of
them, via the UnionGroup mesh variant). That is the SCALE-DOWN axis.
This module is the SCALE-OUT axis: a :class:`ReplicaFleet` constructs
N engines from one ``ServeConfig`` (``replicas=N``) and hands them to
the network front door (serving/server.py), whose per-replica pump
threads route the shared inbox to whichever replica has room. The
fleet itself holds NO routing logic — routing lives in the front
door's pump/admission layer, the one place every frame already passes
through — and NO request state: it is the fleet's job to keep the N
engines' MODEL SETS identical and their lifecycles coordinated:

* REGISTRATION fans out: ``register``/``swap``/``unregister`` apply to
  every replica in fleet order, so version counters advance in
  lockstep and any replica answers any model at the same version.
  A mid-loop failure raises after rolling the already-updated
  replicas back where possible — a split fleet is the failure mode
  this loop exists to prevent (see ``swap``).
* THE REGISTRY JOURNAL IS THE SHARED SOURCE OF TRUTH: every replica
  attaches the SAME ``journal_path``. Each register/swap atomically
  rewrites the whole-set snapshot, and because every replica applies
  the same ops in the same order at the same versions, the N writes
  are byte-identical — last-writer-wins is idempotent. A restarted
  replica rehydrates from that one file and comes back serving the
  exact versions its peers are serving; ``swap`` therefore coordinates
  across replicas with zero downtime (in-flight work finishes on the
  old version per engine, exactly the single-engine hot-swap
  contract).
* ROLLING RESTART (:meth:`restart_replica`): drain replica k through
  the front door (its pump stops popping, queued work finishes or
  sheds through the normal verdicts, peers keep serving), close its
  engine, construct a fresh one that rehydrates from the shared
  journal, resume. Zero lost or duplicated frames — pinned by
  tests/test_serve_replicas.py under sustained load.

The fleet owns the /metrics exporter (engines are built with
``metrics_port=None``) so one scrape exposes the whole fleet:
``serving_fleet_*`` aggregates plus the front door's
``serving_replica_*`` and ``serving_net_*`` families.
"""

from __future__ import annotations

import threading
from typing import Optional

from dpsvm_tpu.config import ServeConfig
from dpsvm_tpu.obs import export as openmetrics
from dpsvm_tpu.obs import run_obs
from dpsvm_tpu.serving.dispatch import ServingEngine


class ReplicaFleet:
    """``config.replicas`` ServingEngines with identical model sets,
    one shared registry journal, one /metrics exposition — the object
    ``cli serve --listen --replicas N`` hands to ServeServer.

    Duck-type contract with the front door: ``engines`` (list, read
    through on every pump iteration so restarts are picked up live),
    ``config``, ``_obs``, ``attach_net``. Model admin (register/swap/
    unregister) may run on any thread — per-engine it lands on the
    registry's admin path, same as a standalone engine."""

    def __init__(self, config: ServeConfig = ServeConfig()):
        if config.replicas < 1:
            raise ValueError("ReplicaFleet needs replicas >= 1")
        self.config = config
        # Engines never bind their own metrics port (the fleet owns
        # the exposition) and individually look like single-replica
        # configs — replica identity is the constructor arg, stamped
        # into each engine's run-log manifest.
        self._eng_config = config.replace(metrics_port=None, replicas=1)
        self._obs = run_obs("serve", config,
                            meta={"engine": "serving_fleet",
                                  "replicas": config.replicas,
                                  "buckets": list(config.buckets),
                                  "dtype": config.dtype,
                                  "deadline_ms": config.deadline_ms})
        self._front = None
        self._closed = False
        self._lifecycle = threading.RLock()
        self.exporter = None
        self.engines: list = []
        try:
            for i in range(config.replicas):
                self.engines.append(
                    ServingEngine(self._eng_config, replica=i))
            self._obs.event("fleet_up", replicas=len(self.engines),
                            journal=bool(config.journal_path))
            if config.metrics_port is not None:
                import weakref

                ref = weakref.ref(self)

                def _render(_ref=ref):
                    fleet = _ref()
                    if fleet is None or fleet._closed:
                        return "# EOF\n"
                    return fleet.render_openmetrics()

                self.exporter = openmetrics.MetricsExporter(
                    _render, port=config.metrics_port,
                    host=config.metrics_host)
        except BaseException:
            # Half-built fleet: tear down what exists (a leaked engine
            # keeps its compile sink and run log; a leaked exporter
            # keeps the port bound).
            for eng in self.engines:
                try:
                    eng.close()
                except Exception:
                    pass
            if self.exporter is not None:
                self.exporter.close()
            self._obs.finish(aborted=True)
            raise

    # ------------------------------------------------------ registration
    def register(self, name: str, source):
        """Register on EVERY replica (fleet order). Returns the last
        replica's entry — all N are at the same version by
        construction. A failure on replica j unregisters the j
        already-registered replicas so the fleet never serves a model
        from some replicas and 'unknown model' from others."""
        done = []
        try:
            for eng in self.engines:
                entry = eng.register(name, source)
                done.append(eng)
        except BaseException:
            for eng in done:
                try:
                    eng.unregister(name)
                except Exception:
                    pass
            raise
        self._obs.event("fleet_register", model=name,
                        version=entry.version,
                        replicas=len(self.engines))
        return entry

    def swap(self, name: str, source):
        """Hot-swap on EVERY replica. Each engine runs the full
        validate-stage-warm path before its routing flip, so a bad
        model fails on replica 0 BEFORE any replica flipped — the
        common failure (bad source) leaves the fleet untouched on the
        old version. A failure after some replicas flipped (rarer:
        resource exhaustion mid-loop) raises with the fleet split; the
        caller retries the swap, which is idempotent per engine. Every
        flip journals the same whole-set snapshot, so a replica
        restarting at ANY instant rehydrates to a version some live
        replica is serving."""
        entry = None
        flipped = 0
        try:
            for eng in self.engines:
                entry = eng.swap(name, source)
                flipped += 1
        except BaseException:
            if flipped:
                self._obs.event("fleet_swap_split", model=name,
                                flipped=flipped,
                                replicas=len(self.engines))
            raise
        self._obs.event("fleet_swap", model=name,
                        version=entry.version,
                        replicas=len(self.engines))
        return entry

    def unregister(self, name: str):
        out = None
        for eng in self.engines:
            out = eng.unregister(name)
        self._obs.event("fleet_unregister", model=name,
                        replicas=len(self.engines))
        return out

    # -------------------------------------------------- rolling restart
    def restart_replica(self, rep: int, timeout_s: float = 60.0):
        """Rolling restart of one replica with zero downtime: drain it
        through the front door (peers keep serving), close its engine,
        construct a fresh one — which REHYDRATES the model set from
        the shared registry journal at the exact versions its peers
        serve — and resume its pump. Returns the fresh engine.

        Requires a journal (``config.journal_path``) for the model set
        to survive the restart; without one the fresh engine comes up
        empty and the caller must re-register (in-memory models are
        never journaled — the single-engine crash-recovery contract).
        """
        if not 0 <= rep < len(self.engines):
            raise ValueError(f"replica {rep} out of range "
                             f"(0..{len(self.engines) - 1})")
        with self._lifecycle:
            if self._closed:
                raise RuntimeError("fleet is closed")
            self._obs.event("restart_replica", phase="begin",
                            replica=rep)
            if self._front is not None:
                self._front.drain_replica(rep, timeout_s=timeout_s)
            old = self.engines[rep]
            old.close()
            fresh = ServingEngine(self._eng_config, replica=rep)
            # Engines are read through this list on every pump
            # iteration — publishing the fresh engine here is the
            # whole swap.
            self.engines[rep] = fresh
            if self._front is not None:
                self._front.resume_replica(rep)
            self._obs.event("restart_replica", phase="end",
                            replica=rep,
                            rehydrated=list(fresh._rehydrated))
            return fresh

    # ---------------------------------------------------------- lifecycle
    def attach_net(self, front) -> None:
        """Attach the network front door: its per-replica pump threads
        drive the engines from here on; the fleet's snapshot and
        /metrics exposition read its routing state."""
        self._front = front

    def drain(self) -> dict:
        """Pump every replica to quiescence (in-process convenience —
        behind a front door, :meth:`ServeServer.drain` is the real
        drain). Returns {replica: results-dict}."""
        return {i: eng.drain() for i, eng in enumerate(self.engines)}

    def close(self) -> None:
        """Close every replica and the fleet exposition. Never touches
        an attached front door — callers own ``server.close()`` BEFORE
        ``fleet.close()`` (the cli teardown ordering), same as the
        single-engine contract."""
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            if self.exporter is not None:
                self.exporter.close()
            for eng in self.engines:
                eng.close()
            self._obs.finish(**self.snapshot())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------- telemetry
    def snapshot(self) -> dict:
        """JSON-able fleet state: aggregates plus each replica's full
        engine snapshot (which carries its ``replica`` stamp and
        ``union_mesh_devices``)."""
        per = [eng.snapshot() for eng in self.engines]
        out = {
            "engine": "serving_fleet",
            "replicas": len(self.engines),
            "requests": sum(p["requests"] for p in per),
            "rows": sum(p["rows"] for p in per),
            "dispatches": sum(p["dispatches"] for p in per),
            "queue_depth": sum(p["queue_depth"] for p in per),
            "deadline_misses": sum(p["deadline_misses"] for p in per),
            "expired": sum(p["expired"] for p in per),
            "hot_swaps": sum(p["hot_swaps"] for p in per),
            "union_mesh_devices": self.config.num_devices,
            "per_replica": per,
        }
        if self._front is not None:
            out["net"] = self._front.net_snapshot()
            out["replica_routing"] = self._front.replica_snapshot()
        return out

    def render_openmetrics(self) -> str:
        """The fleet /metrics exposition: serving_fleet_* aggregates
        with a ``rep`` label where per-replica resolution matters,
        plus the front door's serving_replica_* and serving_net_*
        families (one scrape, one truth — same discipline as the
        single engine)."""
        om = openmetrics
        per = [(str(i), eng.snapshot())
               for i, eng in enumerate(self.engines)]
        fams = [
            om.gauge("serving_fleet_replicas",
                     "engine replicas behind the front door",
                     [({}, len(self.engines))]),
            om.metric("serving_fleet_requests", "counter",
                      "requests admitted, by replica",
                      [("_total", {"rep": i}, p["requests"])
                       for i, p in per]),
            om.metric("serving_fleet_rows", "counter",
                      "query rows admitted, by replica",
                      [("_total", {"rep": i}, p["rows"])
                       for i, p in per]),
            om.metric("serving_fleet_dispatches", "counter",
                      "device dispatches, by replica",
                      [("_total", {"rep": i}, p["dispatches"])
                       for i, p in per]),
        ]
        if self._front is not None:
            fams.extend(self._front.net_families())
        return om.render(fams)
