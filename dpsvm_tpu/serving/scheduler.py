"""Deadline-aware continuous batching: EDF queues per union group.

The v1 micro-batcher (serve.py enqueue/flush) merges whatever is queued
in arrival order and has no notion of time: under offered overload the
queue simply grows and every request gets uniformly late. This
scheduler makes lateness an explicit, per-request property:

* every request carries a DEADLINE (submit time + its deadline_ms, or
  +inf when deadlines are off) and batches form in EARLIEST-DEADLINE-
  FIRST order — the tightest requests ride the next dispatch;
* requests whose deadline has already passed at batch-forming time are
  SHED with an explicit ``expired`` verdict (counted per model) instead
  of occupying bucket rows that cannot help them — the backpressure
  that keeps an overloaded queue from growing without bound;
* requests are queued per UNION GROUP (registry.LoadedModel.group_key):
  models sharing one compacted union / kernel family coalesce into the
  SAME bucket dispatch — one kernel matmul answers all of them (the
  dispatch layer stacks their coefficient columns).

The scheduler is pure host bookkeeping (heapq + counters); device work
lives in :mod:`dpsvm_tpu.serving.dispatch`.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
import threading
from typing import Optional

import numpy as np

from dpsvm_tpu.serving.registry import LoadedModel


@dataclasses.dataclass
class Request:
    """One admitted request. ``entry`` is the LoadedModel resolved AT
    SUBMIT — the hot-swap routing point: this reference, not the name,
    decides which staged union answers the request, so in-flight work
    finishes on the version it was admitted against."""

    ticket: int
    entry: LoadedModel
    rows: np.ndarray  # caller's dtype kept (f64 stays exact on f64 cols)
    t_submit: float
    deadline: float  # absolute monotonic seconds; math.inf = none
    seq: int  # FIFO tiebreak among equal deadlines

    @property
    def n(self) -> int:
        return int(self.rows.shape[0])


class Scheduler:
    """Per-group EDF queues + global accounting.

    ``form(key, now, max_rows)`` pops the group's queue in deadline
    order, shedding expired requests, until the batch would exceed
    ``max_rows`` (a single oversized request forms alone — the
    dispatcher loops it over the top bucket, the v1 discipline).

    All mutation (submit and form) runs under one internal lock, so
    CONCURRENT SUBMIT from several threads is well-defined: seq
    numbers stay dense and FIFO-ordered per admission, queue_rows and
    the per-entry refcounts stay exact, and a scrape iterating the
    queues never races a heappush (threadlint guarded-by contract:
    Scheduler._q/_seq/queue_rows/_entry_refs are _lock's). form() is
    still driven by one pump at a time — the lock makes the
    ACCOUNTING safe, not two dispatchers per group sensible.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._q: dict = {}  # group key -> [(deadline, seq, Request)]
        self._seq = 0
        self.queue_rows = 0
        # Per-entry queued-request refcounts: pending_entries() sits on
        # the per-dispatch path (dispatch.py _group_for) and must stay
        # O(distinct entries), not O(queued requests) — a full queue
        # scan per dispatch is O(queue^2) host work under deep queues.
        # Maintained at submit and at every pop in form().
        self._entry_refs: dict = {}

    # ------------------------------------------------------------ admit
    def submit(self, entry: LoadedModel, rows: np.ndarray, now: float,
               deadline_s: Optional[float], ticket: int,
               dtype: str) -> Request:
        with self._lock:
            self._seq += 1
            req = Request(
                ticket=ticket, entry=entry, rows=rows, t_submit=now,
                deadline=(now + deadline_s if deadline_s is not None
                          else math.inf),
                seq=self._seq)
            key = entry.group_key(dtype)
            heapq.heappush(self._q.setdefault(key, []),
                           (req.deadline, req.seq, req))
            self.queue_rows += req.n
            self._entry_refs[entry] = \
                self._entry_refs.get(entry, 0) + 1
            return req

    # ------------------------------------------------------------ state
    @property
    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._q.values())

    def depth_by_model(self) -> dict:
        """{model name: queued requests} — the exported queue-depth
        gauge's label set. Under the scheduler lock: a /metrics scrape
        thread or an admin thread preparing a hot swap reads this
        while serving threads submit."""
        out: dict = {}
        with self._lock:
            for q in self._q.values():
                for item in q:
                    name = item[2].entry.name
                    out[name] = out.get(name, 0) + 1
        return out

    def pending_entries(self) -> set:
        """Every LoadedModel with queued work — what keeps an old
        version's union group staged across a swap until it drains.
        O(distinct entries) via the maintained refcounts (this is on
        the per-dispatch path)."""
        with self._lock:
            return {e for e, c in self._entry_refs.items() if c > 0}

    def next_key(self):
        """The group whose head request has the earliest deadline (FIFO
        among equals) — the group the next dispatch should serve. None
        when idle."""
        best_key, best = None, None
        with self._lock:
            for key, q in self._q.items():
                if not q:
                    continue
                head = q[0][:2]
                if best is None or head < best:
                    best, best_key = head, key
        return best_key

    # ------------------------------------------------------------- form
    def form(self, key, now: float, max_rows: int):
        """(batch, expired): pop `key`'s queue in EDF order into a batch
        of at most `max_rows` total rows; requests already past their
        deadline are shed into `expired` (they never occupy bucket
        rows). The queue may drain entirely into one call."""
        with self._lock:
            return self._form_locked(key, now, max_rows)

    def _form_locked(self, key, now: float, max_rows: int):
        q = self._q.get(key, ())
        batch: list = []
        expired: list = []
        rows = 0
        while q:
            req = q[0][2]
            if req.deadline < now:
                heapq.heappop(q)
                self._drop_ref(req)
                expired.append(req)
                continue
            if batch and rows + req.n > max_rows:
                break
            heapq.heappop(q)
            self._drop_ref(req)
            batch.append(req)
            rows += req.n
            if rows >= max_rows:
                break
        if q == []:
            self._q.pop(key, None)
        return batch, expired

    def _drop_ref(self, req: Request) -> None:
        # caller holds self._lock (form's pop path)
        self.queue_rows -= req.n
        left = self._entry_refs.get(req.entry, 0) - 1
        if left > 0:
            self._entry_refs[req.entry] = left
        else:
            self._entry_refs.pop(req.entry, None)
