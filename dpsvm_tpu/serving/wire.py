"""Wire protocol for the network front door (ISSUE 15).

Length-prefixed binary frames over a persistent TCP connection —
stdlib ``struct`` + raw sockets, no new deps. This module and
client.py import no jax themselves (importing them through the
package pulls the package ``__init__``, which may import jax — an
import-time cost only: no device is ever touched by the client path).

Frame layout (network byte order throughout)::

    header   !2sBBI   magic b"DS", version (1), frame type, payload len
    REQUEST  !IBdH    req_id, flags, deadline budget ms (f64), name len
             name utf-8
             !II      rows, cols
             rows*cols big-endian f32
    VERDICT  !IBIdIH  req_id, verdict code, retry_after_ms,
                      latency_ms (f64), model version, name len
             name utf-8
             !BI      payload kind (0 none / 1 labels / 2 decision),
                      message len
             message utf-8
             kind 1:  !I n            then n   big-endian i32 labels
             kind 2:  !II n, k        then n*k big-endian f32 columns
    ERROR    !IH      req_id (0 = not attributable), message len
             message utf-8 — a protocol violation; the connection
             closes right after this frame.
    GOODBYE  !H       message len; message utf-8 — graceful drain:
                      every verdict for this connection has already
                      been flushed ahead of this frame; anything the
                      client still considers outstanding after GOODBYE
                      was never admitted (treat as rejected-by-drain,
                      safe to retry against a live server).
    HELLO    (empty)  server banner, first frame on every ACCEPTED
                      connection — EOF before HELLO means the server
                      dropped the connection at accept (nothing was
                      processed; a connect-class retry is safe).

THE CLOCK CONTRACT: deadlines cross the wire as the client's REMAINING
BUDGET in milliseconds — a duration, never a wall-clock timestamp — so
client/server clock skew cannot move a deadline. The server anchors
the budget to its OWN monotonic clock at frame-parse time (the
admitted request's deadline is ``server_now + budget``). A negative
budget means "use the server's configured default"; the scheduler
treats 0 as already due at the next batch forming.

THE VERDICT CONTRACT: every REQUEST frame the server successfully
parses terminates in EXACTLY ONE of the five verdict codes below (or
the connection receives an ERROR frame when the stream itself is
unparseable, after which the connection dies). ``served``/``late``
carry decision payloads; ``expired``/``rejected``/``failed`` never do.
``rejected`` carries a ``retry_after_ms`` hint and is the ONLY verdict
the client library retries (plus connect-level failures): ``failed``
and ``expired`` must never be retried blindly — the server may have
spent real compute on them, and a retry would duplicate it.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

import numpy as np

MAGIC = b"DS"
VERSION = 1

T_REQUEST = 1
T_VERDICT = 2
T_ERROR = 3
T_GOODBYE = 4
#: server -> client banner, sent immediately after accept. Its role is
#: accounting, not greeting: a TCP handshake completes in the LISTEN
#: BACKLOG before the server ever sees the connection, so a client
#: cannot otherwise distinguish "dropped at accept" (server did
#: nothing — safe to retry) from "dropped mid-flight" (request may be
#: in flight — never retried). The client treats EOF-before-HELLO as a
#: connect-class failure.
T_HELLO = 5

#: wire verdict codes. Engine verdict "ok" maps to wire "served"; the
#: other engine verdicts keep their names. "rejected" exists only on
#: the wire (admission control / drain — the engine never sees the
#: request).
VERDICTS = ("served", "late", "expired", "rejected", "failed")
_CODE = {name: i for i, name in enumerate(VERDICTS)}

PAYLOAD_NONE = 0
PAYLOAD_LABELS = 1
PAYLOAD_DECISION = 2

_HEADER = struct.Struct("!2sBBI")
_REQ_HEAD = struct.Struct("!IBdH")
_REQ_SHAPE = struct.Struct("!II")
_VER_HEAD = struct.Struct("!IBIdIH")
_VER_BODY = struct.Struct("!BI")
_ERR_HEAD = struct.Struct("!IH")
_GOODBYE_HEAD = struct.Struct("!H")

HEADER_BYTES = _HEADER.size

#: REQUEST flag bits.
FLAG_WANT_DECISION = 0x01  # verdict carries f32 decision columns, not labels


class WireError(ValueError):
    """A malformed frame (bad magic/version/type, inconsistent
    lengths). The server answers with an ERROR frame and kills ONLY
    the offending connection."""


class ConnectionClosed(ConnectionError):
    """The peer closed the connection. ``mid_frame`` distinguishes a
    clean close at a frame boundary from a truncated frame."""

    def __init__(self, msg: str, mid_frame: bool = False):
        super().__init__(msg)
        self.mid_frame = mid_frame


@dataclasses.dataclass
class Request:
    """One parsed REQUEST frame."""

    req_id: int
    model: Optional[str]  # None = "" on the wire: the single-model default
    budget_ms: Optional[float]  # None = use the server default deadline
    rows: np.ndarray  # (n, d) float32
    want_decision: bool


@dataclasses.dataclass
class Verdict:
    """One parsed VERDICT frame (the client-side view)."""

    req_id: int
    verdict: str
    model: str
    version: int
    latency_ms: float
    retry_after_ms: int
    message: str
    labels: Optional[np.ndarray]
    decision: Optional[np.ndarray]

    @property
    def ok(self) -> bool:
        return self.verdict == "served"


# --------------------------------------------------------------- framing

def pack_frame(ftype: int, payload: bytes) -> bytes:
    return _HEADER.pack(MAGIC, VERSION, ftype, len(payload)) + payload


def parse_header(raw: bytes, max_payload: int) -> tuple:
    """(frame type, payload length); raises WireError on garbage — the
    oversized-length check runs HERE, before any allocation, so a
    hostile length prefix can never balloon server memory."""
    magic, version, ftype, length = _HEADER.unpack(raw)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != VERSION:
        raise WireError(f"unsupported protocol version {version} "
                        f"(this build speaks {VERSION})")
    if ftype not in (T_REQUEST, T_VERDICT, T_ERROR, T_GOODBYE,
                     T_HELLO):
        raise WireError(f"unknown frame type {ftype}")
    if length > max_payload:
        raise WireError(f"frame payload {length} bytes exceeds the "
                        f"{max_payload}-byte bound")
    return ftype, length


def recv_exact(sock, n: int) -> bytes:
    """Read exactly `n` bytes; EOF raises ConnectionClosed (mid_frame
    when any bytes had already arrived — a truncated frame, not a
    clean goodbye). The socket's timeout bounds each recv."""
    parts = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionClosed(
                f"peer closed after {got}/{n} bytes", mid_frame=got > 0)
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


# --------------------------------------------------------------- REQUEST

def pack_request(req_id: int, rows: np.ndarray, model: Optional[str],
                 budget_ms: Optional[float],
                 want_decision: bool = False) -> bytes:
    q = np.ascontiguousarray(rows, np.dtype(">f4"))
    if q.ndim != 2:
        raise ValueError(f"rows must be 2-D, got shape {q.shape}")
    name = (model or "").encode("utf-8")
    flags = FLAG_WANT_DECISION if want_decision else 0
    payload = (_REQ_HEAD.pack(int(req_id), flags,
                              -1.0 if budget_ms is None
                              else float(budget_ms), len(name))
               + name + _REQ_SHAPE.pack(*q.shape) + q.tobytes())
    return pack_frame(T_REQUEST, payload)


def parse_request(payload: bytes) -> Request:
    if len(payload) < _REQ_HEAD.size:
        raise WireError("REQUEST payload shorter than its fixed header")
    req_id, flags, budget_ms, name_len = _REQ_HEAD.unpack_from(payload)
    off = _REQ_HEAD.size
    if len(payload) < off + name_len + _REQ_SHAPE.size:
        raise WireError("REQUEST payload truncated inside the name")
    try:
        name = payload[off:off + name_len].decode("utf-8")
    except UnicodeDecodeError as e:
        # Still a WIRE error: anything a hostile payload can contain
        # must surface as the one refusal type the containment
        # handles, never escape the reader's protocol-error path.
        raise WireError(f"REQUEST model name is not UTF-8: {e}") from e
    off += name_len
    rows, cols = _REQ_SHAPE.unpack_from(payload, off)
    off += _REQ_SHAPE.size
    want = rows * cols * 4
    if len(payload) - off != want:
        raise WireError(
            f"REQUEST declares {rows}x{cols} f32 rows ({want} bytes) "
            f"but carries {len(payload) - off}")
    data = np.frombuffer(payload, np.dtype(">f4"), count=rows * cols,
                         offset=off).reshape(rows, cols)
    return Request(req_id=req_id, model=name or None,
                   budget_ms=None if budget_ms < 0 else budget_ms,
                   rows=data.astype(np.float32),
                   want_decision=bool(flags & FLAG_WANT_DECISION))


# --------------------------------------------------------------- VERDICT

def pack_verdict(req_id: int, verdict: str, model: str = "",
                 version: int = 0, latency_ms: float = 0.0,
                 retry_after_ms: int = 0, message: str = "",
                 labels: Optional[np.ndarray] = None,
                 decision: Optional[np.ndarray] = None) -> bytes:
    name = model.encode("utf-8")
    msg = message.encode("utf-8")
    head = _VER_HEAD.pack(int(req_id), _CODE[verdict],
                          int(retry_after_ms), float(latency_ms),
                          int(version), len(name)) + name
    if labels is not None:
        lab = np.ascontiguousarray(labels, np.dtype(">i4"))
        body = (_VER_BODY.pack(PAYLOAD_LABELS, len(msg)) + msg
                + struct.pack("!I", lab.shape[0]) + lab.tobytes())
    elif decision is not None:
        dec = np.ascontiguousarray(decision, np.dtype(">f4"))
        body = (_VER_BODY.pack(PAYLOAD_DECISION, len(msg)) + msg
                + struct.pack("!II", *dec.shape) + dec.tobytes())
    else:
        body = _VER_BODY.pack(PAYLOAD_NONE, len(msg)) + msg
    return pack_frame(T_VERDICT, head + body)


def parse_verdict(payload: bytes) -> Verdict:
    # A malformed verdict payload — short struct, bad UTF-8, declared
    # counts past the buffer — must surface as WireError (the client
    # maps it to ProtocolError and closes), never a raw struct/codec
    # exception escaping the documented error hierarchy.
    try:
        if len(payload) < _VER_HEAD.size:
            raise WireError(
                "VERDICT payload shorter than its fixed header")
        (req_id, code, retry_ms, latency_ms, version,
         name_len) = _VER_HEAD.unpack_from(payload)
        if code >= len(VERDICTS):
            raise WireError(f"unknown verdict code {code}")
        off = _VER_HEAD.size
        name = payload[off:off + name_len].decode("utf-8")
        off += name_len
        kind, msg_len = _VER_BODY.unpack_from(payload, off)
        off += _VER_BODY.size
        msg = payload[off:off + msg_len].decode("utf-8")
        off += msg_len
        labels = decision = None
        if kind == PAYLOAD_LABELS:
            (n,) = struct.unpack_from("!I", payload, off)
            off += 4
            labels = np.frombuffer(payload, np.dtype(">i4"), count=n,
                                   offset=off).astype(np.int32)
        elif kind == PAYLOAD_DECISION:
            n, k = struct.unpack_from("!II", payload, off)
            off += 8
            decision = np.frombuffer(payload, np.dtype(">f4"),
                                     count=n * k,
                                     offset=off).reshape(n, k).astype(
                                         np.float32)
        elif kind != PAYLOAD_NONE:
            raise WireError(f"unknown verdict payload kind {kind}")
    except WireError:
        raise
    except (struct.error, UnicodeDecodeError, ValueError) as e:
        raise WireError(f"malformed VERDICT payload: "
                        f"{type(e).__name__}: {e}") from e
    return Verdict(req_id=req_id, verdict=VERDICTS[code], model=name,
                   version=version, latency_ms=latency_ms,
                   retry_after_ms=retry_ms, message=msg, labels=labels,
                   decision=decision)


# ---------------------------------------------------------- ERROR/GOODBYE

def pack_error(req_id: int, message: str) -> bytes:
    msg = message.encode("utf-8")[:512]
    return pack_frame(T_ERROR, _ERR_HEAD.pack(int(req_id), len(msg))
                      + msg)


def parse_error(payload: bytes) -> tuple:
    req_id, msg_len = _ERR_HEAD.unpack_from(payload)
    off = _ERR_HEAD.size
    return req_id, payload[off:off + msg_len].decode("utf-8")


def pack_goodbye(message: str = "") -> bytes:
    msg = message.encode("utf-8")[:512]
    return pack_frame(T_GOODBYE, _GOODBYE_HEAD.pack(len(msg)) + msg)


def pack_hello() -> bytes:
    return pack_frame(T_HELLO, b"")


def parse_goodbye(payload: bytes) -> str:
    (msg_len,) = _GOODBYE_HEAD.unpack_from(payload)
    return payload[_GOODBYE_HEAD.size:
                   _GOODBYE_HEAD.size + msg_len].decode("utf-8")
